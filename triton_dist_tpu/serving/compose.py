"""Disaggregated prefill × sharded decode (ISSUE 12 tentpole, rung 1):
``DisaggServingEngine``'s decode role IS a ``ShardedServingEngine``.

The production topology the ROADMAP names — a prefill fleet feeding a
sharded decode fleet — composes the two serving subsystems that used to
refuse each other:

- the **decode fleet** is an unmodified :class:`ShardedServingEngine` on
  a TP/SP/EP mesh: SP-sharded page pool (``page_pool_pspec``), TP
  projections, EP-MoE FFN through the overlap library, replicated-
  decision digest guard — everything PR 8 pinned.
- the **prefill fleet** runs on the SAME mesh with its OWN pool + ledger
  + scheduler, reusing the decode engine's compiled chunk program (the
  pools are built with identical shapes and the identical committed SP
  sharding, so pjit serves both from ONE executable —
  ``prefill_chunk_compiles == 1`` stays pinned).
- the **handoff** is the disagg signal protocol verbatim
  (``PageMigrationChannel`` + ``ChunkSignalLedger`` + the ISSUE 7
  recovery ladder), over a different transport tier: the one-sided
  Pallas ``migrate_pages`` kernel moves pages between two ranks of ONE
  mesh axis, while here the two pools live on the SAME multi-axis mesh
  as differently-owned arrays — the DCN tier of the reference's
  hierarchy, where a host-driven copy is the idiomatic primitive. ONE
  jitted gather/scatter program (``_xmig``) copies the chunk's pages
  bit-exactly and reports the landed count + echoed attempt tag exactly
  like the kernel's consumer-side report, so the ledger, the signal
  gate, the deadline/retry/degrade ladder and the chaos hooks all run
  UNCHANGED on top of it.

The unified pool contract (kv_pool.py) is what makes the composition
sound: both ledgers carry ``sp_ranks``, so ``check_migratable`` refuses
SP padding ids on either side and ``landed_row`` exposes only real
signal-covered pages — a migration can never land KV in a padding slot
no block table can reach.

Bit-identity chain (tests/test_cluster.py): the sharded engine's tokens
are bitwise mesh-size-independent (PR 8), migration is an exact page
copy, and the first token is argmaxed by the same chunk program — so the
composed engine's per-request traces replay the 1x1x1
``ShardedServingEngine`` golden exactly, at every mesh size, preemptions
and recovery rungs included.

Degradation differs from two-worker disagg in ONE honest way: the
decode fleet natively runs chunked prefill, so a degraded request is
simply requeued (front) into the decode engine's own admission queue —
it keeps its decode-side page reservation and re-prefills through the
decode engine's ordinary chunk path. The decode panel's
``step_prefill_tokens == 0`` isolation invariant therefore holds for
fault-free runs only (same caveat as disagg's degraded rung).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.llama import init_page_pool
from triton_dist_tpu.models.moe import MoEConfig
from triton_dist_tpu.ops.allgather_gemm import GemmConfig
from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.deadline import (Backoff, Deadline,
                                              EngineStallError)
from triton_dist_tpu.serving.disagg import (DECODE_ROLE, ChunkSignalLedger,
                                            MigrationSignalTimeout,
                                            PageMigrationChannel,
                                            SignalProtocolError)
from triton_dist_tpu.serving.engine import (class_label, mark_prefill_start,
                                            record_first_token)
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import (KVPagePool, _fnv1a,
                                             shard_pool_arrays)
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.prefix_cache import PrefixCache
from triton_dist_tpu.serving.scheduler import (AdmissionRejected,
                                               ContinuousBatchingScheduler,
                                               Request, RequestState,
                                               SLOPolicy, TtlExpired)
from triton_dist_tpu.serving.sharded import ShardedServingEngine
from triton_dist_tpu.shmem import faults
from triton_dist_tpu.shmem.context import ShmemContext


class DisaggShardedEngine:
    """Disaggregated serving with a :class:`ShardedServingEngine` decode
    fleet (module docstring). Constructor knobs are the union of the
    disagg ladder knobs and the sharded mesh knobs; ``prefill_chunk`` is
    mandatory (chunks are both the migration unit and the sharded
    engine's only prefill path).

    Request lifecycle mirrors disagg: QUEUED (prefill queue) →
    PREFILLING (prefill fleet seat; decode pages reserved at admission;
    chunks run and migrate) → MIGRATING (seated on the decode fleet,
    signal-gated) → ACTIVE (fully decode-owned — from here the sharded
    engine runs it natively, preemptions and all) → FINISHED, with the
    ladder's degrade rung requeueing into the decode engine's own
    chunked-prefill admission and FAILED only at the bottom.
    """

    def __init__(self, params: dict, cfg: MoEConfig, ctx: ShmemContext,
                 num_slots: int = 4, num_prefill_slots: int = 2,
                 page_size: int = 16, num_pages: int = 64,
                 pages_per_seq: int = 8,
                 metrics: ServingMetrics | None = None,
                 metrics_decode: ServingMetrics | None = None,
                 decode_horizon: int = 1, eos_id: int | None = None,
                 prefill_chunk: int | None = None,
                 signal_deadline_steps: int = 8, max_retries: int = 3,
                 allow_degradation: bool = True, max_degradations: int = 1,
                 stall_deadline_steps: int | None = None,
                 wall_deadline_s: float | None = None,
                 wire_dtype: str | None = "auto", tp_impl: str = "xla",
                 tp_cfg: GemmConfig | None = None, moe_block_m: int = 128,
                 digest_every: int = 1,
                 journal: ControlJournal | None = None,
                 checkpoint_every: int | None = None,
                 queue_cap: int | None = None,
                 ttl_steps: int | None = None,
                 fault_plan: "faults.FaultPlan | None" = None,
                 prefix_cache: bool = False,
                 slo: SLOPolicy | None = None,
                 artifact=None, artifact_key: str | None = None):
        assert prefill_chunk is not None, (
            "the composed engine requires prefill_chunk: chunks are the "
            "migration unit AND the sharded engine's only prefill path")
        assert signal_deadline_steps >= 1 and max_retries >= 0
        assert checkpoint_every is None or journal is not None, (
            "checkpoint_every needs a journal to record into")
        self.ctx = ctx
        self.params = params
        self.moe_cfg = cfg
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.num_slots = num_slots
        self.prefill_chunk = prefill_chunk
        self.decode_horizon = decode_horizon
        self.eos_id = eos_id
        self.signal_deadline_steps = signal_deadline_steps
        self.max_retries = max_retries
        self.allow_degradation = allow_degradation
        self.max_degradations = max_degradations
        self.wall_deadline_s = wall_deadline_s
        ladder = signal_deadline_steps * (2 ** (max_retries + 1) - 1)
        self._stall_steps = (stall_deadline_steps if stall_deadline_steps
                             is not None else max(256, 4 * ladder))
        self.metrics = metrics or ServingMetrics()
        self.metrics_decode = metrics_decode or ServingMetrics()

        # -- the decode fleet: an unmodified sharded engine ---------------
        # journal/TTL/queue-cap stay None — the COMPOSED engine owns the
        # crash-consistency and overload surfaces (one journal, one intake
        # queue); the decode engine's digest guard runs at full cadence.
        # AOT artifact (ISSUE 15): the composition's programs live under
        # ONE key — the inner decode engine seeds chunk/decode from it,
        # and the xmig copy program is seeded below.
        self._aot_artifact = artifact
        self._aot_key = artifact_key or (
            f"disagg_sharded:{ctx.axis_size('tp')}x"
            f"{ctx.axis_size('sp')}x{ctx.axis_size('ep')}")
        self.decode = ShardedServingEngine(
            params, cfg, ctx, num_slots=num_slots, page_size=page_size,
            num_pages=num_pages, pages_per_seq=pages_per_seq,
            metrics=self.metrics_decode, decode_horizon=decode_horizon,
            eos_id=eos_id, prefill_chunk=prefill_chunk,
            wire_dtype=wire_dtype, tp_impl=tp_impl, tp_cfg=tp_cfg,
            moe_block_m=moe_block_m, digest_every=digest_every,
            prefix_cache=prefix_cache,
            artifact=artifact, artifact_key=self._aot_key)
        self.decode._preempt_hook = self._on_decode_preempt
        self.mesh_desc = self.decode.mesh_desc
        self.wire_dtype = self.decode.wire_dtype
        n_sp = ctx.axis_size("sp")

        # -- the prefill fleet: own pool/ledger/scheduler on the SAME mesh,
        # arrays shaped + sharded IDENTICALLY to the decode pool so the
        # decode engine's compiled chunk program serves both (one pjit
        # executable — compile_stats pins it)
        self.alloc_p = KVPagePool(num_pages + 1, page_size, reserved=1,
                                  sp_ranks=n_sp)
        self.pool_p = shard_pool_arrays(
            init_page_pool(cfg.base, num_pages + 1, page_size), n_sp,
            self.decode._pool_out_sharding)
        # SLO policy (ISSUE 14) on the composed intake only — the decode
        # fleet's scheduler stays policy-free (class-aware victim ordering
        # reads the shed_level stamp each request carries)
        self.slo = slo
        self.sched_p = ContinuousBatchingScheduler(num_prefill_slots,
                                                   queue_cap=queue_cap,
                                                   policy=slo)
        # prefix cache (ISSUE 13), disagg-shaped: one index per fleet.
        # The PREFILL-fleet cache adopts solely-owned pages and skips the
        # chunk compute inside the hit (every page still migrates); the
        # decode fleet's own cache — constructed above — serves the
        # degradation rung's local re-prefills.
        self.prefix_cache = (PrefixCache(self.alloc_p, page_size)
                             if prefix_cache else None)

        # -- the DCN-tier migration program: one jitted gather/scatter
        # copying up to pmax (src → dst) pages between the two pools, with
        # the landed-count + echoed-tag report the channel/ledger protocol
        # expects from the kernel path. Masked lanes gather dst page 0's
        # own bytes and scatter them back — an identity write on the
        # scratch page, never a live one.
        pmax = max(prefill_chunk // page_size + 2, pages_per_seq)

        def xmig(src, dst, n, tag, skp, svp, dkp, dvp):
            m = jnp.arange(pmax, dtype=jnp.int32) < n[0]
            gsrc = jnp.where(m, src, 0)
            gdst = jnp.where(m, dst, 0)
            mk = m[None, :, None, None, None]
            pk = jnp.where(mk, skp[:, gsrc], dkp[:, gdst])
            pv = jnp.where(mk, svp[:, gsrc], dvp[:, gdst])
            dkp = dkp.at[:, gdst].set(pk)
            dvp = dvp.at[:, gdst].set(pv)
            landed_row = jnp.concatenate([n, tag])     # [count, echoed tag]
            landed = jnp.stack([landed_row, landed_row])
            return dkp, dvp, landed

        pshard = self.decode._pool_out_sharding
        kw = {"out_shardings": (pshard, pshard, self.decode._rep_sharding)}
        if jax.default_backend() == "cpu":
            self._xmig = jax.jit(xmig, **kw)
        else:
            self._xmig = jax.jit(xmig, donate_argnums=(6, 7), **kw)
        if artifact is not None:
            # _launch reads self._xmig at call time, so seeding here is
            # enough — no closure rebind needed
            self._xmig = artifact.program(self._aot_key, "xmig")

        # TDT_SIGCHECK=1: the decode engine linted its own two programs in
        # its constructor; lint the composition's third program here
        if os.environ.get("TDT_SIGCHECK") == "1":
            from triton_dist_tpu.analysis.lint import lint_engine_programs
            abstract = lambda tree: jax.tree_util.tree_map(  # noqa: E731
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
            kp = abstract(self.pool_p["k"])
            vp = abstract(self.pool_p["v"])
            lint_engine_programs({"xmig_pages": (xmig, (
                i32(pmax), i32(pmax), i32(1), i32(1), kp, vp, kp, vp))},
                type(self).__name__)

        def _launch(src, dst, n, tag, kp, vp):
            dk, dv, landed = self._xmig(src, dst, n, tag, kp, vp,
                                        self.decode.pool["k"],
                                        self.decode.pool["v"])
            self.decode.pool = {"k": dk, "v": dv}
            return kp, vp, landed       # prefill pool is a read-only source

        self.channel = PageMigrationChannel(
            _launch, pmax, reserved=1, metrics=self.metrics,
            consumer=DECODE_ROLE, plan=fault_plan,
            clock=lambda: self._steps)

        # -- crash consistency + ladder state (disagg-shaped) -------------
        self.journal = journal
        self.checkpoint_every = checkpoint_every
        self.ttl_steps = ttl_steps
        self._fault_plan = fault_plan
        self._journal_muted = False
        self._replaying = False
        self._incarnation = 0
        self._last_ckpt_step = -1
        self._handoff: deque[Request] = deque()   # MIGRATING, no seat yet
        self._dslot: dict[int, int] = {}          # rid -> MIGRATING seat
        self._wait_steps: dict[int, int] = {}
        self._recovery: dict[int, tuple[Deadline, Backoff]] = {}
        self._poisoned: dict[int, Exception] = {}
        self._degraded: dict[int, Request] = {}   # rid -> req, in decode q
        self._finished: list[Request] = []
        self._failed: list[Request] = []
        self._rejected: list[Request] = []
        self._next_rid = 0
        self._steps = 0

    # the decode fleet's ledger/scheduler under the disagg names — the
    # PROPERTY matters: the decode engine's _restore_state replaces the
    # objects, and the composed engine must always see the live ones
    @property
    def alloc_d(self) -> KVPagePool:
        return self.decode.alloc

    @property
    def sched_d(self) -> ContinuousBatchingScheduler:
        return self.decode.sched

    # -- request intake ----------------------------------------------------
    def _ttl_for(self, req: Request) -> int | None:
        """Class TTL override (ISSUE 14) beats the engine-wide knob."""
        spec = self.sched_p.class_spec(req)
        if spec is not None and spec.ttl_steps is not None:
            return spec.ttl_steps
        return self.ttl_steps

    def submit(self, prompt, max_new_tokens: int, rid: int | None = None,
               tenant: str | None = None, cls: str | None = None) -> int:
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        assert prompt and max_new_tokens >= 1
        total = len(prompt) + max_new_tokens - 1
        need = -(-total // self.page_size)
        assert need <= self.pages_per_seq, (
            f"request needs {need} pages > pages_per_seq "
            f"{self.pages_per_seq}")
        assert need <= self.alloc_d.num_pages - self.alloc_d.reserved, (
            f"request needs {need} pages > decode pool size")
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      eos_token=self.eos_id, submit_step=self._steps,
                      submit_time=time.perf_counter())
        self.sched_p.stamp(req, tenant=tenant, cls=cls)
        self.metrics.inc("requests_submitted")
        self.metrics.inc_class("requests_submitted", class_label(req))
        if self.sched_p.at_capacity_for(req.cls) and not self._replaying:
            cap = self.sched_p.queue_cap if self.sched_p.at_capacity else \
                self.sched_p.policy.spec(req.cls).queue_cap
            req.state = RequestState.REJECTED
            req.failure = AdmissionRejected(
                f"admission queue full for class {req.cls!r} (cap {cap}) "
                f"— request {rid} rejected")
            self._rejected.append(req)
            self.metrics.inc("rejections")
            self.metrics.inc_class("rejections", class_label(req))
            self._jlog("reject", rid=rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)
            return rid
        ttl = self._ttl_for(req)
        if ttl is not None:
            req.deadline = Deadline(ttl, req.submit_step)
        self.sched_p.submit(req)
        self._jlog("submit", rid=rid, prompt=list(prompt),
                   max_new_tokens=max_new_tokens,
                   tenant=req.tenant, cls=req.cls)
        return rid

    # -- prefill fleet -----------------------------------------------------
    def _can_hold(self, req: Request) -> bool:
        """Admission needs BOTH pools (disagg semantics): prefill pages to
        compute into and the decode-side reservation fixed at admit."""
        need = -(-len(req.prompt) // self.page_size)
        need_p = need - len(self.alloc_p.pages_of(req.rid))
        need_d = need - len(self.alloc_d.pages_of(req.rid))
        # refcount-0 cached pages count as reclaimable capacity on BOTH
        # fleets: the prefill fleet evicts through its own index, the
        # decode fleet through the sharded engine's (degradation-rung
        # re-prefills populate it) — otherwise a full cached pool would
        # wedge remote admission forever
        avail_p = self.alloc_p.free_pages + (
            self.prefix_cache.evictable if self.prefix_cache else 0)
        avail_d = self.alloc_d.free_pages + (
            self.decode.prefix_cache.evictable
            if self.decode.prefix_cache else 0)
        return avail_p >= max(need_p, 0) and avail_d >= max(need_d, 0)

    def _cache_adopt(self, req: Request) -> None:
        """Disagg-shaped adoption (sole-ownership rule): adopt the
        longest prefix of the hit whose pages are ALL refcount-0, so the
        acquired pages are solely owned and ``check_migratable`` accepts
        them when their chunks migrate."""
        cache = self.prefix_cache
        if (cache is None or req.prefill_cursor > 0
                or self.alloc_p.holds(req.rid)):
            return
        solo = []
        for p in cache.match(req.prompt):
            if self.alloc_p.refcount(p) != 0:
                break
            solo.append(p)
        if not solo:
            self.metrics.inc("prefix_misses")
            return
        self.alloc_p.acquire(req.rid, solo)
        req.cache_hit_tokens = len(solo) * self.page_size
        self.metrics.inc("prefix_hits")
        self.metrics.inc("prefix_hit_tokens", req.cache_hit_tokens)

    def _admit_prefill(self, slot: int, req: Request) -> None:
        self._cache_adopt(req)
        sp = len(req.prompt)
        need = -(-sp // self.page_size)
        have_p = len(self.alloc_p.pages_of(req.rid))
        if need > have_p:
            short = (need - have_p) - self.alloc_p.free_pages
            if short > 0 and self.prefix_cache is not None:
                self.metrics.inc("prefix_evictions",
                                 self.prefix_cache.evict(short))
            got = self.alloc_p.alloc(req.rid, need - have_p)
            assert got is not None, "admissible() guaranteed the pages"
        have_d = len(self.alloc_d.pages_of(req.rid))
        if need > have_d:
            self.decode._reclaim(need - have_d)   # no-op when cache off
            got = self.alloc_d.alloc(req.rid, need - have_d)
            assert got is not None, "admissible() guaranteed the pages"
        self.sched_p.activate(slot, req)
        self._jlog("admit", rid=req.rid, slot=slot)
        req.state = RequestState.PREFILLING
        mark_prefill_start(req, self.metrics, self._steps)
        self.metrics.inc("prefills")

    def _dispatch_prefill_chunk(self) -> int:
        """Advance the oldest PREFILLING prefill seat by one chunk through
        the DECODE engine's compiled chunk program (same executable — the
        pools are twins), then migrate whatever the chunk finalized. The
        final chunk flips the request to MIGRATING with its device-
        argmaxed first token on the host control plane; its prefill-side
        pages are RETAINED as the retry source until coverage confirms."""
        slot, req = None, None
        for i, r in enumerate(self.sched_p.slots):
            if (r is not None and r.state is RequestState.PREFILLING
                    and (req is None or r.admitted_seq < req.admitted_seq)):
                slot, req = i, r
        if slot is None:
            return 0
        C = self.prefill_chunk
        sp = len(req.prompt)
        start = req.prefill_cursor
        part = req.prompt[start:start + C]
        # cache-hit fast path (ISSUE 13, disagg semantics): a chunk fully
        # inside the adopted prefix skips the device compute — the pages
        # already hold that KV — but still migrates; the final chunk
        # always computes (fused first-token argmax)
        skip = start + C <= req.cache_hit_tokens and start + C < sp
        tok0 = None
        if not skip:
            toks = np.zeros(C, np.int32)
            toks[:len(part)] = part
            row = np.asarray(self.alloc_p.block_table_row(
                req.rid, self.pages_per_seq), np.int32)
            t0 = time.perf_counter()
            tok_dev, self.pool_p = self.decode._chunk_step(
                self.params, jnp.asarray(toks),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(sp, jnp.int32), self.pool_p, jnp.asarray(row))
            tok0 = int(tok_dev)
            dt = time.perf_counter() - t0
        cursor_new = min(start + C, sp)
        req.prefill_cursor = cursor_new
        if skip:
            self.metrics.inc("prefix_skipped_chunks")
        else:
            self.metrics.inc("prefill_chunks")
            self.metrics.observe("prefill_stall_s", dt)
        self._jlog("chunk", rid=req.rid, cursor=cursor_new)
        try:
            self._migrate_finalized(req, start, cursor_new)
        except SignalProtocolError as e:
            self._poison(slot, req, e)
        if req.state is RequestState.PREFILLING and cursor_new >= sp:
            if self.prefix_cache is not None:
                self.prefix_cache.insert(
                    req.prompt,
                    self.alloc_p.pages_of(req.rid)[:sp // self.page_size])
                if req.first_token_time is None:
                    self.metrics.observe(
                        "ttft_cached_s" if req.cache_hit_tokens
                        else "ttft_cold_s",
                        time.perf_counter() - req.submit_time)
            req.first_token = tok0
            record_first_token(req, self.metrics, self._steps)
            self.metrics.inc("tokens_generated")
            self.metrics.inc("handoffs")
            self.sched_p.remove(slot)
            req.state = RequestState.MIGRATING
            self._jlog("handoff", rid=req.rid)
            if req.rid not in self._dslot:
                self._handoff.append(req)
        return len(part)

    def _migrate_finalized(self, req: Request, start: int,
                           cursor_new: int) -> None:
        """Send exactly the pages this chunk FINALIZED (disagg's cursor
        arithmetic verbatim) over the host-driven copy program. Both
        ledgers' ``check_migratable`` run first — with the unified pool
        contract that refuses scratch, SP padding AND foreign ids on
        either side of the mesh."""
        ps = self.page_size
        sp = len(req.prompt)
        done_before = start // ps
        done_after = (-(-sp // ps) if cursor_new >= sp
                      else cursor_new // ps)
        if done_after <= done_before:
            return
        src = self.alloc_p.pages_of(req.rid)[done_before:done_after]
        dst = self.alloc_d.pages_of(req.rid)[done_before:done_after]
        self.alloc_p.check_migratable(req.rid, src)
        self.alloc_d.check_migratable(req.rid, dst)
        chunk_idx = start // self.prefill_chunk
        pk, pv = self.channel.send_chunk(
            req.rid, chunk_idx, src, dst,
            self.pool_p["k"], self.pool_p["v"])
        self.pool_p = {"k": pk, "v": pv}
        self._jlog("migrate", rid=req.rid, chunk=chunk_idx,
                   pages=len(src), attempt=self.channel._attempt.get(
                       (req.rid, chunk_idx), 0))

    # -- decode fleet seating + signal-gated admission ---------------------
    def _seat_decode_slots(self) -> None:
        while self._handoff:
            slot = self.sched_d.free_slot()
            if slot is None:
                return
            req = self._handoff.popleft()
            self.sched_d.place(slot, req)
            self._dslot[req.rid] = slot

    def _check_signal_gate(self, slot: int, covered: set[int]) -> None:
        for p in self.decode._bt[slot]:
            p = int(p)
            if p >= self.alloc_d.reserved and p not in covered:
                raise RuntimeError(
                    f"signal-gate violation: decode block table exposes "
                    f"page {p} before its delivery signal fired")

    def _patch_and_admit(self) -> None:
        """Disagg's block-table patching + signal-gated admission, over
        the DECODE ENGINE's slot mirrors. On the ACTIVE flip the request
        becomes fully decode-owned: mirrors set, ``_dslot`` dropped — the
        sharded engine decodes, preempts and finishes it natively from
        here (its evictions re-prefill bit-identically by determinism)."""
        for slot in range(self.num_slots):
            req = self.sched_d.slots[slot]
            if req is None or req.state is not RequestState.MIGRATING:
                continue
            rid = req.rid
            if rid in self._poisoned:
                self._degrade_or_fail(slot, req, self._poisoned.pop(rid))
                continue
            covered = self.channel.ledger.covered(rid)
            row = np.asarray(self.alloc_d.landed_row(
                rid, covered, self.pages_per_seq), np.int32)
            if not np.array_equal(row, self.decode._bt[slot]):
                self.decode._bt[slot] = row
                self.decode._dirty = True
            self._check_signal_gate(slot, covered)
            sp = len(req.prompt)
            need = set(self.alloc_d.pages_of(rid)[:-(-sp // self.page_size)])
            if req.first_token is not None and need <= covered:
                self.metrics_decode.observe(
                    "migrate_wait_steps", self._wait_steps.pop(rid, 0))
                if req.retries:
                    self.metrics_decode.observe(
                        "recovered_ttft_s",
                        time.perf_counter() - req.submit_time)
                self._recovery.pop(rid, None)
                if self.alloc_p.holds(rid):
                    self.alloc_p.free_seq(rid)
                req.state = RequestState.ACTIVE
                req.generated.append(req.first_token)
                self.metrics_decode.inc("handoffs")
                self.decode._token[slot] = req.first_token
                self.decode._pos[slot] = sp
                self.decode._bt[slot] = np.asarray(
                    self.alloc_d.block_table_row(rid, self.pages_per_seq),
                    np.int32)
                self.decode._dirty = True
                del self._dslot[rid]
                if req.done:
                    self.decode._finish(slot)
                continue
            self._wait_steps[rid] = self._wait_steps.get(rid, 0) + 1
            rec = self._recovery.get(rid)
            if rec is None:
                rec = (Deadline(self.signal_deadline_steps, self._steps,
                                wall_s=self.wall_deadline_s),
                       Backoff(self.signal_deadline_steps,
                               max_retries=self.max_retries))
                self._recovery[rid] = rec
            deadline, backoff = rec
            if not deadline.expired(self._steps):
                continue
            budget = backoff.next_budget()
            retried = False
            if budget is not None:
                try:
                    retried = self._retry_migration(req)
                except SignalProtocolError as e:
                    self._degrade_or_fail(slot, req, e)
                    continue
            if retried:
                deadline.rearm(budget, self._steps)
                continue
            missing = sorted(need - covered)
            self._degrade_or_fail(slot, req, MigrationSignalTimeout(
                f"request {rid} waited {self._wait_steps.get(rid, 0)} "
                f"steps (deadline {self.signal_deadline_steps}, "
                f"{backoff.attempt} retry rung(s) spent) for migration "
                f"signals covering pages {missing}; ledger: "
                f"{self.channel.ledger.describe(rid)}. A signal or page "
                "delivery was lost (or a chunk was never sent)."))

    # -- recovery ladder (disagg's, over the composed transport) -----------
    def _retry_migration(self, req: Request) -> bool:
        rid = req.rid
        if not self.alloc_p.holds(rid):
            return False
        incomplete = self.channel.ledger.incomplete_chunks(rid)
        if not incomplete:
            return False
        src_owned = set(self.alloc_p.pages_of(rid))
        for _, src_ids, _ in incomplete:
            if not src_ids or not set(src_ids) <= src_owned:
                return False
        for ci, src_ids, dst_ids in incomplete:
            pk, pv = self.channel.send_chunk(
                rid, ci, list(src_ids), list(dst_ids),
                self.pool_p["k"], self.pool_p["v"])
            self.pool_p = {"k": pk, "v": pv}
            self._jlog("migrate", rid=rid, chunk=ci, pages=len(src_ids),
                       attempt=self.channel._attempt.get((rid, ci), 0),
                       retry=True)
        req.retries += 1
        self.metrics_decode.inc("retries")
        return True

    def _degrade_or_fail(self, slot: int, req: Request,
                         exc: Exception) -> None:
        if (self.allow_degradation
                and req.degradations < self.max_degradations):
            self._degrade(slot, req)
        else:
            self._fail_decode(slot, req, exc)

    def _degrade(self, slot: int, req: Request) -> None:
        """The composed degrade rung: requeue (front) into the DECODE
        engine's own admission queue. The request keeps its decode-side
        page reservation, so the decode engine's chunked admission
        allocates nothing new and re-prefills the prompt locally through
        its ordinary chunk path — the possibly-lossy migration transport
        is out of the loop, and determinism makes the recomputed tokens
        bit-identical."""
        rid = req.rid
        req.degradations += 1
        self.metrics_decode.inc("degradations")
        self.metrics_decode.observe("degraded_prefill_tokens",
                                    len(req.prompt))
        self.channel.ledger.reset(rid)
        self._recovery.pop(rid, None)
        self._wait_steps.pop(rid, None)
        self._poisoned.pop(rid, None)
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        self.sched_d.remove(slot)
        self.decode._park(slot)
        req.state = RequestState.QUEUED
        req.prefill_cursor = 0
        req.generated.clear()
        req.first_token = None
        del self._dslot[rid]
        self.sched_d.submit(req, front=True)
        self._degraded[rid] = req

    def _note_degraded_progress(self) -> None:
        """Close the recovery clock of degraded requests the decode
        engine has carried back to life (first locally recomputed token
        seen, or already finished within the same composed step)."""
        done = [rid for rid, r in self._degraded.items()
                if r.generated or r.state in (RequestState.FINISHED,
                                              RequestState.ACTIVE)]
        for rid in done:
            req = self._degraded.pop(rid)
            self.metrics_decode.observe(
                "degraded_ttft_s", time.perf_counter() - req.submit_time)

    def _fail_decode(self, slot: int, req: Request, exc: Exception) -> None:
        rid = req.rid
        self.sched_d.remove(slot)
        req.state = RequestState.FAILED
        req.failure = exc
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        self.alloc_d.free_seq(rid)
        self.channel.ledger.reset(rid)
        self.channel.forget(rid)
        self._recovery.pop(rid, None)
        self._wait_steps.pop(rid, None)
        self._poisoned.pop(rid, None)
        del self._dslot[rid]
        self.decode._park(slot)
        self._failed.append(req)
        self.metrics_decode.inc("failed_requests")
        self._jlog("fail", rid=rid, error_type=type(exc).__name__,
                   reason=str(exc).splitlines()[0])

    def _poison(self, slot: int, req: Request, exc: Exception) -> None:
        rid = req.rid
        self.channel.ledger.reset(rid)
        if (self.allow_degradation
                and req.degradations < self.max_degradations):
            self._poisoned[rid] = exc
            return
        self.sched_p.remove(slot)
        req.state = RequestState.FAILED
        req.failure = exc
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        if self.alloc_d.holds(rid):
            self.alloc_d.free_seq(rid)
        self.channel.forget(rid)
        self._failed.append(req)
        self.metrics_decode.inc("failed_requests")
        self._jlog("fail", rid=rid, error_type=type(exc).__name__,
                   reason=str(exc).splitlines()[0])

    def _on_decode_preempt(self, slot: int, req: Request) -> bool:
        """``ServingEngine._preempt`` hook: a MIGRATING seat holds pages
        in the prefill fleet's pool (which the decode engine cannot see)
        and must bounce back to the PREFILL queue — the composed teardown
        below. Post-flip ACTIVE and degraded seats are decode-owned; the
        decode engine's native eviction (local re-prefill, bit-identical)
        handles them, we only void stale migration state first."""
        rid = req.rid
        if rid in self._dslot:
            self._preempt_decode(slot, req)
            return True
        self.channel.ledger.reset(rid)
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        return False

    def _preempt_decode(self, slot: int, req: Request) -> None:
        rid = req.rid
        self.sched_d.remove(slot)
        req.state = RequestState.QUEUED
        req.preemptions += 1
        req.generated.clear()
        req.prefill_cursor = 0
        req.first_token = None
        req.cache_hit_tokens = 0
        self.alloc_d.free_seq(rid)
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        self.channel.ledger.reset(rid)
        self._recovery.pop(rid, None)
        self._wait_steps.pop(rid, None)
        self._poisoned.pop(rid, None)
        del self._dslot[rid]
        self.sched_p.submit(req, front=True)
        self.decode._park(slot)
        self.metrics_decode.inc("preemptions")
        self._jlog("preempt", rid=rid, slot=slot, worker="decode")

    def _harvest_decode(self) -> None:
        """Requests the decode engine finished this step move to the
        composed terminal list, with the composed journal's ``finish``
        entry (the decode engine has no journal) and any residual
        migration state torn down."""
        if not self.decode._finished:
            return
        for req in self.decode._finished:
            rid = req.rid
            self.channel.ledger.reset(rid)
            self.channel.forget(rid)
            self._recovery.pop(rid, None)
            self._wait_steps.pop(rid, None)
            self._poisoned.pop(rid, None)
            self._degraded.pop(rid, None)
            self._dslot.pop(rid, None)
            if self.alloc_p.holds(rid):
                self.alloc_p.free_seq(rid)
            req.finish_step = self._steps
            self._finished.append(req)
            self._jlog("finish", rid=rid, tokens=list(req.generated),
                       submit_step=req.submit_step,
                       first_token_step=req.first_token_step,
                       preemptions=req.preemptions)
        self.decode._finished = []

    # -- one driver iteration ---------------------------------------------
    @property
    def idle(self) -> bool:
        return (self.sched_p.idle and not self._handoff
                and self.sched_d.idle)

    def step(self) -> bool:
        self.sched_p.tick(self._steps)
        self._expire_queued()
        progressed = self._step_impl()
        self.metrics.counters["quota_throttled"] = \
            self.sched_p.quota_throttled
        if progressed:
            self._maybe_checkpoint()
        return progressed

    def _expire_queued(self) -> None:
        for req in self.sched_p.expire(self._steps):
            ttl = self._ttl_for(req)
            req.failure = TtlExpired(
                f"request {req.rid} (class {req.cls!r}) queued past its "
                f"TTL ({ttl} steps from step {req.submit_step}) "
                "without admission")
            self._rejected.append(req)
            self.metrics.inc("expirations")
            self.metrics.inc_class("expirations", class_label(req))
            self._jlog("expire", rid=req.rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)

    def _step_impl(self) -> bool:
        """One composed step: prefill fleet (admissions + ≤1 chunk +
        migration), delayed-report delivery, decode seating + signal-
        gated admission, then ONE full step of the sharded decode engine
        (its own admissions — the degrade rung — growth/preemption,
        decode dispatch, digest cross-check), then harvest."""
        if self.idle:
            return False
        while True:
            adm = self.sched_p.admissible(self._can_hold)
            if adm is None:
                break
            self._admit_prefill(*adm)
        ptoks = self._dispatch_prefill_chunk()
        self.metrics.observe("step_prefill_tokens", ptoks)

        for rid, exc in self.channel.tick(self._steps):
            self._poisoned.setdefault(rid, exc)
        self._seat_decode_slots()
        self._patch_and_admit()
        self.decode.step()
        self._note_degraded_progress()
        self._harvest_decode()
        self._steps += 1
        return True

    def run(self, max_steps: int | None = None,
            arrivals=None, recover=None) -> dict[int, list[int]]:
        """Drive ``step()`` until idle (or ``max_steps``); same contract
        and recovery/watchdog semantics as the disagg engine's ``run``."""
        if recover:
            assert self.journal is not None, "recover= needs a journal"
            ck = recover if isinstance(recover, ckpt_mod.Checkpoint) \
                else ckpt_mod.latest(self.journal)
            ckpt_mod.restore(self, ck, self.journal)
        pending = deque(arrivals or [])
        i = 0
        marker, since = self._progress_marker(), 0
        while max_steps is None or i < max_steps:
            while pending and pending[0][0] <= i:
                item = pending.popleft()
                self.submit(item[1], item[2],
                            tenant=item[3] if len(item) > 3 else None,
                            cls=item[4] if len(item) > 4 else None)
            if not self.step() and not pending:
                break
            i += 1
            plan = self._fault_plan if self._fault_plan is not None \
                else faults.active_plan()
            if plan is not None and plan.crash(self._steps,
                                               self._incarnation):
                self.metrics.inc("faults_injected")
                raise faults.InjectedCrash(
                    f"injected crash at step {self._steps} "
                    f"(incarnation {self._incarnation})")
            m = self._progress_marker()
            if m != marker:
                marker, since = m, 0
            else:
                since += 1
                if since >= self._stall_steps and not self.idle:
                    raise EngineStallError(self._stall_report(since)
                                           + self._postmortem())
        return {req.rid: list(req.generated) for req in self._finished}

    def _progress_marker(self) -> tuple:
        c, d = self.metrics.counters, self.metrics_decode.counters
        return (c["prefill_chunks"], c["pages_migrated"],
                c["migrate_chunks"], c["restores"], c["expirations"],
                d["tokens_generated"], d["handoffs"], d["retries"],
                d["degradations"], d["failed_requests"], d["preemptions"],
                d["prefill_chunks"], len(self._finished), len(self._failed))

    def _stall_report(self, since: int) -> str:
        rows = []
        for name, sched in (("prefill", self.sched_p),
                            ("decode", self.sched_d)):
            for slot, req in sched.active:
                rows.append(
                    f"{name}[{slot}]: rid={req.rid} {req.state.value} "
                    f"cursor={req.prefill_cursor} retries={req.retries} "
                    f"degradations={req.degradations}")
        return (f"engine made no progress for {since} steps "
                f"(stall deadline {self._stall_steps}, step {self._steps}, "
                f"mesh {self.mesh_desc}); queues: "
                f"prefill={self.sched_p.queue_depth} "
                f"handoff={len(self._handoff)} "
                f"decode={self.sched_d.queue_depth} "
                f"degraded={sorted(self._degraded)} "
                f"recovering={sorted(self._recovery)} "
                f"poisoned={sorted(self._poisoned)}; slots: "
                + ("; ".join(rows) if rows else "<none>"))

    # -- crash consistency (disagg-shaped, over both fleets) ---------------
    def control_digest(self) -> int:
        return _fnv1a(0x811C9DC5, self.alloc_p.digest(),
                      self.sched_p.digest(), self.alloc_d.digest(),
                      self.sched_d.digest())

    def _jlog(self, kind: str, **payload) -> None:
        if self.journal is None or self._journal_muted:
            return
        self.journal.append(kind, self._steps, self.control_digest(),
                            **payload)

    def _maybe_checkpoint(self) -> None:
        if (self.journal is None or not self.checkpoint_every
                or self._steps == 0
                or self._steps % self.checkpoint_every
                or self._steps == self._last_ckpt_step):
            return
        self.checkpoint()

    def checkpoint(self) -> "ckpt_mod.Checkpoint":
        assert self.journal is not None, "checkpoint() needs a journal"
        t0 = time.perf_counter()
        ck = ckpt_mod.capture(self)
        self.journal.record_checkpoint(ck.step, ck.digest, ck.state,
                                       ck.journal_seq)
        self._last_ckpt_step = self._steps
        self.metrics.inc("checkpoints")
        self.metrics.observe("checkpoint_s", time.perf_counter() - t0)
        return ck

    def _capture_state(self) -> dict:
        """Disagg-shaped snapshot over both fleets. Live order: decode
        seats by ticket, the decode queue (degraded), the handoff queue,
        prefill seats by ticket, then the prefill queue — every one
        restores as a fresh QUEUED prefill (restart-from-prompt re-earns
        pages AND re-migrates)."""
        live: list[Request] = []
        seen: set[int] = set()

        def add(r: Request | None) -> None:
            if r is not None and r.rid not in seen:
                seen.add(r.rid)
                live.append(r)

        for _, r in sorted(((r.admitted_seq, r)
                            for _, r in self.sched_d.active),
                           key=lambda t: t[0]):
            add(r)
        for r in self.sched_d.queue:
            add(r)
        for r in self._handoff:
            add(r)
        for _, r in sorted(((r.admitted_seq, r)
                            for _, r in self.sched_p.active),
                           key=lambda t: t[0]):
            add(r)
        for r in self.sched_p.queue:
            add(r)
        return {
            "engine": "disagg_sharded",
            "step": self._steps,
            "next_rid": self._next_rid,
            "admit_ticket_p": self.sched_p._admit_ticket,
            "admit_ticket_d": self.sched_d._admit_ticket,
            "pool_p": self.alloc_p.snapshot(),
            "pool_p_digest": self.alloc_p.digest(),
            "pool_d": self.alloc_d.snapshot(),
            "pool_d_digest": self.alloc_d.digest(),
            "prefix_index": (None if self.prefix_cache is None
                             else self.prefix_cache.snapshot()),
            "prefix_digest": (None if self.prefix_cache is None
                              else self.prefix_cache.digest()),
            "prefix_index_d": (None if self.decode.prefix_cache is None
                               else self.decode.prefix_cache.snapshot()),
            "prefix_digest_d": (None if self.decode.prefix_cache is None
                                else self.decode.prefix_cache.digest()),
            "live": [ckpt_mod.snapshot_request(r) for r in live],
            "finished": [ckpt_mod.snapshot_finished(r)
                         for r in self._finished],
            "failed": [{"rid": r.rid,
                        "error_type": type(r.failure).__name__,
                        "reason": str(r.failure).splitlines()[0]}
                       for r in self._failed],
            "rejected": [{"rid": r.rid, "kind": "expire"
                          if isinstance(r.failure, TtlExpired) else "reject",
                          "reason": str(r.failure), "tenant": r.tenant,
                          "cls": r.cls} for r in self._rejected],
            "policy": self.sched_p.policy_state(),
            "counters": dict(self.metrics.counters),
            "counters_decode": dict(self.metrics_decode.counters),
        }

    def _restore_state(self, state: dict | None) -> None:
        """Rebuild both fleets' host control state (None = from nothing).
        The decode engine rebuilds through its own ``_restore_state``
        (mirrors re-uploaded committed, ``sp_ranks`` preserved by the
        unified pool contract); coverage must be re-earned — the ledger
        and the channel's attempt/delay state are cleared."""
        n_sp = self.alloc_p.sp_ranks
        self.alloc_p = KVPagePool(self.alloc_p.num_pages, self.page_size,
                                  reserved=1, sp_ranks=n_sp)
        self.sched_p = ContinuousBatchingScheduler(
            self.sched_p.num_slots, queue_cap=self.sched_p.queue_cap,
            policy=self.sched_p.policy)
        if self.prefix_cache is not None:
            # empty cache on the fresh ledger: cached KV is device state,
            # re-earned by re-prefill (the decode fleet's cache resets
            # inside decode._restore_state the same way)
            self.prefix_cache = PrefixCache(self.alloc_p, self.page_size)
        self.decode._restore_state(None)
        self._handoff.clear()
        self._dslot.clear()
        self._wait_steps.clear()
        self._recovery.clear()
        self._poisoned.clear()
        self._degraded.clear()
        self._finished = []
        self._failed = []
        self._rejected = []
        self.channel.ledger = ChunkSignalLedger()
        self.channel._attempt.clear()
        self.channel._delayed.clear()
        if state is None:
            return
        ckpt_mod.audit_pool_snapshot(
            state["pool_p"], state["pool_p_digest"],
            self.alloc_p.num_pages, self.page_size, 1)
        ckpt_mod.audit_pool_snapshot(
            state["pool_d"], state["pool_d_digest"],
            self.alloc_d.num_pages, self.page_size, 1)
        for ix, dg in (("prefix_index", "prefix_digest"),
                       ("prefix_index_d", "prefix_digest_d")):
            if state.get(ix) is not None:
                ckpt_mod.audit_prefix_snapshot(state[ix], state[dg])
        self._steps = state["step"]
        self._next_rid = state["next_rid"]
        self.sched_p._admit_ticket = state["admit_ticket_p"]
        self.sched_d._admit_ticket = state["admit_ticket_d"]
        for snap in state["live"]:
            req = ckpt_mod.rebuild_request(snap)
            req.submit_time = time.perf_counter()
            ttl = self._ttl_for(req)
            if ttl is not None:
                req.deadline = Deadline(ttl, req.submit_step)
            self.sched_p.submit(req)
        # WFQ/bucket books restore AFTER the requeues: submit()'s idle-
        # class vfloor snap ran against zeroed counters above, and the
        # checkpoint values now overwrite them (order-dependent)
        self.sched_p.restore_policy_state(state.get("policy"))
        for f in state["finished"]:
            self._restore_finished(f["rid"], f["tokens"], meta=f)
        for f in state["failed"]:
            self._restore_terminal(f["rid"], "fail", f["reason"],
                                   f.get("error_type"))
        for f in state["rejected"]:
            self._restore_terminal(f["rid"], f["kind"], f["reason"])

    _ERROR_TYPES = {
        "MigrationSignalTimeout": MigrationSignalTimeout,
        "SignalProtocolError": SignalProtocolError,
        "AdmissionRejected": AdmissionRejected,
        "TtlExpired": TtlExpired,
    }

    def _restore_finished(self, rid: int, tokens: list[int],
                          meta: dict | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            prompt = tuple((meta or {}).get("prompt", (0,)))
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=len(tokens), eos_token=self.eos_id)
        req.state = RequestState.FINISHED
        req.generated = list(tokens)
        for k in ("submit_step", "first_token_step", "preemptions"):
            if meta is not None and k in meta:
                setattr(req, k, meta[k])
        self._finished.append(req)

    def _restore_terminal(self, rid: int, kind: str, reason: str,
                          error_type: str | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            req = Request(rid=rid, prompt=(0,), max_new_tokens=1,
                          eos_token=self.eos_id)
        if kind == "fail":
            req.state = RequestState.FAILED
            cls = self._ERROR_TYPES.get(error_type or "", RuntimeError)
            req.failure = cls(reason)
            self._failed.append(req)
        else:
            req.state = RequestState.REJECTED
            req.failure = (TtlExpired(reason) if kind == "expire"
                           else AdmissionRejected(reason))
            self._rejected.append(req)

    def _pop_queued(self, rid: int) -> Request | None:
        for r in self.sched_p.queue:
            if r.rid == rid:
                self.sched_p.queue.remove(r)
                return r
        return None

    def _postmortem(self) -> str:
        counters = {k: v for k, v in self.metrics.counters.items() if v}
        counters_d = {k: v for k, v in self.metrics_decode.counters.items()
                      if v}
        tail = (self.journal.format_tail(8) if self.journal is not None
                else "  <no journal attached>")
        return ("\ncounters: " + json.dumps(counters)
                + "\ncounters_decode: " + json.dumps(counters_d)
                + "\njournal tail:\n" + tail)

    @property
    def failed(self) -> list[Request]:
        return list(self._failed) + list(self._rejected)

    # -- introspection ----------------------------------------------------
    @property
    def compile_stats(self) -> dict:
        """The composition adds NO programs to the sharded engine's two
        (the prefill fleet reuses its chunk executable — same shapes,
        same committed sharding) beyond the one migration copy program."""
        def n(fn, fallback):
            try:
                return int(fn._cache_size())
            except Exception:
                return fallback

        base = self.decode.compile_stats
        stats = {
            "prefill_chunk_compiles": base["prefill_chunk_compiles"],
            "decode_compiles": base["decode_compiles"],
            "migrate_compiles": n(
                self._xmig,
                1 if self.metrics.counters["migrate_chunks"] else 0),
        }
        if self._aot_artifact is not None:
            from triton_dist_tpu.aot.artifact import LoadedProgram
            stats["aot_programs"] = (
                base.get("aot_programs", 0)
                + int(isinstance(self._xmig, LoadedProgram)))
        return stats


__all__ = ["DisaggShardedEngine"]
