"""Elastic autoscaling for the replica fleet (ISSUE 18).

The static fleet serves the ISSUE 14 diurnal swing at peak provisioning
or not at all. This module closes that gap with a deterministic policy
loop over the cluster's per-class SLO attainment: grow the fleet when a
class's windowed TTFT/ITL attainment falls below the scale-up threshold,
drain the highest-index replica when attainment is comfortably above the
scale-down threshold AND the survivors can seat the current load. Like
T3's contract (PAPERS.md), the controller may change the *schedule* —
here, fleet membership — but never the observable outputs: every request
trace stays bitwise identical to the closed-form golden through any
schedule of scale-ups, drains and crashes, because membership changes
only move WHERE a prompt re-earns its KV, never WHAT the deterministic
decode produces from it.

The sensing is :class:`~triton_dist_tpu.serving.metrics.AttainmentWindow`
over the cluster's step-space latency feed — engine steps, not wall
clock, so the same trace always yields the same decisions. Thrash
control is hysteresis (separate up/down thresholds with a dead band
between them) plus a cooldown after every membership change, so a burst
front triggers ONE scale-up, not one per bad sample.

Scale-up spins an :class:`EngineReplica` from the PR 15 AOT artifact
mid-run: the new engine reaches its first token with zero fresh traces
(``aot_programs`` asserted in the bench), so scale-up-to-first-token is
dominated by artifact load, not compilation. Scale-down runs the
graceful ladder in cluster.py: ``DRAINING`` stops admission, queued
requests requeue through the journal cursor, in-flight decodes finish in
place, hot prefixes lend ahead to their rendezvous successors
(lending.py), and only then the replica retires.

Every decision is journaled (``scale_up``/``drain_begin``/``drain_done``
/``retire`` — journal.py) through a controller-private ControlJournal,
so a controller crash loses nothing: :meth:`Autoscaler.resume` reloads
the journal, re-adopts the fleet view and the cooldown clock, and the
policy loop continues where it stopped. Replica crashes compose with the
PR 12 machinery — a replica that dies mid-drain is auto-restored
(journal replay requeues its live requests) and its drain resumes.
"""

from __future__ import annotations

import os

from triton_dist_tpu.serving.cluster import ReplicaState
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import _fnv1a
from triton_dist_tpu.serving.metrics import AttainmentWindow

__all__ = ["Autoscaler", "parse_budgets"]

# scale_history kinds the controller journals; kill/restore ride the
# replica's own journal, warm promotion is implicit in the scale_up step
_JOURNALED = ("scale_up", "drain_begin", "drain_done", "retire")


def parse_budgets(spec: str) -> dict[str, tuple[int, int | None]]:
    """Parse a CLI budget spec: ``cls:ttft[/itl][,cls:ttft[/itl]]`` with
    budgets in engine steps — e.g. ``chat:8/2,batch:64``. Step space,
    like every other SLO knob here: deterministic and replay-stable."""
    out: dict[str, tuple[int, int | None]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        cls, _, bud = part.partition(":")
        assert bud, f"budget spec {part!r} needs cls:ttft[/itl]"
        ttft, _, itl = bud.partition("/")
        out[cls.strip()] = (int(ttft), int(itl) if itl else None)
    return out


class Autoscaler:
    """Deterministic policy loop over one :class:`Cluster`.

    ``budgets`` maps class label -> TTFT budget in engine steps, or
    ``(ttft, itl)`` tuples to police inter-token latency too. The fleet
    attainment is the MINIMUM attainment across every budgeted series
    with at least ``min_samples`` observations in the window — the worst
    class drives scaling, which is what a per-class SLO means.

    Call :meth:`step` once per cluster step, AFTER ``cluster.step()``.
    """

    def __init__(self, cluster, budgets, *, window: int = 128,
                 min_samples: int = 8, min_replicas: int = 1,
                 max_replicas: int = 8, up_below: float = 0.9,
                 down_above: float = 0.98, cooldown: int = 64,
                 warm_steps: int = 1,
                 journal: "ControlJournal | str | None" = None):
        assert 1 <= min_replicas <= max_replicas
        assert 0.0 < up_below <= down_above <= 1.0, (
            "hysteresis needs up_below <= down_above — a dead band, "
            "not an oscillator")
        assert cooldown >= 1 and window >= 1
        self.cluster = cluster
        self.budgets = {
            cls: (b if isinstance(b, tuple) else (int(b), None))
            for cls, b in budgets.items()}
        assert self.budgets, "at least one class budget required"
        self.window = window
        self.min_samples = min_samples
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_below = up_below
        self.down_above = down_above
        self.cooldown = cooldown
        self.warm_steps = warm_steps
        if isinstance(journal, str):
            journal = ControlJournal(path=journal)
        self.journal = journal
        self.attain = AttainmentWindow(window)
        self.decisions: list[tuple[int, str, int]] = []
        self.scale_up_build_s: list[float] = []
        self._now = 0
        self._last_event = -cooldown    # first decision needs no warmup
        self._hcursor = 0               # cluster.scale_history high-water

    # -- sensing -----------------------------------------------------------
    def _ingest(self) -> None:
        for cls, ttft, itl in self.cluster.drain_latency_feed():
            self.attain.observe(("ttft", cls), ttft)
            if itl is not None:
                self.attain.observe(("itl", cls), itl)

    def attainment(self) -> float | None:
        """Worst windowed attainment across budgeted series with enough
        samples; None until any budgeted class has ``min_samples``."""
        worst = None
        for cls, (b_ttft, b_itl) in self.budgets.items():
            for kind, budget in (("ttft", b_ttft), ("itl", b_itl)):
                if budget is None:
                    continue
                key = (kind, cls)
                if self.attain.count(key) < self.min_samples:
                    continue
                a = self.attain.attainment(key, budget)
                worst = a if worst is None else min(worst, a)
        return worst

    # -- journal -----------------------------------------------------------
    def _digest(self) -> int:
        counts = self.cluster.lifecycle_counts()
        return _fnv1a(0x811C9DC5, len(self.cluster.replicas),
                      *(counts.get(s.value, 0) for s in ReplicaState))

    def _journal_history(self) -> None:
        """Journal every new cluster scale event (cursor-read: manual
        drains in tests/sims land in the controller journal too).
        ``hseq`` — the event's index in ``cluster.scale_history`` — is
        what resume() rebuilds the cursor from."""
        hist = self.cluster.scale_history
        while self._hcursor < len(hist):
            cstep, kind, index = hist[self._hcursor]
            if self.journal is not None and kind in _JOURNALED:
                self.journal.append(kind, self._now, self._digest(),
                                    replica=index, cluster_step=cstep,
                                    hseq=self._hcursor)
            self._hcursor += 1

    # -- the policy step ---------------------------------------------------
    def step(self) -> tuple[str, int] | None:
        """One controller tick: sense, journal, heal, decide. Returns
        the decision taken this tick (kind, replica index) or None."""
        self._now += 1
        c = self.cluster
        self._ingest()
        self._journal_history()
        # crash-mid-drain fallback (PR 12 ladder): a replica that died
        # DRAINING is restored — journal replay requeues its live
        # requests, the drain pass moves them to peers, it retires
        for rep in c.replicas:
            if (rep.lifecycle is ReplicaState.KILLED
                    and rep._prekill is ReplicaState.DRAINING):
                c.restore(rep.index)
                self._journal_history()
        if self._now - self._last_event < self.cooldown:
            return None
        att = self.attainment()
        if att is None:
            return None
        active = [r for r in c.replicas if r.admitting]
        warming = [r for r in c.replicas
                   if r.lifecycle is ReplicaState.WARMING]
        fleet = len(active) + len(warming)   # capacity present or en route
        if att < self.up_below and fleet < self.max_replicas:
            rep = c.add_replica(warm_steps=self.warm_steps)
            self.scale_up_build_s.append(rep.build_s)
            self._last_event = self._now
            self._journal_history()
            self.decisions.append((self._now, "scale_up", rep.index))
            return ("scale_up", rep.index)
        if (att >= self.down_above and not warming
                and len(active) > self.min_replicas
                and self._can_drain(active)):
            victim = max(active, key=lambda r: r.index)
            c.begin_drain(victim.index)
            self._last_event = self._now
            self._journal_history()
            self.decisions.append((self._now, "drain_begin", victim.index))
            return ("drain_begin", victim.index)
        return None

    def _can_drain(self, active) -> bool:
        """Only drain when the survivors can SEAT the fleet's current
        load — attainment says the SLO is met, this says removing a
        replica won't immediately un-meet it (the down-side half of the
        hysteresis dead band)."""
        load = sum(r.load for r in active)
        slots = sum(r._sched.num_slots for r in active)
        victim_slots = max(active, key=lambda r: r.index)._sched.num_slots
        return load <= slots - victim_slots

    # -- controller restart ------------------------------------------------
    @classmethod
    def resume(cls, cluster, journal_path: str, budgets, **kw
               ) -> "Autoscaler":
        """Rebuild a controller from its journal after a crash: reload
        the scale-event log, re-attach the append handle (same ladder as
        EngineReplica.restore), and re-adopt the fleet view — the
        history cursor from the newest ``hseq``, the cooldown clock from
        the newest event's controller step. The attainment window starts
        empty (latency samples are re-earned, like KV — the cooldown
        carried over keeps the fresh window from thrashing), and the
        cluster's lifecycle states are cross-checked against what the
        journal says retired."""
        j = ControlJournal.load(journal_path)
        j.path = journal_path
        j._fh = open(journal_path, "a", encoding="utf-8")
        asc = cls(cluster, budgets, journal=j, **kw)
        retired_in_journal: set[int] = set()
        for e in j.entries:
            if e["kind"] not in _JOURNALED:
                continue
            asc._now = max(asc._now, e["step"])
            asc._last_event = max(asc._last_event, e["step"])
            asc._hcursor = max(asc._hcursor, e["hseq"] + 1)
            asc.decisions.append((e["step"], e["kind"], e["replica"]))
            if e["kind"] == "retire":
                retired_in_journal.add(e["replica"])
        # the journal is the controller's truth — every replica it
        # recorded retired must actually be out of the fleet
        for i in retired_in_journal:
            assert cluster.replicas[i].lifecycle is ReplicaState.RETIRED, (
                f"journal says replica {i} retired but cluster has it "
                f"{cluster.replicas[i].lifecycle.value}")
        # events the dead controller never journaled replay through the
        # cursor on the next step() — nothing is lost, nothing doubled
        return asc

    @staticmethod
    def journal_path_for(journal_dir: str) -> str:
        """The controller's private journal path, namespaced beside the
        replicas' ``journal-r{i}.jsonl`` files."""
        return os.path.join(journal_dir, "journal-controller.jsonl")
