"""Serving-runtime tests (ISSUE 2): allocator + scheduler invariants, the
cache<->pages bit-exact round trip, and the headline end-to-end property —
a contended continuous-batching trace (with forced preemptions) produces
per-request tokens BIT-IDENTICAL to decoding each request alone."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import (LlamaConfig, decode_step,
                                          init_kv_cache, init_page_pool,
                                          init_params, prefill)
from triton_dist_tpu.serving import (ContinuousBatchingScheduler, KVPagePool,
                                     PageLedgerError, Request, ServingEngine,
                                     cache_to_pages, pages_to_cache)

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# allocator invariants
# ---------------------------------------------------------------------------

def test_pool_no_double_allocation():
    """A page id is owned by at most one sequence; alloc is all-or-nothing;
    reserved ids are never handed out; frees return exactly what was
    owned."""
    pool = KVPagePool(num_pages=8, page_size=16, reserved=1)
    a = pool.alloc("a", 3)
    b = pool.alloc("b", 4)
    assert a is not None and b is not None
    assert 0 not in a + b                      # reserved page never leaves
    assert len(set(a) | set(b)) == 7           # disjoint ownership
    assert pool.free_pages == 0
    assert pool.alloc("c", 1) is None          # dry: all-or-nothing None
    assert not pool.holds("c")
    assert pool.free_seq("a") == 3
    got = pool.alloc("c", 2)
    assert got is not None and set(got) <= set(a)   # recycled, still unique
    assert set(got).isdisjoint(pool.pages_of("b"))
    with pytest.raises(AssertionError):        # double free is a bug, loudly
        pool._free.append(got[0])
        pool.free_seq("c")


def test_pool_ensure_growth_math():
    pool = KVPagePool(num_pages=6, page_size=8, reserved=1)
    assert pool.ensure("s", 1) and len(pool.pages_of("s")) == 1
    assert pool.ensure("s", 8) and len(pool.pages_of("s")) == 1   # no-op
    assert pool.ensure("s", 9) and len(pool.pages_of("s")) == 2
    assert pool.ensure("s", 40) and len(pool.pages_of("s")) == 5  # 5*8=40
    assert not pool.ensure("s", 41)            # pool is 5 usable pages
    assert len(pool.pages_of("s")) == 5        # failed ensure changed nothing
    row = pool.block_table_row("s", pages_per_seq=8)
    assert len(row) == 8 and row[5:] == [0, 0, 0]


def test_pool_free_tail_partial_fill_invariants():
    """The mid-prefill preemption primitive hardened (ISSUE 6): a
    partially-filled slot keeps exactly its first ``keep`` pages in
    allocation order, the freed tail is reusable, out-of-range keeps are
    loud, and a second tail-free of already-freed pages is a detected
    double free, not silent free-list corruption."""
    pool = KVPagePool(num_pages=10, page_size=8, reserved=1)
    got = pool.alloc("s", 6)
    assert got is not None
    assert pool.free_tail("s", keep=2) == 4
    assert pool.pages_of("s") == got[:2]       # filled prefix, exact order
    assert pool.free_pages == 7
    assert pool.free_tail("s", keep=2) == 0    # idempotent no-op tail
    with pytest.raises(PageLedgerError):       # keep > owned: loud
        pool.free_tail("s", keep=3)
    with pytest.raises(PageLedgerError):
        pool.free_tail("s", keep=-1)
    # keep=0 drops ownership entirely (full-restart preemption)
    assert pool.free_tail("s", keep=0) == 2
    assert not pool.holds("s")
    assert pool.free_pages == 9
    # double free through either path is a PageLedgerError (an
    # AssertionError subclass, so it still fails python -O-less asserts)
    pool2 = KVPagePool(num_pages=6, page_size=8, reserved=1)
    mine = pool2.alloc("t", 3)
    pool2._free.append(mine[-1])               # simulate ledger corruption
    with pytest.raises(PageLedgerError, match="double free"):
        pool2.free_tail("t", keep=0)
    pool3 = KVPagePool(num_pages=6, page_size=8, reserved=1)
    mine = pool3.alloc("u", 2)
    pool3._free.append(mine[0])
    with pytest.raises(PageLedgerError, match="double free"):
        pool3.free_seq("u")


def test_pool_scratch_pages_never_migrate():
    """Migration preconditions (ISSUE 6): reserved scratch pages and
    foreign pages are refused loudly; owned non-reserved pages pass."""
    pool = KVPagePool(num_pages=8, page_size=8, reserved=2)
    a = pool.alloc("a", 3)
    pool.alloc("b", 2)
    pool.check_migratable("a", a)              # the happy path
    with pytest.raises(PageLedgerError, match="scratch"):
        pool.check_migratable("a", [0])
    with pytest.raises(PageLedgerError, match="scratch"):
        pool.check_migratable("a", [1])        # every reserved id, not just 0
    with pytest.raises(PageLedgerError, match="foreign"):
        pool.check_migratable("a", pool.pages_of("b")[:1])
    with pytest.raises(PageLedgerError, match="foreign"):
        pool.check_migratable("nobody", [a[0]])


def test_pool_landed_row_exposes_prefix_only():
    """Signal-gated block-table patching: a row exposes the landed PREFIX
    of a sequence's pages — a hole means everything after it stays hidden
    (pages are positional), and the fill id pads the rest."""
    pool = KVPagePool(num_pages=10, page_size=8, reserved=1)
    got = pool.alloc("s", 4)
    assert pool.landed_row("s", set(), 6) == [0] * 6
    assert pool.landed_row("s", set(got), 6) == got + [0, 0]
    # a hole at position 1 hides pages 2 and 3 even though they landed
    holey = {got[0], got[2], got[3]}
    assert pool.landed_row("s", holey, 6) == [got[0]] + [0] * 5
    assert pool.landed_row("s", set(got[:2]), 6, fill=9) == got[:2] + [9] * 4
    assert pool.landed_row("unknown", {1, 2}, 4) == [0] * 4


def test_pool_deterministic_replay():
    """Same alloc/free trace => same page assignment (LIFO free list)."""
    def trace():
        p = KVPagePool(12, 8, reserved=1)
        out = [tuple(p.alloc("x", 3)), tuple(p.alloc("y", 2))]
        p.free_seq("x")
        out.append(tuple(p.alloc("z", 4)))
        return out
    assert trace() == trace()


# ---------------------------------------------------------------------------
# ONE pool contract (ISSUE 12): SP-sharded AND migratable, same ledger
# ---------------------------------------------------------------------------

def test_pool_sp_padding_never_migratable():
    """An SP-aware pool pads the DEVICE array to a multiple of sp_ranks
    but the allocator never hands the pad ids out — and
    ``check_migratable`` refuses them loudly, so no migration can land
    KV in a padding slot no block table will ever expose."""
    pool = KVPagePool(num_pages=10, page_size=8, reserved=1, sp_ranks=4)
    assert pool.device_pages == 12                  # 10 padded up to 12
    got = pool.alloc("a", 3)
    pool.check_migratable("a", got)                 # real pages pass
    for pad_id in (10, 11):                         # the two padding slots
        with pytest.raises(PageLedgerError, match="padding"):
            pool.check_migratable("a", [pad_id])
    with pytest.raises(PageLedgerError, match="padding"):
        pool.check_migratable("a", [12])            # out of range entirely
    # the shard map covers the PADDED range: every device page has a home
    assert [pool.page_shard(p) for p in (0, 2, 3, 5, 6, 8, 9, 11)] == \
        [0, 0, 1, 1, 2, 2, 3, 3]
    with pytest.raises(PageLedgerError, match="outside"):
        pool.page_shard(12)


@pytest.mark.parametrize("sp_ranks", [1, 2, 4])
def test_pool_digest_layout_independent_across_sp_ranks(sp_ranks):
    """The FNV-1a control digest hashes page OWNERSHIP, not device
    layout: the same alloc / landed_row / free_tail trace digests
    identically at every sp_ranks — which is what lets the sharded
    engine's replicated-decision guard and the disagg journal compare
    digests across differently-laid-out pools."""
    def trace(n_sp):
        p = KVPagePool(num_pages=10, page_size=8, reserved=1,
                       sp_ranks=n_sp)
        a = p.alloc("a", 4)
        p.alloc("b", 2)
        out = [p.digest()]
        assert p.landed_row("a", set(a[:2]), 6) == a[:2] + [0] * 4
        p.free_tail("a", keep=2)
        p.free_seq("b")
        out.append(p.digest())
        out.append(p.snapshot())
        return out
    assert trace(sp_ranks) == trace(1)


def test_pool_free_tail_after_cross_mesh_migration():
    """The disagg-on-sharded handoff shape (compose.py): pages migrate
    from a prefill-side ledger into an SP-sharded decode-side ledger,
    then the SOURCE is partially reclaimed mid-prefill (free_tail). Both
    ledgers must stay audit-clean and the destination's landed_row must
    expose exactly the migrated prefix."""
    src = KVPagePool(num_pages=10, page_size=8, reserved=1, sp_ranks=2)
    dst = KVPagePool(num_pages=10, page_size=8, reserved=1, sp_ranks=4)
    s = src.alloc("r", 4)
    d = dst.alloc("r", 4)                   # remote reservation at admit
    src.check_migratable("r", s[:2])        # chunk 0 finalized 2 pages
    dst.check_migratable("r", d[:2])
    covered = set(d[:2])                    # ...and their signals fired
    assert dst.landed_row("r", covered, 6) == d[:2] + [0] * 4
    # mid-prefill preemption on the source: keep the 2 migrated pages
    freed = src.free_tail("r", keep=2)
    assert freed == 2 and src.pages_of("r") == s[:2]
    src.check()
    dst.check()
    # the already-migrated pages are still re-sendable (retry rung)...
    src.check_migratable("r", s[:2])
    # ...but the freed tail is not: those ids went back to the free list
    with pytest.raises(PageLedgerError, match="foreign"):
        src.check_migratable("r", s[2:])
    # full reclaim on finish frees the reservation on both sides
    src.free_seq("r")
    dst.free_seq("r")
    assert src.free_pages == src.num_pages - src.reserved
    assert dst.landed_row("r", covered, 6) == [0] * 6


# ---------------------------------------------------------------------------
# scheduler invariants
# ---------------------------------------------------------------------------

def _req(rid, plen=4, mnt=4):
    return Request(rid=rid, prompt=tuple(range(1, plen + 1)),
                   max_new_tokens=mnt)


def test_scheduler_fifo_head_of_line():
    """Admission is strict FIFO: a head request that does not fit blocks
    later (smaller) requests — no starvation-by-reordering."""
    s = ContinuousBatchingScheduler(num_slots=2)
    big, small = _req(0, plen=100), _req(1, plen=2)
    s.submit(big)
    s.submit(small)
    fits = lambda r: len(r.prompt) <= 10        # noqa: E731
    assert s.admissible(fits) is None           # big blocks the line
    slot, req = s.admissible(lambda r: True)
    assert req is big
    s.activate(slot, req)
    slot2, req2 = s.admissible(fits)
    assert req2 is small and slot2 != slot


def test_scheduler_victim_is_youngest_and_requeues_front():
    s = ContinuousBatchingScheduler(num_slots=3)
    reqs = [_req(i) for i in range(3)]
    for r in reqs:
        s.submit(r)
        slot, q = s.admissible(lambda _: True)
        s.activate(slot, q)
    assert s.pick_victim() == 2                      # youngest ticket
    assert s.pick_victim(exclude_slot=2) == 1        # next youngest
    victim = s.slots[2]
    victim.generated.extend([7, 8, 9])
    s.evict(2)
    assert s.queue[0] is victim                      # requeued at the FRONT
    assert victim.generated == [] and victim.preemptions == 1
    assert s.slots[2] is None
    # re-admission goes back into the freed slot before anything else
    slot, q = s.admissible(lambda _: True)
    assert q is victim and slot == 2


# ---------------------------------------------------------------------------
# cache <-> pages converters
# ---------------------------------------------------------------------------

def test_cache_pages_roundtrip_bit_exact():
    """cache -> pages -> cache is a bit-exact round trip (pure data
    movement), in the cache's own bf16."""
    L, B, Hkv, D, ps, n_pages, P_pool = 2, 3, 2, 64, 8, 4, 16
    S = n_pages * ps
    rng = np.random.default_rng(0)
    cache = jnp.asarray(rng.standard_normal((L, B, Hkv, S, D)),
                        jnp.bfloat16)
    pool = jnp.asarray(rng.standard_normal((L, P_pool, Hkv, ps, D)),
                       jnp.bfloat16)
    bt = jnp.asarray(rng.permutation(P_pool - 1)[:B * n_pages]
                     .reshape(B, n_pages).astype(np.int32) + 1)
    pool2 = cache_to_pages(cache, pool, bt)
    back = pages_to_cache(pool2, bt)
    assert back.dtype == cache.dtype
    np.testing.assert_array_equal(
        np.asarray(back, np.float32), np.asarray(cache, np.float32))
    # untouched pages keep their previous bits (scatter is surgical)
    untouched = np.setdiff1d(np.arange(P_pool), np.asarray(bt).ravel())
    np.testing.assert_array_equal(
        np.asarray(pool2[:, untouched], np.float32),
        np.asarray(pool[:, untouched], np.float32))


def test_page_pool_shapes_match_kernel_contract():
    cfg = LlamaConfig.tiny()
    pool = init_page_pool(cfg, num_pages=5, page_size=8)
    assert pool["k"].shape == (cfg.n_layers, 5, cfg.n_kv_heads, 8,
                               cfg.head_dim)
    assert pool["k"].dtype == cfg.dtype


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    cfg = dataclasses.replace(LlamaConfig.tiny(n_layers=2),
                              dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _mk_requests(cfg, n, seed=0, mnt_lo=2, mnt_hi=10):
    rng = np.random.RandomState(seed)
    return [(list(rng.randint(1, cfg.vocab_size,
                              size=int(rng.randint(3, 20)))),
             int(rng.randint(mnt_lo, mnt_hi)))
            for _ in range(n)]


@pytest.mark.quick
def test_engine_smoke(tiny_model):
    """Quick-tier smoke: a few requests through a 2-slot engine finish,
    tokens match the contiguous prefill+decode_step reference, and the
    metrics JSON line carries the counters."""
    import json

    cfg, params = tiny_model
    reqs = _mk_requests(cfg, 3, seed=1, mnt_hi=6)

    ref_prefill = jax.jit(lambda p, t, c: prefill(p, t, cfg, c))
    ref_step = jax.jit(lambda p, tk, ps, c: decode_step(p, tk, ps, cfg, c))

    def reference(prompt, mnt):
        cache = init_kv_cache(cfg, 1, 32)
        logits, cache = ref_prefill(params, jnp.asarray([prompt], jnp.int32),
                                    cache)
        toks = [int(jnp.argmax(logits[0]))]
        pos = len(prompt)
        while len(toks) < mnt:
            logits, cache = ref_step(
                params, jnp.asarray([toks[-1]], jnp.int32),
                jnp.int32(pos), cache)
            toks.append(int(jnp.argmax(logits[0])))
            pos += 1
        return toks

    eng = ServingEngine(params, cfg, num_slots=2, page_size=8, num_pages=16,
                        pages_per_seq=4)
    rids = [eng.submit(p, m) for p, m in reqs]
    res = eng.run(max_steps=500)
    for rid, (p, m) in zip(rids, reqs):
        assert res[rid] == reference(p, m), f"rid {rid} diverged"
    snap = json.loads(eng.metrics.json_line())
    assert snap["requests_finished"] == len(reqs)
    assert snap["tokens_generated"] == sum(m for _, m in reqs)
    assert snap["ttft_s"]["count"] == len(reqs)


@pytest.fixture(scope="module")
def golden_trace(tiny_model):
    """Golden for the acceptance trace: 50 requests through ONE
    single-slot engine with an ample pool — requests run strictly one at
    a time (per-request single-batch decoding, horizon 1)."""
    cfg, params = tiny_model
    reqs = _mk_requests(cfg, 50, seed=2, mnt_lo=6, mnt_hi=14)
    gold_eng = ServingEngine(params, cfg, num_slots=1, page_size=8,
                             num_pages=8, pages_per_seq=8)
    gold_rids = [gold_eng.submit(p, m) for p, m in reqs]
    gold = gold_eng.run(max_steps=5000)
    assert gold_eng.metrics.counters["preemptions"] == 0
    return reqs, gold_rids, gold


@pytest.mark.parametrize("horizon", [1, 4])
@pytest.mark.parametrize("chunk", [None, 64, 256])
def test_trace_bit_identical_under_preemption(tiny_model, golden_trace,
                                              chunk, horizon):
    """The acceptance trace: 50 requests through a 4-slot engine with a
    pool small enough to force preemptions. Every request's tokens must be
    bit-identical to the same request decoded in a single-batch engine
    with an uncontended pool — including every preempted request, at
    every decode horizon (K=1 per-token semantics, K=4 scanned), and on
    BOTH admit paths (bucketed inline prefill and chunked paged prefill —
    ISSUE 5's ``prefill_chunk=None`` bit-for-bit guarantee)."""
    cfg, params = tiny_model
    reqs, gold_rids, gold = golden_trace

    # contended: 4 slots, pool deliberately too small for 4 long tails —
    # growth must preempt. Arrivals staggered so admission interleaves
    # with decode of earlier requests.
    eng = ServingEngine(params, cfg, num_slots=4, page_size=8, num_pages=9,
                        pages_per_seq=8, decode_horizon=horizon,
                        prefill_chunk=chunk)
    arrivals = [(i // 2, p, m) for i, (p, m) in enumerate(reqs)]
    res = eng.run(max_steps=5000, arrivals=arrivals)
    snap = eng.metrics.snapshot()
    assert snap["requests_finished"] == len(reqs)
    assert snap["preemptions"] >= 1, "trace was meant to force preemption"
    if chunk is not None:
        # every finished request went through the chunk program at least
        # once (admissions preempted at cursor 0 may dispatch no chunk) —
        # and the bucketed prefill programs never compiled
        assert snap["prefill_chunks"] >= len(reqs)
        assert eng.compile_stats["prefill_programs"] == 0
        assert eng.compile_stats["prefill_chunk_compiles"] == 1

    preempted = [r for r in eng._finished if r.preemptions > 0]
    assert preempted, "no request actually lost work to preemption"
    rids = sorted(res)
    assert rids == sorted(gold_rids)
    for rid, grid_ in zip(rids, sorted(gold_rids)):
        assert res[rid] == gold[grid_], f"request {rid} not bit-identical"
    # spot-check: the preempted ones specifically
    for r in preempted:
        assert res[r.rid] == gold[r.rid]
    if horizon > 1:
        # the multi-token win: far fewer host dispatches than tokens, and
        # quiet dispatches re-upload nothing
        decode_toks = (snap["tokens_generated"] - snap["prefills"])
        assert snap["dispatches"] < decode_toks
        assert snap["host_syncs"] <= snap["dispatches"]


def test_engine_refuses_impossible_request(tiny_model):
    cfg, params = tiny_model
    eng = ServingEngine(params, cfg, num_slots=2, page_size=8, num_pages=4,
                        pages_per_seq=8)
    with pytest.raises(AssertionError):
        eng.submit(list(range(1, 50)), 8)      # needs 7 pages, pool has 4


def test_truncated_run_returns_only_finished(tiny_model):
    """run() with a small step budget must return ONLY finished requests —
    no None placeholders for work still in flight — and a follow-up run()
    finishes the rest."""
    cfg, params = tiny_model
    eng = ServingEngine(params, cfg, num_slots=2, page_size=8, num_pages=16,
                        pages_per_seq=4)
    reqs = _mk_requests(cfg, 5, seed=4, mnt_lo=6, mnt_hi=9)
    rids = [eng.submit(p, m) for p, m in reqs]
    res = eng.run(max_steps=3)
    assert all(v is not None for v in res.values())
    assert set(res) == {r.rid for r in eng._finished}
    assert len(res) < len(reqs)                # budget was really too small
    res2 = eng.run(max_steps=5000)
    assert set(res2) == set(rids)
    assert all(len(res2[r]) == m for r, (_, m) in zip(rids, reqs))


def test_bucketed_prefill_token_identical_to_exact(tiny_model):
    """Bucketed (padded + length-masked) prefill must produce the same
    tokens as exact-length prefill for every request — the compile-cache
    bound may not change a single sampled token."""
    cfg, params = tiny_model
    reqs = _mk_requests(cfg, 6, seed=7, mnt_lo=2, mnt_hi=7)

    def run(buckets):
        eng = ServingEngine(params, cfg, num_slots=2, page_size=8,
                            num_pages=16, pages_per_seq=4,
                            prefill_buckets=buckets)
        rids = [eng.submit(p, m) for p, m in reqs]
        return [eng.run(max_steps=2000)[r] for r in rids]

    assert run("pow2") == run(None)


def test_compile_count_guard(tiny_model, monkeypatch):
    """A trace with 20 DISTINCT prompt lengths must compile the decode
    step exactly once and at most one prefill program per bucket — the
    whole point of bucketing + shape-stable multi-step decode."""
    cfg, params = tiny_model
    real_jit = jax.jit
    made = []

    def counting_jit(fun, *a, **k):
        made.append(fun)
        return real_jit(fun, *a, **k)

    monkeypatch.setattr(jax, "jit", counting_jit)
    eng = ServingEngine(params, cfg, num_slots=4, page_size=8, num_pages=32,
                        pages_per_seq=8, decode_horizon=2,
                        prefill_buckets=(8, 16, 32))
    rng = np.random.RandomState(3)
    arrivals = []
    for i, plen in enumerate(range(3, 23)):    # 20 distinct prompt lengths
        prompt = [int(t) for t in rng.randint(1, cfg.vocab_size, size=plen)]
        arrivals.append((i, prompt, int(rng.randint(2, 8))))
    res = eng.run(max_steps=5000, arrivals=arrivals)
    assert len(res) == 20
    stats = eng.compile_stats
    assert stats["decode_compiles"] == 1
    assert stats["prefill_programs"] <= 3      # one per bucket, max
    assert stats["prefill_compiles"] <= 3
    # the jit-entry hook agrees: one decode program + one per prefill bucket
    # (pallas interpret mode jits its own internal wrappers — not ours)
    ours = [f for f in made
            if "ServingEngine" in getattr(f, "__qualname__", "")]
    assert len(ours) == 1 + stats["prefill_programs"]


def test_eos_truncation_multistep(tiny_model):
    """With eos_id set, generation stops right after the first EOS even
    mid-scan at K=4 — the frozen-lane mask must not let a finished row
    keep decoding (or keep writing KV) inside the horizon."""
    cfg, params = tiny_model
    prompt, _ = _mk_requests(cfg, 1, seed=5)[0]
    mnt = 12
    base = ServingEngine(params, cfg, num_slots=1, page_size=8, num_pages=8,
                         pages_per_seq=8, decode_horizon=4)
    rid = base.submit(prompt, mnt)
    toks = base.run(max_steps=1000)[rid]
    assert len(toks) == mnt

    eos = toks[len(toks) // 2]                 # a token we KNOW gets emitted
    first = toks.index(eos)
    eng = ServingEngine(params, cfg, num_slots=1, page_size=8, num_pages=8,
                        pages_per_seq=8, decode_horizon=4, eos_id=eos)
    rid2 = eng.submit(prompt, mnt)
    got = eng.run(max_steps=1000)[rid2]
    assert got == toks[:first + 1]             # truncated AT the EOS


@pytest.mark.parametrize("horizon", [1, 4])
def test_dispatch_count_bound(tiny_model, horizon):
    """One request alone: dispatches == ceil(decode_tokens / K) exactly,
    and host re-uploads stay rare (device state is authoritative between
    control-plane changes)."""
    cfg, params = tiny_model
    prompt, _ = _mk_requests(cfg, 1, seed=6)[0]
    mnt = 13
    eng = ServingEngine(params, cfg, num_slots=1, page_size=8, num_pages=8,
                        pages_per_seq=8, decode_horizon=horizon)
    rid = eng.submit(prompt, mnt)
    res = eng.run(max_steps=1000)
    assert len(res[rid]) == mnt
    c = eng.metrics.counters
    decode_tokens = mnt - 1                    # token 0 comes from prefill
    assert c["dispatches"] == -(-decode_tokens // horizon)
    assert c["host_syncs"] <= c["dispatches"]
    if horizon == 1:
        # only admission + page growth dirty the mirrors; the steady-state
        # dispatch re-uploads nothing
        assert c["host_syncs"] < c["dispatches"]


# ---------------------------------------------------------------------------
# chunked paged prefill (ISSUE 5)
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_compile_count_guard_chunked(tiny_model, monkeypatch):
    """With prefill_chunk set, 20 DISTINCT prompt lengths compile exactly
    TWO ServingEngine programs total: one decode step and one chunk
    program. The bucketed prefill programs never compile — start offset
    and prompt length are runtime scalars of the chunk program."""
    cfg, params = tiny_model
    real_jit = jax.jit
    made = []

    def counting_jit(fun, *a, **k):
        made.append(fun)
        return real_jit(fun, *a, **k)

    monkeypatch.setattr(jax, "jit", counting_jit)
    eng = ServingEngine(params, cfg, num_slots=4, page_size=8, num_pages=32,
                        pages_per_seq=8, decode_horizon=2,
                        prefill_buckets=(8, 16, 32), prefill_chunk=8)
    rng = np.random.RandomState(3)
    arrivals = []
    for i, plen in enumerate(range(3, 23)):    # 20 distinct prompt lengths
        prompt = [int(t) for t in rng.randint(1, cfg.vocab_size, size=plen)]
        arrivals.append((i, prompt, int(rng.randint(2, 8))))
    res = eng.run(max_steps=5000, arrivals=arrivals)
    assert len(res) == 20
    stats = eng.compile_stats
    assert stats["decode_compiles"] == 1
    assert stats["prefill_chunk_compiles"] == 1
    assert stats["prefill_programs"] == 0
    assert stats["prefill_compiles"] == 0
    ours = [f for f in made
            if "ServingEngine" in getattr(f, "__qualname__", "")]
    assert len(ours) == 2                      # decode + chunk, nothing else


@pytest.mark.quick
def test_mid_prefill_preemption_resumes_at_cursor(tiny_model):
    """A request preempted MID-prefill (cursor between chunks) resumes at
    its chunk cursor, not from chunk 0: pages already filled survive the
    eviction (free_tail keeps them) and total chunk dispatches equal the
    zero-rework count ceil(10/4) + ceil(40/4) = 13. A from-scratch restart
    would dispatch strictly more. Tokens stay bit-identical to solo."""
    cfg, params = tiny_model
    rng = np.random.RandomState(11)
    pa = [int(t) for t in rng.randint(1, cfg.vocab_size, size=10)]
    pb = [int(t) for t in rng.randint(1, cfg.vocab_size, size=40)]

    def solo(prompt, mnt):
        e = ServingEngine(params, cfg, num_slots=1, page_size=8, num_pages=8,
                          pages_per_seq=7, prefill_chunk=4)
        rid = e.submit(prompt, mnt)
        return e.run(max_steps=2000)[rid]

    gold_a, gold_b = solo(pa, 21), solo(pb, 2)

    # contended: B's 40-token prompt needs 5 pages mid-prefill while A's
    # decode tail grows — the pool (6 usable pages) forces a mid-prefill
    # eviction of B, whose cursor + filled pages must survive.
    eng = ServingEngine(params, cfg, num_slots=2, page_size=8, num_pages=7,
                        pages_per_seq=6, prefill_chunk=4)
    ra = eng.submit(pa, 21)
    rb = eng.submit(pb, 2)
    res = eng.run(max_steps=4000)
    snap = eng.metrics.snapshot()
    assert snap["preemptions"] >= 1
    assert res[ra] == gold_a and res[rb] == gold_b
    assert snap["prefill_chunks"] == 13        # ceil(10/4)+ceil(40/4): no rework


@pytest.mark.quick
def test_chunked_admit_no_converters_no_host_argmax(tiny_model, monkeypatch):
    """Acceptance criterion: the chunked admit path never calls the
    cache<->pages converters (KV is written into pages in place) and never
    argmaxes on host (the chunk program samples on device). We make the
    converter a landmine and count host syncs."""
    import triton_dist_tpu.serving.engine as engine_mod
    cfg, params = tiny_model

    def boom(*a, **k):
        raise AssertionError("cache_to_pages called on the chunked path")

    monkeypatch.setattr(engine_mod, "cache_to_pages", boom)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=8, num_pages=16,
                        pages_per_seq=4, prefill_chunk=8)
    reqs = _mk_requests(cfg, 4, seed=9, mnt_lo=2, mnt_hi=6)
    rids = [eng.submit(p, m) for p, m in reqs]
    res = eng.run(max_steps=2000)
    assert all(rid in res for rid in rids)
    snap = eng.metrics.snapshot()
    assert snap["prefills"] == len(reqs)
    assert snap["prefill_chunks"] >= len(reqs)
    # sampling stays on device: syncs only re-upload control-plane state
    assert snap["host_syncs"] <= snap["dispatches"]


@pytest.mark.quick
def test_decode_stall_bounded_by_chunk(tiny_model):
    """The headline scheduling property: with chunking on, no single step
    admits more than C prompt tokens (running decodes stall for at most
    one chunk), while the inline path admits whole prompts at once."""
    cfg, params = tiny_model
    C = 8
    reqs = _mk_requests(cfg, 8, seed=10, mnt_lo=2, mnt_hi=5)
    assert max(len(p) for p, _ in reqs) > C    # trace must exceed the chunk

    def run(chunk):
        eng = ServingEngine(params, cfg, num_slots=2, page_size=8,
                            num_pages=16, pages_per_seq=4,
                            prefill_chunk=chunk)
        arrivals = [(i, p, m) for i, (p, m) in enumerate(reqs)]
        res = eng.run(max_steps=4000, arrivals=arrivals)
        assert len(res) == len(reqs)
        return eng.metrics.snapshot()["step_prefill_tokens"]["max"]

    assert run(C) <= C                         # stall bounded by the chunk
    assert run(None) > C                       # inline path: whole prompts
