"""ReduceScatter kernel family (analog of reference
python/triton_dist/kernels/nvidia/reduce_scatter.py).

The reference builds a 2-D hierarchical RS from CE scatter copies, ring
reduce kernels and inter-node p2p (reduce_scatter.py:45-785). The TPU-native
core is a single in-kernel ring: each segment travels the ring once,
accumulating each PE's contribution on the VPU, landing on its owner after
n-1 hops — compute and communication overlap step-by-step by construction.

Flow control: relay slots are reused every 2 steps, so a receiver *acks* its
upstream sender after consuming a slot (REGULAR semaphore credits) — the
TPU-native replacement for the reference's scatter_signal flags
(gemm_reduce_scatter.py:77-87); DMA recv semaphores already provide the
arrival signal.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def _rs_ring_kernel(axis, mesh_axes, in_ref, out_ref,
                    acc, loc, comm, send_sem, recv_sems, ack_sem):
    """Ring reduce-scatter: segment j starts at PE j+1 and ends at its owner
    PE j after n-1 right-hops, accumulating every PE's contribution."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m = out_ref.shape[0]  # rows per segment
    right_idx = lax.rem(me + 1, n)
    right = shd.pe_at(mesh_axes, axis, right_idx)
    left = shd.pe_at(mesh_axes, axis, lax.rem(me - 1 + n, n))

    # entry barrier: ack credits and recv semaphores are physical registers;
    # without it a fast neighbor's call-k+1 signals could be consumed by our
    # still-running call k (see _ag_push_kernel in allgather.py). Emitted
    # before the n==1 early-out so the kernel always uses its barrier
    # semaphore — compiled TPU rejects collective_id otherwise.
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    if n == 1:
        pltpu.sync_copy(in_ref, out_ref)
        return

    # acc ← my contribution to the first segment I forward (j = me-1)
    seg0 = lax.rem(me - 1 + n, n)
    pltpu.sync_copy(in_ref.at[pl.ds(seg0 * m, m)], acc)

    for s in range(n - 1):
        slot = s % 2
        if s >= 2:
            # wait for downstream to have consumed the slot (credit)
            shd.signal_wait_until(ack_sem, 1)
        rdma = shd.putmem_nbi(comm.at[slot], acc, send_sem,
                              recv_sems.at[slot], right)
        rdma.wait_send()
        # receive the partial travelling toward me from upstream
        shd.wait_recv(comm.at[slot], recv_sems.at[slot])
        seg = lax.rem(me - s - 2 + 2 * n, n)
        pltpu.sync_copy(in_ref.at[pl.ds(seg * m, m)], loc)
        acc[...] = comm[slot] + loc[...]
        # tell upstream the slot is free again
        shd.signal_op(ack_sem, 1, left)

    pltpu.sync_copy(acc, out_ref)
    # drain credits we never waited on (acks for the last ≤2 sends)
    shd.signal_wait_until(ack_sem, min(n - 1, 2))


def _rs_call(axis: str, mesh_axes, n: int, shard):
    assert shard.shape[0] % n == 0, (
        f"reduce_scatter: leading dim {shard.shape[0]} not divisible by {n}")
    m = shard.shape[0] // n
    seg_shape = (m,) + shard.shape[1:]
    kernel = lambda i, o, *s: _rs_ring_kernel(axis, mesh_axes, i, o, *s)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(seg_shape, shard.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM(seg_shape, shard.dtype),   # acc
            pltpu.VMEM(seg_shape, shard.dtype),   # loc
            pltpu.VMEM((2,) + seg_shape, shard.dtype),  # relay slots
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=collective_id_for(f"rs_ring_{axis}")),
        interpret=default_interpret(),
    )(shard)


def reduce_scatter(ctx: ShmemContext, x: jax.Array, axis: str | None = None,
                   method: str = "auto"):
    """Reduce(sum)-scatter over ``axis``. ``x`` is globally ``(n*M, ...)``
    sharded ``P(axis)`` — each device's local ``[M, ...]`` block is its own
    full-size contribution (e.g. a GEMM partial). Device i receives the sum
    of all contributions' segment i; the result is the ``(M, ...)`` global
    array sharded ``P(axis)``. Golden: ``jax.lax.psum_scatter`` inside
    shard_map.

    ``method`` ∈ auto|ring|ring_2d. With ``axis=None`` on a multi-axis mesh
    (or ``method="ring_2d"``), runs the hierarchical RS over ALL mesh axes,
    innermost (fastest tier, ICI) first — the multi-tier analog of the
    reference's 2-D RS (reduce_scatter.py:430-785: intra-node scatter +
    per-node reduce + inter-node tier), generalized to any axis count."""
    if axis is not None and not isinstance(axis, str):
        # tuple spelling, consistent with ag_gemm/gemm_rs/all_gather: a
        # tuple of ALL mesh axes selects the hierarchical path
        if tuple(axis) != tuple(ctx.axis_names):
            raise ValueError(
                f"multi-axis reduce_scatter spans ALL mesh axes "
                f"{ctx.axis_names}; got subset/reorder {tuple(axis)!r}")
        axis = None
    involved = tuple(ctx.axis_names) if axis is None else (axis,)
    if method == "xla" or any(ctx.is_dcn_axis(a) for a in involved):
        # DCN tier: a scatter group containing a slice-crossing axis runs
        # on XLA ``psum_scatter`` end to end (remote DMA cannot cross DCN;
        # XLA's collectives route each hop over the right transport —
        # the reference's inter-node tier analog, reduce_scatter.py:430-785)
        return _rs_xla(ctx, x, involved)
    if method == "auto":
        method = "ring_2d" if (axis is None and len(ctx.axis_names) > 1) \
            else "ring"
    if method == "ring_2d":
        if axis is not None:
            raise ValueError(
                "ring_2d reduce_scatter spans ALL mesh axes; "
                f"it cannot take axis={axis!r} — use method='ring' for a "
                "single-axis RS")
        if len(ctx.axis_names) < 2:
            raise ValueError("ring_2d reduce_scatter needs a >=2-axis mesh; "
                             f"mesh axes are {ctx.axis_names}")
        return _rs_ring_2d(ctx, x)
    if method != "ring":
        raise ValueError(f"unknown reduce_scatter method {method!r}; "
                         "expected auto|ring|ring_2d")
    if axis is None:
        axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    f = lambda shard: _rs_call(axis, mesh_axes, n, shard)
    sm = ctx.shard_map(f, in_specs=P(axis), out_specs=P(axis))
    return sm(x)


def _rs_xla(ctx: ShmemContext, x: jax.Array, involved: tuple):
    """XLA-collective reduce-scatter over ``involved`` axes, outermost
    first so device (o, …, i) ends up owning the row-major P(involved)
    segment — the order the ring paths also produce."""
    from jax import lax

    def f(shard):
        out = shard
        for ax in involved:
            out = lax.psum_scatter(out, ax, scatter_dimension=0, tiled=True)
        return out

    sm = ctx.shard_map(f, in_specs=P(involved), out_specs=P(involved))
    return sm(x)


def _rs_ring_2d(ctx: ShmemContext, x: jax.Array):
    """Hierarchical RS over a multi-axis mesh: ring-RS along the minor
    (fast) axis first, then ring-RS of the surviving super-segment along
    each outer axis in turn — each row crosses a slower tier exactly once,
    already reduced over all faster tiers (the reference's
    intra-node-reduce-then-inter-node structure, reduce_scatter.py:430-785).
    Works for any axis count >= 2.

    Device (c0, …, c_{k-1}) must end up owning the row-major P(mesh_axes)
    segment, but peeling stages innermost-first leaves it with the
    reversed-order segment — so each contribution's segment blocks are
    pre-permuted (a VPU-local transpose to [n_{k-1}, …, n0, seg] order)
    before the rings; stage j then peels the leading dim by the j-th
    innermost axis."""
    mesh_axes = ctx.axis_names
    sizes = [ctx.axis_size(a) for a in mesh_axes]
    n = 1
    for s in sizes:
        n *= s

    def f(shard):
        M = shard.shape[0]
        assert M % n == 0, (M, n)
        seg = M // n
        k = len(sizes)
        xr = shard.reshape(tuple(sizes) + (seg,) + shard.shape[1:])
        xr = jnp.transpose(
            xr, tuple(range(k - 1, -1, -1)) + tuple(range(k, xr.ndim)))
        out = xr.reshape(shard.shape)
        for axis in reversed(mesh_axes):
            out = _rs_call(axis, mesh_axes, ctx.axis_size(axis), out)
        return out

    sm = ctx.shard_map(f, in_specs=P(mesh_axes), out_specs=P(mesh_axes))
    return sm(x)


__all__ = ["reduce_scatter"]
