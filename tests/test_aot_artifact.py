"""Tuned-config registry + persisted AOT serving artifact (ISSUE 15).

Two contracts under test:

1. **Registry**: winners persist as JSON keyed on
   ``(op, mesh_shape, dtype, shape_bucket)``; sigcheck is the ADMISSION
   gate — a mesh-keyed config whose kernel the verifier flags never
   becomes a persisted default (proved with a gallery-broken kernel
   through the ``run=`` override); a torn/tampered file is a typed
   ``RegistryIntegrityError``, never a silently-default sweep.

2. **Artifact**: ``build_artifact`` → fresh ``load_artifact`` →
   ``make_engine(artifact=...)`` reaches its first token with ZERO fresh
   jit traces (every ``*_compiles`` stat pinned to 0, ``aot_programs``
   pinned to the program-set size), and a 50-request forced-preemption
   trace is BIT-IDENTICAL artifact-on vs artifact-off — on the colocated
   engine and the sharded engine at n∈{1,2} (n=4 rides the slow tier).
   A stale key (spec digest, topology, jax version) is a typed
   ``ArtifactMissError``; a tampered manifest or program file is a typed
   ``ArtifactIntegrityError``.

Every test runs under the per-test SIGALRM watchdog (test_chaos.py
pattern): a wedged collective or a stalled probe must kill the test
loudly, not the suite.
"""

import json
import os
import shutil
import signal

import jax
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.aot import (ArtifactIntegrityError, ArtifactMissError,
                                 ArtifactSpec, RegistryAdmissionError,
                                 RegistryIntegrityError, TunedConfigRegistry,
                                 TunedKey, build_artifact, load_artifact,
                                 make_engine, shape_bucket_of)
from triton_dist_tpu.ops.gemm import GemmConfig

pytestmark = [pytest.mark.aot, pytest.mark.serving]

WATCHDOG_S = 240
N_REQUESTS = 50
MAX_STEPS = 100_000


@pytest.fixture(scope="module", autouse=True)
def _private_xla_cache(tmp_path_factory):
    """Run this module against a module-PRIVATE XLA persistent cache.

    ``build_artifact``/``load_artifact`` deliberately redirect and seed the
    process's persistent compilation cache — that IS the cold-start feature
    under test. Under pytest the conftest installs ONE cache dir shared by
    the whole run, so without isolation this module's rehearsals and
    artifact-entry copies would change which compile instance later test
    modules hit, breaking their run-order hermeticity (observed as a
    bit-identity failure in test_slo.py only in full-suite order)."""
    from triton_dist_tpu.aot.artifact import _reset_xla_cache

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir",
                      str(tmp_path_factory.mktemp("aot-private-xla-cache")))
    _reset_xla_cache()
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        _reset_xla_cache()


@pytest.fixture(autouse=True)
def aot_watchdog():
    def boom(signum, frame):
        raise TimeoutError(
            f"aot watchdog: test exceeded {WATCHDOG_S}s wall — an artifact "
            "build/probe or a mesh collective is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# -- 1. the tuned-config registry --------------------------------------------

def _local_key(op="grouped_gemm", bucket=((64, 128),)):
    """A single-device key (no mesh → no signal protocol → ungated)."""
    return TunedKey(op=op, mesh_shape=(), dtype="float32",
                    shape_bucket=bucket)


def test_registry_round_trip(tmp_path):
    """put → save → load → get returns the SAME configs, every key type."""
    reg = TunedConfigRegistry()
    k1 = _local_key()
    k2 = _local_key(op="moe_ffn_gated",
                    bucket=shape_bucket_of((48, 100), (4, 100, 60)))
    reg.put(k1, GemmConfig(64, 64, 64))
    reg.put(k2, 128)
    path = str(tmp_path / "tuned.json")
    reg.save(path)

    reg2 = TunedConfigRegistry.load(path)
    assert len(reg2) == 2
    assert reg2.get(k1) == GemmConfig(64, 64, 64)
    assert reg2.get(k2) == 128
    assert reg2.get(_local_key(op="nope")) is None
    assert reg2.hit_rate == pytest.approx(2 / 3)


def test_registry_tamper_is_typed(tmp_path):
    """A flipped byte in the persisted file is a RegistryIntegrityError —
    a torn registry must never silently feed default configs."""
    reg = TunedConfigRegistry()
    reg.put(_local_key(), GemmConfig(64, 64, 64))
    path = str(tmp_path / "tuned.json")
    reg.save(path)

    with open(path, encoding="utf-8") as f:
        text = f.read()
    assert '"block_m": 64' in text
    with open(path, "w", encoding="utf-8") as f:
        f.write(text.replace('"block_m": 64', '"block_m": 65', 1))
    with pytest.raises(RegistryIntegrityError, match="torn or tampered"):
        TunedConfigRegistry.load(path)


def test_registry_admits_verified_mesh_config():
    """The happy path through the admission gate: a real op's config is
    sigcheck-captured on the gate meshes and recorded as checked."""
    reg = TunedConfigRegistry()
    key = TunedKey(op="ag_gemm", mesh_shape=(2,), dtype="float32",
                   shape_bucket=((128, 128), (128, 128)))
    reg.put(key, GemmConfig(8, 16, 0))
    assert reg.get(key) == GemmConfig(8, 16, 0)
    assert reg.checked(key)


def test_registry_gate_refuses_flagged_kernel():
    """THE admission contract: a gallery-broken kernel pushed through the
    ``run=`` override is refused with a typed finding — a flagged config
    never becomes a persisted default."""
    from triton_dist_tpu.analysis.checker import UNORDERED_READ
    from triton_dist_tpu.analysis.gallery import GALLERY
    reg = TunedConfigRegistry()
    key = TunedKey(op="ag_gemm", mesh_shape=(2,), dtype="float32",
                   shape_bucket=((128, 128), (128, 128)))
    with pytest.raises(RegistryAdmissionError) as ei:
        reg.put(key, GemmConfig(8, 16, 0),
                run=GALLERY["missing_wait"].run)
    assert UNORDERED_READ in ei.value.finding_kinds
    assert reg.get(key) is None          # nothing persisted


def test_registry_refuses_unverifiable_mesh_op():
    """A mesh-keyed op with NO gate runner cannot enter a sigcheck-gated
    registry: unverified-by-construction is refused, not waved through."""
    reg = TunedConfigRegistry()
    key = TunedKey(op="mystery_op", mesh_shape=(2,), dtype="float32",
                   shape_bucket=((8, 8),))
    with pytest.raises(RegistryAdmissionError, match="no sigcheck gate"):
        reg.put(key, 64)
    # the same put is fine on an explicitly ungated registry — recorded
    # as unchecked, the caller opted out
    reg2 = TunedConfigRegistry(require_sigcheck=False)
    reg2.put(key, 64)
    assert reg2.get(key) == 64
    assert not reg2.checked(key)


# -- 2. the persisted AOT artifact -------------------------------------------
# Tight pools (9 pages, 4 slots) force growth-driven preemption in every
# trace — the bit-identity claim covers the preemption path, not a
# steady-state decode loop.

_LLAMA = {"kind": "llama", "vocab_size": 128, "d_model": 32,
          "n_layers": 1, "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
          "max_seq_len": 64, "dtype": "float32"}
_MOE = {"kind": "moe",
        "base": {"vocab_size": 128, "d_model": 128, "n_layers": 1,
                 "n_heads": 4, "n_kv_heads": 2, "d_ff": 128,
                 "max_seq_len": 128, "dtype": "float32"},
        "num_experts": 4, "topk": 2, "moe_d_ff": 64}
_POOL = {"num_slots": 4, "page_size": 8, "num_pages": 9,
         "pages_per_seq": 4, "prefill_chunk": 8}


def _trace():
    """50 bursty requests against the 9-page pool (test_sharded_serving
    idiom, same seed): preemption is forced, not incidental."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(N_REQUESTS):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        out.append((i // 2, rng.randint(1, 128, size=plen).tolist(), mnt))
    return out


def _spec(model, kind, mesh=None):
    decl = dict(_POOL, kind=kind)
    if mesh is not None:
        decl["mesh"] = mesh
    return ArtifactSpec(model=model, engines=[decl], seed=0)


def _build(tmp_path_factory, name, spec):
    out = str(tmp_path_factory.mktemp(name) / "artifact")
    build_artifact(spec, out)
    return out


@pytest.fixture(scope="module")
def colocated_art(tmp_path_factory):
    return _spec(_LLAMA, "colocated"), _build(
        tmp_path_factory, "aot-colo", _spec(_LLAMA, "colocated"))


@pytest.fixture(scope="module")
def sharded_arts(tmp_path_factory):
    """One artifact per rank count n∈{1,2} (sp is the split axis — the
    MoE's 2 KV heads cap tp at 2 but sp scales freely)."""
    out = {}
    for n in (1, 2):
        spec = _spec(_MOE, "sharded", mesh={"tp": 1, "sp": n, "ep": 1})
        out[n] = (spec, _build(tmp_path_factory, f"aot-sh{n}", spec))
    return out


def _serve(spec, art_dir=None):
    """Build the spec's engine (artifact-seeded when ``art_dir`` is set),
    serve the 50-request trace, return tokens + compile stats."""
    cfg = spec.model_config()
    params = spec.init_params()
    artifact = load_artifact(art_dir, spec=spec) if art_dir else None
    eng = make_engine(spec.engines[0], params, cfg, artifact=artifact)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    return tokens, eng.compile_stats, dict(eng.metrics.counters)


def _assert_zero_traces(stats, n_programs):
    """THE cold-start guard: no compile stat moved, every dispatched
    program came out of the artifact."""
    fresh = {k: v for k, v in stats.items()
             if k.endswith("_compiles") and v}
    assert not fresh, f"artifact cold start paid fresh traces: {fresh}"
    assert stats["aot_programs"] == n_programs, stats


def test_colocated_zero_trace_and_bit_identity(colocated_art):
    spec, art = colocated_art
    golden, g_stats, g_counters = _serve(spec)
    tokens, stats, counters = _serve(spec, art)

    assert sum(v for k, v in g_stats.items()
               if k.endswith("_compiles")) > 0     # the baseline DID trace
    _assert_zero_traces(stats, n_programs=2)       # chunk + decode
    assert g_counters["preemptions"] > 0           # the trace preempts
    assert counters["preemptions"] == g_counters["preemptions"]
    assert tokens == golden                        # bit-identical, all 50


@pytest.mark.parametrize("n", [1, 2])
def test_sharded_zero_trace_and_bit_identity(sharded_arts, n):
    spec, art = sharded_arts[n]
    golden, _, g_counters = _serve(spec)
    tokens, stats, counters = _serve(spec, art)
    _assert_zero_traces(stats, n_programs=2)       # chunk + decode
    assert counters["preemptions"] == g_counters["preemptions"] > 0
    assert tokens == golden


@pytest.mark.slow
def test_sharded_zero_trace_and_bit_identity_n4(tmp_path_factory):
    spec = _spec(_MOE, "sharded", mesh={"tp": 1, "sp": 4, "ep": 1})
    art = _build(tmp_path_factory, "aot-sh4", spec)
    golden, _, g_counters = _serve(spec)
    tokens, stats, counters = _serve(spec, art)
    _assert_zero_traces(stats, n_programs=2)
    assert counters["preemptions"] == g_counters["preemptions"] > 0
    assert tokens == golden


def test_stale_spec_is_typed_miss(colocated_art):
    """A changed fleet declaration = a different spec digest = a LOUD
    typed miss at load, never a shape error at dispatch."""
    _, art = colocated_art
    changed = _spec(dict(_LLAMA, d_model=64), "colocated")
    with pytest.raises(ArtifactMissError, match="spec digest"):
        load_artifact(art, spec=changed)


def test_missing_program_is_typed_miss(colocated_art):
    spec, art = colocated_art
    loaded = load_artifact(art, spec=spec)
    with pytest.raises(ArtifactMissError, match="holds no program"):
        loaded.program("colocated", "warp_drive")


def test_tampered_manifest_is_typed(colocated_art, tmp_path):
    """Editing the manifest without recomputing its digest is detected —
    the copy keeps the module-scoped fixture pristine."""
    _, art = colocated_art
    copy = str(tmp_path / "artifact")
    shutil.copytree(art, copy)
    mpath = os.path.join(copy, "MANIFEST.json")
    with open(mpath, encoding="utf-8") as f:
        manifest = json.load(f)
    manifest["device_count"] = 1
    with open(mpath, "w", encoding="utf-8") as f:
        json.dump(manifest, f)
    with pytest.raises(ArtifactIntegrityError, match="torn or tampered"):
        load_artifact(copy)


def test_tampered_program_is_typed(colocated_art, tmp_path):
    spec, art = colocated_art
    copy = str(tmp_path / "artifact")
    shutil.copytree(art, copy)
    pdir = os.path.join(copy, "programs")
    fname = sorted(os.listdir(pdir))[0]
    with open(os.path.join(pdir, fname), "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    loaded = load_artifact(copy, spec=spec)
    name = loaded.program_names("colocated")[0]
    with pytest.raises(ArtifactIntegrityError, match="torn or tampered"):
        loaded.program("colocated", name)


def test_jax_version_mismatch_is_typed_miss(colocated_art, tmp_path):
    """The load key covers the jax version — a manifest from another
    toolchain misses loudly (digest recomputed, so this is the MISS path,
    not the tamper path)."""
    _, art = colocated_art
    copy = str(tmp_path / "artifact")
    shutil.copytree(art, copy)
    mpath = os.path.join(copy, "MANIFEST.json")
    with open(mpath, encoding="utf-8") as f:
        manifest = json.load(f)
    manifest["jax"] = "0.0.1"
    from triton_dist_tpu.aot.artifact import _canon_digest
    manifest["digest"] = _canon_digest(
        {k: v for k, v in manifest.items() if k != "digest"})
    with open(mpath, "w", encoding="utf-8") as f:
        json.dump(manifest, f)
    with pytest.raises(ArtifactMissError, match="jax 0.0.1"):
        load_artifact(copy)
