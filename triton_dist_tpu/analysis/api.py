"""Top-level sigcheck entry points: check one op, the whole registry, or the
broken-kernel gallery.

``sigcheck(run, op=...)`` instantiates the op at several concrete rank
counts (default n ∈ {2, 3, 4} — enough to expose wait cycles whose period
divides the ring length), captures the per-rank event streams and runs the
cross-rank checker on each, then fits the peer-pattern summary across all
captured n. A capture-time exception becomes a ``capture_error`` finding
rather than an escape: an op the verifier cannot replay is a verifier
coverage bug and must fail loudly, not silently pass.
"""

from __future__ import annotations

import dataclasses
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .capture import capture_op
from .checker import (CAPTURE_ERROR, Finding, check_events,
                      fit_peer_patterns)

DEFAULT_MESHES: Tuple[Dict[str, int], ...] = (
    {"x": 2}, {"x": 3}, {"x": 4})


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


@dataclasses.dataclass
class OpReport:
    """Verification result for one registered op (or one gallery kernel)."""

    op: str
    ns: List[int] = dataclasses.field(default_factory=list)
    findings: List[Finding] = dataclasses.field(default_factory=list)
    protocol: Dict[str, str] = dataclasses.field(default_factory=dict)
    event_counts: Dict[int, int] = dataclasses.field(default_factory=dict)
    skipped: Optional[str] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    @property
    def finding_kinds(self) -> List[str]:
        return [f.kind for f in self.findings]

    def to_json(self) -> dict:
        return {
            "op": self.op,
            "ns": self.ns,
            "skipped": self.skipped,
            "event_counts": {str(n): c for n, c in self.event_counts.items()},
            "protocol": self.protocol,
            "findings": [f.to_json() for f in self.findings],
        }


def sigcheck(run: Callable[..., Any], op: str = "op",
             meshes: Sequence[Dict[str, int]] = DEFAULT_MESHES) -> OpReport:
    """Capture ``run(ctx)`` on each mesh in ``meshes`` and verify the
    recorded signal protocol. ``run`` receives a
    :class:`~.capture.FakeContext` and should invoke the op end to end the
    way real callers do (workspace creation included)."""
    report = OpReport(op=op)
    streams_by_n: Dict[int, Dict[int, list]] = {}
    for mesh in meshes:
        n = _prod(mesh.values())
        report.ns.append(n)
        try:
            streams = capture_op(run, mesh)
        except Exception as exc:  # noqa: BLE001 — must become a finding
            tb = traceback.format_exc(limit=8).strip().splitlines()
            report.findings.append(Finding(
                CAPTURE_ERROR, op, n,
                f"capture raised {type(exc).__name__}: {exc}",
                events=tb[-6:]))
            continue
        streams_by_n[n] = streams
        report.event_counts[n] = sum(len(v) for v in streams.values())
        report.findings.extend(check_events(op, streams, n))
    if streams_by_n:
        report.protocol = fit_peer_patterns(streams_by_n)
    return report


def check_registry(names: Optional[Sequence[str]] = None
                   ) -> Dict[str, OpReport]:
    """Run sigcheck over every registered op (or just ``names``). Skipped
    entries yield an :class:`OpReport` with ``skipped`` set so reports stay
    surface-complete."""
    from .registry import REGISTRY

    reports: Dict[str, OpReport] = {}
    for name, entry in REGISTRY.items():
        if names is not None and name not in names:
            continue
        if entry.skip is not None:
            reports[name] = OpReport(op=name, skipped=entry.skip)
            continue
        reports[name] = sigcheck(entry.run, op=name, meshes=entry.meshes)
    return reports


def check_gallery() -> Dict[str, Tuple[str, OpReport]]:
    """Run sigcheck over the intentionally-broken gallery kernels. Returns
    name → (expected finding kind, report); callers assert the expected
    kind is present (a gallery kernel that sigcheck stops flagging means a
    checker regression)."""
    from .gallery import GALLERY

    out: Dict[str, Tuple[str, OpReport]] = {}
    for name, entry in GALLERY.items():
        if entry.lint is not None:
            report = OpReport(op=name, findings=entry.lint())
        else:
            report = sigcheck(entry.run, op=name, meshes=entry.meshes)
        out[name] = (entry.expected, report)
    return out
