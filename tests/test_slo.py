"""Multi-tenant SLO scheduling (ISSUE 14): WFQ isolation, quotas,
per-class shedding, and deadline-aware chunk sizing held to the same
bit-identity contract as everything else in the serving tier.

The tentpole claim is *isolation under bursty overload*: a batch-tier
flood must not change a single admitted chat token, chat TTFT must stay
within a fixed bound of its unflooded value, and every shed request must
carry a TYPED terminal naming its class — on the colocated engine and on
the mesh (n ∈ {1, 2, 4}). The policy plumbing itself must compose with
the ISSUE 7 fault ladder and the ISSUE 9 crash-recovery contract, so the
chaos schedules and the strided crash sweep re-run here under two-class
WFQ and must still be bit-identical to their (policied) goldens.

Layers pinned, cheapest first:

- **scheduler units** (no model, no device): WFQ weighted shares and the
  idle-class virtual-time snap-up, token-bucket throttle/refill/deficit,
  youngest-within-lowest-class victim ordering, per-class caps/TTLs,
  digest sensitivity to class regrouping and bucket levels, policy-book
  capture/restore round-trip.
- **spec parsing**: every malformed --workload / --slo field fails with
  a ValueError NAMING the field; traces are pure functions of the spec.
- **journal schema**: the checked-in headerless v1 fixture loads with
  default tenant/class backfill (pre-ISSUE-14 journals replay under the
  new engines); v2 files lead with a schema header.
- **engine integration**: batch-flood isolation (tokens + TTFT bound +
  typed per-class shed) colocated and sharded, deadline-aware chunk
  shrink with flat compile_stats, chaos schedules and the crash sweep
  under WFQ.

Every test runs under the per-test SIGALRM watchdog (test_chaos.py
pattern)."""

import dataclasses
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from test_chaos import SCHEDULES
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.serving import (AdmissionRejected, ControlJournal,
                                     DisaggServingEngine, ServingEngine,
                                     ShardedServingEngine, TtlExpired,
                                     serving_mesh)
from triton_dist_tpu.serving.deadline import Deadline
from triton_dist_tpu.serving.journal import SCHEMA_VERSION
from triton_dist_tpu.serving.scheduler import (ClassSpec,
                                               ContinuousBatchingScheduler,
                                               Request, SLOPolicy)
from triton_dist_tpu.serving.workload import (WorkloadSpec, generate_arrivals,
                                              parse_slo, parse_workload)
from triton_dist_tpu.shmem import FaultPlan
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.shmem.faults import InjectedCrash

pytestmark = [pytest.mark.slo, pytest.mark.serving, pytest.mark.quick]

WATCHDOG_S = 240
MAX_STEPS = 6000
WIRE = jnp.float8_e4m3fn


@pytest.fixture(autouse=True)
def slo_watchdog():
    """Hard per-test wall-clock watchdog: a scheduling bug that starves a
    class must kill the test loudly, not stall the suite."""
    def boom(signum, frame):
        raise TimeoutError(
            f"slo watchdog: test exceeded {WATCHDOG_S}s wall — the "
            "engine (or the policy scheduler) is starving/hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# ------------------------------------------------------ scheduler helpers
def _policy(**kw):
    return SLOPolicy.chat_batch(**kw)


def _req(rid, cls="chat", tenant=None, plen=4, mnt=4):
    return Request(rid=rid, prompt=tuple(range(1, plen + 1)),
                   max_new_tokens=mnt, tenant=tenant or f"{cls[0]}0",
                   cls=cls, shed_level=0)


def _sched(policy, num_slots=1):
    s = ContinuousBatchingScheduler(num_slots, policy=policy)
    return s


def _submit(s, req):
    s.stamp(req, req.tenant, req.cls)
    s.submit(req)
    return req


def _drain_one(s):
    """One admission + instant completion — isolates WFQ admission order
    from everything else the engine does."""
    adm = s.admissible(lambda r: True)
    if adm is None:
        return None
    slot, req = adm
    s.activate(slot, req)
    req.generated = [1] * req.max_new_tokens
    s.finish(slot)
    return req.cls


# ------------------------------------------------------------- WFQ units
def test_wfq_weighted_share():
    """weight 4:1 with equal-cost requests → 4:1 admission counts under
    sustained two-class backlog, and the order is deterministic."""
    orders = []
    for _ in range(2):
        s = _sched(_policy(chat_weight=4, batch_weight=1))
        for i in range(8):
            _submit(s, _req(i, "chat"))
            _submit(s, _req(100 + i, "batch"))
        order = [_drain_one(s) for _ in range(10)]
        orders.append(order)
        assert order.count("chat") == 8 and order.count("batch") == 2, order
    assert orders[0] == orders[1], "WFQ admission order is not deterministic"


def test_wfq_fifo_within_class():
    s = _sched(_policy(chat_weight=1, batch_weight=1), num_slots=2)
    reqs = [_submit(s, _req(i, "chat")) for i in range(4)]
    admitted = []
    for _ in range(4):
        slot, req = s.admissible(lambda r: True)
        s.activate(slot, req)
        admitted.append(req.rid)
        s.slots[slot] = None           # vacate without finishing
    assert admitted == [r.rid for r in reqs], "intra-class order not FIFO"


def test_wfq_idle_class_cannot_bank_service():
    """A class idle while the other drains must snap UP to the virtual-
    time floor on re-arrival — equal weights then ALTERNATE rather than
    letting the newcomer monopolize with its banked zero service."""
    s = _sched(_policy(chat_weight=1, batch_weight=1))
    for i in range(8):
        _submit(s, _req(100 + i, "batch"))
    for _ in range(6):                  # batch-only era: service builds
        assert _drain_one(s) == "batch"
    for i in range(4):
        _submit(s, _req(i, "chat"))
    order = [_drain_one(s) for _ in range(4)]
    assert order == ["chat", "batch", "chat", "batch"], (
        f"idle chat banked service and monopolized: {order}")


# ----------------------------------------------------------- quota units
def test_token_bucket_throttles_then_refills():
    s = _sched(_policy(quotas={"t0": (1, 2)}))
    _submit(s, _req(0, "chat", tenant="t0"))      # cost 8, burst 2
    _submit(s, _req(1, "chat", tenant="t0"))
    slot, req = s.admissible(lambda r: True)      # level 2 > 0: admits
    s.activate(slot, req)
    assert req.rid == 0 and s._bucket["t0"][0] == 2 - req.cost  # deficit
    s.slots[slot] = None
    throttled0 = s.quota_throttled
    for now in range(1, 7):                        # -6 + 6 = 0: still dry
        s.tick(now)
        assert s.admissible(lambda r: True) is None
    assert s.quota_throttled == throttled0 + 6, "throttle skips uncounted"
    s.tick(7)                                      # level 1 > 0
    slot, req = s.admissible(lambda r: True)
    assert req.rid == 1, "bucket refill never re-admitted the tenant"


def test_token_bucket_clamps_at_burst():
    s = _sched(_policy(quotas={"t0": (5, 3)}))
    s.tick(100)
    assert s._bucket["t0"] == [3, 100], "refill overshot the burst cap"


def test_unquotaed_tenant_never_throttled():
    s = _sched(_policy(quotas={"t0": (1, 1)}))
    _submit(s, _req(0, "chat", tenant="anon"))
    before = s.quota_throttled
    assert s.admissible(lambda r: True) is not None
    assert s.quota_throttled == before


def test_dry_bucket_blocks_only_its_class():
    """The isolation property the flood test leans on: a dry batch
    tenant must not head-of-line-block the chat tier."""
    s = _sched(_policy(quotas={"b0": (1, 1)}))
    _submit(s, _req(0, "batch", tenant="b0"))
    slot, req = s.admissible(lambda r: True)
    s.activate(slot, req)                          # b0 now in deficit
    s.slots[slot] = None
    _submit(s, _req(1, "batch", tenant="b0"))      # dry
    _submit(s, _req(2, "chat"))
    slot, req = s.admissible(lambda r: True)
    assert req.rid == 2, "dry batch bucket blocked the chat class"


# ------------------------------------------------- victim/shed/TTL units
def test_pick_victim_lowest_class_youngest_first():
    s = _sched(_policy(), num_slots=4)
    for slot, (rid, cls) in enumerate(
            [(0, "chat"), (1, "batch"), (2, "batch"), (3, "chat")]):
        r = _req(rid, cls)
        s.stamp(r, r.tenant, r.cls)
        s.place(slot, r)               # admitted_seq = seating order
    assert s.pick_victim() == 2                    # youngest batch
    assert s.pick_victim(exclude_slot=2) == 1      # older batch next
    s.slots[1] = s.slots[2] = None
    assert s.pick_victim() == 3, "chat order should be youngest-first"


def test_per_class_queue_cap_composes_with_global():
    s = ContinuousBatchingScheduler(
        1, queue_cap=10, policy=_policy(batch_queue_cap=2))
    for i in range(2):
        _submit(s, _req(i, "batch"))
    assert s.at_capacity_for("batch") and not s.at_capacity_for("chat")
    for i in range(8):
        _submit(s, _req(10 + i, "chat"))
    assert s.at_capacity_for("chat"), "global cap stopped composing"


def test_expire_sweeps_only_ttl_armed_never_admitted():
    s = _sched(_policy(batch_ttl_steps=3), num_slots=2)
    b = _submit(s, _req(0, "batch"))
    b.deadline = Deadline(3, 0)
    c = _submit(s, _req(1, "chat"))                # no TTL: never expires
    requeued = _submit(s, _req(2, "batch"))
    requeued.deadline = Deadline(3, 0)
    requeued.admitted_seq = 5                      # preemption requeue
    assert s.expire(2) == []
    assert s.expire(50) == [b], "TTL swept the wrong requests"
    assert b.state.value == "rejected" and b not in s.queue
    assert c in s.queue and requeued in s.queue


# ------------------------------------------------------ digest/checkpoint
def test_digest_folds_class_regrouping_and_buckets():
    def build(swap=False):
        s = _sched(_policy(quotas={"t0": (1, 4)}))
        a, b = ("batch", "chat") if swap else ("chat", "batch")
        _submit(s, _req(0, a))
        _submit(s, _req(1, b))
        return s

    assert build().digest() == build().digest()
    assert build().digest() != build(swap=True).digest(), (
        "class regrouping of the same rids must fork the digest")
    s = build()
    d0 = s.digest()
    s._bucket["t0"][0] -= 1
    assert s.digest() != d0, "bucket level is outside the digest"
    s._bucket["t0"][0] += 1
    s._service["chat"] += 1
    assert s.digest() != d0, "WFQ service counter is outside the digest"


def test_policy_books_capture_restore_round_trip():
    s = _sched(_policy(quotas={"c0": (2, 6)}))
    for i in range(4):
        _submit(s, _req(i, "chat" if i % 2 else "batch"))
    for _ in range(3):
        _drain_one(s)
    s.tick(9)
    state = s.policy_state()
    s2 = _sched(_policy(quotas={"c0": (2, 6)}))
    s2.restore_policy_state(state)
    assert s2.policy_state() == state, "policy books did not round-trip"
    # negative (deficit) levels survive the round trip too
    s._bucket["c0"][0] = -17
    s2.restore_policy_state(s.policy_state())
    assert s2._bucket["c0"][0] == -17


def test_stamp_validates_class_and_maps_default():
    s = _sched(_policy())
    r = _req(0)
    s.stamp(r, "t9", None)
    assert r.cls == "chat" and r.shed_level == 0   # policy default
    r2 = Request(rid=1, prompt=(1,), max_new_tokens=1)
    s.stamp(r2, None, "default")                   # v1-journal backfill
    assert r2.cls == "chat"
    with pytest.raises(KeyError, match="unknown class"):
        s.stamp(_req(2), None, "platinum")


# ------------------------------------------------------------ spec parsing
def test_parse_workload_round_trips_every_field():
    spec = parse_workload(
        "n=30,seed=7,chat=0.6,rate=0.8,burst_every=32,burst_len=8,"
        "burst_x=4,zipf=1.2,prefixes=4,tenants=2,plen=4:16,mnt=2:8")
    assert spec == WorkloadSpec(n=30, seed=7, chat=0.6, rate=0.8,
                                burst_every=32, burst_len=8, burst_x=4.0,
                                zipf=1.2, prefixes=4, tenants=2,
                                plen=(4, 16), mnt=(2, 8))
    assert parse_workload("") == WorkloadSpec()    # all defaults


@pytest.mark.parametrize("spec,field", [
    ("n=0", "n"),
    ("n=many", "n"),
    ("chat=1.5", "chat"),
    ("rate=0", "rate"),
    ("rate=fast", "rate"),
    ("burst_len=9,burst_every=4", "burst_len"),
    ("burst_x=0.5", "burst_x"),
    ("zipf=1.0", "zipf"),
    ("tenants=0", "tenants"),
    ("plen=9:2", "plen"),
    ("plen=4-9", "plen"),
    ("mnt=0:3", "mnt"),
    ("frobs=3", "frobs"),
    ("n", "'n'"),
])
def test_parse_workload_errors_name_the_field(spec, field):
    with pytest.raises(ValueError, match="workload spec field") as ei:
        parse_workload(spec)
    assert field in str(ei.value), (
        f"error for {spec!r} does not name {field!r}: {ei.value}")


@pytest.mark.parametrize("spec,field", [
    ("chat_weight=heavy", "chat_weight"),
    ("batch_ttl=soon", "batch_ttl"),
    ("quota=b0:1", "quota"),
    ("quota=b0:1:fat", "quota"),
    ("tier=gold", "tier"),
])
def test_parse_slo_errors_name_the_field(spec, field):
    with pytest.raises(ValueError, match="slo spec field") as ei:
        parse_slo(spec)
    assert field in str(ei.value)


def test_parse_slo_builds_chat_batch_policy():
    p = parse_slo("chat_weight=3,batch_cap=5,batch_ttl=40,quota=b0:1:4|c1:2:8")
    assert p.spec("chat").weight == 3 and p.spec("chat").level == 0
    assert p.spec("batch").queue_cap == 5
    assert p.spec("batch").ttl_steps == 40
    assert dict(p.quotas) == {"b0": (1, 4), "c1": (2, 8)}


def test_generate_arrivals_deterministic_and_well_formed():
    spec = parse_workload("n=40,seed=3,chat=0.7,rate=1.0,plen=4:12,mnt=2:6")
    a1 = generate_arrivals(spec)
    a2 = generate_arrivals(spec)
    assert a1 == a2, "same spec must replay the same trace bitwise"
    assert a1 != generate_arrivals(dataclasses.replace(spec, seed=4))
    assert len(a1) == 40
    steps = [s for s, *_ in a1]
    assert steps == sorted(steps)
    for step, prompt, mnt, tenant, cls in a1:
        assert cls in ("chat", "batch") and tenant.startswith(cls[0])
        assert 4 <= len(prompt) <= 12 and 2 <= mnt <= 6
    assert {c for *_, c in a1} == {"chat", "batch"}


def test_generate_arrivals_bursts_are_denser():
    spec = parse_workload(
        "n=400,seed=1,rate=0.5,burst_every=40,burst_len=10,burst_x=6")
    arr = generate_arrivals(spec)
    in_b = sum(1 for s, *_ in arr if (s % 40) < 10)
    out_b = len(arr) - in_b
    # 10 burst steps at 3/step vs 30 quiet steps at 0.5/step per period:
    # per-step density in-burst must dominate clearly
    assert in_b / 10 > 2 * (out_b / 30), (
        f"burst windows not denser: {in_b} in, {out_b} out")


# -------------------------------------------------------- journal schema
def test_journal_v1_fixture_loads_with_backfill():
    """The checked-in pre-ISSUE-14 journal (headerless = v1): classed
    kinds gain the default tenant/cls stamps, nothing else changes, and
    a save() round-trip re-emits it as v2 with identical entries."""
    j = ControlJournal.load("tests/fixtures/journal_v1.jsonl")
    assert j.schema == 1 and len(j) == 11
    for e in j.entries:
        if e["kind"] in ("submit", "reject", "expire"):
            assert e["tenant"] == "default" and e["cls"] == "default", e
        else:
            assert "tenant" not in e and "cls" not in e, (
                f"backfill leaked onto {e['kind']}")
    assert j.counts() == {"submit": 3, "admit": 2, "chunk": 1,
                          "reject": 1, "checkpoint": 1, "expire": 1,
                          "finish": 2}


def test_journal_v1_fixture_save_round_trip(tmp_path):
    j = ControlJournal.load("tests/fixtures/journal_v1.jsonl")
    p = tmp_path / "upgraded.jsonl"
    j.save(str(p))
    j2 = ControlJournal.load(str(p))
    assert j2.entries == j.entries
    # the rewrite leads with ITS schema header; entries stamped once,
    # backfill does not double-apply
    assert p.read_text().splitlines()[0] == '{"schema": 1}'


def test_journal_v2_header_on_fresh_files(tmp_path):
    p = tmp_path / "live.jsonl"
    j = ControlJournal(path=str(p))
    j.append("submit", 0, 1, rid=0, prompt=[1], max_new_tokens=1,
             tenant="t0", cls="chat")
    j.close()
    lines = p.read_text().splitlines()
    assert lines[0] == '{"schema": %d}' % SCHEMA_VERSION
    j2 = ControlJournal.load(str(p))
    assert j2.schema == SCHEMA_VERSION
    assert j2.entries[0]["tenant"] == "t0"         # no backfill on v2


# ------------------------------------------------------ engine fixtures
@pytest.fixture(scope="module")
def tiny_model():
    cfg = dataclasses.replace(
        LlamaConfig(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
                    n_kv_heads=1, d_ff=64, max_seq_len=64),
        dtype=jnp.float32)
    params = init_params(jax.random.key(1), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def moe_model():
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def role_ctx():
    return initialize_distributed(axis_names=("role",), mesh_shape=(2,))


def _colocated(tiny_model, **kw):
    cfg, params = tiny_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 16)
    kw.setdefault("pages_per_seq", 6)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_buckets", None)
    return ServingEngine(params, cfg, **kw)


def _sharded(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 12)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _disagg(tiny_model, ctx, **kw):
    cfg, params = tiny_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("num_prefill_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("pages_per_seq", 6)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("signal_deadline_steps", 3)
    kw.setdefault("max_retries", 3)
    return DisaggServingEngine(params, cfg, ctx=ctx, **kw)


FLOOD_POLICY = dict(chat_weight=4, batch_weight=1, batch_queue_cap=6,
                    batch_ttl_steps=40)


def _chat_trace(n=12, seed=5, vocab=128):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(3, 15))
        mnt = int(rng.randint(2, 6))
        out.append((2 * i, rng.randint(1, vocab, size=plen).tolist(), mnt,
                    f"c{i % 3}", "chat"))
    return out


def _batch_flood(n=24, seed=9, vocab=128, max_plen=30):
    """The burst: long batch prompts slamming the queue in the first few
    steps — far beyond what the batch queue cap admits. ``max_plen``
    keeps the flood inside the engine's pages_per_seq ceiling."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(12, max_plen))
        mnt = int(rng.randint(4, 8))
        out.append((i % 6, rng.randint(1, vocab, size=plen).tolist(), mnt,
                    f"b{i % 2}", "batch"))
    return out


def _chat_map(eng):
    """prompt → tokens for finished chat requests (rids differ between
    the flooded and unflooded runs; prompts are the stable key)."""
    return {tuple(r.prompt): list(r.generated)
            for r in eng._finished if r.cls == "chat"}


def _chat_ttft(eng):
    """Step-clock TTFT per finished chat request — deterministic, unlike
    wall time."""
    return sorted(r.first_token_step - r.submit_step
                  for r in eng._finished if r.cls == "chat")


# ---------------------------------------------------- flood isolation
def test_flood_isolation_colocated(tiny_model):
    """The headline: a 2x batch flood on the colocated engine sheds ONLY
    batch (typed, class-named), admits and finishes every chat request
    with tokens bit-identical to the unflooded golden, and holds chat
    TTFT within a fixed bound of the unflooded p99."""
    chat = _chat_trace()
    slo = SLOPolicy.chat_batch(**FLOOD_POLICY)
    golden = _colocated(tiny_model, slo=slo)
    golden.run(max_steps=MAX_STEPS, arrivals=chat)
    gold_map, gold_ttft = _chat_map(golden), _chat_ttft(golden)
    assert len(gold_map) == len(chat)

    flooded = _colocated(tiny_model, slo=slo)
    arrivals = sorted(chat + _batch_flood(), key=lambda a: a[0])
    flooded.run(max_steps=MAX_STEPS, arrivals=arrivals)

    # every chat request finished, bit-identical to the unflooded golden
    assert _chat_map(flooded) == gold_map, (
        "batch flood changed admitted chat tokens")
    # all shedding is batch-tier and typed
    shed = flooded._rejected
    assert shed, "flood never shed — the overload lost its teeth"
    for r in shed:
        assert r.cls == "batch", f"chat request {r.rid} was shed"
        assert isinstance(r.failure, (AdmissionRejected, TtlExpired))
        assert "'batch'" in str(r.failure), "terminal does not name class"
    c = flooded.metrics.counters
    assert c.get("rejections{class=batch}", 0) \
        + c.get("expirations{class=batch}", 0) == len(shed)
    assert c.get("rejections{class=chat}", 0) == 0
    assert c.get("expirations{class=chat}", 0) == 0
    # chat TTFT bound (step clock): flooded p99 within a fixed budget of
    # the unflooded p99 — the WFQ isolation claim, as a number
    budget = 3 * gold_ttft[-1] + 12
    assert _chat_ttft(flooded)[-1] <= budget, (
        f"flooded chat p99 TTFT {_chat_ttft(flooded)[-1]} steps blew the "
        f"{budget}-step bound (unflooded p99 {gold_ttft[-1]})")


@pytest.mark.mesh
@pytest.mark.parametrize("tp,sp,ep", [(1, 1, 1), (1, 2, 1), (2, 2, 1)])
def test_flood_isolation_sharded(moe_model, tp, sp, ep):
    """Same isolation contract on the mesh (n ∈ {1, 2, 4}): admitted
    chat tokens bit-identical to the n=1 unflooded golden — the policy
    books are replicated host state, so WFQ must not fork the digest."""
    chat = _chat_trace(n=8)
    slo = SLOPolicy.chat_batch(**FLOOD_POLICY)
    golden = _sharded(moe_model, 1, 1, 1, slo=slo)
    golden.run(max_steps=MAX_STEPS, arrivals=chat)
    gold_map = _chat_map(golden)
    assert len(gold_map) == len(chat)

    flooded = _sharded(moe_model, tp, sp, ep, slo=slo)
    arrivals = sorted(chat + _batch_flood(n=12, max_plen=24),
                      key=lambda a: a[0])
    flooded.run(max_steps=MAX_STEPS, arrivals=arrivals)
    assert _chat_map(flooded) == gold_map, (
        f"mesh {tp}x{sp}x{ep}: flood changed admitted chat tokens")
    for r in flooded._rejected:
        assert r.cls == "batch", f"chat shed on mesh {tp}x{sp}x{ep}"


# ------------------------------------------- deadline-aware chunk sizing
def test_chunk_shrink_fires_with_flat_compile_stats(tiny_model):
    """chat_stall_budget shrinks co-scheduled batch prefill chunks while
    a chat request decodes — through the SAME chunk program (runtime
    prompt_len scalar), so compile_stats stays at one decode + one chunk
    program and tokens are bit-identical to the unbudgeted run."""
    rng = np.random.RandomState(21)
    arrivals = [(0, rng.randint(1, 128, size=4).tolist(), 12, "c0", "chat")]
    for i in range(4):
        arrivals.append((1 + i, rng.randint(1, 128, size=24).tolist(), 2,
                         "b0", "batch"))

    res_by_budget = {}
    for budget in (None, 4):
        eng = _colocated(tiny_model, slo=SLOPolicy.chat_batch(
            chat_stall_budget=budget))
        res = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
        res_by_budget[budget] = res
        stats = eng.compile_stats
        assert stats["decode_compiles"] == 1, stats
        assert stats["prefill_chunk_compiles"] == 1, (
            f"chunk shrink compiled a new program: {stats}")
        shrinks = eng.metrics.counters["chunk_shrinks"]
        if budget is None:
            assert shrinks == 0
        else:
            assert shrinks > 0, "stall budget never shrank a chunk"
    assert res_by_budget[None] == res_by_budget[4], (
        "chunk shrink changed tokens")


def test_unpoliced_engine_has_no_class_metrics(tiny_model):
    """Pay-for-play: without a policy the metrics panel is exactly the
    pre-ISSUE-14 shape — no {class=...} keys, no quota counters moving."""
    eng = _colocated(tiny_model)
    eng.run(max_steps=MAX_STEPS,
            arrivals=[(0, [3, 5, 7], 3), (1, [2, 4, 6, 8], 2)])
    assert len(eng._finished) == 2
    assert not [k for k in eng.metrics.counters if "{class=" in k]
    assert eng.metrics.counters["quota_throttled"] == 0
    assert eng.metrics.counters["chunk_shrinks"] == 0


# ------------------------------------- chaos + crash recovery under WFQ
def _two_class_trace(n=24, seed=77, vocab=128):
    """The chaos/crash trace with class stamps: same shape as the ISSUE
    7/9 suites' _trace, alternating tenants, no caps/quotas in the
    policy — shedding must stay OFF so every request reaches a terminal
    the goldens can be compared against."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        cls = "batch" if i % 3 == 0 else "chat"
        out.append((2 * i, rng.randint(1, vocab, size=plen).tolist(), mnt,
                    f"{cls[0]}{i % 2}", cls))
    return out


@pytest.fixture(scope="module")
def chaos_wfq_golden(tiny_model, role_ctx):
    slo = SLOPolicy.chat_batch()
    eng = _disagg(tiny_model, role_ctx, slo=slo)
    gold = eng.run(max_steps=MAX_STEPS, arrivals=_two_class_trace())
    assert len(gold) == 24 and not eng.failed
    return gold


@pytest.mark.chaos
@pytest.mark.parametrize("name,plan", SCHEDULES,
                         ids=[n for n, _ in SCHEDULES])
def test_chaos_schedules_bit_identical_under_wfq(tiny_model, role_ctx,
                                                 chaos_wfq_golden, name,
                                                 plan):
    """The ISSUE 7 fault matrix re-run with two-class WFQ live: every
    survivable schedule still finishes all requests bit-identical to the
    policied fault-free golden — the policy composes with the recovery
    ladder instead of racing it."""
    eng = _disagg(tiny_model, role_ctx, slo=SLOPolicy.chat_batch(),
                  fault_plan=plan)
    res = eng.run(max_steps=MAX_STEPS, arrivals=_two_class_trace())
    assert eng.failed == [], (
        f"{name}: ladder should have saved every request under WFQ; "
        f"failures: {[(r.rid, r.failure) for r in eng.failed]}")
    assert res == chaos_wfq_golden, (
        f"{name}: tokens diverged from the policied golden")


@pytest.mark.recovery
def test_crash_sweep_bit_identical_under_wfq(tiny_model):
    """The ISSUE 9 strided crash sweep with WFQ + a quota bucket in
    deficit at most crash points: checkpoint/restore must carry the
    policy books (service counters, vfloor, bucket levels) or replay
    forks — the union of pre-crash and post-recovery finishes must stay
    bit-identical to the fault-free policied golden."""
    arrivals = _two_class_trace(n=20)
    slo = dict(chat_weight=4, batch_weight=1, quotas={"b0": (1, 2)})
    mk = lambda **kw: _colocated(                           # noqa: E731
        tiny_model, slo=SLOPolicy.chat_batch(**slo), **kw)

    journal = ControlJournal()
    eng = mk(journal=journal, checkpoint_every=8)
    golden = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    total = eng._steps
    assert len(golden) == 20
    assert eng.metrics.counters["quota_throttled"] > 0, (
        "quota never bit — the sweep is not exercising bucket restore")

    stride = max(1, total // 6)
    for s in range(1, total, stride):
        j = ControlJournal()
        e1 = mk(journal=j, checkpoint_every=8,
                fault_plan=FaultPlan(seed=3, crash_at=(s,)))
        try:
            e1.run(max_steps=MAX_STEPS, arrivals=arrivals)
            continue                    # finished before the crash point
        except InjectedCrash:
            pass
        done = sum(1 for e in j.entries if e["kind"] == "submit")
        e2 = mk(journal=j, checkpoint_every=8)
        res = e2.run(max_steps=MAX_STEPS, arrivals=arrivals[done:],
                     recover=True)
        assert res == golden, f"crash at step {s}: not bit-identical"
