"""Cluster-scale serving simulation (ISSUE 12 rung 3): a deterministic
prefix-affinity router over N engine replicas, driven by a Zipf workload
of hundreds of thousands of requests, with a mid-run replica kill +
restore through the crash-consistency ladder — and EVERY surviving
request's trace verified bit-identical to its single-replica golden.

    python scripts/cluster_sim.py                          # 100k over 4
    python scripts/cluster_sim.py --requests 250000 --replicas 8
    python scripts/cluster_sim.py --requests 200 --engine colocated
    python scripts/cluster_sim.py --no-kill                # fault-free
    python scripts/cluster_sim.py --autoscale --prefix-cache --lend \
        --pages 129 --min-replicas 1 --max-replicas 4 \
        --workload 'n=1500,rate=0.25,burst_every=300,burst_len=60,\
burst_x=10,seed=7'                                         # ISSUE 18

The default engine is ``SimEngine`` (serving/cluster.py): the REAL page
ledger / scheduler / journal / checkpoint control plane with a closed-
form token function, so the workload exercises admission, growth-driven
preemption, routing, journaling and kill/restore at a scale the device
engines cannot reach on CPU — and ``expected_tokens`` IS the golden, no
second run needed. ``--engine colocated`` swaps in the real jitted
``ServingEngine`` (tiny Llama) for a small-scale cross-check that the
replica/router layer is engine-agnostic; goldens then come from a
single-replica reference run of the same engine configuration.

Workload: ``--templates`` distinct prompt prefixes, Zipf-ranked
(``--zipf``), each request = template prefix + a unique tail. The router
hashes the first 8 tokens, so one template's requests land on one
replica (KV locality) until it dies — rendezvous hashing then moves only
its keys. Prints one JSON summary line: aggregate tok/s, TTFT p50/p99,
per-replica placement, failover timing, verification counts.
"""
import argparse
import json
import sys
import tempfile
import time

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
p.add_argument("--requests", type=int, default=100_000,
               help="total requests to route through the cluster")
p.add_argument("--replicas", type=int, default=4)
p.add_argument("--engine", choices=("sim", "colocated"), default="sim",
               help="'sim' = host-only SimEngine (scale); 'colocated' = "
                    "the real jitted ServingEngine (small cross-check)")
p.add_argument("--slots", type=int, default=8, help="slots per replica")
p.add_argument("--page-size", type=int, default=8)
p.add_argument("--pages", type=int, default=48,
               help="usable KV pool pages per replica")
p.add_argument("--pages-per-seq", type=int, default=8)
p.add_argument("--templates", type=int, default=64,
               help="distinct Zipf-ranked prompt prefixes")
p.add_argument("--zipf", type=float, default=1.1,
               help="Zipf exponent over the templates")
p.add_argument("--max-new", type=int, default=8,
               help="decode budget per request (uniform 2..max-new)")
p.add_argument("--arrive-per-step", type=int, default=None,
               help="requests submitted per cluster step (default: "
                    "2 per replica)")
p.add_argument("--seed", type=int, default=0)
p.add_argument("--journal-dir", default=None,
               help="directory for the per-replica journal-r{i}.jsonl "
                    "files (default: a fresh temp dir)")
p.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
               help="checkpoint cadence in engine steps; 0 (default) "
                    "cuts NO checkpoints — the restore then replays the "
                    "ENTIRE journal (the slowest, most honest rung)")
p.add_argument("--kill-at", type=int, default=None, metavar="REQ",
               help="kill a replica after this many submissions "
                    "(default: requests // 2); --no-kill disables")
p.add_argument("--restore-after", type=int, default=None, metavar="REQ",
               help="restore it after this many further submissions "
                    "(default: requests // 10)")
p.add_argument("--kill-replica", type=int, default=1, metavar="I")
p.add_argument("--no-kill", action="store_true",
               help="fault-free run (no kill/restore cycle)")
p.add_argument("--prefix-cache", action="store_true",
               help="ref-counted prefix caching inside each replica "
                    "(ISSUE 13; SimEngine runs the same ledger/cache "
                    "control plane with chunked prefill since ISSUE 17). "
                    "The router's radix index already sends shared-"
                    "template prompts to one replica, so its cache sees "
                    "them all; prints an aggregate hit-rate + cold/"
                    "cached/rewarmed TTFT line to stderr")
p.add_argument("--lend", action="store_true",
               help="cluster-wide prefix sharing (ISSUE 17): on a local "
                    "cache miss with a remote radix-index hit, the owner "
                    "replica LENDS its refcount-0 cached pages to the "
                    "routed replica, and a restored replica re-warms its "
                    "cache from peers instead of cold re-prefilling. "
                    "Needs --prefix-cache; prints a lend-rate panel to "
                    "stderr")
p.add_argument("--no-affinity", action="store_true",
               help="disable the router's radix/prefix affinity: "
                    "rendezvous hashes the FULL prompt, so same-template "
                    "requests scatter across the fleet — the adversarial "
                    "placement the lending tier must absorb (the ISSUE "
                    "17 acceptance compares this + --lend against the "
                    "single-replica hit rate)")
p.add_argument("--lend-deadline", type=int, default=4, metavar="STEPS",
               help="first Backoff rung of the lend ladder, in engine "
                    "steps (a dead/slow lender burns rungs, exhaustion "
                    "degrades to local re-prefill)")
p.add_argument("--lend-retries", type=int, default=2, metavar="N",
               help="rung count of the lend ladder")
p.add_argument("--workload", default=None, metavar="SPEC",
               help="bursty two-class trace (ISSUE 14) replacing the "
                    "template workload: key=value pairs (see serve_sim "
                    "--workload) — every request stamped (tenant, class); "
                    "overrides --requests/--templates/--zipf/--max-new. "
                    "Bad fields fail loudly BY NAME")
p.add_argument("--slo", default=None, metavar="SPEC",
               help="per-replica multi-tenant SLO policy (ISSUE 14): "
                    "chat/batch WFQ weights + per-class overrides + "
                    "token-bucket quotas (see serve_sim --slo)")
p.add_argument("--autoscale", action="store_true",
               help="elastic fleet (ISSUE 18): start at --min-replicas "
                    "and let the Autoscaler grow/shrink on windowed "
                    "per-class SLO attainment, draining gracefully "
                    "(journal-cursor requeue + lend-ahead) on the way "
                    "down. Needs --workload (the attainment sensor is "
                    "per-class); overrides --replicas, disables the "
                    "default kill/restore schedule (inject crashes with "
                    "--crash-mid-drain), and defaults --slo to the "
                    "chat-priority WFQ policy so batch — not the "
                    "latency-lagged chat signal — is the binding class. "
                    "Prints an autoscale panel to stderr")
p.add_argument("--min-replicas", type=int, default=1, metavar="N",
               help="autoscale floor AND the starting fleet size")
p.add_argument("--max-replicas", type=int, default=4, metavar="N",
               help="autoscale ceiling; also the static-peak "
                    "counterfactual the panel's replica-steps-saved "
                    "row is measured against")
p.add_argument("--slo-budget", default="chat:12,batch:20", metavar="SPEC",
               help="per-class step-space budgets 'cls:ttft[/itl],...' "
                    "the attainment windows police (parse_budgets)")
p.add_argument("--slo-window", type=int, default=32, metavar="N",
               help="attainment window: finished-request samples kept "
                    "per (kind, class) series")
p.add_argument("--slo-min-samples", type=int, default=6, metavar="N",
               help="samples a series needs before it can drive scaling")
p.add_argument("--cooldown", type=int, default=20, metavar="STEPS",
               help="controller steps between membership changes "
                    "(thrash control, with the up/down hysteresis band)")
p.add_argument("--warm-steps", type=int, default=1, metavar="STEPS",
               help="cluster steps a scale-up spends WARMING before it "
                    "admits (models the artifact-load window)")
p.add_argument("--spill-threshold", type=int, default=None, metavar="N",
               help="router load spill threshold (default: 10 under "
                    "--autoscale — affinity must not pin a template to "
                    "an overloaded replica while peers sit idle — "
                    "otherwise off)")
p.add_argument("--crash-mid-drain", action="store_true",
               help="kill the first replica observed DRAINING (once): "
                    "the controller auto-restores it, journal replay "
                    "requeues its live requests, the drain resumes and "
                    "retires — and every trace must STILL verify "
                    "bitwise (--autoscale only)")
p.add_argument("--mesh", default=None, metavar="TPxSPxEP",
               help="run each colocated replica as a ShardedServingEngine "
                    "on this TP/SP/EP mesh serving the tiny MoE model "
                    "(--engine colocated only; implied 1x1x1 by "
                    "--overlap)")
p.add_argument("--overlap", choices=("off", "ep", "ep+sp"), default="off",
               help="fine-grained compute/comm overlap inside each "
                    "sharded replica (ISSUE 16; --engine colocated only). "
                    "The single-replica golden reference always runs "
                    "overlap=off, so the per-request trace verification "
                    "IS the overlap bit-identity check at cluster scale")
p.add_argument("--speculate", default=None, metavar="K",
               help="model-free speculative decoding inside each replica "
                    "(ISSUE 20; --engine colocated only — SimEngine has "
                    "no decode dispatch to draft through): an integer K "
                    "or 'auto'. The single-replica golden reference "
                    "always runs speculate=off, so the per-request trace "
                    "verification IS the spec bit-identity check at "
                    "cluster scale. Prints a fleet spec panel to stderr")
p.add_argument("--artifact", default=None, metavar="DIR",
               help="persisted AOT artifact (ISSUE 15; --engine colocated "
                    "only — SimEngine has nothing to compile). EVERY "
                    "replica — cold-built AND kill/restored — seeds its "
                    "jit caches from the artifact's programs instead of "
                    "tracing; a stale artifact is a loud typed error. "
                    "Prints a cold_start summary line to stderr")
args = p.parse_args()
if args.lend and not args.prefix_cache:
    p.error("--lend needs --prefix-cache (lending moves CACHED prefix "
            "pages; without a cache there is nothing to lend or adopt)")
if args.artifact is not None and args.engine != "colocated":
    p.error("--artifact needs --engine colocated")
if args.speculate is not None:
    if args.speculate != "auto":
        try:
            args.speculate = int(args.speculate)
        except ValueError:
            p.error("--speculate wants an integer K or 'auto'")
    if args.engine != "colocated":
        p.error("--speculate needs --engine colocated (SimEngine's token "
                "function is closed-form — there is no decode dispatch "
                "to draft through)")
if ((args.overlap != "off" or args.mesh is not None)
        and args.engine != "colocated"):
    p.error("--overlap/--mesh need --engine colocated (SimEngine has no "
            "device programs to overlap)")
if args.overlap != "off" and args.mesh is None:
    args.mesh = "1x1x1"
if args.crash_mid_drain and not args.autoscale:
    p.error("--crash-mid-drain needs --autoscale (only elastic drains "
            "can crash mid-drain)")
if args.autoscale:
    if args.workload is None:
        p.error("--autoscale needs --workload (the attainment sensor is "
                "per-class; the template workload has no classes)")
    if not 1 <= args.min_replicas <= args.max_replicas:
        p.error("--autoscale needs 1 <= --min-replicas <= --max-replicas")
    # chat-priority WFQ keeps chat TTFT flat through burst fronts, which
    # makes BATCH the binding scaling class — reactive TTFT sensing lags
    # by the TTFT itself, so the class that can wait must carry the lag
    if args.slo is None:
        args.slo = "chat_weight=4,batch_weight=1"
    if args.spill_threshold is None:
        args.spill_threshold = 10
    args.replicas = args.min_replicas
    args.no_kill = True     # fault injection is --crash-mid-drain here

# multi-tenant SLO scheduling (ISSUE 14): both specs fail loudly NAMING
# the bad field instead of silently replaying a default-shaped trace
slo_policy = None
workload_spec = None
if args.slo is not None:
    from triton_dist_tpu.serving.workload import parse_slo  # noqa: E402
    try:
        slo_policy = parse_slo(args.slo)
    except ValueError as e:
        p.error(str(e))
if args.workload is not None:
    from triton_dist_tpu.serving.workload import parse_workload  # noqa: E402
    try:
        workload_spec = parse_workload(args.workload)
    except ValueError as e:
        p.error(str(e))
    args.requests = workload_spec.n
budgets = None
if args.autoscale:
    from triton_dist_tpu.serving.autoscaler import parse_budgets  # noqa: E402
    try:
        budgets = parse_budgets(args.slo_budget)
    except (AssertionError, ValueError) as e:
        p.error(f"--slo-budget: {e}")

kill_at = args.kill_at if args.kill_at is not None else args.requests // 2
restore_after = (args.restore_after if args.restore_after is not None
                 else max(args.requests // 10, 1))
arrive = args.arrive_per_step or 2 * args.replicas
ckpt_every = args.checkpoint_every or None

from triton_dist_tpu.serving.cluster import (Cluster, SimEngine,  # noqa: E402
                                             expected_tokens)

# AOT artifact (ISSUE 15): loaded ONCE before any replica exists; the
# wall clock for cold-start-to-first-token starts here so the load (or
# the fleet-wide fresh traces it replaces) is inside the measurement
_t_cold0 = time.perf_counter()
artifact = None
if args.artifact is not None:
    from triton_dist_tpu.aot import load_artifact  # noqa: E402
    artifact = load_artifact(args.artifact)

if args.engine == "sim":
    VOCAB = 32000

    def factory(journal):
        # prefix caching needs chunked prefill (a cache hit resumes the
        # chunk cursor past the adopted pages — ISSUE 17); one page per
        # chunk mirrors the colocated engines below
        return SimEngine(num_slots=args.slots, page_size=args.page_size,
                         num_pages=args.pages,
                         pages_per_seq=args.pages_per_seq,
                         journal=journal, checkpoint_every=ckpt_every,
                         slo=slo_policy, prefix_cache=args.prefix_cache,
                         prefill_chunk=(args.page_size
                                        if args.prefix_cache else None))

    def golden(prompt, mnt):
        return expected_tokens(prompt, mnt)
else:
    # the real jitted engine, replica/router layer unchanged. Goldens
    # come from one single-replica reference engine fed every request —
    # the engine's own determinism contract (tokens are a pure function
    # of (params, prompt)) makes per-request traces placement-invariant.
    import jax  # noqa: E402

    if args.mesh is not None:
        # sharded replicas (ISSUE 16): each replica is the MoE
        # ShardedServingEngine on its own TP/SP/EP mesh, overlap as
        # requested — while the golden reference below is the SAME
        # engine pinned to overlap=off, so every verified trace is an
        # overlap-on-vs-off bit-identity witness
        tp, sp, ep = (int(d) for d in args.mesh.lower().split("x"))
        from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402
        force_virtual_cpu_devices(tp * sp * ep)
        from triton_dist_tpu.models.moe import (MoEConfig,  # noqa: E402
                                                init_moe_params)
        from triton_dist_tpu.serving import (ShardedServingEngine,  # noqa: E402
                                             serving_mesh)

        cfg = MoEConfig.tiny(n_layers=2)
        params = init_moe_params(jax.random.PRNGKey(args.seed), cfg)
        VOCAB = cfg.base.vocab_size

        def factory(journal, artifact=None):
            return ShardedServingEngine(
                params, cfg, serving_mesh(tp, sp, ep),
                num_slots=args.slots, page_size=args.page_size,
                num_pages=args.pages, pages_per_seq=args.pages_per_seq,
                prefill_chunk=args.page_size, overlap=args.overlap,
                journal=journal, checkpoint_every=ckpt_every,
                prefix_cache=args.prefix_cache, slo=slo_policy,
                speculate=args.speculate, artifact=artifact)

        _ref = ShardedServingEngine(
            params, cfg, serving_mesh(tp, sp, ep), num_slots=args.slots,
            page_size=args.page_size, num_pages=args.pages,
            pages_per_seq=args.pages_per_seq,
            prefill_chunk=args.page_size, overlap="off")
    else:
        from triton_dist_tpu.models.llama import (LlamaConfig,  # noqa: E402
                                                  init_params)
        from triton_dist_tpu.serving import ServingEngine  # noqa: E402

        cfg = LlamaConfig.tiny(n_layers=2)
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
        VOCAB = cfg.vocab_size

        def factory(journal, artifact=None):
            # EngineReplica passes artifact= on the cold build AND on
            # every restore, so a failed-over replica reaches its first
            # replayed token with zero fresh traces too
            return ServingEngine(params, cfg, num_slots=args.slots,
                                 page_size=args.page_size,
                                 num_pages=args.pages,
                                 pages_per_seq=args.pages_per_seq,
                                 prefill_chunk=args.page_size,
                                 journal=journal,
                                 checkpoint_every=ckpt_every,
                                 prefix_cache=args.prefix_cache,
                                 slo=slo_policy,
                                 speculate=args.speculate,
                                 artifact=artifact)

        _ref = ServingEngine(params, cfg, num_slots=args.slots,
                             page_size=args.page_size, num_pages=args.pages,
                             pages_per_seq=args.pages_per_seq,
                             prefill_chunk=args.page_size)
    _ref_cache: dict = {}

    def golden(prompt, mnt):
        key = (tuple(prompt), mnt)
        if key not in _ref_cache:
            rid = _ref.submit(prompt, mnt)
            out = _ref.run(max_steps=200_000)
            _ref_cache[key] = out[rid]
        return _ref_cache[key]

rng = np.random.RandomState(args.seed)
max_plen = args.pages_per_seq * args.page_size - args.max_new
tpl_lens = rng.randint(3, max(4, min(max_plen - 4, 17)),
                       size=args.templates)
templates = [rng.randint(1, VOCAB, size=int(n)).tolist()
             for n in tpl_lens]
ranks = np.arange(1, args.templates + 1, dtype=np.float64)
zipf_p = ranks ** -args.zipf
zipf_p /= zipf_p.sum()

journal_dir = args.journal_dir or tempfile.mkdtemp(prefix="cluster-sim-")
# the golden reference engine (_ref) deliberately stays artifact-OFF:
# bit-identity of every verified trace vs its fresh-traced golden IS the
# artifact-transparency check at cluster scale
cluster = Cluster(factory, replicas=args.replicas, journal_dir=journal_dir,
                  artifact=artifact, affinity=not args.no_affinity,
                  spill_threshold=args.spill_threshold,
                  lend=args.lend, lend_deadline_steps=args.lend_deadline,
                  lend_retries=args.lend_retries)
asc = None
if args.autoscale:
    from triton_dist_tpu.serving.autoscaler import Autoscaler  # noqa: E402
    asc = Autoscaler(cluster, budgets, window=args.slo_window,
                     min_samples=args.slo_min_samples,
                     min_replicas=args.min_replicas,
                     max_replicas=args.max_replicas,
                     cooldown=args.cooldown, warm_steps=args.warm_steps,
                     journal=Autoscaler.journal_path_for(journal_dir))

reqs: dict[int, tuple[list[int], int]] = {}
killed_step = restored_step = None
failover_s = None
tk = None
t0 = time.perf_counter()
submitted = 0
_t_first = None  # wall clock when the cluster's first token surfaced


_crash_fired_at = None


def _maybe_crash_mid_drain() -> None:
    """Forced crash-mid-drain (once): kill the first DRAINING replica we
    see; the controller's next tick restores it, journal replay requeues
    its live requests, and the drain resumes."""
    global _crash_fired_at
    if not args.crash_mid_drain or _crash_fired_at is not None:
        return
    for rep in cluster.replicas:
        if rep.draining and rep.engine is not None:
            cluster.kill(rep.index)
            _crash_fired_at = (rep.index, cluster._cluster_steps)
            print(json.dumps({"crash_mid_drain": {
                "replica": rep.index,
                "at_step": cluster._cluster_steps}}), file=sys.stderr)
            break


def _step() -> None:
    """cluster.step() + the controller tick + first-token clock
    (engine._finished is harvested and cleared inside step, so the
    summary can't read it post-drain)."""
    global _t_first
    cluster.step()
    if asc is not None:
        asc.step()
        _maybe_crash_mid_drain()
    if _t_first is None and cluster._results:
        _t_first = time.perf_counter()


def _maybe_kill_restore() -> None:
    """The mid-run kill/restore cycle, keyed on the submission count —
    shared by the template loop and the --workload loop."""
    global killed_step, restored_step, failover_s, tk
    if not args.no_kill and submitted == kill_at:
        cluster.kill(args.kill_replica)
        killed_step = submitted
        tk = time.perf_counter()
    if (not args.no_kill and killed_step is not None
            and restored_step is None
            and submitted == kill_at + restore_after):
        stats = cluster.restore(args.kill_replica)
        restored_step = submitted
        failover_s = time.perf_counter() - tk
        print(json.dumps({"restore": stats,
                          "failover_us": round(failover_s * 1e6, 1)}),
              file=sys.stderr)


if workload_spec is not None:
    # bursty two-class arrivals (ISSUE 14): the generator's step stamps
    # drive submission cadence; every request lands routed AND stamped
    from collections import deque  # noqa: E402

    from triton_dist_tpu.serving.workload import generate_arrivals  # noqa: E402
    cap = args.pages_per_seq * args.page_size
    if workload_spec.plen[1] + workload_spec.mnt[1] - 1 > cap:
        p.error(f"workload spec field 'plen': plen+mnt-1 = "
                f"{workload_spec.plen[1] + workload_spec.mnt[1] - 1} "
                f"exceeds pages_per_seq*page_size = {cap}")
    if (workload_spec.long > 0
            and workload_spec.lplen[1] + workload_spec.mnt[1] - 1 > cap):
        p.error(f"workload spec field 'lplen': lplen+mnt-1 = "
                f"{workload_spec.lplen[1] + workload_spec.mnt[1] - 1} "
                f"exceeds pages_per_seq*page_size = {cap} — raise "
                f"--pages-per-seq (long-context prompts span many pages)")
    pending = deque(generate_arrivals(workload_spec, vocab=VOCAB,
                                      page_size=args.page_size))
    i = 0
    while pending:
        while pending and pending[0][0] <= i:
            _, prompt, mnt, tenant, cls = pending.popleft()
            gid = cluster.submit(prompt, mnt, tenant=tenant, cls=cls)
            reqs[gid] = (prompt, mnt)
            submitted += 1
            _maybe_kill_restore()
        _step()
        i += 1
else:
    while submitted < args.requests:
        burst = min(arrive, args.requests - submitted)
        for _ in range(burst):
            t = int(rng.choice(args.templates, p=zipf_p))
            tail = rng.randint(1, VOCAB,
                               size=int(rng.randint(1, 5))).tolist()
            prompt = (templates[t] + tail)[:max_plen]
            mnt = int(rng.randint(2, args.max_new + 1))
            gid = cluster.submit(prompt, mnt)
            reqs[gid] = (prompt, mnt)
            submitted += 1
            _maybe_kill_restore()
        _step()
if asc is not None:
    # drain the tail with the controller still ticking: a crash-mid-drain
    # landing near the end needs its auto-restore, and a quiet cluster
    # step right after one is NOT quiescence — hence the idle debounce
    idle = 0
    while idle < 3:
        idle = 0 if cluster.step() else idle + 1
        asc.step()
        _maybe_crash_mid_drain()
    results = cluster.results()
else:
    results = cluster.drain()
if _t_first is None and cluster._results:
    _t_first = time.perf_counter()
wall = time.perf_counter() - t0

# -- verification: every surviving trace vs its single-replica golden ----
missing = sorted(set(reqs) - set(results) - cluster.failed_gids)
mismatched = [g for g, toks in results.items()
              if toks != golden(*reqs[g])]
ok = not missing and not mismatched

per_replica = [0] * len(cluster.replicas)   # elastic: may exceed seed N
for gid, (ri, _) in cluster._placement.items():
    per_replica[ri] += 1
if args.prefix_cache:
    # aggregate the per-replica engine caches; the reference engine is
    # cache-off on purpose — bit-identity of verified traces IS the
    # cache-transparency check at cluster scale
    agg: dict[str, int] = {}
    from triton_dist_tpu.serving.metrics import Histogram  # noqa: E402
    # wall-clock split (device engines) AND step-space split (SimEngine)
    # — cold vs cached vs REWARMED (pages adopted from a peer, ISSUE 17);
    # the kill/restore acceptance is rewarmed ≈ cached, NOT cold
    wall_h = {k: Histogram() for k in ("cold", "cached", "rewarmed")}
    step_h = {k: Histogram() for k in ("cold", "cached", "rewarmed")}
    for rep in cluster.replicas:
        if rep.engine is None:
            continue
        c = rep.engine.metrics.counters
        for k in ("prefix_hits", "prefix_misses", "prefix_hit_tokens",
                  "cow_copies", "prefix_evictions"):
            agg[k] = agg.get(k, 0) + c[k]
        for kind in ("cold", "cached", "rewarmed"):
            for src, dst in ((f"ttft_{kind}_s", wall_h[kind]),
                             (f"ttft_{kind}_steps", step_h[kind])):
                for v in rep.engine.metrics.hist[src]._samples:
                    dst.observe(v)
    hm = lambda h: (None if h.mean is None  # noqa: E731
                    else round(h.mean * 1e6, 1))
    split = {f"ttft_{k}_us_mean": hm(wall_h[k])
             for k in ("cold", "cached", "rewarmed")}
    if any(h.count for h in step_h.values()):   # SimEngine's step space
        split.update({f"ttft_{k}_steps_mean":
                      None if step_h[k].mean is None
                      else round(step_h[k].mean, 2)
                      for k in ("cold", "cached", "rewarmed")})
    print(json.dumps({
        "prefix_cache": True,
        **agg,
        "hit_rate": round(agg["prefix_hits"]
                          / max(agg["prefix_hits"]
                                + agg["prefix_misses"], 1), 3),
        "router_radix_hits": cluster.metrics.counters["router_radix_hits"],
        "router_radix_misses":
            cluster.metrics.counters["router_radix_misses"],
        **split,
    }), file=sys.stderr)
if args.lend:
    # lend-rate panel (ISSUE 17): how much of the fleet's hit rate the
    # lending tier bought, and what each lent page cost
    cm = cluster.metrics
    lp = cm.hist["lend_us_per_page"]
    print(json.dumps({
        "lend": True,
        "affinity": not args.no_affinity,
        "lends": cm.counters["lends"],
        "lent_pages": cm.counters["lent_pages"],
        "lend_tokens": cm.counters["lend_tokens"],
        "lend_degradations": cm.counters["lend_degradations"],
        "rewarmed_prefixes": cm.counters["rewarmed_prefixes"],
        "lend_rate": round(cm.counters["lends"]
                           / max(args.requests, 1), 4),
        "lend_us_per_page_mean": None if lp.mean is None
        else round(lp.mean, 1),
    }), file=sys.stderr)
if workload_spec is not None or slo_policy is not None:
    # per-class fleet aggregate (ISSUE 14): summed over alive replicas
    agg_cls: dict[str, dict[str, int]] = {}
    throttled = 0
    for rep in cluster.replicas:
        if rep.engine is None:
            continue
        throttled += rep.engine.metrics.counters.get("quota_throttled", 0)
        for c, row in rep.engine.metrics.per_class().items():
            dst = agg_cls.setdefault(c, {"finished": 0, "rejections": 0,
                                         "expirations": 0})
            for k in dst:
                dst[k] += row[k]
    print(json.dumps({"per_class": agg_cls,
                      "quota_throttled": throttled}), file=sys.stderr)
    if workload_spec is not None and workload_spec.long > 0:
        # long-class panel (ISSUE 19): the long tenants' fleet view —
        # whether 64k-class prompts finished inside their TTL, how often
        # the chunk budget clamped a dispatch to protect decode ITL, and
        # the long-vs-fleet TTFT tail the clamp is trading against
        from triton_dist_tpu.serving.metrics import Histogram  # noqa: E402
        _lt, _li = Histogram(), Histogram()
        _shrinks = 0
        for rep in cluster.replicas:
            if rep.engine is None:
                continue
            m = rep.engine.metrics
            _shrinks += m.counters.get("chunk_shrinks", 0)
            for src, dst in ((m.hist.get(m.class_key("ttft_s", "long")),
                              _lt),
                             (m.hist.get(m.class_key("itl_s", "long")),
                              _li)):
                for v in (src._samples if src is not None else ()):
                    dst.observe(v)
        _us = lambda v: (None if v is None  # noqa: E731
                         else round(v * 1e6, 1))
        _row = agg_cls.get("long", {})
        print(json.dumps({
            "long_class": True,
            "long_share": workload_spec.long,
            "lplen": list(workload_spec.lplen),
            "finished": _row.get("finished", 0),
            "rejections": _row.get("rejections", 0),
            "expirations": _row.get("expirations", 0),
            "chunk_shrinks": _shrinks,
            "ttft_long_p50_us": _us(_lt.percentile(50)),
            "ttft_long_p99_us": _us(_lt.percentile(99)),
            "itl_long_p99_us": _us(_li.percentile(99)),
        }), file=sys.stderr)
# cold-start summary (ISSUE 15): fleet-wide fresh traces paid before any
# token, plus wall time from cold start (artifact load / replica builds)
# to the cluster's first token. Printed for every --engine colocated run
# so artifact-on vs artifact-off compare 1:1; restored replicas are
# included — their compiles land in the same aggregate.
if args.engine == "colocated":
    _alive = [rep.engine for rep in cluster.replicas
              if rep.engine is not None]
    _stats = [e.compile_stats for e in _alive]
    print(json.dumps({"cold_start": {
        "artifact": args.artifact,
        "replicas_alive": len(_alive),
        "cold_start_compiles": sum(
            v for s in _stats for k, v in s.items()
            if k.endswith("_compiles")),
        "aot_programs": sum(s.get("aot_programs", 0) for s in _stats),
        "cold_start_to_first_token_s":
            None if _t_first is None else round(_t_first - _t_cold0, 4),
    }}), file=sys.stderr)

if args.autoscale:
    # autoscale panel (ISSUE 18): the fleet-size timeline against the
    # offered rate, per-class attainment, the replica-steps-saved row
    # against the static-peak counterfactual (a fleet of --max-replicas
    # stepping every cluster step — the provisioning the autoscaler
    # replaces; counterfactual, not a second run), and the scale-up-to-
    # first-token split (replica build/artifact-load wall time vs fresh
    # compiles — the latter must be zero with an artifact)
    from triton_dist_tpu.serving.workload import rate_at  # noqa: E402
    cm = cluster.metrics
    csteps = cluster._cluster_steps
    rsteps = cm.counters["replica_steps"]
    static_peak = args.max_replicas * csteps
    att_rows = {}
    for _cls in sorted(budgets):
        b_ttft, b_itl = budgets[_cls]
        for _kind, _budget in (("ttft", b_ttft), ("itl", b_itl)):
            if _budget is None:
                continue
            _key = (_kind, _cls)
            if asc.attain.count(_key):
                att_rows[f"{_kind}_{_cls}_attainment"] = round(
                    asc.attain.attainment(_key, _budget), 3)
        # whole-run step-space tail next to the windowed attainment — the
        # window only remembers the newest --slo-window finishes
        _h = cm.hist.get(cm.class_key("ttft_steps", _cls))
        if _h is not None and _h.count:
            att_rows[f"ttft_{_cls}_p99_steps"] = _h.percentile(99)
    _bs = asc.scale_up_build_s
    panel = {
        "autoscale": True,
        "min_replicas": args.min_replicas,
        "max_replicas": args.max_replicas,
        "fleet_final": cluster.lifecycle_counts(),
        "scale_ups": cm.counters["scale_ups"],
        "drains_done": cm.counters["drains_done"],
        "retires": cm.counters["retires"],
        "requeues": cm.counters["requeues"],
        "lend_aheads": cm.counters["lend_aheads"],
        "lend_ahead_pages": cm.counters["lend_ahead_pages"],
        "lend_ahead_noops": cm.counters["lend_ahead_noops"],
        "cluster_steps": csteps,
        "replica_steps": rsteps,
        "static_peak_replica_steps": static_peak,
        "replica_steps_saved_pct": round(
            100.0 * (1 - rsteps / max(static_peak, 1)), 1),
        "warm_steps": args.warm_steps,
        "scale_up_build_s_mean": None if not _bs
        else round(sum(_bs) / len(_bs), 6),
        **att_rows,
        "controller_journal": None if asc.journal is None
        else asc.journal.path,
        "crash_mid_drain": None if not args.crash_mid_drain else (
            None if _crash_fired_at is None
            else {"replica": _crash_fired_at[0],
                  "at_step": _crash_fired_at[1]}),
        # every membership event with the offered rate at that step —
        # rate_at is the SAME function the generator drew arrivals from,
        # so the two timelines always agree
        "timeline": [
            {"step": s, "kind": k, "replica": i,
             "offered_rate": rate_at(workload_spec, s)}
            for s, k, i in cluster.scale_history],
    }
    if args.engine == "colocated":
        # the split's other half: late joiners must seed from the
        # artifact — fresh traces at scale-up time would put compile
        # latency inside the scale-up-to-first-token window
        _late = [r.engine for r in cluster.replicas
                 if r.index >= args.min_replicas and r.engine is not None]
        panel["scale_up_aot_programs"] = sum(
            e.compile_stats.get("aot_programs", 0) for e in _late)
        panel["scale_up_fresh_compiles"] = sum(
            v for e in _late for k, v in e.compile_stats.items()
            if k.endswith("_compiles"))
    print(json.dumps(panel), file=sys.stderr)

if args.mesh is not None:
    # overlap panel (ISSUE 16): fleet-aggregated per-step EP wire split
    # under the wire-fit model (serving/sharded.py _comm_split_us) —
    # modeled, labeled as such: CPU wall clock serializes ranks and can
    # never show real overlap. overlap=off replicas report all-exposed.
    _exp = _ovl = 0.0
    _cnt = 0
    _mb = None
    for rep in cluster.replicas:
        if rep.engine is None:
            continue
        _h = rep.engine.metrics.hist
        _exp += _h["exposed_comm_us"].total
        _ovl += _h["overlapped_comm_us"].total
        _cnt += _h["exposed_comm_us"].count
        _mb = rep.engine.overlap_microbatches
    print(json.dumps({
        "overlap": args.overlap, "mesh": args.mesh,
        "overlap_microbatches": _mb,
        "exposed_comm_us_mean": round(_exp / max(_cnt, 1), 2),
        "overlapped_comm_us_mean": round(_ovl / max(_cnt, 1), 2),
    }), file=sys.stderr)

if args.speculate is not None:
    # spec panel (ISSUE 20): fleet-aggregated draft economics. The
    # golden reference is speculate-OFF, so the verified_bit_identical
    # count in the summary below is the spec-transparency witness.
    from triton_dist_tpu.serving.metrics import Histogram  # noqa: E402
    _acc = Histogram()
    _drafted = _accepted = _rewinds = _sdisp = 0
    for rep in cluster.replicas:
        if rep.engine is None:
            continue
        _c = rep.engine.metrics.counters
        _drafted += _c["draft_tokens"]
        _accepted += _c["draft_accepted"]
        _rewinds += _c["spec_rewinds"]
        _sdisp += _c["spec_dispatches"]
        for v in rep.engine.metrics.hist["accepted_per_dispatch"]._samples:
            _acc.observe(v)
    print(json.dumps({
        "speculate": args.speculate,
        "spec_dispatches": _sdisp,
        "accepted_per_dispatch_mean": None if _acc.mean is None
        else round(_acc.mean, 3),
        "draft_hit_rate": round(_accepted / _drafted, 4)
        if _drafted else None,
        "spec_rewinds": _rewinds,
    }), file=sys.stderr)

toks_total = sum(len(t) for t in results.values())
ttft = cluster.metrics.hist["ttft_s"]
us = lambda v: None if v is None else round(v * 1e6, 1)  # noqa: E731
print(json.dumps({
    "engine": args.engine,
    "replicas": args.replicas,
    "requests": args.requests,
    "finished": len(results),
    "failed": len(cluster.failed_gids),
    "verified_bit_identical": len(results) - len(mismatched),
    "mismatched": len(mismatched),
    "missing": len(missing),
    "wall_s": round(wall, 3),
    "agg_tok_per_s": round(toks_total / wall, 1) if wall else None,
    "ttft_p50_us": us(ttft.percentile(50)),
    "ttft_p99_us": us(ttft.percentile(99)),
    "per_replica_requests": per_replica,
    "kill": None if args.no_kill else {
        "replica": args.kill_replica, "at_request": killed_step,
        "restored_at_request": restored_step,
        "failover_us": None if failover_s is None
        else round(failover_s * 1e6, 1)},
    "journal_dir": journal_dir,
}))
if not ok:
    print(json.dumps({"error": "trace verification failed",
                      "missing": missing[:10],
                      "mismatched": mismatched[:10]}), file=sys.stderr)
    sys.exit(1)
