"""Raw device-API breadth sweep: PE addressing, sub-group barriers, and
remote signals at odd mesh shapes, independent of the ops that use them.

Parity target: the reference's standalone ``test_nvshmem_api`` (598 LoC —
teams, fcollect, signal ops, broadcast as an API surface, SURVEY §4). The
ops-level tests exercise these primitives *through* protocols; this module
pins the addressing math itself — ``pe_at_group`` over non-power-of-two and
3-axis meshes is exactly where a flat-id bug would alias two devices and
corrupt a hierarchical kernel silently.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import default_interpret


@pytest.mark.parametrize("shape,axes,group", [
    ((2, 3), ("a", "b"), ("b",)),
    ((2, 3), ("a", "b"), ("a",)),
    ((2, 3), ("a", "b"), ("a", "b")),
    ((3, 2), ("a", "b"), ("b", "a")),       # group order != mesh order
    ((2, 2, 3), ("a", "b", "c"), ("c",)),
    ((2, 2, 3), ("a", "b", "c"), ("a", "c")),
    ((2, 2, 3), ("a", "b", "c"), ("b", "a")),
])
def test_pe_at_group_flat_ids(shape, axes, group):
    """pe_at_group(index) from every device, for every group coordinate,
    against a numpy golden computed from mesh coordinates."""
    if int(np.prod(shape)) > jax.device_count():
        pytest.skip(f"mesh {shape} needs more than {jax.device_count()} "
                    "devices (smaller TDT_TEST_DEVICES run)")
    ctx = initialize_distributed(axis_names=axes, mesh_shape=shape)
    gsize = int(np.prod([shape[axes.index(a)] for a in group]))

    def f():
        ids = [shd.pe_at_group(axes, group, jnp.int32(i))
               for i in range(gsize)]
        me = shd.my_pe(axes)
        return jnp.stack(ids + [me])[None]

    got = np.asarray(jax.jit(ctx.shard_map(
        f, in_specs=(), out_specs=P(axes)))())          # [n_dev, gsize+1]

    # golden: flat id over `axes` of the device whose `group` coords are the
    # row-major unflattening of i, other coords = the caller's
    n_dev = int(np.prod(shape))
    golden = np.zeros((n_dev, gsize + 1), np.int32)
    for flat in range(n_dev):
        coords = dict(zip(axes, np.unravel_index(flat, shape)))
        golden[flat, gsize] = flat
        for i in range(gsize):
            gcoords = dict(zip(group, np.unravel_index(
                i, tuple(shape[axes.index(a)] for a in group))))
            tgt = {**coords, **gcoords}
            golden[flat, i] = int(np.ravel_multi_index(
                tuple(tgt[a] for a in axes), shape))
    np.testing.assert_array_equal(got, golden)


def test_my_pe_flattened_multi_axis():
    """my_pe/n_pes over an axis tuple = row-major flattening (major first)."""
    ctx = initialize_distributed(axis_names=("a", "b"), mesh_shape=(2, 3))

    def f():
        return jnp.stack([shd.my_pe(("a", "b")), shd.n_pes(("a", "b")),
                          shd.my_pe("b"), shd.n_pes("b")])[None]

    got = np.asarray(jax.jit(ctx.shard_map(
        f, in_specs=(), out_specs=P(("a", "b"))))())
    for flat in range(6):
        a, b = divmod(flat, 3)
        np.testing.assert_array_equal(got[flat], [flat, 6, b, 3])


def test_group_ring_put_odd_mesh():
    """One-sided put around the ring of the FLATTENED (a, b) group on a
    (2, 3) mesh — a raw-primitive version of what the hierarchical relay
    kernels do, pinning pe_at_group inside an actual DMA."""
    axes = ("a", "b")
    ctx = initialize_distributed(axis_names=axes, mesh_shape=(2, 3))
    n = 6

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        me = shd.my_pe(axes)
        dst = shd.pe_at_group(axes, axes, lax.rem(me + 1, n))
        rdma = shd.putmem_nbi(out_ref, in_ref, send_sem, recv_sem, dst)
        shd.quiet(rdma)
        shd.wait_recv(out_ref, recv_sem)

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("shmem_api_ring")),
            interpret=default_interpret(),
        )(x)

    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    y = jax.jit(ctx.shard_map(f, in_specs=P(axes), out_specs=P(axes)))(x)
    want = np.roll(np.asarray(x), 8, axis=0)
    np.testing.assert_array_equal(np.asarray(y), want)


@pytest.mark.parametrize("barrier_axes", [("b",), ("a",), ("a", "b")])
def test_subaxis_barrier_then_signal(barrier_axes):
    """barrier_all over an axis SUBSET of a (2, 3) mesh, then a remote
    signal_op to the next neighbor within that group and a consuming wait —
    the teams-like surface (reference test_nvshmem_api's team barriers +
    signal ops)."""
    axes = ("a", "b")
    ctx = initialize_distributed(axis_names=axes, mesh_shape=(2, 3))

    def kernel(out_ref, sig):
        shd.barrier_all(barrier_axes, mesh_axes=axes)
        gsz = shd.n_pes(barrier_axes)
        me_g = shd.my_pe(barrier_axes)
        nxt = shd.pe_at_group(axes, barrier_axes, lax.rem(me_g + 1, gsz))
        shd.signal_op(sig, 7, pe=nxt)
        shd.signal_wait_until(sig, 7)   # consumes the neighbor's signal
        out_ref[0] = 1

    def f():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(
                    f"shmem_api_bar_{barrier_axes}")),
            interpret=default_interpret(),
        )()

    got = np.asarray(jax.jit(ctx.shard_map(
        f, in_specs=(), out_specs=P(axes)))())
    np.testing.assert_array_equal(got, np.ones(6, np.int32))


def test_signal_read_after_partial_consume():
    """signal_read is NON-destructive and sees the residue of a partially
    consumed count: accumulate 3, wait 2 (TPU waits consume), read -> 1,
    read again -> still 1, then drain the last arrival so the physical
    register leaves the kernel clean."""
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))

    def kernel(out_ref, sig):
        shd.signal_op(sig, 3)           # self-signal: deterministic count
        shd.signal_wait_until(sig, 2)   # consumes 2 of the 3
        out_ref[0] = shd.signal_read(sig)
        out_ref[1] = shd.signal_read(sig)   # non-destructive: unchanged
        shd.signal_wait_until(sig, 1)   # drain the residue
        out_ref[2] = shd.signal_read(sig)

    def f():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((3,), jnp.int32),
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("shmem_api_sigread")),
            interpret=default_interpret(),
        )()[None]

    got = np.asarray(jax.jit(ctx.shard_map(
        f, in_specs=(), out_specs=P("x")))())
    np.testing.assert_array_equal(
        got, np.tile(np.array([1, 1, 0], np.int32), (TEST_WORLD, 1)))


def test_quiet_with_zero_rdmas():
    """``quiet()`` with nothing outstanding is a legal no-op — protocols
    built over a dynamic rdma list hit the empty case whenever a rank has
    no remote peers (n=1 subgroup, self-only slice)."""
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))

    def kernel(out_ref):
        shd.quiet()                     # zero descriptors: must not block
        shd.fence()                     # ordering no-op rides along
        out_ref[0] = 1

    def f():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
            interpret=default_interpret(),
        )()

    got = np.asarray(jax.jit(ctx.shard_map(
        f, in_specs=(), out_specs=P("x")))())
    np.testing.assert_array_equal(got, np.ones(TEST_WORLD, np.int32))


def test_barrier_pair_reentry():
    """Back-to-back ``barrier_pair`` on the same physical barrier register:
    each crossing must consume exactly what it signalled (signal 1 / wait 1)
    so re-entry neither deadlocks nor inherits residue from the previous
    crossing. This jax's mosaic interpreter cannot execute remote REGULAR
    signals, so the protocol is proven through the sigcheck capture layer
    (no device): the cross-rank checker simulates all interleavings and
    flags any starvation, wait cycle, or leftover count."""
    from triton_dist_tpu.analysis import sigcheck

    def run(ctx):
        def kernel(out_ref, sig):
            me = shd.my_pe("x")
            peer = me ^ 1               # even<->odd partner pairs
            for _ in range(3):          # re-entry: three crossings in a row
                shd.barrier_pair(("x",), peer)
            out_ref[0] = 1

        def f():
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
                out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
                scratch_shapes=[pltpu.SemaphoreType.REGULAR],
                compiler_params=pltpu.CompilerParams(
                    has_side_effects=True,
                    collective_id=collective_id_for(
                        "shmem_api_pair_reentry")),
                interpret=default_interpret(),
            )()

        ctx.shard_map(f, in_specs=(), out_specs=P("x"))()

    rep = sigcheck(run, op="barrier_pair_reentry",
                   meshes=({"x": 2}, {"x": 4}))
    assert rep.ok, "\n".join(str(f) for f in rep.findings)
    assert all(c > 0 for c in rep.event_counts.values())
