from triton_dist_tpu.layers.allgather_layer import AllGatherLayer  # noqa: F401
from triton_dist_tpu.layers.ep_a2a_layer import EPAll2AllLayer  # noqa: F401
from triton_dist_tpu.layers.sp_flash_decode_layer import (  # noqa: F401
    PagedGQADecodeAttention, SpGQAFlashDecodeAttention)
from triton_dist_tpu.layers.tp_linear import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear)
