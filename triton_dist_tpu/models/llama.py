"""Flagship dense model family: Llama-style decoder-only transformer.

The reference is a kernel library, not a model zoo — its "models" are the
benchmark shape tables (LLaMA-7B/8B/70B/405B, Mistral-7B, Qwen2-72B,
reference python/triton_dist/test/nvidia/test_ag_gemm_intra_node.py:153-160)
plus module-level layers (SpGQAFlashDecodeAttention, EPAll2AllLayer). This
framework goes one step further and wires those layers into a full
functional model so the overlap kernels are exercised in situ.

Design is TPU-first and functional:
- params are a pytree of stacked per-layer arrays (leading ``L`` dim) so the
  layer loop is a single-trace ``lax.scan`` — one compile of one block.
- the standard forward is pure jnp/einsum: under jit with GSPMD sharding
  annotations XLA inserts the TP collectives itself (the baseline the
  overlap kernels must beat).
- ``forward_tp_overlap`` runs the same math through the hand-overlapped
  Pallas AG-GEMM / GEMM-RS kernels (Megatron sequence-parallel residual
  layout: activations sequence-sharded between blocks), the analog of the
  reference's tutorial-07/08 TP forward.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 11008
    rope_theta: float = 1e4
    norm_eps: float = 1e-5
    max_seq_len: int = 4096
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    # -- benchmark shape presets (cf. test_ag_gemm_intra_node.py:153-160) --
    @classmethod
    def llama_7b(cls):
        return cls()

    @classmethod
    def llama3_8b(cls):
        return cls(vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, d_ff=14336, rope_theta=5e5)

    @classmethod
    def llama3_70b(cls):
        return cls(vocab_size=128256, d_model=8192, n_layers=80, n_heads=64,
                   n_kv_heads=8, d_ff=28672, rope_theta=5e5)

    @classmethod
    def llama3_405b(cls):
        return cls(vocab_size=128256, d_model=16384, n_layers=126,
                   n_heads=128, n_kv_heads=8, d_ff=53248, rope_theta=5e5)

    @classmethod
    def mistral_7b(cls):
        return cls(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, d_ff=14336)

    @classmethod
    def qwen2_72b(cls):
        return cls(vocab_size=152064, d_model=8192, n_layers=80, n_heads=64,
                   n_kv_heads=8, d_ff=29568)

    @classmethod
    def tiny(cls, n_layers: int = 2):
        """Test/dryrun config: every sharded dim stays tile-friendly."""
        return cls(vocab_size=512, d_model=128, n_layers=n_layers, n_heads=4,
                   n_kv_heads=2, d_ff=256, max_seq_len=128)


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Stacked-per-layer param pytree. Truncated-normal-ish init (scaled
    normal) in ``cfg.dtype`` (bf16 keeps the MXU fed); norm gains in f32."""
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    keys = jax.random.split(key, 9)
    s = 0.02

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(cfg.dtype)

    return {
        "embed": norm(keys[0], cfg.vocab_size, D),
        "blocks": {
            "attn_norm": jnp.ones((L, D), jnp.float32),
            "wq": norm(keys[1], L, D, Hq * Dh),
            "wk": norm(keys[2], L, D, Hkv * Dh),
            "wv": norm(keys[3], L, D, Hkv * Dh),
            "wo": norm(keys[4], L, Hq * Dh, D) / math.sqrt(2 * L),
            "mlp_norm": jnp.ones((L, D), jnp.float32),
            "w_gate": norm(keys[5], L, D, F),
            "w_up": norm(keys[6], L, D, F),
            "w_down": norm(keys[7], L, F, D) / math.sqrt(2 * L),
        },
        "final_norm": jnp.ones((D,), jnp.float32),
        "lm_head": norm(keys[8], D, cfg.vocab_size),
    }


def param_specs(cfg: LlamaConfig, tp: str | None = "tp",
                pp: str | None = None) -> dict:
    """GSPMD PartitionSpecs matching ``init_params``'s tree: Megatron TP
    layout (qkv/gate/up column-sharded, o/down row-sharded, embedding
    vocab-sharded), with the stacked layer dim optionally pipeline-sharded."""
    return {
        "embed": P(tp, None),
        "blocks": {
            "attn_norm": P(pp, None),
            "wq": P(pp, None, tp),
            "wk": P(pp, None, tp),
            "wv": P(pp, None, tp),
            "wo": P(pp, tp, None),
            "mlp_norm": P(pp, None),
            "w_gate": P(pp, None, tp),
            "w_up": P(pp, None, tp),
            "w_down": P(pp, tp, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, tp),
    }


# ---------------------------------------------------------------------------
# math building blocks
# ---------------------------------------------------------------------------

def rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return ((x32 * rms) * w).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x [..., S, H, Dh]; positions [..., S]. Half-split RoPE."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., S, half]
    cos = jnp.cos(angles)[..., None, :]                        # [..., S, 1, half]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=-1).astype(x.dtype)


def _attention(q, k, v, sm_scale: float, kv_len=None) -> jax.Array:
    """Causal GQA attention. q [B,S,Hq,Dh]; k,v [B,S,Hkv,Dh]. ``kv_len``
    [B] int32 (optional) additionally masks keys at/after each row's
    length — the bucketed-prefill guard against padded tail positions
    (causality already shields queries < kv_len; the extra mask keeps the
    padded queries' rows finite too, same -1e30 fill as the causal mask,
    so valid rows are bit-identical with or without it)."""
    B, S, Hq, Dh = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    q = q.reshape(B, S, Hkv, G, Dh)
    scores = jnp.einsum("bshgd,bthd->bhgst", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None, None]
    if kv_len is not None:
        valid = jnp.arange(S)[None] < kv_len[:, None]      # [B, S] keys
        mask = jnp.logical_and(mask, valid[:, None, None, None])
    scores = jnp.where(mask, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgst,bthd->bshgd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, S, Hq, Dh).astype(q.dtype)


def block_apply(cfg: LlamaConfig, x: jax.Array, p: dict,
                positions: jax.Array, act_spec: P | None = None,
                attn_fn=None) -> jax.Array:
    """One transformer block. x [B,S,D]. ``act_spec`` re-pins the residual
    stream sharding after each sublayer (GSPMD sequence/data parallel).
    ``attn_fn(q, k, v, sm_scale)`` replaces the dense attention (e.g. the
    context-parallel ring kernel, parallel.train cp plan)."""
    B, S, D = x.shape
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def pin(h):
        if act_spec is not None:
            h = lax.with_sharding_constraint(h, act_spec)
        return h

    h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
    q = (h @ p["wq"]).reshape(B, S, Hq, Dh)
    k = (h @ p["wk"]).reshape(B, S, Hkv, Dh)
    v = (h @ p["wv"]).reshape(B, S, Hkv, Dh)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    attn = (attn_fn or _attention)(q, k, v, 1.0 / math.sqrt(Dh))
    x = pin(x + attn.reshape(B, S, Hq * Dh) @ p["wo"])

    h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
    ff = jax.nn.silu((h @ p["w_gate"]).astype(jnp.float32)).astype(h.dtype) \
        * (h @ p["w_up"])
    x = pin(x + ff @ p["w_down"])
    return x


def forward(params: dict, tokens: jax.Array, cfg: LlamaConfig,
            act_spec: P | None = None, remat: bool = False,
            attn_fn=None) -> jax.Array:
    """Full-sequence forward → logits [B,S,V]. Pure jnp: under jit + sharded
    params, XLA inserts TP collectives (the compiler baseline the overlap
    kernels race against, cf. tutorial 07's torch baseline). ``attn_fn``
    swaps in a distributed attention kernel (ring attention for cp)."""
    B, S = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.arange(S)[None, :].repeat(B, 0)

    def body(x, p):
        return block_apply(cfg, x, p, positions, act_spec, attn_fn), None

    if remat:
        body = jax.checkpoint(body)
    x, _ = lax.scan(body, x, params["blocks"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def mlp_tp_overlap(ctx, x2d: jax.Array, w_gate: jax.Array, w_up: jax.Array,
                   w_down: jax.Array, axis: str | None = None,
                   gemm_cfg=None) -> jax.Array:
    """Llama MLP over the differentiable overlap kernels, for the Megatron
    sequence-parallel residual layout: x2d [T, D] sharded P(axis) on rows →
    [T, D] sharded P(axis). Gate and up weights are fused per-shard into
    one [D, 2F] operand so the sequence shard crosses the wire ONCE
    (a single AG-GEMM instead of two); the down projection is the GEMM-RS
    adjoint. Fully differentiable (ops.autodiff), so this is a *training*
    MLP with hand-overlapped comms on both passes — beyond the reference's
    inference-only scope."""
    from triton_dist_tpu.ops.autodiff import ag_gemm_diff, gemm_rs_diff
    from triton_dist_tpu.ops.gemm import GemmConfig

    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    D, F = w_gate.shape
    assert F % n == 0, f"FFN width {F} not divisible by TP size {n}"
    T_local = x2d.shape[0] // n
    if gemm_cfg is not None:
        cfg_ag = cfg_rs = gemm_cfg
    else:  # largest power-of-two tiles ≤128 that divide each stage
        cfg_ag = GemmConfig(math.gcd(128, T_local),
                            math.gcd(128, 2 * (F // n)))
        cfg_rs = GemmConfig(math.gcd(128, T_local), math.gcd(128, D))
    # per-shard [gate_i ‖ up_i] interleave: all reshape/concat stay inside
    # shards (no comms), and the fused output splits the same way
    wf = jnp.concatenate([w_gate.reshape(D, n, F // n),
                          w_up.reshape(D, n, F // n)], axis=2)
    wf = wf.reshape(D, 2 * F)
    h2 = ag_gemm_diff(ctx, axis, cfg_ag, x2d, wf)          # [T, 2F] P(None, ax)
    h2 = h2.reshape(-1, n, 2 * (F // n))
    gate, up = h2[..., :F // n], h2[..., F // n:]
    ff = (jax.nn.silu(gate.astype(jnp.float32)).astype(x2d.dtype)
          * up).reshape(-1, F)
    return gemm_rs_diff(ctx, axis, cfg_rs, ff, w_down)     # [T, D] P(ax)


# ---------------------------------------------------------------------------
# decode / serving path (KV cache + flash-decode kernel)
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: LlamaConfig, batch: int, max_seq: int) -> dict:
    """Head-major cache layout [L, B, Hkv, S, D] — KV blocks are
    tiling-aligned DMA slices for the decode kernel (ops.flash_decode)."""
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    shape = (cfg.n_layers, batch, Hkv, max_seq, Dh)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def prefill(params: dict, tokens: jax.Array, cfg: LlamaConfig,
            cache: dict, length: jax.Array | None = None
            ) -> tuple[jax.Array, dict]:
    """Full-sequence forward that also writes K/V into ``cache[:, :, :S]``.
    Returns (last-position logits [B, V], cache).

    ``length`` [B] int32 (optional) is the per-row VALID prompt length for
    bucketed prefill: ``tokens`` is padded to a bucket size S ≥ length, an
    attention length mask hides the padded tail from every query row, and
    the returned logits are taken at position ``length - 1`` per row (not
    ``S - 1``). Cache rows at/after ``length`` hold padding K/V — callers
    hand off only the first ``length`` positions (the serving engine's
    page handoff already copies exactly the prompt's pages). ``None``
    keeps the original exact-length code path unchanged."""
    B, S = tokens.shape
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.arange(S)[None, :].repeat(B, 0)

    def body(x, layer):
        p, ck, cv = layer
        h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q = rope((h @ p["wq"]).reshape(B, S, Hq, Dh), positions,
                 cfg.rope_theta)
        k = rope((h @ p["wk"]).reshape(B, S, Hkv, Dh), positions,
                 cfg.rope_theta)
        v = (h @ p["wv"]).reshape(B, S, Hkv, Dh)
        ck = lax.dynamic_update_slice(
            ck, k.transpose(0, 2, 1, 3), (0, 0, 0, 0))
        cv = lax.dynamic_update_slice(
            cv, v.transpose(0, 2, 1, 3), (0, 0, 0, 0))
        attn = _attention(q, k, v, 1.0 / math.sqrt(Dh), kv_len=length)
        x = x + attn.reshape(B, S, Hq * Dh) @ p["wo"]
        h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
        ff = jax.nn.silu((h @ p["w_gate"]).astype(jnp.float32)
                         ).astype(h.dtype) * (h @ p["w_up"])
        x = x + ff @ p["w_down"]
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["blocks"], cache["k"],
                                     cache["v"]))
    if length is None:
        last = x[:, -1]
    else:
        last = jnp.take_along_axis(
            x, (length - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    x = rmsnorm(last, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def decode_step(params: dict, token: jax.Array, pos: jax.Array,
                cfg: LlamaConfig, cache: dict,
                ffn=None) -> tuple[jax.Array, dict]:
    """One-token decode via the flash-decode kernel. ``token`` [B] int32,
    ``pos`` scalar int32 (cache slots filled so far). Returns
    (logits [B, V], cache). Attention = ops.flash_decode.gqa_decode_partial
    over the cache (the single-rank half of SpGQAFlashDecodeAttention).

    ``ffn(h, p) -> [B, D]`` overrides the per-layer FFN block (same hook as
    ``decode_step_sp`` — lets single-device references for MoE variants
    reuse this plumbing). With a custom ``ffn`` the layer loop unrolls in
    Python instead of ``lax.scan`` (the callback may close over shard_map'd
    kernels that don't compose with scan on every backend)."""
    from triton_dist_tpu.ops.flash_decode import gqa_decode_partial

    B = token.shape[0]
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][token].astype(cfg.dtype)          # [B, D]
    positions = jnp.full((B, 1), pos, jnp.int32)

    def body(x, layer):
        p, ck, cv = layer
        h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q = rope((h @ p["wq"]).reshape(B, 1, Hq, Dh), positions,
                 cfg.rope_theta)[:, 0]                     # [B, Hq, Dh]
        k = rope((h @ p["wk"]).reshape(B, 1, Hkv, Dh), positions,
                 cfg.rope_theta)
        v = (h @ p["wv"]).reshape(B, 1, Hkv, Dh)
        ck = lax.dynamic_update_slice(ck, k.transpose(0, 2, 1, 3),
                                      (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(cv, v.transpose(0, 2, 1, 3),
                                      (0, 0, pos, 0))
        kv_len = jnp.full((B,), pos + 1, jnp.int32)
        attn, _lse = gqa_decode_partial(q, ck, cv, kv_len)
        x = x + attn.reshape(B, Hq * Dh) @ p["wo"]
        h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
        if ffn is None:
            ff = (jax.nn.silu((h @ p["w_gate"]).astype(jnp.float32)
                              ).astype(h.dtype) * (h @ p["w_up"])
                  ) @ p["w_down"]
        else:
            ff = ffn(h, p)
        x = x + ff.astype(x.dtype)
        return x, (ck, cv)

    if ffn is None:
        x, (ks, vs) = lax.scan(body, x, (params["blocks"], cache["k"],
                                         cache["v"]))
    else:
        ks_l, vs_l = [], []
        for i in range(cfg.n_layers):
            p = jax.tree.map(lambda a, i=i: a[i], params["blocks"])
            x, (ck, cv) = body(x, (p, cache["k"][i], cache["v"][i]))
            ks_l.append(ck)
            vs_l.append(cv)
        ks, vs = jnp.stack(ks_l), jnp.stack(vs_l)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def init_page_pool(cfg: LlamaConfig, num_pages: int, page_size: int) -> dict:
    """Paged KV pool: per-layer page-major arrays [L, P, Hkv, page_size, D]
    — each page is the tiling-aligned DMA slice ``gqa_decode_paged``
    streams by block-table index. The serving runtime
    (``triton_dist_tpu.serving``) owns page accounting; this is just the
    device memory."""
    Hkv, Dh = cfg.n_kv_heads, cfg.head_dim
    assert page_size % 8 == 0, f"page_size {page_size} must be 8-aligned"
    shape = (cfg.n_layers, num_pages, Hkv, page_size, Dh)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def decode_step_paged(params: dict, token: jax.Array, pos: jax.Array,
                      cfg: LlamaConfig, pages: dict,
                      block_table: jax.Array, ffn=None,
                      active: jax.Array | None = None,
                      sample: bool = False, attn_io=None,
                      linear=None) -> tuple[jax.Array, dict]:
    """One-token decode over the paged KV pool — the continuous-batching
    twin of ``decode_step``. Differences that make it a serving hot loop:

    - ``pos`` is PER-SLOT [B] int32 (every slot sits at its own depth —
      arrivals and finishes never force a shared position), vs
      ``decode_step``'s single scalar.
    - the cache is the page pool from ``init_page_pool`` plus a
      ``block_table`` [B, pages_per_seq] int32; the new (k, v) is
      scattered into page ``bt[b, pos_b // page_size]`` row
      ``pos_b % page_size`` and attention is ``gqa_decode_paged``.
    - inactive slots are driven by pointing their block-table row at a
      reserved scratch page (the serving engine reserves page 0): their
      writes land there, their reads mask out, and the batch shape never
      changes — one compiled step per token regardless of arrivals.

    Returns (logits [B, V] f32, updated pages). ``ffn(h, p) -> [B, D]``
    overrides the per-layer FFN exactly as in ``decode_step`` (MoE
    serving plugs ``moe_mlp_ep_overlap`` here); with a custom ``ffn`` the
    layer loop unrolls in Python for the same backend reasons.

    ``active`` [B] bool (optional) parks frozen rows' KV writes on the
    scratch page (``ops.flash_decode.paged_kv_write``) — the device-side
    slot mask the scanned multi-token loop uses for rows done mid-scan.
    ``sample=True`` fuses greedy sampling: the first return value is the
    on-device argmax ``next_token`` [B] int32 instead of the [B, vocab]
    logits, so a serving host only ever downloads a token slab.

    ``attn_io(q, k, v, kp, vp, bt, pos, kv_len, active) -> (attn, kp, vp)``
    overrides the KV-write + paged-attention pair (the SP serving path
    plugs ``ops.flash_decode.sp_paged_attend_write`` here — the pool
    arrays then stay sharded on their page dim). ``linear(h, w, name)``
    overrides every dense projection (wq/wk/wv/wo/lm_head — the TP
    serving path plugs ``ops.allgather_gemm.tp_column_linear``). Either
    hook unrolls the layer loop like ``ffn`` does."""
    from triton_dist_tpu.ops.flash_decode import (gqa_decode_paged,
                                                  paged_kv_write)

    lin = linear or (lambda h, w, name: h @ w)
    B = token.shape[0]
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][token].astype(cfg.dtype)          # [B, D]
    positions = pos[:, None].astype(jnp.int32)            # [B, 1]
    kv_len = (pos + 1).astype(jnp.int32)

    def body(x, layer):
        p, kp, vp = layer
        h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q = rope(lin(h, p["wq"], "wq").reshape(B, 1, Hq, Dh), positions,
                 cfg.rope_theta)[:, 0]                     # [B, Hq, Dh]
        k = rope(lin(h, p["wk"], "wk").reshape(B, 1, Hkv, Dh), positions,
                 cfg.rope_theta)[:, 0]                     # [B, Hkv, Dh]
        v = lin(h, p["wv"], "wv").reshape(B, 1, Hkv, Dh)[:, 0]
        if attn_io is None:
            kp, vp = paged_kv_write(kp, vp, k, v, block_table, pos,
                                    active=active)
            attn, _lse = gqa_decode_paged(q, kp, vp, block_table, kv_len)
        else:
            attn, kp, vp = attn_io(q, k, v, kp, vp, block_table, pos,
                                   kv_len, active)
        x = x + lin(attn.reshape(B, Hq * Dh), p["wo"], "wo")
        h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
        if ffn is None:
            ff = (jax.nn.silu((h @ p["w_gate"]).astype(jnp.float32)
                              ).astype(h.dtype) * (h @ p["w_up"])
                  ) @ p["w_down"]
        else:
            ff = ffn(h, p)
        x = x + ff.astype(x.dtype)
        return x, (kp, vp)

    if ffn is None and attn_io is None and linear is None:
        x, (ks, vs) = lax.scan(body, x, (params["blocks"], pages["k"],
                                         pages["v"]))
    else:
        ks_l, vs_l = [], []
        for i in range(cfg.n_layers):
            p = jax.tree.map(lambda a, i=i: a[i], params["blocks"])
            x, (kp, vp) = body(x, (p, pages["k"][i], pages["v"][i]))
            ks_l.append(kp)
            vs_l.append(vp)
        ks, vs = jnp.stack(ks_l), jnp.stack(vs_l)
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = lin(x, params["lm_head"], "lm_head").astype(jnp.float32)
    if sample:
        return jnp.argmax(logits, -1).astype(jnp.int32), {"k": ks, "v": vs}
    return logits, {"k": ks, "v": vs}


def prefill_chunk_paged(params: dict, tokens: jax.Array, start: jax.Array,
                        prompt_len: jax.Array, cfg: LlamaConfig,
                        pages: dict, block_table: jax.Array,
                        ffn=None, attn_io=None,
                        linear=None) -> tuple[jax.Array, dict]:
    """Prefill one fixed-size chunk of a prompt DIRECTLY into the page
    pool — the admission half of the serving hot loop (ISSUE 5 tentpole).

    ``tokens`` [C] int32 is chunk ``[start, start + C)`` of the prompt,
    zero-padded past ``prompt_len``; ``start`` and ``prompt_len`` are
    runtime scalars, so ONE compiled program (keyed only by the chunk
    size C) serves every prompt length and every chunk position — the
    prefill jit cache shrinks from O(log max_prompt) bucket programs to
    O(1). ``block_table`` [pages_per_seq] int32 is the sequence's block-
    table row (fill entries past the owned pages are never dereferenced).

    The chunk rides the PAGED machinery end to end, treating its C tokens
    as C batch rows of ``ops.flash_decode``:

    - KV lands straight in the pool via ``paged_kv_write`` (pos = the
      absolute token position, ``active`` masks the padded tail onto the
      scratch page) — no temporary contiguous cache, no
      ``cache_to_pages`` converter copy on the admit path.
    - attention is ``gqa_decode_paged`` with per-row
      ``kv_len = position + 1``: each query walks the block table over
      ALL pages filled so far — the pages of every previous chunk plus
      this chunk's own causal prefix (written just above). The chunk-
      boundary attention state therefore never crosses the host: it IS
      the pages, re-read through the same online-softmax walk decode
      uses, instead of an (m, l, acc) carry threaded between chunk
      calls. Padded rows run with ``kv_len = 0`` (the empty-shard
      convention — zeros out, masked writes) and their residual-stream
      garbage is never read.

    Returns ``(tok [()], pages)``: ``tok`` is the on-device greedy argmax
    of the logits at row ``prompt_len - 1 - start`` (the first generated
    token, fused like ``decode_step_paged(sample=True)`` — the host never
    downloads logits or argmaxes them). It is meaningful only for the
    chunk that contains the prompt's last token; earlier chunks compute
    the same (cheap, one-row) head on a garbage row and the engine
    ignores it — the price of keeping every chunk the same program.

    ``ffn(h, p) -> [C, D]`` overrides the per-layer FFN exactly as in
    ``decode_step_paged`` (the MoE serving hook); with a custom ``ffn``
    the layer loop unrolls in Python for the same backend reasons.
    ``attn_io``/``linear`` hook the KV-write+attention pair and the dense
    projections exactly as in ``decode_step_paged`` (the chunk's C rows
    play the batch-row role; ``active`` is the padded-tail mask).
    """
    from triton_dist_tpu.ops.flash_decode import (gqa_decode_paged,
                                                  paged_kv_write)

    lin = linear or (lambda h, w, name: h @ w)
    C = tokens.shape[0]
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    idx = start.astype(jnp.int32) + jnp.arange(C, dtype=jnp.int32)   # [C]
    valid = idx < prompt_len                                         # [C]
    # padded rows park on the scratch page: position 0 keeps the block-
    # table lookup in range, active=False reroutes the write to page 0
    pos = jnp.where(valid, idx, 0).astype(jnp.int32)
    kv_len = jnp.where(valid, idx + 1, 0).astype(jnp.int32)
    bt = jnp.broadcast_to(block_table[None, :], (C, block_table.shape[0]))
    x = params["embed"][tokens].astype(cfg.dtype)                    # [C, D]
    positions = pos[:, None]                                         # [C, 1]

    def body(x, layer):
        p, kp, vp = layer
        h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q = rope(lin(h, p["wq"], "wq").reshape(C, 1, Hq, Dh), positions,
                 cfg.rope_theta)[:, 0]                    # [C, Hq, Dh]
        k = rope(lin(h, p["wk"], "wk").reshape(C, 1, Hkv, Dh), positions,
                 cfg.rope_theta)[:, 0]
        v = lin(h, p["wv"], "wv").reshape(C, 1, Hkv, Dh)[:, 0]
        if attn_io is None:
            kp, vp = paged_kv_write(kp, vp, k, v, bt, pos, active=valid)
            attn, _lse = gqa_decode_paged(q, kp, vp, bt, kv_len)
        else:
            attn, kp, vp = attn_io(q, k, v, kp, vp, bt, pos, kv_len, valid)
        x = x + lin(attn.reshape(C, Hq * Dh), p["wo"], "wo")
        h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
        if ffn is None:
            ff = (jax.nn.silu((h @ p["w_gate"]).astype(jnp.float32)
                              ).astype(h.dtype) * (h @ p["w_up"])
                  ) @ p["w_down"]
        else:
            ff = ffn(h, p)
        x = x + ff.astype(x.dtype)
        return x, (kp, vp)

    if ffn is None and attn_io is None and linear is None:
        x, (ks, vs) = lax.scan(body, x, (params["blocks"], pages["k"],
                                         pages["v"]))
    else:
        ks_l, vs_l = [], []
        for i in range(cfg.n_layers):
            p = jax.tree.map(lambda a, i=i: a[i], params["blocks"])
            x, (kp, vp) = body(x, (p, pages["k"][i], pages["v"][i]))
            ks_l.append(kp)
            vs_l.append(vp)
        ks, vs = jnp.stack(ks_l), jnp.stack(vs_l)
    # one-row head: the prompt's last token sits at chunk row
    # prompt_len - 1 - start when this is the final chunk (clamped into
    # range otherwise — the result is then garbage the engine discards)
    last = jnp.clip(prompt_len - 1 - start, 0, C - 1).astype(jnp.int32)
    h_last = lax.dynamic_slice_in_dim(x, last, 1)                    # [1, D]
    h_last = rmsnorm(h_last, params["final_norm"], cfg.norm_eps)
    logits = lin(h_last, params["lm_head"], "lm_head").astype(jnp.float32)
    tok = jnp.argmax(logits[0], -1).astype(jnp.int32)
    return tok, {"k": ks, "v": vs}


def decode_multistep_paged(params: dict, token: jax.Array, pos: jax.Array,
                           cfg: LlamaConfig, pages: dict,
                           block_table: jax.Array, limit: jax.Array,
                           horizon: int, eos_id: int | None = None,
                           ffn=None, attn_io=None, linear=None
                           ) -> tuple[jax.Array, jax.Array, jax.Array, dict]:
    """Device-resident multi-token decode: ``horizon`` fused sampled steps
    (``decode_step_paged(..., sample=True)``) chained under one trace, so
    ONE host dispatch advances every slot up to ``horizon`` tokens. The
    serving hot loop (``serving.engine``) jits this once per engine — the
    horizon and ``eos_id`` are static trace constants; all per-step
    dynamism rides in ``limit``.

    ``limit`` [B] int32 is the per-slot step budget for THIS dispatch:
    ``min(horizon, tokens remaining, page capacity headroom)``, 0 for
    parked slots. A row freezes once its inner step index reaches its
    limit OR it has emitted ``eos_id`` — its token/pos stop advancing and
    its KV writes are parked on the scratch page via the ``active`` mask.
    The limit clamp is how the horizon auto-clamps so no slot can outgrow
    its pre-ensured pages mid-scan; the EOS freeze is the device half of
    the done-mask (the host reconciles finishes from the slab). Frozen
    rows keep computing harmlessly — the fixed-shape batch never changes.

    Returns ``(toks [horizon, B] int32, token' [B], pos' [B], pages)``:
    ``toks[i, b]`` is the token sampled by row ``b``'s step ``i`` (valid
    while the row was live); ``token'``/``pos'`` are the post-scan slot
    states (advanced exactly as many steps as the row was live) the
    engine keeps device-resident for the next dispatch. ``horizon=1``
    is exactly one fused ``decode_step_paged`` — today's per-token
    semantics."""
    assert horizon >= 1
    limit = limit.astype(jnp.int32)
    stopped0 = jnp.zeros(token.shape, jnp.bool_)

    def one(carry, i):
        tok, pos_c, stopped, pages_c = carry
        act = jnp.logical_and(i < limit, ~stopped)         # [B] bool
        nxt, pages_c = decode_step_paged(params, tok, pos_c, cfg, pages_c,
                                         block_table, ffn=ffn, active=act,
                                         sample=True, attn_io=attn_io,
                                         linear=linear)
        tok = jnp.where(act, nxt, tok)
        pos_c = jnp.where(act, pos_c + 1, pos_c)
        if eos_id is not None:
            stopped = jnp.logical_or(stopped,
                                     jnp.logical_and(act, nxt == eos_id))
        return (tok, pos_c, stopped, pages_c), nxt

    if ffn is None and attn_io is None and linear is None and horizon > 1:
        (token, pos, _, pages), toks = lax.scan(
            one, (token, pos, stopped0, pages),
            jnp.arange(horizon, dtype=jnp.int32))
    else:
        # custom ffn may close over shard_map'd kernels that don't compose
        # with scan on every backend — unroll (same reason as the layer
        # loop above); horizon=1 skips the scan machinery entirely
        toks_l = []
        carry = (token, pos, stopped0, pages)
        for i in range(horizon):
            carry, nxt = one(carry, jnp.int32(i))
            toks_l.append(nxt)
        token, pos, _, pages = carry
        toks = jnp.stack(toks_l)
    return toks, token, pos, pages


def decode_speculate_paged(params: dict, token: jax.Array, pos: jax.Array,
                           cfg: LlamaConfig, pages: dict,
                           block_table: jax.Array, limit: jax.Array,
                           horizon: int, hist: jax.Array,
                           hist_len: jax.Array, eos_id: int | None = None,
                           ffn=None, attn_io=None, linear=None
                           ) -> tuple[jax.Array, jax.Array, jax.Array,
                                      jax.Array, jax.Array, jax.Array, dict]:
    """Draft-verify speculative decode: ONE dispatch commits up to
    ``horizon`` tokens per slot, bit-identical to ``horizon`` sequential
    greedy steps (ISSUE 20 tentpole). The spec twin of
    ``decode_multistep_paged`` — same signature family, same per-slot
    ``limit`` clamp / EOS freeze / scratch-page parking — but where the
    multistep scan runs K *sequential* fused steps, this runs K
    *positions in parallel* as K batch rows and accepts a prefix:

    - **draft**: ``serving.speculate.ngram_draft`` proposes K-1 tokens
      per slot from ``hist`` [B, H] (the device-resident recent-token
      window, newest at column H-1) — no host sync, no draft model.
    - **verify**: one ``decode_step_paged`` call over ``B*K`` rows —
      row (b, i) consumes token i of (last_token ‖ drafts) at position
      ``pos_b + i``. Per layer, ``paged_kv_write`` scatters ALL rows'
      KV before ``gqa_decode_paged`` reads, and row (b, i)'s
      ``kv_len = pos_b + i + 1`` masks everything deeper — exactly
      ``prefill_chunk_paged``'s C-rows-of-decode intra-call causality,
      so row i attends the KV rows 0..i-1 wrote THIS call. Rows past
      ``limit`` park on the scratch page (``active`` mask), same as a
      frozen multistep row.
    - **accept**: ``serving.speculate.spec_accept`` keeps the longest
      prefix where each row consumed the token the previous row
      argmaxed (exact-match greedy — a committed token is committed
      because a row fed the identical committed prefix produced it,
      which is the whole bitwise-trace argument), clamped by ``limit``
      and frozen after EOS so EOS is always the LAST committed token.

    Rejected rows' KV lands at positions ``>= pos'`` and is dead: the
    next dispatch re-writes those positions before any row's ``kv_len``
    admits them (writes precede reads per layer), and whole rejected
    pages are returned to the pool host-side via ``free_tail`` — no
    device-side unwind needed, which is why the accept path has no host
    sync.

    Returns ``(toks [K, B], accepted [B], token' [B], pos' [B],
    hist' [B, H], hist_len' [B], pages)``. ``toks[i, b]`` is row (b,i)'s
    verified argmax — the committed tokens are exactly
    ``toks[:accepted[b], b]``; ``token'``/``pos'`` advance by
    ``accepted`` (``accepted >= 1`` for every live row, since position 0
    consumes the authentic last token); ``hist'`` is ``hist`` rolled
    left by ``accepted`` with the committed tokens appended — the host
    mirrors the same roll, so history never re-uploads on the hot path.
    ``horizon=1`` drafts nothing and degenerates to one greedy step."""
    from triton_dist_tpu.serving.speculate import ngram_draft, spec_accept

    K = int(horizon)
    assert K >= 1
    B = token.shape[0]
    limit = limit.astype(jnp.int32)
    drafts = ngram_draft(hist, hist_len, K - 1)                # [B, K-1]
    inp = jnp.concatenate([token[:, None].astype(jnp.int32), drafts],
                          axis=1)                              # [B, K]
    offs = jnp.arange(K, dtype=jnp.int32)[None, :]             # [1, K]
    ract = offs < limit[:, None]                               # [B, K]
    rpos = jnp.where(ract, pos[:, None] + offs, 0).astype(jnp.int32)
    fl = lambda a: a.reshape((B * K,) + a.shape[2:])           # row-major
    fbt = jnp.repeat(block_table, K, axis=0)                   # [B*K, S]
    nxt_fl, pages = decode_step_paged(params, fl(inp), fl(rpos), cfg,
                                      pages, fbt, ffn=ffn,
                                      active=fl(ract), sample=True,
                                      attn_io=attn_io, linear=linear)
    nxt = nxt_fl.reshape(B, K)
    m = spec_accept(inp, nxt, ract, eos_id)                    # [B]
    tok2 = jnp.take_along_axis(nxt, jnp.maximum(m - 1, 0)[:, None],
                               axis=1)[:, 0]
    token2 = jnp.where(m > 0, tok2, token)
    pos2 = pos + m
    # roll history left by m and append the committed tokens — the last
    # H entries of (hist ‖ nxt[:, :m]); the zero-masked tail past m
    # never enters the gather window
    H = hist.shape[1]
    commit = offs < m[:, None]
    ext = jnp.concatenate([hist, jnp.where(commit, nxt, 0)], axis=1)
    cols = m[:, None] + jnp.arange(H, dtype=jnp.int32)[None, :]
    hist2 = jnp.take_along_axis(ext, cols, axis=1)
    hlen2 = jnp.minimum(hist_len + m, H).astype(jnp.int32)
    return nxt.T, m, token2, pos2, hist2, hlen2, pages


def decode_step_sp(ctx, params: dict, token: jax.Array, pos: jax.Array,
                   cfg: LlamaConfig, cache: dict,
                   axis: str | None = None,
                   ag_method: str = "fused",
                   ffn=None) -> tuple[jax.Array, dict]:
    """Sequence-parallel one-token decode: the KV cache is sharded on its
    sequence dim across ``axis`` and attention runs the distributed
    flash-decode (local split-KV + fused partial-AG + lse-merge) — the
    model-level serving loop over ``SpGQAFlashDecodeAttention`` (reference
    sp_flash_decode_layer.py:78-184; its README decode-scaling workload).
    The cache update for the new token's (k, v) is a global
    dynamic_update_slice — GSPMD routes it to the owning shard. Weights
    are replicated (compose TP separately).

    ``cache`` as from ``init_kv_cache`` with k/v sharded
    P(None, None, None, axis, None) ([layers, B, Hkv, S, D] on S).

    ``ffn(h, p) -> [B, D]`` overrides the per-layer FFN block (``h`` is the
    post-mlp_norm hidden, ``p`` the layer's params) — how
    ``models.moe.moe_decode_step_sp`` swaps in the expert-parallel MoE FFN
    without duplicating the attention/cache plumbing.
    """
    from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode

    axis = axis or ctx.axis_names[0]
    B = token.shape[0]
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][token].astype(cfg.dtype)
    positions = jnp.full((B, 1), pos, jnp.int32)

    # python-unrolled layer loop (not lax.scan): the distributed decode
    # kernel's shard_map does not compose with scan under the SPMD
    # partitioner on every backend, and decode-step jaxprs are small
    ks_out, vs_out = [], []
    for i in range(cfg.n_layers):
        p = jax.tree.map(lambda a, i=i: a[i], params["blocks"])
        ck, cv = cache["k"][i], cache["v"][i]
        h = rmsnorm(x, p["attn_norm"], cfg.norm_eps)
        q = rope((h @ p["wq"]).reshape(B, 1, Hq, Dh), positions,
                 cfg.rope_theta)[:, 0]
        k = rope((h @ p["wk"]).reshape(B, 1, Hkv, Dh), positions,
                 cfg.rope_theta)
        v = (h @ p["wv"]).reshape(B, 1, Hkv, Dh)
        ck = lax.dynamic_update_slice(ck, k.transpose(0, 2, 1, 3),
                                      (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(cv, v.transpose(0, 2, 1, 3),
                                      (0, 0, pos, 0))
        kv_len = jnp.full((B,), pos + 1, jnp.int32)
        attn = sp_gqa_flash_decode(ctx, q, ck, cv, kv_len, axis=axis,
                                   ag_method=ag_method)
        x = x + attn.reshape(B, Hq * Dh).astype(x.dtype) @ p["wo"]
        h = rmsnorm(x, p["mlp_norm"], cfg.norm_eps)
        if ffn is None:
            ff = (jax.nn.silu((h @ p["w_gate"]).astype(jnp.float32)
                              ).astype(h.dtype) * (h @ p["w_up"])
                  ) @ p["w_down"]
        else:
            ff = ffn(h, p)
        x = x + ff.astype(x.dtype)
        ks_out.append(ck)
        vs_out.append(cv)

    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": jnp.stack(ks_out), "v": jnp.stack(vs_out)}


def generate(params: dict, prompt: jax.Array, cfg: LlamaConfig,
             max_new_tokens: int, max_seq: int | None = None) -> jax.Array:
    """Greedy generation: prefill + scanned decode loop (batch decode, the
    reference's target regime, SURVEY.md §5.7). Returns [B, max_new_tokens].
    """
    B, S0 = prompt.shape
    max_seq = max_seq or cfg.max_seq_len
    assert S0 + max_new_tokens <= max_seq
    cache = init_kv_cache(cfg, B, max_seq)
    logits, cache = prefill(params, prompt, cfg, cache)
    tok0 = jnp.argmax(logits, -1).astype(jnp.int32)

    def step(carry, i):
        tok, cache = carry
        logits, cache = decode_step(params, tok, S0 + i, cfg, cache)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        return (nxt, cache), tok

    (_, _), toks = lax.scan(step, (tok0, cache),
                            jnp.arange(max_new_tokens, dtype=jnp.int32))
    return toks.T                                          # [B, new]


# ---------------------------------------------------------------------------
# hand-overlapped TP forward (the reference's raison d'être)
# ---------------------------------------------------------------------------

def forward_tp_overlap(ctx: ShmemContext, params: dict, tokens: jax.Array,
                       cfg: LlamaConfig, axis: str | None = None) -> jax.Array:
    """TP forward where every Megatron linear pair runs through the Pallas
    overlap kernels: qkv/gate/up = AG-GEMM (activations sequence-sharded in,
    column-sharded weights), o/down = GEMM-RS (back to sequence-sharded) —
    the model-level composition of reference tutorials 07 (AG-GEMM) and 08
    (GEMM-RS). Layer loop is a Python loop (one pallas_call per linear);
    params may be replicated or TP-sharded on the mesh.

    tokens [B, S] with B*S divisible by (ranks * 128). Returns logits.
    """
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    from triton_dist_tpu.ops.gemm import GemmConfig
    from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs

    axis = axis or ctx.axis_names[0]
    nr = ctx.axis_size(axis)
    B, S = tokens.shape
    D = cfg.d_model
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.arange(S)[None, :].repeat(B, 0)
    blocks = params["blocks"]

    def tile(m, n):   # largest power-of-two tile ≤128 dividing the problem
        return GemmConfig(block_m=math.gcd(128, m), block_n=math.gcd(128, n))

    def col(x2d, w):
        return ag_gemm(ctx, x2d, w, axis=axis,
                       cfg=tile(x2d.shape[0] // nr, w.shape[1] // nr))

    def row(x2d, w):
        return gemm_rs(ctx, x2d, w, axis=axis,
                       cfg=tile(x2d.shape[0] // nr, w.shape[1]))

    T = B * S
    xs = x.reshape(T, D)  # sequence-major token rows, P(axis)-sharded by ops
    for l in range(cfg.n_layers):
        p = jax.tree.map(lambda a: a[l], blocks)
        h = rmsnorm(xs, p["attn_norm"], cfg.norm_eps)
        # fused qkv column-parallel AG-GEMM (one gather, one wide GEMM),
        # interleaved PER SHARD — a plain concat of the TP-sharded weights
        # would reshard them every layer (the gate‖up trick of
        # mlp_tp_overlap, with heterogeneous widths)
        qw, kw = Hq * Dh // nr, Hkv * Dh // nr
        wqkv = jnp.concatenate(
            [p["wq"].reshape(D, nr, qw), p["wk"].reshape(D, nr, kw),
             p["wv"].reshape(D, nr, kw)], axis=2).reshape(D, -1)
        qkv = col(h, wqkv).reshape(T, nr, qw + 2 * kw)
        q = qkv[..., :qw].reshape(T, Hq * Dh)
        k = qkv[..., qw:qw + kw].reshape(T, Hkv * Dh)
        v = qkv[..., qw + kw:].reshape(T, Hkv * Dh)
        q = rope(q.reshape(B, S, Hq, Dh), positions, cfg.rope_theta)
        k = rope(k.reshape(B, S, Hkv, Dh), positions, cfg.rope_theta)
        attn = _attention(q, k, v.reshape(B, S, Hkv, Dh),
                          1.0 / math.sqrt(Dh))
        xs = xs + row(attn.reshape(T, Hq * Dh), p["wo"])

        h = rmsnorm(xs, p["mlp_norm"], cfg.norm_eps)
        xs = xs + mlp_tp_overlap(ctx, h, p["w_gate"], p["w_up"],
                                 p["w_down"], axis=axis)

    x = rmsnorm(xs.reshape(B, S, D), params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


__all__ = ["LlamaConfig", "init_params", "param_specs", "forward",
           "forward_tp_overlap", "mlp_tp_overlap", "rmsnorm", "rope",
           "block_apply", "init_kv_cache", "init_page_pool", "prefill",
           "decode_step", "decode_step_paged", "decode_multistep_paged",
           "decode_speculate_paged", "prefill_chunk_paged", "generate"]
