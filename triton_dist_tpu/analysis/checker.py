"""Cross-rank verification over captured event streams.

Given the per-rank event streams from :mod:`.capture` at a concrete rank
count n, the checker:

1. **simulates** the streams against counting-semaphore semantics (signals
   and put deliveries credit, waits consume; semaphores are monotone, so
   greedy round-robin saturation reaches completion iff ANY schedule does);
2. classifies a stuck simulation as **under-signal** (static supply on some
   blocked semaphore is less than its demand — the wait can never be paid)
   or **deadlock** (supply suffices globally but every order leaves a
   wait-before-signal cycle);
3. flags **over-signal** residue: credits left on any semaphore after a
   completed run — the PR-6 ledger-poison class (a later call on the same
   scratch inherits the stale count);
4. flags **unordered reads**: a consumer-side read overlapping a put's
   destination region that is neither dominated by the wait covering that
   delivery nor provably happens-before the put's issuance (vector clocks
   carried through signal/put credits — the entry-barrier and ack-credit
   patterns are what make reads-before-reuse legal);
5. fits **peer patterns** per put/signal site — ``(me+k)%n`` or constant —
   purely as a protocol summary for the JSON report.

Vector clocks: each executed event joins the clocks attached to the
credits it consumed; a credit carries the producer's clock at deposit
time. ``read ⊑ signal ⊑ wait ⊑ put`` chains therefore rescue slot-reuse
protocols (ring ack credits) from rule 4.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

from .events import Event, Region, SemId

# finding kinds (the taxonomy docs/debugging.md tabulates)
UNDER_SIGNAL = "under_signal"
OVER_SIGNAL = "over_signal"
DEADLOCK = "deadlock"
UNORDERED_READ = "unordered_read"
NONDETERMINISM = "nondeterminism"
CAPTURE_ERROR = "capture_error"


@dataclasses.dataclass
class Finding:
    kind: str
    op: str
    n: Optional[int]
    detail: str
    events: List[str] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        return {"kind": self.kind, "op": self.op, "n": self.n,
                "detail": self.detail, "events": self.events}

    def __str__(self) -> str:
        where = f" n={self.n}" if self.n is not None else ""
        return f"[{self.kind}] {self.op}{where}: {self.detail}"


class _Clock:
    """Vector clock over ranks: component r = highest seq at rank r known to
    happen-before this point."""

    __slots__ = ("v",)

    def __init__(self, n: int):
        self.v = [-1] * n

    def copy(self) -> "_Clock":
        c = _Clock(len(self.v))
        c.v = list(self.v)
        return c

    def join(self, other: "_Clock") -> None:
        self.v = [max(a, b) for a, b in zip(self.v, other.v)]

    def dominates(self, rank: int, seq: int) -> bool:
        return self.v[rank] >= seq


@dataclasses.dataclass
class _Credit:
    amount: int
    clock: _Clock
    delivery: Optional["_Delivery"] = None


@dataclasses.dataclass
class _Delivery:
    """One put landing at a consumer: region + issuance clock; filled in
    with the covering wait (if any) during simulation."""
    src_rank: int
    put: Event
    issue_clock: _Clock
    wait_seq: Optional[int] = None       # consumer seq of the covering wait
    consumed: int = 0


def _sem_key(rank: int, sem: SemId) -> Tuple[int, str, Tuple[int, ...]]:
    return (rank, sem.alloc, sem.cell)


def check_events(op: str, streams: Dict[int, List[Event]],
                 n: int) -> List[Finding]:
    """Run all cross-rank checks over one captured instantiation."""
    findings: List[Finding] = []
    ranks = sorted(streams)
    if len(ranks) != n:
        findings.append(Finding(CAPTURE_ERROR, op, n,
                                f"captured {len(ranks)} rank streams, "
                                f"expected {n}"))
        return findings

    # ---- static supply/demand per (rank, sem-cell)
    supply: Dict[Tuple, int] = defaultdict(int)
    demand: Dict[Tuple, int] = defaultdict(int)
    for r in ranks:
        for e in streams[r]:
            if e.kind == "signal":
                supply[_sem_key(e.dst_rank, e.sem)] += e.value
            elif e.kind == "put":
                supply[_sem_key(e.dst_rank, e.sem)] += e.value
                if e.send_sem is not None:
                    # send completion credits the SOURCE-side send sem — the
                    # standard quiet-by-same-ref-wait drains it as a wait_recv
                    supply[_sem_key(e.rank, e.send_sem)] += e.value
            elif e.kind in ("wait", "wait_recv"):
                demand[_sem_key(e.rank, e.sem)] += e.value
            elif e.kind == "wait_send" and e.sem is not None:
                demand[_sem_key(e.rank, e.sem)] += e.value

    for key in sorted(set(supply) | set(demand),
                      key=lambda k: (k[0], k[1], k[2])):
        s, d = supply.get(key, 0), demand.get(key, 0)
        rank, alloc, cell = key
        sem_str = f"{alloc}{list(cell)}" if cell else alloc
        if s > d:
            findings.append(Finding(
                OVER_SIGNAL, op, n,
                f"semaphore {sem_str} at rank {rank} accumulates {s} but "
                f"only {d} is ever consumed — {s - d} left behind poisons "
                f"the next call on this scratch"))

    # ---- simulation
    queues: Dict[Tuple, deque] = defaultdict(deque)
    deliveries: List[_Delivery] = []
    clocks = {r: _Clock(n) for r in ranks}
    pos = {r: 0 for r in ranks}

    def _is_wait(e: Event) -> bool:
        if e.kind in ("wait", "wait_recv"):
            return True
        return e.kind == "wait_send" and e.sem is not None

    def executable(e: Event) -> bool:
        if _is_wait(e):
            q = queues[_sem_key(e.rank, e.sem)]
            return sum(c.amount for c in q) >= e.value
        return True

    def execute(e: Event) -> None:
        clk = clocks[e.rank]
        if _is_wait(e):
            q = queues[_sem_key(e.rank, e.sem)]
            need = e.value
            while need > 0:
                c = q[0]
                take = min(need, c.amount)
                c.amount -= take
                need -= take
                clk.join(c.clock)
                if c.delivery is not None:
                    c.delivery.consumed += take
                    # a wait_send that happens to drain a delivery credit
                    # (shared sem cell) proves nothing about arrival — never
                    # let it stand in as the covering wait
                    if c.delivery.wait_seq is None and e.kind != "wait_send":
                        c.delivery.wait_seq = e.seq
                if c.amount == 0:
                    q.popleft()
        clk.v[e.rank] = e.seq
        if e.kind == "signal":
            queues[_sem_key(e.dst_rank, e.sem)].append(
                _Credit(e.value, clk.copy()))
        elif e.kind == "put":
            d = _Delivery(e.rank, e, clk.copy())
            deliveries.append(d)
            queues[_sem_key(e.dst_rank, e.sem)].append(
                _Credit(e.value, clk.copy(), d))
            if e.send_sem is not None:
                # no delivery attached: draining the send sem proves the
                # source buffer is reusable, NOT that the remote write landed
                queues[_sem_key(e.rank, e.send_sem)].append(
                    _Credit(e.value, clk.copy()))

    progressed = True
    while progressed:
        progressed = False
        for r in ranks:
            while pos[r] < len(streams[r]) and executable(streams[r][pos[r]]):
                execute(streams[r][pos[r]])
                pos[r] += 1
                progressed = True

    stuck = {r: pos[r] for r in ranks if pos[r] < len(streams[r])}
    if stuck:
        blocked = [streams[r][pos[r]] for r in sorted(stuck)]
        starved = [e for e in blocked
                   if supply.get(_sem_key(e.rank, e.sem), 0)
                   < demand.get(_sem_key(e.rank, e.sem), 0)]
        if starved:
            e = starved[0]
            key = _sem_key(e.rank, e.sem)
            findings.append(Finding(
                UNDER_SIGNAL, op, n,
                f"rank {e.rank} waits {demand[key]} on {e.sem} but total "
                f"signal supply is {supply.get(key, 0)} — static deadlock "
                "(missing/dropped signal)",
                [e.describe() for e in blocked]))
        else:
            findings.append(Finding(
                DEADLOCK, op, n,
                "no execution order exists: every rank is blocked on a "
                "wait whose signals sit behind other blocked waits "
                "(wait-before-signal cycle)",
                [e.describe() for e in blocked]))
        # hazard analysis below would double-report on a half-run protocol
        return findings

    # ---- unordered-read hazards (completed runs only)
    reads_by_rank: Dict[int, List[Tuple[int, Region]]] = {r: [] for r in ranks}
    for r in ranks:
        for e in streams[r]:
            if e.kind == "read" and e.src is not None:
                reads_by_rank[r].append((e.seq, e.src))
            elif e.kind == "put" and e.src is not None:
                # a put reads its source region (ring forwarding)
                reads_by_rank[r].append((e.seq, e.src))

    reported = set()
    for d in deliveries:
        cons_rank = d.put.dst_rank
        if cons_rank == d.src_rank:
            pass  # local async copy: same rules apply to its waiter
        region = d.put.dst
        for seq, rregion in reads_by_rank[cons_rank]:
            if not region.overlaps(rregion):
                continue
            if d.wait_seq is not None and seq > d.wait_seq:
                continue  # dominated by the covering wait
            if d.issue_clock.dominates(cons_rank, seq):
                continue  # read happens-before the put was even issued
            key = (cons_rank, region.buffer, seq)
            if key in reported:
                continue
            reported.add(key)
            covering = ("no wait ever covers this delivery"
                        if d.wait_seq is None else
                        f"the covering wait runs at seq {d.wait_seq}, after "
                        "the read")
            findings.append(Finding(
                UNORDERED_READ, op, n,
                f"rank {cons_rank} reads {rregion} (seq {seq}) which "
                f"overlaps the destination of a put from rank "
                f"{d.src_rank}; {covering}",
                [d.put.describe()]))

    return findings


# -- peer-pattern fitting (informational) ------------------------------------

def fit_peer_patterns(streams_by_n: Dict[int, Dict[int, List[Event]]]
                      ) -> Dict[str, str]:
    """Best-effort symbolic summary: for each put/signal site (aligned by
    per-rank occurrence index), fit ``dst = (me+k)%n`` or ``dst = c``
    consistent across every rank and every captured n. Asymmetric protocols
    (root broadcast, ring-position-dependent counts) report ``asymmetric``.
    """
    # site key -> {n: {rank: [dst,...]}}
    table: Dict[str, Dict[int, Dict[int, List[int]]]] = defaultdict(
        lambda: defaultdict(dict))
    for n, streams in streams_by_n.items():
        for r, evs in streams.items():
            per_site: Dict[str, List[int]] = defaultdict(list)
            for e in evs:
                if e.kind in ("put", "signal") and e.dst_rank is not None:
                    site = f"{e.site}:{e.kind}:{e.sem.alloc if e.sem else ''}"
                    per_site[site].append(e.dst_rank)
            for site, dsts in per_site.items():
                table[site][n][r] = dsts

    out: Dict[str, str] = {}
    for site, by_n in table.items():
        shifts: set = set()
        consts: set = set()
        ok = True
        for n, by_rank in by_n.items():
            counts = {len(v) for v in by_rank.values()}
            if len(by_rank) != n or len(counts) != 1:
                ok = False
                break
            m = counts.pop()
            for i in range(m):
                k0 = {(by_rank[r][i] - r) % n for r in by_rank}
                c0 = {by_rank[r][i] for r in by_rank}
                if len(k0) == 1:
                    shifts.add((i, k0.pop()))
                elif len(c0) == 1:
                    consts.add((i, c0.pop()))
                else:
                    ok = False
        if not ok:
            out[site] = "asymmetric"
        elif shifts and not consts:
            ks = sorted({k for _, k in shifts})
            out[site] = ("dst=(me+k)%n, k in " + repr(ks)) if len(ks) > 1 \
                else f"dst=(me+{ks[0]})%n"
        elif consts and not shifts:
            cs = sorted({c for _, c in consts})
            out[site] = f"dst=const {cs}"
        else:
            out[site] = "mixed"
    return out
