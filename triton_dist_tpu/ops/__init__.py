"""Kernel library — overlapping distributed ops (the analog of reference
python/triton_dist/kernels/nvidia/*, re-exported the same way its
kernels/nvidia/__init__.py:25-89 does)."""

from triton_dist_tpu.ops.common import collective_id_for, barrier_all_op  # noqa: F401
from triton_dist_tpu.ops.allgather import all_gather  # noqa: F401
from triton_dist_tpu.ops.reduce_scatter import reduce_scatter  # noqa: F401
