"""Cluster serving (ISSUE 12 rungs 2+3): replicas + a deterministic
router.

The reference's L7 seam — "user code" above the overlap library — is
where serving becomes a FLEET problem: N independent engine replicas
behind a router, each replica its own failure domain (ISSUE 7) with its
own crash-consistency journal (ISSUE 9). This module supplies the two
host-side abstractions:

- :class:`EngineReplica` wraps ANY of the serving engines (colocated,
  disagg, sharded, composed, or the host-only :class:`SimEngine`) with a
  PRIVATE, path-namespaced journal (``journal-r{i}.jsonl`` — N replicas
  sharing one ``ControlJournal`` path would interleave their entries and
  cross-replay each other's requests on restore), load/occupancy/queue-
  depth signals read duck-typed off the engine's intake scheduler and
  pool ledger, and a ``kill()``/``restore()`` pair that drives the full
  ISSUE 9 recovery ladder: reload the journal from disk, rebuild a fresh
  engine, restore from the newest checkpoint (or replay the whole
  journal when none was cut), re-attach the append handle.
- :class:`Cluster` routes by **prefix affinity with a least-loaded
  tie-break**, rendezvous style: every alive replica scores
  ``fnv1a(index, prompt[:prefix_tokens])`` and the highest score wins,
  so a shared prompt prefix lands on the same replica (KV/page locality)
  WITHOUT a routing table — and when a replica dies, only its keys move
  (classic highest-random-weight behaviour). Ties break to the least
  loaded then the lowest index; an optional spill threshold diverts from
  a hot affinity target to the least-loaded replica. Everything is a
  pure function of (alive set, prompt, load) — the router adds no
  nondeterminism, which is what lets cluster traces be verified
  bit-identically against single-replica goldens.

:class:`SimEngine` is the scale vehicle: a host-only engine with the
REAL page ledger, the REAL scheduler (admission tickets, strict-FIFO
head-of-line, growth-driven preemption, queue caps, TTLs) and the real
journal/checkpoint surface, but a closed-form token function instead of
device dispatches — ``sim_token(prompt, i)``, a pure function of the
prompt and the token index, exactly the determinism contract the device
engines pin (tokens are a function of (params, prompt) — here params
degenerate to the hash seed). ``expected_tokens`` is therefore the
single-replica golden in closed form, and ``scripts/cluster_sim.py``
checks hundreds of thousands of routed, preempted, killed-and-restored
requests against it bitwise.
"""

from __future__ import annotations

import enum
import os
import time
from collections import deque

import numpy as np

from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.deadline import Deadline
from triton_dist_tpu.serving.engine import (class_label, mark_prefill_start,
                                            record_first_token)
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import KVPagePool, _fnv1a
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.prefix_cache import (PrefixCache,
                                                  ReplicaPrefixIndex)
from triton_dist_tpu.serving.scheduler import (AdmissionRejected,
                                               ContinuousBatchingScheduler,
                                               Request, RequestState,
                                               SLOPolicy, TtlExpired)
from triton_dist_tpu.shmem import faults

SIM_VOCAB = 32000


def sim_token(prompt: tuple[int, ...], i: int, vocab: int = SIM_VOCAB
              ) -> int:
    """The SimEngine's "model": token ``i`` of a request is a pure
    function of the prompt (first 8 tokens + length) and the index —
    the same shape of determinism contract the device engines pin."""
    return _fnv1a(0x811C9DC5, *prompt[:8], len(prompt), i) % vocab


def expected_tokens(prompt, max_new_tokens: int, vocab: int = SIM_VOCAB
                    ) -> list[int]:
    """Closed-form single-replica golden for a SimEngine request."""
    prompt = tuple(int(t) for t in prompt)
    return [sim_token(prompt, i, vocab) for i in range(max_new_tokens)]


class SimEngine:
    """Host-only serving engine: real control plane (page ledger,
    scheduler, journal, checkpoints, TTL/queue-cap shedding, growth-
    driven preemption), closed-form tokens (``sim_token``) instead of
    device dispatches. One token per ACTIVE slot per step; "prefill" is
    instantaneous at admission (the first token appears the admitting
    step, exactly like a one-chunk prompt). Exposes the same duck-typed
    surface ``serving/checkpoint.py`` restores through, so an
    :class:`EngineReplica` can kill/restore it like the device engines.

    With ``prefix_cache=True`` (ISSUE 17) the instant prefill becomes the
    device engines' chunked state machine in step space: admission adopts
    the longest cached full-page prefix (real ``PrefixCache`` over the
    real ledger), the PREFILLING slot advances ``prefill_chunk`` tokens
    per step from its cursor, and the first token lands the step the
    cursor reaches the prompt end — so cold, cached and re-warmed TTFTs
    separate DETERMINISTICALLY (``ttft_*_steps`` histograms), which is
    what the cluster lending acceptance asserts on. ``export_prefix`` /
    ``adopt_prefix`` are the lend surface ``serving/lending.py`` drives;
    the Sim pool is a pure ledger, so the "transfer" is bookkeeping only
    (device engines move the actual page bytes — ``ops.lend_pages``).
    """

    def __init__(self, num_slots: int = 4, page_size: int = 16,
                 num_pages: int = 64, pages_per_seq: int = 8,
                 metrics: ServingMetrics | None = None,
                 eos_id: int | None = None, vocab: int = SIM_VOCAB,
                 journal: ControlJournal | None = None,
                 checkpoint_every: int | None = None,
                 queue_cap: int | None = None,
                 ttl_steps: int | None = None,
                 fault_plan: "faults.FaultPlan | None" = None,
                 slo: SLOPolicy | None = None,
                 prefix_cache: bool = False,
                 prefill_chunk: int | None = None):
        assert checkpoint_every is None or journal is not None
        assert prefill_chunk is None or prefill_chunk >= 1
        assert not prefix_cache or prefill_chunk is not None, (
            "prefix_cache needs prefill_chunk set — a cache hit resumes "
            "chunked prefill at its cursor; the instant path has no "
            "cursor to resume at (same contract as ServingEngine)")
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.num_slots = num_slots
        self.eos_id = eos_id
        self.vocab = vocab
        self.metrics = metrics or ServingMetrics()
        self.alloc = KVPagePool(num_pages + 1, page_size, reserved=1)
        self.prefill_chunk = prefill_chunk
        self.prefix_cache = PrefixCache(self.alloc, page_size) \
            if prefix_cache else None
        # lend bookkeeping (ISSUE 17): pages adopted FROM a peer replica
        # (for the rewarmed-vs-cached TTFT split) and a generation counter
        # for the transient ledger seq-ids adopt_prefix allocates under
        self._lent_pages: set[int] = set()
        self._lend_gen = 0
        self._ttft_kind: dict[int, str] = {}
        self.slo = slo
        self.sched = ContinuousBatchingScheduler(num_slots,
                                                 queue_cap=queue_cap,
                                                 policy=slo)
        self.journal = journal
        self.checkpoint_every = checkpoint_every
        self.ttl_steps = ttl_steps
        self._fault_plan = fault_plan
        self._journal_muted = False
        self._replaying = False
        self._incarnation = 0
        self._last_ckpt_step = -1
        self._finished: list[Request] = []
        self._failed: list[Request] = []
        self._rejected: list[Request] = []
        self._next_rid = 0
        self._steps = 0

    # -- intake (device engines' contract verbatim) ------------------------
    def _ttl_for(self, req: Request) -> int | None:
        """Class TTL override (ISSUE 14) beats the engine-wide knob."""
        spec = self.sched.class_spec(req)
        if spec is not None and spec.ttl_steps is not None:
            return spec.ttl_steps
        return self.ttl_steps

    def submit(self, prompt, max_new_tokens: int, rid: int | None = None,
               tenant: str | None = None, cls: str | None = None) -> int:
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        assert prompt and max_new_tokens >= 1
        total = len(prompt) + max_new_tokens - 1
        need = -(-total // self.page_size)
        assert need <= self.pages_per_seq, (
            f"request needs {need} pages > pages_per_seq "
            f"{self.pages_per_seq}")
        assert need <= self.alloc.num_pages - self.alloc.reserved
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      eos_token=self.eos_id, submit_step=self._steps,
                      submit_time=time.perf_counter())
        self.sched.stamp(req, tenant=tenant, cls=cls)
        self.metrics.inc("requests_submitted")
        self.metrics.inc_class("requests_submitted", class_label(req))
        if self.sched.at_capacity_for(req.cls) and not self._replaying:
            cap = self.sched.queue_cap if self.sched.at_capacity else \
                self.sched.policy.spec(req.cls).queue_cap
            req.state = RequestState.REJECTED
            req.failure = AdmissionRejected(
                f"admission queue full for class {req.cls!r} (cap {cap}) "
                f"— request {rid} rejected")
            self._rejected.append(req)
            self.metrics.inc("rejections")
            self.metrics.inc_class("rejections", class_label(req))
            self._jlog("reject", rid=rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)
            return rid
        ttl = self._ttl_for(req)
        if ttl is not None:
            req.deadline = Deadline(ttl, req.submit_step)
        self.sched.submit(req)
        self._jlog("submit", rid=rid, prompt=list(prompt),
                   max_new_tokens=max_new_tokens,
                   tenant=req.tenant, cls=req.cls)
        return rid

    # -- one step ----------------------------------------------------------
    @property
    def idle(self) -> bool:
        return self.sched.idle

    def step(self) -> bool:
        self.sched.tick(self._steps)
        self._expire_queued()
        progressed = self._step_impl()
        self.metrics.counters["quota_throttled"] = self.sched.quota_throttled
        if progressed:
            self._maybe_checkpoint()
        return progressed

    def _can_hold(self, req: Request) -> bool:
        need = -(-len(req.prompt) // self.page_size)
        need -= len(self.alloc.pages_of(req.rid))
        avail = self.alloc.free_pages
        if self.prefix_cache is not None:
            avail += self.prefix_cache.evictable
        return avail >= max(need, 0)

    def _reclaim(self, n_pages: int) -> None:
        """Evict cached prefixes until ``n_pages`` are allocatable
        (engine.py's ``_reclaim``, verbatim semantics)."""
        short = n_pages - self.alloc.free_pages
        if short > 0 and self.prefix_cache is not None:
            self.metrics.inc("prefix_evictions",
                             self.prefix_cache.evict(short))

    def _cache_adopt(self, req: Request) -> None:
        """Admission-time prefix adoption (engine.py's ``_cache_adopt``
        in step space): acquire the longest cached full-page prefix and
        start the prefill cursor past it. Also classifies the request's
        eventual TTFT — cold (no hit), cached (local hit) or rewarmed
        (hit on pages a peer lent us)."""
        cache = self.prefix_cache
        if cache is None or req.prefill_cursor > 0 \
                or self.alloc.holds(req.rid):
            return      # resumed-after-preempt or replayed: re-prefills
        hit = cache.match(req.prompt)
        if not hit:
            self.metrics.inc("prefix_misses")
            self._ttft_kind[req.rid] = "cold"
            return
        self.alloc.acquire(req.rid, hit)
        req.prefill_cursor = len(hit) * self.page_size
        req.cache_hit_tokens = req.prefill_cursor
        self.metrics.inc("prefix_hits")
        self.metrics.inc("prefix_hit_tokens", req.prefill_cursor)
        # unlike the device engines there is no argmax to recompute, so a
        # whole-prompt hit keeps cursor == len(prompt): the first token
        # emits the admitting step — TTFT identical to a cached hit
        self._ttft_kind[req.rid] = (
            "rewarmed" if any(p in self._lent_pages for p in hit)
            else "cached")

    def _advance_prefill(self, slot: int, req: Request) -> None:
        """One chunk of step-space prefill; on reaching the prompt end,
        emit the first token, index the prompt's full pages, and record
        the cold/cached/rewarmed TTFT split (all deterministic: steps,
        not wall time)."""
        sp = len(req.prompt)
        if req.prefill_cursor < sp:
            chunk = min(self.prefill_chunk, sp - req.prefill_cursor)
            req.prefill_cursor += chunk
            self.metrics.inc("prefill_chunks")
            self._jlog("chunk", rid=req.rid, cursor=req.prefill_cursor)
            if req.prefill_cursor < sp:
                return
        req.state = RequestState.ACTIVE
        req.first_token = sim_token(req.prompt, 0, self.vocab)
        req.generated.append(req.first_token)
        record_first_token(req, self.metrics, self._steps)
        self.metrics.inc("tokens_generated")
        if self.prefix_cache is not None:
            # index full prompt pages BEFORE decode grows the sequence —
            # the partial last page (decode writes there) never enters
            self.prefix_cache.insert(
                req.prompt,
                self.alloc.pages_of(req.rid)[:sp // self.page_size])
        kind = self._ttft_kind.pop(req.rid, "cold")
        self.metrics.observe(f"ttft_{kind}_steps",
                             self._steps - req.submit_step)
        if req.done:
            self._finish(slot)

    def _step_impl(self) -> bool:
        if self.sched.idle:
            return False
        # admissions: instant "prefill" (first token the admitting step)
        # unless prefill_chunk arms the chunked state machine
        while True:
            adm = self.sched.admissible(self._can_hold)
            if adm is None:
                break
            slot, req = adm
            self._cache_adopt(req)
            need = -(-len(req.prompt) // self.page_size)
            have = len(self.alloc.pages_of(req.rid))
            if need > have:
                self._reclaim(need - have)
                got = self.alloc.alloc(req.rid, need - have)
                assert got is not None
            self.sched.activate(slot, req)
            self._jlog("admit", rid=req.rid, slot=slot)
            req.state = RequestState.PREFILLING
            mark_prefill_start(req, self.metrics, self._steps)
            self.metrics.inc("prefills")
            if self.prefill_chunk is None:
                self.metrics.inc("prefill_chunks")
                req.prefill_cursor = len(req.prompt)
                req.state = RequestState.ACTIVE
                req.first_token = sim_token(req.prompt, 0, self.vocab)
                req.generated.append(req.first_token)
                record_first_token(req, self.metrics, self._steps)
                self.metrics.inc("tokens_generated")
                if req.done:
                    self._finish(slot)
        # chunked prefill: every PREFILLING slot (including ones admitted
        # this very step) advances one chunk; a slot whose cursor reaches
        # the prompt end emits its first token and joins decode below —
        # so a whole-prompt cache hit reaches its token the admitting
        # step, exactly like the instant path (TTFT ≈ cached)
        if self.prefill_chunk is not None:
            for slot in range(self.num_slots):
                req = self.sched.slots[slot]
                if req is not None \
                        and req.state is RequestState.PREFILLING:
                    self._advance_prefill(slot, req)
        # growth + decode: one token per ACTIVE slot, paged growth with
        # the real eviction ladder when the pool runs dry. Token i's KV
        # lands at position len(prompt)+i and the LAST token's KV is
        # never written (the request finishes on emission) — so the max
        # footprint is len(prompt)+max_new_tokens-1, the submit() bound.
        for slot in range(self.num_slots):
            req = self.sched.slots[slot]
            if req is None or req.state is not RequestState.ACTIVE:
                continue
            kv_len = len(req.prompt) + len(req.generated)
            ok = self.alloc.ensure(req.rid, kv_len)
            while not ok:
                victim = self.sched.pick_victim(exclude_slot=slot)
                if victim is None:
                    break   # nobody to evict — this slot waits a step
                self._preempt(victim)
                ok = self.alloc.ensure(req.rid, kv_len)
            if not ok:
                continue
            req.generated.append(
                sim_token(req.prompt, len(req.generated), self.vocab))
            self.metrics.inc("tokens_generated")
            self.metrics.inc("decode_steps")
            if req.done:
                self._finish(slot)
        self.metrics.observe("queue_depth", self.sched.queue_depth)
        self.metrics.observe("pool_occupancy", self.alloc.occupancy())
        self._steps += 1
        return True

    def _finish(self, slot: int) -> None:
        req = self.sched.finish(slot)
        self.alloc.free_seq(req.rid)
        req.finish_step = self._steps
        self._finished.append(req)
        self.metrics.inc("requests_finished")
        self.metrics.inc_class("requests_finished", class_label(req))
        self._jlog("finish", rid=req.rid, tokens=list(req.generated),
                   submit_step=req.submit_step,
                   first_token_step=req.first_token_step,
                   preemptions=req.preemptions)

    def _preempt(self, slot: int) -> None:
        req = self.sched.slots[slot]
        self.alloc.free_seq(req.rid)
        req.prefill_cursor = 0
        req.first_token = None
        self._ttft_kind.pop(req.rid, None)   # re-classified on re-admit
        self.sched.evict(slot)
        self.metrics.inc("preemptions")
        self._jlog("preempt", rid=req.rid, slot=slot)

    def _expire_queued(self) -> None:
        for req in self.sched.expire(self._steps):
            ttl = self._ttl_for(req)
            req.failure = TtlExpired(
                f"request {req.rid} (class {req.cls!r}) queued past its "
                f"TTL ({ttl} steps from step {req.submit_step}) "
                "without admission")
            self._rejected.append(req)
            self.metrics.inc("expirations")
            self.metrics.inc_class("expirations", class_label(req))
            self._jlog("expire", rid=req.rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)

    # -- cluster page lending (ISSUE 17, serving/lending.py drives) --------
    def export_prefix(self, prompt,
                      payload: bool = True) -> tuple[int, list[int], None]:
        """Lender half: the longest locally cached full-page prefix of
        ``prompt`` that is LENDABLE — trimmed to the positional prefix
        ``KVPagePool.check_lendable`` accepts (refcount-0 AND index-
        retained; a page some live sequence still references is never
        shipped, keeping the sole-ownership/COW contract untouched).
        Returns ``(tokens, page_ids, payload)``; the Sim pool is a pure
        ledger so the payload slot is always None (device engines return
        the page bytes here — the host twin of what ``ops.lend_pages``
        moves — and skip the gather when ``payload=False``, the cheap
        depth-only probe rewarm's peer selection uses)."""
        if self.prefix_cache is None:
            return 0, [], None
        prompt = tuple(int(t) for t in prompt)
        hit = self.prefix_cache.match(prompt)
        n = self.alloc.check_lendable(hit)
        return n * self.page_size, hit[:n], None

    def adopt_prefix(self, prompt, n_tokens: int, payload=None) -> int:
        """Borrower half: materialize the first ``n_tokens`` of
        ``prompt`` as locally cached prefix pages. Pages are allocated
        under a transient lend seq-id, indexed, and immediately released
        — ``insert`` marked them cacheable, so the release parks them on
        the cached LRU exactly like a finished prefill's pages. Returns
        pages newly adopted (0 = nothing to do or pool too tight; the
        lending tier degrades to cold prefill, never stalls)."""
        cache = self.prefix_cache
        if cache is None or n_tokens <= 0:
            return 0
        prompt = tuple(int(t) for t in prompt)
        want = min(n_tokens, len(prompt)) // self.page_size
        have = cache.match(prompt)
        if want <= len(have):
            return 0        # local cache already at least as deep
        need = want - len(have)
        sid = ("lend", self._lend_gen)
        self._lend_gen += 1
        if have:
            # pin the local hit under the lend sid BEFORE reclaiming:
            # `have` sits refcount-0 on the cached LRU, so an unpinned
            # reclaim under pool pressure could evict it out from under
            # the insert below (same acquire-first order as _cache_adopt)
            self.alloc.acquire(sid, have)
        self._reclaim(need)
        got = self.alloc.alloc(sid, need)
        if got is None:
            self.alloc.free_seq(sid)    # unpin the hit
            return 0        # pool too tight even after eviction
        # [device engines scatter payload bytes into `got` here]
        # the first len(have) entries ride existing trie edges (insert is
        # first-writer-wins: pages for existing runs are ignored), the
        # fresh pages take the runs beyond the local hit
        cache.insert(prompt[:want * self.page_size], have + got)
        self.alloc.free_seq(sid)    # refcount-0 + cacheable → cached LRU
        self._lent_pages.update(got)
        self._jlog("lend", tokens=want * self.page_size, pages=need)
        return need

    def run(self, max_steps: int | None = None, arrivals=None,
            recover=None) -> dict[int, list[int]]:
        if recover:
            assert self.journal is not None
            ck = recover if isinstance(recover, ckpt_mod.Checkpoint) \
                else ckpt_mod.latest(self.journal)
            ckpt_mod.restore(self, ck, self.journal)
        pending = deque(arrivals or [])
        i = 0
        while max_steps is None or i < max_steps:
            while pending and pending[0][0] <= i:
                item = pending.popleft()
                self.submit(item[1], item[2],
                            tenant=item[3] if len(item) > 3 else None,
                            cls=item[4] if len(item) > 4 else None)
            if not self.step() and not pending:
                break
            i += 1
            plan = self._fault_plan if self._fault_plan is not None \
                else faults.active_plan()
            if plan is not None and plan.crash(self._steps,
                                               self._incarnation):
                self.metrics.inc("faults_injected")
                raise faults.InjectedCrash(
                    f"injected crash at step {self._steps} "
                    f"(incarnation {self._incarnation})")
        return {req.rid: list(req.generated) for req in self._finished}

    # -- crash consistency (checkpoint.py duck-typed surface) --------------
    def control_digest(self) -> int:
        # cheap by design: folded counters, not the full ledgers — at
        # cluster_sim scale (100k+ requests) an O(pages+queue) digest per
        # journal entry dominates the run. The checkpoint audit still
        # hashes the REAL pool ledger (pool_digest below).
        return _fnv1a(0x811C9DC5, self._steps, self._next_rid,
                      self.alloc.used_pages, self.sched.queue_depth,
                      self.sched._admit_ticket,
                      self.metrics.counters["requests_finished"])

    def _jlog(self, kind: str, **payload) -> None:
        if self.journal is None or self._journal_muted:
            return
        self.journal.append(kind, self._steps, self.control_digest(),
                            **payload)

    def _maybe_checkpoint(self) -> None:
        if (self.journal is None or not self.checkpoint_every
                or self._steps == 0
                or self._steps % self.checkpoint_every
                or self._steps == self._last_ckpt_step):
            return
        self.checkpoint()

    def checkpoint(self) -> "ckpt_mod.Checkpoint":
        assert self.journal is not None
        ck = ckpt_mod.capture(self)
        self.journal.record_checkpoint(ck.step, ck.digest, ck.state,
                                       ck.journal_seq)
        self._last_ckpt_step = self._steps
        self.metrics.inc("checkpoints")
        return ck

    def _capture_state(self) -> dict:
        live: list[Request] = []
        seen: set[int] = set()
        for _, req in sorted(((r.admitted_seq, r)
                              for _, r in self.sched.active),
                             key=lambda t: t[0]):
            seen.add(req.rid)
            live.append(req)
        for req in self.sched.queue:
            if req.rid not in seen:
                live.append(req)
        return {
            "engine": "sim",
            "step": self._steps,
            "next_rid": self._next_rid,
            "admit_ticket": self.sched._admit_ticket,
            "pool": self.alloc.snapshot(),
            "pool_digest": self.alloc.digest(),
            # prefix index (ISSUE 17): integrity artifact, like the pool
            # snapshot — restore starts with an EMPTY cache (the cluster
            # re-warms it from peers; pre-crash pages are never adopted)
            "prefix_index": None if self.prefix_cache is None
            else self.prefix_cache.snapshot(),
            "prefix_digest": None if self.prefix_cache is None
            else self.prefix_cache.digest(),
            "live": [ckpt_mod.snapshot_request(r) for r in live],
            "finished": [ckpt_mod.snapshot_finished(r)
                         for r in self._finished],
            "rejected": [{"rid": r.rid, "kind": "expire"
                          if isinstance(r.failure, TtlExpired) else "reject",
                          "reason": str(r.failure), "tenant": r.tenant,
                          "cls": r.cls} for r in self._rejected],
            "policy": self.sched.policy_state(),
            "counters": dict(self.metrics.counters),
        }

    def _restore_state(self, state: dict | None) -> None:
        self.alloc = KVPagePool(self.alloc.num_pages, self.page_size,
                                reserved=1)
        self.sched = ContinuousBatchingScheduler(
            self.sched.num_slots, queue_cap=self.sched.queue_cap,
            policy=self.sched.policy)
        if self.prefix_cache is not None:
            # EMPTY cache over the fresh pool: restored requests re-earn
            # KV via re-prefill; the cluster's restore() re-warms shared
            # prefixes from peers through the lending tier
            self.prefix_cache = PrefixCache(self.alloc, self.page_size)
        self._lent_pages = set()
        self._ttft_kind = {}
        self._finished = []
        self._failed = []
        self._rejected = []
        if state is None:
            return
        ckpt_mod.audit_pool_snapshot(state["pool"], state["pool_digest"],
                                     self.alloc.num_pages, self.page_size, 1)
        if state.get("prefix_index") is not None:
            ckpt_mod.audit_prefix_snapshot(state["prefix_index"],
                                           state["prefix_digest"])
        self._steps = state["step"]
        self._next_rid = state["next_rid"]
        self.sched._admit_ticket = state["admit_ticket"]
        for snap in state["live"]:
            req = ckpt_mod.rebuild_request(snap)
            req.submit_time = time.perf_counter()
            ttl = self._ttl_for(req)
            if ttl is not None:
                req.deadline = Deadline(ttl, req.submit_step)
            self.sched.submit(req)
        # WFQ/bucket books restore AFTER the requeues: submit()'s idle-
        # class vfloor snap ran against zeroed counters above, and the
        # checkpoint values now overwrite them (order-dependent)
        self.sched.restore_policy_state(state.get("policy"))
        for f in state["finished"]:
            self._restore_finished(f["rid"], f["tokens"], meta=f)
        for f in state["rejected"]:
            self._restore_terminal(f["rid"], f["kind"], f["reason"])

    def _restore_finished(self, rid: int, tokens: list[int],
                          meta: dict | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            prompt = tuple((meta or {}).get("prompt", (0,)))
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=len(tokens), eos_token=self.eos_id)
        req.state = RequestState.FINISHED
        req.generated = list(tokens)
        for k in ("submit_step", "first_token_step", "preemptions"):
            if meta is not None and k in meta:
                setattr(req, k, meta[k])
        self._finished.append(req)

    def _restore_terminal(self, rid: int, kind: str, reason: str,
                          error_type: str | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            req = Request(rid=rid, prompt=(0,), max_new_tokens=1,
                          eos_token=self.eos_id)
        req.state = RequestState.REJECTED
        req.failure = (TtlExpired(reason) if kind == "expire"
                       else AdmissionRejected(reason))
        self._rejected.append(req)

    def _pop_queued(self, rid: int) -> Request | None:
        for r in self.sched.queue:
            if r.rid == rid:
                self.sched.queue.remove(r)
                return r
        return None

    @property
    def failed(self) -> list[Request]:
        return list(self._failed) + list(self._rejected)


class ReplicaState(enum.Enum):
    """Replica lifecycle (ISSUE 18). The router admits only ACTIVE
    replicas; DRAINING replicas keep stepping (they finish in-flight
    decodes and may still LEND — that is drain-time lend-ahead) but
    receive no new work; WARMING replicas exist (their engine is built,
    the AOT artifact loaded) but neither admit nor step until the
    cluster promotes them; KILLED is the crash state (engine gone,
    journal on disk is the surviving truth — restore() returns the
    replica to whatever it was doing when it died, which is how a crash
    mid-drain resumes the drain rather than resurrecting an admitting
    replica); RETIRED is terminal — a drain completed, the journal
    closed, the engine dropped. Fleet indices are append-only: a retired
    index is never reused, so journal paths and rendezvous scores stay
    stable across any schedule of scale events."""

    WARMING = "warming"
    ACTIVE = "active"
    DRAINING = "draining"
    RETIRED = "retired"
    KILLED = "killed"


class EngineReplica:
    """One engine + one PRIVATE journal + one failure domain.

    ``factory(journal)`` builds the engine; the replica derives its own
    journal path (``journal-r{index}.jsonl`` under ``journal_dir``) so N
    replicas in one directory never interleave entries — the namespacing
    the two-replica restart test pins (no cross-replica replay bleed).
    ``journal_dir=None`` keeps the journal in memory (kill/restore then
    replays the retained object instead of re-reading disk).
    """

    def __init__(self, index: int, factory, journal_dir: str | None = None,
                 artifact=None):
        self.index = index
        self._factory = factory
        self.artifact = artifact
        self.journal_path = (os.path.join(journal_dir,
                                          f"journal-r{index}.jsonl")
                             if journal_dir is not None else None)
        self.journal = ControlJournal(path=self.journal_path)
        self.lifecycle = ReplicaState.WARMING
        t0 = time.perf_counter()
        self.engine = self._build(self.journal)
        # scale-up-to-first-token split: with an AOT artifact threaded
        # the build is dominated by artifact load, not tracing — the
        # number cluster_sim's autoscale panel reports per scale-up
        self.build_s = time.perf_counter() - t0
        self.lifecycle = ReplicaState.ACTIVE
        self.warm_remaining = 0
        self.failovers = 0
        # crash bookkeeping: what the replica was doing when kill() hit
        # (restore() resumes THAT state — a crash mid-drain must come
        # back DRAINING, not admitting) and, for a drain interrupted by
        # a crash, the kill-time tombstones finish_drain still lends
        # ahead (prune already ran at kill time, so the drain-completion
        # prune would otherwise find nothing to hand off)
        self._prekill = ReplicaState.ACTIVE
        self._drain_prefixes: list[tuple[int, ...]] = []

    @property
    def alive(self) -> bool:
        """An engine exists and can step/lend. DRAINING and WARMING
        replicas are alive — only KILLED and RETIRED are not."""
        return self.engine is not None

    @property
    def admitting(self) -> bool:
        """The router's gate: only ACTIVE replicas receive new work."""
        return self.lifecycle is ReplicaState.ACTIVE

    @property
    def draining(self) -> bool:
        return self.lifecycle is ReplicaState.DRAINING

    def _build(self, journal):
        """AOT artifact (ISSUE 15): thread the artifact through BOTH the
        cold build and every restore — a restored replica must reach its
        first token with zero fresh traces, exactly like a cold one."""
        if self.artifact is not None:
            return self._factory(journal, artifact=self.artifact)
        return self._factory(journal)

    # load signals, duck-typed off the engine's intake scheduler and the
    # pool the decode work actually occupies
    @property
    def _sched(self):
        return getattr(self.engine, "sched_p", None) or self.engine.sched

    @property
    def _alloc(self):
        return getattr(self.engine, "alloc_d", None) or self.engine.alloc

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def occupancy(self) -> float:
        return self._alloc.occupancy()

    @property
    def load(self) -> int:
        """Routing load: queued + seated requests on the intake side."""
        s = self._sched
        return s.queue_depth + sum(r is not None for r in s.slots)

    @property
    def idle(self) -> bool:
        e = self.engine
        v = getattr(e, "idle", None)
        return bool(v) if v is not None else e.sched.idle

    def submit(self, prompt, max_new_tokens: int,
               tenant: str | None = None, cls: str | None = None) -> int:
        assert self.alive, f"replica {self.index} is dead"
        return self.engine.submit(prompt, max_new_tokens,
                                  tenant=tenant, cls=cls)

    def step(self) -> bool:
        assert self.alive, f"replica {self.index} is dead"
        return self.engine.step()

    def kill(self) -> None:
        """Fail the replica: close the journal's append handle (the
        on-disk jsonl is the surviving truth) and drop the engine.
        Legal in ANY alive state — killing a DRAINING replica is the
        crash-mid-drain case, and ``_prekill`` remembers the state so
        restore() resumes the drain instead of re-admitting."""
        assert self.alive, f"replica {self.index} is already dead"
        self._prekill = self.lifecycle
        self.journal.close()
        self.engine = None
        self.lifecycle = ReplicaState.KILLED
        self.failovers += 1

    def restore(self) -> dict:
        """The full ISSUE 9 ladder: reload the journal (from disk when
        path-backed), rebuild a fresh engine through the factory, restore
        from the newest checkpoint — or replay the ENTIRE journal when
        none was cut — then re-attach the append handle so post-restore
        events keep journaling to the same file. The replica comes back
        in its pre-kill lifecycle state: a crash mid-drain resumes
        DRAINING (replay requeues its live requests, the cluster's drain
        pass hands them to peers and retires it), never admitting."""
        assert self.lifecycle is ReplicaState.KILLED, \
            f"replica {self.index} is not killed"
        if self.journal_path is not None:
            j = ControlJournal.load(self.journal_path)
            # .load() returns an in-memory journal: re-attach the file so
            # the restored replica keeps appending where it left off
            j.path = self.journal_path
            j._fh = open(self.journal_path, "a", encoding="utf-8")
        else:
            j = self.journal
        self.journal = j
        self.engine = self._build(j)
        stats = ckpt_mod.restore(self.engine, ckpt_mod.latest(j), j)
        self.lifecycle = self._prekill
        return stats

    def retire(self) -> None:
        """Terminal exit of a completed drain: close the journal, drop
        the engine. Unlike kill() there is nothing to restore — every
        request either finished (harvested) or was requeued to a peer."""
        assert self.lifecycle is ReplicaState.DRAINING, \
            f"replica {self.index} is not draining"
        self.journal.close()
        self.engine = None
        self.lifecycle = ReplicaState.RETIRED


class Cluster:
    """Deterministic router over N replicas (module docstring): cache-
    aware radix-hit affinity first (ISSUE 13), rendezvous hashing as the
    fallback, least-loaded tie-break, optional spill threshold,
    kill/restore through each replica's private journal."""

    def __init__(self, factory, replicas: int = 4,
                 journal_dir: str | None = None, prefix_tokens: int = 8,
                 spill_threshold: int | None = None, artifact=None,
                 affinity: bool = True, lend: bool = False,
                 lend_plan: "faults.FaultPlan | None" = None,
                 lend_deadline_steps: int = 4, lend_retries: int = 2):
        assert replicas >= 1
        # kept for elastic scale-up: add_replica() builds late joiners
        # through the same factory/journal_dir/artifact as the seed fleet
        self._factory = factory
        self._journal_dir = journal_dir
        self._artifact = artifact
        self.replicas = [EngineReplica(i, factory, journal_dir,
                                       artifact=artifact)
                         for i in range(replicas)]
        self.prefix_tokens = prefix_tokens
        self.spill_threshold = spill_threshold
        self.affinity = affinity
        # authoritative cluster prefix index (ISSUE 13 → promoted in
        # ISSUE 17): token runs of routed prompts map to the replica that
        # first served them. Two consumers: the router (radix-hit
        # affinity, gated by ``affinity`` so the lending tier can be
        # measured without routing help) and the page-lending tier. A
        # dead replica's entries are PRUNED by kill() — stale entries
        # would route, and worse LEND, against pages that no longer exist
        # — and stashed as tombstones that restore() re-warms from peers
        # and re-registers.
        self.prefix_index = ReplicaPrefixIndex(prefix_tokens)
        self._tombstones: dict[int, list[tuple[int, ...]]] = {}
        self.metrics = ServingMetrics()
        # the lending tier is imported lazily: lending.py is pure host
        # control plane over this module's duck-typed engine surface
        if lend:
            from triton_dist_tpu.serving.lending import PageLendingTier
            self.lending = PageLendingTier(
                self, plan=lend_plan,
                deadline_steps=lend_deadline_steps,
                max_retries=lend_retries)
        else:
            self.lending = None
        self._placement: dict[int, tuple[int, int]] = {}  # gid -> (ri, rid)
        self._rindex: dict[tuple[int, int], int] = {}     # (ri, rid) -> gid
        # gid -> (prompt, max_new_tokens, tenant, cls): enough to re-place
        # the request on a peer when its replica drains (ISSUE 18)
        self._requests: dict[
            int, tuple[tuple[int, ...], int, str | None, str | None]] = {}
        self._results: dict[int, list[int]] = {}
        self._failed: set[int] = set()
        self._next_gid = 0
        # elastic autoscaling (ISSUE 18): every membership event, append-
        # only — (cluster_step, kind, replica index). The Autoscaler
        # journals from this feed (cursor-read, so manual scale events in
        # tests/sims are journaled too); panels read it whole.
        self.scale_history: list[tuple[int, str, int]] = []
        # per-finish (cls, ttft_steps, itl_steps|None) — the autoscaler's
        # attainment sensor drains this; bounded so a run without an
        # autoscaler attached never grows it past the window
        self._latency_feed: deque = deque(maxlen=4096)
        self._cluster_steps = 0

    @property
    def admitting_replicas(self) -> list[EngineReplica]:
        """The router's candidate set: ACTIVE replicas only. Draining,
        warming, killed and retired replicas are all distinguishable
        here — none admit, but DRAINING ones still step and lend."""
        return [r for r in self.replicas if r.admitting]

    def lifecycle_counts(self) -> dict[str, int]:
        """Fleet composition by lifecycle state (panel/debug summary)."""
        out: dict[str, int] = {}
        for r in self.replicas:
            out[r.lifecycle.value] = out.get(r.lifecycle.value, 0) + 1
        return out

    def rendezvous_owner(self, prompt) -> int:
        """Load-free rendezvous winner for ``prompt`` over the current
        admitting set — the pure hash placement, no affinity index, no
        load tie-break. This is the function whose stability under
        membership change the O(1/N) churn tests pin: adding or removing
        one replica moves only the keys the new replica wins (or the
        removed replica owned), ≈ 1/N of a fixed population."""
        prompt = tuple(int(t) for t in prompt)
        key = prompt[:self.prefix_tokens] if self.affinity else prompt
        cands = self.admitting_replicas
        assert cands, "no admitting replicas"
        return max(cands, key=lambda r: (
            _fnv1a(0x811C9DC5, r.index, *key), -r.index)).index

    def route(self, prompt) -> EngineReplica:
        """Longest radix-index hit wins (the deepest run's replica most
        likely holds the prefix KV); rendezvous hashing with least-loaded
        tie-break handles misses and non-admitting affinity targets. Pure
        function of (index state, admitting set, prompt, load) — still
        deterministic through any schedule of scale events."""
        prompt = tuple(int(t) for t in prompt)
        cands = self.admitting_replicas
        assert cands, "no admitting replicas"
        owner = None
        if self.affinity:
            _, owner = self.prefix_index.match(prompt)
        if owner is not None and self.replicas[owner].admitting:
            pick = self.replicas[owner]
            self.metrics.inc("router_radix_hits")
        else:
            # affinity ON keys rendezvous by the shared prefix (a
            # template's requests co-locate even before its first index
            # entry); affinity OFF keys by the FULL prompt — same-prefix
            # requests scatter across the fleet, the adversarial placement
            # the lending tier must absorb (the ISSUE 17 acceptance:
            # cluster hit rate ≈ single-replica hit rate even then)
            key = prompt[:self.prefix_tokens] if self.affinity else prompt
            pick = max(cands, key=lambda r: (
                _fnv1a(0x811C9DC5, r.index, *key),
                -r.load, -r.index))
            self.metrics.inc("router_radix_misses")
        if (self.spill_threshold is not None
                and pick.load > self.spill_threshold):
            pick = min(cands, key=lambda r: (r.load, r.index))
        return pick

    def _place(self, gid: int, prompt, max_new_tokens: int,
               tenant: str | None, cls: str | None) -> EngineReplica:
        """Route + lend + index + submit + book one request under an
        existing gid — the shared tail of submit() and the drain-time
        requeue (which re-places a moved request under its ORIGINAL
        gid, so callers' handles survive the move)."""
        rep = self.route(prompt)
        if self.lending is not None:
            # borrower-side pre-warm (ISSUE 17): if a PEER owns this
            # prompt's deepest indexed prefix and the target replica's
            # cache misses, lend the pages NOW — the request's chunked
            # prefill then resumes past the adopted prefix, so the lend
            # latency overlaps admission instead of serializing with it
            self.lending.lend(rep, prompt)
        # first-writer-wins: runs this prompt ADDS stick to the replica
        # that actually received it, existing runs keep their owner
        self.prefix_index.insert(tuple(int(t) for t in prompt), rep.index)
        rid = rep.submit(prompt, max_new_tokens, tenant=tenant, cls=cls)
        self._placement[gid] = (rep.index, rid)
        self._rindex[(rep.index, rid)] = gid
        self._requests[gid] = (tuple(int(t) for t in prompt),
                               max_new_tokens, tenant, cls)
        return rep

    def submit(self, prompt, max_new_tokens: int,
               tenant: str | None = None, cls: str | None = None) -> int:
        gid = self._next_gid
        self._next_gid += 1
        self._place(gid, prompt, max_new_tokens, tenant, cls)
        self.metrics.inc("requests_submitted")
        return gid

    def step(self) -> bool:
        progressed = False
        # warming → active: promotion is a cluster-step event, so a
        # scale-up becomes routable at a deterministic point in the trace
        # (warm_remaining models the artifact-load window in step space)
        for rep in self.replicas:
            if rep.lifecycle is ReplicaState.WARMING:
                rep.warm_remaining -= 1
                if rep.warm_remaining <= 0:
                    rep.lifecycle = ReplicaState.ACTIVE
                    progressed = True
        stepped = 0
        for rep in self.replicas:
            if rep.alive and rep.lifecycle is not ReplicaState.WARMING:
                progressed |= rep.step()
                stepped += 1
        self.metrics.inc("replica_steps", stepped)
        self._cluster_steps += 1
        self.metrics.observe("fleet_size", sum(
            1 for r in self.replicas if r.lifecycle in
            (ReplicaState.ACTIVE, ReplicaState.WARMING)))
        self._harvest()
        # drain pass: a DRAINING replica sheds its queue every step (the
        # journal-cursor requeue — normally once at drain_begin, again
        # after a crash-mid-drain restore replays its live requests) and
        # retires the step it reaches quiescence
        for rep in self.replicas:
            if rep.draining and rep.engine is not None:
                progressed |= self._requeue_queued(rep) > 0
                if rep.idle:
                    self._finish_drain(rep)
                    progressed = True
        return progressed

    def _harvest(self) -> None:
        for rep in self.replicas:
            if rep.engine is None:
                continue
            fin = rep.engine._finished
            if fin:
                for req in fin:
                    gid = self._rindex.get((rep.index, req.rid))
                    if gid is None:
                        continue
                    if gid not in self._results:
                        self.metrics.inc("requests_finished")
                        if (req.first_token_time is not None
                                and req.submit_time is not None):
                            self.metrics.observe(
                                "ttft_s",
                                req.first_token_time - req.submit_time)
                        self._observe_latency(req)
                    self._results[gid] = list(req.generated)
                rep.engine._finished = []
            for req in rep.engine.failed:
                gid = self._rindex.get((rep.index, req.rid))
                if gid is not None and gid not in self._failed:
                    self._failed.add(gid)
                    self.metrics.inc("failed_requests")

    def _observe_latency(self, req) -> None:
        """Deterministic step-space TTFT/ITL for one first-time finish —
        the per-class series the autoscaler's attainment windows sample
        (engine-local steps: both stamps come off the same clock, so a
        requeued request measures from its re-placement)."""
        if req.first_token_step is None or req.submit_step is None:
            return
        cls = getattr(req, "cls", None) or "default"
        ttft = req.first_token_step - req.submit_step
        self.metrics.observe("ttft_steps", ttft)
        self.metrics.observe_class("ttft_steps", cls, ttft)
        itl = None
        fin_step = getattr(req, "finish_step", None)
        if fin_step is not None and len(req.generated) > 1:
            itl = ((fin_step - req.first_token_step)
                   / (len(req.generated) - 1))
            self.metrics.observe("itl_steps", itl)
            self.metrics.observe_class("itl_steps", cls, itl)
        self._latency_feed.append((cls, ttft, itl))

    # -- elastic membership (ISSUE 18) -------------------------------------
    def _scale_event(self, kind: str, index: int) -> None:
        self.scale_history.append((self._cluster_steps, kind, index))

    def add_replica(self, warm_steps: int = 0) -> EngineReplica:
        """Grow the fleet: build a late joiner through the same factory
        (and AOT artifact — it reaches its first token with zero fresh
        traces, which is what makes mid-run scale-up affordable) under
        the next never-used index. The replica joins WARMING and is
        promoted to ACTIVE ``warm_steps`` cluster steps later (0 = the
        next step), so the membership change lands at a deterministic
        point in the trace."""
        assert warm_steps >= 0
        rep = EngineReplica(len(self.replicas), self._factory,
                            self._journal_dir, artifact=self._artifact)
        rep.lifecycle = ReplicaState.WARMING
        rep.warm_remaining = warm_steps
        self.replicas.append(rep)
        self.metrics.inc("scale_ups")
        self.metrics.observe("scale_up_build_s", rep.build_s)
        self._scale_event("scale_up", rep.index)
        return rep

    def begin_drain(self, index: int) -> int:
        """Start a graceful drain: the replica stops admitting NOW and
        its queued (never-admitted) requests move to peers immediately —
        each one re-routed under its original gid, journaled as a
        ``requeue`` on the source engine so a crash after the move never
        re-serves it. In-flight PREFILLING/ACTIVE slots finish where
        they sit (their KV exists only there; determinism means a peer
        would regenerate identical tokens, but letting them run costs no
        correctness and no handoff). step()'s drain pass retires the
        replica at quiescence. Returns the number of requests moved."""
        rep = self.replicas[index]
        assert rep.admitting, (
            f"replica {index} is {rep.lifecycle.value}, not active")
        assert any(r.admitting and r.index != index for r in self.replicas), \
            "cannot drain the last admitting replica"
        rep.lifecycle = ReplicaState.DRAINING
        self.metrics.inc("drains_begun")
        self._scale_event("drain_begin", index)
        return self._requeue_queued(rep)

    def _requeue_queued(self, rep: EngineReplica) -> int:
        """The journal-cursor requeue: pop every QUEUED request off the
        draining replica's intake (admitted slots stay — they finish
        in place) and re-place it on an admitting peer under the same
        gid. KV is never moved — the peer re-earns it from the prompt
        and the determinism contract regenerates identical tokens, the
        same restart-from-prompt argument restore runs on."""
        sched = rep._sched
        moved = 0
        # snapshot: _place mutates nothing on THIS replica, but pop first
        # so a reroute back here (impossible — it no longer admits) or an
        # assert can't leave the queue half-walked
        queued = list(sched.queue)
        for req in queued:
            gid = self._rindex.pop((rep.index, req.rid), None)
            if gid is None:
                continue    # replay artifact not booked here — drop
            sched.queue.remove(req)
            del self._placement[gid]
            rep.engine._jlog("requeue", rid=req.rid)
            prompt, mnt, tenant, cls = self._requests[gid]
            self._place(gid, prompt, mnt, tenant, cls)
            moved += 1
        if moved:
            self.metrics.inc("requeues", moved)
        return moved

    def _successor_of(self, prefix) -> EngineReplica | None:
        """Rendezvous successor for a drained prefix: the admitting
        replica that wins the SAME key route() would hash once the
        drainee is gone — so lend-ahead lands pages exactly where the
        prefix's future traffic will rendezvous."""
        prefix = tuple(int(t) for t in prefix)
        key = prefix[:self.prefix_tokens] if self.affinity else prefix
        cands = self.admitting_replicas
        if not cands:
            return None
        return max(cands, key=lambda r: (
            _fnv1a(0x811C9DC5, r.index, *key), -r.index))

    def _finish_drain(self, rep: EngineReplica) -> None:
        """Quiescence reached: hand the drainee's hot prefix-index
        entries to their rendezvous successors (drain-time lend-ahead,
        the PR 17 surface pushed instead of pulled), prune what could
        not move, retire."""
        # prune returns the drainee's owned prefixes; a crash-mid-drain
        # already pruned at kill time and stashed them on the replica
        tombs = list(rep._drain_prefixes)
        rep._drain_prefixes = []
        tombs += self.prefix_index.prune(rep.index)
        if self.lending is not None and tombs:
            placed = self.lending.lend_ahead(rep, tombs,
                                             self._successor_of)
            for prefix, succ in placed.items():
                # the successor now holds the pages warm — point the
                # index at it so the very next route() radix-hits there
                self.prefix_index.reassign(prefix, succ)
        rep.retire()
        self.metrics.inc("drains_done")
        self.metrics.inc("retires")
        self._scale_event("drain_done", rep.index)
        self._scale_event("retire", rep.index)

    def kill(self, index: int) -> None:
        self.replicas[index].kill()
        self.metrics.inc("faults_injected")
        self._scale_event("kill", index)
        # ISSUE 17 satellite: a dead replica's pages are gone — prune its
        # index entries so neither the router nor the lending tier targets
        # them, and stash the tombstoned prefixes for restore-time re-warm
        self._tombstones[index] = self.prefix_index.prune(index)

    def restore(self, index: int) -> dict:
        stats = self.replicas[index].restore()
        self.metrics.inc("restores")
        self._scale_event("restore", index)
        tombs = self._tombstones.pop(index, [])
        if self.replicas[index].draining:
            # crash-mid-drain fallback: the replica came back DRAINING —
            # it will never admit again, so re-warming its cache or
            # re-registering its index entries would aim traffic at a
            # retiree. Stash the kill-time tombstones instead: the drain
            # pass requeues the replayed queue to peers and finish_drain
            # lends THESE prefixes ahead to their successors.
            self.replicas[index]._drain_prefixes = tombs
            self._harvest()   # replayed finishes reappear — re-record
            return stats
        if self.lending is not None and tombs:
            # re-warm the restored replica's cache from peers instead of
            # letting every shared prefix re-prefill cold (deepest-first:
            # one lend covers every ancestor tombstone via early-out)
            self.lending.rewarm(self.replicas[index], tombs)
        # re-register only AFTER the restore (and re-warm, when lending)
        # verified: the checkpoint audit ran inside restore(), and the
        # re-warm adopts through the same audited ledger — re-check it
        # before the index points traffic back here. reassign OVERWRITES
        # the current owner, so a tombstone comes back only if the
        # restored cache actually holds it warm (a deep lend covers its
        # ancestor tombstones — the match sees them all) OR nobody else
        # claimed it mid-death (unowned affinity returns even cold: both
        # sides are equally cold, and entries are never dropped). A
        # prefix a peer claimed that the restoree could not re-warm —
        # every claimed prefix when lending is off, the cache being
        # empty by contract — stays with the peer that holds it warm;
        # first-writer-wins re-registers on the next submit routed here.
        eng = self.replicas[index].engine
        if tombs and getattr(eng, "alloc", None) is not None:
            eng.alloc.check()
        cache = getattr(eng, "prefix_cache", None)
        for prefix in tombs:
            warm = cache is not None and cache.match(prefix)
            if warm or self.prefix_index.match(prefix)[1] is None:
                self.prefix_index.reassign(prefix, index)
        self._harvest()   # replayed finishes reappear — re-record them
        return stats

    def drain(self, max_steps: int = 1_000_000) -> dict[int, list[int]]:
        for _ in range(max_steps):
            if not self.step():
                break
        return self.results()

    def results(self) -> dict[int, list[int]]:
        return dict(self._results)

    @property
    def failed_gids(self) -> set[int]:
        return set(self._failed)

    def drain_latency_feed(self) -> list[tuple[str, int, float | None]]:
        """Drain the per-finish (cls, ttft_steps, itl_steps) feed — the
        autoscaler's attainment sensor calls this once per step."""
        out = list(self._latency_feed)
        self._latency_feed.clear()
        return out


__all__ = ["Cluster", "EngineReplica", "ReplicaState", "SimEngine",
           "expected_tokens", "sim_token", "SIM_VOCAB"]
