"""sigcheck: the static signal-protocol verifier's own gate (ISSUE 10).

Everything here is trace-time only — the capture replays kernels on
numpy-backed fake refs and the determinism lint runs ``jax.make_jaxpr``,
so NO kernel executes on any device. The suite pins three contracts:

1. every registered op verifies CLEAN at n ∈ {2, 3, 4} (and the 2d/pair
   meshes its entry declares) — zero findings of any kind;
2. the three serving programs pass the determinism lint;
3. every broken-kernel gallery entry is flagged WITH ITS EXPECTED finding
   kind — if a checker change stops catching one, that is a checker
   regression, not a cleaner gallery;

plus the registry↔ops parity satellite: the registry must name the entire
``triton_dist_tpu.ops`` public surface (checked or skipped-with-reason), so
a new op cannot land unverified by accident.
"""

import json
import os
import subprocess
import sys

import pytest

from triton_dist_tpu.analysis import (check_gallery, check_registry,
                                      lint_serving_programs, sigcheck)
from triton_dist_tpu.analysis.registry import REGISTRY, surface_names

pytestmark = [pytest.mark.quick, pytest.mark.analysis]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. the registry verifies clean ------------------------------------------

_CHECKED = sorted(n for n, e in REGISTRY.items() if e.skip is None)
_SKIPPED = sorted(n for n, e in REGISTRY.items() if e.skip is not None)


@pytest.mark.parametrize("name", _CHECKED)
def test_registered_op_is_clean(name):
    entry = REGISTRY[name]
    rep = sigcheck(entry.run, op=name, meshes=entry.meshes)
    assert rep.ok, (
        f"{name} has findings:\n" +
        "\n".join(f"  {f}" for f in rep.findings))
    # on multi-rank meshes the capture must have actually recorded the
    # protocol, not no-opped (local single-rank kernels legitimately have
    # no signal events)
    assert rep.event_counts
    for n, count in rep.event_counts.items():
        if n >= 2:
            assert count > 0, f"{name}: no events captured at n={n}"


def test_skips_carry_reasons():
    for name in _SKIPPED:
        assert REGISTRY[name].skip.strip(), f"{name} skipped without reason"


def test_registry_matches_ops_surface():
    """Satellite (a): the registry must cover the whole ops re-export
    surface and name nothing stale — parity both ways."""
    surface = set(surface_names())
    registry = set(REGISTRY)
    assert surface - registry == set(), (
        f"public ops missing from the sigcheck registry: "
        f"{sorted(surface - registry)}")
    assert registry - surface == set(), (
        f"registry names no longer exported from triton_dist_tpu.ops: "
        f"{sorted(registry - surface)}")


def test_ops_init_reexports_submodule_surface():
    """Satellite (a): ``ops/__init__.py`` re-exports every public symbol of
    every ops submodule (lockstep guard for the next op that lands)."""
    import importlib
    import pkgutil

    import triton_dist_tpu.ops as ops_pkg

    top = {n for n in dir(ops_pkg) if not n.startswith("_")}
    for info in pkgutil.iter_modules(ops_pkg.__path__):
        mod = importlib.import_module(f"triton_dist_tpu.ops.{info.name}")
        public = getattr(mod, "__all__", None)
        if public is None:
            continue
        missing = set(public) - top
        assert missing == set(), (
            f"ops.{info.name}.__all__ names not re-exported from "
            f"triton_dist_tpu.ops: {sorted(missing)}")


# -- 2. serving determinism lint ---------------------------------------------

def test_serving_programs_lint_clean():
    findings = lint_serving_programs()
    assert findings == [], (
        "serving trace-determinism contract violated:\n" +
        "\n".join(f"  {f}" for f in findings))


# -- 3. the broken-kernel gallery is caught ----------------------------------

_GALLERY = check_gallery()


@pytest.mark.parametrize("name", sorted(_GALLERY))
def test_gallery_kernel_is_flagged(name):
    expected, rep = _GALLERY[name]
    assert expected in rep.finding_kinds, (
        f"gallery kernel {name} must be flagged {expected!r}, got "
        f"{rep.finding_kinds or 'nothing'} — checker regression")


def test_gallery_spans_the_taxonomy():
    """One gallery kernel per finding class the issue names."""
    kinds = {expected for expected, _ in _GALLERY.values()}
    assert {"under_signal", "over_signal", "deadlock", "unordered_read",
            "nondeterminism"} <= kinds


def test_capture_error_is_a_finding_not_an_escape():
    """An op the verifier cannot replay must FAIL the check, loudly."""
    def broken(ctx):
        raise RuntimeError("kernel changed its host signature")

    rep = sigcheck(broken, op="broken", meshes=({"x": 2},))
    assert rep.finding_kinds == ["capture_error"]
    assert "kernel changed its host signature" in rep.findings[0].detail


# -- CLI ---------------------------------------------------------------------

@pytest.mark.slow
def test_cli_json_contract():
    """``scripts/sigcheck.py --all --gallery`` emits one parseable JSON doc
    and exits 0 with --fail-on-findings (slow tier: it re-runs the whole
    registry in a subprocess; the in-process tests above already gate
    tier 1)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "sigcheck.py"),
         "--all", "--gallery", "--fail-on-findings", "--quiet"],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["n_findings"] == 0
    assert doc["gallery_misses"] == []
    assert doc["ops"] and doc["gallery"]
