"""Broken-kernel gallery: known-bad signal protocols sigcheck must flag.

Each kernel is a deliberately-miswired variant of the repo's push AG
pattern (allgather.py ``_ag_push_kernel``), one per finding class. The
quick tier asserts every gallery entry is flagged WITH ITS EXPECTED KIND —
if a checker change stops catching one of these, that is a checker
regression, not a cleaner gallery.

The bugs are rank-count independent (they reproduce at n=2) so the
gallery stays cheap enough for the dryrun gate.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from .capture import FakeContext
from .checker import (DEADLOCK, Finding, NONDETERMINISM, OVER_SIGNAL,
                      UNDER_SIGNAL, UNORDERED_READ)

f32 = jnp.float32
_M = 8  # rows per rank in every gallery kernel


# -- kernels -----------------------------------------------------------------

def _missing_wait_kernel(axis, mesh_axes, in_ref, out_ref, send_sems,
                         recv_sems):
    """Push AG that reads the gathered buffer WITHOUT waiting for the
    arrivals — the classic torn-read: remote puts are in flight while the
    consumer computes over their destination slots."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m = in_ref.shape[0]
    shd.barrier_all((axis,), mesh_axes=mesh_axes)
    local = pltpu.make_async_copy(in_ref, out_ref.at[pl.ds(me * m, m)],
                                  recv_sems.at[me])
    local.start()
    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(out_ref.at[pl.ds(me * m, m)], in_ref,
                                    send_sems.at[dst], recv_sems.at[me],
                                    pid))
    local.wait()
    # BUG: no wait_recv on any peer slot before consuming the buffer
    out_ref[pl.ds(me * m, m)] = out_ref[pl.ds(0, m)] + 1.0
    shd.quiet(*rdmas)


def _dropped_signal_kernel(axis, mesh_axes, in_ref, out_ref, flag):
    """Arrival-counting barrier that forgets the self-arrival: every rank
    contributes n-1 signals but each waits for n — the count can never be
    reached (static starvation)."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    for p in range(1, n):
        pid = shd.pe_at(mesh_axes, axis, lax.rem(me + p, n))
        shd.signal_op(flag, 1, pid)
    # BUG: waits for n arrivals, only n-1 are ever sent
    shd.signal_wait_until(flag, n)
    out_ref[...] = in_ref[...]


def _seg_dropped_signal_kernel(axis, mesh_axes, in_ref, out_ref, flag):
    """Microbatch-segmented announcement protocol (the ISSUE 16 overlap
    wire: one counted signal per (peer, segment), consumer gates on the
    aggregate per-segment count) whose producer FORGETS the last
    microbatch's segment signal — the waits budget 2 segments per peer but
    only segment 0 is ever announced, so the per-segment gate starves
    (static under-signal)."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    segments = 2
    for p in range(1, n):
        pid = shd.pe_at(mesh_axes, axis, lax.rem(me + p, n))
        # BUG: announces segment 0 only — segment 1 (the second
        # microbatch) is never signalled to any peer
        for s in range(segments - 1):
            shd.signal_op(flag, 1, pid)
    shd.signal_wait_until(flag, segments * (n - 1))
    out_ref[...] = in_ref[...]


def _lend_dropped_last_signal_kernel(axis, mesh_axes, in_ref, out_ref,
                                     flag):
    """The lend_pages wire (ISSUE 17: lender announces one counted signal
    per page, borrower gates on the total page count) whose lender
    FORGETS the LAST page's announcement — the classic off-by-one on the
    counted protocol: pages-1 signals arrive against a wait budget of
    pages, so the borrower's delivery gate starves (static
    under-signal). The pages themselves may well have landed; the
    ANNOUNCEMENT protocol is what the checker accounts."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    pages = 3
    lender, borrower = 0, 1
    bpid = shd.pe_at(mesh_axes, axis, borrower)

    @pl.when(me == lender)
    def _send():
        # BUG: announces pages-1 of the `pages` puts — the final page's
        # counted signal is dropped on the floor
        for _ in range(pages - 1):
            shd.signal_op(flag, 1, bpid)

    @pl.when(me == borrower)
    def _recv():
        shd.signal_wait_until(flag, pages)

    out_ref[...] = in_ref[...]


def _fold_dropped_slice_signal_kernel(axis, mesh_axes, in_ref, out_ref,
                                      flag):
    """The flash_decode_dist fold wire (ISSUE 19: every rank announces its
    page-partial slab to each peer with one counted ``signal_op``; each
    consumer's fold gates on ONE count per remote slab it folds, in
    canonical rank order) where RANK 0 forgets to announce its slab:
    every peer budgets n-1 announcement counts but only n-2 ever arrive,
    so the fold's slice gate starves waiting on rank 0's partial (static
    under-signal). The slab bytes may well have landed — the announcement
    protocol is what the checker accounts."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)

    @pl.when(me != 0)
    def _():
        # BUG: rank 0 skips this announce loop entirely — its partial
        # slab is never signalled to any consumer
        for p in range(1, n):
            pid = shd.pe_at(mesh_axes, axis, lax.rem(me + p, n))
            shd.signal_op(flag, 1, pid)

    # one count consumed per remote slab, in canonical fold order
    for _ in range(n - 1):
        shd.signal_wait_until(flag, 1)
    out_ref[...] = in_ref[...]


def _over_signal_kernel(axis, mesh_axes, in_ref, out_ref, flag):
    """Arrival counter whose producers double-signal: the wait consumes n-1
    but 2(n-1) arrive — the residue poisons the next call on this scratch
    (the PR-6 ledger bug class)."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    for p in range(1, n):
        pid = shd.pe_at(mesh_axes, axis, lax.rem(me + p, n))
        # BUG: inc=2 against a wait budget of 1 per peer
        shd.signal_op(flag, 2, pid)
    shd.signal_wait_until(flag, n - 1)
    out_ref[...] = in_ref[...]


def _swapped_sem_kernel(axis, mesh_axes, in_ref, out_ref, send_sems,
                        recv_sems):
    """Two puts to the right neighbor tracked by two DMA semaphores — but
    the consumer waits them in swapped order, so the first read is covered
    by the WRONG semaphore (the byte counts balance; only delivery
    attribution exposes it)."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m = in_ref.shape[0]
    half = m // 2
    right = shd.pe_at(mesh_axes, axis, lax.rem(me + 1, n))
    shd.barrier_all((axis,), mesh_axes=mesh_axes)
    lo, hi = pl.ds(0, half), pl.ds(half, half)
    r0 = shd.putmem_nbi(out_ref.at[lo], in_ref.at[lo],
                        send_sems.at[0], recv_sems.at[0], right)
    r1 = shd.putmem_nbi(out_ref.at[hi], in_ref.at[hi],
                        send_sems.at[1], recv_sems.at[1], right)
    # BUG: sem 1 covers the HIGH half, yet it gates the low-half read
    shd.wait_recv(out_ref.at[lo], recv_sems.at[1])
    out_ref[lo] = out_ref[lo] + 1.0
    shd.wait_recv(out_ref.at[hi], recv_sems.at[0])
    out_ref[hi] = out_ref[hi] + 1.0
    shd.quiet(r0, r1)


def _wait_cycle_kernel(axis, mesh_axes, in_ref, out_ref, flag):
    """Signal-after-wait with no rank ever signalling first: every rank
    waits for its left neighbor's token before sending its own — a
    wait-before-signal cycle with sufficient total supply (each sem IS
    eventually signalled once... behind the wait)."""
    from ..shmem import device as shd
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    right = shd.pe_at(mesh_axes, axis, lax.rem(me + 1, n))
    # BUG: everyone waits before signalling — nobody moves
    shd.signal_wait_until(flag, 1)
    shd.signal_op(flag, 1, right)
    out_ref[...] = in_ref[...]


# -- host plumbing -----------------------------------------------------------

def _dma_call(ctx: FakeContext, kernel, name: str):
    from ..ops.common import collective_id_for
    from ..utils import default_interpret
    axis = ctx.axis_names[0]
    mesh_axes = ctx.axis_names
    n = ctx.axis_size(axis)
    x = jnp.zeros((n * _M, 128), f32)

    def f(shard):
        return pl.pallas_call(
            functools.partial(kernel, axis, mesh_axes),
            out_shape=jax.ShapeDtypeStruct((n * _M, 128), f32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((n,)),
                            pltpu.SemaphoreType.DMA((n,))],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"gallery_{name}")),
            interpret=default_interpret(),
            name=name,
        )(shard)

    ctx.shard_map(f, in_specs=P(axis), out_specs=None)(x)


def _flag_call(ctx: FakeContext, kernel, name: str):
    from ..ops.common import collective_id_for
    from ..utils import default_interpret
    axis = ctx.axis_names[0]
    mesh_axes = ctx.axis_names
    n = ctx.axis_size(axis)
    x = jnp.zeros((n * _M, 128), f32)

    def f(shard):
        return pl.pallas_call(
            functools.partial(kernel, axis, mesh_axes),
            out_shape=jax.ShapeDtypeStruct((_M, 128), f32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"gallery_{name}")),
            interpret=default_interpret(),
            name=name,
        )(shard)

    ctx.shard_map(f, in_specs=P(axis), out_specs=P(axis))(x)


def _lint_psum_hot_loop() -> List[Finding]:
    """Decode-style hot loop with a ``psum`` inside the scan body — the
    rank-count-dependent reduction the serving trace contract bans. Traced
    under a 2-rank axis env (a size-1 mesh would constant-fold the psum away
    before the lint could see it)."""
    from .lint import lint_determinism

    def step(x):
        def body(carry, _):
            return lax.psum(carry, "tp"), ()
        out, _ = lax.scan(body, x, None, length=4)
        return out

    return lint_determinism(step, jax.ShapeDtypeStruct((8, 128), f32),
                            op="gallery.psum_hot_loop",
                            axis_env=(("tp", 2),))


# -- the gallery -------------------------------------------------------------

@dataclasses.dataclass
class GalleryEntry:
    name: str
    expected: str                      # finding kind that MUST be reported
    run: Optional[Callable[[FakeContext], None]] = None
    lint: Optional[Callable[[], List[Finding]]] = None
    meshes: Sequence[Dict[str, int]] = ({"x": 2},)


_ENTRIES = [
    GalleryEntry("missing_wait", UNORDERED_READ,
                 run=lambda ctx: _dma_call(ctx, _missing_wait_kernel,
                                           "missing_wait")),
    GalleryEntry("dropped_signal", UNDER_SIGNAL,
                 run=lambda ctx: _flag_call(ctx, _dropped_signal_kernel,
                                            "dropped_signal")),
    GalleryEntry("seg_dropped_signal", UNDER_SIGNAL,
                 run=lambda ctx: _flag_call(ctx, _seg_dropped_signal_kernel,
                                            "seg_dropped_signal")),
    GalleryEntry("lend_dropped_last_signal", UNDER_SIGNAL,
                 run=lambda ctx: _flag_call(
                     ctx, _lend_dropped_last_signal_kernel,
                     "lend_dropped_last_signal")),
    GalleryEntry("fold_dropped_slice_signal", UNDER_SIGNAL,
                 run=lambda ctx: _flag_call(
                     ctx, _fold_dropped_slice_signal_kernel,
                     "fold_dropped_slice_signal")),
    GalleryEntry("over_signal", OVER_SIGNAL,
                 run=lambda ctx: _flag_call(ctx, _over_signal_kernel,
                                            "over_signal")),
    GalleryEntry("swapped_sem", UNORDERED_READ,
                 run=lambda ctx: _dma_call(ctx, _swapped_sem_kernel,
                                           "swapped_sem")),
    GalleryEntry("wait_cycle", DEADLOCK,
                 run=lambda ctx: _flag_call(ctx, _wait_cycle_kernel,
                                            "wait_cycle"),
                 meshes=({"x": 2}, {"x": 3})),
    GalleryEntry("psum_hot_loop", NONDETERMINISM, lint=_lint_psum_hot_loop),
]

GALLERY: Dict[str, GalleryEntry] = {e.name: e for e in _ENTRIES}
