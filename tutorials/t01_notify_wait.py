"""Tutorial 01 — notify/wait ping-pong over one-sided puts.

The tpushmem primitive set (shmem/device.py): ``my_pe``/``pe_at`` for PE
identity, ``putmem_nbi`` for a one-sided put whose receive DMA semaphore IS
the delivery notify, ``wait_recv`` to consume it, ``barrier_all`` for entry
safety. Analog of reference tutorials/01 (producer sets data + signal,
consumer spins on the flag then reads — docs/primitives.md:22-56); on TPU
the flag is the hardware DMA semaphore, so delivery and notification are
one event.

Run:  python -m tutorials.t01_notify_wait [--sim 4] [--case correctness]
"""

from tutorials.common import register_case, tutorial_main, world_context


@register_case("correctness")
def correctness():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.common import collective_id_for
    from triton_dist_tpu.shmem import device as shd
    from triton_dist_tpu.utils import default_interpret

    ctx = world_context()
    n = ctx.num_ranks
    axis = "x"

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        """Each PE sends its block to its right neighbor (a ring 'ping');
        the neighbor's wait_recv is the 'notify' consumption."""
        me = shd.my_pe(axis)
        shd.barrier_all((axis,), mesh_axes=ctx.axis_names)
        right = shd.pe_at(ctx.axis_names, axis, lax.rem(me + 1, n))
        rdma = shd.putmem_nbi(out_ref, in_ref, send_sem, recv_sem, right)
        shd.wait_recv(out_ref, recv_sem)   # left neighbor's put landed
        shd.quiet(rdma)

    def f(shard):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(shard.shape, shard.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("tut01")),
            interpret=default_interpret(),
        )(shard)

    # block i carries the value i; after the ring ping, device i holds the
    # block of its left neighbor
    x = jnp.arange(n, dtype=jnp.float32)[:, None, None] * jnp.ones((1, 8, 128))
    xs = ctx.shard(x, P(axis))
    y = jax.jit(ctx.shard_map(f, in_specs=P(axis), out_specs=P(axis)))(xs)
    got = np.asarray(y)[:, 0, 0]
    want = np.roll(np.arange(n, dtype=np.float32), 1)
    np.testing.assert_array_equal(got, want)
    print(f"ring ping over {n} PEs: each device received its left "
          f"neighbor's block {got.tolist()}")


if __name__ == "__main__":
    tutorial_main(__doc__)
