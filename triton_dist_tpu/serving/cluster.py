"""Cluster serving (ISSUE 12 rungs 2+3): replicas + a deterministic
router.

The reference's L7 seam — "user code" above the overlap library — is
where serving becomes a FLEET problem: N independent engine replicas
behind a router, each replica its own failure domain (ISSUE 7) with its
own crash-consistency journal (ISSUE 9). This module supplies the two
host-side abstractions:

- :class:`EngineReplica` wraps ANY of the serving engines (colocated,
  disagg, sharded, composed, or the host-only :class:`SimEngine`) with a
  PRIVATE, path-namespaced journal (``journal-r{i}.jsonl`` — N replicas
  sharing one ``ControlJournal`` path would interleave their entries and
  cross-replay each other's requests on restore), load/occupancy/queue-
  depth signals read duck-typed off the engine's intake scheduler and
  pool ledger, and a ``kill()``/``restore()`` pair that drives the full
  ISSUE 9 recovery ladder: reload the journal from disk, rebuild a fresh
  engine, restore from the newest checkpoint (or replay the whole
  journal when none was cut), re-attach the append handle.
- :class:`Cluster` routes by **prefix affinity with a least-loaded
  tie-break**, rendezvous style: every alive replica scores
  ``fnv1a(index, prompt[:prefix_tokens])`` and the highest score wins,
  so a shared prompt prefix lands on the same replica (KV/page locality)
  WITHOUT a routing table — and when a replica dies, only its keys move
  (classic highest-random-weight behaviour). Ties break to the least
  loaded then the lowest index; an optional spill threshold diverts from
  a hot affinity target to the least-loaded replica. Everything is a
  pure function of (alive set, prompt, load) — the router adds no
  nondeterminism, which is what lets cluster traces be verified
  bit-identically against single-replica goldens.

:class:`SimEngine` is the scale vehicle: a host-only engine with the
REAL page ledger, the REAL scheduler (admission tickets, strict-FIFO
head-of-line, growth-driven preemption, queue caps, TTLs) and the real
journal/checkpoint surface, but a closed-form token function instead of
device dispatches — ``sim_token(prompt, i)``, a pure function of the
prompt and the token index, exactly the determinism contract the device
engines pin (tokens are a function of (params, prompt) — here params
degenerate to the hash seed). ``expected_tokens`` is therefore the
single-replica golden in closed form, and ``scripts/cluster_sim.py``
checks hundreds of thousands of routed, preempted, killed-and-restored
requests against it bitwise.
"""

from __future__ import annotations

import os
import time
from collections import deque

import numpy as np

from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.deadline import Deadline
from triton_dist_tpu.serving.engine import (class_label, mark_prefill_start,
                                            record_first_token)
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import KVPagePool, _fnv1a
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.prefix_cache import ReplicaPrefixIndex
from triton_dist_tpu.serving.scheduler import (AdmissionRejected,
                                               ContinuousBatchingScheduler,
                                               Request, RequestState,
                                               SLOPolicy, TtlExpired)
from triton_dist_tpu.shmem import faults

SIM_VOCAB = 32000


def sim_token(prompt: tuple[int, ...], i: int, vocab: int = SIM_VOCAB
              ) -> int:
    """The SimEngine's "model": token ``i`` of a request is a pure
    function of the prompt (first 8 tokens + length) and the index —
    the same shape of determinism contract the device engines pin."""
    return _fnv1a(0x811C9DC5, *prompt[:8], len(prompt), i) % vocab


def expected_tokens(prompt, max_new_tokens: int, vocab: int = SIM_VOCAB
                    ) -> list[int]:
    """Closed-form single-replica golden for a SimEngine request."""
    prompt = tuple(int(t) for t in prompt)
    return [sim_token(prompt, i, vocab) for i in range(max_new_tokens)]


class SimEngine:
    """Host-only serving engine: real control plane (page ledger,
    scheduler, journal, checkpoints, TTL/queue-cap shedding, growth-
    driven preemption), closed-form tokens (``sim_token``) instead of
    device dispatches. One token per ACTIVE slot per step; "prefill" is
    instantaneous at admission (the first token appears the admitting
    step, exactly like a one-chunk prompt). Exposes the same duck-typed
    surface ``serving/checkpoint.py`` restores through, so an
    :class:`EngineReplica` can kill/restore it like the device engines.
    """

    def __init__(self, num_slots: int = 4, page_size: int = 16,
                 num_pages: int = 64, pages_per_seq: int = 8,
                 metrics: ServingMetrics | None = None,
                 eos_id: int | None = None, vocab: int = SIM_VOCAB,
                 journal: ControlJournal | None = None,
                 checkpoint_every: int | None = None,
                 queue_cap: int | None = None,
                 ttl_steps: int | None = None,
                 fault_plan: "faults.FaultPlan | None" = None,
                 slo: SLOPolicy | None = None):
        assert checkpoint_every is None or journal is not None
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.num_slots = num_slots
        self.eos_id = eos_id
        self.vocab = vocab
        self.metrics = metrics or ServingMetrics()
        self.alloc = KVPagePool(num_pages + 1, page_size, reserved=1)
        self.slo = slo
        self.sched = ContinuousBatchingScheduler(num_slots,
                                                 queue_cap=queue_cap,
                                                 policy=slo)
        self.journal = journal
        self.checkpoint_every = checkpoint_every
        self.ttl_steps = ttl_steps
        self._fault_plan = fault_plan
        self._journal_muted = False
        self._replaying = False
        self._incarnation = 0
        self._last_ckpt_step = -1
        self._finished: list[Request] = []
        self._failed: list[Request] = []
        self._rejected: list[Request] = []
        self._next_rid = 0
        self._steps = 0

    # -- intake (device engines' contract verbatim) ------------------------
    def _ttl_for(self, req: Request) -> int | None:
        """Class TTL override (ISSUE 14) beats the engine-wide knob."""
        spec = self.sched.class_spec(req)
        if spec is not None and spec.ttl_steps is not None:
            return spec.ttl_steps
        return self.ttl_steps

    def submit(self, prompt, max_new_tokens: int, rid: int | None = None,
               tenant: str | None = None, cls: str | None = None) -> int:
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        assert prompt and max_new_tokens >= 1
        total = len(prompt) + max_new_tokens - 1
        need = -(-total // self.page_size)
        assert need <= self.pages_per_seq, (
            f"request needs {need} pages > pages_per_seq "
            f"{self.pages_per_seq}")
        assert need <= self.alloc.num_pages - self.alloc.reserved
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      eos_token=self.eos_id, submit_step=self._steps,
                      submit_time=time.perf_counter())
        self.sched.stamp(req, tenant=tenant, cls=cls)
        self.metrics.inc("requests_submitted")
        self.metrics.inc_class("requests_submitted", class_label(req))
        if self.sched.at_capacity_for(req.cls) and not self._replaying:
            cap = self.sched.queue_cap if self.sched.at_capacity else \
                self.sched.policy.spec(req.cls).queue_cap
            req.state = RequestState.REJECTED
            req.failure = AdmissionRejected(
                f"admission queue full for class {req.cls!r} (cap {cap}) "
                f"— request {rid} rejected")
            self._rejected.append(req)
            self.metrics.inc("rejections")
            self.metrics.inc_class("rejections", class_label(req))
            self._jlog("reject", rid=rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)
            return rid
        ttl = self._ttl_for(req)
        if ttl is not None:
            req.deadline = Deadline(ttl, req.submit_step)
        self.sched.submit(req)
        self._jlog("submit", rid=rid, prompt=list(prompt),
                   max_new_tokens=max_new_tokens,
                   tenant=req.tenant, cls=req.cls)
        return rid

    # -- one step ----------------------------------------------------------
    @property
    def idle(self) -> bool:
        return self.sched.idle

    def step(self) -> bool:
        self.sched.tick(self._steps)
        self._expire_queued()
        progressed = self._step_impl()
        self.metrics.counters["quota_throttled"] = self.sched.quota_throttled
        if progressed:
            self._maybe_checkpoint()
        return progressed

    def _can_hold(self, req: Request) -> bool:
        need = -(-len(req.prompt) // self.page_size)
        need -= len(self.alloc.pages_of(req.rid))
        return self.alloc.free_pages >= max(need, 0)

    def _step_impl(self) -> bool:
        if self.sched.idle:
            return False
        # admissions: instant "prefill" — first token the admitting step
        while True:
            adm = self.sched.admissible(self._can_hold)
            if adm is None:
                break
            slot, req = adm
            need = -(-len(req.prompt) // self.page_size)
            have = len(self.alloc.pages_of(req.rid))
            if need > have:
                got = self.alloc.alloc(req.rid, need - have)
                assert got is not None
            self.sched.activate(slot, req)
            self._jlog("admit", rid=req.rid, slot=slot)
            req.state = RequestState.PREFILLING
            mark_prefill_start(req, self.metrics, self._steps)
            self.metrics.inc("prefills")
            self.metrics.inc("prefill_chunks")
            req.prefill_cursor = len(req.prompt)
            req.state = RequestState.ACTIVE
            req.first_token = sim_token(req.prompt, 0, self.vocab)
            req.generated.append(req.first_token)
            record_first_token(req, self.metrics, self._steps)
            self.metrics.inc("tokens_generated")
            if req.done:
                self._finish(slot)
        # growth + decode: one token per ACTIVE slot, paged growth with
        # the real eviction ladder when the pool runs dry. Token i's KV
        # lands at position len(prompt)+i and the LAST token's KV is
        # never written (the request finishes on emission) — so the max
        # footprint is len(prompt)+max_new_tokens-1, the submit() bound.
        for slot in range(self.num_slots):
            req = self.sched.slots[slot]
            if req is None or req.state is not RequestState.ACTIVE:
                continue
            kv_len = len(req.prompt) + len(req.generated)
            ok = self.alloc.ensure(req.rid, kv_len)
            while not ok:
                victim = self.sched.pick_victim(exclude_slot=slot)
                if victim is None:
                    break   # nobody to evict — this slot waits a step
                self._preempt(victim)
                ok = self.alloc.ensure(req.rid, kv_len)
            if not ok:
                continue
            req.generated.append(
                sim_token(req.prompt, len(req.generated), self.vocab))
            self.metrics.inc("tokens_generated")
            self.metrics.inc("decode_steps")
            if req.done:
                self._finish(slot)
        self.metrics.observe("queue_depth", self.sched.queue_depth)
        self.metrics.observe("pool_occupancy", self.alloc.occupancy())
        self._steps += 1
        return True

    def _finish(self, slot: int) -> None:
        req = self.sched.finish(slot)
        self.alloc.free_seq(req.rid)
        req.finish_step = self._steps
        self._finished.append(req)
        self.metrics.inc("requests_finished")
        self.metrics.inc_class("requests_finished", class_label(req))
        self._jlog("finish", rid=req.rid, tokens=list(req.generated),
                   submit_step=req.submit_step,
                   first_token_step=req.first_token_step,
                   preemptions=req.preemptions)

    def _preempt(self, slot: int) -> None:
        req = self.sched.slots[slot]
        self.alloc.free_seq(req.rid)
        req.prefill_cursor = 0
        req.first_token = None
        self.sched.evict(slot)
        self.metrics.inc("preemptions")
        self._jlog("preempt", rid=req.rid, slot=slot)

    def _expire_queued(self) -> None:
        for req in self.sched.expire(self._steps):
            ttl = self._ttl_for(req)
            req.failure = TtlExpired(
                f"request {req.rid} (class {req.cls!r}) queued past its "
                f"TTL ({ttl} steps from step {req.submit_step}) "
                "without admission")
            self._rejected.append(req)
            self.metrics.inc("expirations")
            self.metrics.inc_class("expirations", class_label(req))
            self._jlog("expire", rid=req.rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)

    def run(self, max_steps: int | None = None, arrivals=None,
            recover=None) -> dict[int, list[int]]:
        if recover:
            assert self.journal is not None
            ck = recover if isinstance(recover, ckpt_mod.Checkpoint) \
                else ckpt_mod.latest(self.journal)
            ckpt_mod.restore(self, ck, self.journal)
        pending = deque(arrivals or [])
        i = 0
        while max_steps is None or i < max_steps:
            while pending and pending[0][0] <= i:
                item = pending.popleft()
                self.submit(item[1], item[2],
                            tenant=item[3] if len(item) > 3 else None,
                            cls=item[4] if len(item) > 4 else None)
            if not self.step() and not pending:
                break
            i += 1
            plan = self._fault_plan if self._fault_plan is not None \
                else faults.active_plan()
            if plan is not None and plan.crash(self._steps,
                                               self._incarnation):
                self.metrics.inc("faults_injected")
                raise faults.InjectedCrash(
                    f"injected crash at step {self._steps} "
                    f"(incarnation {self._incarnation})")
        return {req.rid: list(req.generated) for req in self._finished}

    # -- crash consistency (checkpoint.py duck-typed surface) --------------
    def control_digest(self) -> int:
        # cheap by design: folded counters, not the full ledgers — at
        # cluster_sim scale (100k+ requests) an O(pages+queue) digest per
        # journal entry dominates the run. The checkpoint audit still
        # hashes the REAL pool ledger (pool_digest below).
        return _fnv1a(0x811C9DC5, self._steps, self._next_rid,
                      self.alloc.used_pages, self.sched.queue_depth,
                      self.sched._admit_ticket,
                      self.metrics.counters["requests_finished"])

    def _jlog(self, kind: str, **payload) -> None:
        if self.journal is None or self._journal_muted:
            return
        self.journal.append(kind, self._steps, self.control_digest(),
                            **payload)

    def _maybe_checkpoint(self) -> None:
        if (self.journal is None or not self.checkpoint_every
                or self._steps == 0
                or self._steps % self.checkpoint_every
                or self._steps == self._last_ckpt_step):
            return
        self.checkpoint()

    def checkpoint(self) -> "ckpt_mod.Checkpoint":
        assert self.journal is not None
        ck = ckpt_mod.capture(self)
        self.journal.record_checkpoint(ck.step, ck.digest, ck.state,
                                       ck.journal_seq)
        self._last_ckpt_step = self._steps
        self.metrics.inc("checkpoints")
        return ck

    def _capture_state(self) -> dict:
        live: list[Request] = []
        seen: set[int] = set()
        for _, req in sorted(((r.admitted_seq, r)
                              for _, r in self.sched.active),
                             key=lambda t: t[0]):
            seen.add(req.rid)
            live.append(req)
        for req in self.sched.queue:
            if req.rid not in seen:
                live.append(req)
        return {
            "engine": "sim",
            "step": self._steps,
            "next_rid": self._next_rid,
            "admit_ticket": self.sched._admit_ticket,
            "pool": self.alloc.snapshot(),
            "pool_digest": self.alloc.digest(),
            "live": [ckpt_mod.snapshot_request(r) for r in live],
            "finished": [ckpt_mod.snapshot_finished(r)
                         for r in self._finished],
            "rejected": [{"rid": r.rid, "kind": "expire"
                          if isinstance(r.failure, TtlExpired) else "reject",
                          "reason": str(r.failure), "tenant": r.tenant,
                          "cls": r.cls} for r in self._rejected],
            "policy": self.sched.policy_state(),
            "counters": dict(self.metrics.counters),
        }

    def _restore_state(self, state: dict | None) -> None:
        self.alloc = KVPagePool(self.alloc.num_pages, self.page_size,
                                reserved=1)
        self.sched = ContinuousBatchingScheduler(
            self.sched.num_slots, queue_cap=self.sched.queue_cap,
            policy=self.sched.policy)
        self._finished = []
        self._failed = []
        self._rejected = []
        if state is None:
            return
        ckpt_mod.audit_pool_snapshot(state["pool"], state["pool_digest"],
                                     self.alloc.num_pages, self.page_size, 1)
        self._steps = state["step"]
        self._next_rid = state["next_rid"]
        self.sched._admit_ticket = state["admit_ticket"]
        for snap in state["live"]:
            req = ckpt_mod.rebuild_request(snap)
            req.submit_time = time.perf_counter()
            ttl = self._ttl_for(req)
            if ttl is not None:
                req.deadline = Deadline(ttl, req.submit_step)
            self.sched.submit(req)
        # WFQ/bucket books restore AFTER the requeues: submit()'s idle-
        # class vfloor snap ran against zeroed counters above, and the
        # checkpoint values now overwrite them (order-dependent)
        self.sched.restore_policy_state(state.get("policy"))
        for f in state["finished"]:
            self._restore_finished(f["rid"], f["tokens"], meta=f)
        for f in state["rejected"]:
            self._restore_terminal(f["rid"], f["kind"], f["reason"])

    def _restore_finished(self, rid: int, tokens: list[int],
                          meta: dict | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            prompt = tuple((meta or {}).get("prompt", (0,)))
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=len(tokens), eos_token=self.eos_id)
        req.state = RequestState.FINISHED
        req.generated = list(tokens)
        for k in ("submit_step", "first_token_step", "preemptions"):
            if meta is not None and k in meta:
                setattr(req, k, meta[k])
        self._finished.append(req)

    def _restore_terminal(self, rid: int, kind: str, reason: str,
                          error_type: str | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            req = Request(rid=rid, prompt=(0,), max_new_tokens=1,
                          eos_token=self.eos_id)
        req.state = RequestState.REJECTED
        req.failure = (TtlExpired(reason) if kind == "expire"
                       else AdmissionRejected(reason))
        self._rejected.append(req)

    def _pop_queued(self, rid: int) -> Request | None:
        for r in self.sched.queue:
            if r.rid == rid:
                self.sched.queue.remove(r)
                return r
        return None

    @property
    def failed(self) -> list[Request]:
        return list(self._failed) + list(self._rejected)


class EngineReplica:
    """One engine + one PRIVATE journal + one failure domain.

    ``factory(journal)`` builds the engine; the replica derives its own
    journal path (``journal-r{index}.jsonl`` under ``journal_dir``) so N
    replicas in one directory never interleave entries — the namespacing
    the two-replica restart test pins (no cross-replica replay bleed).
    ``journal_dir=None`` keeps the journal in memory (kill/restore then
    replays the retained object instead of re-reading disk).
    """

    def __init__(self, index: int, factory, journal_dir: str | None = None,
                 artifact=None):
        self.index = index
        self._factory = factory
        self.artifact = artifact
        self.journal_path = (os.path.join(journal_dir,
                                          f"journal-r{index}.jsonl")
                             if journal_dir is not None else None)
        self.journal = ControlJournal(path=self.journal_path)
        self.engine = self._build(self.journal)
        self.alive = True
        self.failovers = 0

    def _build(self, journal):
        """AOT artifact (ISSUE 15): thread the artifact through BOTH the
        cold build and every restore — a restored replica must reach its
        first token with zero fresh traces, exactly like a cold one."""
        if self.artifact is not None:
            return self._factory(journal, artifact=self.artifact)
        return self._factory(journal)

    # load signals, duck-typed off the engine's intake scheduler and the
    # pool the decode work actually occupies
    @property
    def _sched(self):
        return getattr(self.engine, "sched_p", None) or self.engine.sched

    @property
    def _alloc(self):
        return getattr(self.engine, "alloc_d", None) or self.engine.alloc

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def occupancy(self) -> float:
        return self._alloc.occupancy()

    @property
    def load(self) -> int:
        """Routing load: queued + seated requests on the intake side."""
        s = self._sched
        return s.queue_depth + sum(r is not None for r in s.slots)

    @property
    def idle(self) -> bool:
        e = self.engine
        v = getattr(e, "idle", None)
        return bool(v) if v is not None else e.sched.idle

    def submit(self, prompt, max_new_tokens: int,
               tenant: str | None = None, cls: str | None = None) -> int:
        assert self.alive, f"replica {self.index} is dead"
        return self.engine.submit(prompt, max_new_tokens,
                                  tenant=tenant, cls=cls)

    def step(self) -> bool:
        assert self.alive, f"replica {self.index} is dead"
        return self.engine.step()

    def kill(self) -> None:
        """Fail the replica: close the journal's append handle (the
        on-disk jsonl is the surviving truth) and drop the engine."""
        assert self.alive, f"replica {self.index} is already dead"
        self.journal.close()
        self.engine = None
        self.alive = False
        self.failovers += 1

    def restore(self) -> dict:
        """The full ISSUE 9 ladder: reload the journal (from disk when
        path-backed), rebuild a fresh engine through the factory, restore
        from the newest checkpoint — or replay the ENTIRE journal when
        none was cut — then re-attach the append handle so post-restore
        events keep journaling to the same file."""
        assert not self.alive, f"replica {self.index} is alive"
        if self.journal_path is not None:
            j = ControlJournal.load(self.journal_path)
            # .load() returns an in-memory journal: re-attach the file so
            # the restored replica keeps appending where it left off
            j.path = self.journal_path
            j._fh = open(self.journal_path, "a", encoding="utf-8")
        else:
            j = self.journal
        self.journal = j
        self.engine = self._build(j)
        stats = ckpt_mod.restore(self.engine, ckpt_mod.latest(j), j)
        self.alive = True
        return stats


class Cluster:
    """Deterministic router over N replicas (module docstring): cache-
    aware radix-hit affinity first (ISSUE 13), rendezvous hashing as the
    fallback, least-loaded tie-break, optional spill threshold,
    kill/restore through each replica's private journal."""

    def __init__(self, factory, replicas: int = 4,
                 journal_dir: str | None = None, prefix_tokens: int = 8,
                 spill_threshold: int | None = None, artifact=None):
        assert replicas >= 1
        self.replicas = [EngineReplica(i, factory, journal_dir,
                                       artifact=artifact)
                         for i in range(replicas)]
        self.prefix_tokens = prefix_tokens
        self.spill_threshold = spill_threshold
        # cache-aware routing (ISSUE 13): token runs of routed prompts
        # map to the replica that first served them, so a shared-prefix
        # prompt follows its KV. Entries are never dropped — a dead
        # replica's keys fall back to rendezvous below and the affinity
        # returns the moment the replica is restored.
        self.prefix_index = ReplicaPrefixIndex(prefix_tokens)
        self.metrics = ServingMetrics()
        self._placement: dict[int, tuple[int, int]] = {}  # gid -> (ri, rid)
        self._rindex: dict[tuple[int, int], int] = {}     # (ri, rid) -> gid
        self._requests: dict[int, tuple[tuple[int, ...], int]] = {}
        self._results: dict[int, list[int]] = {}
        self._failed: set[int] = set()
        self._next_gid = 0

    def route(self, prompt) -> EngineReplica:
        """Longest radix-index hit wins (the deepest run's replica most
        likely holds the prefix KV); rendezvous hashing with least-loaded
        tie-break handles misses and dead affinity targets. Pure function
        of (index state, alive set, prompt, load) — still deterministic."""
        prompt = tuple(int(t) for t in prompt)
        alive = [r for r in self.replicas if r.alive]
        assert alive, "no alive replicas"
        _, owner = self.prefix_index.match(prompt)
        if owner is not None and self.replicas[owner].alive:
            pick = self.replicas[owner]
            self.metrics.inc("router_radix_hits")
        else:
            pick = max(alive, key=lambda r: (
                _fnv1a(0x811C9DC5, r.index, *prompt[:self.prefix_tokens]),
                -r.load, -r.index))
            self.metrics.inc("router_radix_misses")
        if (self.spill_threshold is not None
                and pick.load > self.spill_threshold):
            pick = min(alive, key=lambda r: (r.load, r.index))
        return pick

    def submit(self, prompt, max_new_tokens: int,
               tenant: str | None = None, cls: str | None = None) -> int:
        rep = self.route(prompt)
        # first-writer-wins: runs this prompt ADDS stick to the replica
        # that actually received it, existing runs keep their owner
        self.prefix_index.insert(tuple(int(t) for t in prompt), rep.index)
        rid = rep.submit(prompt, max_new_tokens, tenant=tenant, cls=cls)
        gid = self._next_gid
        self._next_gid += 1
        self._placement[gid] = (rep.index, rid)
        self._rindex[(rep.index, rid)] = gid
        self._requests[gid] = (tuple(int(t) for t in prompt),
                               max_new_tokens)
        self.metrics.inc("requests_submitted")
        return gid

    def step(self) -> bool:
        progressed = False
        for rep in self.replicas:
            if rep.alive:
                progressed |= rep.step()
        self._harvest()
        return progressed

    def _harvest(self) -> None:
        for rep in self.replicas:
            if not rep.alive:
                continue
            fin = rep.engine._finished
            if fin:
                for req in fin:
                    gid = self._rindex.get((rep.index, req.rid))
                    if gid is None:
                        continue
                    if gid not in self._results:
                        self.metrics.inc("requests_finished")
                        if (req.first_token_time is not None
                                and req.submit_time is not None):
                            self.metrics.observe(
                                "ttft_s",
                                req.first_token_time - req.submit_time)
                    self._results[gid] = list(req.generated)
                rep.engine._finished = []
            for req in rep.engine.failed:
                gid = self._rindex.get((rep.index, req.rid))
                if gid is not None and gid not in self._failed:
                    self._failed.add(gid)
                    self.metrics.inc("failed_requests")

    def kill(self, index: int) -> None:
        self.replicas[index].kill()
        self.metrics.inc("faults_injected")

    def restore(self, index: int) -> dict:
        stats = self.replicas[index].restore()
        self.metrics.inc("restores")
        self._harvest()   # replayed finishes reappear — re-record them
        return stats

    def drain(self, max_steps: int = 1_000_000) -> dict[int, list[int]]:
        for _ in range(max_steps):
            if not self.step():
                break
        return self.results()

    def results(self) -> dict[int, list[int]]:
        return dict(self._results)

    @property
    def failed_gids(self) -> set[int]:
        return set(self._failed)


__all__ = ["Cluster", "EngineReplica", "SimEngine", "expected_tokens",
           "sim_token", "SIM_VOCAB"]
