"""Continuous-batching scheduler: FIFO admission into fixed batch slots,
prefill/decode interleaving, preemption-by-eviction when the KV pool runs
dry.

TPU-shaped by construction: the engine's decode step is ONE compiled
kernel over ``num_slots`` batch rows, so the scheduler never changes
shapes — it only decides which request occupies which slot and which
slots are active this step (inactive rows are masked by parking them on
the engine's scratch page). Policy lives here; mechanics (page
allocation, prefill handoff, the jitted step) live in ``engine.py``.

Policies (all deterministic — bit-identical replay is a test invariant):

- **admission**: strict FIFO. A request is admitted when a slot is free
  AND the pool can hold its whole prompt; admission stops at the first
  request that does not fit (no reordering — small requests cannot
  starve a big head-of-line request).
- **preemption**: when decode growth finds the pool dry, evict the
  YOUNGEST active request (latest admission wins the victim lottery —
  it has the least sunk prefill+decode work), free its pages, requeue it
  at the FRONT of the queue so it reclaims a slot as soon as pressure
  clears. A preempted request restarts from its prompt: greedy decode is
  deterministic, so the regenerated tokens are identical to the lost
  ones (tests assert bit-equality against uncontended runs).

Multi-tenant SLO policy (ISSUE 14) — all still host-integer-deterministic:

- **classes** (``ClassSpec``): every request carries a class name; the
  policy orders classes by WEIGHTED FAIR QUEUEING over integer
  virtual-service counters (service += prompt + budget tokens at
  admission; the backlogged class with the smallest service/weight goes
  first, compared by cross-multiplication so no floats ever enter the
  control plane). FIFO is preserved WITHIN a class — head-of-line
  blocking is per class, so a big batch request cannot block chat.
- **quotas** (``SLOPolicy.quotas``): per-tenant integer token buckets
  (rate tokens/step, burst cap) refilled by ``tick(now)``. A dry bucket
  skips that head in the WFQ scan (counted in ``quota_throttled``);
  admission debits the full request cost — the level may go negative
  (deficit), which enforces the long-run rate exactly.
- **degradation order**: ``pick_victim`` evicts the youngest WITHIN the
  least-important (highest ``level``) class first, so overload pressure
  lands on batch tiers before chat; with no policy every request is
  level 0 and the pre-ISSUE-14 youngest-first order is bit-identical.

``SLOPolicy=None`` (the default) keeps every decision bit-for-bit
identical to strict FIFO — the policy machinery is pay-for-play.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Mapping

from triton_dist_tpu.serving.deadline import Deadline


class AdmissionRejected(RuntimeError):
    """Typed overload terminal (ISSUE 9): the bounded admission queue was
    at capacity when the request arrived. The request never held a slot or
    a page — rejecting it is free and keeps queue wait bounded, which the
    TTL below turns into a hard latency contract."""


class TtlExpired(AdmissionRejected):
    """Typed overload terminal (ISSUE 9): the request sat in the admission
    queue past its ``Deadline`` without ever being admitted. Only
    never-admitted requests expire — once a request is admitted it is
    carried to completion (possibly through preemptions), so 'every
    admitted request finishes bit-identically' stays an invariant under
    overload."""


@dataclasses.dataclass(frozen=True)
class ClassSpec:
    """One priority class of the multi-tenant policy (ISSUE 14).

    ``weight`` is the WFQ share (integer ≥ 1 — a weight-3 class gets 3×
    the admission bandwidth of a weight-1 class under contention).
    ``level`` is the degradation rank: 0 is the MOST protected tier;
    preemption and shedding hit the highest level first. ``queue_cap`` /
    ``ttl_steps`` override the engine-global bounds for this class
    (None = inherit), so a batch tier can run a tight queue while chat
    keeps a deep one. ``stall_budget`` caps the prefill tokens
    co-scheduled per step WHILE a request of this class is decoding —
    the deadline-aware chunk-sizing control (None = no cap).
    ``chunk_budget`` (ISSUE 19) is the dual knob on the PREFILL side: it
    caps the prompt tokens one of THIS class's own prefills may consume
    per step, so a 64k-token ``long`` prompt drips through admission
    without monopolizing the co-scheduled chunk slot (None = the engine's
    full ``prefill_chunk``). Both are runtime scalars into the one
    compiled chunk program — never a shape."""
    name: str
    weight: int = 1
    level: int = 0
    queue_cap: int | None = None
    ttl_steps: int | None = None
    stall_budget: int | None = None
    chunk_budget: int | None = None

    def __post_init__(self):
        assert self.name, "class name must be non-empty"
        assert self.weight >= 1, f"class {self.name}: weight must be >= 1"
        assert self.level >= 0, f"class {self.name}: level must be >= 0"
        assert self.queue_cap is None or self.queue_cap >= 1
        assert self.ttl_steps is None or self.ttl_steps >= 1
        assert self.stall_budget is None or self.stall_budget >= 1
        assert self.chunk_budget is None or self.chunk_budget >= 1


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """The whole multi-tenant scheduling policy: an ordered tuple of
    classes (declaration order is the deterministic WFQ tie-break; the
    FIRST class is the default for unclassed submissions) plus per-tenant
    token-bucket quotas ``{tenant: (rate_tokens_per_step, burst_cap)}``.
    Frozen — policy is configuration, all mutable state (service
    counters, bucket levels) lives in the scheduler where it is folded
    into the control digest and captured by checkpoints."""
    classes: tuple[ClassSpec, ...]
    quotas: Mapping[str, tuple[int, int]] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        assert self.classes, "policy needs at least one class"
        names = [c.name for c in self.classes]
        assert len(set(names)) == len(names), f"duplicate class in {names}"
        for tenant, (rate, burst) in dict(self.quotas).items():
            assert rate >= 1 and burst >= 1, (
                f"tenant {tenant!r}: quota (rate={rate}, burst={burst}) "
                "must be positive integers")

    @property
    def default(self) -> str:
        return self.classes[0].name

    def spec(self, name: str) -> ClassSpec:
        for c in self.classes:
            if c.name == name:
                return c
        raise KeyError(f"unknown class {name!r} — policy has "
                       f"{[c.name for c in self.classes]}")

    def index(self, name: str) -> int:
        for i, c in enumerate(self.classes):
            if c.name == name:
                return i
        raise KeyError(f"unknown class {name!r}")

    @classmethod
    def chat_batch(cls, chat_weight: int = 4, batch_weight: int = 1,
                   batch_queue_cap: int | None = None,
                   batch_ttl_steps: int | None = None,
                   chat_stall_budget: int | None = None,
                   quotas: Mapping[str, tuple[int, int]] | None = None,
                   long_weight: int | None = None,
                   long_chunk_budget: int | None = None,
                   long_stall_budget: int | None = None,
                   long_queue_cap: int | None = None,
                   long_ttl_steps: int | None = None) -> "SLOPolicy":
        """The canonical two-tier policy the sims/tests/bench use: a
        protected ``chat`` tier (level 0) and a best-effort ``batch``
        tier (level 1) that absorbs shedding and preemption first.

        Any ``long_*`` kwarg set (ISSUE 19) inserts the long-context
        tier between them — ``chat`` L0, ``long`` L1, ``batch`` L2 — so
        overload pressure still evicts batch before a half-prefilled 64k
        prompt, and chat ITL stays protected from long prefill via the
        tier's ``chunk_budget``/``stall_budget``. With every ``long_*``
        kwarg None the returned policy is the two-class one, bit-for-bit
        (the third class is pay-for-play)."""
        long_kw = (long_weight, long_chunk_budget, long_stall_budget,
                   long_queue_cap, long_ttl_steps)
        if all(v is None for v in long_kw):
            return cls(classes=(
                ClassSpec("chat", weight=chat_weight, level=0,
                          stall_budget=chat_stall_budget),
                ClassSpec("batch", weight=batch_weight, level=1,
                          queue_cap=batch_queue_cap,
                          ttl_steps=batch_ttl_steps),
            ), quotas=quotas or {})
        return cls(classes=(
            ClassSpec("chat", weight=chat_weight, level=0,
                      stall_budget=chat_stall_budget),
            ClassSpec("long", weight=long_weight or 1, level=1,
                      chunk_budget=long_chunk_budget,
                      stall_budget=long_stall_budget,
                      queue_cap=long_queue_cap,
                      ttl_steps=long_ttl_steps),
            ClassSpec("batch", weight=batch_weight, level=2,
                      queue_cap=batch_queue_cap,
                      ttl_steps=batch_ttl_steps),
        ), quotas=quotas or {})


def _str_fnv(s: str) -> int:
    """32-bit FNV-1a over a string's UTF-8 bytes — folds class/tenant
    NAMES into the integer-only control digest."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"          # holds a slot + pages, chunk cursor
    # disaggregated handoff (ISSUE 6): prefill is DONE (first token known,
    # prefill-side pages freed) but the request sits on the decode worker
    # waiting for the signals covering its migrated pages to fire —
    # signal-gated admission flips it to ACTIVE, never the host clock
    MIGRATING = "migrating"
    ACTIVE = "active"
    FINISHED = "finished"
    # per-request failure domain (ISSUE 7): the recovery ladder (deadline
    # -> bounded retry -> local re-prefill degradation) ran dry for THIS
    # request. Its pages are freed, ``failure`` carries the typed reason
    # (with the ledger dump), and the engine keeps serving everyone else —
    # a failed request never takes the engine down with it.
    FAILED = "failed"
    # overload terminal (ISSUE 9): rejected at submit (bounded admission
    # queue at capacity) or expired in the queue past its TTL deadline —
    # the request never held a slot or a page. ``failure`` carries the
    # typed AdmissionRejected/TtlExpired reason. Appended AFTER the
    # pre-existing states so their digest indices are unchanged.
    REJECTED = "rejected"


@dataclasses.dataclass
class Request:
    """One serving request plus its runtime bookkeeping."""
    rid: int
    prompt: tuple[int, ...]            # token ids
    max_new_tokens: int
    eos_token: int | None = None       # finish early when generated
    state: RequestState = RequestState.QUEUED
    generated: list[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    admitted_seq: int = -1             # admission ticket (victim ordering)
    submit_step: int = -1              # engine step counters for metrics
    first_token_step: int = -1
    finish_step: int = -1
    submit_time: float | None = None   # wall clocks for TTFT
    first_token_time: float | None = None
    # chunked-prefill state (engine's PREFILLING state machine): prompt
    # tokens whose KV is already in pages. Survives mid-prefill eviction —
    # the request requeues AT ITS CURSOR (with its filled pages) and
    # resumes there, not at the prompt start. The TTFT split clocks ride
    # along: queue time = submit → first admission, prefill time = first
    # admission → first token.
    prefill_cursor: int = 0
    prefill_start_step: int = -1
    prefill_start_time: float | None = None
    # disaggregated handoff (ISSUE 6): the first token rides the HOST
    # control plane from the prefill worker (it was argmaxed on the
    # prefill device by the final chunk); everything bulky — the KV pages
    # — moves device-to-device through the migration kernel instead.
    # None until the final prefill chunk lands; reset on decode-side
    # preemption (full re-prefill recomputes it bit-identically).
    first_token: int | None = None
    # recovery ladder bookkeeping (ISSUE 7): how many times this request's
    # migration was re-sent after a signal deadline expired, how many
    # times it fell back to decode-local re-prefill, and — terminal —
    # the typed exception that FAILED it (None while alive). The per-
    # request twins of the engine-level retries/degradations counters.
    retries: int = 0
    degradations: int = 0
    failure: Exception | None = None
    # bounded-queue TTL (ISSUE 9): armed by the engine at submit when
    # ``ttl_steps`` is configured; ``expire()`` sweeps never-admitted
    # queued requests whose deadline has passed. None = no TTL.
    deadline: Deadline | None = None
    # prefix cache (ISSUE 13): prompt tokens served by adopting cached
    # pages at first admission (0 = cold). Drives the cached-vs-cold
    # TTFT split; re-admissions after preemption keep the original value
    # (the clock, like the hit, belongs to the first admission).
    cache_hit_tokens: int = 0
    # multi-tenant SLO policy (ISSUE 14): the submitting tenant, the
    # priority class, and the class's degradation level stamped at
    # submit (``shed_level`` is denormalized from the ClassSpec so
    # victim ordering never needs a policy lookup). Defaults make an
    # unclassed request indistinguishable from pre-ISSUE-14 behavior.
    tenant: str = "default"
    cls: str = "default"
    shed_level: int = 0
    # speculative decoding (ISSUE 20): draft positions this request's
    # verify rows consumed and how many of them committed — per-request
    # observability only (NOT folded into the control digest, NOT
    # checkpointed: the token trace is bit-identical spec-on/off, so
    # acceptance bookkeeping must never perturb recovery or replay).
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def cost(self) -> int:
        """WFQ service / quota debit unit: the tokens this request may
        consume end to end (prompt KV + generation budget)."""
        return len(self.prompt) + self.max_new_tokens

    @property
    def kv_len(self) -> int:
        """Tokens holding KV right now: prompt + all but the newest
        generated token (the newest one's KV is written by the step that
        consumes it)."""
        return len(self.prompt) + max(len(self.generated) - 1, 0)

    @property
    def done(self) -> bool:
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token is not None and bool(self.generated)
                and self.generated[-1] == self.eos_token)

    @property
    def remaining(self) -> int:
        """Token budget left (0 once done — EOS or max_new_tokens)."""
        return 0 if self.done else self.max_new_tokens - len(self.generated)


class ContinuousBatchingScheduler:
    """Slot + queue state machine. The engine calls, in step order:
    ``admissible()`` → prefill each admitted request → ``activate()``,
    then ``pick_victim()`` whenever growth fails, then ``finish()`` as
    slots complete."""

    def __init__(self, num_slots: int, queue_cap: int | None = None,
                 policy: SLOPolicy | None = None):
        assert num_slots >= 1
        self.num_slots = num_slots
        self.queue_cap = queue_cap
        self.policy = policy
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * num_slots
        self._admit_ticket = 0
        # WFQ state (ISSUE 14) — integers only, folded into digest():
        # per-class virtual service, a monotone global virtual-time floor
        # (num/den rational — newly-backlogged classes snap UP to it so an
        # idle class cannot bank service and monopolize later), per-tenant
        # token buckets [level, last_refill_step], and the cumulative
        # quota-skip count the engine mirrors into metrics.
        self._service: dict[str, int] = \
            {c.name: 0 for c in policy.classes} if policy else {}
        self._vfloor = (0, 1)
        self._bucket: dict[str, list[int]] = {
            t: [burst, 0] for t, (_, burst) in
            (dict(policy.quotas).items() if policy else ())}
        self.quota_throttled = 0

    # -- multi-tenant policy (ISSUE 14) -----------------------------------
    def stamp(self, req: Request, tenant: str | None = None,
              cls: str | None = None) -> Request:
        """Stamp class/tenant onto a fresh request (engine submit path):
        validates the class against the policy, fills the default class,
        and denormalizes the degradation level. No-op classification
        without a policy (everything stays the level-0 default)."""
        if tenant is not None:
            req.tenant = tenant
        if self.policy is None:
            if cls is not None:
                req.cls = cls
            return req
        req.cls = cls if cls is not None else self.policy.default
        if req.cls == "default" and not any(
                c.name == "default" for c in self.policy.classes):
            # v1-journal backfill value replayed into a policied engine:
            # "default" means "the policy's default class"
            req.cls = self.policy.default
        req.shed_level = self.policy.spec(req.cls).level
        return req

    def class_spec(self, req: Request) -> ClassSpec | None:
        return None if self.policy is None else self.policy.spec(req.cls)

    def tick(self, now: int) -> None:
        """Refill every tenant's token bucket up to step ``now`` (engine
        calls once per step, before admission). Integer refill: rate
        tokens per elapsed step, clamped at burst. Deterministic —
        iteration order is the policy's quota declaration order."""
        if self.policy is None:
            return
        for tenant, (rate, burst) in dict(self.policy.quotas).items():
            b = self._bucket[tenant]
            if now > b[1]:
                b[0] = min(burst, b[0] + rate * (now - b[1]))
                b[1] = now

    def _quota_ok(self, req: Request) -> bool:
        b = self._bucket.get(req.tenant)
        return b is None or b[0] > 0

    def _wfq_order(self) -> list[str]:
        """Backlogged classes ordered by virtual time (service/weight,
        ascending) — compared by cross-multiplication so the control
        plane stays integer-only; ties break on class declaration order.
        """
        heads = []
        for r in self.queue:
            if r.cls not in (c for c, _, _ in heads):
                w = self.policy.spec(r.cls).weight
                heads.append((r.cls, self._service[r.cls], w))
        out = []
        while heads:
            best = 0
            for i in range(1, len(heads)):
                _, s_b, w_b = heads[best]
                _, s_i, w_i = heads[i]
                if s_i * w_b < s_b * w_i or (
                        s_i * w_b == s_b * w_i
                        and self.policy.index(heads[i][0])
                        < self.policy.index(heads[best][0])):
                    best = i
            out.append(heads.pop(best)[0])
        return out

    def _class_head(self, cls: str) -> Request:
        for r in self.queue:
            if r.cls == cls:
                return r
        raise AssertionError(f"no queued request of class {cls!r}")

    # -- queue ------------------------------------------------------------
    def submit(self, req: Request, front: bool = False) -> None:
        if self.policy is not None and req.cls not in self._service:
            # lenient for restored pre-policy requests: unknown classes
            # ride the default class's books but keep their stamp
            self._service.setdefault(req.cls, 0)
        if (self.policy is not None
                and not any(q.cls == req.cls for q in self.queue)):
            # newly-backlogged class: snap its virtual time UP to the
            # global floor (max — never down) so idle time cannot be
            # banked as future burst right-of-way
            num, den = self._vfloor
            w = (self.policy.spec(req.cls).weight
                 if any(c.name == req.cls for c in self.policy.classes)
                 else 1)
            self._service[req.cls] = max(self._service[req.cls],
                                         (num * w) // den)
        (self.queue.appendleft if front else self.queue.append)(req)

    # -- bounded admission (ISSUE 9) --------------------------------------
    @property
    def at_capacity(self) -> bool:
        """True when a NEW submission must be rejected. Preemption requeues
        (``front=True``) are exempt — an admitted request always keeps its
        place in line, only fresh arrivals are shed."""
        return self.queue_cap is not None and len(self.queue) >= self.queue_cap

    def at_capacity_for(self, cls: str | None) -> bool:
        """Per-class bounded admission (ISSUE 14): the class's own
        ``queue_cap`` (when the policy sets one) bounds the count of
        QUEUED requests of that class, composing with the global cap —
        so a batch flood fills the batch budget and is shed there while
        the chat tier keeps admitting."""
        if self.at_capacity:
            return True
        if self.policy is None or cls is None:
            return False
        spec = self.policy.spec(cls)
        if spec.queue_cap is None:
            return False
        return sum(1 for r in self.queue if r.cls == cls) >= spec.queue_cap

    def expire(self, now: int) -> list[Request]:
        """Sweep never-admitted queued requests whose TTL ``Deadline`` has
        passed at step ``now``. Expired requests are removed from the queue
        and flipped to REJECTED; the engine attaches the typed failure and
        counts them. Requests that have ever been admitted
        (``admitted_seq >= 0``, i.e. preemption requeues) never expire —
        their work is carried to completion."""
        expired = [r for r in self.queue
                   if r.admitted_seq < 0 and r.deadline is not None
                   and r.deadline.expired(now)]
        for r in expired:
            self.queue.remove(r)
            r.state = RequestState.REJECTED
        return expired

    def digest(self) -> int:
        """Order-sensitive 32-bit FNV-1a digest of the WHOLE scheduling
        state: queue order (with each request's resume-relevant cursors),
        slot seating, and the admission ticket. The scheduler half of the
        replicated-decision guard (see ``KVPagePool.digest``): sharded
        serving runs one scheduler instance per rank and asserts the
        digests match every step — a forked admission or victim choice is
        caught before its block tables diverge, not after."""
        from triton_dist_tpu.serving.kv_pool import _fnv1a
        h = _fnv1a(0x811C9DC5, self.num_slots, self._admit_ticket,
                   len(self.queue))
        for r in self.queue:
            h = _fnv1a(h, r.rid, r.prefill_cursor, r.preemptions,
                       len(r.generated))
        for r in self.slots:
            if r is None:
                h = _fnv1a(h, 0xFFFFFFFF)
            else:
                h = _fnv1a(h, r.rid, list(RequestState).index(r.state),
                           r.admitted_seq, r.prefill_cursor,
                           len(r.generated))
        # multi-tenant policy fold (ISSUE 14): PER-CLASS queue order (the
        # same rids regrouped by class — a class-reorder changes the
        # digest even when the flat queue order is a permutation), class/
        # tenant stamps, WFQ service counters, the virtual-time floor and
        # every token bucket. Unconditional for the stamps (forked
        # classification must fork the digest even without a policy).
        for r in self.queue:
            h = _fnv1a(h, _str_fnv(r.cls), _str_fnv(r.tenant),
                       r.shed_level)
        if self.policy is not None:
            for cls in sorted(self._service):
                h = _fnv1a(h, _str_fnv(cls), self._service[cls])
                h = _fnv1a(h, len([0 for r in self.queue if r.cls == cls]))
                for r in self.queue:
                    if r.cls == cls:
                        h = _fnv1a(h, r.rid)
            h = _fnv1a(h, *self._vfloor, self.quota_throttled)
            for tenant in sorted(self._bucket):
                lvl, last = self._bucket[tenant]
                h = _fnv1a(h, _str_fnv(tenant), lvl & 0xFFFFFFFF, last)
        return h

    def policy_state(self) -> dict | None:
        """JSON-able snapshot of the mutable policy books (checkpoint
        capture half); None without a policy."""
        if self.policy is None:
            return None
        return {"service": dict(self._service),
                "vfloor": list(self._vfloor),
                "buckets": {t: list(b) for t, b in self._bucket.items()},
                "quota_throttled": self.quota_throttled}

    def restore_policy_state(self, state: dict | None) -> None:
        if state is None or self.policy is None:
            return
        self._service.update({k: int(v)
                              for k, v in state["service"].items()})
        self._vfloor = tuple(int(v) for v in state["vfloor"])
        for t, b in state["buckets"].items():
            if t in self._bucket:
                self._bucket[t] = [int(b[0]), int(b[1])]
        self.quota_throttled = int(state["quota_throttled"])

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active(self) -> list[tuple[int, Request]]:
        return [(i, r) for i, r in enumerate(self.slots) if r is not None]

    @property
    def idle(self) -> bool:
        return not self.queue and all(s is None for s in self.slots)

    def free_slot(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    # -- admission --------------------------------------------------------
    def admissible(self, pool_can_hold) -> tuple[int, Request] | None:
        """Next (slot, request) to admit, or None. ``pool_can_hold(req)``
        is the engine's pages-available check.

        Without a policy: strict FIFO — a head-of-line request that does
        not fit blocks admission (it will fit once finishes/preemptions
        release pages).

        With a policy (ISSUE 14): weighted fair queueing over classes.
        Classes are scanned in ascending virtual-time order and each
        class's own FIFO head is the candidate; a head blocked by pages
        or a dry tenant bucket only blocks ITS class — the scan moves on,
        which is exactly the isolation a flooded batch tier must not
        break. Quota skips are counted in ``quota_throttled``."""
        slot = self.free_slot()
        if slot is None or not self.queue:
            return None
        if self.policy is None:
            req = self.queue[0]
            if not pool_can_hold(req):
                return None
            return slot, req
        for cls in self._wfq_order():
            req = self._class_head(cls)
            if not self._quota_ok(req):
                self.quota_throttled += 1
                continue
            if not pool_can_hold(req):
                continue            # per-class head-of-line blocking only
            return slot, req
        return None

    def activate(self, slot: int, req: Request) -> None:
        assert self.slots[slot] is None and req in self.queue
        if self.queue[0] is req:
            self.queue.popleft()
        else:
            assert self.policy is not None, \
                "mid-queue admission requires a policy"
            self.queue.remove(req)
        req.state = RequestState.ACTIVE
        req.admitted_seq = self._admit_ticket
        self._admit_ticket += 1
        self.slots[slot] = req
        if self.policy is not None:
            # WFQ service charge + virtual-time floor advance + quota
            # debit (deficit allowed — enforces the long-run rate)
            self._service[req.cls] = \
                self._service.get(req.cls, 0) + req.cost
            w = (self.policy.spec(req.cls).weight
                 if any(c.name == req.cls for c in self.policy.classes)
                 else 1)
            s = self._service[req.cls]
            num, den = self._vfloor
            if s * den > num * w:          # s/w > floor: advance it
                self._vfloor = (s, w)
            b = self._bucket.get(req.tenant)
            if b is not None:
                b[0] -= req.cost

    # -- disaggregated handoff (ISSUE 6) ----------------------------------
    def place(self, slot: int, req: Request) -> None:
        """Seat a request arriving from the PEER role's scheduler (the
        decode worker seating a prefilling/migrating request). Unlike
        ``activate`` it does not touch the queue and does not change
        ``req.state`` — the disagg engine drives the PREFILLING →
        MIGRATING → ACTIVE handoff states itself — but it DOES take an
        admission ticket so victim ordering stays uniform across
        colocated and handed-off requests."""
        assert self.slots[slot] is None
        req.admitted_seq = self._admit_ticket
        self._admit_ticket += 1
        self.slots[slot] = req

    def remove(self, slot: int) -> Request:
        """Unseat WITHOUT requeue — the other half of the handoff verbs:
        a completed prefill leaves the prefill scheduler through here (it
        continues on the DECODE worker, not in this queue), and a decode-
        side victim is routed back to the PREFILL role's queue by the
        engine. State/cursor/requeue policy is entirely the caller's
        (contrast ``evict``, which requeues locally)."""
        req = self.slots[slot]
        assert req is not None
        self.slots[slot] = None
        return req

    # -- preemption -------------------------------------------------------
    def pick_victim(self, exclude_slot: int | None = None) -> int | None:
        """Youngest-within-lowest-class victim (ISSUE 14): among seated
        requests the one with the HIGHEST (shed_level, admitted_seq) —
        i.e. the least-protected class first, youngest admission within
        it. Without a policy every request is level 0 and this is the
        pre-ISSUE-14 youngest-first order bit-for-bit. ``exclude_slot``
        protects the grower (evicting self frees its own pages but
        forfeits more progress than evicting the youngest)."""
        best, best_key = None, (-1, -1)
        for i, r in enumerate(self.slots):
            if r is None or i == exclude_slot:
                continue
            key = (r.shed_level, r.admitted_seq)
            if key > best_key:
                best, best_key = i, key
        return best

    def evict(self, slot: int) -> Request:
        """Remove the slot's request and requeue it at the FRONT. A
        decoding request restarts from its prompt (greedy decode is
        deterministic — the regenerated tokens are bit-identical); a
        mid-prefill request keeps ``prefill_cursor`` — the ENGINE decides
        whether the cursor (and the pages behind it) survives or resets
        (engine._preempt: kept when there is an unfilled page tail to
        reclaim, reset to 0 otherwise)."""
        req = self.slots[slot]
        assert req is not None
        self.slots[slot] = None
        req.state = RequestState.QUEUED
        req.preemptions += 1
        req.generated.clear()
        self.submit(req, front=True)
        return req

    # -- completion -------------------------------------------------------
    def finish(self, slot: int) -> Request:
        req = self.slots[slot]
        assert req is not None and req.done
        self.slots[slot] = None
        req.state = RequestState.FINISHED
        return req


__all__ = ["Request", "RequestState", "ContinuousBatchingScheduler",
           "AdmissionRejected", "TtlExpired", "ClassSpec", "SLOPolicy"]
