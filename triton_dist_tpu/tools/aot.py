"""AOT compilation / deployment path (analog of reference tools/compile_aot.py
``@aot_compile_spaces`` + generated C dispatchers + triton_aot_runtime.cc,
SURVEY.md §5.9).

The reference generates C dispatcher code per kernel signature, compiles it
into ``libtriton_distributed_kernel.so``, and loads CUDA cubins at runtime.
On TPU the whole machinery collapses into jax's AOT stack:

- ``aot_compile``        = ``jit(fn).lower(*args).compile()`` — an executable
  bound to this process's devices (no re-trace, no re-compile at call time).
- ``export_serialized``  = ``jax.export`` → portable StableHLO artifact on
  disk (the ``.so``-shipping analog); ``load_serialized`` rehydrates it in a
  fresh process and recompiles for the local topology.
- ``aot_compile_spaces`` = the dispatcher: a decorator precompiling one
  executable per declared signature and dispatching on arg shapes/dtypes at
  call time (cf. compile_aot.py:61-77's signature/grid spaces and the
  generated per-variant C entry points).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Mapping, Sequence

import jax


def aot_compile(fn: Callable, *example_args, **jit_kw):
    """Lower+compile ``fn`` for ``example_args``'s shapes now; returns the
    compiled executable (callable with matching-shaped args)."""
    return jax.jit(fn, **jit_kw).lower(*example_args).compile()


def export_serialized(fn: Callable, *example_args, **jit_kw) -> bytes:
    """Portable serialized artifact (StableHLO) of ``fn`` at these shapes."""
    from jax import export
    exp = export.export(jax.jit(fn, **jit_kw))(*example_args)
    return bytes(exp.serialize())


def load_serialized(data: bytes) -> Callable:
    """Rehydrate an ``export_serialized`` artifact; the returned callable
    compiles for the local topology on first call."""
    from jax import export
    return export.deserialize(data).call


def _sig_of(args: Sequence[Any]) -> tuple:
    return tuple((tuple(a.shape), str(a.dtype))
                 if hasattr(a, "shape") and hasattr(a, "dtype")
                 else ("static", a)
                 for a in args)


def aot_compile_spaces(spaces: Mapping[str, Callable[[], tuple]],
                       **jit_kw):
    """Decorator: precompile ``fn`` for every named signature space and
    dispatch by runtime arg signature.

    ``spaces`` maps variant name → zero-arg factory returning example args
    (factories defer allocation until ``precompile`` or first use). Unknown
    signatures fall back to plain ``jax.jit`` (and are cached thereafter).
    """
    def deco(fn):
        jitted = jax.jit(fn, **jit_kw)
        compiled: dict[tuple, Any] = {}

        def precompile():
            for name, factory in spaces.items():
                args = factory()
                compiled[_sig_of(args)] = aot_compile(fn, *args, **jit_kw)
            return {n: True for n in spaces}

        @functools.wraps(fn)
        def wrapper(*args):
            exe = compiled.get(_sig_of(args))
            return exe(*args) if exe is not None else jitted(*args)

        wrapper.precompile = precompile
        wrapper.compiled = compiled
        return wrapper

    return deco


__all__ = ["aot_compile", "export_serialized", "load_serialized",
           "aot_compile_spaces"]
