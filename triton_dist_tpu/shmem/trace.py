"""Event-recording seam for the device-side shmem primitives.

``analysis.capture`` installs a tracer here while it replays a kernel's
Python body per rank; every ``shmem.device`` primitive first asks for the
active tracer and, when one is installed, appends a symbolic protocol
event instead of emitting a Mosaic op. The indirection lives in its own
tiny module (no jax imports) so ``device.py`` pays one attribute read per
call when tracing is off and ``analysis`` never becomes an import cycle.

This is NOT the runtime fault-injection hook (``shmem.faults``) — faults
perturb the real lowering; the tracer replaces it entirely.
"""

from __future__ import annotations

_TRACER = None


def active_tracer():
    """The installed event tracer, or None (the usual case)."""
    return _TRACER


def set_tracer(tracer) -> None:
    """Install ``tracer`` (or None to clear). The tracer must provide the
    device-primitive hooks ``analysis.capture.RankTracer`` implements:
    putmem_nbi, signal_op, signal_wait_until, wait_recv, signal_read,
    quiet, fence, barrier_all, barrier_pair, producer_noise."""
    global _TRACER
    _TRACER = tracer
