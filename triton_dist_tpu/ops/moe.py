"""MoE tensor-parallel overlap ops (analog of reference
python/triton_dist/kernels/nvidia/allgather_group_gemm.py and
moe_reduce_rs.py).

- ``ag_moe_group_gemm``: AllGather token shards (+ routing ids) across the TP
  group, then grouped expert GEMM against the local N-shard of every expert's
  up-weights — the reference's "AG + GroupGEMM" stage
  (allgather_group_gemm.py:317-770). Gather and compute are Pallas kernels;
  their fusion into a single arrival-driven kernel (per-segment waits like
  ag_gemm) is the planned optimization.
- ``moe_reduce_rs``: grouped expert GEMM on the K-shard, topk-weighted
  per-token reduction, then ReduceScatter of the result — the reference's
  "GroupGEMM + topk-reduce + RS" stage (moe_reduce_rs.py:365-1027).

Routing ids ride the wire as lane-aligned int32 blocks (cf. the splits
transfer in low_latency_all_to_all.py:75-86).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.group_gemm import apply_grouped, grouped_gemm
from triton_dist_tpu.ops.reduce_scatter import reduce_scatter
from triton_dist_tpu.shmem.context import ShmemContext


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def ag_moe_group_gemm(ctx: ShmemContext, tokens: jax.Array, ids: jax.Array,
                      weights: jax.Array, axis: str | None = None,
                      block_m: int = 128) -> jax.Array:
    """tokens [T, H] sharded P(axis); ids [T] int32 expert per row (-1 pad);
    weights [E, H, N] sharded P(None, None, axis) (N column-parallel).
    Returns all ranks' tokens processed by their experts against the local
    weight shard: [T, N_local] per device → global [T, N] sharded
    P(None, axis). Golden: all_gather + dense per-expert matmul."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    T, H = tokens.shape
    assert T % n == 0
    t_local = T // n
    pad = _round_up(t_local, 128) - t_local

    def pack(ids_shard):
        w = jnp.pad(ids_shard, (0, pad), constant_values=-1)
        return w.reshape(-1, 128)

    ids_wire = ctx.shard_map(pack, in_specs=P(axis), out_specs=P(axis))(ids)
    g_tokens = all_gather(ctx, tokens, axis=axis, method="ring")
    g_ids_wire = all_gather(ctx, ids_wire, axis=axis, method="ring")

    def compute(gt, gi, w_shard):
        gids = gi.reshape(n, -1)[:, :t_local].reshape(-1)
        E = w_shard.shape[0]
        return apply_grouped(
            gt, gids, E,
            lambda x, be: grouped_gemm(x, w_shard, be, block_m=block_m),
            block_m=block_m)

    sm = ctx.shard_map(compute,
                       in_specs=(P(None, None), P(None, None), P(None, None, axis)),
                       out_specs=P(None, axis))
    return sm(g_tokens, g_ids_wire, weights)


def moe_reduce_rs(ctx: ShmemContext, tokens: jax.Array, ids: jax.Array,
                  topk_weights: jax.Array, weights: jax.Array,
                  axis: str | None = None, block_m: int = 128) -> jax.Array:
    """Second MoE-TP stage: ``tokens`` [T*topk, K] sharded P(None, axis) on K
    (the up-projection's activations, one row per (token, k) pair);
    ``ids`` [T*topk] global expert of each row; ``topk_weights`` [T, topk];
    ``weights`` [E, K, N] sharded P(None, axis, None). Computes the grouped
    down-GEMM partial on each rank, folds topk rows into per-token rows
    (weighted sum), then ReduceScatters token rows across the group →
    [T, N] sharded P(axis). Golden: dense compute + psum_scatter
    (cf. moe_reduce_rs.py:889-1027)."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    Tk, K = tokens.shape
    T, topk = topk_weights.shape
    assert Tk == T * topk
    E = weights.shape[0]

    def partial(tok_shard, ids_full, w_shard, tw):
        rows = apply_grouped(
            tok_shard, ids_full, E,
            lambda x, be: grouped_gemm(x, w_shard, be, block_m=block_m),
            block_m=block_m).astype(jnp.float32)
        # topk-weighted fold: [T*topk, N] -> [T, N]
        rows = rows.reshape(T, topk, -1) * tw[..., None].astype(jnp.float32)
        return jnp.sum(rows, axis=1).astype(tokens.dtype)

    sm = ctx.shard_map(
        partial,
        in_specs=(P(None, axis), P(None), P(None, axis, None), P(None, None)),
        out_specs=P(axis))
    # each device's partial stacked along dim0 -> reduce_scatter input layout
    partials = sm(tokens, ids, weights, topk_weights)
    return reduce_scatter(ctx, partials, axis=axis)


__all__ = ["ag_moe_group_gemm", "moe_reduce_rs"]
