"""triton_dist_tpu — a TPU-native distributed compute/communication
overlapping framework.

This package provides the capabilities of Triton-distributed (a distributed
compiler + overlapping-kernel library for GPUs, see /root/reference) re-designed
TPU-first on JAX/XLA/Pallas:

- ``shmem``    : the ``tpushmem`` layer — symmetric buffers over a
  ``jax.sharding.Mesh`` plus one-sided remote-DMA/semaphore primitives usable
  inside Pallas kernels (the role NVSHMEM/pynvshmem plays in the reference,
  cf. reference shmem/nvshmem_bind/*).
- ``language`` : the ``dl.*`` device-language surface (rank/num_ranks/wait/
  notify/consume_token, cf. reference python/triton_dist/language.py).
- ``ops``      : the overlapping kernel library (AG-GEMM, GEMM-RS, MoE
  grouped-GEMM, EP All-to-All, distributed Flash-Decode, collectives;
  cf. reference python/triton_dist/kernels/nvidia/*).
- ``layers``   : module layer over the kernels (cf. reference
  python/triton_dist/layers/nvidia/*).
- ``models``   : flagship model families wired to the distributed layers.
- ``parallel`` : mesh/sharding helpers and tp/pp/dp/sp/ep train-step
  composition (what jax gives beyond the reference's scope).
- ``tools``    : distributed autotuner, perf/trace harness, AOT export
  (cf. reference python/triton_dist/autotuner.py, tools/*).
"""

__version__ = "0.1.0"

from triton_dist_tpu.shmem import ShmemContext, initialize_distributed  # noqa: F401
