"""Backend/environment detection.

The same kernel code runs in two modes:
- compiled Mosaic on real TPU chips (bench, production), and
- Pallas TPU *interpret mode* on a virtual CPU device mesh (tests, CI) —
  an improvement over the reference, whose tests require real GPUs
  (reference SURVEY: no single-process cluster simulator).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
from jax.experimental.pallas import tpu as pltpu


@lru_cache(None)
def backend_platform() -> str:
    return jax.devices()[0].platform.lower()


def on_cpu() -> bool:
    return backend_platform() == "cpu"


def on_tpu() -> bool:
    # The axon PJRT plugin reports devices as TPU; be liberal.
    p = backend_platform()
    return ("tpu" in p) or (p == "axon")


def interpret_params(**kw) -> "pltpu.InterpretParams":
    """TPU-interpret-mode params used when running on CPU devices.

    ``dma_execution_mode='on_wait'`` preserves the async-DMA/semaphore
    semantics closely enough to catch missing waits; set
    ``TDT_DETECT_RACES=1`` to enable the interpreter's race detector
    (the reference's analog is sleep-noise fuzzing, allgather.py:72-76).
    """
    if os.environ.get("TDT_DETECT_RACES") == "1":
        kw.setdefault("detect_races", True)
    return pltpu.InterpretParams(**kw)


def default_interpret():
    """What to pass as ``pallas_call(interpret=...)`` on this backend."""
    return interpret_params() if on_cpu() else False
