"""Disaggregated prefill/decode tests (ISSUE 6): the signal-protocol
ledger, the page-migration kernel, and the headline end-to-end property —
a two-role disaggregated trace produces per-request tokens BIT-IDENTICAL
to the colocated chunked engine, including under forced mid-prefill
preemption on the prefill worker; a lost signal times out loudly instead
of admitting a slot over unlanded pages."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.ops import migrate_pages
from triton_dist_tpu.serving import (ChunkSignalLedger, DisaggServingEngine,
                                     MigrationSignalTimeout, PageLedgerError,
                                     PageMigrationChannel, ServingEngine)
from triton_dist_tpu.shmem import FaultPlan
from triton_dist_tpu.serving.disagg import DECODE_ROLE
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.scheduler import RequestState
from triton_dist_tpu.shmem.context import initialize_distributed

pytestmark = pytest.mark.disagg


@pytest.fixture(scope="module")
def role_ctx():
    """One 2-rank role mesh shared by every engine in this module (each
    engine allocates its own symmetric pools inside it)."""
    return initialize_distributed(axis_names=("role",), mesh_shape=(2,))


@pytest.fixture(scope="module")
def tiny_model():
    cfg = dataclasses.replace(LlamaConfig.tiny(n_layers=2),
                              dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _mk_requests(cfg, n, seed=0, mnt_lo=2, mnt_hi=10, plen_lo=3, plen_hi=20):
    rng = np.random.RandomState(seed)
    return [(list(rng.randint(1, cfg.vocab_size,
                              size=int(rng.randint(plen_lo, plen_hi)))),
             int(rng.randint(mnt_lo, mnt_hi)))
            for _ in range(n)]


def _disagg(params, cfg, ctx, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("num_prefill_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 32)
    kw.setdefault("pages_per_seq", 8)
    kw.setdefault("prefill_chunk", 8)
    return DisaggServingEngine(params, cfg, ctx=ctx, **kw)


# ---------------------------------------------------------------------------
# signal-protocol ledger (host mirror of the per-chunk counted signal)
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_ledger_signal_count_matches_pages():
    """A chunk covers its pages exactly when the signal count reaches the
    page count — the kernel signals +n for an n-page chunk, so per-chunk
    signal count == pages landed is the protocol invariant."""
    led = ChunkSignalLedger()
    led.expect(7, 0, [3, 4, 5])
    assert not led.chunk_complete(7, 0)
    assert led.covered(7) == set()             # 0/3 signals: nothing
    led.landed(7, 0, 2)
    assert led.covered(7) == set()             # 2/3: partial covers NOTHING
    assert not led.complete(7)
    led.landed(7, 0, 1)                        # third signal arrives
    assert led.chunk_complete(7, 0)
    assert led.covered(7) == {3, 4, 5}
    assert led.complete(7)
    # a signal for a chunk nobody announced is a protocol bug, loudly
    with pytest.raises(KeyError):
        led.landed(7, 9, 1)
    with pytest.raises(KeyError):
        led.landed(8, 0, 1)


@pytest.mark.quick
def test_ledger_tolerates_out_of_order_chunks():
    """Chunk completion order is NOT delivery order: coverage is the union
    over complete chunks, whatever order their signals landed in."""
    led = ChunkSignalLedger()
    led.expect(1, 0, [2, 3])
    led.expect(1, 1, [4])
    led.expect(1, 2, [5, 6])
    led.landed(1, 2, 2)                        # last chunk completes first
    assert led.covered(1) == {5, 6}
    led.landed(1, 0, 2)                        # then the first
    assert led.covered(1) == {2, 3, 5, 6}
    assert not led.complete(1)                 # chunk 1 still outstanding
    led.landed(1, 1, 1)
    assert led.complete(1)
    assert led.covered(1) == {2, 3, 4, 5, 6}
    # re-expect (preemption re-send) resets that chunk's count only
    led.expect(1, 0, [2, 3])
    assert led.covered(1) == {4, 5, 6}
    assert not led.complete(1)
    led.reset(1)
    assert led.covered(1) == set() and led.expected(1) == set()


@pytest.mark.quick
def test_channel_refuses_scratch_page():
    """Scratch pages are engine-local parking (inactive rows mutate them
    every dispatch) — migrating one plants live garbage in the peer pool.
    The channel refuses before anything is launched or ledgered."""
    def boom(*_a, **_k):
        raise AssertionError("kernel must not launch for a refused chunk")

    ch = PageMigrationChannel(boom, pmax=4, reserved=1,
                              metrics=ServingMetrics())
    with pytest.raises(PageLedgerError, match="scratch"):
        ch.send_chunk(0, 0, [0, 2], [3, 4], None, None)
    with pytest.raises(PageLedgerError, match="scratch"):
        ch.send_chunk(0, 0, [2, 3], [4, 0], None, None)
    assert ch.ledger.expected(0) == set()      # refused chunk never ledgered


# ---------------------------------------------------------------------------
# the migration kernel, in isolation
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_migrate_pages_exact_copy(role_ctx):
    """Producer-side pages land bit-exactly at the consumer-side dst ids
    (every layer), padding beyond n_pages is never dereferenced, producer
    pages are untouched, and both roles report the landed count."""
    ctx = role_ctx
    L, Pg, H, ps, D = 2, 8, 2, 4, 8
    shape = (L, Pg, H, ps, D)
    host_k = np.zeros((2,) + shape, np.float32)
    host_v = np.zeros((2,) + shape, np.float32)
    for p in range(Pg):                        # distinct stamp per page
        host_k[0, :, p] = 100 + p
        host_v[0, :, p] = 200 + p
    pool_k = ctx.shard(jnp.asarray(host_k),
                       jax.sharding.PartitionSpec("role"))
    pool_v = ctx.shard(jnp.asarray(host_v),
                       jax.sharding.PartitionSpec("role"))

    src = jnp.array([3, 5, 1, 7], jnp.int32)   # entry past n is padding
    dst = jnp.array([2, 6, 4, 7], jnp.int32)
    pool_k, pool_v, landed = migrate_pages(
        ctx, pool_k, pool_v, src, dst, jnp.array([3], jnp.int32),
        axis="role", tag=5)
    # landed report rows are (count, echoed generation tag) per role —
    # the tag is what lets the ledger discard stale re-sent deliveries
    assert int(np.asarray(landed)[DECODE_ROLE, 0]) == 3
    assert int(np.asarray(landed)[DECODE_ROLE, 1]) == 5
    hk, hv = np.asarray(pool_k), np.asarray(pool_v)
    for s, d in [(3, 2), (5, 6), (1, 4)]:
        assert (hk[1, :, d] == 100 + s).all()
        assert (hv[1, :, d] == 200 + s).all()
    assert not hk[1, :, 7].any(), "padding entry must not migrate"
    # producer shard untouched outside its scratch page (id 0 is scratch
    # by the migrate_pages contract — the interpret path mirror-writes it)
    for p in range(1, Pg):
        assert (hk[0, :, p] == 100 + p).all()
        assert (hv[0, :, p] == 200 + p).all()


# ---------------------------------------------------------------------------
# end-to-end: disaggregated == colocated, bit for bit
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def disagg_golden(tiny_model):
    """Golden: the COLOCATED chunked engine over the same trace — the
    ISSUE 6 acceptance target ('bit-identical to local chunked
    prefill')."""
    cfg, params = tiny_model
    reqs = _mk_requests(cfg, 6, seed=11, mnt_lo=2, mnt_hi=7)
    eng = ServingEngine(params, cfg, num_slots=2, page_size=8, num_pages=32,
                        pages_per_seq=8, prefill_chunk=8)
    rids = [eng.submit(p, m) for p, m in reqs]
    gold = eng.run(max_steps=2000)
    assert len(gold) == len(reqs)
    return reqs, rids, gold


@pytest.mark.quick
def test_disagg_bit_identical_to_colocated(tiny_model, role_ctx,
                                           disagg_golden):
    """The two-role demo: every request's tokens (first token from the
    prefill worker's fused argmax + the decode worker's stream over
    MIGRATED pages) match the colocated chunked engine bit for bit. Also
    pins the metrics split: the decode worker processed ZERO prompt
    tokens, every request was handed off, and pages actually moved."""
    cfg, params = tiny_model
    reqs, gold_rids, gold = disagg_golden
    eng = _disagg(params, cfg, role_ctx)
    rids = [eng.submit(p, m) for p, m in reqs]
    res = eng.run(max_steps=2000)
    assert sorted(res) == sorted(gold)
    for rid, grid_ in zip(rids, gold_rids):
        assert res[rid] == gold[grid_], f"rid {rid} diverged"
    # role isolation, in token space (host-noise-proof)
    assert eng.metrics_decode.hist["step_prefill_tokens"].max == 0
    assert eng.metrics.counters["handoffs"] == len(reqs)
    assert eng.metrics_decode.counters["handoffs"] == len(reqs)
    need = sum(-(-len(p) // 8) for p, _ in reqs)
    assert eng.metrics.counters["pages_migrated"] == need
    assert eng.metrics.counters["migrate_chunks"] >= len(reqs)
    # every page freed on both sides at the end
    assert eng.alloc_p.used_pages == 0 and eng.alloc_d.used_pages == 0


def test_disagg_bit_identical_under_prefill_preemption(tiny_model, role_ctx,
                                                       disagg_golden):
    """Forced mid-prefill preemption on the PREFILL worker (the ISSUE 6
    acceptance twist): the victim resumes at its chunk cursor with its
    filled pages, never re-sends already-migrated pages, and every
    request still finishes bit-identical to the colocated golden."""
    cfg, params = tiny_model
    reqs, gold_rids, gold = disagg_golden
    eng = _disagg(params, cfg, role_ctx, num_prefill_slots=1)
    rids = [eng.submit(p, m) for p, m in reqs]
    preempted = 0
    for i in range(2000):
        if not eng.step():
            break
        if i % 2 == 0 and preempted < 4:       # hammer early prefills
            if eng.force_preempt_prefill() is not None:
                preempted += 1
    res = {r.rid: list(r.generated) for r in eng._finished}
    assert preempted >= 1, "trace was meant to force prefill preemption"
    assert eng.metrics.counters["preemptions"] >= 1
    assert sorted(res) == sorted(gold)
    for rid, grid_ in zip(rids, gold_rids):
        assert res[rid] == gold[grid_], f"rid {rid} diverged"


# ---------------------------------------------------------------------------
# signal-gated admission: loss, landmine, timeout
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_lost_signal_fails_request_not_engine(tiny_model, role_ctx,
                                              monkeypatch):
    """TDT_SERIAL lost-signal drill, ISSUE-7 contract: every signal for
    ONE request is dropped (scoped FaultPlan) and degradation is off, so
    after the retry rungs run dry THAT request fails with a typed,
    ledger-dumping reason — while the un-faulted neighbor finishes
    normally in the SAME run. The old whole-engine
    MigrationSignalTimeout raise is gone: the engine never dies for a
    transport fault."""
    monkeypatch.setenv("TDT_SERIAL", "1")
    cfg, params = tiny_model
    plan = FaultPlan(seed=3, p_drop=1.0, rids=(0,))
    eng = _disagg(params, cfg, role_ctx, fault_plan=plan,
                  signal_deadline_steps=2, max_retries=1,
                  allow_degradation=False)
    prompt = list(range(1, 13))                # 12 tokens: 2 chunks, 2 pages
    rid = eng.submit(prompt, 4)                # rid 0 — the faulted one
    rid_ok = eng.submit(list(range(20, 29)), 3)
    req = eng.sched_p.queue[0]

    res = eng.run(max_steps=400)               # must NOT raise
    assert rid not in res and rid_ok in res
    assert len(res[rid_ok]) == 3               # the neighbor was untouched
    assert [r.rid for r in eng.failed] == [rid]
    assert req.state is RequestState.FAILED
    assert isinstance(req.failure, MigrationSignalTimeout)
    msg = str(req.failure)
    assert f"request {rid}" in msg
    assert "chunk 0: 0/" in msg                # per-chunk count in the report
    assert "missing" in msg                    # ledger dump rode along
    assert req.generated == []                 # not one token decoded
    assert eng.metrics_decode.counters["failed_requests"] == 1
    assert eng.metrics_decode.counters["retries"] >= 1
    assert eng.metrics.counters["faults_injected"] >= 2
    # failure released every page on both sides
    assert eng.alloc_p.used_pages == 0 and eng.alloc_d.used_pages == 0
    eng.alloc_p.check(); eng.alloc_d.check(eng.channel.ledger)


@pytest.mark.quick
def test_unsent_chunk_landmine(tiny_model, role_ctx, monkeypatch):
    """The landmine (ISSUE 6 acceptance, ISSUE 7 failure domain): a chunk
    that is never SENT at all. The decode-side block table must never
    expose the unlanded pages (the signal gate would raise if it did),
    the retry rung must recognize there is nothing to re-send (the ledger
    has no incomplete chunk), and with degradation off the request fails
    typed, saying a chunk may never have been sent."""
    cfg, params = tiny_model
    eng = _disagg(params, cfg, role_ctx, signal_deadline_steps=4,
                  max_retries=2, allow_degradation=False)
    prompt = list(range(1, 13))
    rid = eng.submit(prompt, 4)
    req = eng.sched_p.queue[0]
    real_send = eng.channel.send_chunk

    def dropping(r, ci, src, dst, pk, pv):
        if r == rid and ci == 1:
            return pk, pv                      # chunk silently not sent
        return real_send(r, ci, src, dst, pk, pv)

    monkeypatch.setattr(eng.channel, "send_chunk", dropping)
    res = eng.run(max_steps=400)               # per-request failure, no raise
    assert res == {}
    assert req.state is RequestState.FAILED
    assert isinstance(req.failure, MigrationSignalTimeout)
    assert "never sent" in str(req.failure)
    # no retries counted: the ledger had no incomplete chunk to re-send,
    # so the ladder skipped straight past the retry rung
    assert eng.metrics_decode.counters["retries"] == 0
    assert eng.metrics_decode.counters["failed_requests"] == 1
    assert eng.alloc_p.used_pages == 0 and eng.alloc_d.used_pages == 0


# ---------------------------------------------------------------------------
# decode stall independent of prompt length
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("peer_plen", [8, 64])
def test_decode_cadence_independent_of_peer_prompt(tiny_model, role_ctx,
                                                   peer_plen):
    """The reason to disaggregate, pinned in STEP space (where CPU wall
    clocks cannot fake it): once a request is decoding, it emits exactly
    one token per engine step even while the prefill worker grinds a peer
    prompt — whether that prompt is 8 or 64 tokens. In the colocated
    engine the chunk compute sits inside the same step; here the decode
    worker's prompt-token count is identically zero."""
    cfg, params = tiny_model
    eng = _disagg(params, cfg, role_ctx, num_slots=2, num_prefill_slots=1,
                  page_size=8, num_pages=32, pages_per_seq=10,
                  prefill_chunk=8)
    target = eng.submit(list(range(1, 6)), 16)
    treq = eng.sched_p.queue[0]
    for _ in range(50):                        # drive until target decodes
        eng.step()
        if treq.state is RequestState.ACTIVE and len(treq.generated) >= 2:
            break
    assert treq.state is RequestState.ACTIVE
    before = len(treq.generated)
    eng.submit(list(range(1, peer_plen + 1)), 2)
    probe = 6                                  # peer is mid-prefill for all 6
    for _ in range(probe):
        eng.step()
    gained = len(treq.generated) - before
    assert gained == probe, (
        f"decode cadence broke: {gained} tokens in {probe} steps while "
        f"peer prompt of {peer_plen} was prefilling")
    assert eng.metrics_decode.hist["step_prefill_tokens"].max == 0
    assert eng.metrics.hist["step_prefill_tokens"].max > 0   # prefill role did
    eng.run(max_steps=500)                     # drain cleanly
    assert target in {r.rid for r in eng._finished}


# ---------------------------------------------------------------------------
# compile guard: bounded program set per role
# ---------------------------------------------------------------------------

def test_disagg_compile_guard(tiny_model, role_ctx):
    """Prefill and decode roles each compile a BOUNDED program set: one
    chunk program, one decode program, one migration program — across 8
    DISTINCT prompt lengths and every chunk size. No per-prompt-length
    recompiles anywhere (the page ids and counts ride in SMEM as runtime
    scalars)."""
    cfg, params = tiny_model
    eng = _disagg(params, cfg, role_ctx, pages_per_seq=10)
    rng = np.random.RandomState(3)
    arrivals = []
    for i, plen in enumerate(range(3, 19, 2)):   # 8 distinct prompt lengths
        prompt = [int(t) for t in rng.randint(1, cfg.vocab_size, size=plen)]
        arrivals.append((i, prompt, int(rng.randint(2, 6))))
    res = eng.run(max_steps=2000, arrivals=arrivals)
    assert len(res) == 8
    stats = eng.compile_stats
    assert stats == {"prefill_chunk_compiles": 1, "decode_compiles": 1,
                     "migrate_compiles": 1}, stats
