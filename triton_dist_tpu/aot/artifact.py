"""Persisted AOT serving artifact: the full compiled-program set of a
declared engine fleet, serialized to one versioned directory so a replica
restart reaches its first token with ZERO fresh jit traces.

Two mechanisms compose (probed on this toolchain, both required):

1. **Serialized programs** — every engine program (prefill chunk, bucketed
   prefills, decode multistep, migrate, and the sharded variants at each
   declared mesh shape) is exported through ``jax.export`` at build time
   with the exact dispatch-time argument signature, recorded by driving a
   tiny probe workload through the real engine. Loading deserializes the
   StableHLO — the Python model code is never re-traced.
2. **The persisted XLA compilation cache** — deserialized programs still
   XLA-compile for the local topology, so the build rehearses the load
   path (``jit(exported.call).lower(...).compile()``) with the artifact's
   own ``xla-cache/`` directory active. A cold process installs that cache
   and the load-path compile becomes a disk hit.

Loading is keyed on (jax version, backend, topology, spec digest); any
mismatch raises a typed :class:`ArtifactMissError` — a stale artifact is a
loud miss, never a silent fresh trace. Program bytes are FNV-1a-digest
audited (the PR 13 snapshot-audit idiom, same as the registry file).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.aot.registry import (TunedConfigRegistry, _fnv1a_bytes)

FORMAT_VERSION = 1
_MANIFEST = "MANIFEST.json"
_REGISTRY = "registry.json"
_PROGRAMS = "programs"
_XLA_CACHE = "xla-cache"


class ArtifactMissError(RuntimeError):
    """The artifact does not match this process (jax version / backend /
    topology / spec digest) or lacks a program the engine needs. Loud and
    typed: the caller decides between fresh-trace fallback and abort."""


class ArtifactIntegrityError(RuntimeError):
    """Persisted program bytes or the manifest fail their digest audit —
    the artifact directory is torn or tampered."""


# -- loaded programs ---------------------------------------------------------

class LoadedProgram:
    """One deserialized AOT program standing in for an engine's ``jax.jit``
    object. Dispatches go through the exported StableHLO via a thin
    ``jit(exported.call)`` wrapper — the SOURCE program (the model code the
    engine would otherwise trace) is never traced in this process, which
    is what ``_cache_size() == 0`` reports to ``compile_stats`` and the
    cold-start guards. The wrapper's own first call XLA-compiles the
    deserialized module; with the artifact's xla-cache installed that is a
    disk hit, not a compile."""

    def __init__(self, name: str, exported):
        self.name = name
        self.exported = exported
        self._fn = jax.jit(exported.call)

    def __call__(self, *args):
        return self._fn(*args)

    def _cache_size(self) -> int:
        # fresh traces of the source program: zero by construction
        return 0


# -- specs -------------------------------------------------------------------

def _canon_digest(obj) -> str:
    return f"{_fnv1a_bytes(json.dumps(obj, sort_keys=True).encode()):08x}"


@dataclasses.dataclass
class ArtifactSpec:
    """Declares what the artifact compiles: one model and a list of engine
    declarations. Each engine entry is a plain dict::

        {"kind": "colocated" | "sharded" | "disagg" | "disagg_sharded",
         "mesh": {"tp": 1, "sp": 2, "ep": 2},     # sharded kinds only
         ...engine ctor kwargs (num_slots, page_size, num_pages,
            pages_per_seq, prefill_chunk, prefill_buckets, ...)}

    ``model`` is ``{"kind": "llama"|"moe", ...config fields}`` (dtype as a
    string). The spec digest keys artifact staleness: change the fleet
    declaration and every consumer sees a typed miss, not a shape error.
    """

    model: dict
    engines: List[dict]
    seed: int = 0

    def to_json(self) -> dict:
        return {"model": self.model, "engines": self.engines,
                "seed": self.seed}

    @classmethod
    def from_json(cls, d: dict) -> "ArtifactSpec":
        return cls(model=d["model"], engines=d["engines"],
                   seed=d.get("seed", 0))

    def digest(self) -> str:
        return _canon_digest(self.to_json())

    # -- model materialization -------------------------------------------
    def model_config(self):
        from triton_dist_tpu.models.llama import LlamaConfig
        m = dict(self.model)
        kind = m.pop("kind")
        if kind == "llama":
            m["dtype"] = jnp.dtype(m.get("dtype", "float32")).type
            return LlamaConfig(**m)
        if kind == "moe":
            from triton_dist_tpu.models.moe import MoEConfig
            base = dict(m.pop("base"))
            base["dtype"] = jnp.dtype(base.get("dtype", "float32")).type
            return MoEConfig(base=LlamaConfig(**base), **m)
        raise ValueError(f"unknown model kind {kind!r}")

    def init_params(self) -> dict:
        cfg = self.model_config()
        key = jax.random.PRNGKey(self.seed)
        if self.model["kind"] == "moe":
            from triton_dist_tpu.models.moe import init_moe_params
            return init_moe_params(key, cfg)
        from triton_dist_tpu.models.llama import init_params
        return init_params(key, cfg)


def engine_artifact_key(kind: str, mesh: Optional[dict] = None) -> str:
    """Canonical program-set key for one engine declaration — the string
    the engines themselves derive at seed time."""
    if kind in ("colocated", "disagg"):
        return kind
    mesh = mesh or {}
    desc = f"{mesh.get('tp', 1)}x{mesh.get('sp', 1)}x{mesh.get('ep', 1)}"
    return f"{kind}:{desc}"


def make_engine(decl: dict, params: dict, cfg, journal=None,
                artifact: "ServingArtifact | None" = None, **overrides):
    """Construct the engine a spec entry declares. Shared by the artifact
    builder, ``tools/compile_aot.py``, the sims' ``--artifact`` restart
    path, and the tests — one decl, one construction rule."""
    decl = dict(decl)
    kind = decl.pop("kind")
    decl.pop("probe", None)
    mesh = decl.pop("mesh", None)
    decl.update(overrides)
    if kind == "colocated":
        from triton_dist_tpu.serving.engine import ServingEngine
        return ServingEngine(params, cfg, journal=journal,
                             artifact=artifact, **decl)
    if kind == "sharded":
        from triton_dist_tpu.serving.sharded import (ShardedServingEngine,
                                                     serving_mesh)
        mesh = mesh or {}
        ctx = serving_mesh(**mesh)
        return ShardedServingEngine(params, cfg, ctx, journal=journal,
                                    artifact=artifact, **decl)
    if kind == "disagg":
        from triton_dist_tpu.serving.disagg import DisaggServingEngine
        return DisaggServingEngine(params, cfg, journal=journal,
                                   artifact=artifact, **decl)
    if kind == "disagg_sharded":
        from triton_dist_tpu.serving.compose import DisaggShardedEngine
        from triton_dist_tpu.serving.sharded import serving_mesh
        mesh = mesh or {}
        ctx = serving_mesh(**mesh)
        return DisaggShardedEngine(params, cfg, ctx, journal=journal,
                                   artifact=artifact, **decl)
    raise ValueError(f"unknown engine kind {kind!r}")


# -- build: signature recording ---------------------------------------------

def _aval_of(x, mesh=None):
    """Dispatch-time aval: shape/dtype plus the committed sharding when one
    exists. Uncommitted args on a multi-device engine are pinned replicated
    (that is how GSPMD places them in the source program too)."""
    sharding = None
    if isinstance(x, jax.Array) and getattr(x, "_committed", False):
        sharding = x.sharding
    if sharding is None and mesh is not None:
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
    if sharding is None:
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype
                                    if not hasattr(x, "dtype") else x.dtype)
    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)


class _Recorder:
    """Wraps one engine jit object during the artifact build: the first
    dispatch records the exact argument avals (committed shardings
    included) that the export and the load-path rehearsal then reuse."""

    def __init__(self, fn, mesh=None):
        self._fn = fn
        self._mesh = mesh
        self.avals: Optional[tuple] = None

    def __call__(self, *args):
        if self.avals is None:
            self.avals = jax.tree_util.tree_map(
                lambda a: _aval_of(a, self._mesh), args)
        return self._fn(*args)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _instrument(engine) -> Dict[str, _Recorder]:
    """Swap every program attribute the engine dispatches through for a
    recorder. Returns program-name → recorder (avals filled once the probe
    workload has exercised the program)."""
    mesh = getattr(getattr(engine, "ctx", None), "mesh", None)
    recs: Dict[str, _Recorder] = {}

    def wrap(obj, attr, name):
        fn = getattr(obj, attr, None)
        if fn is None:
            return
        recs[name] = _Recorder(fn, mesh)
        setattr(obj, attr, recs[name])

    from triton_dist_tpu.serving.compose import DisaggShardedEngine
    from triton_dist_tpu.serving.disagg import DisaggServingEngine
    if isinstance(engine, DisaggShardedEngine):
        wrap(engine.decode, "_step", "decode")
        wrap(engine.decode, "_chunk_step", "chunk")
        wrap(engine, "_xmig", "xmig")
        # the migration channel launch closure captured self._xmig before
        # instrumentation — rebind it through the recorder
        return recs
    if isinstance(engine, DisaggServingEngine):
        wrap(engine, "_dec_step", "decode")
        wrap(engine, "_chunk_step", "chunk")
        wrap(engine, "_migrate", "migrate")
        engine.channel._launch = recs["migrate"]
        return recs

    wrap(engine, "_step", "decode")
    if engine._chunk_step is not None:
        wrap(engine, "_chunk_step", "chunk")

    orig_prefill_fn = engine._prefill_fn

    def rec_prefill(bucket, cache_len):
        key = (bucket, cache_len)
        fn = orig_prefill_fn(bucket, cache_len)
        if not isinstance(fn, _Recorder):
            fn = _Recorder(fn, mesh)
            engine._prefill_jit[key] = fn
            recs[f"prefill:{bucket}x{cache_len}"] = fn
        return fn

    engine._prefill_fn = rec_prefill
    return recs


def _drive(engine, prompts: List[List[int]], max_new: int = 2,
           max_steps: int = 600) -> None:
    """Probe workload: run every prompt to completion so each program the
    engine owns dispatches at least once (chunked prefill, decode, and —
    on the disagg engines — the migration kernel)."""
    for p in prompts:
        engine.submit(p, max_new)
    steps = 0
    while len(engine._finished) < len(prompts):
        engine.step()
        steps += 1
        assert steps < max_steps, (
            "artifact probe workload did not finish: engine stalled "
            f"after {steps} steps ({len(engine._finished)}/{len(prompts)})")


def _probe_prompts(decl: dict) -> List[List[int]]:
    """One prompt per program the declaration implies: chunked engines get
    a single chunk-spanning prompt; bucketed engines get one prompt per
    declared bucket (the bucket list IS the compiled-program set)."""
    if decl.get("probe"):
        return [list(p) for p in decl["probe"]]
    buckets = decl.get("prefill_buckets", "pow2")
    chunk = decl.get("prefill_chunk")
    if chunk is not None:
        return [[(i % 30) + 1 for i in range(chunk + 3)]]
    assert isinstance(buckets, (list, tuple)), (
        "a non-chunked engine declaration must carry an explicit "
        "prefill_buckets list — 'pow2' is open-ended and cannot be "
        "enumerated into a closed compiled-program set")
    return [[(i % 30) + 1 for i in range(b)] for b in buckets]


# -- build -------------------------------------------------------------------

def build_artifact(spec: ArtifactSpec, out_dir: str,
                   params: Optional[dict] = None,
                   registry: Optional[TunedConfigRegistry] = None,
                   log: Callable[[str], None] = lambda s: None) -> str:
    """Compile the spec's full program set and persist it under
    ``out_dir``. Returns ``out_dir``. The build pays every fresh trace so
    no cold start ever does."""
    cfg = spec.model_config()
    if params is None:
        params = spec.init_params()
    os.makedirs(os.path.join(out_dir, _PROGRAMS), exist_ok=True)
    cache_dir = os.path.join(out_dir, _XLA_CACHE)
    os.makedirs(cache_dir, exist_ok=True)

    # the artifact's cache must hold EVERY load-path executable — drop the
    # min-compile-time floor for the build's duration
    old_cache = jax.config.jax_compilation_cache_dir
    old_floor = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _reset_xla_cache()

    from jax import export as jax_export
    programs: Dict[str, Dict[str, dict]] = {}
    try:
        for decl in spec.engines:
            ekey = engine_artifact_key(decl["kind"], decl.get("mesh"))
            log(f"[aot] building {ekey}")
            engine = make_engine(decl, params, cfg)
            recs = _instrument(engine)
            _drive(engine, _probe_prompts(decl))
            programs[ekey] = {}
            for name, rec in sorted(recs.items()):
                assert rec.avals is not None, (
                    f"probe workload never dispatched program {name!r} of "
                    f"{ekey} — widen the probe (see ArtifactSpec docs)")
                exp = jax_export.export(rec._fn)(*rec.avals)
                data = exp.serialize()
                fname = f"{ekey.replace(':', '_')}--{name.replace(':', '_')}.stablehlo"
                with open(os.path.join(out_dir, _PROGRAMS, fname),
                          "wb") as f:
                    f.write(data)
                # rehearse the LOAD path so its XLA compile lands in the
                # artifact cache: deserialize + jit(call) + lower/compile
                # is byte-for-byte what a cold process will do
                g = jax_export.deserialize(data)
                jax.jit(g.call).lower(*rec.avals).compile()
                programs[ekey][name] = {
                    "file": f"{_PROGRAMS}/{fname}",
                    "digest": f"{_fnv1a_bytes(data):08x}",
                    "nr_devices": exp.nr_devices,
                }
                log(f"[aot]   {name}: {len(data)} bytes, "
                    f"{exp.nr_devices} device(s)")
    finally:
        jax.config.update("jax_compilation_cache_dir", old_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          old_floor)
        _reset_xla_cache()

    if registry is not None:
        registry.save(os.path.join(out_dir, _REGISTRY))

    manifest = {
        "format": FORMAT_VERSION,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "spec": spec.to_json(),
        "spec_digest": spec.digest(),
        "programs": programs,
    }
    manifest["digest"] = _canon_digest(
        {k: v for k, v in manifest.items() if k != "digest"})
    tmp = os.path.join(out_dir, _MANIFEST + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(out_dir, _MANIFEST))
    return out_dir


# -- load --------------------------------------------------------------------

def _reset_xla_cache() -> None:
    """Re-initialize jax's persistent-cache singleton: it binds its
    directory at FIRST use and silently ignores later config updates — a
    process that compiled anything before the artifact dir was installed
    would otherwise never read (or write) a single artifact entry."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass      # private API moved — stale-cache-dir is a perf miss only


def _install_xla_cache(artifact_cache: str) -> None:
    """Make the artifact's persisted executables visible to this process:
    copy entries into the active compilation-cache dir when one is
    configured (tests run under a per-suite temp cache), else point the
    process at the artifact's own cache directory."""
    if not os.path.isdir(artifact_cache):
        return
    active = jax.config.jax_compilation_cache_dir
    if active is None or active == "":
        jax.config.update("jax_compilation_cache_dir", artifact_cache)
        _reset_xla_cache()
        return
    if os.path.abspath(active) == os.path.abspath(artifact_cache):
        return
    os.makedirs(active, exist_ok=True)
    for fname in os.listdir(artifact_cache):
        dst = os.path.join(active, fname)
        if not os.path.exists(dst):
            shutil.copy2(os.path.join(artifact_cache, fname), dst)


class ServingArtifact:
    """A loaded artifact directory: validated manifest + lazy per-program
    deserialization. Engines pull their program set out of this handle at
    construction (``artifact=`` kwarg) instead of tracing."""

    def __init__(self, path: str, manifest: dict,
                 registry: Optional[TunedConfigRegistry]):
        self.path = path
        self.manifest = manifest
        self.registry = registry
        self._loaded: Dict[Tuple[str, str], LoadedProgram] = {}

    # -- keyed load -------------------------------------------------------
    @classmethod
    def load(cls, path: str,
             spec: Optional[ArtifactSpec] = None) -> "ServingArtifact":
        mpath = os.path.join(path, _MANIFEST)
        if not os.path.isfile(mpath):
            raise ArtifactMissError(
                f"no artifact manifest at {mpath} — build one with "
                f"tools/compile_aot.py")
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        body = {k: v for k, v in manifest.items() if k != "digest"}
        if _canon_digest(body) != manifest.get("digest"):
            raise ArtifactIntegrityError(
                f"artifact manifest at {mpath} is torn or tampered: "
                f"digest mismatch")
        if manifest.get("format") != FORMAT_VERSION:
            raise ArtifactMissError(
                f"artifact format {manifest.get('format')!r} != "
                f"{FORMAT_VERSION}")
        misses = []
        if manifest["jax"] != jax.__version__:
            misses.append(f"jax {manifest['jax']} != {jax.__version__}")
        if manifest["backend"] != jax.default_backend():
            misses.append(f"backend {manifest['backend']!r} != "
                          f"{jax.default_backend()!r}")
        if manifest["device_count"] > jax.device_count():
            misses.append(f"topology: built for {manifest['device_count']} "
                          f"devices, process has {jax.device_count()}")
        if spec is not None and spec.digest() != manifest["spec_digest"]:
            misses.append(f"spec digest {manifest['spec_digest']} != "
                          f"requested {spec.digest()}")
        if misses:
            raise ArtifactMissError(
                "stale artifact at " + path + ": " + "; ".join(misses))
        registry = None
        rpath = os.path.join(path, _REGISTRY)
        if os.path.isfile(rpath):
            registry = TunedConfigRegistry.load(rpath)
        _install_xla_cache(os.path.join(path, _XLA_CACHE))
        return cls(path, manifest, registry)

    @property
    def spec(self) -> ArtifactSpec:
        return ArtifactSpec.from_json(self.manifest["spec"])

    def engine_keys(self) -> List[str]:
        return sorted(self.manifest["programs"].keys())

    def program_names(self, ekey: str) -> List[str]:
        return sorted(self.manifest["programs"].get(ekey, {}).keys())

    def prefill_keys(self, ekey: str) -> List[Tuple[int, int]]:
        """(bucket, cache_len) pairs the artifact holds bucketed prefill
        programs for under ``ekey``."""
        out = []
        for name in self.program_names(ekey):
            if name.startswith("prefill:"):
                b, c = name.split(":", 1)[1].split("x")
                out.append((int(b), int(c)))
        return sorted(out)

    def program(self, ekey: str, name: str) -> LoadedProgram:
        """Deserialize (once) and return the program; a missing key is a
        typed loud miss, never a silent fresh trace."""
        if (ekey, name) in self._loaded:
            return self._loaded[(ekey, name)]
        entry = self.manifest["programs"].get(ekey, {}).get(name)
        if entry is None:
            have = {k: self.program_names(k) for k in self.engine_keys()}
            raise ArtifactMissError(
                f"artifact at {self.path} holds no program "
                f"{ekey!r}/{name!r}; available: {have}")
        with open(os.path.join(self.path, entry["file"]), "rb") as f:
            data = f.read()
        if f"{_fnv1a_bytes(data):08x}" != entry["digest"]:
            raise ArtifactIntegrityError(
                f"program {ekey}/{name} at {entry['file']} is torn or "
                f"tampered: digest mismatch")
        from jax import export as jax_export
        prog = LoadedProgram(f"{ekey}/{name}", jax_export.deserialize(data))
        self._loaded[(ekey, name)] = prog
        return prog


def load_artifact(path: str,
                  spec: Optional[ArtifactSpec] = None) -> ServingArtifact:
    """Module-level convenience mirroring :meth:`ServingArtifact.load`."""
    return ServingArtifact.load(path, spec=spec)


__all__ = ["ArtifactSpec", "ServingArtifact", "LoadedProgram",
           "ArtifactMissError", "ArtifactIntegrityError", "build_artifact",
           "load_artifact", "make_engine", "engine_artifact_key"]
