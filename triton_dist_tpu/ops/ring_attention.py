"""Ring attention — context parallelism for long-sequence *training*.

The reference scales decode-time sequence length only (KV-sharded
flash-decode, SURVEY §5.7); its lse-weighted combine is exactly the ring
attention merge step, and this module is the generalization the survey
calls for: blockwise attention over a sequence-sharded KV cache where KV
blocks travel a ring while the MXU computes on the block already present.

One kernel per device (same transport idiom as ``ops/reduce_scatter``):

1. Entry barrier (comm slots + semaphores are reused across calls).
2. n ring steps. Step s computes blockwise attention of the local Q shard
   against the KV block that originated at rank ``me - s``; before
   computing, the block is forwarded right as a non-blocking DMA, so the
   transfer of step s+1's data rides behind step s's compute (the
   copy-engine-producer role). 2 relay slots with ack credits (regular
   semaphore) provide the same flow control as the RS ring.
3. Online softmax across steps: per-row running (max, denom, acc) state
   lives in HBM ping-pong buffers packed as [acc ‖ m ‖ l] lanes, updated
   by an ``emit_pipeline`` over (head, q-tile, kv-tile) blocks per step —
   the blockwise flash pattern, with the ring as the outermost loop.
4. Causal masking by *global* positions, derived per tile from the
   sequence layout (``_layout_offs``/``_tile_off``). Two layouts:
   contiguous (rank r holds rows [r*S, (r+1)*S); fully-masked steps
   src > me skip the whole pipeline with one state-copy DMA) and zigzag
   (rank r holds chunks (r, 2n-1-r) — balanced causal work every step;
   fully-masked TILES skip their MXU work via ``pl.when``).

Returns (out, lse): lse = m + log(l) per q row, the residual the backward
pass and the decode combine both need (cf. reference
flash_decode.py:481-566).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for, norm_axis
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret

_NEG = -1e30
_LOG2E = 1.4426950408889634   # log2(e): folded into the q prescale so the
_LN2 = 0.6931471805599453     # inner softmax runs in base 2; ln2 converts
                              # the lse residual back to the ln domain


def _layout_offs(zigzag, r, c, S, n):
    """(lo, hi) global offsets of rank ``r``'s local block: contiguous —
    one run at r*S; zigzag — chunk pair (r, 2n-1-r) of c rows each."""
    return (r * c, (2 * n - 1 - r) * c) if zigzag else (r * S, 0)


def _tile_off(zigzag, c, lo, hi, start):
    """Global position of a tile starting at LOCAL row ``start``. Contiguous
    layout: one offset. Zigzag layout: the local block is [chunk lo ‖ chunk
    hi] of c rows each (tiles never straddle the seam — block sizes divide
    c), so the offset depends on which half the tile sits in."""
    if not zigzag:
        return lo + start
    return jnp.where(start < c, lo + start, hi + (start - c))


def _diag_sub(bq: int, bk: int, causal: bool,
              default: int = 256) -> int | None:
    """Row-band height for the diagonal-tile split, or None when the split
    does not apply (non-causal; non-square tiles, whose diagonal crossing
    is not a single aligned tile; tiles too small to sub-divide). 256 rows
    = 2×128 MXU passes per band — small enough that the skipped upper
    triangle dominates the extra per-band state updates, large enough
    that each dot still fills the MXU (round-5 on-chip A/B at
    (1024, 1024): see docs/benchmarks.md). Override with TDT_DIAG_SUB
    (0 disables the split)."""
    import os
    env = os.environ.get("TDT_DIAG_SUB")
    if env is not None:
        v = int(env)
        if v <= 0:
            return None
        default = v
    if not causal or bq != bk:
        return None
    sub = min(default, bq)
    if bq % sub or sub % 128:
        return None
    if bq // sub < 2:
        return None
    return sub


def _causal_tile_dispatch(q_t, kv_t, bq, bk, compute):
    """Route one causal tile to the cheapest body: skip fully-masked
    tiles, run interior tiles mask-free, pay the iota+where mask only on
    diagonal tiles (the kernel is VPU-bound, so interior tiles must not
    generate mask work — docs/benchmarks.md roofline note)."""
    has_work = kv_t <= q_t + (bq - 1)
    interior = kv_t + (bk - 1) <= q_t
    pl.when(jnp.logical_and(has_work, interior))(lambda: compute(False))
    pl.when(jnp.logical_and(has_work, jnp.logical_not(interior)))(
        lambda: compute(True))


def _band_keep(sub: int):
    """Local (sub, sub) lower-triangular keep mask for an
    exactly-diagonal band (position-independent: q_t == kv_t)."""
    return (lax.broadcasted_iota(jnp.int32, (sub, sub), 1)
            <= lax.broadcasted_iota(jnp.int32, (sub, sub), 0))


def _dispatch_with_diag(causal, diag_sub, q_t, kv_t, bq, bk, compute,
                        compute_diag):
    """Four-way causal tile routing shared by the forward and both
    backward pipelines: skip / interior mask-free / EXACT diagonal via
    the row-band split (``compute_diag``) / other straddles (tiles not
    aligned to the diagonal, e.g. unaligned layout offsets) whole-tile
    masked. Falls back to the three-way dispatch when the split does not
    apply (non-causal, non-square tiles — see ``_diag_sub``)."""
    if not causal:
        compute(False)
        return
    if diag_sub is None:
        _causal_tile_dispatch(q_t, kv_t, bq, bk, compute)
        return
    has_work = kv_t <= q_t + (bq - 1)
    interior = kv_t + (bk - 1) <= q_t
    straddle = jnp.logical_and(has_work, jnp.logical_not(interior))
    on_diag = q_t == kv_t
    pl.when(jnp.logical_and(has_work, interior))(lambda: compute(False))
    pl.when(jnp.logical_and(straddle, on_diag))(compute_diag)
    pl.when(jnp.logical_and(straddle, jnp.logical_not(on_diag)))(
        lambda: compute(True))


def _attn_step_pipeline(step_init, step_final, causal, zigzag, D, bq, bk,
                        offs, BH, Hq, Hkv, S, scr,
                        q_ref, k_src, v_src, st_in, st_out,
                        o_ref, lse_ref, out_dtype, flat=None):
    """One ring step's blockwise attention: grid (head, q-tile, kv-tile),
    kv innermost. The running [acc ‖ m ‖ l] state accumulates in the
    ``scr`` VMEM scratch (never HBM) across the kv sweep; only at the last
    kv tile does it leave VMEM — to the ``st_out`` carry buffer on
    intermediate ring steps, or fused straight to (o, lse) on the FINAL
    step (``step_final``), which deletes both the final state spill and
    the separate epilogue pipeline's re-read (~3 MB HBM per q-tile at
    bq=1024 — the gap to the canonical single-chip flash kernel).
    ``step_init`` (python-static) selects fresh-state initialization
    (s == 0; no carry-in fetch) vs carry-in from the previous step's
    buffer. Fully-masked causal tiles skip all compute (``pl.when``) —
    with the zigzag layout this makes per-step causal work identical on
    every rank.

    ``q_ref`` arrives PRESCALED by sm_scale·log2(e) (one XLA pass in the
    wrapper), so the inner loop neither multiplies s_ij by the softmax
    scale (saves one VPU op per score element) nor pays natural-exp
    pricing: the running softmax runs in base 2 (``exp2``, the
    transcendental unit's native base); the lse residual converts back to
    the ln domain on the way out.

    ``flat`` (optional ``(n_tiles, qi_ref, kvi_ref)`` with the maps in
    SMEM) replaces the rectangular (q-tile, kv-tile) grid with a
    1-D walk over VALID tiles only — the single-step causal-contiguous
    case (n=1 prefill) otherwise burns a grid step (block bookkeeping,
    dispatch branches) on every fully-masked tile: ~37% of the grid at
    square tiles. The same scalar-prefetch pattern as the grouped GEMM's
    block-expert map. Only meaningful when this step is both first and
    last (the maps encode the whole triangle)."""
    g = Hq // Hkv
    W = D + 256  # acc lanes ‖ m lanes ‖ l lanes
    q_lo, q_hi, kv_lo, kv_hi = offs
    c = S // 2 if zigzag else S
    nkv = S // bk
    if flat is not None:
        assert step_init and step_final, "flat walk encodes one whole step"
        n_tiles, qi_ref, kvi_ref = flat

    def kv_head(bh):
        return (bh // Hq) * Hkv + (bh % Hq) // g

    def body(q_blk, k_blk, v_blk, *st):
        if step_final:
            in_blk = None if step_init else st[0]
            o_blk, lse_blk = st[-2:]
        elif step_init:
            in_blk, (out_blk,) = None, st
        else:
            in_blk, out_blk = st
        if flat is not None:
            t = pl.program_id(1)
            qi, kvi = qi_ref[t], kvi_ref[t]
            # last valid kv tile of this q row — same formula that built
            # the tile list, so the flush point cannot drift from it
            last_of_q = kvi == ((qi + 1) * bq - 1) // bk
        else:
            kvi = pl.program_id(2)
            qi = pl.program_id(1)
            last_of_q = kvi == nkv - 1

        @pl.when(kvi == 0)
        def _():
            if step_init:
                scr[:, :D] = jnp.zeros((bq, D), jnp.float32)
                scr[:, D:D + 128] = jnp.full((bq, 128), _NEG, jnp.float32)
                scr[:, D + 128:] = jnp.zeros((bq, 128), jnp.float32)
            else:
                scr[...] = in_blk[0]

        q_t = _tile_off(zigzag, c, q_lo, q_hi, qi * bq)
        kv_t = _tile_off(zigzag, c, kv_lo, kv_hi, kvi * bk)

        def update_rows(r0, rows, q_rows, k_cols, v_cols, keep):
            """Online-softmax update of scr rows [r0, r0+rows) against the
            key/value column slice. ``keep`` (None = mask-free) masks the
            scores before the running max and the probabilities after.
            Matmul operands stay in the INPUT dtype (f32 accumulate):
            upcasting bf16 q/k to f32 first would run the MXU at its
            ~4x-slower f32 rate — the round-2 42%-MFU bottleneck. q is
            prescaled (sm_scale·log2e folded in), so s_ij feeds the
            base-2 running softmax as-is."""
            s_ij = lax.dot_general(q_rows, k_cols,
                                   (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)
            if keep is not None:
                s_ij = jnp.where(keep, s_ij, _NEG)

            acc_p = scr[r0:r0 + rows, :D]
            m_p = jnp.max(scr[r0:r0 + rows, D:D + 128], axis=-1,
                          keepdims=True)
            l_p = jnp.max(scr[r0:r0 + rows, D + 128:], axis=-1,
                          keepdims=True)

            m_c = jnp.maximum(jnp.max(s_ij, axis=-1, keepdims=True), m_p)
            p = jnp.exp2(s_ij - m_c)
            if keep is not None:
                # exp2(-1e30 - (-1e30)) == 1 on fully-masked rows; re-mask
                p = jnp.where(keep, p, 0.0)
            alpha = jnp.exp2(m_p - m_c)
            l_c = l_p * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_c = acc_p * alpha + lax.dot_general(
                p.astype(v_cols.dtype), v_cols, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

            scr[r0:r0 + rows, :D] = acc_c
            scr[r0:r0 + rows, D:D + 128] = jnp.broadcast_to(m_c, (rows, 128))
            scr[r0:r0 + rows, D + 128:] = jnp.broadcast_to(l_c, (rows, 128))

        def compute(masked: bool):
            if masked:
                qpos = q_t + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                kpos = kv_t + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
                keep = kpos <= qpos
            else:
                keep = None
            update_rows(0, bq, q_blk[0], k_blk[0], v_blk[0], keep)

        diag_sub = _diag_sub(bq, bk, causal)

        def compute_diag():
            # exactly-diagonal square tile (q_t == kv_t): walk row bands
            # of ``diag_sub`` rows. Band i multiplies against columns
            # [0, i·sub) mask-free (everything there is strictly below the
            # diagonal) plus a (sub, sub) masked band on the diagonal
            # itself — skipping the upper triangle's MXU work entirely and
            # paying the iota+where mask on sub²/bq·bk of the tile (1/16
            # at sub=256, bq=bk=1024). This is the "masked sub-band +
            # interior remainder" split the round-4 roofline named as the
            # remaining causal lever (docs/benchmarks.md).
            band_keep = _band_keep(diag_sub)
            for i in range(bq // diag_sub):
                r0 = i * diag_sub
                q_rows = q_blk[0][r0:r0 + diag_sub, :]
                if r0 > 0:
                    update_rows(r0, diag_sub, q_rows, k_blk[0][:r0, :],
                                v_blk[0][:r0, :], None)
                update_rows(r0, diag_sub, q_rows,
                            k_blk[0][r0:r0 + diag_sub, :],
                            v_blk[0][r0:r0 + diag_sub, :], band_keep)

        # (under ``flat`` every enumerated tile has work; the dispatch
        # still routes interior tiles to the mask-free body)
        _dispatch_with_diag(causal, diag_sub, q_t, kv_t, bq, bk, compute,
                            compute_diag)

        @pl.when(last_of_q)
        def _():
            if step_final:
                # fused epilogue — ln-domain lse for the backward/combine
                # consumers (shared math with the skip-path pipeline)
                o, lse = _finalize_state(scr[...], D, out_dtype)
                o_blk[...] = o[None]
                lse_blk[...] = lse[None]
            else:
                out_blk[...] = scr[...][None]

    if flat is not None:
        pltpu.emit_pipeline(
            body,
            grid=(BH, n_tiles),
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda bh, t: (bh, qi_ref[t], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda bh, t: (kv_head(bh), kvi_ref[t], 0)),
                pl.BlockSpec((1, bk, D),
                             lambda bh, t: (kv_head(bh), kvi_ref[t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda bh, t: (bh, qi_ref[t], 0)),
                pl.BlockSpec((1, 1, bq), lambda bh, t: (bh, 0, qi_ref[t])),
            ],
        )(q_ref, k_src, v_src, o_ref, lse_ref)
        return

    if causal and not zigzag:
        # fully-masked tiles are a SUFFIX of each q-row's kv sweep in the
        # contiguous layout: clamp the kv block index to the last tile
        # with any un-masked work, so skipped steps REVISIT the previous
        # block instead of DMA-ing one they will never read (the pipeline
        # skips the copy when the index is unchanged). The body still
        # routes those steps to no-op via _causal_tile_dispatch.
        def kv_idx(bh, qi, kvi):
            last = jnp.maximum(
                (q_lo - kv_lo + (qi + 1) * bq - 1) // bk, 0)
            return (kv_head(bh), jnp.minimum(kvi, last), 0)
    else:
        kv_idx = lambda bh, qi, kvi: (kv_head(bh), kvi, 0)
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda bh, qi, kvi: (bh, qi, 0)),
        pl.BlockSpec((1, bk, D), kv_idx),
        pl.BlockSpec((1, bk, D), kv_idx),
    ]
    args = [q_ref, k_src, v_src]
    if not step_init:
        in_specs.append(pl.BlockSpec((1, bq, W),
                                     lambda bh, qi, kvi: (bh, qi, 0)))
        args.append(st_in)
    if step_final:
        out_specs = [
            pl.BlockSpec((1, bq, D), lambda bh, qi, kvi: (bh, qi, 0)),
            # lse stored [BH, 1, S]: lane dim = sequence (128-tiled), the
            # sublane-safe layout for per-row scalars
            pl.BlockSpec((1, 1, bq), lambda bh, qi, kvi: (bh, 0, qi)),
        ]
        outs = (o_ref, lse_ref)
    else:
        out_specs = [pl.BlockSpec((1, bq, W),
                                  lambda bh, qi, kvi: (bh, qi, 0))]
        outs = (st_out,)
    pltpu.emit_pipeline(
        body,
        grid=(BH, S // bq, S // bk),
        in_specs=in_specs,
        out_specs=out_specs,
    )(*args, *outs)


def _finalize_state(st, D, out_dtype):
    """THE epilogue math, one copy for both the fused final-step path and
    the skip-path pipeline (a formula drift between them would be a
    rank-dependent divergence): o = acc / l, lse = (m + log2 l)·ln2 —
    the running softmax is base-2 (q prescaled by sm_scale·log2e), the
    stored lse is ln-domain for the backward/combine consumers. ``st`` is
    an [rows, D+256] packed [acc ‖ m ‖ l] state VALUE; returns
    (o [rows, D], lse [1-row-transposed [.., rows]] f32)."""
    m = jnp.max(st[:, D:D + 128], axis=-1, keepdims=True)
    l = jnp.max(st[:, D + 128:], axis=-1, keepdims=True)
    safe = jnp.where(l > 0, l, 1.0)
    o = (st[:, :D] / safe).astype(out_dtype)
    lse = jnp.where(l > 0, _LN2 * (m + jnp.log2(safe)), _NEG
                    ).astype(jnp.float32).T
    return o, lse


def _epilogue_pipeline(D, bq, BH, S, st_src, o_ref, lse_ref):
    """Epilogue from a carried state buffer. Only used when the FINAL ring
    step's compute is skipped whole (causal contiguous layout, src > me) —
    the compute path fuses the same ``_finalize_state`` math into its own
    last kv tile."""
    W = D + 256

    def epi(st_blk, o_blk, lse_blk):
        o, lse = _finalize_state(st_blk[0], D, o_blk.dtype)
        o_blk[...] = o[None]
        lse_blk[...] = lse[None]

    pltpu.emit_pipeline(
        epi,
        grid=(BH, S // bq),
        in_specs=[pl.BlockSpec((1, bq, W), lambda bh, qi: (bh, qi, 0))],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            # lse stored [BH, 1, S]: lane dim = sequence (128-tiled), the
            # sublane-safe layout for per-row scalars (see verify notes on
            # sub-8-row DMAs)
            pl.BlockSpec((1, 1, bq), lambda bh, qi: (bh, 0, qi)),
        ],
    )(st_src, o_ref, lse_ref)


def _ring_fwd_kernel(axis, mesh_axes, causal, zigzag, flat_tiles,
                     cfg_bq, cfg_bk, Hq, Hkv, *refs):
    if flat_tiles is not None:
        # single-step flat walk (n=1 causal contiguous): the two SMEM
        # tile maps ride as extra inputs after v
        (q_ref, k_ref, v_ref, qi_map, kvi_map,
         o_ref, lse_ref, st0, st1, kv_slots,
         send_sems, recv_sems, ack_sem, state_scr) = refs
        flat = (flat_tiles, qi_map, kvi_map)
    else:
        (q_ref, k_ref, v_ref, o_ref, lse_ref, st0, st1, kv_slots,
         send_sems, recv_sems, ack_sem, state_scr) = refs
        flat = None
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    BH, S, D = q_ref.shape
    bq, bk = cfg_bq, cfg_bk
    right = shd.pe_at(mesh_axes, axis, lax.rem(me + 1, n))
    left = shd.pe_at(mesh_axes, axis, lax.rem(me - 1 + n, n))
    c = S // 2
    q_offs = _layout_offs(zigzag, me, c, S, n)

    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    states = (st0, st1)
    for s in range(n):
        slot = s % 2
        src = lax.rem(me - s + n, n)
        kv_offs = _layout_offs(zigzag, src, c, S, n)

        if s >= 1:
            shd.wait_recv(kv_slots.at[slot], recv_sems.at[slot])

        rdma = None
        if s < n - 1:
            if s >= 2:
                shd.signal_wait_until(ack_sem, 1)  # right freed slot (s+1)%2
            nxt = (s + 1) % 2
            if s == 0:
                rd_k = shd.putmem_nbi(kv_slots.at[nxt, :, :, :D], k_ref,
                                      send_sems.at[0], recv_sems.at[nxt],
                                      right)
                rd_v = shd.putmem_nbi(kv_slots.at[nxt, :, :, D:], v_ref,
                                      send_sems.at[1], recv_sems.at[nxt],
                                      right)
                rdma = (rd_k, rd_v)
            else:
                rdma = (shd.putmem_nbi(kv_slots.at[nxt], kv_slots.at[slot],
                                       send_sems.at[slot], recv_sems.at[nxt],
                                       right),)

        st_in, st_out = states[s % 2], states[(s + 1) % 2]
        if s == 0:
            k_src, v_src = k_ref, v_ref
        else:
            k_src = kv_slots.at[slot, :, :, :D]
            v_src = kv_slots.at[slot, :, :, D:]

        pipeline = functools.partial(
            _attn_step_pipeline, s == 0, s == n - 1, causal, zigzag, D, bq,
            bk, q_offs + kv_offs, BH, Hq, Hkv, S, state_scr,
            q_ref, k_src, v_src, st_in, st_out, o_ref, lse_ref,
            o_ref.dtype, flat=flat)
        if causal and not zigzag and s > 0:
            # contiguous layout: src > me ⇒ every kv position is beyond
            # every q position — skip the whole pipeline. Intermediate
            # steps carry the state forward with one DMA; the FINAL step
            # instead runs the epilogue-only pipeline over the carried
            # state (the compute path fuses its own epilogue).
            # (Zigzag has work every step by design; its balance comes
            # from per-tile skips inside the pipeline.)
            @pl.when(src > me)
            def _():
                if s == n - 1:
                    _epilogue_pipeline(D, bq, BH, S, st_in, o_ref, lse_ref)
                else:
                    pltpu.sync_copy(st_in, st_out)

            @pl.when(src <= me)
            def _():
                pipeline()
        else:
            pipeline()

        if rdma is not None:
            shd.quiet(*rdma)
        if s >= 1:
            shd.signal_op(ack_sem, 1, left)  # slot consumed + forwarded

    # unwaited ack credits from our right neighbor (we stop waiting after
    # the last send): steps s=1..n-1 acked, waits happened at s=2..n-2
    if n > 1:
        shd.signal_wait_until(ack_sem, min(n - 1, 2))

    # (the epilogue is fused into the final step's pipeline — see
    # _attn_step_pipeline; _epilogue_pipeline above handles the
    # causal-contiguous whole-step skip at s == n-1)


def _tile_sizes(half: int, block_q: int, block_k: int) -> tuple[int, int]:
    """THE derived q/k tile formula — the one source for the guard and both
    kernel bodies (``half`` is the per-rank row span: s_loc, or s_loc/2
    for zigzag)."""
    return math.gcd(block_q, half), math.gcd(block_k, half)


def _check_compiled_tiles(S: int, n: int, block_q: int, block_k: int,
                          zigzag: bool) -> None:
    """Compiled backends need the DERIVED q/k tile sizes (``_tile_sizes``
    of the per-rank row span — the half-chunk for zigzag) to be
    128-multiples: the lse-wire BlockSpecs slice the row dim along LANES,
    and Mosaic rejects sub-128 lane slices. Interpret mode accepts any
    tiling (it doesn't model the layout), so small-shape simulator tests
    keep working. Raises with the failing numbers."""
    if S % n:
        raise ValueError(
            f"ring attention needs S divisible by ranks: S={S}, ranks={n}")
    if default_interpret():
        return
    if zigzag and S % (2 * n):
        raise ValueError(
            f"zigzag ring attention needs S divisible by 2*ranks: "
            f"S={S}, ranks={n}")
    half = S // (2 * n) if zigzag else S // n
    bq, bk = _tile_sizes(half, block_q, block_k)
    if bq % 128 or bk % 128:
        raise ValueError(
            f"ring attention on compiled TPU needs 128-multiple row tiles: "
            f"S={S} over {n} ranks ({'zigzag half-chunks of ' if zigzag else 'local rows '}"
            f"{half}) with block_q={block_q}/block_k={block_k} derives "
            f"tiles ({bq}, {bk}) — the lse-wire slices would be "
            "lane-unaligned (Mosaic tiles by 128; the interpret-mode "
            "simulator does not enforce this)")


def ring_attention_fwd(ctx: ShmemContext, q: jax.Array, k: jax.Array,
                       v: jax.Array, axis: str | None = None,
                       causal: bool = True, sm_scale: float | None = None,
                       block_q: int = 1024, block_k: int = 1024,
                       batch_axis: str | None = None,
                       head_axis: str | None = None,
                       layout: str = "contiguous"):
    """Forward ring attention. ``q`` [B, Hq, S, D], ``k``/``v``
    [B, Hkv, S, D], all sharded P(batch_axis, head_axis, axis, None) —
    sequence over the ring ``axis`` (global S = n * S local), optionally
    batch over a dp axis and heads over a tp axis (each (dp, tp) row forms
    an independent ring). Returns (out [B, Hq, S, D] sharded like q, lse
    [B, Hq, S] f32 sharded the same) — lse is the backward/composition
    residual.

    ``layout``: "contiguous" — device r holds global rows [r*S_loc,
    (r+1)*S_loc); causal steps from future ranks are skipped whole.
    "zigzag" — device r holds chunks (r, 2n-1-r) of S_glob/(2n) rows each
    (concatenated), the standard load-balanced causal CP layout: every
    rank computes exactly two chunk-pairs per step (fully-masked tiles are
    skipped dynamically), vs 0..n for contiguous. Inputs/outputs stay in
    zigzag order — see ``zigzag_indices`` for the global permutation.

    Hq % Hkv == 0 per shard (GQA; a head_axis must divide both); S_local
    divisible by block_q and block_k; D a lane multiple (128).
    """
    axis = norm_axis(ctx, axis)
    assert isinstance(axis, str), "ring attention rings one axis"
    assert layout in ("contiguous", "zigzag"), layout
    zigzag = layout == "zigzag"
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    B, Hq, S, D = q.shape
    _, Hkv, Sk, Dk = k.shape
    assert (S, D) == (Sk, Dk) and v.shape == k.shape, (q.shape, k.shape)
    assert S % n == 0, f"S={S} not divisible by ranks {n}"
    assert D % 128 == 0, f"head dim {D} must be a lane multiple"
    _check_compiled_tiles(S, n, block_q, block_k, zigzag)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    def f(q_s, k_s, v_s):
        Bl, Hql, s_loc, _ = q_s.shape
        Hkvl = k_s.shape[1]
        assert Hql % Hkvl == 0, (
            f"per-shard GQA needs Hq % Hkv == 0, got {Hql}/{Hkvl}")
        half = s_loc // 2 if zigzag else s_loc
        if zigzag:
            assert s_loc % 2 == 0, "zigzag needs an even local row count"
        bq, bk = _tile_sizes(half, block_q, block_k)
        BH, BHkv = Bl * Hql, Bl * Hkvl
        # fold sm_scale·log2e into q ONCE (an O(S·D) pass) so the O(S²)
        # inner loop neither scales s_ij nor pays natural-exp conversion;
        # multiply in f32 so the constant stays exact and only the result
        # rounds to the input dtype
        q3 = (q_s.astype(jnp.float32) * (scale * _LOG2E)
              ).astype(q_s.dtype).reshape(BH, s_loc, D)
        k3 = k_s.reshape(BHkv, s_loc, D)
        v3 = v_s.reshape(BHkv, s_loc, D)
        W = D + 256
        flat_args = ()
        flat_specs = []
        flat_n = None
        if causal and not zigzag and n == 1:
            # single-chip causal prefill: enumerate the valid (q, kv)
            # tiles once (static — the triangle is fixed at n=1) and walk
            # them as a 1-D grid with SMEM maps; fully-masked tiles never
            # become grid steps (see _attn_step_pipeline's ``flat``)
            import numpy as np
            tiles = [(qi, kv)
                     for qi in range(s_loc // bq)
                     for kv in range(((qi + 1) * bq - 1) // bk + 1)]
            flat_n = len(tiles)
            qi_m = np.array([t[0] for t in tiles], np.int32)
            kvi_m = np.array([t[1] for t in tiles], np.int32)
            flat_args = (jnp.asarray(qi_m), jnp.asarray(kvi_m))
            flat_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
        kernel = lambda *refs: _ring_fwd_kernel(
            axis, mesh_axes, causal, zigzag, flat_n, bq, bk, Hql, Hkvl,
            *refs)
        out, lse, *_ = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((BH, s_loc, D), q_s.dtype),
                jax.ShapeDtypeStruct((BH, 1, s_loc), jnp.float32),
                jax.ShapeDtypeStruct((BH, s_loc, W), jnp.float32),  # st0
                jax.ShapeDtypeStruct((BH, s_loc, W), jnp.float32),  # st1
                jax.ShapeDtypeStruct((2, BHkv, s_loc, 2 * D), k_s.dtype),
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3 + flat_specs,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 5,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
                # VMEM-resident [acc ‖ m ‖ l] running-softmax state — the
                # kv-sweep accumulator for every step's pipeline
                pltpu.VMEM((bq, W), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"ring_attn_{axis}")),
            cost_estimate=pl.CostEstimate(
                flops=4 * BH * s_loc * (n * s_loc) * D,
                bytes_accessed=(q3.size + n * (k3.size + v3.size)
                                + BH * s_loc * D) * q_s.dtype.itemsize,
                transcendentals=BH * s_loc * n * s_loc),
            interpret=default_interpret(),
        )(q3, k3, v3, *flat_args)
        return (out.reshape(Bl, Hql, s_loc, D),
                lse.reshape(Bl, Hql, s_loc))

    spec = P(batch_axis, head_axis, axis, None)
    sm = ctx.shard_map(
        f, in_specs=(spec,) * 3,
        out_specs=(spec, P(batch_axis, head_axis, axis)))
    return sm(q, k, v)


def _bwd_dq_pipeline(step_init, causal, zigzag, scale, D, bq, bk, offs,
                     BH, Hq, Hkv, S,
                     q_ref, do_ref, lse_ref, dl_ref, k_src, v_src,
                     dq_in, dq_out):
    """dq accumulation for one ring step: grid (head, q-tile, kv-tile), kv
    innermost so the dq block stays resident across the kv sweep."""
    g = Hq // Hkv
    q_lo, q_hi, kv_lo, kv_hi = offs
    c = S // 2 if zigzag else S

    def kv_head(bh):
        return (bh // Hq) * Hkv + (bh % Hq) // g

    def body(q_blk, do_blk, lse_blk, dl_blk, k_blk, v_blk, *st):
        if step_init:
            (dq_o,) = st
        else:
            dq_i, dq_o = st
        kvi = pl.program_id(2)
        qi = pl.program_id(1)

        @pl.when(kvi == 0)
        def _():
            if step_init:
                dq_o[...] = jnp.zeros((1, bq, D), jnp.float32)
            else:
                dq_o[...] = dq_i[...]

        q_t = _tile_off(zigzag, c, q_lo, q_hi, qi * bq)
        kv_t = _tile_off(zigzag, c, kv_lo, kv_hi, kvi * bk)

        def compute(masked: bool):
            p, dS, keep = _recompute_p_ds(
                masked, bq, bk, q_t, kv_t,
                q_blk, do_blk, lse_blk, dl_blk, k_blk, v_blk)
            # k is unscaled, so dq keeps the explicit sm_scale factor; the
            # result is d(q), not d(q·scale·log2e) — chain rule folds the
            # prescale constant right back out
            dq_o[0] += lax.dot_general(
                dS.astype(k_blk.dtype), k_blk[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

        diag_sub = _diag_sub(bq, bk, causal)

        def compute_diag():
            # exactly-diagonal square tile: row bands skip the upper
            # triangle's MXU work and shrink the mask to (sub, sub) per
            # band — the forward's split (see _attn_step_pipeline),
            # applied to the dq accumulation with sliced += updates
            band_keep = _band_keep(diag_sub)
            for i in range(bq // diag_sub):
                r0 = i * diag_sub
                q_r = q_blk[0][r0:r0 + diag_sub, :]
                do_r = do_blk[0][r0:r0 + diag_sub, :]
                lse_r = lse_blk[0].T[r0:r0 + diag_sub]
                dl_r = dl_blk[0].T[r0:r0 + diag_sub]
                if r0 > 0:
                    _, dS = _p_ds_core(q_r, k_blk[0][:r0, :], do_r,
                                       v_blk[0][:r0, :], lse_r, dl_r, None)
                    dq_o[0, r0:r0 + diag_sub] += lax.dot_general(
                        dS.astype(k_blk.dtype), k_blk[0][:r0, :],
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
                _, dS = _p_ds_core(q_r, k_blk[0][r0:r0 + diag_sub, :],
                                   do_r, v_blk[0][r0:r0 + diag_sub, :],
                                   lse_r, dl_r, band_keep)
                dq_o[0, r0:r0 + diag_sub] += lax.dot_general(
                    dS.astype(k_blk.dtype), k_blk[0][r0:r0 + diag_sub, :],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale

        _dispatch_with_diag(causal, diag_sub, q_t, kv_t, bq, bk, compute,
                            compute_diag)

    in_specs = [
        pl.BlockSpec((1, bq, D), lambda bh, qi, kvi: (bh, qi, 0)),
        pl.BlockSpec((1, bq, D), lambda bh, qi, kvi: (bh, qi, 0)),
        pl.BlockSpec((1, 1, bq), lambda bh, qi, kvi: (bh, 0, qi)),
        pl.BlockSpec((1, 1, bq), lambda bh, qi, kvi: (bh, 0, qi)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, kvi: (kv_head(bh), kvi, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, kvi: (kv_head(bh), kvi, 0)),
    ]
    args = [q_ref, do_ref, lse_ref, dl_ref, k_src, v_src]
    if not step_init:
        in_specs.append(pl.BlockSpec((1, bq, D),
                                     lambda bh, qi, kvi: (bh, qi, 0)))
        args.append(dq_in)
    pltpu.emit_pipeline(
        body, grid=(BH, S // bq, S // bk), in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, bq, D),
                                lambda bh, qi, kvi: (bh, qi, 0))],
    )(*args, dq_out)


def _bwd_dkv_pipeline(step_init, causal, zigzag, scale, D, bq, bk, offs,
                      BHkv, Hq, Hkv, S,
                      q_ref, do_ref, lse_ref, dl_ref, k_src, v_src,
                      g_in, g_out):
    """dk‖dv accumulation for one ring step: grid (kv-head, kv-tile,
    group-member, q-tile) — the g block (dk ‖ dv lanes) stays resident
    across the whole (group, q) sweep, initialized from the arriving
    partial (or zeros at s == 0) and shipped onward afterwards."""
    g = Hq // Hkv
    q_lo, q_hi, kv_lo, kv_hi = offs
    c = S // 2 if zigzag else S

    def q_head(bhkv, hg):
        return (bhkv // Hkv) * Hq + (bhkv % Hkv) * g + hg

    def body(q_blk, do_blk, lse_blk, dl_blk, k_blk, v_blk, *st):
        if step_init:
            (g_o,) = st
        else:
            g_i, g_o = st
        kvi = pl.program_id(1)
        hg = pl.program_id(2)
        qi = pl.program_id(3)

        @pl.when(jnp.logical_and(hg == 0, qi == 0))
        def _():
            if step_init:
                g_o[...] = jnp.zeros((1, bk, 2 * D), jnp.float32)
            else:
                g_o[...] = g_i[...]

        q_t = _tile_off(zigzag, c, q_lo, q_hi, qi * bq)
        kv_t = _tile_off(zigzag, c, kv_lo, kv_hi, kvi * bk)

        def compute(masked: bool):
            p, dS, keep = _recompute_p_ds(
                masked, bq, bk, q_t, kv_t,
                q_blk, do_blk, lse_blk, dl_blk, k_blk, v_blk)
            # q arrives prescaled by scale·log2e, so dS @ q² carries an
            # extra log2e vs the wanted dS @ q · scale — ln2 cancels it
            g_o[0, :, :D] += lax.dot_general(
                dS.astype(q_blk.dtype), q_blk[0], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * _LN2
            g_o[0, :, D:] += lax.dot_general(
                p.astype(do_blk.dtype), do_blk[0], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        diag_sub = _diag_sub(bq, bk, causal)

        def compute_diag():
            # diagonal split over q row bands: band i touches kv rows
            # [0, r0+sub) only — the rect part accumulates into g_o rows
            # [0, r0) mask-free, the (sub, sub) band masked
            band_keep = _band_keep(diag_sub)
            for i in range(bq // diag_sub):
                r0 = i * diag_sub
                q_r = q_blk[0][r0:r0 + diag_sub, :]
                do_r = do_blk[0][r0:r0 + diag_sub, :]
                lse_r = lse_blk[0].T[r0:r0 + diag_sub]
                dl_r = dl_blk[0].T[r0:r0 + diag_sub]
                if r0 > 0:
                    p, dS = _p_ds_core(q_r, k_blk[0][:r0, :], do_r,
                                       v_blk[0][:r0, :], lse_r, dl_r, None)
                    g_o[0, :r0, :D] += lax.dot_general(
                        dS.astype(q_blk.dtype), q_r,
                        (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32) * _LN2
                    g_o[0, :r0, D:] += lax.dot_general(
                        p.astype(do_blk.dtype), do_r,
                        (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                p, dS = _p_ds_core(q_r, k_blk[0][r0:r0 + diag_sub, :],
                                   do_r, v_blk[0][r0:r0 + diag_sub, :],
                                   lse_r, dl_r, band_keep)
                g_o[0, r0:r0 + diag_sub, :D] += lax.dot_general(
                    dS.astype(q_blk.dtype), q_r, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * _LN2
                g_o[0, r0:r0 + diag_sub, D:] += lax.dot_general(
                    p.astype(do_blk.dtype), do_r, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

        _dispatch_with_diag(causal, diag_sub, q_t, kv_t, bq, bk, compute,
                            compute_diag)

    in_specs = [
        pl.BlockSpec((1, bq, D),
                     lambda bhkv, kvi, hg, qi: (q_head(bhkv, hg), qi, 0)),
        pl.BlockSpec((1, bq, D),
                     lambda bhkv, kvi, hg, qi: (q_head(bhkv, hg), qi, 0)),
        pl.BlockSpec((1, 1, bq),
                     lambda bhkv, kvi, hg, qi: (q_head(bhkv, hg), 0, qi)),
        pl.BlockSpec((1, 1, bq),
                     lambda bhkv, kvi, hg, qi: (q_head(bhkv, hg), 0, qi)),
        pl.BlockSpec((1, bk, D), lambda bhkv, kvi, hg, qi: (bhkv, kvi, 0)),
        pl.BlockSpec((1, bk, D), lambda bhkv, kvi, hg, qi: (bhkv, kvi, 0)),
    ]
    args = [q_ref, do_ref, lse_ref, dl_ref, k_src, v_src]
    if not step_init:
        in_specs.append(pl.BlockSpec((1, bk, 2 * D),
                                     lambda bhkv, kvi, hg, qi: (bhkv, kvi, 0)))
        args.append(g_in)
    pltpu.emit_pipeline(
        body, grid=(BHkv, S // bk, g, S // bq), in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, bk, 2 * D),
                                lambda bhkv, kvi, hg, qi: (bhkv, kvi, 0))],
    )(*args, g_out)


def _p_ds_core(q_rows, k_cols, do_rows, v_cols, lse_rows, dl_rows, keep):
    """Array-form backward-tile math on (possibly sliced) operands:
    recompute p from (q, k, lse), then dS = p * (do @ v^T - delta).
    ``keep`` (None = mask-free) zeroes masked probabilities. Shared by
    the whole-tile path (`_recompute_p_ds`) and the diagonal row-band
    split in the bwd pipelines."""
    s_ij = lax.dot_general(q_rows, k_cols, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)
    p = jnp.exp2(s_ij - lse_rows * _LOG2E)
    if keep is not None:
        p = jnp.where(keep, p, 0.0)
    dp = lax.dot_general(do_rows, v_cols, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dS = p * (dp - dl_rows)
    return p, dS


def _recompute_p_ds(masked, bq, bk, q_pos0, kv_pos0,
                    q_blk, do_blk, lse_blk, dl_blk, k_blk, v_blk):
    """Shared backward-tile math: recompute p from (q, k, lse), then
    dS = p * (do @ v^T - delta). Returns (p, dS, keep-mask). Matmul
    operands stay in the input dtype (f32 accumulate) — see the forward
    pipeline's MXU-rate note. ``q_blk`` arrives PRESCALED by
    sm_scale·log2e (like the forward), so p = exp2(s₂ − lse·log2e) =
    exp(s − lse) with no per-element scale multiply and the base-2
    transcendental; the lse conversion is one (bq, 1) multiply per tile.
    ``masked`` is python-static: True only for diagonal causal tiles
    (``_causal_tile_dispatch``); interior tiles run the mask-free body."""
    keep = None
    if masked:
        qpos = q_pos0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = kv_pos0 + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        keep = kpos <= qpos
    p, dS = _p_ds_core(q_blk[0], k_blk[0], do_blk[0], v_blk[0],
                       lse_blk[0].T, dl_blk[0].T, keep)
    return p, dS, keep


def _ring_bwd_kernel(axis, mesh_axes, causal, zigzag, scale, bq, bk,
                     Hq, Hkv,
                     q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                     dq_ref, dk_ref, dv_ref,
                     dl_ref, dst0, dst1, gacc, kv_slots, g_slots,
                     kv_send, g_send, kv_recv, g_recv, ack_sem):
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    BH, S, D = q_ref.shape
    right = shd.pe_at(mesh_axes, axis, lax.rem(me + 1, n))
    left = shd.pe_at(mesh_axes, axis, lax.rem(me - 1 + n, n))
    c = S // 2
    q_offs = _layout_offs(zigzag, me, c, S, n)

    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    # delta = rowsum(do * o) per q row, stored lane-major like lse
    def delta_body(do_blk, o_blk, dl_blk):
        d = jnp.sum(do_blk[0].astype(jnp.float32)
                    * o_blk[0].astype(jnp.float32), axis=-1, keepdims=True)
        dl_blk[...] = d.T[None]

    pltpu.emit_pipeline(
        delta_body, grid=(BH, S // bq),
        in_specs=[pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
                  pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0))],
        out_specs=[pl.BlockSpec((1, 1, bq), lambda bh, qi: (bh, 0, qi))],
    )(do_ref, o_ref, dl_ref)

    dstates = (dst0, dst1)
    for s in range(n):
        slot = s % 2
        nxt = (s + 1) % 2
        src = lax.rem(me - s + n, n)
        kv_offs = _layout_offs(zigzag, src, c, S, n)

        if s >= 1:
            shd.wait_recv(kv_slots.at[slot], kv_recv.at[slot])
            shd.wait_recv(g_slots.at[slot], g_recv.at[slot])

        rdmas = []
        if s >= 2:
            shd.signal_wait_until(ack_sem, 1)  # right freed its nxt slots
        if s < n - 1:
            if s == 0:
                rdmas.append(shd.putmem_nbi(kv_slots.at[nxt, :, :, :D],
                                            k_ref, kv_send.at[0],
                                            kv_recv.at[nxt], right))
                rdmas.append(shd.putmem_nbi(kv_slots.at[nxt, :, :, D:],
                                            v_ref, kv_send.at[1],
                                            kv_recv.at[nxt], right))
            else:
                rdmas.append(shd.putmem_nbi(kv_slots.at[nxt],
                                            kv_slots.at[slot],
                                            kv_send.at[slot],
                                            kv_recv.at[nxt], right))

        if s == 0:
            k_src, v_src = k_ref, v_ref
        else:
            k_src = kv_slots.at[slot, :, :, :D]
            v_src = kv_slots.at[slot, :, :, D:]

        dq_in, dq_out = dstates[slot], dstates[nxt]
        run_a = functools.partial(
            _bwd_dq_pipeline, s == 0, causal, zigzag, scale, D, bq, bk,
            q_offs + kv_offs, BH, Hq, Hkv, S, q_ref, do_ref, lse_ref,
            dl_ref, k_src, v_src, dq_in, dq_out)
        run_b = functools.partial(
            _bwd_dkv_pipeline, s == 0, causal, zigzag, scale, D, bq, bk,
            q_offs + kv_offs, kv_slots.shape[1], Hq, Hkv, S, q_ref, do_ref,
            lse_ref, dl_ref, k_src, v_src, g_slots.at[slot], gacc)

        if causal and not zigzag and s > 0:
            @pl.when(src > me)
            def _():
                pltpu.sync_copy(dq_in, dq_out)
                pltpu.sync_copy(g_slots.at[slot], gacc)

            @pl.when(src <= me)
            def _():
                run_a()
                run_b()
        else:
            run_a()
            run_b()

        if n > 1:
            # ship the accumulated dk‖dv onward; at s == n-1 this is the
            # homecoming delivery of OUR block's finished gradients
            rdmas.append(shd.putmem_nbi(g_slots.at[nxt], gacc,
                                        g_send.at[slot], g_recv.at[nxt],
                                        right))
        shd.quiet(*rdmas)
        if s >= 1:
            shd.signal_op(ack_sem, 1, left)

    if n > 1:
        shd.signal_wait_until(ack_sem, 1)  # unwaited trailing credit
        shd.wait_recv(g_slots.at[n % 2], g_recv.at[n % 2])
        g_final = g_slots.at[n % 2]
    else:
        g_final = gacc

    # epilogue: cast dq, split dk ‖ dv
    def dq_epi(st_blk, dq_blk):
        dq_blk[...] = st_blk[...].astype(dq_ref.dtype)

    pltpu.emit_pipeline(
        dq_epi, grid=(BH, S // bq),
        in_specs=[pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0))],
        out_specs=[pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0))],
    )(dstates[n % 2], dq_ref)

    def dkv_epi(g_blk, dk_blk, dv_blk):
        dk_blk[...] = g_blk[:, :, :D].astype(dk_ref.dtype)
        dv_blk[...] = g_blk[:, :, D:].astype(dv_ref.dtype)

    BHkv = kv_slots.shape[1]
    pltpu.emit_pipeline(
        dkv_epi, grid=(BHkv, S // bk),
        in_specs=[pl.BlockSpec((1, bk, 2 * D),
                               lambda bh, ki: (bh, ki, 0))],
        out_specs=[pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0)),
                   pl.BlockSpec((1, bk, D), lambda bh, ki: (bh, ki, 0))],
    )(g_final, dk_ref, dv_ref)


def ring_attention_bwd(ctx: ShmemContext, q, k, v, o, lse, do,
                       axis: str, causal: bool, sm_scale: float | None,
                       block_q: int = 1024, block_k: int = 1024,
                       batch_axis: str | None = None,
                       head_axis: str | None = None,
                       layout: str = "contiguous"):
    """Backward ring attention: a second ring pass where each KV block
    travels with its partial (dk ‖ dv) accumulator and arrives home after a
    full circle, while dq accumulates locally — flash-attention backward
    with the ring as the outer loop."""
    mesh_axes = ctx.axis_names
    n = ctx.axis_size(axis)
    D = q.shape[-1]
    assert layout in ("contiguous", "zigzag"), layout
    zigzag = layout == "zigzag"
    _check_compiled_tiles(q.shape[2], n, block_q, block_k, zigzag)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    def f(q_s, k_s, v_s, o_s, lse_s, do_s):
        Bl, Hql, s_loc, _ = q_s.shape
        Hkvl = k_s.shape[1]
        if zigzag:
            assert s_loc % 2 == 0, "zigzag needs an even local row count"
        half = s_loc // 2 if zigzag else s_loc
        bq, bk = _tile_sizes(half, block_q, block_k)
        BH, BHkv = Bl * Hql, Bl * Hkvl
        # prescale q once (sm_scale·log2e) in f32, mirroring the forward —
        # the recompute then runs the base-2 softmax with no per-element
        # scale and the constant never rounds to the input dtype
        q3 = (q_s.astype(jnp.float32) * (scale * _LOG2E)
              ).astype(q_s.dtype).reshape(BH, s_loc, D)
        k3 = k_s.reshape(BHkv, s_loc, D)
        v3 = v_s.reshape(BHkv, s_loc, D)
        o3 = o_s.reshape(BH, s_loc, D)
        lse3 = lse_s.reshape(BH, 1, s_loc)
        do3 = do_s.reshape(BH, s_loc, D)
        kernel = lambda *refs: _ring_bwd_kernel(
            axis, mesh_axes, causal, zigzag, scale, bq, bk, Hql, Hkvl,
            *refs)
        dq, dk, dv, *_ = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((BH, s_loc, D), q_s.dtype),
                jax.ShapeDtypeStruct((BHkv, s_loc, D), k_s.dtype),
                jax.ShapeDtypeStruct((BHkv, s_loc, D), v_s.dtype),
                jax.ShapeDtypeStruct((BH, 1, s_loc), jnp.float32),   # delta
                jax.ShapeDtypeStruct((BH, s_loc, D), jnp.float32),   # dq st0
                jax.ShapeDtypeStruct((BH, s_loc, D), jnp.float32),   # dq st1
                jax.ShapeDtypeStruct((BHkv, s_loc, 2 * D), jnp.float32),
                jax.ShapeDtypeStruct((2, BHkv, s_loc, 2 * D), k_s.dtype),
                jax.ShapeDtypeStruct((2, BHkv, s_loc, 2 * D), jnp.float32),
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 6,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 9,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"ring_attn_bwd_{axis}")),
            cost_estimate=pl.CostEstimate(
                flops=10 * BH * s_loc * (n * s_loc) * D,
                bytes_accessed=3 * (q3.size + 2 * n * k3.size)
                * q_s.dtype.itemsize,
                transcendentals=BH * s_loc * n * s_loc),
            interpret=default_interpret(),
        )(q3, k3, v3, o3, lse3, do3)
        return (dq.reshape(Bl, Hql, s_loc, D),
                dk.reshape(Bl, Hkvl, s_loc, D),
                dv.reshape(Bl, Hkvl, s_loc, D))

    spec = P(batch_axis, head_axis, axis, None)
    lse_spec = P(batch_axis, head_axis, axis)
    sm = ctx.shard_map(
        f, in_specs=(spec, spec, spec, spec, lse_spec, spec),
        out_specs=(spec,) * 3)
    return sm(q, k, v, o, lse, do)


def ring_attention(ctx: ShmemContext, q: jax.Array, k: jax.Array,
                   v: jax.Array, axis: str | None = None,
                   causal: bool = True, sm_scale: float | None = None,
                   block_q: int = 1024, block_k: int = 1024,
                   batch_axis: str | None = None,
                   head_axis: str | None = None,
                   layout: str = "contiguous") -> jax.Array:
    """Context-parallel blockwise attention over a ring (public,
    differentiable entry). Golden: dense softmax attention on the gathered
    sequence; gradient golden: jax.grad of the dense computation.
    ``batch_axis``/``head_axis`` compose with dp/tp meshes (independent
    rings per (dp, tp) row). ``layout="zigzag"`` is the load-balanced
    causal layout (see ``ring_attention_fwd`` and ``zigzag_indices``)."""
    axis_n = norm_axis(ctx, axis)
    kw = dict(axis=axis_n, causal=causal, sm_scale=sm_scale,
              block_q=block_q, block_k=block_k, batch_axis=batch_axis,
              head_axis=head_axis, layout=layout)

    @jax.custom_vjp
    def attn(q, k, v):
        out, _ = ring_attention_fwd(ctx, q, k, v, **kw)
        return out

    def fwd(q, k, v):
        out, lse = ring_attention_fwd(ctx, q, k, v, **kw)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        return ring_attention_bwd(ctx, q, k, v, out, lse, do, **kw)

    attn.defvjp(fwd, bwd)
    return attn(q, k, v)


def zigzag_indices(S: int, n: int):
    """Global row permutation for the zigzag layout: device r holds global
    chunks (r, 2n-1-r) of S/(2n) rows each, concatenated. Returns ``idx``
    with ``x_zigzag = x[idx]`` (sharding the result P(axis) gives each
    device its zigzag block) and ``inv`` with ``x = x_zigzag[inv]``."""
    assert S % (2 * n) == 0, (S, n)
    import numpy as np
    c = S // (2 * n)
    idx = np.concatenate([
        np.concatenate([np.arange(r * c, (r + 1) * c),
                        np.arange((2 * n - 1 - r) * c, (2 * n - r) * c)])
        for r in range(n)])
    inv = np.argsort(idx)
    return idx, inv


__all__ = ["ring_attention", "ring_attention_fwd", "ring_attention_bwd",
           "zigzag_indices"]
