"""On-chip bisection runbook for the 2-tier EP A2A hang — runnable form.

Round-2 state: `dispatch_2d` compiled on-chip at a (1,1) mesh hung, and
killing the client mid-(remote-)compile wedged the device for hours.
Round-3 state: the same graphs compile CLEAN through the local libtpu
topology client at (2,4) and (1,1) (tests/test_aot_topology.py), so the
hang is in the remote-compile service or in execution.

This script executes the recorded recipe stage by stage, client-side
compile only, in SEPARATE subprocesses with generous timeouts so one hung
stage cannot take the parent (or, with remote compile disabled, the
device) down with it:

    python scripts/bisect_a2a_onchip.py            # all stages
    python scripts/bisect_a2a_onchip.py put serial_push   # specific ones

A pre-flight probe (subprocess jax.devices(), short timeout) runs first:
on a wedged tunnel EVERY stage would otherwise hang in backend discovery
before reaching any kernel, and a backend-init hang must not be
misattributed to the kernel under test.

Each kernel stage has a TDT_SERIAL=1 twin that runs first —
serial-passes/pipelined-hangs ⇒ protocol sync bug; both hang ⇒
lowering/runtime:
    put                known-good single-chip ring put (chip sanity)
    serial_push/push   bare all_to_all_push, 2-axis (1,1) mesh
    serial_d2d/d2d     dispatch_2d, (1,1)
    serial_roundtrip/roundtrip   dispatch_2d + combine_2d
    serial_d2d_fp8/d2d_fp8       quantized wire variant
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

STAGE_BODIES = {
    "put": """
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.shmem import device as shd
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P
import jax, jax.numpy as jnp
ctx = initialize_distributed(axis_names=("x",), mesh_shape=(1,))
def kernel(i_ref, o_ref, s_sem, r_sem):
    rdma = shd.putmem_nbi(o_ref, i_ref, s_sem, r_sem, shd.my_pe("x"))
    shd.quiet(rdma)
    shd.wait_recv(o_ref, r_sem)
f = lambda x: pl.pallas_call(
    kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
    out_specs=pl.BlockSpec(memory_space=pl.ANY),
    scratch_shapes=[pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())],
    compiler_params=pltpu.CompilerParams(has_side_effects=True),
    interpret=__import__("triton_dist_tpu.utils", fromlist=["x"]
                         ).default_interpret())(x)
x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
y = jax.jit(ctx.shard_map(f, in_specs=P("x"), out_specs=P("x")))(x)
assert jnp.allclose(y, x), "self-put mismatch"
""",
    "push": """
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.ops.all_to_all import all_to_all_push
ctx = initialize_distributed(axis_names=("o", "i"), mesh_shape=(1, 1))
spec = P(("o", "i"))
x = jnp.arange(1 * 32 * 128, dtype=jnp.bfloat16).reshape(1, 32, 128)
(y,) = all_to_all_push(ctx, ctx.shard(x, spec), axis="i", spec=spec)
jax.block_until_ready(y)
assert jnp.allclose(y.astype(jnp.float32), x.astype(jnp.float32))
""",
    "d2d": """
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.ops.all_to_all import (create_all_to_all_context_2d,
                                            dispatch_2d)
ctx = initialize_distributed(axis_names=("o", "i"), mesh_shape=(1, 1))
T, H, topk, E = 8, 128, 2, 4
a2a = create_all_to_all_context_2d(ctx, max_tokens=T, hidden=H, topk=topk,
                                   num_experts=E, dtype=jnp.bfloat16{wire})
spec = P(("o", "i"))
t = jax.random.normal(jax.random.key(0), (T, H), jnp.float32).astype(jnp.bfloat16)
i = jax.random.randint(jax.random.key(1), (T, topk), 0, E)
rt, ri, lay = dispatch_2d(a2a, ctx.shard(t, spec), ctx.shard(i, spec))
jax.block_until_ready(rt)
""",
    "roundtrip": """
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.ops.all_to_all import (combine_2d,
                                            create_all_to_all_context_2d,
                                            dispatch_2d)
ctx = initialize_distributed(axis_names=("o", "i"), mesh_shape=(1, 1))
T, H, topk, E = 8, 128, 2, 4
a2a = create_all_to_all_context_2d(ctx, max_tokens=T, hidden=H, topk=topk,
                                   num_experts=E, dtype=jnp.bfloat16)
spec = P(("o", "i"))
t = jax.random.normal(jax.random.key(0), (T, H), jnp.float32).astype(jnp.bfloat16)
i = jax.random.randint(jax.random.key(1), (T, topk), 0, E)
w = jnp.full((T, topk), 1.0 / topk)
rt, ri, lay = dispatch_2d(a2a, ctx.shard(t, spec), ctx.shard(i, spec))
back = combine_2d(a2a, rt, lay, ctx.shard(w, spec))
jax.block_until_ready(back)
import numpy as np
np.testing.assert_allclose(np.asarray(back, np.float32),
                           np.asarray(t, np.float32), rtol=3e-2, atol=3e-2)
""",
}

# (name, body_key, env overrides, wire-dtype code suffix)
FP8 = ", wire_dtype=jnp.float8_e4m3fn"
STAGES = [
    ("put", "put", {}, ""),
    ("serial_push", "push", {"TDT_SERIAL": "1"}, ""),
    ("push", "push", {}, ""),
    ("serial_d2d", "d2d", {"TDT_SERIAL": "1"}, ""),
    ("d2d", "d2d", {}, ""),
    ("serial_roundtrip", "roundtrip", {"TDT_SERIAL": "1"}, ""),
    ("roundtrip", "roundtrip", {}, ""),
    ("serial_d2d_fp8", "d2d", {"TDT_SERIAL": "1"}, FP8),
    ("d2d_fp8", "d2d", {}, FP8),
]


def preflight(timeout_s: int = 180) -> bool:
    """Backend reachable AND an accelerator, probed in a subprocess: a
    wedged tunnel hangs jax.devices() in ANY process with the device
    plugin registered (a hang must not be misread as a kernel-stage
    failure), and a CPU-fallback backend would run every stage in
    interpreter mode — interpreter results must never read as on-chip
    bisection evidence."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(len(d), d[0].platform); "
             "raise SystemExit(1 if d[0].platform == 'cpu' else 0)"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_stage(name: str, body_key: str, env_extra: dict, wire: str,
              timeout_s: int = 1200) -> str:
    body = STAGE_BODIES[body_key].replace("{wire}", wire)
    env = dict(os.environ)
    if os.environ.get("TDT_BISECT_REMOTE") == "1":
        # explicit override: an ambient =0 (exported per the r3 recipe)
        # must not silently keep stages on the mismatching client compiler
        env["PALLAS_AXON_REMOTE_COMPILE"] = "1"
    else:
        # client-side compile: a hung compile stays local and killable;
        # never let the remote terminal own the compile of a suspect graph.
        # NOTE (r4): when the client AOT libtpu and the terminal disagree
        # (rolling upgrade), this fails fast with FAILED_PRECONDITION
        # "libtpu version mismatch" — then remote compile is the ONLY
        # path: re-run with TDT_BISECT_REMOTE=1, one stage at a time, and
        # let the between-stage health probe catch a wedge before the next
        # stage walks into it.
        env["PALLAS_AXON_REMOTE_COMPILE"] = "0"
    env.update(env_extra)
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, "-c", body], env=env,
                           timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        # timeout kills the LOCAL process; with client-side compile this
        # cannot wedge the remote device the way round 2's kill did
        return f"TIMEOUT after {timeout_s}s"
    dt = time.time() - t0
    if r.returncode == 0:
        return f"OK in {dt:.0f}s"
    tail = (r.stderr or r.stdout).strip().splitlines()[-6:]
    return f"rc={r.returncode} in {dt:.0f}s\n    " + "\n    ".join(tail)


def main() -> int:
    want = set(sys.argv[1:])
    known = {name for name, _, _, _ in STAGES}
    unknown = want - known
    if unknown:
        print(f"unknown stage(s) {sorted(unknown)}; "
              f"choose from {sorted(known)}", file=sys.stderr)
        return 2
    print("[bisect] preflight: backend reachability ...", flush=True)
    if not preflight():
        print("[bisect] BACKEND UNREACHABLE (jax.devices() hung/failed in "
              "a subprocess) — the tunnel is wedged; no kernel stage was "
              "reached. Nothing below would measure the kernels.",
              flush=True)
        return 3
    print("[bisect] preflight OK", flush=True)
    results = {}
    for name, body_key, env_extra, wire in STAGES:
        if want and name not in want:
            continue
        print(f"[bisect] {name} ...", flush=True)
        results[name] = run_stage(name, body_key, dict(env_extra), wire)
        print(f"[bisect] {name}: {results[name]}", flush=True)
        if not results[name].startswith("OK"):
            # before blaming the kernel, check whether the stage took the
            # device down with it — a wedged tunnel must stop everything
            # (the next stage would hang in backend discovery, and any
            # result after this point would be noise)
            if not preflight():
                print("[bisect] DEVICE WEDGED after this stage — stopping; "
                      "do not start more device work until a probe "
                      "succeeds", flush=True)
                results[name] += " [device wedged after stage]"
                break
            print("[bisect] stopping at first failure (run remaining "
                  "stages explicitly to continue)", flush=True)
            break
    print("\n=== summary ===")
    for k, v in results.items():
        print(f"{k:14s} {v.splitlines()[0]}")
    return 0 if (results
                 and all(v.startswith("OK") for v in results.values())) else 1


if __name__ == "__main__":
    sys.exit(main())
