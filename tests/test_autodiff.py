"""Custom VJPs for the overlap TP linears vs jax.grad of a dense golden.

AG-GEMM and GEMM-RS are each other's adjoints; these tests pin both the
primal and every gradient term against pure-XLA autodiff."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.autodiff import ag_gemm_diff, gemm_rs_diff
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def test_ag_gemm_grads_match_dense(ctx):
    n = ctx.num_ranks
    M = K = 32 * n
    N = 64 * n
    cfg = GemmConfig(32, 64)
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32) * 0.3
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32) * 0.3
    t = jax.random.normal(jax.random.key(2), (M, N), jnp.float32)

    def loss(a, b):
        c = ag_gemm_diff(ctx, "x", cfg, a, b)
        return jnp.sum((c.astype(jnp.float32) - t) ** 2)

    def loss_dense(a, b):
        return jnp.sum((a @ b - t) ** 2)

    a_s, b_s = ctx.shard(a, P("x")), ctx.shard(b, P(None, "x"))
    val, (da, db) = jax.jit(jax.value_and_grad(loss, (0, 1)))(a_s, b_s)
    val_d, (da_d, db_d) = jax.jit(jax.value_and_grad(loss_dense, (0, 1)))(a, b)
    assert_allclose(np.asarray(val), np.asarray(val_d), rtol=1e-4, atol=1e-3)
    assert_allclose(np.asarray(da), np.asarray(da_d), rtol=1e-3, atol=1e-2)
    assert_allclose(np.asarray(db), np.asarray(db_d), rtol=1e-3, atol=1e-2)
    # gradient shardings follow the operands (the adjoint dualities)
    assert da.sharding.is_equivalent_to(a_s.sharding, da.ndim)
    assert db.sharding.is_equivalent_to(b_s.sharding, db.ndim)


def test_gemm_rs_grads_match_dense(ctx):
    n = ctx.num_ranks
    M, K, N = 32 * n, 32 * n, 64
    cfg = GemmConfig(32, 32)
    x = jax.random.normal(jax.random.key(0), (M, K), jnp.float32) * 0.3
    w = jax.random.normal(jax.random.key(1), (K, N), jnp.float32) * 0.3
    t = jax.random.normal(jax.random.key(2), (M, N), jnp.float32)

    def loss(x, w):
        y = gemm_rs_diff(ctx, "x", cfg, x, w)
        return jnp.sum((y.astype(jnp.float32) - t) ** 2)

    def loss_dense(x, w):
        return jnp.sum((x @ w - t) ** 2)

    x_s, w_s = ctx.shard(x, P(None, "x")), ctx.shard(w, P("x", None))
    val, (dx, dw) = jax.jit(jax.value_and_grad(loss, (0, 1)))(x_s, w_s)
    val_d, (dx_d, dw_d) = jax.jit(jax.value_and_grad(loss_dense, (0, 1)))(x, w)
    assert_allclose(np.asarray(val), np.asarray(val_d), rtol=1e-4, atol=1e-3)
    assert_allclose(np.asarray(dx), np.asarray(dx_d), rtol=1e-3, atol=1e-2)
    assert_allclose(np.asarray(dw), np.asarray(dw_d), rtol=1e-3, atol=1e-2)
    assert dx.sharding.is_equivalent_to(x_s.sharding, dx.ndim)
    assert dw.sharding.is_equivalent_to(w_s.sharding, dw.ndim)


def test_tp_mlp_end_to_end_grads(ctx):
    """Two-layer TP MLP (column- then row-parallel — the Megatron pair)
    trained one step vs the dense twin."""
    n = ctx.num_ranks
    M, D, F = 16 * n, 32 * n, 64 * n
    cfg = GemmConfig(16, 32)
    x = jax.random.normal(jax.random.key(0), (M, D), jnp.float32) * 0.3
    w1 = jax.random.normal(jax.random.key(1), (D, F), jnp.float32) * 0.1
    w2 = jax.random.normal(jax.random.key(2), (F, D), jnp.float32) * 0.1

    def mlp(x, w1, w2):
        h = ag_gemm_diff(ctx, "x", cfg, x, w1)          # [M, F] P(None, x)
        h = jax.nn.gelu(h.astype(jnp.float32)).astype(x.dtype)
        return gemm_rs_diff(ctx, "x", cfg, h, w2)       # [M, D] P(x)

    def loss(x, w1, w2):
        return jnp.mean(mlp(x, w1, w2).astype(jnp.float32) ** 2)

    def loss_dense(x, w1, w2):
        h = jax.nn.gelu(x @ w1)
        return jnp.mean((h @ w2) ** 2)

    args = (ctx.shard(x, P("x")), ctx.shard(w1, P(None, "x")),
            ctx.shard(w2, P("x", None)))
    grads = jax.jit(jax.grad(loss, (0, 1, 2)))(*args)
    grads_d = jax.jit(jax.grad(loss_dense, (0, 1, 2)))(x, w1, w2)
    for g, gd in zip(grads, grads_d):
        assert_allclose(np.asarray(g), np.asarray(gd), rtol=2e-3, atol=2e-3)


def test_llama_mlp_tp_overlap_grads(ctx):
    """Llama-style silu-gate MLP with the fused gate||up single-AG trick:
    forward and grads vs the dense twin."""
    from triton_dist_tpu.models.llama import mlp_tp_overlap

    n = ctx.num_ranks
    T, D, F = 16 * n, 32 * n, 32 * n
    cfg = GemmConfig(16, 32)
    x = jax.random.normal(jax.random.key(0), (T, D), jnp.float32) * 0.3
    wg = jax.random.normal(jax.random.key(1), (D, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(2), (D, F), jnp.float32) * 0.1
    wd = jax.random.normal(jax.random.key(3), (F, D), jnp.float32) * 0.1

    def loss(x, wg, wu, wd):
        y = mlp_tp_overlap(ctx, x, wg, wu, wd, axis="x", gemm_cfg=cfg)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    def loss_dense(x, wg, wu, wd):
        ff = jax.nn.silu(x @ wg) * (x @ wu)
        return jnp.mean((ff @ wd) ** 2)

    args = (ctx.shard(x, P("x")), ctx.shard(wg, P(None, "x")),
            ctx.shard(wu, P(None, "x")), ctx.shard(wd, P("x", None)))
    val, grads = jax.jit(jax.value_and_grad(loss, (0, 1, 2, 3)))(*args)
    val_d, grads_d = jax.jit(jax.value_and_grad(loss_dense, (0, 1, 2, 3)))(
        x, wg, wu, wd)
    assert_allclose(np.asarray(val), np.asarray(val_d), rtol=1e-4, atol=1e-5)
    for g, gd in zip(grads, grads_d):
        assert_allclose(np.asarray(g), np.asarray(gd), rtol=2e-3, atol=2e-3)
