"""Overlapped serving (ISSUE 16): fine-grained compute/comm overlap in
the decode/chunk hot loop, held to the SAME bitwise cross-mesh contract
as tests/test_sharded_serving.py.

THE claim under test: ``overlap="ep"`` (microbatched EP dispatch riding
the segmented counted-signal a2a, expert FFN overlapping the next
microbatch's wire) and ``overlap="ep+sp"`` (plus start-local SP pool
assembly under the allgather) move the SCHEDULE only — every combine is
still a concat or fixed-order fold — so the 50-request forced-preemption
trace is BIT-IDENTICAL to the overlap=off n=1 golden at every mesh size,
decode horizon and chunk size. The fast tier covers n∈{1,2,4}, K∈{1,4}
and chunk∈{4,8} across its runs; the slow tier fills in the full cross
product.

Also covered: the one-decode + one-chunk compile-count guard stays
pinned with overlap on; a PR 7-style chaos schedule (seeded digest skew
through the restore rung) replays bit-identically with overlap on; the
``serving_overlap_mb`` tuned key is sigcheck-gated into the PR 15
registry (and a broken protocol — the seg_dropped_signal gallery kernel
— is REFUSED admission); the exposed/overlapped comm split lands in the
metrics.

Wire dtype pinned to fp8, never "auto" (same caveat as the sharded
suite: auto resolves per rank count, a pinned wire makes every run
quantize identically).
"""

import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.serving import ShardedServingEngine, serving_mesh
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.shmem import FaultPlan

pytestmark = [pytest.mark.mesh, pytest.mark.serving]

WATCHDOG_S = 240
N_REQUESTS = 50
MAX_STEPS = 100_000
WIRE = jnp.float8_e4m3fn  # pinned (NOT "auto") — see module docstring

# exactly one compiled program per path, regardless of overlap mode —
# overlap must not fork the program cache
ONE_OF_EACH = {"decode_compiles": 1, "prefill_compiles": 0,
               "prefill_programs": 0, "prefill_chunk_compiles": 1}


@pytest.fixture(autouse=True)
def mesh_watchdog():
    """Per-test SIGALRM wall cap (test_sharded_serving.py pattern)."""
    def boom(signum, frame):
        raise TimeoutError(
            f"mesh watchdog: test exceeded {WATCHDOG_S}s wall — "
            "a mesh collective (or the engine) is hanging")
    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def moe_model():
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _trace(n=N_REQUESTS):
    """The sharded suite's 50-request bursty trace against a 9-page pool:
    growth-driven preemption is forced, not incidental."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(n):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        out.append((i // 2, rng.randint(1, 128, size=plen).tolist(), mnt))
    return out


def _engine(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)          # tight: forces preemption
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _serve(moe_model, tp, sp, ep, **kw):
    eng = _engine(moe_model, tp, sp, ep, **kw)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    return {"tokens": tokens, "compiles": eng.compile_stats,
            "engine": eng, "snap": eng.metrics.snapshot()}


@pytest.fixture(scope="module")
def golden(moe_model):
    """Lazy per-(K, chunk) overlap=off n=1 goldens — each (horizon,
    chunk) pair is its own trace, computed once and shared by the fast
    and slow matrices."""
    cache = {}

    def get(horizon, chunk):
        key = (horizon, chunk)
        if key not in cache:
            cache[key] = _serve(moe_model, 1, 1, 1, decode_horizon=horizon,
                                prefill_chunk=chunk)["tokens"]
        return cache[key]

    return get


def _assert_identical(tokens, gold):
    assert tokens.keys() == gold.keys()
    bad = [r for r in gold if tokens[r] != gold[r]]
    assert not bad, f"token streams diverged from n=1 golden: rids {bad}"


# -- the bit-identity matrix -------------------------------------------------
# fast tier: the two cheapest corners (n=1 degenerate + the canonical n=2
# ep+sp case) keep the quick suite inside the tier-1 time budget; the slow
# tier completes the n∈{1,2,4} × K∈{1,4} × chunk∈{4,8} × mode cross
# product (every combo runs the full 50-request forced-preemption trace).

_FAST = [
    (1, 1, 1, 1, 8, "ep+sp"),
    (1, 1, 2, 1, 8, "ep+sp"),
]
_SLOW = [
    (1, 1, 1, 4, 4, "ep"),
    (1, 1, 1, 4, 8, "ep+sp"),
    (1, 1, 2, 1, 4, "ep+sp"),
    (1, 1, 2, 4, 4, "ep"),
    (1, 1, 2, 4, 8, "ep"),
    (1, 2, 2, 1, 4, "ep"),
    (1, 2, 2, 1, 8, "ep+sp"),
    (1, 2, 2, 4, 4, "ep+sp"),
    (1, 2, 2, 4, 8, "ep+sp"),
]


def _run_matrix_case(moe_model, golden, tp, sp, ep, horizon, chunk, mode):
    run = _serve(moe_model, tp, sp, ep, decode_horizon=horizon,
                 prefill_chunk=chunk, overlap=mode)
    _assert_identical(run["tokens"], golden(horizon, chunk))
    # compile guard: overlap still compiles exactly ONE decode + ONE
    # chunk program at this mesh size
    assert run["compiles"] == ONE_OF_EACH, run["compiles"]
    assert run["engine"].overlap == mode
    assert run["engine"].overlap_microbatches == 2   # the tuned default


@pytest.mark.parametrize("tp,sp,ep,horizon,chunk,mode", _FAST)
def test_overlap_bit_identical(moe_model, golden, tp, sp, ep, horizon,
                               chunk, mode):
    _run_matrix_case(moe_model, golden, tp, sp, ep, horizon, chunk, mode)


@pytest.mark.slow
@pytest.mark.parametrize("tp,sp,ep,horizon,chunk,mode", _SLOW)
def test_overlap_bit_identical_full(moe_model, golden, tp, sp, ep, horizon,
                                    chunk, mode):
    _run_matrix_case(moe_model, golden, tp, sp, ep, horizon, chunk, mode)


# -- chaos replay with overlap on --------------------------------------------

def test_chaos_digest_skew_replay_with_overlap(moe_model):
    """A seeded fault schedule (transient digest skew through the PR 9
    restore rung) replayed with overlap ON: the divergence is absorbed
    exactly once and the tokens still match the overlap=off run of the
    SAME schedule — overlap changes nothing the control plane can see."""
    arrivals = _trace(20)

    def run(overlap):
        eng = _engine(moe_model, 1, 1, 2, journal=ControlJournal(),
                      checkpoint_every=4, digest_every=1, overlap=overlap,
                      fault_plan=FaultPlan(seed=5, digest_skew_at=(9,)))
        toks = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
        return toks, eng.metrics.counters

    toks_off, _ = run("off")
    toks_on, c = run("ep+sp")
    assert c["digest_recoveries"] == 1
    assert c["faults_injected"] >= 1
    assert toks_on == toks_off


# -- tuned-key gate ----------------------------------------------------------

def test_overlap_mb_tuned_key_gated_and_consumed():
    """The microbatch depth is a sigcheck-gated registry key: a clean
    config admits (checked=True) and the engine consumes it; admission
    with a broken protocol runner — the seg_dropped_signal gallery
    kernel, the overlap wire's own hazard — is REFUSED with the
    under_signal finding attached."""
    from triton_dist_tpu.analysis.gallery import GALLERY
    from triton_dist_tpu.aot.registry import (RegistryAdmissionError,
                                              TunedConfigRegistry, TunedKey,
                                              set_default_registry)

    reg = TunedConfigRegistry()
    key = TunedKey("serving_overlap_mb", mesh_shape=(1, 1, 1),
                   dtype=str(jnp.dtype(WIRE)))
    reg.put(key, 4)                       # gate runs 4 seg-a2a rounds
    assert reg.checked(key)

    with pytest.raises(RegistryAdmissionError) as exc:
        reg.put(TunedKey("serving_overlap_mb", mesh_shape=(1, 1, 2),
                         dtype=str(jnp.dtype(WIRE))), 2,
                run=GALLERY["seg_dropped_signal"].run)
    assert "under_signal" in exc.value.finding_kinds
    assert len(reg) == 1                  # the refused config never landed
    set_default_registry(reg)
    try:
        # (num_slots // ep) % 4 == 0 holds at this shape, so the tuned
        # depth is admissible and must win over the built-in default 2
        cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                         n_layers=1, n_heads=4,
                                         n_kv_heads=2, d_ff=128,
                                         max_seq_len=128,
                                         dtype=jnp.float32),
                        num_experts=4, topk=2, moe_d_ff=64)
        params = init_moe_params(jax.random.PRNGKey(0), cfg)
        eng = ShardedServingEngine(params, cfg, serving_mesh(1, 1, 1),
                                   num_slots=4, page_size=8, num_pages=9,
                                   pages_per_seq=4, prefill_chunk=8,
                                   wire_dtype=WIRE, overlap="ep")
        assert eng.overlap_microbatches == 4
    finally:
        set_default_registry(None)


def test_overlap_mb_explicit_overrides_registry(moe_model):
    eng = _engine(moe_model, 1, 1, 1, overlap="ep", overlap_microbatches=1)
    assert eng.overlap_microbatches == 1


def test_overlap_rejects_indivisible_microbatch(moe_model):
    with pytest.raises(AssertionError, match="microbatch"):
        _engine(moe_model, 1, 1, 1, overlap="ep", overlap_microbatches=3)


def test_overlap_rejects_unknown_mode(moe_model):
    with pytest.raises(AssertionError, match="overlap"):
        _engine(moe_model, 1, 1, 1, overlap="sp")


# -- exposed/overlapped comm split -------------------------------------------

def test_comm_split_metrics(moe_model):
    """The modeled wire split (serving/metrics.py ISSUE 16 hists):
    overlap=off exposes everything, overlap=on hides a strictly positive
    share at n>1, and n=1 (no wire) observes zeros on both."""
    def split(tp, sp, ep, overlap):
        eng = _engine(moe_model, tp, sp, ep, overlap=overlap)
        eng.run(max_steps=MAX_STEPS, arrivals=_trace(6))
        s = eng.metrics.snapshot()
        return (s["exposed_comm_us"]["mean"],
                s["overlapped_comm_us"]["mean"])

    exp_off, ovl_off = split(1, 1, 2, "off")
    assert exp_off > 0 and ovl_off == 0
    exp_on, ovl_on = split(1, 1, 2, "ep")
    assert 0 < exp_on < exp_off
    assert ovl_on > 0
    exp1, ovl1 = split(1, 1, 1, "ep+sp")
    assert exp1 == 0 and ovl1 == 0
