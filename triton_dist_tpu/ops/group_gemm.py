"""Grouped (expert) GEMM + MoE token alignment (analog of reference
``sort_topk_ids_align_block_size`` allgather_group_gemm.py:54-139, the
grouped-GEMM consumer kernels :229-316, and csrc's
``moe_ag_scatter_align_block_size`` moe_utils.cu:61-356).

TPU-native design: tokens are sorted by expert and padded so every
``block_m`` row-block belongs to exactly one expert; a scalar-prefetch array
maps each block to its expert, letting the BlockSpec index_map stream the
right expert's weight tile — the Pallas/TPU shape of "grouped GEMM" (cf.
megablox). Sorting/alignment is pure jnp (argsort + one-hot cumsum), not a
hand-written CUDA kernel: it runs on the VPU inside the same jit.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.utils import default_interpret


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class PackedGatedWeights:
    """The [E, H, 2F] interleaved gate‖up layout from ``pack_gated_weights``
    together with the ``block_n`` it was packed with. The interleave is
    invisible in the array's shape, so a bare array cannot be validated by
    the consumer — carrying the pack width in the type is what closes that
    contract: ``grouped_gemm_gated(packed=True)`` and
    ``moe_mlp_ep_overlap`` reject a width mismatch instead of silently
    computing garbage. ``block_n`` is pytree aux data (static under jit)."""

    w: jax.Array
    block_n: int

    def tree_flatten(self):
        return (self.w,), self.block_n

    @classmethod
    def tree_unflatten(cls, block_n, children):
        return cls(children[0], block_n)

    @property
    def shape(self):
        return self.w.shape

    @property
    def dtype(self):
        return self.w.dtype


def align_tokens_by_expert(ids: jax.Array, num_experts: int, block_m: int,
                           with_used_count: bool = False):
    """Sort token indices by expert and pad each expert's run to a multiple
    of ``block_m`` (analog of sort_topk_ids_align_block_size,
    allgather_group_gemm.py:54-139 — there a CPU/CUDA helper, here jnp).

    ids: [T] expert id per row (-1 = invalid/padding row).
    Returns (gather_idx [P], row_valid [P], block_expert [P//block_m]) with
    the *packed* static bound ``P = round_up(T, bm) + E*bm`` (each expert
    wastes < one block of padding; per-expert offsets are runtime values —
    ``block_expert`` is a scalar-prefetch array, so dynamic packing is
    free). Gathered row j participates in expert ``block_expert[j//bm]``'s
    GEMM iff ``row_valid[j]``; blocks past the used range carry no valid
    rows.

    ``with_used_count=True`` appends the runtime used-block bound (see
    ``used_block_count``) as a 4th element, computed from the counts this
    layout already materializes — callers that need both avoid a second
    one-hot pass over ``ids``.

    Host routing tables (numpy ``ids``) take the native C++ path
    (``csrc.moe_align_block_size`` — the analog of the reference's
    registered host op, csrc moe_utils.cu:61-356 via registry.cc:32-44):
    no device round-trip, no one-hot materialization. Traced/device ids
    use the jnp twin below; the two are cross-tested in test_tools.py.
    """
    import numpy as np
    if isinstance(ids, np.ndarray) and not isinstance(ids, jax.Array):
        from triton_dist_tpu import csrc
        res = csrc.native_or_none("moe_align_block_size", ids, num_experts,
                                  block_m)
        if res is not None:
            g, v, b = res
            if not with_used_count:
                return g, v, b
            # out-of-range ids (>= E) are invalid rows in both twins'
            # layouts — they must not count toward the block bound
            in_range = ids[(ids >= 0) & (ids < num_experts)]
            counts = np.bincount(in_range.astype(np.int64),
                                 minlength=num_experts)
            n_used = max(1, int(np.sum(-(-counts // block_m))))
            return g, v, b, np.int32(n_used)
    T = ids.shape[0]
    E = num_experts
    bm = block_m
    P = ((T + bm - 1) // bm) * bm + E * bm
    n_blocks = P // bm
    ids_safe = jnp.where(ids >= 0, ids, E)
    oh = jax.nn.one_hot(ids_safe, E + 1, dtype=jnp.int32)
    rank_in_e = (jnp.cumsum(oh, axis=0) - oh)[jnp.arange(T), ids_safe]
    counts = jnp.sum(oh[:, :E], axis=0)                       # [E]
    blocks_e = (counts + bm - 1) // bm                        # [E]
    block_start = jnp.cumsum(blocks_e) - blocks_e             # [E] (blocks)
    row_start = block_start * bm                              # [E] (rows)
    dest_row = jnp.where(ids >= 0,
                         jnp.take(row_start, jnp.clip(ids_safe, 0, E - 1))
                         + rank_in_e,
                         P)  # invalid rows -> dropped
    gather_idx = jnp.zeros((P,), jnp.int32).at[dest_row].set(
        jnp.arange(T, dtype=jnp.int32), mode="drop")
    row_valid = jnp.zeros((P,), jnp.bool_).at[dest_row].set(True, mode="drop")
    # expert of block i: number of experts whose block range ends at or
    # before i (unused tail blocks get expert E-1; their rows are invalid)
    blk = jnp.arange(n_blocks, dtype=jnp.int32)
    block_expert = jnp.sum(
        (block_start + blocks_e)[None, :] <= blk[:, None], axis=1
    ).astype(jnp.int32)
    block_expert = jnp.clip(block_expert, 0, E - 1)
    if with_used_count:
        n_used = jnp.maximum(1, jnp.sum(blocks_e)).astype(jnp.int32)
        return gather_idx, row_valid, block_expert, n_used
    return gather_idx, row_valid, block_expert


def used_block_count(ids: jax.Array, num_experts: int, block_m: int):
    """Runtime number of ``block_m`` row-blocks that carry any valid rows
    under ``align_tokens_by_expert``'s layout: ``sum_e ceil(count_e / bm)``,
    clamped to ≥1 so downstream dynamic grids are never empty. All blocks at
    or past this index hold only invalid rows — a grouped GEMM bounded by
    it skips up to ``E`` blocks of pure padding (the analog of the
    reference's ``num_tokens_post_padded`` early-exit,
    allgather_group_gemm.py:278-285).

    Standalone form for callers that have no use for the alignment arrays;
    when you need both, pass ``with_used_count=True`` to
    ``align_tokens_by_expert`` instead of paying this one-hot pass twice."""
    E, bm = num_experts, block_m
    ids_safe = jnp.where(ids >= 0, ids, E)
    oh = jax.nn.one_hot(ids_safe, E + 1, dtype=jnp.int32)
    counts = jnp.sum(oh[:, :E], axis=0)
    return jnp.maximum(1, jnp.sum((counts + bm - 1) // bm)).astype(jnp.int32)


def _gemm_block(t_blk, w_blk, sc_row, out_dtype):
    """THE grouped-GEMM accumulator body, shared by the bounded and
    unbounded paths: f32 MXU accumulate, optional per-row dequant scale
    fold (``sc_row`` [block_m] f32 or None), cast to ``out_dtype``."""
    acc = jnp.dot(t_blk[...], w_blk[0], preferred_element_type=jnp.float32)
    if sc_row is not None:
        acc = acc * sc_row[:, None]
    return acc.astype(out_dtype)


def _gated_math(g, u, sc_row, out_dtype, activation):
    """THE gated epilogue, shared by every gated path (unbounded, bounded,
    packed, K-split): optional per-row dequant scale folded into BOTH f32
    accumulators (scaling commutes with each matmul, and
    ``act(s·g)·(s·u)`` IS the dequantized math), activation in f32, one
    cast out."""
    if sc_row is not None:
        g = g * sc_row[:, None]
        u = u * sc_row[:, None]
    return (activation(g) * u).astype(out_dtype)


def _gated_block(t_blk, wg_blk, wu_blk, sc_row, out_dtype, activation):
    """Fused gate+up accumulator body: BOTH expert projections of one row
    block against the SAME resident x-tile, activation applied in f32
    before anything leaves VMEM — ``act(x@wg) * (x@wu)`` never stages the
    two [bm, bn] halves in HBM (vs the reference's separate gate/up GEMM
    launches + elementwise pass)."""
    g = jnp.dot(t_blk[...], wg_blk[0], preferred_element_type=jnp.float32)
    u = jnp.dot(t_blk[...], wu_blk[0], preferred_element_type=jnp.float32)
    return _gated_math(g, u, sc_row, out_dtype, activation)


def emit_grouped_gemm(t_ref, w_ref, o_ref, be_ref, base_blk,
                      block_m: int, block_n: int, out_dtype=None,
                      n_blocks_used=None, sc_ref=None,
                      block_k: int | None = None, acc_ref=None):
    """In-kernel pipelined grouped GEMM over HBM refs:
    ``o[i*bm:(i+1)*bm] = t[i*bm:(i+1)*bm] @ w[be_ref[base_blk + i]]``.

    ``be_ref`` is an SMEM int32 ref of per-block expert ids (flattened over
    segments; ``base_blk`` offsets into it, may be a traced value). The
    dynamic index_map streams each block's expert weight tile HBM→VMEM
    double-buffered — the in-kernel form of ``grouped_gemm`` that the fused
    MoE overlap kernels call per *arrived segment*, the TPU analog of the
    reference's per-token-block ``dl.wait`` + grouped ``tl.dot``
    (kernel_consumer_m_parallel_scatter_group_gemm,
    allgather_group_gemm.py:229-316).

    ``n_blocks_used`` (traced scalar, e.g. ``used_block_count``'s result read
    from SMEM) truncates the row-block grid at runtime: padding blocks past
    it are neither DMA'd nor computed (reference parity:
    ``num_tokens_post_padded`` early-exit, allgather_group_gemm.py:278-285).
    Output rows past ``n_blocks_used * block_m`` are left UNWRITTEN — the
    caller must mask by row validity (``apply_grouped`` and the fused MoE
    unscrambles already do).

    ``sc_ref`` (optional [P // block_m, block_m] f32 ref) folds a per-row
    dequant scale into the accumulator — see ``grouped_gemm.row_scale``.

    ``block_k`` splits the contraction: x strips become (block_m, block_k)
    and weight tiles (block_k, block_n), with the k grid dimension
    innermost accumulating into ``acc_ref`` (caller-allocated
    [block_m, block_n] f32 VMEM scratch — f32 partials, one cast at the
    end). This is what lets block_m/block_n grow past the full-K strip's
    scoped-VMEM cliff (a (256, 7168) bf16 x strip alone double-buffers to
    ~7 MB; measured OOM at 17.6 MB round 5)."""
    import math

    P, H = t_ref.shape
    E, H2, N = w_ref.shape
    assert H == H2, (H, H2)
    block_n = math.gcd(min(block_n, N), N)
    assert P % block_m == 0, (P, block_m)
    out_dtype = out_dtype or o_ref.dtype
    m_steps = (P // block_m if n_blocks_used is None
               else jnp.minimum(n_blocks_used, P // block_m))
    sc_specs3 = ([pl.BlockSpec((1, block_m), lambda i, j, k: (i, 0))]
                 if sc_ref is not None else [])
    sc_args = (sc_ref,) if sc_ref is not None else ()

    if block_k is not None and block_k < H:
        assert H % block_k == 0, (H, block_k)
        assert acc_ref is not None, "block_k needs an f32 VMEM acc_ref"
        nk = H // block_k

        def body_acc(t_blk, w_blk, *rest):
            o_blk = rest[-1]
            sc_row = rest[0][0] if sc_ref is not None else None
            k = pl.program_id(2)
            part = jnp.dot(t_blk[...], w_blk[0],
                           preferred_element_type=jnp.float32)

            @pl.when(k == 0)
            def _():
                acc_ref[...] = part

            @pl.when(k > 0)
            def _():
                acc_ref[...] = acc_ref[...] + part

            @pl.when(k == nk - 1)
            def _():
                acc = acc_ref[...]
                if sc_row is not None:
                    acc = acc * sc_row[:, None]
                o_blk[...] = acc.astype(out_dtype)

        pltpu.emit_pipeline(
            body_acc,
            grid=(m_steps, N // block_n, nk),
            in_specs=[
                pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
                pl.BlockSpec((1, block_k, block_n),
                             lambda i, j, k: (be_ref[base_blk + i], k, j)),
            ] + sc_specs3,
            out_specs=[pl.BlockSpec((block_m, block_n),
                                    lambda i, j, k: (i, j))],
        )(t_ref, w_ref, *sc_args, o_ref)
        return

    def body(t_blk, w_blk, *rest):
        o_blk = rest[-1]
        sc_row = rest[0][0] if sc_ref is not None else None
        o_blk[...] = _gemm_block(t_blk, w_blk, sc_row, out_dtype)

    sc_specs = ([pl.BlockSpec((1, block_m), lambda i, j: (i, 0))]
                if sc_ref is not None else [])
    pltpu.emit_pipeline(
        body,
        grid=(m_steps, N // block_n),
        in_specs=[
            pl.BlockSpec((block_m, H), lambda i, j: (i, 0)),
            pl.BlockSpec((1, H, block_n),
                         lambda i, j: (be_ref[base_blk + i], 0, j)),
        ] + sc_specs,
        out_specs=[pl.BlockSpec((block_m, block_n), lambda i, j: (i, j))],
    )(t_ref, w_ref, *sc_args, o_ref)


def grouped_gemm(tokens: jax.Array, weights: jax.Array,
                 block_expert: jax.Array, block_m: int = 128,
                 block_n: int = 128, out_dtype=None,
                 n_blocks_used: jax.Array | None = None,
                 row_scale: jax.Array | None = None,
                 masked: bool = True,
                 block_k: int | None = None) -> jax.Array:
    """``out[i*bm:(i+1)*bm] = tokens[i*bm:(i+1)*bm] @ weights[block_expert[i]]``.

    tokens: [P, H] (expert-aligned rows), weights: [E, H, N],
    block_expert: [P // block_m] int32. The scalar-prefetch index_map streams
    each block's expert weight tile HBM→VMEM double-buffered (grid analog of
    the reference's ``kernel_consumer_m_parallel_scatter_group_gemm``,
    allgather_group_gemm.py:229-316).

    ``n_blocks_used`` (traced int32 scalar from ``used_block_count``)
    truncates the row-block walk at runtime, skipping the up-to-``E`` blocks
    of pure per-expert padding in the aligned layout — rows past the bound
    are returned ZEROED (callers mask by row validity anyway; zero keeps the
    op total-function for reuse in autodiff contexts). ``masked=False``
    skips that zeroing pass (a full read+write of the output) and leaves
    rows past the bound UNDEFINED — for callers whose scatter-back already
    drops invalid rows by index (``apply_grouped``'s out-of-range ``src``
    with ``mode="drop"`` never reads them).

    ``row_scale`` ([P] f32) folds a per-row dequantization scale into the
    f32 accumulator: ``out_row = scale · (q_row @ w)``. Per-row scaling
    commutes with the matmul, so quantized-wire tokens (fp8/int8 rows from
    an EP dispatch with ``dequant_edge="expert"``) feed the MXU directly —
    no standalone dequant pass, halved token-read bytes, and the scale is
    applied once in f32 exactly like the reference's expert GEMM consumes
    its scale side-channel (README.md:55 fp8 protocol)."""
    import math

    P, H = tokens.shape
    E, H2, N = weights.shape
    assert H == H2, (H, H2)
    # ragged N (e.g. a 192-wide TP shard): fall back to the largest common
    # divisor, like flash_decode's block_s handling
    block_n = math.gcd(min(block_n, N), N)
    assert P % block_m == 0, (P, block_m)
    # quantized rows can't default the output to their own (wire) dtype —
    # follow the weights' compute dtype instead (bf16 weights → bf16 out,
    # f32 pipeline → f32 out)
    out_dtype = out_dtype or (tokens.dtype if row_scale is None
                              else weights.dtype)
    sc2d = (None if row_scale is None
            else row_scale.astype(jnp.float32).reshape(P // block_m,
                                                       block_m))
    n_sc = 0 if sc2d is None else 1

    if n_blocks_used is None:
        assert block_k is None or block_k >= H, (
            "block_k (K-split) is implemented on the runtime-bounded path "
            "only — pass n_blocks_used (the serving path always does)")

        def kernel(be_ref, *refs):
            o_ref = refs[-1]
            t_ref, w_ref = refs[:2]
            sc_row = refs[2][0] if n_sc else None
            o_ref[...] = _gemm_block(t_ref, w_ref, sc_row, out_dtype)

        grid = (P // block_m, N // block_n)
        sc_specs = ([pl.BlockSpec((1, block_m), lambda i, j, be: (i, 0))]
                    if n_sc else [])
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=[
                    pl.BlockSpec((block_m, H), lambda i, j, be: (i, 0)),
                    pl.BlockSpec((1, H, block_n),
                                 lambda i, j, be: (be[i], 0, j)),
                ] + sc_specs,
                out_specs=pl.BlockSpec((block_m, block_n),
                                       lambda i, j, be: (i, j)),
            ),
            out_shape=jax.ShapeDtypeStruct((P, N), out_dtype),
            cost_estimate=pl.CostEstimate(
                flops=2 * P * H * N,
                bytes_accessed=(P * H + E * H * N + P * N)
                * jnp.dtype(tokens.dtype).itemsize,
                transcendentals=0),
            interpret=default_interpret(),
        )(block_expert, tokens, weights, *(() if sc2d is None else (sc2d,)))

    # runtime-bounded path: zero-init the output, then emit_pipeline over a
    # dynamic grid — padding blocks cost neither DMA nor MXU work
    # block_n was gcd-clamped above — safe for the scratch shape directly
    nb = jnp.asarray(n_blocks_used, jnp.int32).reshape(1)
    ksplit = block_k is not None and block_k < H

    def kernel(be_ref, nb_ref, *refs):
        o_ref = refs[-1] if not ksplit else refs[-2]
        acc = refs[-1] if ksplit else None
        t_ref, w_ref = refs[:2]
        sc_ref = refs[2] if n_sc else None
        emit_grouped_gemm(t_ref, w_ref, o_ref, be_ref, 0, block_m, block_n,
                          out_dtype, n_blocks_used=nb_ref[0],
                          sc_ref=sc_ref, block_k=block_k, acc_ref=acc)

    out = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)]
        + [pl.BlockSpec(memory_space=pl.ANY)] * n_sc,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=([pltpu.VMEM((block_m, block_n), jnp.float32)]
                        if ksplit else []),
        out_shape=jax.ShapeDtypeStruct((P, N), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * P * H * N,
            bytes_accessed=(P * H + E * H * N + P * N)
            * jnp.dtype(tokens.dtype).itemsize,
            transcendentals=0),
        interpret=default_interpret(),
    )(block_expert, nb, tokens, weights,
      *(() if sc2d is None else (sc2d,)))
    if not masked:
        return out
    # rows past the bound were never written; zero them so the result is a
    # total function of the inputs
    row_blk = jnp.arange(P, dtype=jnp.int32) // block_m
    return jnp.where((row_blk < nb[0])[:, None], out,
                     jnp.zeros((), out_dtype))


def pack_gated_weights(w_gate: jax.Array, w_up: jax.Array,
                       block_n: int = 128) -> PackedGatedWeights:
    """Interleave gate and up weights into ONE [E, H, 2F] array whose
    column groups alternate [g_j ‖ u_j] per ``block_n``-wide tile — the
    layout ``grouped_gemm_gated(packed=True)`` consumes. Two separate
    weight streams (one DMA sequence per projection) measured ~545 GB/s
    on v5e vs the dense GEMM's ~740; packing merges them into one
    double-width tile stream. Pack ONCE at weight-load time (serving
    weights are static).

    Returns a ``PackedGatedWeights`` wrapper carrying ``block_n`` so the
    consumer can verify the pack width instead of trusting the caller to
    thread the same value to both sides."""
    E, H, F = w_gate.shape
    assert w_up.shape == (E, H, F), (w_up.shape, w_gate.shape)
    # STRICT: no silent re-tiling — the interleave is invisible in the
    # shape, so the pack width must be carried alongside the array (the
    # wrapper) and re-checked by the consumer
    assert F % block_n == 0, (
        f"pack_gated_weights: block_n={block_n} must divide F={F} exactly "
        "(and must equal the block_n passed to grouped_gemm_gated)")
    bn = block_n
    g = w_gate.reshape(E, H, F // bn, 1, bn)
    u = w_up.reshape(E, H, F // bn, 1, bn)
    return PackedGatedWeights(
        jnp.concatenate([g, u], axis=3).reshape(E, H, 2 * F), block_n)


def grouped_gemm_gated(tokens: jax.Array, w_gate: jax.Array,
                       w_up: jax.Array | None, block_expert: jax.Array,
                       block_m: int = 128, block_n: int = 128,
                       out_dtype=None,
                       n_blocks_used: jax.Array | None = None,
                       row_scale: jax.Array | None = None,
                       activation=jax.nn.silu,
                       masked: bool = True,
                       block_k: int | None = None,
                       packed: bool = False,
                       prefetch_depth: int = 2) -> jax.Array:
    """Fused gated grouped GEMM: ``out = act(x @ wg[e]) * (x @ wu[e])`` per
    expert-aligned row block — the gate and up projections of the MoE FFN in
    ONE kernel. Each x-tile is read from HBM once and contracted against
    both experts' weight tiles while resident in VMEM; the activation and
    elementwise product happen on the f32 accumulators before the result is
    cast — no intermediate gate/up arrays in HBM, no separate activation
    pass, one kernel launch instead of two (the reference runs gate and up
    as separate grouped GEMM launches plus an elementwise kernel,
    test_ep_moe_inference.py FFN; this fusion is the TPU-shaped cut).

    Signature follows ``grouped_gemm``: w_gate/w_up [E, H, F]; ``row_scale``
    folds a per-row wire-dequant scale into BOTH accumulators (scaling
    commutes with each matmul, and ``act(s·g)·(s·u)`` IS the dequantized
    math); ``n_blocks_used`` bounds the row-block walk at runtime;
    ``masked=False`` leaves rows past the bound undefined (see
    ``grouped_gemm``).

    ``packed=True``: ``w_gate`` is the ``PackedGatedWeights`` wrapper from
    ``pack_gated_weights(..., block_n)`` (``w_up`` must be None) — gate
    and up tiles ride ONE double-width DMA stream instead of two
    interleaved sequences (the measured ~545 GB/s two-stream rate vs the
    dense GEMM's ~740 is the gap this targets). Bounded path only; the
    wrapper's pack width is VERIFIED against ``block_n`` (a bare [E, H,
    2F] array is still accepted for internal callers, where divisibility
    is the only possible check).

    ``prefetch_depth`` (packed path): number of weight tiles kept in
    flight by the kernel's own multi-buffered DMA stream. Depth ≥ 2
    replaces the emit_pipeline weight stream with explicit
    ``make_async_copy`` lookahead that crosses expert-block boundaries
    without re-priming (the grouped dynamic-expert index_map is what
    keeps the generic pipeline's prefetch shallow — measured ~545 GB/s vs
    the dense GEMM's ~740). Depth is clamped to the VMEM budget; 1 (or a
    non-packed layout) falls back to the emit_pipeline stream."""
    import math

    P, H = tokens.shape
    if packed:
        assert w_up is None, "packed layout carries gate AND up in w_gate"
        assert n_blocks_used is not None, (
            "packed gated GEMM is implemented on the bounded path only")
        if isinstance(w_gate, PackedGatedWeights):
            assert w_gate.block_n == block_n, (
                f"PackedGatedWeights packed with block_n={w_gate.block_n} "
                f"but the kernel was asked for block_n={block_n} — the "
                "interleave would silently mix gate and up columns")
            w_gate = w_gate.w
        E, H2, F2 = w_gate.shape
        assert F2 % 2 == 0, F2
        F = F2 // 2
        assert F % block_n == 0, (
            f"block_n={block_n} must divide F={F}")
        # Divisibility is necessary but NOT sufficient for a bare array —
        # prefer passing the PackedGatedWeights wrapper, which carries
        # the actual pack width and is verified above.
    else:
        E, H2, F = w_gate.shape
        assert w_up.shape == (E, H2, F), (w_up.shape, w_gate.shape)
        block_n = math.gcd(min(block_n, F), F)
    assert H == H2, (H, H2)
    assert P % block_m == 0, (P, block_m)
    out_dtype = out_dtype or (tokens.dtype if row_scale is None
                              else w_gate.dtype)
    sc2d = (None if row_scale is None
            else row_scale.astype(jnp.float32).reshape(P // block_m,
                                                       block_m))
    n_sc = 0 if sc2d is None else 1
    cost = pl.CostEstimate(
        flops=4 * P * H * F,
        bytes_accessed=(P * H + 2 * E * H * F + P * F)
        * jnp.dtype(tokens.dtype).itemsize,
        transcendentals=P * F)

    if n_blocks_used is None:
        assert block_k is None or block_k >= H, (
            "block_k (K-split) is implemented on the runtime-bounded path "
            "only — pass n_blocks_used (the serving path always does)")
        def kernel(be_ref, *refs):
            o_ref = refs[-1]
            t_ref, wg_ref, wu_ref = refs[:3]
            sc_row = refs[3][0] if n_sc else None
            o_ref[...] = _gated_block(t_ref, wg_ref, wu_ref, sc_row,
                                      out_dtype, activation)

        sc_specs = ([pl.BlockSpec((1, block_m), lambda i, j, be: (i, 0))]
                    if n_sc else [])
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(P // block_m, F // block_n),
                in_specs=[
                    pl.BlockSpec((block_m, H), lambda i, j, be: (i, 0)),
                    pl.BlockSpec((1, H, block_n),
                                 lambda i, j, be: (be[i], 0, j)),
                    pl.BlockSpec((1, H, block_n),
                                 lambda i, j, be: (be[i], 0, j)),
                ] + sc_specs,
                out_specs=pl.BlockSpec((block_m, block_n),
                                       lambda i, j, be: (i, j)),
            ),
            out_shape=jax.ShapeDtypeStruct((P, F), out_dtype),
            cost_estimate=cost,
            interpret=default_interpret(),
        )(block_expert, tokens, w_gate, w_up,
          *(() if sc2d is None else (sc2d,)))

    nb = jnp.asarray(n_blocks_used, jnp.int32).reshape(1)
    ksplit = block_k is not None and block_k < H
    if ksplit:
        assert H % block_k == 0, (H, block_k)
    # quantized-wire x (fp8/int8 vs bf16 weights): Mosaic re-converts the
    # x tile before the MXU once per (m, n[, k]) step, re-paying the VPU
    # convert F/block_n times per strip (the measured cost that cancelled
    # the halved read bytes, docs/benchmarks.md expert-edge table).
    # Convert ONCE per m-step into a compute-dtype VMEM scratch at the
    # first n-step and feed the MXU from it.
    convert_once = (n_sc == 1
                    and jnp.dtype(tokens.dtype).itemsize
                    < jnp.dtype(w_gate.dtype).itemsize
                    and F // block_n > 1)
    cdtype = w_gate.dtype
    n_w = 1 if packed else 2
    # Deep weight-stream prefetch (packed layout only): keep ``depth``
    # double-width weight tiles in flight via an explicit DMA ring instead
    # of emit_pipeline's single-step lookahead. The ring is clamped so it
    # plus the pipelined x strips stays under the scoped-VMEM budget; if
    # even 2 tiles don't fit, fall back to the emit_pipeline stream.
    bk_w = block_k if ksplit else H
    _w_tile_bytes = bk_w * 2 * block_n * jnp.dtype(w_gate.dtype).itemsize
    deep_depth = 0
    if packed and prefetch_depth is not None and prefetch_depth >= 2:
        _budget = 9 * 1024 * 1024
        deep_depth = min(int(prefetch_depth), _budget // _w_tile_bytes)
    deep = deep_depth >= 2
    if not deep:
        deep_depth = 0

    def split_w(w_blks):
        """(gate tile, up tile) from the weight block(s) — packed layout
        splits the double-width tile's columns."""
        if packed:
            w = w_blks[0][0]
            return w[:, :block_n], w[:, block_n:]
        return w_blks[0][0], w_blks[1][0]

    def kernel(be_ref, nb_ref, *refs):
        n_scr = ((1 if convert_once else 0) + (2 if ksplit else 0)
                 + (2 if deep else 0))
        scratch = refs[len(refs) - n_scr:] if n_scr else ()
        refs = refs[:len(refs) - n_scr]
        xcv = scratch[0] if convert_once else None
        w_buf, w_sem = (scratch[-2], scratch[-1]) if deep else (None, None)
        if ksplit:
            acc_g, acc_u = ((scratch[-4], scratch[-3]) if deep
                            else (scratch[-2], scratch[-1]))
        else:
            acc_g = acc_u = None
        o_ref = refs[-1]
        t_ref = refs[0]
        w_refs = refs[1:1 + n_w]
        sc_ref = refs[1 + n_w] if n_sc else None
        m_steps = jnp.minimum(nb_ref[0], P // block_m)
        sc_args = (sc_ref,) if sc_ref is not None else ()

        # --- deep mode: explicit multi-buffered weight DMA ring.
        # Flat step s walks the SAME (m, n[, k]) order as the pipeline
        # grid; the copy for step s+depth-1 is issued at the TOP of step
        # s (the guide's double-buffer shape generalized to depth): the
        # slot it overwrites was last read at step s-1, already consumed.
        # The dynamic-expert lookup ``be_ref[i]`` happens at ISSUE time,
        # so the ring keeps streaming across expert-block boundaries —
        # the re-priming that capped the two-stream rate at ~545 GB/s.
        nn_steps = F // block_n
        nk_steps = (H // block_k) if ksplit else 1

        def w_dma(s):
            i = s // (nn_steps * nk_steps)
            r = s % (nn_steps * nk_steps)
            j = r // nk_steps
            kk = r % nk_steps
            slot = s % deep_depth
            src = w_refs[0].at[be_ref[i], pl.ds(kk * bk_w, bk_w),
                               pl.ds(j * 2 * block_n, 2 * block_n)]
            return pltpu.make_async_copy(src, w_buf.at[slot],
                                         w_sem.at[slot])

        def w_stream(s, n_steps):
            """Warm the ring at step 0, issue the lookahead copy, wait
            for this step's tile; returns the resident (bk_w, 2bn)
            tile."""
            @pl.when(s == 0)
            def _():
                for d in range(deep_depth - 1):
                    @pl.when(d < n_steps)
                    def _(d=d):
                        w_dma(d).start()

            @pl.when(s + deep_depth - 1 < n_steps)
            def _():
                w_dma(s + deep_depth - 1).start()

            w_dma(s).wait()
            return w_buf[s % deep_depth]

        if ksplit:
            nk = H // block_k
            n_wp = 0 if deep else n_w

            def body_acc(t_blk, *rest):
                o_blk = rest[-1]
                w_blks = rest[:n_wp]
                sc_row = rest[n_wp][0] if sc_ref is not None else None
                k = pl.program_id(2)
                if convert_once:
                    j = pl.program_id(1)

                    @pl.when(j == 0)
                    def _():
                        xcv[k, :, :] = t_blk[...].astype(cdtype)

                    x_use = xcv[k, :, :]
                else:
                    x_use = t_blk[...]
                if deep:
                    i = pl.program_id(0)
                    j2 = pl.program_id(1)
                    s = (i * nn_steps + j2) * nk_steps + k
                    wtile = w_stream(s, m_steps * nn_steps * nk_steps)
                    wg_t, wu_t = wtile[:, :block_n], wtile[:, block_n:]
                else:
                    wg_t, wu_t = split_w(w_blks)
                g = jnp.dot(x_use, wg_t,
                            preferred_element_type=jnp.float32)
                u = jnp.dot(x_use, wu_t,
                            preferred_element_type=jnp.float32)

                @pl.when(k == 0)
                def _():
                    acc_g[...] = g
                    acc_u[...] = u

                @pl.when(k > 0)
                def _():
                    acc_g[...] = acc_g[...] + g
                    acc_u[...] = acc_u[...] + u

                @pl.when(k == nk - 1)
                def _():
                    o_blk[...] = _gated_math(acc_g[...], acc_u[...],
                                             sc_row, out_dtype, activation)

            sc_specs = ([pl.BlockSpec((1, block_m),
                                      lambda i, j, k: (i, 0))]
                        if sc_ref is not None else [])
            w_specs = ([] if deep else
                       ([pl.BlockSpec((1, block_k, 2 * block_n),
                                      lambda i, j, k: (be_ref[i], k, j))]
                        if packed else
                        [pl.BlockSpec((1, block_k, block_n),
                                      lambda i, j, k: (be_ref[i], k, j))]
                        * 2))
            pltpu.emit_pipeline(
                body_acc,
                grid=(m_steps, F // block_n, nk),
                in_specs=[
                    pl.BlockSpec((block_m, block_k),
                                 lambda i, j, k: (i, k)),
                ] + w_specs + sc_specs,
                out_specs=[pl.BlockSpec((block_m, block_n),
                                        lambda i, j, k: (i, j))],
            )(t_ref, *(() if deep else tuple(w_refs)), *sc_args, o_ref)
            return

        n_wp = 0 if deep else n_w

        def body(t_blk, *rest):
            o_blk = rest[-1]
            w_blks = rest[:n_wp]
            sc_row = rest[n_wp][0] if sc_ref is not None else None
            if convert_once:
                j = pl.program_id(1)

                @pl.when(j == 0)
                def _():
                    xcv[...] = t_blk[...].astype(cdtype)

                x_use = xcv[...]
            else:
                x_use = t_blk[...]
            if deep:
                i = pl.program_id(0)
                j2 = pl.program_id(1)
                s = i * nn_steps + j2
                wtile = w_stream(s, m_steps * nn_steps)
                wg_t, wu_t = wtile[:, :block_n], wtile[:, block_n:]
            else:
                wg_t, wu_t = split_w(w_blks)
            g = jnp.dot(x_use, wg_t, preferred_element_type=jnp.float32)
            u = jnp.dot(x_use, wu_t, preferred_element_type=jnp.float32)
            o_blk[...] = _gated_math(g, u, sc_row, out_dtype, activation)

        sc_specs = ([pl.BlockSpec((1, block_m), lambda i, j: (i, 0))]
                    if sc_ref is not None else [])
        w_specs = ([] if deep else
                   ([pl.BlockSpec((1, H, 2 * block_n),
                                  lambda i, j: (be_ref[i], 0, j))]
                    if packed else
                    [pl.BlockSpec((1, H, block_n),
                                  lambda i, j: (be_ref[i], 0, j))] * 2))
        pltpu.emit_pipeline(
            body,
            grid=(m_steps, F // block_n),
            in_specs=[
                pl.BlockSpec((block_m, H), lambda i, j: (i, 0)),
            ] + w_specs + sc_specs,
            out_specs=[pl.BlockSpec((block_m, block_n),
                                    lambda i, j: (i, j))],
        )(t_ref, *(() if deep else tuple(w_refs)), *sc_args, o_ref)

    w_args = (w_gate,) if packed else (w_gate, w_up)
    out = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)]
        + [pl.BlockSpec(memory_space=pl.ANY)] * n_w
        + [pl.BlockSpec(memory_space=pl.ANY)] * n_sc,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=(
            ([pltpu.VMEM(((H // block_k, block_m, block_k) if ksplit
                          else (block_m, H)), cdtype)]
             if convert_once else [])
            + ([pltpu.VMEM((block_m, block_n), jnp.float32)] * 2
               if ksplit else [])
            + ([pltpu.VMEM((deep_depth, bk_w, 2 * block_n), w_gate.dtype),
                pltpu.SemaphoreType.DMA((deep_depth,))]
               if deep else [])),
        out_shape=jax.ShapeDtypeStruct((P, F), out_dtype),
        cost_estimate=cost,
        interpret=default_interpret(),
    )(block_expert, nb, tokens, *w_args,
      *(() if sc2d is None else (sc2d,)))
    if not masked:
        return out
    row_blk = jnp.arange(P, dtype=jnp.int32) // block_m
    return jnp.where((row_blk < nb[0])[:, None], out,
                     jnp.zeros((), out_dtype))


def apply_grouped(tokens: jax.Array, ids: jax.Array, num_experts: int, fn,
                  block_m: int = 128,
                  row_scale: jax.Array | None = None,
                  gather_dtype=None) -> jax.Array:
    """The shared align→gather→mask→compute→scatter-back sequence every MoE
    op needs: align rows by expert, call ``fn(x_aligned, block_expert,
    n_blocks_used) -> y_aligned`` (one or more grouped GEMMs sharing the
    alignment, runtime-bounded by the used-block count), and scatter results
    back to the original row order (invalid ids → zero rows). Returns
    [T, N].

    ``row_scale`` ([T] f32, quantized-wire rows): gathered through the same
    alignment and passed to ``fn(x, block_expert, nb, scale_aligned)`` so
    the grouped GEMMs can fold the dequant into their accumulators
    (``grouped_gemm.row_scale``); ``tokens`` then stay in the wire dtype
    end to end.

    ``gather_dtype``: cast the gathered rows inside the (fused) gather
    pass — the free place to leave a wire dtype the downstream kernels
    cannot consume (measured round 5: Mosaic rejects fp8 x-strips in the
    grouped pipelines on this toolchain; int8 compiles). The scale
    contract is unchanged — dequant still rides the accumulators."""
    T = tokens.shape[0]
    gather_idx, row_valid, block_expert, nb = align_tokens_by_expert(
        ids, num_experts, block_m, with_used_count=True)
    P_rows = gather_idx.shape[0]
    vmask = row_valid[:, None]
    x = jnp.where(vmask, tokens[gather_idx], 0).astype(gather_dtype
                                                       or tokens.dtype)
    if row_scale is not None:
        s = jnp.where(row_valid, row_scale.astype(jnp.float32)[gather_idx],
                      1.0)
        y = fn(x, block_expert, nb, s)
    else:
        y = fn(x, block_expert, nb)
    # Scatter-back is a GATHER by the inverse permutation: each source row
    # lands in at most one aligned slot, so ``out[t] = y[dest_row[t]]``
    # with out-of-range fill for unrouted rows. The scatter-add spelling
    # (`out.at[src].add`) measured 1.5 ms at the DeepSeek serving shape —
    # TPU scatter serializes; the inverse gather is a plain take. The
    # tiny int scatter building dest_row ([P] int32) is noise.
    dest_row = jnp.full((T,), P_rows, jnp.int32).at[
        jnp.where(row_valid, gather_idx, T)].set(
        jnp.arange(P_rows, dtype=jnp.int32), mode="drop")
    return jnp.take(y, dest_row, axis=0, mode="fill", fill_value=0)


def moe_ffn_local(tokens: jax.Array, ids: jax.Array, w_up: jax.Array,
                  w_down: jax.Array, block_m: int = 128,
                  activation=jax.nn.silu) -> jax.Array:
    """Per-device MoE FFN over locally-present tokens: grouped up-projection,
    activation, grouped down-projection, rows restored to their original
    positions. ``ids`` may contain -1 for padding rows (they produce zeros).
    Building block for the EP layer and the MoE overlap ops."""
    E = w_up.shape[0]

    def ffn(x, block_expert, nb):
        h = grouped_gemm(x, w_up, block_expert, block_m=block_m,
                         n_blocks_used=nb)
        h = activation(h.astype(jnp.float32)).astype(tokens.dtype)
        return grouped_gemm(h, w_down, block_expert, block_m=block_m,
                            n_blocks_used=nb)

    return apply_grouped(tokens, ids, E, ffn, block_m=block_m)


__all__ = ["align_tokens_by_expert", "used_block_count", "emit_grouped_gemm",
           "grouped_gemm", "grouped_gemm_gated", "pack_gated_weights",
           "PackedGatedWeights", "apply_grouped", "moe_ffn_local"]
