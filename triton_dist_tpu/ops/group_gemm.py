"""Grouped (expert) GEMM + MoE token alignment (analog of reference
``sort_topk_ids_align_block_size`` allgather_group_gemm.py:54-139, the
grouped-GEMM consumer kernels :229-316, and csrc's
``moe_ag_scatter_align_block_size`` moe_utils.cu:61-356).

TPU-native design: tokens are sorted by expert and padded so every
``block_m`` row-block belongs to exactly one expert; a scalar-prefetch array
maps each block to its expert, letting the BlockSpec index_map stream the
right expert's weight tile — the Pallas/TPU shape of "grouped GEMM" (cf.
megablox). Sorting/alignment is pure jnp (argsort + one-hot cumsum), not a
hand-written CUDA kernel: it runs on the VPU inside the same jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.utils import default_interpret


def align_tokens_by_expert(ids: jax.Array, num_experts: int, block_m: int):
    """Sort token indices by expert and pad each expert's run to a multiple
    of ``block_m`` (analog of sort_topk_ids_align_block_size,
    allgather_group_gemm.py:54-139 — there a CPU/CUDA helper, here jnp).

    ids: [T] expert id per row (-1 = invalid/padding row).
    Returns (gather_idx [P], row_valid [P], block_expert [P//block_m]) with
    the *packed* static bound ``P = round_up(T, bm) + E*bm`` (each expert
    wastes < one block of padding; per-expert offsets are runtime values —
    ``block_expert`` is a scalar-prefetch array, so dynamic packing is
    free). Gathered row j participates in expert ``block_expert[j//bm]``'s
    GEMM iff ``row_valid[j]``; blocks past the used range carry no valid
    rows.
    """
    T = ids.shape[0]
    E = num_experts
    bm = block_m
    P = ((T + bm - 1) // bm) * bm + E * bm
    n_blocks = P // bm
    ids_safe = jnp.where(ids >= 0, ids, E)
    oh = jax.nn.one_hot(ids_safe, E + 1, dtype=jnp.int32)
    rank_in_e = (jnp.cumsum(oh, axis=0) - oh)[jnp.arange(T), ids_safe]
    counts = jnp.sum(oh[:, :E], axis=0)                       # [E]
    blocks_e = (counts + bm - 1) // bm                        # [E]
    block_start = jnp.cumsum(blocks_e) - blocks_e             # [E] (blocks)
    row_start = block_start * bm                              # [E] (rows)
    dest_row = jnp.where(ids >= 0,
                         jnp.take(row_start, jnp.clip(ids_safe, 0, E - 1))
                         + rank_in_e,
                         P)  # invalid rows -> dropped
    gather_idx = jnp.zeros((P,), jnp.int32).at[dest_row].set(
        jnp.arange(T, dtype=jnp.int32), mode="drop")
    row_valid = jnp.zeros((P,), jnp.bool_).at[dest_row].set(True, mode="drop")
    # expert of block i: number of experts whose block range ends at or
    # before i (unused tail blocks get expert E-1; their rows are invalid)
    blk = jnp.arange(n_blocks, dtype=jnp.int32)
    block_expert = jnp.sum(
        (block_start + blocks_e)[None, :] <= blk[:, None], axis=1
    ).astype(jnp.int32)
    block_expert = jnp.clip(block_expert, 0, E - 1)
    return gather_idx, row_valid, block_expert


def emit_grouped_gemm(t_ref, w_ref, o_ref, be_ref, base_blk,
                      block_m: int, block_n: int, out_dtype=None):
    """In-kernel pipelined grouped GEMM over HBM refs:
    ``o[i*bm:(i+1)*bm] = t[i*bm:(i+1)*bm] @ w[be_ref[base_blk + i]]``.

    ``be_ref`` is an SMEM int32 ref of per-block expert ids (flattened over
    segments; ``base_blk`` offsets into it, may be a traced value). The
    dynamic index_map streams each block's expert weight tile HBM→VMEM
    double-buffered — the in-kernel form of ``grouped_gemm`` that the fused
    MoE overlap kernels call per *arrived segment*, the TPU analog of the
    reference's per-token-block ``dl.wait`` + grouped ``tl.dot``
    (kernel_consumer_m_parallel_scatter_group_gemm,
    allgather_group_gemm.py:229-316)."""
    import math

    P, H = t_ref.shape
    E, H2, N = w_ref.shape
    assert H == H2, (H, H2)
    block_n = math.gcd(min(block_n, N), N)
    assert P % block_m == 0, (P, block_m)
    out_dtype = out_dtype or o_ref.dtype

    def body(t_blk, w_blk, o_blk):
        o_blk[...] = jnp.dot(t_blk[...], w_blk[0],
                             preferred_element_type=jnp.float32
                             ).astype(out_dtype)

    pltpu.emit_pipeline(
        body,
        grid=(P // block_m, N // block_n),
        in_specs=[
            pl.BlockSpec((block_m, H), lambda i, j: (i, 0)),
            pl.BlockSpec((1, H, block_n),
                         lambda i, j: (be_ref[base_blk + i], 0, j)),
        ],
        out_specs=[pl.BlockSpec((block_m, block_n), lambda i, j: (i, j))],
    )(t_ref, w_ref, o_ref)


def grouped_gemm(tokens: jax.Array, weights: jax.Array,
                 block_expert: jax.Array, block_m: int = 128,
                 block_n: int = 128, out_dtype=None) -> jax.Array:
    """``out[i*bm:(i+1)*bm] = tokens[i*bm:(i+1)*bm] @ weights[block_expert[i]]``.

    tokens: [P, H] (expert-aligned rows), weights: [E, H, N],
    block_expert: [P // block_m] int32. The scalar-prefetch index_map streams
    each block's expert weight tile HBM→VMEM double-buffered (grid analog of
    the reference's ``kernel_consumer_m_parallel_scatter_group_gemm``,
    allgather_group_gemm.py:229-316).
    """
    import math

    P, H = tokens.shape
    E, H2, N = weights.shape
    assert H == H2, (H, H2)
    # ragged N (e.g. a 192-wide TP shard): fall back to the largest common
    # divisor, like flash_decode's block_s handling
    block_n = math.gcd(min(block_n, N), N)
    assert P % block_m == 0, (P, block_m)
    out_dtype = out_dtype or tokens.dtype

    def kernel(be_ref, t_ref, w_ref, o_ref):
        o_ref[...] = jnp.dot(t_ref[...], w_ref[0],
                             preferred_element_type=jnp.float32
                             ).astype(out_dtype)

    grid = (P // block_m, N // block_n)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_m, H), lambda i, j, be: (i, 0)),
                pl.BlockSpec((1, H, block_n), lambda i, j, be: (be[i], 0, j)),
            ],
            out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, be: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((P, N), out_dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * P * H * N,
            bytes_accessed=(P * H + E * H * N + P * N)
            * jnp.dtype(tokens.dtype).itemsize,
            transcendentals=0),
        interpret=default_interpret(),
    )(block_expert, tokens, weights)


def apply_grouped(tokens: jax.Array, ids: jax.Array, num_experts: int, fn,
                  block_m: int = 128) -> jax.Array:
    """The shared align→gather→mask→compute→scatter-back sequence every MoE
    op needs: align rows by expert, call ``fn(x_aligned, block_expert) ->
    y_aligned`` (one or more grouped GEMMs sharing the alignment), and
    scatter results back to the original row order (invalid ids → zero
    rows). Returns [T, N]."""
    T = tokens.shape[0]
    gather_idx, row_valid, block_expert = align_tokens_by_expert(
        ids, num_experts, block_m)
    x = tokens[gather_idx] * row_valid[:, None].astype(tokens.dtype)
    y = fn(x, block_expert)
    out = jnp.zeros((T, y.shape[-1]), y.dtype)
    src = jnp.where(row_valid, gather_idx, T)
    return out.at[src].add(y * row_valid[:, None].astype(y.dtype),
                           mode="drop")


def moe_ffn_local(tokens: jax.Array, ids: jax.Array, w_up: jax.Array,
                  w_down: jax.Array, block_m: int = 128,
                  activation=jax.nn.silu) -> jax.Array:
    """Per-device MoE FFN over locally-present tokens: grouped up-projection,
    activation, grouped down-projection, rows restored to their original
    positions. ``ids`` may contain -1 for padding rows (they produce zeros).
    Building block for the EP layer and the MoE overlap ops."""
    E = w_up.shape[0]

    def ffn(x, block_expert):
        h = grouped_gemm(x, w_up, block_expert, block_m=block_m)
        h = activation(h.astype(jnp.float32)).astype(tokens.dtype)
        return grouped_gemm(h, w_down, block_expert, block_m=block_m)

    return apply_grouped(tokens, ids, E, ffn, block_m=block_m)


__all__ = ["align_tokens_by_expert", "emit_grouped_gemm", "grouped_gemm",
           "apply_grouped", "moe_ffn_local"]
