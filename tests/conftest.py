"""Test bootstrap: force an 8-device virtual CPU mesh.

The distributed kernels run in Pallas TPU interpret mode on CPU devices —
this is the single-process cluster simulator the reference lacks (its tests
need real GPUs + torchrun; see SURVEY.md §4).

The container's axon sitecustomize eagerly initializes the single-chip TPU
backend at interpreter start, so setting JAX_PLATFORMS=cpu in the
environment is not enough — we re-point jax at CPU and drop the cached
backend before any test imports run.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(12, skip_if_satisfied=False)

assert jax.device_count() == 12, (
    f"expected 12 virtual CPU devices, got {jax.devices()}"
)

# NOTE: kernel tests build meshes over a *subset* of the 12 virtual devices.
# The Pallas TPU interpreter's device threads can deadlock when every device
# thread simultaneously blocks in semaphore waits/barriers (threads pile up
# in the interpreter's internal _barrier/_allocate_buffer); keeping spare
# non-participating devices avoids it — 8 participants out of 12 devices is
# verified reliable, 8/8 is not. Most tests use a 4-way mesh for speed;
# TEST_WORLD_WIDE exercises the driver's exact 8-way configuration
# (tests/test_eight_way.py).
TEST_WORLD = 4
TEST_WORLD_WIDE = 8
