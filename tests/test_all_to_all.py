"""EP All-to-All dispatch/combine tests (parity targets: reference
test/nvidia/test_all_to_all.py, test_ep_a2a.py — dispatch correctness against
a dense golden, then a full dispatch→expert-compute→combine round trip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.all_to_all import (
    all_to_all_push, combine, create_all_to_all_context, dispatch,
    route_tokens)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def test_all_to_all_push_collective(ctx):
    """Wire collective: out[src] on device d == in[d] on device src.
    Golden: jax.lax.all_to_all."""
    n = ctx.num_ranks
    x = jax.random.normal(jax.random.key(0), (n * n, 8, 128), jnp.float32)
    xs = ctx.shard(x, P("x"))
    (y,) = jax.jit(lambda v: all_to_all_push(ctx, v))(xs)

    def g(shard):
        return jax.lax.all_to_all(shard, "x", split_axis=0, concat_axis=0,
                                  tiled=True)
    golden = jax.jit(ctx.shard_map(g, in_specs=P("x"), out_specs=P("x")))(xs)
    assert_allclose(np.asarray(y), np.asarray(golden))


def test_route_tokens_slots_unique(ctx):
    a2a = create_all_to_all_context(ctx, max_tokens=16, hidden=128, topk=2,
                                    num_experts=ctx.num_ranks * 2)
    ids = jax.random.randint(jax.random.key(1), (16, 2), 0, a2a.num_experts)
    dest, slot, valid = route_tokens(a2a, ids)
    # within one destination rank, slots must be unique
    d, s = np.asarray(dest).reshape(-1), np.asarray(slot).reshape(-1)
    for r in range(a2a.n_ranks):
        ss = s[d == r]
        assert len(set(ss.tolist())) == len(ss), f"dup slots for rank {r}"


def _moe_golden(tokens, topk_ids, topk_w, expert_scale):
    """Dense golden: expert e multiplies token by expert_scale[e]."""
    t = np.asarray(tokens, np.float32)
    out = np.zeros_like(t)
    ids, w = np.asarray(topk_ids), np.asarray(topk_w, np.float32)
    for i in range(t.shape[0]):
        acc = 0.0
        for j in range(ids.shape[1]):
            acc = acc + w[i, j] * (t[i] * expert_scale[ids[i, j]])
        out[i] = acc
    return out


@pytest.mark.quick
def test_dispatch_combine_roundtrip(ctx):
    """Full EP MoE round trip with a linear 'expert' (scale per expert):
    dispatch → per-rank processing of received tokens → combine. Matches the
    dense golden exactly in f32."""
    n = ctx.num_ranks
    T, H, k, E = 8, 128, 2, n * 2
    a2a = create_all_to_all_context(ctx, max_tokens=T, hidden=H, topk=k,
                                    num_experts=E, dtype=jnp.float32)
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    # distinct experts per token (sample without replacement per row)
    keys = jax.random.split(jax.random.key(1), n * T)
    topk_ids = jnp.stack([jax.random.permutation(kk, E)[:k] for kk in keys])
    topk_w = jax.nn.softmax(
        jax.random.normal(jax.random.key(2), (n * T, k)), axis=-1)

    tokens_s = ctx.shard(tokens, P("x"))
    ids_s = ctx.shard(topk_ids, P("x"))
    w_s = ctx.shard(topk_w, P("x"))

    expert_scale = jnp.arange(1.0, E + 1.0, dtype=jnp.float32)  # scale per expert

    def process(recv_tok, recv_ids):
        # recv_tok [n, cap, H], recv_ids [n, cap] local expert ids (or -1)
        me_base = jax.lax.axis_index("x") * a2a.experts_per_rank
        gid = jnp.where(recv_ids >= 0, recv_ids + me_base, 0)
        scale = expert_scale[gid] * (recv_ids >= 0)
        return recv_tok * scale[..., None]

    @jax.jit
    def run(tokens_s, ids_s, w_s):
        recv_tok, recv_ids, layout = dispatch(a2a, tokens_s, ids_s)
        proc = ctx.shard_map(process, in_specs=(P("x"), P("x")),
                             out_specs=P("x"))(recv_tok, recv_ids)
        return combine(a2a, proc, layout, w_s)

    out = run(tokens_s, ids_s, w_s)
    golden = _moe_golden(tokens, topk_ids, topk_w,
                         np.asarray(expert_scale))
    assert_allclose(np.asarray(out), golden, atol=1e-4, rtol=1e-4)


@pytest.mark.quick
@pytest.mark.parametrize("wire", [jnp.float8_e4m3fn, jnp.int8])
def test_dispatch_combine_quantized_wire(ctx, wire):
    """fp8/int8 wire with per-token scale side-channel (reference
    low_latency_all_to_all.py:60-88 fp8+scales protocol): dispatch→combine
    roundtrip stays within quantization error of the bf16 path."""
    n = ctx.num_ranks
    T, H, topk = n * 8, 256, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x",
                                    dtype=jnp.bfloat16, wire_dtype=wire)
    assert a2a.capacity % 32 == 0  # 1-byte wire tiling

    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32
                               ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(1), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk

    def roundtrip(t, i, ww):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, ww)

    out = jax.jit(roundtrip)(ctx.shard(tokens, P("x")),
                             ctx.shard(ids, P("x")), ctx.shard(w, P("x")))
    # identity processing → combine ≈ original tokens, up to 2x quantization
    # (dispatch + return trip). e4m3 has ~2 mantissa-bit error ≈ 6%.
    assert_allclose(np.asarray(out, np.float32),
                    np.asarray(tokens, np.float32), rtol=0.15, atol=0.15)


def test_quantized_wire_preserves_ids(ctx):
    n = ctx.num_ranks
    T, topk = n * 4, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=128,
                                    topk=topk, num_experts=2 * n, axis="x",
                                    wire_dtype=jnp.float8_e4m3fn)
    tokens = jnp.ones((T, 128), jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(2), (T, topk), 0, 2 * n)
    bf = create_all_to_all_context(ctx, max_tokens=T // n, hidden=128,
                                   topk=topk, num_experts=2 * n, axis="x")
    _, ids_q, _ = jax.jit(lambda t, i: dispatch(a2a, t, i))(
        ctx.shard(tokens, P("x")), ctx.shard(ids, P("x")))
    _, ids_b, _ = jax.jit(lambda t, i: dispatch(bf, t, i))(
        ctx.shard(tokens, P("x")), ctx.shard(ids, P("x")))
    # same routing metadata regardless of wire dtype (capacities match: both
    # round T/n*topk=8 up to their tile)
    q, b = np.asarray(ids_q), np.asarray(ids_b)
    assert sorted(q[q >= 0].tolist()) == sorted(b[b >= 0].tolist())


def test_quantized_wire_fused_dequant_aligned_cap(ctx):
    """capacity=128 + dequant_edge="kernel" hits the IN-KERNEL per-arrival
    dequant path (sub-128 caps take the post-kernel fallback — both must
    agree with the bf16 roundtrip within quantization error)."""
    n = ctx.num_ranks
    T, H, topk = n * 8, 256, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x",
                                    capacity=128, dtype=jnp.bfloat16,
                                    wire_dtype=jnp.float8_e4m3fn,
                                    dequant_edge="kernel")
    assert a2a.capacity == 128 and a2a._dequant_in_kernel()

    tokens = jax.random.normal(jax.random.key(5), (T, H), jnp.float32
                               ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(6), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk

    def roundtrip(t, i, ww):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, ww)

    out = jax.jit(roundtrip)(ctx.shard(tokens, P("x")),
                             ctx.shard(ids, P("x")), ctx.shard(w, P("x")))
    assert_allclose(np.asarray(out, np.float32),
                    np.asarray(tokens, np.float32), rtol=0.15, atol=0.15)


def test_dispatch_combine_capacity_drop_semantics(ctx):
    """Over-capacity routing must DROP the excess (token, k) pairs, not
    corrupt surviving slots: with every token targeting rank 0 and
    capacity < T, combine returns w_sum_of_survivors * token for survivors
    and exactly zero for fully-dropped tokens (standard expert-capacity
    semantics; the reference instead sizes for worst case — capacity =
    max_tokens * topk — which create_all_to_all_context defaults to)."""
    n = ctx.num_ranks
    # slots are per (src, dst) pair: with every (token, k) pair of a source
    # targeting rank 0, source-local demand is (T/n)*topk = 16 pairs into
    # cap=8 slots — a genuine 2x overflow (8 is the f32 sublane-tile floor,
    # so _cap_round keeps it)
    T, H, topk, cap = n * 8, 128, 2, 8
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=n,
                                    capacity=cap, axis="x",
                                    dtype=jnp.float32)
    cap = a2a.capacity  # post-rounding
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    # every (token, k) pair -> expert 0 (rank 0): source-local demand is
    # 2 * 8 = 16 pairs into `cap` slots
    ids = jnp.zeros((T, topk), jnp.int32)
    w = jnp.full((T, topk), 0.5)

    def roundtrip(t, i, ww):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, ww), layout[2]

    out, valid = jax.jit(roundtrip)(ctx.shard(tokens, P("x")),
                                    ctx.shard(ids, P("x")),
                                    ctx.shard(w, P("x")))
    out, valid = np.asarray(out), np.asarray(valid)
    demand = (T // n) * topk
    assert demand > cap, (demand, cap)  # the test must actually overflow
    # per source shard: exactly cap pairs survive, in slot-assign order
    assert valid.reshape(n, -1).sum(axis=1).tolist() == [cap] * n
    toks = np.asarray(tokens)
    surv_w = valid.reshape(T, topk).sum(axis=1) * 0.5
    np.testing.assert_allclose(out, toks * surv_w[:, None], rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("quant_edge", ["pre", "fused"])
@pytest.mark.parametrize("dequant_edge", ["kernel", "post"])
def test_quantized_wire_edge_strategies(ctx, quant_edge, dequant_edge):
    """Every (quant_edge, dequant_edge) wiring of the fp8 wire produces the
    same roundtrip result: "pre" quantizes source rows then gathers, "fused"
    quantizes per gathered slot — identical scales bit-for-bit (same
    reduction over the same row); dequant in-kernel vs post-pass is pure
    placement. The measured-best wiring (docs/benchmarks.md fp8-edge table)
    is the default; the others must stay correct to remain selectable."""
    n = ctx.num_ranks
    T, H, topk = n * 8, 256, 2
    mk = lambda qe, de: create_all_to_all_context(
        ctx, max_tokens=T // n, hidden=H, topk=topk, num_experts=2 * n,
        axis="x", capacity=128, dtype=jnp.bfloat16,
        wire_dtype=jnp.float8_e4m3fn, quant_edge=qe, dequant_edge=de)
    a2a = mk(quant_edge, dequant_edge)
    ref = mk("pre", "post")

    tokens = jax.random.normal(jax.random.key(9), (T, H), jnp.float32
                               ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(10), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk

    def roundtrip(c, t, i, ww):
        recv, _, layout = dispatch(c, t, i)
        return combine(c, recv, layout, ww)

    args = (ctx.shard(tokens, P("x")), ctx.shard(ids, P("x")),
            ctx.shard(w, P("x")))
    out = jax.jit(lambda *a: roundtrip(a2a, *a))(*args)
    gold = jax.jit(lambda *a: roundtrip(ref, *a))(*args)
    assert_allclose(np.asarray(out, np.float32),
                    np.asarray(gold, np.float32), atol=1e-6, rtol=1e-6)
    assert_allclose(np.asarray(out, np.float32),
                    np.asarray(tokens, np.float32), rtol=0.15, atol=0.15)


def test_expected_capacity_sizing(ctx):
    """expected_capacity gives a tuned per-pair slot budget (balanced load
    × headroom, wire-tile rounded) and composes with the context + dispatch
    without drops under balanced routing."""
    from triton_dist_tpu.ops.all_to_all import expected_capacity
    n = ctx.num_ranks
    T_loc, topk = 32, 2
    cap = expected_capacity(n, T_loc, topk, headroom=2.0)
    assert cap < T_loc * topk          # strictly below the worst case
    assert cap % 16 == 0               # bf16 wire tile rounding
    assert expected_capacity(n, T_loc, topk, wire_dtype=jnp.int8) % 32 == 0
    # small n: clamped to the drop-proof worst case, never beyond
    assert expected_capacity(1, T_loc, topk, headroom=2.0) == T_loc * topk

    a2a = create_all_to_all_context(ctx, max_tokens=T_loc, hidden=128,
                                    topk=topk, num_experts=n,
                                    capacity=cap, axis="x")
    T = n * T_loc
    tokens = jnp.ones((T, 128), jnp.bfloat16)
    # balanced routing: expert e for row r = r % n (== rank r % n)
    ids = (jnp.arange(T)[:, None] + jnp.arange(topk)[None, :]) % n
    _, recv_ids, (dest, slot, valid) = jax.jit(
        lambda t, i: dispatch(a2a, t, i))(
        ctx.shard(tokens, P("x")), ctx.shard(ids.astype(jnp.int32), P("x")))
    assert bool(jnp.all(valid)), "balanced routing must not drop at 2x headroom"


# ---------------------------------------------------------------------------
# fused send-edge quantization (quant_edge="kernel" / all_to_all_push
# quant_from) and the expert-major capacity layout
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_push_fused_send_quant_matches_unfused(ctx):
    """``all_to_all_push(quant_from=...)`` with the in-collective send-edge
    quantization must produce the SAME wire bytes and scales as the
    ``fuse_quant=False`` standalone-qpack fallback — bit-for-bit (both run
    the ``_quant`` row math; the fused path just runs it per departure
    slot inside the collective)."""
    n = ctx.num_ranks
    cap, H = 128, 256
    x = jax.random.normal(jax.random.key(0), (n * n, cap, H), jnp.float32)
    xs = ctx.shard(x, P("x"))
    for wq in (jnp.float8_e4m3fn, jnp.int8):
        q1, s1 = jax.jit(lambda v: all_to_all_push(ctx, v, quant_from=wq))(xs)
        q0, s0 = jax.jit(lambda v: all_to_all_push(
            ctx, v, quant_from=wq, fuse_quant=False))(xs)
        assert q1.dtype == jnp.dtype(wq) and q1.shape == q0.shape
        np.testing.assert_array_equal(np.asarray(q1).view(np.uint8),
                                      np.asarray(q0).view(np.uint8))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s0))

    # fused quant + fused dequant roundtrip == unfused both edges
    y1, _ = jax.jit(lambda v: all_to_all_push(
        ctx, v, quant_from=jnp.float8_e4m3fn, dequant_to=jnp.float32))(xs)
    y0, _ = jax.jit(lambda v: all_to_all_push(
        ctx, v, quant_from=jnp.float8_e4m3fn, dequant_to=jnp.float32,
        fuse_quant=False, fuse_dequant=False))(xs)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y0))


def test_quant_tile_pipelines_match_xla_golden():
    """The kernel's per-slot quant/dequant emit_pipelines — the exact tile
    programs ``_a2a_kernel`` runs at the send and receive edges — are
    bit-identical to the jitted XLA ``_quant``/``_dequant`` reference on a
    single device. This is the piece of the fused-edge contract the
    simulator CAN check directly (the collective around it falls back to
    XLA on backends without a remote-DMA interpreter)."""
    from jax.experimental import pallas as pl
    from triton_dist_tpu.ops.all_to_all import (
        _dequant, _dequant_slot_pipeline, _quant, _quant_slot_pipeline)
    from triton_dist_tpu.utils import default_interpret

    cap, H = 256, 384
    x = jax.random.normal(jax.random.key(3), (cap, H), jnp.float32)
    x = x.at[7].set(0.0)  # zero row -> scale-1 rule
    for wq in (jnp.float8_e4m3fn, jnp.int8):
        def qk(xr, qr, sr):
            _quant_slot_pipeline(xr, qr, sr, jnp.dtype(wq), cap, H)

        q, s = pl.pallas_call(
            qk,
            out_shape=(jax.ShapeDtypeStruct((cap, H), wq),
                       jax.ShapeDtypeStruct((cap // 128, 128), jnp.float32)),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
            interpret=default_interpret(),
        )(x)
        q0, s0 = jax.jit(lambda v: _quant(v, jnp.dtype(wq)))(x)
        np.testing.assert_array_equal(np.asarray(q).view(np.uint8),
                                      np.asarray(q0).view(np.uint8))
        np.testing.assert_array_equal(np.asarray(s).reshape(-1),
                                      np.asarray(s0))

        import math
        bn = math.gcd(512, H)

        def dk(qr, sr, orf):
            _dequant_slot_pipeline(qr, sr, orf, jnp.bfloat16, cap, H, bn)

        y = pl.pallas_call(
            dk,
            out_shape=jax.ShapeDtypeStruct((cap, H), jnp.bfloat16),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            interpret=default_interpret(),
        )(q, s)
        y0 = jax.jit(lambda a, b: _dequant(a, b, jnp.bfloat16))(q0, s0)
        np.testing.assert_array_equal(np.asarray(y).view(np.uint16),
                                      np.asarray(y0).view(np.uint16))


def test_quant_edge_kernel_strategy(ctx):
    """quant_edge="kernel" (send-edge quantization inside the collective)
    composes with both dequant edges and stays within quantization error of
    the identity roundtrip — and its routing metadata matches the "fused"
    gather edge exactly."""
    n = ctx.num_ranks
    T, H, topk = n * 8, 256, 2
    tokens = jax.random.normal(jax.random.key(11), (T, H), jnp.float32
                               ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(12), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk
    args = (ctx.shard(tokens, P("x")), ctx.shard(ids, P("x")),
            ctx.shard(w, P("x")))

    def roundtrip(c, t, i, ww):
        recv, rids, layout = dispatch(c, t, i)
        return combine(c, recv, layout, ww), rids

    outs = {}
    for qe in ("kernel", "fused"):
        a2a = create_all_to_all_context(
            ctx, max_tokens=T // n, hidden=H, topk=topk, num_experts=2 * n,
            axis="x", capacity=128, dtype=jnp.bfloat16,
            wire_dtype=jnp.float8_e4m3fn, quant_edge=qe)
        outs[qe], rids = jax.jit(lambda *a, c=a2a: roundtrip(c, *a))(*args)
    assert_allclose(np.asarray(outs["kernel"], np.float32),
                    np.asarray(tokens, np.float32), rtol=0.15, atol=0.15)
    assert_allclose(np.asarray(outs["kernel"], np.float32),
                    np.asarray(outs["fused"], np.float32),
                    rtol=2e-2, atol=2e-2)


@pytest.mark.quick
def test_expert_major_layout_and_roundtrip(ctx):
    """expert_major=True: every (src, dst) capacity block arrives
    expert-segmented — rows [e*cap_e, (e+1)*cap_e) hold local expert e —
    and the full dispatch→expert-scale→combine roundtrip matches both the
    rank-major layout and the dense golden (ample capacity: no drops)."""
    n = ctx.num_ranks
    T, H, k, E = 32, 256, 2, 2 * n
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n * T, k), 0, E)
    w = jax.nn.softmax(jax.random.normal(jax.random.key(2), (n * T, k)), -1)
    args = (ctx.shard(tokens, P("x")), ctx.shard(ids, P("x")),
            ctx.shard(w, P("x")))

    def roundtrip(a2a, t, i, ww):
        recv, rids, layout = dispatch(a2a, t, i)
        epr = a2a.experts_per_rank

        def proc(r, il):
            gid = il + jax.lax.axis_index("x") * epr
            f = jnp.where(il >= 0, (gid + 1).astype(jnp.float32), 0.0)
            return (r.astype(jnp.float32) * f[..., None]).astype(r.dtype)

        pr = ctx.shard_map(proc, in_specs=(P("x"), P("x")),
                           out_specs=P("x"))(
            recv.reshape(n * n, a2a.capacity, H), rids)
        return combine(a2a, pr, layout, ww), rids

    outs = {}
    for em in (False, True):
        a2a = create_all_to_all_context(ctx, max_tokens=T, hidden=H, topk=k,
                                        num_experts=E, capacity=T * k,
                                        dtype=jnp.float32, expert_major=em)
        if em:
            cap_e, epr = a2a.capacity_per_expert, a2a.experts_per_rank
            assert a2a.capacity == cap_e * epr
            # routing: slots stay inside their expert's segment
            dest, slot, valid = route_tokens(a2a, ids[:T])
            s, v = np.asarray(slot).reshape(-1), np.asarray(valid).reshape(-1)
            le = np.asarray(ids[:T]).reshape(-1) % epr
            assert np.all((s[v] // cap_e) == le[v])
        outs[em], rids = jax.jit(lambda *a, c=a2a: roundtrip(c, *a))(*args)
        if em:
            # receive blocks are expert-segmented (or -1 padding)
            ri = np.asarray(rids).reshape(n, n, a2a.capacity)
            seg = np.arange(a2a.capacity) // cap_e
            assert (((ri < 0) | (ri == seg[None, None, :]))).all()

    golden = _moe_golden(tokens, ids, w,
                         np.arange(1.0, E + 1.0, dtype=np.float32))
    for em in (False, True):
        assert_allclose(np.asarray(outs[em]), golden, atol=2e-4, rtol=2e-4)


def test_expert_major_per_expert_drop_semantics(ctx):
    """Under expert_major the budget is per (src, dst, EXPERT): skewing all
    tokens onto one expert drops past cap_e (not past the whole per-rank
    capacity), while the same skew on the rank-major layout survives up to
    ``capacity``. That is the documented trade-off for capping multinomial
    spill at the source."""
    n = ctx.num_ranks
    T, H, k = n * 16, 128, 1
    ids = jnp.zeros((T, k), jnp.int32)       # everything -> global expert 0
    caps = {}
    for em in (False, True):
        a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                        topk=k, num_experts=2 * n,
                                        capacity=16, axis="x",
                                        dtype=jnp.float32, expert_major=em)
        sm = ctx.shard_map(lambda i: route_tokens(a2a, i)[2],
                           in_specs=P("x"), out_specs=P("x"))
        valid = np.asarray(jax.jit(sm)(ctx.shard(ids, P("x"))))
        caps[em] = int(valid.reshape(n, -1).sum(axis=1)[0])
        budget = (a2a.capacity_per_expert if em else a2a.capacity)
        assert caps[em] == min(T // n, budget), (em, caps[em], budget)
    assert caps[True] < caps[False], caps  # finer budget drops sooner


def test_slot_gather_nonfinite_containment():
    """S2 contract: a non-finite source row is clamped (NaN→0, ±Inf→±max)
    BEFORE the slot gather, so it cannot poison other slots through the
    MXU one-hot contraction (0.0·Inf = NaN would hit EVERY slot), and the
    MXU and take-gather twins stay bit-comparable."""
    from triton_dist_tpu.ops.all_to_all import (_MXU_GATHER_MAX_ROWS,
                                                _slot_gather,
                                                _slot_gather_quant)
    R, H, n_dst, cap = 16, 128, 2, 8
    rows = jax.random.normal(jax.random.key(0), (R, H), jnp.float32)
    rows = rows.at[3, 5].set(jnp.nan).at[4, 7].set(jnp.inf)
    src = jnp.arange(n_dst * cap, dtype=jnp.int32).reshape(n_dst, cap) % R
    assert R <= _MXU_GATHER_MAX_ROWS     # MXU one-hot path

    out = np.asarray(jax.jit(
        lambda r, s: _slot_gather(r, s, jnp.float32))(rows, src))
    assert np.isfinite(out).all()
    # clean rows arrive exactly; the poisoned rows arrive clamped
    ref = np.asarray(jnp.nan_to_num(rows))
    np.testing.assert_array_equal(out.reshape(-1, H), ref[np.asarray(src).reshape(-1)])

    q, s = jax.jit(
        lambda r, m: _slot_gather_quant(r, m, jnp.float8_e4m3fn))(rows, src)
    assert np.isfinite(np.asarray(s)).all()
    assert np.isfinite(np.asarray(q, np.float32)).all()


@pytest.mark.quick
def test_wire_dtype_auto_crossover(ctx):
    """``wire_dtype="auto"`` resolves per message size from the per-dtype
    wire fits (bench.py ``a2a_wire_fit`` shape): below the crossover the
    fp8 quant/dequant + scale-wire latency loses and the bf16 wire is
    kept; above it the halved payload bytes win."""
    from triton_dist_tpu.ops.all_to_all import (a2a_wire_bytes,
                                                pick_wire_dtype)

    # fp8 pays 40 µs of fixed latency, both segments at 100 GB/s: the
    # crossover sits where the saved bytes cover 40 µs (= 4 MB saved)
    fit = {"bf16": {"t0_us": 5.0, "gb_per_s": 100.0},
           "fp8": {"t0_us": 45.0, "gb_per_s": 100.0}}
    n = 4
    small = pick_wire_dtype(n, max_tokens=8, hidden=256, topk=2,
                            wire_fit=fit)
    big = pick_wire_dtype(n, max_tokens=2048, hidden=7168, topk=8,
                          wire_fit=fit)
    assert small is None
    assert big == jnp.dtype(jnp.float8_e4m3fn)
    # sanity: the byte model agrees with the decision at both sizes
    for toks, h, k, picked in ((8, 256, 2, small), (2048, 7168, 8, big)):
        t16 = 5.0 + a2a_wire_bytes(n, toks, h, k, None) / 100e3
        t8 = 45.0 + a2a_wire_bytes(n, toks, h, k, jnp.float8_e4m3fn) / 100e3
        assert (t16 <= t8) == (picked is None)

    # end to end: "auto" lands in the context as a concrete dtype and the
    # quantized roundtrip still works
    a2a = create_all_to_all_context(ctx, max_tokens=2048, hidden=7168,
                                    topk=8, num_experts=2 * ctx.num_ranks,
                                    wire_dtype="auto", wire_fit=fit)
    assert a2a.wire_dtype == jnp.dtype(jnp.float8_e4m3fn)
    small_ctx = create_all_to_all_context(
        ctx, max_tokens=8, hidden=256, topk=2,
        num_experts=2 * ctx.num_ranks, wire_dtype="auto", wire_fit=fit)
    assert small_ctx.wire_dtype is None
