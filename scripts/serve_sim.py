"""Synthetic-trace replay through the continuous-batching serving engine
(docs/serving.md). Generates a deterministic request trace (seeded prompt
lengths / decode budgets / staggered arrivals), drives ``ServingEngine``
to completion, and prints the metrics snapshot as ONE JSON line — the same
counters/histograms bench.py's ``serving_*`` extras are built from, with
matching knobs (--slots/--page-size/--layers mirror bench_serving's).

    python scripts/serve_sim.py --sim 50
    python scripts/serve_sim.py --sim 20 --slots 8 --pages 12  # preempts
    python scripts/serve_sim.py --sim 20 --model moe --mesh 1x2x2
    python scripts/serve_sim.py --sim 20 --disagg --mesh 1x2x1  # composed
    python scripts/serve_sim.py --sim 30 --crash-at 25 --recover  # ISSUE 9
    python scripts/serve_sim.py --sim 40 --queue-cap 6 --ttl 50  # overload

A deliberately small --pages forces preemption-by-eviction; the replay is
bit-deterministic (same seed => same tokens, same metrics counters), which
is also how tests/test_serving.py pins the trace down. ``--mesh TPxSPxEP``
serves the MoE model through ``ShardedServingEngine`` under shard_map
(docs/serving.md "Sharded serving"); the replay stays bit-identical across
mesh shapes when --wire is pinned (``auto`` resolves per rank count).
"""
import argparse
import json
import sys

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from triton_dist_tpu.models.llama import LlamaConfig, init_params  # noqa: E402
from triton_dist_tpu.serving import ServingEngine  # noqa: E402

p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
p.add_argument("--sim", type=int, default=50,
               help="number of synthetic requests to replay")
p.add_argument("--slots", type=int, default=4,
               help="continuous-batching slots (engine batch rows)")
p.add_argument("--page-size", type=int, default=8,
               help="KV pool page size in tokens (multiple of 8)")
p.add_argument("--pages", type=int, default=24,
               help="usable KV pool pages (small => forced preemption)")
p.add_argument("--pages-per-seq", type=int, default=8,
               help="block-table width (max pages one request may own)")
p.add_argument("--layers", type=int, default=2, help="model layers")
p.add_argument("--max-new", type=int, default=12,
               help="max decode budget per request (uniform 2..max-new)")
p.add_argument("--arrive-every", type=int, default=2,
               help="one new request submitted every N engine steps")
p.add_argument("--seed", type=int, default=0, help="trace RNG seed")
p.add_argument("--tokens", action="store_true",
               help="also print one JSON line per finished request")
p.add_argument("--decode-horizon", type=int, default=1,
               help="K: scanned decode steps per host dispatch")
p.add_argument("--speculate", default=None, metavar="K",
               help="model-free speculative decoding (ISSUE 20): draft up "
                    "to K-1 tokens per slot from the bigram prompt-lookup "
                    "drafter and verify ALL K positions in the one decode "
                    "dispatch (exact-match-greedy accept) — an integer K "
                    "or 'auto' (tuned registry, workload-bucketed). "
                    "Tokens stay bit-identical to greedy; only the "
                    "dispatch count moves. Prints a spec panel "
                    "(accepted/dispatch, draft hit rate, rewinds) to "
                    "stderr. Owns the horizon (needs --decode-horizon 1); "
                    "not plumbed through --disagg")
p.add_argument("--prefill-buckets", default="pow2",
               help='"pow2" (default), "exact", or a comma-separated '
                    "ascending list of bucket lengths, e.g. 8,16,32")
p.add_argument("--prefill-chunk", type=int, default=None,
               help="chunked paged prefill: tokens per co-scheduled chunk "
                    "(≤1 chunk per step rides beside the decode dispatch; "
                    "omit for the bucketed inline prefill path)")
p.add_argument("--disagg", action="store_true",
               help="disaggregated prefill/decode over a 2-rank role mesh "
                    "(KV handed off by page migration; needs >= 2 devices; "
                    "--prefill-chunk defaults to 2*page_size here — chunks "
                    "ARE the migration unit)")
p.add_argument("--model", choices=("llama", "moe"), default="llama",
               help="'moe' serves MoEConfig.tiny through the sharded "
                    "engine (EP MoE FFN; defaults --mesh to 1x1x1)")
p.add_argument("--mesh", default=None, metavar="TPxSPxEP",
               help="serve under shard_map on this TP/SP/EP mesh, e.g. "
                    "2x2x2 (implies --model moe; spins up tp*sp*ep "
                    "virtual CPU devices when hardware has fewer; "
                    "--prefill-chunk defaults to 8 — the sharded engine "
                    "REQUIRES the chunked path). Combine with --disagg "
                    "for the COMPOSED engine: disaggregated prefill "
                    "feeding a sharded decode fleet on this one mesh")
p.add_argument("--wire", choices=("auto", "fp8", "none"), default="auto",
               help="A2A wire dtype for --mesh: 'auto' (wire-fit driven, "
                    "resolves PER RANK COUNT), 'fp8' (pinned e4m3 — use "
                    "this when comparing tokens across mesh shapes), "
                    "'none' (full-width wire)")
p.add_argument("--long-context", action="store_true",
               help="distributed flash-decode (ISSUE 19) for --mesh: the "
                    "KV pool is laid out interleaved so each SP rank owns "
                    "every sp-th page of EVERY request, decode attention "
                    "runs flash_decode_dist (per-page softmax partials, "
                    "one-sided folds), and one request's context may span "
                    "the whole mesh. Tokens stay bit-identical to the "
                    "replicated layout at any rank count. Prints a MODELED "
                    "per-step attention split (local scan vs fold wait) "
                    "to stderr")
p.add_argument("--overlap", choices=("off", "ep", "ep+sp"), default="off",
               help="fine-grained compute/comm overlap for --mesh "
                    "(ISSUE 16): 'ep' microbatches each EP dispatch so "
                    "expert FFN overlaps the segmented a2a, 'ep+sp' also "
                    "starts local attention-pool assembly under the "
                    "allgather. Tokens stay bit-identical to 'off' — the "
                    "schedule moves, the reduction order never does")
p.add_argument("--chaos", default=None, metavar="SPEC",
               help="seeded fault injection on the migration signal plane "
                    "(implies --disagg): a bare integer seed (default "
                    "drop/delay probabilities) or a FaultPlan spec like "
                    "'seed=3,drop=0.2,dup=0.05,delay=0.3,dead=40,"
                    "rids=1|4|7'. Replays are bit-deterministic per spec; "
                    "a chaos summary line (retries / degradations / "
                    "failures / recovery latencies) is printed to stderr")
p.add_argument("--crash-at", type=int, default=None, metavar="STEP",
               help="inject a hard crash (InjectedCrash) at this engine "
                    "step; with --recover a FRESH engine is rebuilt from "
                    "the journal and the replay continues (the crash-"
                    "consistency demo, docs/robustness.md). Without "
                    "--recover the crash propagates (exit 1)")
p.add_argument("--recover", action="store_true",
               help="after --crash-at fires, restore a fresh engine from "
                    "the journal (checkpoint + WAL-suffix replay, zero new "
                    "compiles) and finish the trace; prints a recovery "
                    "summary line to stderr. Tokens stay bit-identical to "
                    "the crash-free replay")
p.add_argument("--checkpoint-every", type=int, default=16, metavar="N",
               help="control-plane checkpoint cadence in engine steps "
                    "(journaled runs only; 0 disables checkpoints — "
                    "recovery then replays the whole journal)")
p.add_argument("--queue-cap", type=int, default=None, metavar="N",
               help="bounded admission queue: submissions past N queued "
                    "requests are REJECTED with a typed terminal "
                    "(overload shedding; counted in 'rejections')")
p.add_argument("--ttl", type=int, default=None, metavar="STEPS",
               help="per-request TTL in engine steps: queued requests "
                    "never admitted within the budget EXPIRE with a typed "
                    "terminal (counted in 'expirations')")
p.add_argument("--prefix-cache", action="store_true",
               help="ref-counted copy-on-write prefix caching (ISSUE 13): "
                    "finished prompts' full KV pages stay indexed in a "
                    "radix trie and later shared-prefix prompts adopt them "
                    "instead of re-prefilling; prints a hit-rate + "
                    "cached/cold TTFT summary line to stderr (implies the "
                    "chunked prefill path)")
p.add_argument("--prompt-zipf", default=None, metavar="ALPHA:POOL",
               help="Zipf-shared-prompt generator: draw each request's "
                    "prefix from a POOL of shared page-aligned prefixes "
                    "with Zipf(ALPHA) popularity and append a short "
                    "random tail — the workload prefix caching exists "
                    "for (e.g. 1.1:8). Deterministic per --seed")
p.add_argument("--lend-warm", type=int, default=None, metavar="N",
               help="cluster-wide prefix sharing (ISSUE 17) in one "
                    "process: a peer LENDER engine prefills the top-N "
                    "--prompt-zipf pool prefixes, then lends them to the "
                    "serving engine over the export/adopt page surface "
                    "BEFORE the trace starts — head-of-pool prompts hit "
                    "as REWARMED (peer-adopted) pages instead of paying "
                    "a cold prefill; prints a lend panel to stderr. "
                    "Needs --prefix-cache + --prompt-zipf on the plain "
                    "engine (no --mesh/--disagg)")
p.add_argument("--workload", default=None, metavar="SPEC",
               help="bursty two-class trace (ISSUE 14) replacing the "
                    "uniform generator: key=value pairs, e.g. 'n=200,"
                    "seed=7,chat=0.7,rate=0.5,burst_every=64,burst_len="
                    "16,burst_x=4,zipf=1.2,prefixes=8,tenants=3,plen="
                    "4:20,mnt=2:10' — Zipf prompt sharing x chat-vs-"
                    "batch heterogeneity x diurnal bursts, every request "
                    "stamped (tenant, class). Bad fields fail loudly BY "
                    "NAME. Overrides --sim/--arrive-every/--prompt-zipf")
p.add_argument("--artifact", default=None, metavar="DIR",
               help="load a persisted AOT serving artifact (built by "
                    "tools/compile_aot.py) and seed the engine's compiled "
                    "programs from it — zero fresh jit traces from cold "
                    "start to first token. A stale or mismatched artifact "
                    "is a loud typed error, never a silent re-trace. The "
                    "cold-start summary line on stderr reports "
                    "cold_start_compiles and cold-start-to-first-token "
                    "time either way; with --recover the restarted "
                    "incarnation seeds from the same artifact")
p.add_argument("--slo", default=None, metavar="SPEC",
               help="multi-tenant SLO policy (ISSUE 14): chat/batch WFQ "
                    "weights, per-class overrides and token-bucket "
                    "quotas, e.g. 'chat_weight=4,batch_weight=1,"
                    "batch_cap=8,batch_ttl=40,chat_stall=4,quota="
                    "b0:1:4|b1:2:8'. Adds a per-class summary panel "
                    "(TTFT/ITL p50/p99, shed counts) to stderr")
args = p.parse_args()
if args.recover and args.crash_at is None:
    p.error("--recover needs --crash-at")
if args.chaos is not None:
    args.disagg = True
if args.mesh is not None:
    args.model = "moe"
elif args.model == "moe":
    args.mesh = "1x1x1"
if args.overlap != "off" and (args.mesh is None or args.disagg):
    p.error("--overlap rides the sharded engine: needs --mesh (or "
            "--model moe) and is not plumbed through --disagg")
if args.long_context and (args.mesh is None or args.disagg):
    p.error("--long-context rides the sharded engine: needs --mesh (or "
            "--model moe) and is not plumbed through --disagg")
if args.speculate is not None:
    if args.speculate != "auto":
        try:
            args.speculate = int(args.speculate)
        except ValueError:
            p.error("--speculate wants an integer K or 'auto'")
    if args.disagg:
        p.error("--speculate is not plumbed through --disagg (the verify "
                "dispatch is the colocated/sharded ONE-decode program)")
    if args.decode_horizon != 1:
        p.error("--speculate owns the decode horizon (the verify row "
                "block IS the multistep machinery): needs "
                "--decode-horizon 1")
if (args.prefix_cache and args.prefill_chunk is None
        and not args.disagg and args.mesh is None):
    # the cache rides the chunked path (adoption = cursor jump)
    args.prefill_chunk = 2 * args.page_size
if args.lend_warm is not None and (
        not args.prefix_cache or args.prompt_zipf is None
        or args.disagg or args.mesh is not None):
    p.error("--lend-warm needs --prefix-cache + --prompt-zipf on the "
            "plain engine (no --mesh/--disagg): lending moves CACHED "
            "prefix pages between two engines of the same model")
if args.prefill_buckets == "pow2":
    buckets = "pow2"
elif args.prefill_buckets == "exact":
    buckets = None
else:
    buckets = tuple(int(b) for b in args.prefill_buckets.split(","))

if args.mesh is not None:
    # with --disagg on top, the composed engine runs BOTH fleets on this
    # one mesh (ISSUE 12) — the device count is still tp*sp*ep
    tp, sp, ep = (int(d) for d in args.mesh.lower().split("x"))
    from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402
    force_virtual_cpu_devices(tp * sp * ep)
elif args.disagg:
    # the role mesh needs 2 ranks; on fewer (e.g. plain-CPU jax) fall
    # back to the 2-device virtual CPU simulator — real chips are kept
    from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402
    force_virtual_cpu_devices(2)

if args.model == "moe":
    from triton_dist_tpu.models.moe import MoEConfig, init_moe_params  # noqa: E402
    cfg = MoEConfig.tiny(n_layers=args.layers)
    params = init_moe_params(jax.random.PRNGKey(args.seed), cfg)
    vocab = cfg.base.vocab_size
else:
    cfg = LlamaConfig.tiny(n_layers=args.layers)
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    vocab = cfg.vocab_size

# multi-tenant SLO policy (ISSUE 14): both specs fail loudly NAMING the
# bad field (argparse-style) instead of replaying a default-shaped trace
slo_policy = None
if args.slo is not None:
    from triton_dist_tpu.serving import parse_slo  # noqa: E402
    try:
        slo_policy = parse_slo(args.slo)
    except ValueError as e:
        p.error(str(e))
workload_spec = None
if args.workload is not None:
    from triton_dist_tpu.serving import parse_workload  # noqa: E402
    try:
        workload_spec = parse_workload(args.workload)
    except ValueError as e:
        p.error(str(e))
    args.sim = workload_spec.n

# speculative decoding (ISSUE 20): the kwargs ride beside `common`
# instead of inside it so the disagg branches (already p.error-fenced
# above) never see the knob; 'auto' resolution is bucketed by the
# workload shape when a --workload spec is in play
spec_kwargs = {}
if args.speculate is not None:
    bucket = 0
    if workload_spec is not None:
        from triton_dist_tpu.serving import spec_bucket_of  # noqa: E402
        bucket = spec_bucket_of(workload_spec)
    spec_kwargs = dict(speculate=args.speculate, spec_bucket=bucket)

# crash-consistency plumbing: journaled runs get a WAL + periodic
# checkpoints; --crash-at adds an engine-tier fault plan on top of any
# --chaos signal-plane plan (the two tiers compose, see test_chaos.py)
journaled = (args.crash_at is not None or args.queue_cap is not None
             or args.ttl is not None)
journal = None
if journaled:
    from triton_dist_tpu.serving import ControlJournal  # noqa: E402
    journal = ControlJournal()
ckpt_every = args.checkpoint_every or None if journaled else None


def _fault_plan():
    from triton_dist_tpu.shmem import FaultPlan  # noqa: E402
    plan = FaultPlan.from_spec(args.chaos) if args.chaos else None
    if args.crash_at is not None:
        import dataclasses as _dc  # noqa: E402
        plan = (_dc.replace(plan, crash_at=(args.crash_at,)) if plan
                else FaultPlan(seed=args.seed, crash_at=(args.crash_at,)))
    return plan


# AOT artifact (ISSUE 15): load BEFORE any engine is built so the
# engine's jit caches seed from persisted programs instead of tracing.
# The wall clock starts here — cold-start-to-first-token covers the
# artifact load (or the fresh traces it replaces) plus the first dispatch.
import time as _time  # noqa: E402

_t_cold0 = _time.perf_counter()
artifact = None
if args.artifact is not None:
    from triton_dist_tpu.aot import load_artifact  # noqa: E402
    artifact = load_artifact(args.artifact)


def mk_engine(fresh=False):
    """Build the selected engine. ``fresh=True`` is the restarted
    incarnation after a crash: same configuration, same journal — the
    fault plan rides along unchanged (crash injection is incarnation-
    gated, so it fires only once)."""
    common = dict(num_slots=args.slots, page_size=args.page_size,
                  num_pages=args.pages, pages_per_seq=args.pages_per_seq,
                  decode_horizon=args.decode_horizon, journal=journal,
                  checkpoint_every=ckpt_every, queue_cap=args.queue_cap,
                  ttl_steps=args.ttl, fault_plan=_fault_plan(),
                  prefix_cache=args.prefix_cache, slo=slo_policy,
                  artifact=artifact)
    if args.mesh is not None and args.disagg:
        # ISSUE 12: the composed engine — disaggregated prefill feeding a
        # ShardedServingEngine decode fleet on ONE TP/SP/EP mesh (the
        # unified pool contract made the old mutual exclusion obsolete)
        import jax.numpy as jnp  # noqa: E402

        from triton_dist_tpu.serving import (DisaggShardedEngine,  # noqa: E402
                                             serving_mesh)
        wire = {"auto": "auto", "fp8": jnp.float8_e4m3fn,
                "none": None}[args.wire]
        eng = DisaggShardedEngine(params, cfg, serving_mesh(tp, sp, ep),
                                  prefill_chunk=args.prefill_chunk or 8,
                                  wire_dtype=wire, **common)
        if not fresh:
            print(json.dumps({"mesh": eng.mesh_desc, "disagg": True,
                              "wire": eng.wire_dtype}), file=sys.stderr)
        if args.chaos is not None and not fresh:
            print(json.dumps({"chaos": eng._fault_plan.describe()}),
                  file=sys.stderr)
    elif args.mesh is not None:
        import jax.numpy as jnp  # noqa: E402

        from triton_dist_tpu.serving import (ShardedServingEngine,  # noqa: E402
                                             serving_mesh)
        wire = {"auto": "auto", "fp8": jnp.float8_e4m3fn,
                "none": None}[args.wire]
        eng = ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep),
                                   prefill_chunk=args.prefill_chunk or 8,
                                   wire_dtype=wire, overlap=args.overlap,
                                   long_context=args.long_context,
                                   **spec_kwargs, **common)
        if not fresh:
            # wire=auto resolves PER DISPATCH SIZE and rank count (PR 8
            # caveat), so decode and chunk can land on different wire
            # dtypes at the same mesh — print both resolutions so an
            # --wire auto run is auditable without rerunning pinned
            print(json.dumps({"mesh": eng.mesh_desc,
                              "wire_requested": args.wire,
                              "wire": eng.wire_dtype,
                              "wire_chunk": eng.wire_dtype_chunk,
                              "overlap": eng.overlap,
                              "overlap_microbatches":
                                  eng.overlap_microbatches}),
                  file=sys.stderr)
    elif args.disagg:
        from triton_dist_tpu.serving import DisaggServingEngine  # noqa: E402
        chunk = args.prefill_chunk or 2 * args.page_size
        eng = DisaggServingEngine(params, cfg, prefill_chunk=chunk,
                                  **common)
        if args.chaos is not None and not fresh:
            print(json.dumps({"chaos": eng._fault_plan.describe()}),
                  file=sys.stderr)
    else:
        eng = ServingEngine(params, cfg, prefill_buckets=buckets,
                            prefill_chunk=args.prefill_chunk,
                            **spec_kwargs, **common)
    return eng


eng = mk_engine()

rng = np.random.RandomState(args.seed)
max_plen = min(args.pages_per_seq * args.page_size - args.max_new, 24)
arrivals = []
if workload_spec is not None:
    # the bursty two-class trace (ISSUE 14): 5-tuple arrivals carrying
    # (tenant, class) stamps; run() feeds them through submit()
    from triton_dist_tpu.serving import generate_arrivals  # noqa: E402
    cap = args.pages_per_seq * args.page_size
    if workload_spec.plen[1] + workload_spec.mnt[1] - 1 > cap:
        p.error(f"workload spec field 'plen': plen+mnt-1 = "
                f"{workload_spec.plen[1] + workload_spec.mnt[1] - 1} "
                f"exceeds pages_per_seq*page_size = {cap}")
    if (workload_spec.long > 0
            and workload_spec.lplen[1] + workload_spec.mnt[1] - 1 > cap):
        p.error(f"workload spec field 'lplen': lplen+mnt-1 = "
                f"{workload_spec.lplen[1] + workload_spec.mnt[1] - 1} "
                f"exceeds pages_per_seq*page_size = {cap} — raise "
                f"--pages-per-seq (long-context prompts span many pages)")
    arrivals = generate_arrivals(workload_spec, vocab=vocab,
                                 page_size=args.page_size)
elif args.prompt_zipf is not None:
    # the shared-prompt workload: page-aligned prefixes drawn from a
    # small pool with Zipf popularity, plus a short random tail — head
    # prefixes repeat often enough that a prefix cache serves most of
    # their prompt tokens from adopted pages
    alpha_s, pool_s = args.prompt_zipf.split(":")
    alpha, pool_n = float(alpha_s), int(pool_s)
    assert alpha > 0 and pool_n >= 1, "--prompt-zipf wants ALPHA:POOL > 0"
    prefix_len = max(args.page_size,
                     (max(max_plen - 5, args.page_size)
                      // args.page_size) * args.page_size)
    pool = [rng.randint(1, vocab, size=prefix_len).tolist()
            for _ in range(pool_n)]
    w = np.arange(1, pool_n + 1, dtype=np.float64) ** -alpha
    w /= w.sum()
    for i in range(args.sim):
        k = int(rng.choice(pool_n, p=w))
        tail = rng.randint(1, vocab,
                           size=int(rng.randint(1, 5))).tolist()
        mnt = int(rng.randint(2, max(3, args.max_new + 1)))
        arrivals.append((i * args.arrive_every // max(args.arrive_every, 1),
                         pool[k] + tail, mnt))
else:
    for i in range(args.sim):
        plen = int(rng.randint(3, max(4, max_plen)))
        mnt = int(rng.randint(2, max(3, args.max_new + 1)))
        prompt = rng.randint(1, vocab, size=plen).tolist()
        arrivals.append((i * args.arrive_every // max(args.arrive_every, 1),
                         prompt, mnt))

lend_stats = None
if args.lend_warm is not None:
    # ISSUE 17 demo: a peer lender (same params, its OWN page pool, no
    # journal) earns the head prefixes' KV by prefilling them, then the
    # serving engine adopts the pages over the export/adopt surface —
    # the host twin of ops.lend_pages. Head-of-pool prompts in the trace
    # below then hit as rewarmed pages before any local prefill ran.
    from triton_dist_tpu.serving import ServingEngine  # noqa: E402
    lender = ServingEngine(params, cfg, num_slots=args.slots,
                           page_size=args.page_size, num_pages=args.pages,
                           pages_per_seq=args.pages_per_seq,
                           prefill_chunk=args.prefill_chunk
                           or 2 * args.page_size,
                           prefix_cache=True)
    n_warm = min(args.lend_warm, len(pool))
    for pre in pool[:n_warm]:
        lender.submit(pre + [1], 2)
    lender.run(max_steps=200_000)
    _t_lend = _time.perf_counter()
    lent_pages = lent_tokens = 0
    for pre in pool[:n_warm]:
        toks, _ids, payload = lender.export_prefix(pre)
        if toks > 0:
            got = eng.adopt_prefix(pre, toks, payload)
            lent_pages += got
            lent_tokens += got * args.page_size
    lend_stats = {
        "lend_warm": n_warm,
        "lent_pages": lent_pages,
        "lend_tokens": lent_tokens,
        "lend_us_per_page": round(
            (_time.perf_counter() - _t_lend) * 1e6 / max(lent_pages, 1),
            1),
    }

if args.crash_at is not None:
    from triton_dist_tpu.shmem.faults import InjectedCrash  # noqa: E402
    try:
        results = eng.run(max_steps=200_000, arrivals=arrivals)
    except InjectedCrash as crash:
        if not args.recover:
            print(json.dumps({"crashed": str(crash)}), file=sys.stderr)
            sys.exit(1)
        # process "restart": the journal is the only surviving artifact.
        # Submissions already journaled (admitted or rejected) replay
        # from the WAL; only the rest of the trace is re-fed.
        done = sum(1 for e in journal.entries
                   if e["kind"] in ("submit", "reject"))
        eng = mk_engine(fresh=True)
        results = eng.run(max_steps=200_000, arrivals=arrivals[done:],
                          recover=True)
        ck = journal.last_checkpoint_entry()
        print(json.dumps({
            "recovery": True,
            "crash": str(crash),
            "checkpoint_step": None if ck is None else ck["step"],
            "journal_entries": len(journal),
            "restores": eng.metrics.counters["restores"],
            "replayed_submits": done,
            "final_step": eng._steps,
        }), file=sys.stderr)
else:
    results = eng.run(max_steps=200_000, arrivals=arrivals)
# run() returns FINISHED requests only. Under --chaos a request may
# instead have FAILED (typed, per-request — the ladder ran dry); under
# --queue-cap/--ttl it may have been REJECTED/EXPIRED (typed overload
# terminals); those are accounted for, not "unfinished". Anything else
# absent ran out of steps — a real error.
failed = {r.rid: r for r in getattr(eng, "failed", [])}
unfinished = sorted(set(range(args.sim)) - set(results) - set(failed))
if unfinished:
    print(json.dumps({"error": "unfinished requests", "rids": unfinished}),
          file=sys.stderr)
    sys.exit(1)
for rid in sorted(failed):
    print(json.dumps({"failed_rid": rid,
                      "reason": type(failed[rid].failure).__name__,
                      "detail": str(failed[rid].failure)}), file=sys.stderr)
if args.queue_cap is not None or args.ttl is not None:
    c = eng.metrics.counters
    print(json.dumps({
        "overload": True,
        "queue_cap": args.queue_cap, "ttl_steps": args.ttl,
        "submitted": c["requests_submitted"],
        "admitted_finished": len(results),
        "rejections": c["rejections"],
        "expirations": c["expirations"],
    }), file=sys.stderr)

if args.tokens:
    for req in sorted(eng._finished, key=lambda r: r.rid):
        print(json.dumps({
            "rid": req.rid, "prompt_len": len(req.prompt),
            "tokens": list(req.generated),
            "preemptions": req.preemptions,
            "ttft_steps": req.first_token_step - req.submit_step,
        }))
print(json.dumps({"compile_stats": eng.compile_stats}), file=sys.stderr)

# cold-start summary (ISSUE 15): fresh traces paid before the first token
# and the wall time from process cold start (engine build / artifact
# load) to the first token out. With --artifact both columns should read
# zero-compiles and the ~10x-smaller wall time bench.py's `aot` extras
# pin; printed unconditionally so artifact-on vs artifact-off runs (and
# --recover restarts, which seed from the same artifact) compare 1:1.
_stats = eng.compile_stats
_ftt = [r.first_token_time for r in eng._finished
        if r.first_token_time is not None]
print(json.dumps({"cold_start": {
    "artifact": args.artifact,
    "cold_start_compiles": sum(
        v for k, v in _stats.items() if k.endswith("_compiles")),
    "aot_programs": _stats.get("aot_programs", 0),
    "cold_start_to_first_token_s":
        None if not _ftt else round(min(_ftt) - _t_cold0, 4),
}}), file=sys.stderr)

# prefill-stall / TTFT-split summary: the numbers chunked prefill moves
# (per-step decode stall bound, queue-vs-prefill TTFT split)
snap = eng.metrics.snapshot()
us = lambda v: None if v is None else round(v * 1e6, 1)

# per-class panel (ISSUE 14): TTFT lives on the intake panel, ITL on the
# decode panel for the split engines — merge both per_class() views
# (ints sum, None yields) into one summary line
per_cls = eng.metrics.per_class()
_md = getattr(eng, "metrics_decode", None)
if _md is not None:
    for _c, _row in _md.per_class().items():
        _base = per_cls.setdefault(_c, dict.fromkeys(_row))
        for _k, _v in _row.items():
            if isinstance(_v, int) and isinstance(_base.get(_k), int):
                _base[_k] += _v
            elif _base.get(_k) is None:
                _base[_k] = _v
if per_cls:
    print(json.dumps({
        "per_class": {
            c: {"ttft_p50_us": us(r.get("ttft_p50_s")),
                "ttft_p99_us": us(r.get("ttft_p99_s")),
                "itl_p50_us": us(r.get("itl_p50_s")),
                "itl_p99_us": us(r.get("itl_p99_s")),
                "finished": r.get("finished"),
                "rejections": r.get("rejections"),
                "expirations": r.get("expirations")}
            for c, r in per_cls.items()},
        "quota_throttled": snap["quota_throttled"],
        "chunk_shrinks": snap["chunk_shrinks"],
    }), file=sys.stderr)
if args.prefix_cache:
    # hit-rate + cached/cold TTFT split (ISSUE 13): the point of the
    # cache is the cached-TTFT column sitting far below the cold one on
    # shared-prefix workloads (--prompt-zipf)
    hits, misses = snap["prefix_hits"], snap["prefix_misses"]
    print(json.dumps({
        "prefix_cache": True,
        "hits": hits, "misses": misses,
        "hit_rate": round(hits / max(hits + misses, 1), 3),
        "hit_tokens": snap["prefix_hit_tokens"],
        "cow_copies": snap["cow_copies"],
        "evictions": snap["prefix_evictions"],
        "skipped_chunks": snap["prefix_skipped_chunks"],
        "ttft_cached_us": {k: us(snap["ttft_cached_s"][k])
                           for k in ("mean", "p99")},
        "ttft_cold_us": {k: us(snap["ttft_cold_s"][k])
                         for k in ("mean", "p99")},
        # the ISSUE 17 third band: first hit on pages adopted FROM A
        # PEER (--lend-warm) — the acceptance is rewarmed ≈ cached
        "ttft_rewarmed_us": {k: us(snap["ttft_rewarmed_s"][k])
                             for k in ("mean", "p99")},
    }), file=sys.stderr)
if lend_stats is not None:
    print(json.dumps({"lend": True, **lend_stats}), file=sys.stderr)
if args.disagg:
    # two panels: TTFT lives on the prefill worker, ITL/stall on the
    # decode worker — whose decode stall carries ZERO prefill work (the
    # step_prefill_tokens_max field is the proof, not a wall clock)
    snap_d = eng.metrics_decode.snapshot()
    print(json.dumps({
        "disagg": True,
        "prefill_chunks": snap["prefill_chunks"],
        "pages_migrated": snap["pages_migrated"],
        "migrate_us": {k: us(snap["migrate_s"][k])
                       for k in ("mean", "p99", "max")},
        "migrate_wait_steps_max": snap_d["migrate_wait_steps"]["max"],
        "decode_stall_us": {k: us(snap_d["decode_stall_s"][k])
                            for k in ("mean", "p50", "p99", "max")},
        "decode_step_prefill_tokens_max":
            snap_d["step_prefill_tokens"]["max"],
        "itl_us": {k: us(snap_d["tok_latency_s"][k])
                   for k in ("mean", "p99")},
        "ttft_queue_us": {k: us(snap["ttft_queue_s"][k])
                          for k in ("mean", "p99")},
        "ttft_prefill_us": {k: us(snap["ttft_prefill_s"][k])
                            for k in ("mean", "p99")},
    }), file=sys.stderr)
    if args.chaos is not None:
        # the chaos summary: what the ladder absorbed and what it cost
        print(json.dumps({
            "chaos_summary": True,
            "faults_injected": snap["faults_injected"],
            "stale_signals": snap["stale_signals"],
            "retries": snap_d["retries"],
            "degradations": snap_d["degradations"],
            "failed_requests": snap_d["failed_requests"],
            "recovered_ttft_us": {k: us(snap_d["recovered_ttft_s"][k])
                                  for k in ("mean", "p99")},
            "degraded_ttft_us": {k: us(snap_d["degraded_ttft_s"][k])
                                 for k in ("mean", "p99")},
        }), file=sys.stderr)
    eng.metrics.emit()
    eng.metrics_decode.emit()
else:
    if args.mesh is not None:
        # the replicated-decision guard's coverage for this replay
        print(json.dumps({"digest_checks": snap["digest_checks"]}),
              file=sys.stderr)
        # overlap panel (ISSUE 16): per-step EP wire split under the
        # wire-fit model — comm still exposed on the critical path vs
        # comm hidden behind expert FFN (serving/sharded.py; modeled,
        # labeled as such — CPU wall clock cannot show real overlap)
        print(json.dumps({
            "overlap": eng.overlap,
            "overlap_microbatches": eng.overlap_microbatches,
            "exposed_comm_us_mean": round(
                snap["exposed_comm_us"]["mean"] or 0.0, 2),
            "overlapped_comm_us_mean": round(
                snap["overlapped_comm_us"]["mean"] or 0.0, 2),
        }), file=sys.stderr)
        if args.long_context:
            # long-context panel (ISSUE 19): the per-step decode attention
            # split under the wire-fit model — local page scan (shrinks
            # with SP rank count, each rank walks 1/n of the pages) vs
            # fold wait (the fixed-order partial merge). MODELED, labeled
            # as such — CPU interpret wall clock cannot show the split
            print(json.dumps({
                "long_context": True,
                "kv_layout": eng.alloc.layout,
                "attn_local_us_mean": round(
                    snap["attn_local_us"]["mean"] or 0.0, 3),
                "attn_fold_wait_us_mean": round(
                    snap["attn_fold_wait_us"]["mean"] or 0.0, 3),
            }), file=sys.stderr)
    if args.speculate is not None:
        # spec panel (ISSUE 20): accepted/dispatch > 1 is the whole
        # point — every accepted draft token is a decode dispatch the
        # host never paid for, at bit-identical tokens
        print(json.dumps({
            "speculate": eng.spec_k,
            "spec_dispatches": snap["spec_dispatches"],
            "accepted_per_dispatch_mean": round(
                snap["accepted_per_dispatch"]["mean"] or 0.0, 3),
            "draft_hit_rate": snap["draft_hit_rate"],
            "spec_rewinds": snap["spec_rewinds"],
        }), file=sys.stderr)
    print(json.dumps({
        "prefill_chunk": args.prefill_chunk,
        "prefill_chunks": snap["prefill_chunks"],
        "prefill_stall_us": {k: us(snap["prefill_stall_s"][k])
                             for k in ("mean", "p50", "p99", "max")},
        "decode_stall_us": {k: us(snap["decode_stall_s"][k])
                            for k in ("mean", "p50", "p99", "max")},
        "step_prefill_tokens_max": snap["step_prefill_tokens"]["max"],
        "ttft_queue_us": {k: us(snap["ttft_queue_s"][k])
                          for k in ("mean", "p99")},
        "ttft_prefill_us": {k: us(snap["ttft_prefill_s"][k])
                            for k in ("mean", "p99")},
    }), file=sys.stderr)
    eng.metrics.emit()
