"""Piecewise on-chip probe of the EP MoE serving block (VERDICT r4 #1).

Breaks `moe_ep_block_us` (router → dispatch → grouped gated FFN → combine,
128 tok/rank, hidden 7168, F=512, E=16, topk=8) into measured stages so the
roofline in docs/benchmarks.md is built from numbers, not guesses:

  align        align_tokens_by_expert (one-hot cumsum routing tables)
  edges        apply_grouped with identity fn (align + gather + scatter)
  gated[bm]    fused gate+up+act grouped GEMM alone, block_m sweep
  down[bm]     down grouped GEMM alone
  ffn_fused    gated + down through apply_grouped (the new serving path)
  ffn_unfused  3-launch gate/up/act/down composition (the round-4 path)
  block        full moe_mlp_ep_overlap (router+dispatch+ffn+combine)
  block_em     same block on the expert-major capacity layout (align
               gather/scatter elided: static block→expert map)

Run on the real chip:
  cd /tmp && PYTHONPATH=/root/repo:/root/.axon_site \
      python /root/repo/scripts/moe_probe.py [--quick]

One JSON line per stage. Timing = the bench differenced scan-chain
(bench.py:_per_iter) — see bench.py's module docstring for why.
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from bench import _per_iter, make_chain_timer  # noqa: E402

T, D, F, E, TOPK = 128, 7168, 512, 16, 8
ROWS = T * TOPK  # routed rows at n=1 (every topk copy lands locally)


def main():
    quick = "--quick" in sys.argv
    stages = [a for a in sys.argv[1:] if not a.startswith("-")]

    def want(name):
        return not stages or any(name.startswith(s) for s in stages)

    i1, i2 = (10, 60) if quick else (10, 210)
    from triton_dist_tpu.ops.group_gemm import (align_tokens_by_expert,
                                                apply_grouped, grouped_gemm,
                                                grouped_gemm_gated)

    key = jax.random.key(0)
    ids = jax.random.randint(jax.random.key(1), (ROWS,), 0, E)
    tokens = jax.random.normal(key, (ROWS, D), jnp.float32
                               ).astype(jnp.bfloat16)
    wg = (jax.random.normal(jax.random.key(2), (E, D, F)) * 0.05
          ).astype(jnp.bfloat16)
    wu = (jax.random.normal(jax.random.key(3), (E, D, F)) * 0.05
          ).astype(jnp.bfloat16)
    wd = (jax.random.normal(jax.random.key(4), (E, F, D)) * 0.05
          ).astype(jnp.bfloat16)

    def emit(stage, seconds, **kw):
        print(json.dumps({"stage": stage, "us": round(seconds * 1e6, 1),
                          **kw}), flush=True)

    def guard(name, fn):
        if not want(name):
            return
        try:
            fn()
        except Exception as e:
            print(json.dumps({"stage": name,
                              "error": f"{type(e).__name__}: {e}"[:160]}),
                  flush=True)

    # --- align tables alone -------------------------------------------------
    def align_step(c, _):
        gi, rv, be, nb = align_tokens_by_expert(
            (ids + c.astype(jnp.int32) * 0) % E, E, 128,
            with_used_count=True)
        return c + (jnp.sum(gi) + nb).astype(jnp.float32) * 1e-20

    guard("align", lambda: emit("align", _per_iter(make_chain_timer(
        align_step, jnp.zeros((), jnp.float32), None), i1, i2)))

    # --- forward gather alone (aligned x build) -----------------------------
    gi0, rv0, be0, nb0 = align_tokens_by_expert(ids, E, 128,
                                                with_used_count=True)

    def gather_step(t, _):
        x = jnp.where(rv0[:, None], t[gi0], 0).astype(t.dtype)
        return t + (jnp.sum(x[:8].astype(jnp.float32)) * 1e-20
                    ).astype(t.dtype)

    guard("gather", lambda: emit("gather", _per_iter(make_chain_timer(
        gather_step, tokens, None), i1, i2)))

    # --- align + gather + scatter (identity fn) -----------------------------
    def edges_step(t, _):
        y = apply_grouped(t, ids, E, lambda x, be, nb: x, block_m=128)
        return t + (y * jnp.asarray(1e-20, y.dtype))

    guard("edges", lambda: emit("edges", _per_iter(make_chain_timer(
        edges_step, tokens, None), i1, i2)))

    # --- kernels alone on pre-aligned rows: tile-config sweep ---------------
    gi, rv, be, nb = {}, {}, {}, {}
    xs = {}
    for bm in (128, 256, 512):
        gi[bm], rv[bm], be[bm], nb[bm] = align_tokens_by_expert(
            ids, E, bm, with_used_count=True)
        xs[bm] = jax.block_until_ready(jnp.where(
            rv[bm][:, None], tokens[gi[bm]], 0).astype(jnp.bfloat16))

    GATED_CFGS = [(128, 128, None), (128, 512, 3584), (256, 256, 3584),
                  (256, 512, 3584), (512, 256, 3584), (256, 256, 1792),
                  (256, 512, 1792)]
    for bm, bn, bk in GATED_CFGS:
        def gated_step(xx, _, bm=bm, bn=bn, bk=bk):
            h = grouped_gemm_gated(xx, wg, wu, be[bm], block_m=bm,
                                   block_n=bn, block_k=bk,
                                   n_blocks_used=nb[bm], masked=False)
            eps = (jnp.sum(h[:128].astype(jnp.float32)) * 1e-20
                   ).astype(xx.dtype)
            return xx + eps

        guard(f"gated_{bm}_{bn}_{bk}", lambda s=gated_step, bm=bm: emit(
            f"gated_{bm}_{bn}_{bk}", _per_iter(
                make_chain_timer(s, xs[bm], None), i1, i2)))

    DOWN_CFGS = [(128, 128), (128, 512), (128, 1024), (128, 1792),
                 (256, 512), (256, 1024)]
    h0 = {}
    for bm in (128, 256):
        if any(c[0] == bm for c in DOWN_CFGS) and want("down"):
            h0[bm] = jax.block_until_ready(
                jax.jit(lambda xx, bm=bm: grouped_gemm_gated(
                    xx, wg, wu, be[bm], block_m=bm, block_k=3584,
                    n_blocks_used=nb[bm]))(xs[bm]))
    for bm, bn in DOWN_CFGS:
        def down_step(hh, _, bm=bm, bn=bn):
            y = grouped_gemm(hh, wd, be[bm], block_m=bm, block_n=bn,
                             n_blocks_used=nb[bm], masked=False)
            eps = (jnp.sum(y[:128].astype(jnp.float32)) * 1e-20
                   ).astype(hh.dtype)
            return hh + eps

        guard(f"down_{bm}_{bn}", lambda s=down_step, bm=bm: emit(
            f"down_{bm}_{bn}", _per_iter(
                make_chain_timer(s, h0[bm], None), i1, i2)))

    # --- full expert-FFN stage (weights ride the chain: closures would
    # bake 350 MB into the remote-compile payload -> HTTP 413) ------------
    def ffn_timer(cfg):
        bm, bn, bk, dbn = cfg

        def step(c, w):
            wg_, wu_, wd_, toks = w

            def f(x, be_, nb_):
                hh = grouped_gemm_gated(x, wg_, wu_, be_, block_m=bm,
                                        block_n=bn, block_k=bk,
                                        n_blocks_used=nb_, masked=False)
                return grouped_gemm(hh, wd_, be_, block_m=bm, block_n=dbn,
                                    n_blocks_used=nb_, masked=False)

            y = apply_grouped(toks + c.astype(jnp.bfloat16), ids, E, f,
                              block_m=bm)
            return jnp.max(y.astype(jnp.float32)) * 1e-20

        return make_chain_timer(step, jnp.zeros((), jnp.float32),
                                (wg, wu, wd, tokens))

    for cfg in [(128, 128, None, 512), (256, 512, 1792, 512),
                (256, 256, 1792, 512), (128, 128, None, 128)]:
        guard(f"ffn_{'_'.join(str(c) for c in cfg)}",
              lambda c=cfg: emit(f"ffn_{'_'.join(str(x) for x in c)}",
                                 _per_iter(ffn_timer(c), i1, i2)))

    # --- full serving block + dispatch (shared ctx) -------------------------
    if (want("block") or want("disp") or want("block_fp8_post")
            or want("block_fp8_expert") or want("block_em")):
        from bench import bench_a2a, bench_ep_block
        from triton_dist_tpu.shmem.context import initialize_distributed
        ctx = initialize_distributed(axis_names=("x",),
                                     mesh_shape=(len(jax.devices()),))
        if want("disp"):
            def _disp():
                d, r = bench_a2a(ctx, tokens_per_rank=T, hidden=D,
                                 topk=TOPK, num_experts=64,
                                 i1=10, i2=410 if quick else 1610)
                emit("disp_bf16", d)
                emit("roundtrip_bf16", r)
            guard("disp", _disp)
        if want("block"):
            guard("block", lambda: emit("block", bench_ep_block(
                ctx, i1=10, i2=60 if quick else 210)))
        if want("block_em"):
            # expert-major capacity layout: align gather/scatter elided
            # in the serving FFN (static block→expert map)
            guard("block_em", lambda: emit("block_em", bench_ep_block(
                ctx, i1=10, i2=60 if quick else 210, expert_major=True)))
        if want("block_fp8_post") or want("block_fp8_expert"):
            # the expert-edge QuantTokens protocol (reference
            # architecture) vs post-dequant, with the convert-once
            # x-scratch in the gated kernel (ADVICE r4 #3)
            guard("block_fp8_post", lambda: emit(
                "block_fp8_post", bench_ep_block(
                    ctx, i1=10, i2=60 if quick else 210,
                    wire_dtype=jnp.float8_e4m3fn, dequant_edge="post")))
            guard("block_fp8_expert", lambda: emit(
                "block_fp8_expert", bench_ep_block(
                    ctx, i1=10, i2=60 if quick else 210,
                    wire_dtype=jnp.float8_e4m3fn,
                    dequant_edge="expert")))


if __name__ == "__main__":
    main()
