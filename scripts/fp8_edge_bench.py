"""On-chip microbench for the fp8-wire edge wirings (VERDICT r4 #6).

Round-2 measured 128.8 µs fp8 dispatch (quant-pre-gather + post-kernel
dequant); round-3 replaced both edges untested (fused f32 gather+quant +
in-kernel dequant) and the round-4 campaign measured it at 201.8 µs — a
regression. This sweeps all four (quant_edge, dequant_edge) wirings of the
1-tier dispatch at the DeepSeek-infer shape so docs/benchmarks.md records a
measured table and the context default is the winner, not a guess.

    python scripts/fp8_edge_bench.py              # full sweep
    python scripts/fp8_edge_bench.py --quick      # fewer chain iters

Prints one JSON line per wiring plus the bf16 reference point.
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

from bench import _per_iter, make_chain_timer  # noqa: E402
from triton_dist_tpu.ops.all_to_all import (  # noqa: E402
    create_all_to_all_context, dispatch)
from triton_dist_tpu.shmem.context import initialize_distributed  # noqa: E402
from triton_dist_tpu.utils import on_cpu  # noqa: E402


def bench_wiring(ctx, quant_edge, dequant_edge, i1, i2, shape,
                 wire_dtype=jnp.float8_e4m3fn, expert_major=False):
    """Dispatch latency for one wiring; ``wire_dtype=None`` is the bf16
    reference point (quant/dequant edges absent, same chain otherwise)."""
    kw = ({} if wire_dtype is None
          else dict(wire_dtype=wire_dtype, quant_edge=quant_edge,
                    dequant_edge=dequant_edge))
    a2a = create_all_to_all_context(ctx, axis=ctx.axis_names[0],
                                    expert_major=expert_major, **kw,
                                    **shape)
    n = a2a.n_ranks
    T = n * shape["max_tokens"]
    H = shape["hidden"]
    tokens = ctx.shard(jax.random.normal(jax.random.key(0), (T, H),
                                         jnp.float32).astype(jnp.bfloat16),
                       P("x"))
    ids = ctx.shard(jax.random.randint(jax.random.key(1),
                                       (T, shape["topk"]), 0,
                                       shape["num_experts"]), P("x"))

    def step(t, i):
        recv, _, _ = dispatch(a2a, t, i)
        eps = (jnp.sum(recv.astype(jnp.float32)) * 1e-20).astype(t.dtype)
        return t + eps

    return _per_iter(make_chain_timer(step, tokens, ids), i1, i2)


def main() -> int:
    quick = "--quick" in sys.argv
    n_dev = len(jax.devices())
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(n_dev,))
    if on_cpu():
        shape = dict(max_tokens=16, hidden=256, topk=2, num_experts=8)
        i1, i2 = 1, 3
    else:
        shape = dict(max_tokens=128, hidden=7168, topk=8, num_experts=64)
        i1, i2 = (10, 410) if quick else (10, 1610)

    # bf16 reference point (no wire; same chain as the fp8 wirings)
    s = bench_wiring(ctx, None, None, i1, i2, shape, wire_dtype=None)
    print(json.dumps({"wiring": "bf16_reference",
                      "dispatch_us": round(s * 1e6, 1)}), flush=True)

    # "kernel" quantizes tile-by-tile INSIDE the collective (no standalone
    # qpack pass on the send edge — the fused-send wiring); --expert-major
    # repeats the sweep on the per-expert-slot capacity layout
    em_opts = ((False, True) if "--expert-major" in sys.argv
               else (False,))
    for em in em_opts:
        for qe in ("pre", "fused", "kernel"):
            for de in ("post", "kernel"):
                tag = f"{qe}+{de}" + ("+em" if em else "")
                try:
                    s = bench_wiring(ctx, qe, de, i1, i2, shape,
                                     expert_major=em)
                    print(json.dumps({"wiring": tag,
                                      "dispatch_us": round(s * 1e6, 1)}),
                          flush=True)
                except Exception as e:
                    print(json.dumps(
                        {"wiring": tag,
                         "error": f"{type(e).__name__}: {e}"[:160]}),
                        flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
