"""Kernel library — overlapping distributed ops (the analog of reference
python/triton_dist/kernels/nvidia/*, re-exported the same way its
kernels/nvidia/__init__.py:25-89 does).

This surface is load-bearing: ``triton_dist_tpu.analysis.registry`` (the
sigcheck static verifier) enumerates every name exported here and requires
each to be either protocol-checked or carry a documented skip, and
tests/test_sigcheck.py asserts the two stay in lockstep — add an export and
the registry must learn about it in the same change."""

from triton_dist_tpu.ops.common import collective_id_for, barrier_all_op  # noqa: F401
from triton_dist_tpu.ops.gemm import GemmConfig, best_gemm_config  # noqa: F401
from triton_dist_tpu.ops.allgather import (all_gather, all_gather_ll,  # noqa: F401
                                           AgLLContext,
                                           create_ag_ll_workspace, broadcast)
from triton_dist_tpu.ops.reduce_scatter import reduce_scatter  # noqa: F401
from triton_dist_tpu.ops.allgather_gemm import (  # noqa: F401
    AgGemmContext, ag_gemm, ag_gemm_ws, create_ag_gemm_context,
    create_ag_gemm_workspace, tp_column_linear)
from triton_dist_tpu.ops.gemm_reduce_scatter import (  # noqa: F401
    GemmRsContext, gemm_rs, gemm_rs_ws, create_gemm_rs_context,
    create_gemm_rs_workspace)
from triton_dist_tpu.ops.autodiff import ag_gemm_diff, gemm_rs_diff  # noqa: F401
from triton_dist_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention, ring_attention_fwd, ring_attention_bwd, zigzag_indices)
from triton_dist_tpu.ops.page_migrate import (migrate_pages,  # noqa: F401
                                              paged_transport)
from triton_dist_tpu.ops.lend_pages import lend_pages  # noqa: F401
from triton_dist_tpu.ops.all_to_all import (  # noqa: F401
    EpAllToAllContext, Ep2dAllToAllContext, all_to_all_push,
    all_to_all_push_seg, a2a_wire_bytes,
    pick_wire_dtype, create_all_to_all_context, create_all_to_all_context_2d,
    route_tokens, route_tokens_2d, dispatch, dispatch_2d, combine, combine_2d,
    expected_capacity)
from triton_dist_tpu.ops.flash_decode import (  # noqa: F401
    gqa_decode_partial, gqa_decode_paged, paged_kv_write, decode_combine,
    ll_ag_merge, sp_gqa_flash_decode, sp_paged_attend_write,
    pool_ag_start_local, flash_decode_dist)
from triton_dist_tpu.ops.group_gemm import (  # noqa: F401
    PackedGatedWeights, align_tokens_by_expert, used_block_count,
    emit_grouped_gemm, grouped_gemm, pack_gated_weights, grouped_gemm_gated,
    apply_grouped, moe_ffn_local)
from triton_dist_tpu.ops.moe import ag_moe_group_gemm, moe_reduce_rs  # noqa: F401
from triton_dist_tpu.ops.autotuned import (  # noqa: F401
    ag_gemm_autotuned, gemm_rs_autotuned, ag_moe_group_gemm_autotuned,
    grouped_gemm_autotuned, moe_ffn_gated_autotuned, moe_reduce_rs_autotuned,
    ring_attention_autotuned)
