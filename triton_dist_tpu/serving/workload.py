"""Bursty heterogeneous workload generation (ISSUE 14).

The multi-tenant scheduler is only as honest as the traffic it is tested
under, so this module supplies the adversarial-but-deterministic trace
the SLO tests and ``scripts/serve_sim.py --workload`` replay:

- **Zipf prompt sharing** — prompts draw a shared page-aligned prefix
  from a small pool with Zipf(``zipf``) popularity plus a random tail,
  the shape prefix caching (ISSUE 13) and cache-aware routing exist for.
- **chat vs batch classes** — two request populations: short interactive
  "chat" prompts with small decode budgets and long "batch" prompts with
  large ones, each stamped with a tenant drawn from its own tenant pool.
- **diurnal bursts** — the base arrival rate multiplies by ``burst_x``
  for ``burst_len`` steps out of every ``burst_every`` (a square-wave
  "diurnal" cycle), so overload arrives in waves rather than uniformly —
  the regime where per-class shedding and WFQ isolation actually matter.

Everything is a pure function of the spec (``numpy.random.RandomState``
seeded from ``seed``): the same spec string replays the same 5-tuple
arrival list ``(step, prompt, max_new_tokens, tenant, cls)`` bitwise,
which is what lets flood-isolation tests compare admitted traces against
uncontended goldens.

Spec strings are ``key=value`` pairs joined by commas, e.g.::

    n=200,seed=7,chat=0.7,rate=0.5,burst_every=64,burst_len=16,
    burst_x=4,zipf=1.2,prefixes=8,tenants=3,plen=4:20,mnt=2:10

``parse_workload`` validates every field and raises ``ValueError``
NAMING the offending field — a CLI typo fails loudly, not as a silently
default-shaped trace.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from triton_dist_tpu.serving.scheduler import SLOPolicy


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """One bursty two-class trace, fully determined by its fields."""

    n: int = 100                # total requests
    seed: int = 0
    chat: float = 0.7           # P(class == "chat")
    long: float = 0.0           # P(class == "long"); rest is "batch"
    rate: float = 0.5           # base arrivals per engine step
    burst_every: int = 64       # diurnal period (steps)
    burst_len: int = 16         # burst window within each period (steps)
    burst_x: float = 4.0        # rate multiplier inside the window
    zipf: float = 1.2           # shared-prefix popularity exponent (> 1)
    prefixes: int = 8           # shared-prefix pool size (0 = no sharing)
    tenants: int = 3            # tenant pool size PER class
    plen: tuple[int, int] = (4, 20)   # inclusive prompt-length range
    mnt: tuple[int, int] = (2, 10)    # inclusive decode-budget range
    lplen: tuple[int, int] = (64, 128)   # long-class prompt-length range

    def validate(self) -> "WorkloadSpec":
        def bad(field: str, why: str):
            raise ValueError(
                f"workload spec field '{field}': {why} "
                f"(got {getattr(self, field)!r})")
        if self.n < 1:
            bad("n", "must be >= 1")
        if self.seed < 0:
            bad("seed", "must be >= 0")
        if not 0.0 <= self.chat <= 1.0:
            bad("chat", "must be in [0, 1]")
        if not 0.0 <= self.long <= 1.0:
            bad("long", "must be in [0, 1]")
        if self.chat + self.long > 1.0:
            bad("long", "chat + long must be <= 1")
        if self.rate <= 0:
            bad("rate", "must be > 0")
        if self.burst_every < 1:
            bad("burst_every", "must be >= 1")
        if not 0 <= self.burst_len <= self.burst_every:
            bad("burst_len", "must be in [0, burst_every]")
        if self.burst_x < 1.0:
            bad("burst_x", "must be >= 1")
        if self.zipf <= 1.0:
            bad("zipf", "must be > 1")
        if self.prefixes < 0:
            bad("prefixes", "must be >= 0")
        if self.tenants < 1:
            bad("tenants", "must be >= 1")
        if not (1 <= self.plen[0] <= self.plen[1]):
            bad("plen", "must be LO:HI with 1 <= LO <= HI")
        if not (1 <= self.mnt[0] <= self.mnt[1]):
            bad("mnt", "must be LO:HI with 1 <= LO <= HI")
        if not (1 <= self.lplen[0] <= self.lplen[1]):
            bad("lplen", "must be LO:HI with 1 <= LO <= HI")
        if self.long > 0 and self.lplen[0] <= self.plen[1]:
            bad("lplen", "long prompts must be LONGER than plen's HI — "
                "the class exists to stress the long-context path")
        return self


_INT_FIELDS = ("n", "seed", "burst_every", "burst_len", "prefixes",
               "tenants")
_FLOAT_FIELDS = ("chat", "long", "rate", "burst_x", "zipf")
_RANGE_FIELDS = ("plen", "mnt", "lplen")


def parse_workload(spec: str) -> WorkloadSpec:
    """Parse ``key=value,...`` into a validated :class:`WorkloadSpec`.
    Every failure mode names the bad field."""
    kw: dict = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(
                f"workload spec field {part!r}: expected key=value")
        key, val = (s.strip() for s in part.split("=", 1))
        if key in _INT_FIELDS:
            try:
                kw[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"workload spec field '{key}': expected an integer "
                    f"(got {val!r})") from None
        elif key in _FLOAT_FIELDS:
            try:
                kw[key] = float(val)
            except ValueError:
                raise ValueError(
                    f"workload spec field '{key}': expected a number "
                    f"(got {val!r})") from None
        elif key in _RANGE_FIELDS:
            try:
                lo, hi = (int(s) for s in val.split(":"))
            except ValueError:
                raise ValueError(
                    f"workload spec field '{key}': expected LO:HI "
                    f"integers (got {val!r})") from None
            kw[key] = (lo, hi)
        else:
            known = ", ".join(_INT_FIELDS + _FLOAT_FIELDS + _RANGE_FIELDS)
            raise ValueError(
                f"workload spec field '{key}': unknown field "
                f"(known: {known})")
    return WorkloadSpec(**kw).validate()


def rate_at(spec: WorkloadSpec, step: int) -> float:
    """The square-wave diurnal rate: ``rate * burst_x`` inside the burst
    window of each period, ``rate`` outside. Public since ISSUE 18 — the
    autoscale panel plots the offered-rate timeline against the fleet-
    size timeline from this exact function, so the two always agree."""
    if spec.burst_len and (step % spec.burst_every) < spec.burst_len:
        return spec.rate * spec.burst_x
    return spec.rate


_rate_at = rate_at


def generate_arrivals(spec: WorkloadSpec, vocab: int = 32000,
                      page_size: int = 8
                      ) -> list[tuple[int, list[int], int, str, str]]:
    """Materialize the trace: a step-sorted list of 5-tuple arrivals
    ``(step, prompt, max_new_tokens, tenant, cls)`` — the shape every
    engine's ``run(arrivals=...)`` now accepts.

    Chat prompts/budgets draw from the lower half of the configured
    ranges, batch from the upper half — the heterogeneity (short
    interactive vs long throughput work) the deadline-aware chunk sizing
    and per-class shedding are tested against.

    ``long > 0`` (ISSUE 19) adds a third population: prompts drawn from
    the ``lplen`` range (strictly above ``plen``) with chat-sized decode
    budgets — the "summarize this 64k document" shape the sharded
    long-context engine serves. Long prompts never ride the shared-
    prefix pool (their cost IS the unique prompt). The class draw
    partitions the SAME uniform the two-class generator consumed, so a
    ``long=0`` spec replays the pre-ISSUE-19 trace bitwise.
    """
    rng = np.random.RandomState(spec.seed)
    # shared page-aligned prefixes with Zipf popularity (ISSUE 13 shape)
    pool = []
    weights = None
    if spec.prefixes:
        pre_len = max(page_size, (spec.plen[0] // page_size) * page_size)
        pool = [rng.randint(1, vocab, size=pre_len).tolist()
                for _ in range(spec.prefixes)]
        weights = np.arange(1, spec.prefixes + 1,
                            dtype=np.float64) ** -spec.zipf
        weights /= weights.sum()

    def _half_range(lo: int, hi: int, upper: bool) -> tuple[int, int]:
        mid = (lo + hi) // 2
        return (mid, hi) if upper else (lo, mid)

    out = []
    t = 0.0
    for _ in range(spec.n):
        step = int(t)
        # inter-arrival gap from the CURRENT window's rate; the draw
        # happens unconditionally so the stream of RNG consumption — and
        # with it every downstream prompt — is fixed by (seed, n) alone
        t += float(rng.exponential(1.0 / _rate_at(spec, step)))
        u = float(rng.uniform())
        cls = ("chat" if u < spec.chat
               else "long" if u < spec.chat + spec.long else "batch")
        tenant = f"{cls[0]}{int(rng.randint(spec.tenants))}"
        if cls == "long":
            plen = int(rng.randint(spec.lplen[0], spec.lplen[1] + 1))
            mlo, mhi = _half_range(*spec.mnt, upper=False)
            mnt = int(rng.randint(mlo, mhi + 1))
            prompt = rng.randint(1, vocab, size=plen).tolist()
            out.append((step, prompt, mnt, tenant, cls))
            continue
        is_batch = cls == "batch"
        plo, phi = _half_range(*spec.plen, upper=is_batch)
        mlo, mhi = _half_range(*spec.mnt, upper=is_batch)
        plen = int(rng.randint(plo, phi + 1))
        mnt = int(rng.randint(mlo, mhi + 1))
        if pool:
            k = int(rng.choice(spec.prefixes, p=weights))
            tail = rng.randint(1, vocab, size=max(plen, 1)).tolist()
            prompt = (pool[k] + tail)[:max(plen, 1)]
            if len(prompt) < plen:
                prompt = prompt + tail[:plen - len(prompt)]
        else:
            prompt = rng.randint(1, vocab, size=plen).tolist()
        out.append((step, prompt, mnt, tenant, cls))
    return out


def spec_bucket_of(spec: WorkloadSpec) -> int:
    """Workload-repetitiveness bucket for the ``serving_spec_k`` tuned
    key (ISSUE 20): 0 = no shared structure (empty prefix pool — the
    prompt-lookup drafter has nothing to replay, speculation mostly pays
    for wasted verify rows), 1 = moderate sharing, 2 = heavy sharing (a
    small hot pool under a steep Zipf — the regime where drafts hit and
    K should be large). Pure arithmetic on the spec: calling it draws no
    RNG, so threading it through a sim/bench NEVER perturbs the arrival
    trace the bitwise goldens replay."""
    if spec.prefixes == 0:
        return 0
    return 2 if spec.zipf >= 1.5 or spec.prefixes <= 4 else 1


def parse_slo(spec: str) -> SLOPolicy:
    """Parse an SLO-policy CLI spec into :meth:`SLOPolicy.chat_batch`.

    ``key=value`` pairs joined by commas; every failure names the field::

        chat_weight=4,batch_weight=1,batch_cap=8,batch_ttl=40,
        chat_stall=4,quota=b0:1:4|b1:2:8

    ``quota`` is ``tenant:rate:burst`` triples joined by ``|``. Any
    ``long_*`` field (ISSUE 19: ``long_weight``, ``long_chunk``,
    ``long_stall``, ``long_cap``, ``long_ttl``) inserts the long-context
    tier — see :meth:`SLOPolicy.chat_batch`.
    """
    kw: dict = {}
    quotas: dict[str, tuple[int, int]] = {}
    int_fields = {"chat_weight": "chat_weight", "batch_weight":
                  "batch_weight", "batch_cap": "batch_queue_cap",
                  "batch_ttl": "batch_ttl_steps",
                  "chat_stall": "chat_stall_budget",
                  "long_weight": "long_weight",
                  "long_chunk": "long_chunk_budget",
                  "long_stall": "long_stall_budget",
                  "long_cap": "long_queue_cap",
                  "long_ttl": "long_ttl_steps"}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(
                f"slo spec field {part!r}: expected key=value")
        key, val = (s.strip() for s in part.split("=", 1))
        if key in int_fields:
            try:
                kw[int_fields[key]] = int(val)
            except ValueError:
                raise ValueError(
                    f"slo spec field '{key}': expected an integer "
                    f"(got {val!r})") from None
        elif key == "quota":
            for trip in filter(None, val.split("|")):
                try:
                    tenant, rate, burst = trip.split(":")
                    quotas[tenant] = (int(rate), int(burst))
                except ValueError:
                    raise ValueError(
                        "slo spec field 'quota': expected "
                        f"tenant:rate:burst triples joined by | "
                        f"(got {trip!r})") from None
        else:
            known = ", ".join(list(int_fields) + ["quota"])
            raise ValueError(
                f"slo spec field '{key}': unknown field (known: {known})")
    return SLOPolicy.chat_batch(quotas=quotas or None, **kw)


__all__ = ["WorkloadSpec", "parse_workload", "generate_arrivals",
           "parse_slo", "rate_at", "spec_bucket_of"]
