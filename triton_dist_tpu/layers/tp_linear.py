"""Tensor-parallel linear layers over the overlap kernels — the module-level
API the reference exposes through tutorials 07/08 (AG-GEMM forward,
GEMM-RS forward) rather than as classes; provided as first-class layers
here.

With ``persistent=True`` a layer owns reusable symmetric workspaces (the
reference's create-context-once pattern, allgather_gemm.py:785-832) and must
be called eagerly — each call is internally jitted with workspace donation.
The default (non-persistent) form is freely jit-composable but allocates a
fresh workspace per call.
"""

from __future__ import annotations

import dataclasses

import jax

from triton_dist_tpu.ops.allgather_gemm import (AgGemmContext, ag_gemm,
                                                create_ag_gemm_context)
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import (GemmRsContext,
                                                     create_gemm_rs_context,
                                                     gemm_rs)
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass
class ColumnParallelLinear:
    """y = all_gather(x) @ W with W column-sharded — the Megatron-style
    first TP linear, computed by the AG-GEMM overlap kernel
    (cf. reference allgather_gemm.py:835-880)."""
    ctx: ShmemContext
    axis: str | None = None
    cfg: GemmConfig | None = None
    persistent: bool = False
    _ag_ctxs: dict = dataclasses.field(default_factory=dict)

    def __call__(self, x: jax.Array, w: jax.Array, out_dtype=None):
        if self.persistent:
            n = self.ctx.axis_size(self.axis or self.ctx.axis_names[0])
            key = (x.shape[0] // n, x.shape[1], str(x.dtype))
            agc = self._ag_ctxs.get(key)
            if agc is None:
                agc = self._ag_ctxs[key] = create_ag_gemm_context(
                    self.ctx, key[0], key[1], x.dtype, axis=self.axis)
            return agc(x, w, cfg=self.cfg, out_dtype=out_dtype)
        return ag_gemm(self.ctx, x, w, axis=self.axis, cfg=self.cfg,
                       out_dtype=out_dtype)


@dataclasses.dataclass
class RowParallelLinear:
    """y = reduce_scatter(x @ W) with W row-sharded — the second TP linear,
    computed by the GEMM-RS overlap kernel
    (cf. reference gemm_reduce_scatter.py:524-538)."""
    ctx: ShmemContext
    axis: str | None = None
    cfg: GemmConfig | None = None
    persistent: bool = False
    _rs_ctxs: dict = dataclasses.field(default_factory=dict)

    def __call__(self, x: jax.Array, w: jax.Array, out_dtype=None):
        if self.persistent:
            n = self.ctx.axis_size(self.axis or self.ctx.axis_names[0])
            out_dt = out_dtype or x.dtype
            key = (x.shape[0] // n, w.shape[1], str(out_dt))
            rsc = self._rs_ctxs.get(key)
            if rsc is None:
                rsc = self._rs_ctxs[key] = create_gemm_rs_context(
                    self.ctx, key[0], key[1], out_dt, axis=self.axis)
            return rsc(x, w, cfg=self.cfg, out_dtype=out_dtype)
        return gemm_rs(self.ctx, x, w, axis=self.axis, cfg=self.cfg,
                       out_dtype=out_dtype)
