"""The decode engine: drives ``models.llama.decode_multistep_paged``
under ``jax.jit`` so the hot loop is ONE compiled program per DISPATCH —
and one dispatch advances every slot up to ``decode_horizon`` tokens.

Shape discipline (the TPU contract):

- the batch is ``num_slots`` fixed rows; a request occupies one slot from
  admission to finish. Inactive rows are parked on the reserved scratch
  page (page 0) with pos 0 — their writes land on scratch, their tokens
  are ignored, and the compiled step never sees a shape change.
- the page pool rides the jitted step as a DONATED argument (on backends
  that support donation), so the per-layer scatter of the new (k, v)
  updates pages in place — no pool-sized copy per token.
- prefill runs per request OUTSIDE the batch into a small contiguous
  cache — the layout the full-sequence kernels want — then
  ``cache_to_pages`` hands the pages to the pool. Prompts are padded to
  BUCKET lengths (power-of-two by default) with an attention length mask,
  so the prefill compile cache is O(log max_prompt), not one program per
  distinct prompt length. With ``prefill_chunk`` set the admit path is
  CHUNKED instead: ``prefill_chunk_paged`` writes each chunk's KV
  straight into pages through the block table (no contiguous cache, no
  converter copies, device-fused first-token argmax), at most one chunk
  per engine step co-scheduled with the decode dispatch — see the class
  docstring.

Device-resident hot loop (the host/device split):

- sampling is fused: the jitted program argmaxes on device and the host
  downloads a ``[horizon, num_slots]`` int32 token slab — never the
  ``[B, vocab]`` logits.
- ``token``/``pos``/``block_table`` live on device between dispatches;
  the host keeps numpy MIRRORS for control decisions (growth, finishes,
  preemption) and re-uploads only after a control-plane change (counted
  as ``host_syncs`` — a quiet dispatch uploads nothing but the per-slot
  ``limit`` word).
- ``decode_horizon=K`` runs K fused steps in one ``lax.scan`` dispatch;
  the per-slot ``limit`` input clamps each row to
  ``min(K, budget, pre-ensured page capacity)`` so no slot can outgrow
  its pages mid-scan, and rows freeze on EOS. The engine reconciles
  scheduler state (finishes, growth, preemption) every K tokens — K=1
  preserves per-token semantics exactly.

Determinism: greedy argmax decode + deterministic allocation and policies
mean a request's tokens are a pure function of (params, prompt) — a
preempted-and-restarted request regenerates exactly the tokens it lost,
and a contended run is bit-identical per request to an uncontended one,
at every horizon (tests/test_serving.py asserts both for K in {1, 4}).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.llama import (LlamaConfig,
                                          decode_multistep_paged,
                                          decode_speculate_paged,
                                          init_kv_cache, init_page_pool,
                                          prefill, prefill_chunk_paged)
from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.deadline import Deadline, EngineStallError
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import KVPagePool, _fnv1a, cache_to_pages
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.prefix_cache import PrefixCache
from triton_dist_tpu.serving.scheduler import (AdmissionRejected,
                                               ContinuousBatchingScheduler,
                                               Request, RequestState,
                                               SLOPolicy, TtlExpired)
from triton_dist_tpu.shmem import faults as faults_mod
from triton_dist_tpu.shmem.faults import InjectedCrash


# -- role-shared bookkeeping helpers ----------------------------------------
# The colocated engine plays BOTH serving roles; the disaggregated engine
# (serving/disagg.py) splits them across workers. These module-level
# helpers are the prefill-role half both share, so TTFT semantics cannot
# drift between the colocated and disaggregated paths.

def class_label(req: Request) -> str | None:
    """Per-class metric label for a request (ISSUE 14): None for the
    unclassed default so an engine without a policy emits exactly the
    pre-ISSUE-14 metric panel — labeled series are pay-for-play."""
    return req.cls if req.cls != "default" else None


def mark_prefill_start(req: Request, metrics: ServingMetrics,
                       step: int) -> None:
    """TTFT-split bookkeeping: queue time ends at FIRST admission
    (re-admissions after preemption keep the original clock)."""
    if req.prefill_start_time is None:
        req.prefill_start_step = step
        req.prefill_start_time = time.perf_counter()
        metrics.observe("ttft_queue_s",
                        req.prefill_start_time - req.submit_time)


def record_first_token(req: Request, metrics: ServingMetrics,
                       step: int) -> None:
    """First-token bookkeeping — TTFT clocks close where the token is
    COMPUTED (the prefill role), never where it is eventually served."""
    if req.first_token_time is None:
        req.first_token_step = step
        req.first_token_time = time.perf_counter()
        metrics.observe("ttft_s", req.first_token_time - req.submit_time)
        metrics.observe("ttft_prefill_s",
                        req.first_token_time - req.prefill_start_time)
        metrics.observe_class("ttft_s", class_label(req),
                              req.first_token_time - req.submit_time)


class ServingEngine:
    """Continuous-batching serving engine over the paged decode step.

    ``num_pages`` counts usable pages; one extra scratch page (id 0) is
    allocated on top for inactive rows. ``pages_per_seq`` bounds one
    sequence's pages (the block table width — a compiled-shape constant).
    ``ffn(h, p) -> [B, D]`` plugs a custom per-layer FFN into the decode
    step (e.g. ``moe_mlp_ep_overlap`` for the EP-MoE serving path, the
    same hook ``decode_step``/``decode_step_sp`` expose).

    ``decode_horizon`` is K, the inner scanned steps per dispatch (see
    module docstring). ``prefill_buckets`` is ``"pow2"`` (pad prompts to
    the next power of two, floor 8), an explicit ascending tuple of
    bucket lengths, or ``None`` for exact-length prefill (one compile per
    distinct prompt length — the pre-bucketing behavior, bit-exact).
    ``eos_id`` enables early finish: a slot freezes on device the step it
    emits ``eos_id`` and the host finishes the request at reconcile.

    ``prefill_chunk`` (ISSUE 5 tentpole) switches admission to CHUNKED
    PAGED prefill: an admitted slot enters PREFILLING holding its pages
    and a chunk cursor, and each ``step()`` dispatches AT MOST ONE
    ``prefill_chunk``-token chunk (``models.llama.prefill_chunk_paged``)
    alongside the batched decode dispatch — Sarathi-style co-scheduling
    that bounds the per-step decode stall by one chunk instead of a
    whole prompt. KV goes straight into pages through the block table
    (no contiguous cache, no ``cache_to_pages`` copies) and the first
    token's argmax is fused on device (no host logits download). One
    compiled chunk program serves every prompt length — with chunking on
    the prefill jit cache is O(1) and ``prefill_buckets`` is unused.
    ``prefill_chunk=None`` (default) keeps the bucketed inline path
    bit-for-bit.
    """

    def __init__(self, params: dict, cfg: LlamaConfig, num_slots: int = 4,
                 page_size: int = 16, num_pages: int = 64,
                 pages_per_seq: int = 8, ffn=None,
                 max_prefills_per_step: int | None = None,
                 metrics: ServingMetrics | None = None,
                 decode_horizon: int = 1,
                 prefill_buckets="pow2",
                 eos_id: int | None = None,
                 prefill_chunk: int | None = None,
                 stall_deadline_steps: int = 256,
                 ffn_chunk=None, attn_io=None, linear=None,
                 journal: ControlJournal | None = None,
                 checkpoint_every: int | None = None,
                 queue_cap: int | None = None,
                 ttl_steps: int | None = None,
                 fault_plan=None,
                 prefix_cache: bool = False,
                 slo: SLOPolicy | None = None,
                 artifact=None, artifact_key: str | None = None,
                 speculate: int | str | None = None,
                 spec_hist: int = 64, spec_bucket: int = 0):
        assert decode_horizon >= 1
        assert prefill_chunk is None or prefill_chunk >= 1
        assert not prefix_cache or prefill_chunk is not None, (
            "prefix_cache needs prefill_chunk set — a cache hit resumes "
            "chunked prefill at its cursor; the bucketed inline path has "
            "no cursor to resume at")
        assert stall_deadline_steps >= 1
        assert checkpoint_every is None or checkpoint_every >= 1
        assert queue_cap is None or queue_cap >= 1
        assert ttl_steps is None or ttl_steps >= 1
        self.params = params
        self.cfg = cfg
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.num_slots = num_slots
        self.max_prefills_per_step = max_prefills_per_step
        self.metrics = metrics or ServingMetrics()
        self.decode_horizon = decode_horizon
        self.eos_id = eos_id
        self._stall_steps = stall_deadline_steps
        # speculative decoding (ISSUE 20): speculate = draft length K
        # (int), "auto" (PR 15 registry → default), or None/0/"off".
        # When on, the decode program is decode_speculate_paged — ONE
        # dispatch drafts K-1 tokens, verifies all K positions in one
        # paged-attention pass, and commits the longest draft==argmax
        # prefix; decode_horizon doubles as K so the limits clamp
        # (min(horizon, remaining, page headroom)) bounds the accept
        # burst exactly as it bounds the multistep scan.
        self.spec_k = 0
        self.spec_hist = int(spec_hist)
        if speculate not in (None, 0, "off"):
            assert decode_horizon == 1, (
                "speculate replaces the multistep scan — the verify pass "
                "scores K positions per dispatch, so decode_horizon must "
                "stay 1 when speculation is on")
            assert self.spec_hist >= 8, (
                "spec_hist must be >= 8 — a shorter drafter window cannot "
                "hold a bigram plus its continuation")
            from triton_dist_tpu.serving.speculate import resolve_spec_k
            self.spec_k = resolve_spec_k(
                speculate, getattr(self, "_spec_mesh_shape", ()),
                str(jnp.dtype(cfg.dtype)), spec_bucket)
            self.decode_horizon = self.spec_k
        if prefill_buckets is not None and prefill_buckets != "pow2":
            prefill_buckets = tuple(sorted(int(b) for b in prefill_buckets))
            assert prefill_buckets, "bucket list must be non-empty"
        self.prefill_buckets = prefill_buckets

        self.pool = init_page_pool(cfg, num_pages + 1, page_size)
        # unified pool contract (ISSUE 12): subclasses that shard the pool
        # arrays over SP set _pool_sp_ranks BEFORE super().__init__ so the
        # ledger knows the padded device page range (padding pages are
        # never handed out and never check_migratable-accepted)
        self.alloc = KVPagePool(num_pages + 1, page_size, reserved=1,
                                sp_ranks=getattr(self, "_pool_sp_ranks", 1),
                                layout=getattr(self, "_pool_layout",
                                               "blocked"))
        # prefix cache (ISSUE 13): a radix index over full-page token
        # runs of this pool's pages. Host-side control plane only — it
        # changes WHICH pages a block table points at, never what the
        # compiled programs look like, so compile counts and the sigcheck
        # lint are identical with it on or off.
        self.prefix_cache = PrefixCache(self.alloc, page_size) \
            if prefix_cache else None
        # cluster page lending (ISSUE 17): pages adopted FROM a peer
        # (splits rewarmed TTFT out of cached), the transient seq-id
        # generation adopt_prefix allocates under, and the rids whose
        # admission hit landed on lent pages
        self._lent_pages: set[int] = set()
        self._lend_gen = 0
        self._rewarmed_rids: set[int] = set()
        # multi-tenant SLO policy (ISSUE 14): entirely control-plane —
        # the policy changes WHICH request a slot admits and how many
        # prompt tokens a step co-schedules, never what the compiled
        # programs look like (zero new programs; compile_stats is flat).
        self.slo = slo
        # the smallest per-step prefill budget any class declares — the
        # deadline-aware chunk floor is pure configuration, precomputed
        self._stall_budgeted = slo is not None and any(
            c.stall_budget is not None for c in slo.classes)
        self.sched = ContinuousBatchingScheduler(num_slots,
                                                 queue_cap=queue_cap,
                                                 policy=slo)
        self._next_rid = 0
        self._steps = 0
        self._finished: list[Request] = []

        # crash consistency (ISSUE 9): the journal is the durable
        # artifact — a fresh engine + journal (which embeds periodic
        # checkpoints) reconstructs bit-identical serving state. See
        # serving/journal.py and serving/checkpoint.py.
        self.journal = journal
        self.checkpoint_every = checkpoint_every
        self.ttl_steps = ttl_steps
        self._fault_plan = fault_plan
        self._journal_muted = False     # True while replaying (restore)
        self._replaying = False         # replayed submits bypass the cap
        self._incarnation = 0           # bumped per restore (crash keying)
        self._preempt_hook = None       # composition override (ISSUE 12)
        self._last_ckpt_step = -1
        self._rejected: list[Request] = []

        # host-side mirrors of the per-slot device state (control plane);
        # the device copies below are authoritative between dispatches
        self._token = np.zeros(num_slots, np.int32)
        self._pos = np.zeros(num_slots, np.int32)
        self._bt = np.zeros((num_slots, pages_per_seq), np.int32)
        # drafter history window [B, H] (newest token at column H-1) +
        # valid-suffix lengths. Device-carried between dispatches when
        # speculation is on; the host mirrors the device's roll bitwise
        # so the hot path never re-uploads it (host_syncs stays flat).
        self._hist = np.zeros((num_slots, self.spec_hist), np.int32)
        self._hist_len = np.zeros(num_slots, np.int32)
        self._sync_mirrors()
        self._dirty = False                 # mirrors diverged from device

        # hooked paths (attn_io/linear — the sharded engine's SP attention
        # and TP projections; ffn_chunk — a chunk-row-count FFN distinct
        # from the decode one, needed when the FFN is shape-specialized
        # like the EP a2a dispatch) ride only through the CHUNKED admit
        # path: the bucketed inline prefill has no hook plumbing
        assert (attn_io is None and linear is None) or \
            prefill_chunk is not None, (
            "attn_io/linear hooks need prefill_chunk set — the bucketed "
            "inline prefill path does not thread them")
        K = self.decode_horizon
        if self.spec_k:
            def step(p, t, pos, pages, bt, lim, hist, hlen):
                return decode_speculate_paged(
                    p, t, pos, cfg, pages, bt, lim, horizon=K, hist=hist,
                    hist_len=hlen, eos_id=eos_id, ffn=ffn, attn_io=attn_io,
                    linear=linear)
        else:
            def step(p, t, pos, pages, bt, lim):
                return decode_multistep_paged(
                    p, t, pos, cfg, pages, bt, lim, horizon=K,
                    eos_id=eos_id, ffn=ffn, attn_io=attn_io, linear=linear)
        # pool-output sharding pin (sharded engine sets _pool_out_sharding
        # BEFORE calling super().__init__): without it, GSPMD may choose a
        # different output sharding for the pool than the committed SP
        # input sharding (the a2a's all_to_all regions perturb the
        # propagation; an internal with_sharding_constraint loses too) and
        # the SECOND dispatch recompiles against the flipped signature —
        # breaking the one-program-per-path contract ``compile_stats``
        # pins. out_shardings at the jit boundary always wins.
        ps = getattr(self, "_pool_out_sharding", None)
        # the fed-back token/pos carries are pinned replicated for the
        # same reason (their initial host uploads are committed to the
        # matching sharding by the sharded engine)
        rep = None if ps is None else \
            jax.sharding.NamedSharding(ps.mesh, jax.sharding.PartitionSpec())
        step_kw = {} if ps is None else {"out_shardings": (
            (None, None, rep, rep, rep, rep, {"k": ps, "v": ps})
            if self.spec_k else (None, rep, rep, {"k": ps, "v": ps}))}
        if jax.default_backend() == "cpu":
            self._step = jax.jit(step, **step_kw)  # CPU: no donation
        else:
            self._step = jax.jit(step, donate_argnums=(3,), **step_kw)
        self._prefill_jit = {}              # keyed by (bucket, cache_len)

        self.prefill_chunk = prefill_chunk
        self._chunk_step = None
        if prefill_chunk is not None:
            # ONE program for every prompt length/position: chunk size is
            # the only shape; cursor and prompt length ride as runtime
            # scalars (same trick as the decode limit argument)
            def chunk(p, t, s, n, pages, bt):
                return prefill_chunk_paged(
                    p, t, s, n, cfg, pages, bt, ffn=ffn_chunk or ffn,
                    attn_io=attn_io, linear=linear)
            chunk_kw = {} if ps is None else {
                "out_shardings": (None, {"k": ps, "v": ps})}
            if jax.default_backend() == "cpu":
                self._chunk_step = jax.jit(chunk, **chunk_kw)
            else:
                self._chunk_step = jax.jit(chunk, donate_argnums=(4,),
                                           **chunk_kw)

        # TDT_SIGCHECK=1: lint the engine's compiled programs against the
        # trace-determinism contract at BUILD time (sigcheck rung 0 — see
        # docs/debugging.md). Trace-only on abstract args; a rank-count-
        # dependent reduction or host callback in the hot path raises here,
        # before any request is admitted.
        if os.environ.get("TDT_SIGCHECK") == "1":
            from triton_dist_tpu.analysis.lint import lint_engine_programs
            abstract = lambda tree: jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
            # lint with the shapes the programs actually run on: the
            # sharded subclass pads the pool's page dim up to a multiple
            # of |sp| right after this ctor returns (unified pool
            # contract), so fold the same padding into the abstract args
            sp = getattr(self, "_pool_sp_ranks", 1)
            pool_abs = {
                k: jax.ShapeDtypeStruct(
                    v.shape[:1] + (v.shape[1] + (-v.shape[1]) % sp,)
                    + v.shape[2:], v.dtype)
                for k, v in self.pool.items()}
            if self.spec_k:
                programs = {"decode_speculate_paged": (step, (
                    abstract(self.params), i32(num_slots), i32(num_slots),
                    pool_abs, i32(num_slots, pages_per_seq),
                    i32(num_slots), i32(num_slots, self.spec_hist),
                    i32(num_slots)))}
            else:
                programs = {"decode_multistep_paged": (step, (
                    abstract(self.params), i32(num_slots), i32(num_slots),
                    pool_abs, i32(num_slots, pages_per_seq),
                    i32(num_slots)))}
            if prefill_chunk is not None:
                programs["prefill_chunk_paged"] = (chunk, (
                    abstract(self.params), i32(prefill_chunk), i32(), i32(),
                    pool_abs, i32(pages_per_seq)))
            lint_engine_programs(programs, type(self).__name__)

        # AOT artifact seeding (ISSUE 15): swap the freshly-built jit
        # objects for the artifact's deserialized programs so a cold start
        # reaches first token with ZERO fresh traces of the model code —
        # compile_stats reports the swap via the ``aot_programs`` key and
        # the replaced programs' trace caches stay at size 0 by
        # construction (LoadedProgram never traces its source).
        self._aot_artifact = artifact
        if artifact is not None:
            self._seed_from_artifact(artifact, artifact_key)

    # -- AOT artifact (ISSUE 15) ------------------------------------------
    def _default_artifact_key(self) -> str:
        return "colocated"

    def _seed_from_artifact(self, artifact, artifact_key: str | None) -> None:
        key = artifact_key or self._default_artifact_key()
        self._aot_key = key
        self._step = artifact.program(key, "decode")
        if self._chunk_step is not None:
            self._chunk_step = artifact.program(key, "chunk")
        for bucket, cache_len in artifact.prefill_keys(key):
            self._prefill_jit[(bucket, cache_len)] = artifact.program(
                key, f"prefill:{bucket}x{cache_len}")

    def _sync_mirrors(self) -> None:
        """Upload the host slot mirrors to the device copies. The sharded
        engine overrides this to COMMIT the uploads to the mesh (matching
        the jit out_shardings pin) — pjit's executable cache keys on input
        sharding/committed-ness, so a flip between an uncommitted first
        upload and the committed fed-back outputs would cost one spurious
        recompile per program."""
        self._token_dev = jnp.asarray(self._token)
        self._pos_dev = jnp.asarray(self._pos)
        self._bt_dev = jnp.asarray(self._bt)
        if self.spec_k:
            self._hist_dev = jnp.asarray(self._hist)
            self._hlen_dev = jnp.asarray(self._hist_len)

    # -- ledger id → device row (ISSUE 19) --------------------------------
    # The ledger allocates in ID space; the device arrays are indexed in
    # ROW space (``KVPagePool.device_row`` — identity under the default
    # blocked layout, the round-robin bijection under the long-context
    # interleaved layout). EVERY id that crosses the host→device boundary
    # — block-table uploads and host-side pool gathers/scatters — goes
    # through these two helpers; journal/digest/snapshot payloads stay in
    # id space, so the control-plane trace is layout-independent.

    def _device_rows(self, ids) -> np.ndarray:
        return np.asarray([self.alloc.device_row(int(p)) for p in ids],
                          np.int32)

    def _device_bt_row(self, rid) -> np.ndarray:
        return self._device_rows(
            self.alloc.block_table_row(rid, self.pages_per_seq))

    # -- request intake ---------------------------------------------------
    def _ttl_for(self, req: Request) -> int | None:
        """Effective TTL: the class's override when the policy sets one,
        else the engine-global ``ttl_steps``."""
        spec = self.sched.class_spec(req)
        if spec is not None and spec.ttl_steps is not None:
            return spec.ttl_steps
        return self.ttl_steps

    def submit(self, prompt, max_new_tokens: int, rid: int | None = None,
               tenant: str | None = None, cls: str | None = None) -> int:
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        assert prompt and max_new_tokens >= 1
        total = len(prompt) + max_new_tokens - 1   # KV the request may hold
        need = -(-total // self.page_size)
        assert need <= self.pages_per_seq, (
            f"request needs {need} pages > pages_per_seq "
            f"{self.pages_per_seq}")
        assert need <= self.alloc.num_pages - self.alloc.reserved, (
            f"request needs {need} pages > pool size — it could never run "
            "even alone")
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      eos_token=self.eos_id,
                      submit_step=self._steps,
                      submit_time=time.perf_counter())
        self.sched.stamp(req, tenant, cls)
        self.metrics.inc("requests_submitted")
        self.metrics.inc_class("requests_submitted", class_label(req))
        # bounded admission (ISSUE 9/14): shed fresh arrivals when the
        # queue — global or THIS CLASS's budget — is at capacity. A typed
        # terminal naming the class, never an exception into the
        # submitter. Journal replay bypasses the cap: the journal already
        # holds the authoritative accept/reject decisions.
        if self.sched.at_capacity_for(req.cls) and not self._replaying:
            cap = self.sched.queue_cap if self.sched.at_capacity else \
                self.sched.policy.spec(req.cls).queue_cap
            req.state = RequestState.REJECTED
            req.failure = AdmissionRejected(
                f"admission queue full for class {req.cls!r} (cap {cap}) "
                f"— request {rid} rejected")
            self._rejected.append(req)
            self.metrics.inc("rejections")
            self.metrics.inc_class("rejections", class_label(req))
            self._jlog("reject", rid=rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)
            return rid
        ttl = self._ttl_for(req)
        if ttl is not None:
            req.deadline = Deadline(ttl, req.submit_step)
        self.sched.submit(req)
        self._jlog("submit", rid=rid, prompt=list(prompt),
                   max_new_tokens=max_new_tokens,
                   tenant=req.tenant, cls=req.cls)
        return rid

    # -- prefill + admission ----------------------------------------------
    def _bucket_len(self, prompt_len: int) -> int:
        """Bucket (padded) length for a prompt — the compile-cache key."""
        if self.prefill_buckets is None:
            return prompt_len
        if self.prefill_buckets == "pow2":
            b = 8
            while b < prompt_len:
                b *= 2
            return b
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill "
            f"bucket {self.prefill_buckets[-1]}")

    def _prefill_fn(self, bucket: int, cache_len: int):
        key = (bucket, cache_len)
        if key not in self._prefill_jit:
            if self._aot_artifact is not None:
                # artifact-seeded engines never trace: a bucket outside
                # the artifact's program set is a typed loud miss, not a
                # silent fresh compile on the serving path
                from triton_dist_tpu.aot.artifact import ArtifactMissError
                raise ArtifactMissError(
                    f"prefill bucket {bucket} (cache_len {cache_len}) is "
                    f"not in the artifact's program set for "
                    f"{self._aot_key!r} — rebuild the artifact with this "
                    f"bucket declared")
            cfg = self.cfg
            if self.prefill_buckets is None:
                # exact mode: the legacy no-length trace, bit-for-bit
                self._prefill_jit[key] = jax.jit(
                    lambda p, t, c, n: prefill(p, t, cfg, c))
            else:
                self._prefill_jit[key] = jax.jit(
                    lambda p, t, c, n: prefill(p, t, cfg, c, length=n))
        return self._prefill_jit[key]

    def _mark_prefill_start(self, req: Request) -> None:
        mark_prefill_start(req, self.metrics, self._steps)

    def _admit(self, slot: int, req: Request) -> None:
        if self.prefill_chunk is not None:
            self._admit_chunked(slot, req)
            return
        sp = len(req.prompt)
        bucket = self._bucket_len(sp)
        self._mark_prefill_start(req)
        n_pages = -(-sp // self.page_size)
        pages = self.alloc.alloc(req.rid, n_pages)
        assert pages is not None, "admissible() guaranteed the pages"
        cache_len = -(-bucket // self.page_size) * self.page_size
        cache = init_kv_cache(self.cfg, 1, cache_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :sp] = req.prompt
        logits, cache = self._prefill_fn(bucket, cache_len)(
            self.params, jnp.asarray(toks), cache,
            jnp.asarray([sp], np.int32))
        # only the prompt's pages are handed off; in-page padding tail
        # rows hold padded K/V but decode overwrites position p before
        # any read of kv_len > p sees it
        bt_row = jnp.asarray(self._device_rows(pages)[None])
        self.pool = {
            "k": cache_to_pages(cache["k"], self.pool["k"], bt_row),
            "v": cache_to_pages(cache["v"], self.pool["v"], bt_row),
        }
        tok0 = int(np.argmax(np.asarray(logits[0])))
        self.sched.activate(slot, req)
        self._jlog("admit", rid=req.rid, slot=slot)
        req.generated.append(tok0)
        self.metrics.inc("prefills")
        self.metrics.inc("tokens_generated")
        record_first_token(req, self.metrics, self._steps)
        self._token[slot] = tok0
        self._pos[slot] = sp
        self._bt[slot] = self._device_bt_row(req.rid)
        self._seed_hist(slot, req)
        self._dirty = True
        if req.done:            # max_new_tokens == 1 or tok0 == eos_id
            self._finish(slot)

    # -- chunked paged prefill (the PREFILLING state machine) -------------
    def _cache_adopt(self, req: Request) -> None:
        """Prefix-cache admission half (ISSUE 13): match the prompt
        against the radix index and ADOPT the hit pages — refcounts bump,
        the block table will point at them, and chunked prefill resumes
        at the first miss (the same cursor mechanics a mid-prefill
        preemptee uses). On a whole-prompt hit only the LAST position is
        recomputed (its fused argmax is the first token); that write
        lands in the final adopted page, so the page is COWed first when
        shared — the one organic divergence point on the colocated path.
        Only a FRESH admission matches: a preemptee resuming at its
        cursor already owns its pages."""
        cache = self.prefix_cache
        if cache is None or req.prefill_cursor > 0 \
                or self.alloc.holds(req.rid):
            return
        hit = cache.match(req.prompt)
        sp = len(req.prompt)
        if not hit:
            self.metrics.inc("prefix_misses")
            return
        self.alloc.acquire(req.rid, hit)
        hit_tokens = len(hit) * self.page_size
        if hit_tokens >= sp:
            # whole prompt cached — resume at sp-1, never sp: the final
            # chunk must still run for its on-device first-token argmax
            req.prefill_cursor = sp - 1
            self._cow_writable(req, (sp - 1) // self.page_size)
        else:
            req.prefill_cursor = hit_tokens
        req.cache_hit_tokens = req.prefill_cursor
        self.metrics.inc("prefix_hits")
        self.metrics.inc("prefix_hit_tokens", req.prefill_cursor)
        if any(p in self._lent_pages for p in hit):
            # the hit rode pages a peer lent us — TTFT reports as
            # "rewarmed", the kill/restore acceptance band (ISSUE 17)
            self._rewarmed_rids.add(req.rid)

    def _reclaim(self, n_pages: int) -> None:
        """Refill the free list to ``n_pages`` by LRU-evicting cached
        (refcount-0) pages — the reclaim that composes BEFORE
        youngest-victim preemption. No-op when already covered or the
        cache is off/empty."""
        short = n_pages - self.alloc.free_pages
        if short > 0 and self.prefix_cache is not None:
            self.metrics.inc("prefix_evictions",
                             self.prefix_cache.evict(short))

    def _cow_writable(self, req: Request, page_index: int) -> None:
        """Copy-on-write guard: ``req`` is about to WRITE into its
        ``page_index``-th page. Shared (refcount > 1) pages get a fresh
        page swapped into the ledger and their bytes copied on device —
        eager array ops, NOT a jitted program, so the one-program-per-
        path compile contract is untouched. Sole-owned pages write in
        place (greedy determinism makes the rewrite bit-identical, so
        the index mapping stays valid)."""
        pid = self.alloc.pages_of(req.rid)[page_index]
        if self.alloc.refcount(pid) <= 1:
            return
        self._reclaim(1)
        res = self.alloc.cow_page(req.rid, page_index)
        assert res is not None, "admissible() guaranteed a COW page"
        old, new = res
        # the chunk's attention reads this page's earlier rows through
        # the patched block-table row, so the copy must precede dispatch
        o, w = self.alloc.device_row(old), self.alloc.device_row(new)
        self.pool = {
            "k": self.pool["k"].at[:, w].set(self.pool["k"][:, o]),
            "v": self.pool["v"].at[:, w].set(self.pool["v"][:, o]),
        }
        self.metrics.inc("cow_copies")

    # -- cluster page lending (ISSUE 17, serving/lending.py drives) -------
    def export_prefix(self, prompt, payload: bool = True):
        """Lender half: the longest locally cached full-page prefix of
        ``prompt`` that ``KVPagePool.check_lendable`` accepts (refcount-0
        AND index-retained — no live sequence can observe the copy), plus
        the page payload. Returns ``(tokens, page_ids, payload)`` where
        payload is the gathered K/V bytes — the host-mediated twin of the
        per-(layer, page) puts ``ops.lend_pages`` issues on a device
        mesh. Gathers are eager array ops, so the one-program-per-path
        compile contract is untouched (same argument as _cow_writable).
        ``payload=False`` is the cheap depth-only probe (peer selection
        in ``PageLendingTier.rewarm``): no bytes are gathered."""
        if self.prefix_cache is None:
            return 0, [], None
        prompt = tuple(int(t) for t in prompt)
        hit = self.prefix_cache.match(prompt)
        n = self.alloc.check_lendable(hit)
        if n == 0:
            return 0, [], None
        if not payload:
            return n * self.page_size, hit[:n], None
        ids = self._device_rows(hit[:n])
        kv = {"k": self.pool["k"][:, ids],
              "v": self.pool["v"][:, ids]}
        return n * self.page_size, hit[:n], kv

    def adopt_prefix(self, prompt, n_tokens: int, payload=None) -> int:
        """Borrower half: land a peer's prefix pages locally. Fresh pages
        are allocated under a transient lend seq-id, the payload bytes
        scattered in (eager ``.at[].set`` — no new programs), the runs
        indexed, and the pages released to the cached LRU — from here on
        they are ordinary cached pages (admission adopts, COW guards,
        eviction reclaims). Returns pages newly adopted; 0 degrades to
        local prefill on the caller's side, never a stall."""
        cache = self.prefix_cache
        if cache is None or n_tokens <= 0:
            return 0
        prompt = tuple(int(t) for t in prompt)
        want = min(n_tokens, len(prompt)) // self.page_size
        have = cache.match(prompt)
        if want <= len(have):
            return 0        # local cache already at least as deep
        need = want - len(have)
        sid = ("lend", self._lend_gen)
        self._lend_gen += 1
        if have:
            # pin the local hit under the lend sid BEFORE reclaiming:
            # `have` sits refcount-0 on the cached LRU, so an unpinned
            # reclaim under pool pressure could evict it out from under
            # the insert below (same acquire-first order as _cache_adopt)
            self.alloc.acquire(sid, have)
        self._reclaim(need)
        got = self.alloc.alloc(sid, need)
        if got is None:
            self.alloc.free_seq(sid)    # unpin the hit
            return 0        # pool too tight even after eviction
        if payload is not None:
            # the lender exported `want` pages; ours start past the
            # local hit depth
            idx = self._device_rows(got)
            self.pool = {
                "k": self.pool["k"].at[:, idx].set(
                    payload["k"][:, len(have):want]),
                "v": self.pool["v"].at[:, idx].set(
                    payload["v"][:, len(have):want]),
            }
        # first len(have) entries ride existing trie edges (insert is
        # first-writer-wins); the fresh pages take the deeper runs
        cache.insert(prompt[:want * self.page_size], have + got)
        self.alloc.free_seq(sid)    # refcount-0 + cacheable → cached LRU
        self._lent_pages.update(got)
        self._jlog("lend", tokens=want * self.page_size, pages=need)
        return need

    def _admit_chunked(self, slot: int, req: Request) -> None:
        """Chunked admission does NO prefill math: adopt any cached
        prefix pages (refcount bump + cursor jump), allocate the prompt's
        remaining pages (only the ones the request does not already own —
        a mid-prefill preemptee kept its filled pages and resumes at its
        cursor) and park the slot in PREFILLING. The chunks themselves
        run one per engine step, co-scheduled with decode."""
        self._cache_adopt(req)
        sp = len(req.prompt)
        n_pages = -(-sp // self.page_size)
        have = len(self.alloc.pages_of(req.rid))
        if n_pages > have:
            self._reclaim(n_pages - have)
            got = self.alloc.alloc(req.rid, n_pages - have)
            assert got is not None, "admissible() guaranteed the pages"
        self.sched.activate(slot, req)
        self._jlog("admit", rid=req.rid, slot=slot)
        req.state = RequestState.PREFILLING
        self._mark_prefill_start(req)
        self.metrics.inc("prefills")
        # slot mirrors stay parked (scratch page) until the LAST chunk
        # lands — the chunk program carries its own block-table argument,
        # so the decode batch never sees a half-prefilled row

    def _step_prefill_budget(self) -> int | None:
        """Deadline-aware chunk sizing (ISSUE 14): the prompt tokens this
        step may co-schedule with decode, i.e. the tightest
        ``stall_budget`` over the classes currently DECODING (their ITL
        is what a long chunk stalls). None = no budget (no policy, no
        budgeted class decoding). A pure function of scheduler state —
        deterministic, digest-covered, crash-replayable."""
        if not self._stall_budgeted:
            return None
        budget = None
        for _, r in self.sched.active:
            if r.state is not RequestState.ACTIVE:
                continue
            spec = self.sched.class_spec(r)
            if spec is not None and spec.stall_budget is not None:
                budget = spec.stall_budget if budget is None \
                    else min(budget, spec.stall_budget)
        return budget

    def _dispatch_prefill_chunk(self) -> int:
        """Run AT MOST ONE prefill chunk: the oldest (lowest admission
        ticket) PREFILLING slot advances its cursor by one chunk. The
        final chunk fuses the first-token argmax on device and flips the
        slot to ACTIVE (mirrors set, ready for this step's decode
        dispatch). Returns prompt tokens processed (0 = no prefill work).

        Deadline-aware sizing (ISSUE 14): when a stall-budgeted class is
        decoding, the EFFECTIVE chunk shrinks to its budget — same
        compiled program, fewer real tokens: rows past the reduced
        ``prompt_len`` scalar park on the scratch page exactly like the
        final-chunk padding always has, so KV for the processed prefix
        is bit-identical and ``compile_stats`` stays flat (the scalar is
        a runtime argument, not a shape).
        """
        slot, req = None, None
        for i, r in enumerate(self.sched.slots):
            if (r is not None and r.state is RequestState.PREFILLING
                    and (req is None or r.admitted_seq < req.admitted_seq)):
                slot, req = i, r
        if slot is None:
            return 0
        C = self.prefill_chunk
        budget = self._step_prefill_budget()
        # the prefilling request's OWN class chunk budget (ISSUE 19):
        # a long-context tier drips its 64k prompt through admission at
        # its declared per-step rate even when nothing is decoding
        spec = self.sched.class_spec(req)
        own = spec.chunk_budget if spec is not None else None
        c_eff = C
        for b in (budget, own):
            if b is not None:
                c_eff = min(c_eff, b)
        c_eff = max(1, c_eff)
        if c_eff < C:
            self.metrics.inc("chunk_shrinks")
        sp = len(req.prompt)
        start = req.prefill_cursor
        # the chunk this step actually advances: c_eff real tokens; the
        # compiled program masks rows past n_eff onto the scratch page
        n_eff = min(start + c_eff, sp)
        toks = np.zeros(C, np.int32)
        part = req.prompt[start:n_eff]
        toks[:len(part)] = part
        if self.prefix_cache is not None:
            # COW guard over the chunk's write range: the chunk program
            # never touches a page with refcount > 1 (ISSUE 13). The
            # admission-time guard already covered the whole-prompt-hit
            # rewrite, so these are no-ops unless a new sharing path
            # appears — cheap insurance on the invariant.
            end = n_eff
            for i in range(start // self.page_size,
                           (end - 1) // self.page_size + 1):
                self._cow_writable(req, i)
        row = self._device_bt_row(req.rid)
        t0 = time.perf_counter()
        tok_dev, self.pool = self._chunk_step(
            self.params, jnp.asarray(toks),
            jnp.asarray(start, jnp.int32), jnp.asarray(n_eff, jnp.int32),
            self.pool, jnp.asarray(row))
        # one int32 scalar download — it fences the chunk for honest
        # stall timing and, on the final chunk, IS the first token (the
        # argmax ran on device; the host never sees logits)
        tok0 = int(tok_dev)
        dt = time.perf_counter() - t0
        req.prefill_cursor = n_eff
        self.metrics.inc("prefill_chunks")
        self.metrics.observe("prefill_stall_s", dt)
        self._jlog("chunk", rid=req.rid, cursor=req.prefill_cursor)
        if req.prefill_cursor < sp:
            return len(part)
        # last chunk → the slot starts decoding this very step
        req.state = RequestState.ACTIVE
        req.generated.append(tok0)
        self.metrics.inc("tokens_generated")
        if self.prefix_cache is not None:
            # index the finished prompt's full pages BEFORE decode grows
            # the sequence — later identical prompts adopt them. The
            # partial last page (still being written by decode) is never
            # indexed; already-indexed runs keep their existing mapping.
            self.prefix_cache.insert(
                req.prompt,
                self.alloc.pages_of(req.rid)[:sp // self.page_size])
            if req.first_token_time is None:
                kind = ("ttft_rewarmed_s"
                        if req.rid in self._rewarmed_rids
                        else "ttft_cached_s" if req.cache_hit_tokens
                        else "ttft_cold_s")
                self._rewarmed_rids.discard(req.rid)
                self.metrics.observe(kind,
                                     time.perf_counter() - req.submit_time)
        record_first_token(req, self.metrics, self._steps)
        self._token[slot] = tok0
        self._pos[slot] = sp
        self._bt[slot] = row
        self._seed_hist(slot, req)
        self._dirty = True
        if req.done:            # max_new_tokens == 1 or tok0 == eos_id
            self._finish(slot)
        return len(part)

    # -- slot teardown ----------------------------------------------------
    def _finish(self, slot: int) -> None:
        req = self.sched.finish(slot)
        self.alloc.free_seq(req.rid)
        req.finish_step = self._steps
        self._park(slot)
        self._finished.append(req)
        self.metrics.inc("requests_finished")
        self.metrics.inc_class("requests_finished", class_label(req))
        # the finished tokens ride the journal so a post-checkpoint finish
        # survives a crash without re-running the request; the terminal
        # metadata rides along so the restored record stays faithful
        self._jlog("finish", rid=req.rid, tokens=list(req.generated),
                   submit_step=req.submit_step,
                   first_token_step=req.first_token_step,
                   preemptions=req.preemptions)

    def _preempt(self, slot: int) -> None:
        req = self.sched.slots[slot]
        # composition hook (ISSUE 12): a wrapping engine (compose.py) may
        # own this slot's request — MIGRATING seats hold pages in a pool
        # this engine cannot see — and takes over the eviction when so
        hook = self._preempt_hook
        if hook is not None and hook(slot, req):
            return
        if req.state is RequestState.PREFILLING and req.prefill_cursor > 0:
            filled = -(-req.prefill_cursor // self.page_size)
            if filled < len(self.alloc.pages_of(req.rid)):
                # mid-prefill victim: keep the pages already holding
                # computed KV, reclaim only the unfilled tail — the
                # request requeues AT ITS CHUNK CURSOR and resumes there
                # on re-admission (not at the prompt start)
                self.alloc.free_tail(req.rid, keep=filled)
            else:
                # every owned page is filled — there is no tail to
                # reclaim, so holding them would free nothing: full
                # restart (frees all pages, guaranteed progress for the
                # grower that triggered the preemption)
                self.alloc.free_seq(req.rid)
                req.prefill_cursor = 0
        else:
            self.alloc.free_seq(req.rid)
            req.prefill_cursor = 0      # a decoding victim re-prefills
        self.sched.evict(slot)
        self._park(slot)
        self.metrics.inc("preemptions")
        self._jlog("preempt", rid=req.rid, slot=slot)

    def _ensure_pages(self, rid, kv_len: int) -> bool:
        """``KVPagePool.ensure`` with cache headroom: LRU-evict cached
        pages before declaring the pool dry, so eviction composes BEFORE
        youngest-victim preemption (a refcount-0 cached page is always a
        cheaper reclaim than restarting a live request)."""
        while not self.alloc.ensure(rid, kv_len):
            if self.prefix_cache is None:
                return False
            freed = self.prefix_cache.evict(1)
            if not freed:
                return False
            self.metrics.inc("prefix_evictions", freed)
        return True

    def _park(self, slot: int) -> None:
        """Point an empty slot at the scratch page: its row writes land on
        page 0 (reserved — never a live sequence's), its reads mask out."""
        self._token[slot] = 0
        self._pos[slot] = 0
        self._bt[slot] = 0
        self._hist[slot] = 0
        self._hist_len[slot] = 0
        self._dirty = True

    def _seed_hist(self, slot: int, req: Request) -> None:
        """Seed the drafter window with the slot's token story (prompt +
        generated suffix, right-aligned, newest last) at admission — the
        only host→history upload; thereafter the device rolls the window
        inside the decode program and the host mirrors the same roll
        bitwise (re-prefill after preemption just re-seeds here)."""
        if not self.spec_k:
            return
        H = self.spec_hist
        seq = (list(req.prompt) + list(req.generated))[-H:]
        row = np.zeros(H, np.int32)
        row[H - len(seq):] = seq
        self._hist[slot] = row
        self._hist_len[slot] = len(seq)

    def _spec_account(self, slot: int, req, lim: int,
                      emitted: int) -> None:
        """Per-slot speculation bookkeeping after a dispatch: roll the
        host history window exactly as the device rolled its carry
        (shift left by ``emitted``, append the committed tokens — bitwise
        the same values, so the mirrors stay equal to the device arrays
        and no re-upload happens), and account draft hit/miss metrics.
        Position 0 of a dispatch is the authentic last token, so only
        the ``lim - 1`` draft positions count as drafted."""
        H = self.spec_hist
        committed = np.asarray(req.generated[-emitted:] if emitted
                               else [], np.int32)
        self._hist[slot] = np.concatenate(
            [self._hist[slot], committed])[-H:]
        self._hist_len[slot] = min(
            int(self._hist_len[slot]) + emitted, H)
        req.spec_drafted += max(0, lim - 1)
        req.spec_accepted += max(0, emitted - 1)
        self.metrics.inc("draft_tokens", max(0, lim - 1))
        self.metrics.inc("draft_accepted", max(0, emitted - 1))
        self.metrics.observe("accepted_per_dispatch", emitted)

    def _spec_rewind(self, slot: int, req) -> None:
        """Unwind a rejected draft suffix's KV. The rejected rows wrote
        positions ``>= pos'`` — dead weight the next dispatch overwrites
        before any read (per-layer writes precede reads and every row's
        ``kv_len`` masks deeper positions), so in-page remainders need no
        scrub; only WHOLE pages past the accepted cursor go back to the
        pool via ``free_tail`` (the mid-prefill preemption mechanics).
        The freed-page journal event is observability-only — replay
        ignores it, keeping crash-recovery sweeps bitwise (ISSUE 9)."""
        keep = int(self._pos[slot]) // self.page_size + 1
        freed = 0
        if len(self.alloc.pages_of(req.rid)) > keep:
            freed = self.alloc.free_tail(req.rid, keep=keep)
        self.metrics.inc("spec_rewinds")
        self._jlog("spec_rewind", rid=req.rid, freed=freed,
                   pos=int(self._pos[slot]))

    # -- one engine iteration ---------------------------------------------
    def step(self) -> bool:
        """Admissions (prefill) + one batched decode dispatch (up to
        ``decode_horizon`` tokens per slot). Returns False when there is
        nothing to do (engine idle).

        Thin wrapper around ``_step_impl``: the quota buckets refill and
        the TTL expiry sweep runs before the iteration (an expired
        request must not be admitted), ``_post_step`` after a productive
        one (checkpoint cadence here; the sharded engine chains its
        digest cross-check in front)."""
        self.sched.tick(self._steps)
        self._expire_queued()
        progressed = self._step_impl()
        self.metrics.counters["quota_throttled"] = \
            self.sched.quota_throttled
        if progressed:
            self._post_step()
        return progressed

    def _expire_queued(self) -> None:
        for req in self.sched.expire(self._steps):
            ttl = self._ttl_for(req)
            req.failure = TtlExpired(
                f"request {req.rid} (class {req.cls!r}) queued past its "
                f"TTL ({ttl} steps from step {req.submit_step}) "
                "without admission")
            self._rejected.append(req)
            self.metrics.inc("expirations")
            self.metrics.inc_class("expirations", class_label(req))
            self._jlog("expire", rid=req.rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)

    def _post_step(self) -> None:
        self._maybe_checkpoint()

    def _step_impl(self) -> bool:
        t_begin = time.perf_counter()
        if self.sched.idle:
            return False

        def can_hold(req: Request) -> bool:
            need = -(-len(req.prompt) // self.page_size)
            if self.prefill_chunk is not None:
                # a mid-prefill preemptee kept its filled pages
                need -= len(self.alloc.pages_of(req.rid))
            avail = self.alloc.free_pages
            if self.prefix_cache is not None:
                # cached (refcount-0) pages are reclaimable on demand —
                # admission evicts them as needed, and any page the hit
                # ADOPTS instead was counted in ``need`` anyway
                avail += self.prefix_cache.evictable
            return avail >= need

        admitted = 0
        prefilled_tokens = 0
        while (self.max_prefills_per_step is None
               or admitted < self.max_prefills_per_step):
            adm = self.sched.admissible(can_hold)
            if adm is None:
                break
            if self.prefill_chunk is None:
                prefilled_tokens += len(adm[1].prompt)   # inline prefill
            self._admit(*adm)
            admitted += 1

        # ≤1 prefill chunk co-scheduled with the decode dispatch
        # (Sarathi-style): with chunking on, the decode stall this step
        # is bounded by prefill_chunk tokens, not a whole prompt
        if self.prefill_chunk is not None:
            prefilled_tokens = self._dispatch_prefill_chunk()
        self.metrics.observe("decode_stall_s",
                             time.perf_counter() - t_begin)
        self.metrics.observe("step_prefill_tokens", prefilled_tokens)

        # allocate-on-decode growth, preempting (youngest first) when dry.
        # Slot order is index order — deterministic. The FIRST step is
        # guaranteed (preempt until a page frees); the rest of the horizon
        # is opportunistic: extend capacity page by page WITHOUT
        # preempting, and clamp the slot's limit where growth stops — the
        # auto-clamp that keeps a slot inside its pre-ensured pages
        # mid-scan.
        limits = np.zeros(self.num_slots, np.int32)
        for slot in range(self.num_slots):
            req = self.sched.slots[slot]
            if req is None or req.state is not RequestState.ACTIVE:
                continue            # mid-prefill slots do not decode
            pos = int(self._pos[slot])
            while not self._ensure_pages(req.rid, pos + 1):
                victim = self.sched.pick_victim(exclude_slot=slot)
                if victim is None:
                    raise RuntimeError(
                        f"KV pool too small: request {req.rid} needs a page "
                        "with no preemptible peer left")
                self._preempt(victim)
            want = min(self.decode_horizon, req.remaining)
            lim = 1
            while lim < want and self._ensure_pages(req.rid, pos + lim + 1):
                lim += 1
            limits[slot] = lim
            # refresh AFTER growth — the kernel writes this scan's (k, v)
            # into pages ensure() may just have allocated
            row = self._device_bt_row(req.rid)
            if not np.array_equal(row, self._bt[slot]):
                self._bt[slot] = row
                self._dirty = True
                self._jlog("grow", rid=req.rid,
                           pages=len(self.alloc.pages_of(req.rid)))
        # a slot preempted while a LATER slot grew already has its limit
        # computed — zero it (its mirrors are parked; writes go to scratch)
        for slot in range(self.num_slots):
            r = self.sched.slots[slot]
            if r is None or r.state is not RequestState.ACTIVE:
                limits[slot] = 0

        active = [(s, r) for s, r in self.sched.active
                  if r.state is RequestState.ACTIVE]
        if not active:
            if prefilled_tokens and self.prefill_chunk is not None:
                # the step did real work (a prefill chunk) even with no
                # decodable row — count it and keep the loop hot
                self._steps += 1
                return True
            if self.sched.idle:
                return False
            # nothing dispatched but work is still queued (quota-throttled
            # or capacity-blocked): the logical clock MUST advance anyway —
            # sched.tick(self._steps) refills the token buckets off it, so
            # a frozen clock would turn a bounded deficit wait into
            # permanent starvation (and a spurious stall-watchdog trip)
            self._steps += 1
            return True

        if self._dirty:
            self._sync_mirrors()
            self._dirty = False
            self.metrics.inc("host_syncs")

        t_disp = time.perf_counter()
        if self.spec_k:
            (toks, acc, self._token_dev, self._pos_dev, self._hist_dev,
             self._hlen_dev, self.pool) = self._step(
                self.params, self._token_dev, self._pos_dev, self.pool,
                self._bt_dev, jnp.asarray(limits), self._hist_dev,
                self._hlen_dev)
            accepted = np.asarray(acc)     # [B] committed-count vector
        else:
            toks, self._token_dev, self._pos_dev, self.pool = self._step(
                self.params, self._token_dev, self._pos_dev, self.pool,
                self._bt_dev, jnp.asarray(limits))
            accepted = None
        slab = np.asarray(toks)            # [horizon, B] — blocks on device
        t_done = time.perf_counter()

        self._steps += 1
        self.metrics.inc("dispatches")
        if self.spec_k:
            # the verify pass IS one device step — the whole point is
            # that decode_steps stops tracking tokens
            self.metrics.inc("decode_steps")
            self.metrics.inc("spec_dispatches")
        else:
            self.metrics.inc("decode_steps", int(limits.max()))
        self.metrics.observe("queue_depth", self.sched.queue_depth)
        self.metrics.observe("pool_occupancy", self.alloc.occupancy())
        self.metrics.observe("active_slots", len(active))

        n_tokens = 0
        emitted_by_slot = {}
        for slot, req in active:
            n_commit = int(limits[slot]) if accepted is None \
                else int(accepted[slot])
            emitted = 0
            for i in range(n_commit):
                req.generated.append(int(slab[i, slot]))
                emitted += 1
                self.metrics.inc("tokens_generated")
                if req.done:               # budget exhausted or EOS
                    break
            # the device froze this row after the same ``emitted`` steps
            # (limit clamp / EOS mask / accept prefix), so the mirrors
            # stay equal to the device carry — a continuing slot costs no
            # re-upload
            self._token[slot] = slab[emitted - 1, slot]
            self._pos[slot] += emitted
            if self.spec_k:
                self._spec_account(slot, req, int(limits[slot]), emitted)
            n_tokens += emitted
            emitted_by_slot[slot] = emitted
            if req.done:
                self._finish(slot)
            elif self.spec_k and emitted < int(limits[slot]):
                self._spec_rewind(slot, req)

        dev_dt = t_done - t_disp
        host_dt = (t_disp - t_begin) + (time.perf_counter() - t_done)
        self.metrics.observe("step_device_s", dev_dt)
        self.metrics.observe("step_host_s", host_dt)
        per_tok = (dev_dt + host_dt) / max(n_tokens, 1)
        for _ in range(n_tokens):
            self.metrics.observe("tok_latency_s", per_tok)
        # per-class ITL (ISSUE 14): the same per-token estimate, labeled
        # by the emitting request's class — the isolation panel's number
        for slot, req in active:
            label = class_label(req)
            if label is not None:
                for _ in range(emitted_by_slot.get(slot, 0)):
                    self.metrics.observe_class("itl_s", label, per_tok)
        return True

    def run(self, max_steps: int | None = None,
            arrivals=None, recover=None) -> dict[int, list[int]]:
        """Drive ``step()`` until idle (or ``max_steps``). ``arrivals`` is
        an optional iterable of (step_index, prompt, max_new_tokens)
        3-tuples — or 5-tuples with (…, tenant, cls) appended (ISSUE 14,
        the bursty multi-tenant workloads) — sorted by step: the
        synthetic-trace replay hook serve_sim uses.
        Returns {rid: generated tokens} for FINISHED requests only — a
        truncated run (``max_steps`` hit) simply omits the unfinished.

        ``recover`` (ISSUE 9): truthy = restore from the journal's last
        checkpoint + suffix replay before stepping (a ``Checkpoint``
        object restores from that specific snapshot). Requires a journal.
        The caller feeds only not-yet-journaled arrivals — journaled
        submissions are replayed from the WAL. Restored FINISHED requests
        are included in the returned dict, so a recovered run returns the
        complete trace.

        A progress watchdog (ISSUE 7, shared with the disagg engine)
        deadlines the whole drive loop: ``stall_deadline_steps``
        consecutive non-idle steps with no counter movement raise
        ``EngineStallError`` instead of spinning forever — the colocated
        engine has no migration ladder, so ANY stall here is a bug."""
        if recover:
            assert self.journal is not None, "recover= needs a journal"
            ck = recover if isinstance(recover, ckpt_mod.Checkpoint) \
                else ckpt_mod.latest(self.journal)
            ckpt_mod.restore(self, ck, self.journal)
        pending = deque(arrivals or [])
        i = 0
        marker, since = self._progress_marker(), 0
        while max_steps is None or i < max_steps:
            while pending and pending[0][0] <= i:
                item = pending.popleft()
                self.submit(item[1], item[2],
                            tenant=item[3] if len(item) > 3 else None,
                            cls=item[4] if len(item) > 4 else None)
            if not self.step() and not pending:
                break
            i += 1
            plan = self._fault_plan if self._fault_plan is not None \
                else faults_mod.active_plan()
            if plan is not None and plan.crash(self._steps,
                                               self._incarnation):
                self.metrics.inc("faults_injected")
                raise InjectedCrash(
                    f"injected crash at step {self._steps} "
                    f"(incarnation {self._incarnation})")
            m = self._progress_marker()
            if m != marker:
                marker, since = m, 0
            else:
                since += 1
                if since >= self._stall_steps and not self.sched.idle:
                    active = "; ".join(
                        f"[{s}] rid={r.rid} {r.state.value} "
                        f"cursor={r.prefill_cursor}"
                        for s, r in self.sched.active)
                    raise EngineStallError(
                        f"engine made no progress for {since} steps "
                        f"(stall deadline {self._stall_steps}); queue="
                        f"{self.sched.queue_depth}, slots: "
                        f"{active or '<none>'}" + self._postmortem())
        return {req.rid: list(req.generated) for req in self._finished}

    def _progress_marker(self) -> tuple:
        c = self.metrics.counters
        return (c["tokens_generated"], c["prefills"], c["prefill_chunks"],
                c["preemptions"], c["requests_finished"],
                c["restores"], len(self._finished))

    # -- crash consistency (ISSUE 9) --------------------------------------
    def control_digest(self) -> int:
        """FNV-1a digest of the full host control plane (allocator +
        scheduler) — the per-event stamp journal entries carry, and the
        replicated-decision word the sharded engine cross-checks."""
        return _fnv1a(0x811C9DC5, self.alloc.digest(), self.sched.digest())

    def _jlog(self, kind: str, **payload) -> None:
        """Append one control-plane event to the journal (no-op without
        one; muted while a restore replays the journal into this engine —
        replay must not re-journal its own effects)."""
        if self.journal is None or self._journal_muted:
            return
        self.journal.append(kind, self._steps, self.control_digest(),
                            **payload)

    def _maybe_checkpoint(self) -> None:
        if (self.journal is None or not self.checkpoint_every
                or self._steps == 0
                or self._steps % self.checkpoint_every
                or self._steps == self._last_ckpt_step):
            return
        self.checkpoint()

    def checkpoint(self) -> "ckpt_mod.Checkpoint":
        """Capture a control-plane snapshot into the journal. Host-only
        (no device work, no KV bytes); restore pairs it with the journal
        suffix appended after it."""
        assert self.journal is not None, "checkpoint() needs a journal"
        t0 = time.perf_counter()
        ck = ckpt_mod.capture(self)
        self.journal.record_checkpoint(ck.step, ck.digest, ck.state,
                                       ck.journal_seq)
        self._last_ckpt_step = self._steps
        self.metrics.inc("checkpoints")
        self.metrics.observe("checkpoint_s", time.perf_counter() - t0)
        return ck

    def _capture_state(self) -> dict:
        """JSON-able control-plane snapshot. Live requests are recorded in
        deterministic order (seated slots by admission ticket, then the
        queue); the page-ledger snapshot is an integrity audit artifact —
        restore re-earns pages via re-prefill, it never trusts old
        ownership."""
        live = [r for _, r in sorted(
            ((r.admitted_seq, r) for _, r in self.sched.active),
            key=lambda t: t[0])]
        live += list(self.sched.queue)
        return {
            "engine": "colocated",
            "step": self._steps,
            "next_rid": self._next_rid,
            "admit_ticket": self.sched._admit_ticket,
            "pool": self.alloc.snapshot(),
            "pool_digest": self.alloc.digest(),
            # prefix index (ISSUE 13): integrity artifact, like the pool
            # snapshot — restore starts with an EMPTY cache (re-prefill
            # re-earns KV; pre-crash device bytes are never adopted)
            "prefix_index": None if self.prefix_cache is None
            else self.prefix_cache.snapshot(),
            "prefix_digest": None if self.prefix_cache is None
            else self.prefix_cache.digest(),
            "live": [ckpt_mod.snapshot_request(r) for r in live],
            "finished": [ckpt_mod.snapshot_finished(r)
                         for r in self._finished],
            "rejected": [{"rid": r.rid, "kind": "expire"
                          if isinstance(r.failure, TtlExpired) else "reject",
                          "reason": str(r.failure),
                          "tenant": r.tenant, "cls": r.cls}
                         for r in self._rejected],
            # multi-tenant policy books (ISSUE 14): WFQ service counters,
            # virtual-time floor, token-bucket levels — restored AFTER
            # the live requests requeue so the exact cross-class order
            # resumes (None without a policy)
            "policy": self.sched.policy_state(),
            "counters": dict(self.metrics.counters),
        }

    def _restore_state(self, state: dict | None) -> None:
        """Rebuild host control state from a snapshot (None = from
        nothing — the whole journal is then the replay suffix). Device
        pool arrays are left untouched: every live request restarts from
        its prompt, and re-prefill rewrites a page's KV before any decode
        read of it, so stale device bytes are unreachable."""
        self.alloc = KVPagePool(self.alloc.num_pages, self.page_size,
                                reserved=self.alloc.reserved,
                                sp_ranks=self.alloc.sp_ranks,
                                layout=self.alloc.layout)
        if self.prefix_cache is not None:
            # fresh pool → fresh (empty) index: every cached mapping
            # pointed at KV the restored process never computed
            self.prefix_cache = PrefixCache(self.alloc, self.page_size)
        self._lent_pages = set()
        self._rewarmed_rids = set()
        self.sched = ContinuousBatchingScheduler(
            self.num_slots, queue_cap=self.sched.queue_cap,
            policy=self.sched.policy)
        self._finished = []
        self._rejected = []
        for slot in range(self.num_slots):
            self._park(slot)
        self._sync_mirrors()
        self._dirty = False
        if state is None:
            return
        # integrity audit: the snapshot's ledger must digest to the value
        # recorded at capture time (a torn snapshot fails loudly here)
        ckpt_mod.audit_pool_snapshot(
            state["pool"], state["pool_digest"], self.alloc.num_pages,
            self.page_size, self.alloc.reserved)
        if state.get("prefix_index") is not None:
            ckpt_mod.audit_prefix_snapshot(state["prefix_index"],
                                           state["prefix_digest"])
        self._steps = state["step"]
        self._next_rid = state["next_rid"]
        self.sched._admit_ticket = state["admit_ticket"]
        for snap in state["live"]:
            req = ckpt_mod.rebuild_request(snap)
            req.submit_time = time.perf_counter()
            ttl = self._ttl_for(req)
            if ttl is not None:
                req.deadline = Deadline(ttl, req.submit_step)
            self.sched.submit(req)
        # policy books AFTER the requeues: submit()'s idle-class snap ran
        # against zeroed counters; the checkpoint values overwrite them
        # so the restored WFQ order is exactly the captured one
        self.sched.restore_policy_state(state.get("policy"))
        for f in state["finished"]:
            self._restore_finished(f["rid"], f["tokens"], meta=f)
        for f in state["rejected"]:
            self._restore_terminal(f["rid"], f["kind"], f["reason"])

    def _restore_finished(self, rid: int, tokens: list[int],
                          meta: dict | None = None) -> None:
        """Settle ``rid`` as FINISHED with ``tokens`` (from a snapshot or
        a journal ``finish`` entry), removing it from the restored queue
        if it was live at the checkpoint. ``meta`` carries the terminal
        record's prompt/steps so the restored entry reports the same
        ttft/preemption numbers the original process measured."""
        req = self._pop_queued(rid)
        if req is None:
            prompt = tuple((meta or {}).get("prompt", (0,)))
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=len(tokens), eos_token=self.eos_id)
        req.state = RequestState.FINISHED
        req.generated = list(tokens)
        for k in ("submit_step", "first_token_step", "preemptions"):
            if meta is not None and k in meta:
                setattr(req, k, meta[k])
        self._finished.append(req)

    def _restore_terminal(self, rid: int, kind: str, reason: str,
                          error_type: str | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            req = Request(rid=rid, prompt=(0,), max_new_tokens=1,
                          eos_token=self.eos_id)
        req.state = RequestState.REJECTED
        req.failure = (TtlExpired(reason) if kind == "expire"
                       else AdmissionRejected(reason))
        self._rejected.append(req)

    def _pop_queued(self, rid: int) -> Request | None:
        for r in self.sched.queue:
            if r.rid == rid:
                self.sched.queue.remove(r)
                return r
        return None

    def _postmortem(self) -> str:
        """Counters + journal tail appended to engine-level error reports
        so a post-mortem never needs a live process."""
        counters = {k: v for k, v in self.metrics.counters.items() if v}
        tail = (self.journal.format_tail(8) if self.journal is not None
                else "  <no journal attached>")
        return ("\ncounters: " + json.dumps(counters)
                + "\njournal tail:\n" + tail)

    @property
    def failed(self) -> list[Request]:
        """Typed terminals that will never finish (REJECTED overload
        terminals — the colocated engine has no other failure domain)."""
        return list(self._rejected)

    # -- introspection ----------------------------------------------------
    @property
    def compile_stats(self) -> dict:
        """Compile counts for the hot loop: the decode program (should be
        exactly 1 however mixed the traffic) and the prefill programs
        (bounded by the bucket count). Uses the jit-internal cache size
        when available, falling back to the program-key count."""
        def n(fn, fallback):
            try:
                return int(fn._cache_size())
            except Exception:
                return fallback

        prefills = sum(n(f, 1) for f in self._prefill_jit.values())
        chunk = 0
        if self._chunk_step is not None:
            chunk = n(self._chunk_step,
                      1 if self.metrics.counters["prefill_chunks"] else 0)
        stats = {
            "decode_compiles": n(self._step, 1 if self._steps else 0),
            "prefill_compiles": prefills,
            "prefill_programs": len(self._prefill_jit),
            # chunked mode: exactly one program for ALL prompt lengths
            "prefill_chunk_compiles": chunk,
        }
        if self._aot_artifact is not None:
            from triton_dist_tpu.aot.artifact import LoadedProgram
            stats["aot_programs"] = sum(
                isinstance(f, LoadedProgram)
                for f in (self._step, self._chunk_step,
                          *self._prefill_jit.values()))
        return stats


__all__ = ["ServingEngine", "mark_prefill_start", "record_first_token"]
