"""AllGather-GEMM overlap (analog of reference
python/triton_dist/kernels/nvidia/allgather_gemm.py).

The reference overlaps a copy-engine allgather producer with a persistent
consumer GEMM on separate CUDA streams, synchronized by per-rank flags that
GEMM tiles spin-wait on, with a rank-swizzle so each rank computes its local
segment first (allgather_gemm.py:203-217, :222-225, :405-527).

TPU-native design — ONE kernel per device, no streams:

1. On entry, a light barrier (cf. ``local_copy_and_barrier_all``,
   allgather_gemm.py:99-116) protects the symmetric workspace across calls.
2. Issue *all* remote puts of the local A-shard into every peer's workspace
   slot ``me`` as non-blocking DMAs. The ICI DMA engines are the
   "copy-engine producer" running in the background of compute.
3. Walk segments in swizzled order ``me, me+1, …`` (start-local trick).
   The FIRST segment is always our own shard, so its GEMM reads ``a_ref``
   directly — no workspace copy, no wait: compute starts immediately while
   every transfer is in flight (one better than the reference, which
   local-copies into the symm buffer first, allgather_gemm.py:99-116).
   Each remote segment waits its receive semaphore once (TPU grids are
   sequential per core — no per-tile spin flags needed), then runs the
   pipelined MXU GEMM via ``emit_gemm``.

Steady state overlaps segment s's GEMM with segment s+1's arrival — same
overlap structure, no CUDA-stream machinery. The n=1 degenerate case leaves
barrier + MXU pipeline only, preserving full single-chip GEMM efficiency.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import (collective_id_for, lru_step,
                                         norm_axis as _norm_axis,
                                         require_eager)
from triton_dist_tpu.ops.gemm import (GemmConfig, best_gemm_config,
                                       emit_gemm)
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def ag_overlap_protocol(axis, mesh_axes, a_ref, ws_ref, send_sems, recv_sems,
                        emit):
    """The shared AllGather-overlap kernel protocol (one copy — AG-GEMM and
    the fused MoE AG-GroupGEMM both run it):

    1. Entry barrier: nobody puts into a peer's workspace before that peer
       has entered this call (workspace slots + semaphores are reused).
    2. Producer: non-blocking puts of ``a_ref`` into every peer's ws slot
       ``me``; our own segment never touches the workspace.
    3. Consumer: swizzled start-local segment loop — s=0 is statically the
       local segment, fed by ``a_ref`` with zero wait; each remote segment
       is waited once, then ``emit(src_ref, seg)`` computes on it.
    4. Quiet: drain our outstanding sends.
    """
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    shd.barrier_all(axis if isinstance(axis, tuple) else (axis,),
                    mesh_axes=mesh_axes)

    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(ws_ref.at[me], a_ref,
                                    send_sems.at[dst], recv_sems.at[me], pid))

    emit(a_ref, me)
    for s in range(1, n):
        seg = lax.rem(me + s, n)
        shd.wait_recv(ws_ref.at[seg], recv_sems.at[seg])
        emit(ws_ref.at[seg], seg)

    shd.quiet(*rdmas)


def ag_overlap_protocol_2d(axes, mesh_axes, a_ref, ws_ref,
                           send_sems, recv_sems, emit):
    """Two-tier AllGather-overlap protocol for multi-axis meshes — the
    inter-node analog of ``ag_overlap_protocol`` (reference
    ``ag_gemm_inter_node`` + 2-D ring AG, allgather_gemm.py:938-975,
    allgather.py:291-375).

    ``axes = (outer, *inner)``: the outer axis is the slow tier (DCN /
    inter-slice), the inner axes the fast tier (ICI), flattened into one
    PE group of size ``ni``. Global segment id ``seg = r * ni + j`` for
    outer row ``r``, inner index ``j`` — matching a ``P(axes)`` sharding.

    Same-inner-index ring relay (the reference's same-local-rank inter-node
    p2p): each device is the relay for its own inner index ``mi`` —

    1. Entry barrier over the whole group (slots + sems are reused).
    2. Own shard → every inner peer (fast full push) and, in parallel, to
       the outer-right neighbor (ring hop 1).
    3. Consume rows in swizzled order ``mo, mo-1, …`` — row ``mo`` starts
       with our own shard read directly from ``a_ref`` (zero wait). For a
       remote row ``r``: wait the outer arrival of ``(r, mi)``, immediately
       re-forward it outer-right (until it has made ``no-1`` hops) AND
       distribute it to our inner peers, then compute — so the slow-tier
       relay and fast-tier distribution of row ``r`` ride behind the
       compute of rows ``> r``. Segments ``(r, j≠mi)`` arrive from their
       own relays ``(mo, j)`` over the fast tier.
    4. Quiet: drain our outstanding sends.

    Per-outer-link traffic is ``no-1`` shards (ring-optimal, split across
    the ``ni`` parallel same-inner-index rings); every device receives each
    foreign segment exactly once.
    """
    outer, inner = axes[0], tuple(axes[1:])
    mo, mi = shd.my_pe(outer), shd.my_pe(inner)
    no, ni = shd.n_pes(outer), shd.n_pes(inner)
    shd.barrier_all(axes, mesh_axes=mesh_axes)

    my_seg = mo * ni + mi
    rdmas = []
    right = (shd.pe_at(mesh_axes, outer, lax.rem(mo + 1, no))
             if no > 1 else None)

    def put_inner(seg_idx, src_ref):
        for s in range(1, ni):
            j = lax.rem(mi + s, ni)
            pid = shd.pe_at_group(mesh_axes, inner, j)
            rdmas.append(shd.putmem_nbi(ws_ref.at[seg_idx], src_ref,
                                        send_sems.at[seg_idx],
                                        recv_sems.at[seg_idx], pid))

    # own shard: fast-tier push + outer ring hop 1
    put_inner(my_seg, a_ref)
    if no > 1:
        rdmas.append(shd.putmem_nbi(ws_ref.at[my_seg], a_ref,
                                    send_sems.at[my_seg],
                                    recv_sems.at[my_seg], right))

    # row mo: local segment first (start-local swizzle), then inner arrivals
    emit(a_ref, my_seg)
    for s in range(1, ni):
        j = lax.rem(mi + s, ni)
        seg = mo * ni + j
        shd.wait_recv(ws_ref.at[seg], recv_sems.at[seg])
        emit(ws_ref.at[seg], seg)

    # remote rows, nearest-first: relay + distribute before computing
    for t in range(1, no):
        r = lax.rem(mo - t + no, no)
        seg_r = r * ni + mi
        shd.wait_recv(ws_ref.at[seg_r], recv_sems.at[seg_r])
        if t < no - 1:
            rdmas.append(shd.putmem_nbi(ws_ref.at[seg_r], ws_ref.at[seg_r],
                                        send_sems.at[seg_r],
                                        recv_sems.at[seg_r], right))
        put_inner(seg_r, ws_ref.at[seg_r])
        emit(ws_ref.at[seg_r], seg_r)
        for s in range(1, ni):
            j = lax.rem(mi + s, ni)
            seg = r * ni + j
            shd.wait_recv(ws_ref.at[seg], recv_sems.at[seg])
            emit(ws_ref.at[seg], seg)

    shd.quiet(*rdmas)


def _ag_gemm_kernel(axis, mesh_axes, cfg, out_dtype,
                    a_ref, b_ref, out_ref, ws_ref,
                    send_sems, recv_sems):
    # ws_ref is the symmetric workspace: either a context-owned persistent
    # buffer (aliased input→output, see ag_gemm_ws) or a discarded fresh
    # HBM output (legacy jit-anywhere path; interpret mode cannot allocate
    # ANY-space scratch, so an output covers both backends).
    m_local = a_ref.shape[0]

    def emit(src_ref, seg):
        emit_gemm(src_ref, b_ref, out_ref.at[pl.ds(seg * m_local, m_local)],
                  cfg, out_dtype)

    if isinstance(axis, tuple) and len(axis) > 1:
        ag_overlap_protocol_2d(axis, mesh_axes, a_ref, ws_ref,
                               send_sems, recv_sems, emit)
    else:
        ag_overlap_protocol(axis, mesh_axes, a_ref, ws_ref,
                            send_sems, recv_sems, emit)


def _default_cfg(ctx, a, b, axis) -> GemmConfig:
    """Shape-keyed default tiles (measured-best table, docs/benchmarks.md):
    the per-segment GEMM is [M/n, K] x [K, N/n]."""
    n = ctx.axis_size(axis)
    M, K = a.shape
    return best_gemm_config(max(M // n, 1), max(b.shape[1] // n, 1), K,
                            jnp.dtype(a.dtype).itemsize)


def _validate(ctx, a, b, axis, cfg):
    n = ctx.axis_size(axis)
    M, K = a.shape
    assert M % n == 0, f"M={M} not divisible by ranks {n}"
    m_local = M // n
    assert m_local % cfg.block_m == 0, (
        f"local M {m_local} not divisible by block_m {cfg.block_m}")
    assert cfg.vmem_ok(K, jnp.dtype(a.dtype).itemsize), (
        f"tile config exceeds VMEM budget for K={K}")
    return n, M, K, m_local


def _pallas_ag_gemm(axis, mesh_axes, cfg, out_dtype, n, M, K, m_local,
                    a_shard, b_shard, ws_shard=None):
    """Shared pallas_call builder. With ``ws_shard`` the workspace is an
    aliased input→output pair (persistent, zero per-call allocation);
    without it the workspace is a fresh discarded output."""
    n_local = b_shard.shape[1]
    flops = 2 * M * n_local * K
    common = dict(
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            # keyed by axis: the hierarchical form barriers a different PE
            # group than the 1-D form — they must not share a physical
            # barrier semaphore (cf. allgather.py's per-(family, axis) ids)
            collective_id=collective_id_for(f"ag_gemm_{axis}")),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(a_shard.size + b_shard.size + M * n_local)
            * jnp.dtype(a_shard.dtype).itemsize,
            transcendentals=0),
        interpret=default_interpret(),
    )
    out_c = jax.ShapeDtypeStruct((M, n_local), out_dtype)
    out_ws = jax.ShapeDtypeStruct((n, m_local, K), a_shard.dtype)
    if ws_shard is None:
        kernel = lambda a_r, b_r, c_r, ws_r, *sems: _ag_gemm_kernel(
            axis, mesh_axes, cfg, out_dtype, a_r, b_r, c_r, ws_r, *sems)
        c, _ws = pl.pallas_call(
            kernel,
            out_shape=(out_c, out_ws),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
            **common,
        )(a_shard, b_shard)
        return c, None
    # persistent: ws is input 2 aliased to output 1 (same buffer; the
    # kernel sees one ref for it — ws_in is consumed by the alias)
    kernel = lambda a_r, b_r, ws_in, c_r, ws_r, *sems: _ag_gemm_kernel(
        axis, mesh_axes, cfg, out_dtype, a_r, b_r, c_r, ws_r, *sems)
    c, ws_out = pl.pallas_call(
        kernel,
        out_shape=(out_c, out_ws),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
        input_output_aliases={2: 1},
        **common,
    )(a_shard, b_shard, ws_shard)
    return c, ws_out


def _dcn_prefix(ctx: ShmemContext, axis) -> tuple[tuple, tuple]:
    """Partition a (possibly tuple) gather axis into (dcn_axes, ici_axes).
    DCN axes must form a PREFIX of the tuple — the docstring's "slow tier
    first" rule; a DCN axis behind an ICI axis would scramble the segment
    order the hierarchical protocol produces."""
    axes_t = axis if isinstance(axis, tuple) else (axis,)
    dcn = tuple(a for a in axes_t if ctx.is_dcn_axis(a))
    if dcn and dcn != axes_t[:len(dcn)]:
        raise ValueError(
            f"DCN (slice-crossing) axes {dcn} must come first in the "
            f"hierarchical axis tuple {axes_t} — put the slow tier "
            "outermost (cf. ag_gemm docstring)")
    return dcn, axes_t[len(dcn):]


def _ag_gemm_dcn(ctx, a, b, dcn, ici, cfg, out_dtype, ws=None):
    """AG-GEMM with the outer tier crossing slice boundaries: the DCN
    tier's gather runs as an XLA ``all_gather`` (remote DMA cannot cross
    DCN), the ICI tier keeps the Pallas overlap kernel, and the output
    rows are restored to the P((dcn…, ici…)) order with one local
    block-transpose (each device holds full rows of its N-slice). The TPU
    analog of the reference's inter-node tier swap — its inter-node AG is
    a different transport stacked on the intra-node kernel
    (allgather_gemm.py:938-975, allgather.py:291-375)."""
    mesh_axes = ctx.axis_names
    group = dcn + ici
    n = ctx.axis_size(group)
    n_dcn = ctx.axis_size(dcn)
    n_ici = ctx.axis_size(ici) if ici else 1
    M, K = a.shape
    m_loc = M // n
    ici_axis = None if not ici else (ici[0] if len(ici) == 1 else ici)

    def f(a_shard, b_shard, *ws_shard):
        a2 = a_shard
        for ax in reversed(dcn):
            a2 = lax.all_gather(a2, ax, axis=0, tiled=True)
        # a2: [m_loc * n_dcn, K], rows (dcn…, m) for this device's ici index
        if not ici:
            # every tier crosses DCN: plain XLA GEMM on the gathered rows
            c = jnp.dot(a2, b_shard, preferred_element_type=jnp.float32
                        ).astype(out_dtype)
            return (c,) + tuple(ws_shard)
        ws2 = (ws_shard[0].reshape(n_ici, m_loc * n_dcn, K)
               if ws_shard else None)
        c, ws_out = _pallas_ag_gemm(ici_axis, mesh_axes, cfg, out_dtype,
                                    n_ici, M, K, m_loc * n_dcn, a2, b_shard,
                                    ws2)
        # Pallas tier ordered rows (ici…, dcn…, m); restore (dcn…, ici…, m)
        tail = c.shape[1:]
        c = c.reshape((n_ici, n_dcn, m_loc) + tail)
        c = jnp.swapaxes(c, 0, 1).reshape((M,) + tail)
        if ws_shard:
            return c, ws_out.reshape(ws_shard[0].shape)
        return (c,)

    ws_args = () if ws is None else (ws,)
    sm = ctx.shard_map(
        f,
        in_specs=(P(group), P(None, group)) + (P(group),) * len(ws_args),
        out_specs=(P(None, group),) + (P(group),) * len(ws_args))
    out = sm(a, b, *ws_args)
    return out[0] if ws is None else out


def ag_gemm(ctx: ShmemContext, a: jax.Array, b: jax.Array,
            axis=None, cfg: GemmConfig | None = None,
            out_dtype=None) -> jax.Array:
    """Tensor-parallel AllGather-GEMM: ``a`` is [M, K] sharded P(axis) on M
    (each rank holds [M/n, K]); ``b`` is [K, N] sharded P(None, axis) on N
    (column-parallel weight). Returns C = all_gather(a) @ b — [M, N] sharded
    P(None, axis). Entry analog: ``ag_gemm_intra_node``
    (allgather_gemm.py:835-880); golden: all_gather + dot.

    ``axis`` may be a tuple ``(outer, inner…)`` spanning a multi-axis mesh —
    the hierarchical 2-tier path (same-inner-index outer ring relay + inner
    push, see ``ag_overlap_protocol_2d``), the TPU analog of
    ``ag_gemm_inter_node`` (allgather_gemm.py:938-975). Put the slow tier
    (DCN/inter-slice) first.

    This form allocates a fresh [n, M/n, K] workspace per call (discarded).
    For repeated calls, use ``ag_gemm_ws`` / ``AgGemmContext`` which reuse a
    context-owned symmetric workspace (reference parity:
    create_ag_gemm_intra_node_context, allgather_gemm.py:785-832)."""
    axis = _norm_axis(ctx, axis)
    cfg = cfg or _default_cfg(ctx, a, b, axis)
    out_dtype = out_dtype or a.dtype
    mesh_axes = ctx.axis_names
    n, M, K, m_local = _validate(ctx, a, b, axis, cfg)
    dcn, ici = _dcn_prefix(ctx, axis)
    if dcn:
        return _ag_gemm_dcn(ctx, a, b, dcn, ici, cfg, out_dtype)

    def f(a_shard, b_shard):
        c, _ = _pallas_ag_gemm(axis, mesh_axes, cfg, out_dtype, n, M, K,
                               m_local, a_shard, b_shard)
        return c

    sm = ctx.shard_map(f, in_specs=(P(axis), P(None, axis)),
                       out_specs=P(None, axis))
    return sm(a, b)


def ag_gemm_ws(ctx: ShmemContext, a: jax.Array, b: jax.Array, ws: jax.Array,
               axis=None, cfg: GemmConfig | None = None,
               out_dtype=None) -> tuple[jax.Array, jax.Array]:
    """Workspace-threading AG-GEMM: like ``ag_gemm`` but the symmetric
    workspace is an explicit operand, aliased in place and returned.
    Functional-state idiom (like PRNG keys / optimizer state): jit with
    ``donate_argnums`` on ``ws`` (or carry it through ``lax.scan``) and the
    buffer is reused with zero per-call allocation. Create ``ws`` with
    ``create_ag_gemm_workspace``. ``axis`` may be a tuple (hierarchical
    2-tier path, see ``ag_gemm``)."""
    axis = _norm_axis(ctx, axis)
    cfg = cfg or _default_cfg(ctx, a, b, axis)
    out_dtype = out_dtype or a.dtype
    mesh_axes = ctx.axis_names
    n, M, K, m_local = _validate(ctx, a, b, axis, cfg)
    assert ws.shape == (n, n, m_local, K) and ws.dtype == a.dtype, (
        f"workspace {ws.shape}/{ws.dtype} does not match "
        f"({n}, {n}, {m_local}, {K})/{a.dtype} — create it with "
        f"create_ag_gemm_workspace(ctx, m_local={m_local}, k={K}, ...)")
    dcn, ici = _dcn_prefix(ctx, axis)
    if dcn:
        # same symmetric buffer, re-viewed for the ICI-only Pallas tier
        # (n·m_local rows = n_ici·(m_local·n_dcn) rows — bytes identical)
        return _ag_gemm_dcn(ctx, a, b, dcn, ici, cfg, out_dtype, ws=ws)

    def f(a_shard, b_shard, ws_shard):
        c, ws_out = _pallas_ag_gemm(
            axis, mesh_axes, cfg, out_dtype, n, M, K, m_local,
            a_shard, b_shard, ws_shard.reshape(n, m_local, K))
        return c, ws_out.reshape(ws_shard.shape)

    sm = ctx.shard_map(f, in_specs=(P(axis), P(None, axis), P(axis)),
                       out_specs=(P(None, axis), P(axis)))
    return sm(a, b, ws)


def create_ag_gemm_workspace(ctx: ShmemContext, m_local: int, k: int,
                             dtype=jnp.bfloat16, axis=None) -> jax.Array:
    """Symmetric AG workspace: per-device [n, m_local, k] slots (one per
    source rank), global [n, n, m_local, k] sharded P(axis). Analog of the
    reference's per-context symm workspace tensor list
    (create_ag_gemm_intra_node_context, allgather_gemm.py:785-832)."""
    axis = _norm_axis(ctx, axis)
    n = ctx.axis_size(axis)
    return ctx.create_symm_tensor((n, m_local, k), dtype, axis=axis)


@dataclasses.dataclass
class AgGemmContext:
    """Stateful sugar over ``ag_gemm_ws``: owns the symmetric workspace and
    a per-shape LRU cache of donated jitted steps, so eager callers get
    in-place workspace reuse without threading state themselves. Do NOT
    wrap calls in an outer ``jax.jit`` (each step is already jitted; under
    an outer trace the state update would leak) — use ``ag_gemm_ws`` inside
    jit/scan.
    """
    ctx: ShmemContext
    axis: str
    ws: jax.Array
    _steps: dict = dataclasses.field(default_factory=dict)

    def __call__(self, a: jax.Array, b: jax.Array,
                 cfg: GemmConfig | None = None, out_dtype=None) -> jax.Array:
        require_eager("AgGemmContext", "ag_gemm_ws")
        key = (a.shape, b.shape, str(a.dtype), cfg, out_dtype)
        step = lru_step(self._steps, key, lambda: jax.jit(
            lambda ws, a, b: ag_gemm_ws(self.ctx, a, b, ws, axis=self.axis,
                                        cfg=cfg, out_dtype=out_dtype)[::-1],
            donate_argnums=(0,)))
        self.ws, c = step(self.ws, a, b)
        return c


def create_ag_gemm_context(ctx: ShmemContext, m_local: int, k: int,
                           dtype=jnp.bfloat16, axis=None) -> AgGemmContext:
    axis = _norm_axis(ctx, axis)
    ws = create_ag_gemm_workspace(ctx, m_local, k, dtype, axis)
    return AgGemmContext(ctx=ctx, axis=axis, ws=ws)


def tp_column_linear(ctx: ShmemContext, h: jax.Array, w: jax.Array,
                     axis: str = "tp", impl: str = "xla",
                     cfg: GemmConfig | None = None) -> jax.Array:
    """Tensor-parallel column-sharded linear for the serving hot loop:
    ``h @ w`` with ``w`` [K, N] column-sharded P(None, axis) inside the op's
    own shard_map region, output allgathered back to replicated.

    ``impl="xla"`` (default): each rank computes ``h @ w_local`` over the
    FULL contraction dim — the identical dot a single device runs on its
    column slice — then the tiled last-dim allgather concatenates the
    column blocks. Column-split + concat is bitwise equal to the unsplit
    matmul (no cross-rank reduction anywhere), which is what lets the
    sharded serving trace stay bit-identical to the n=1 golden.

    ``impl="ag_gemm"`` routes through the Pallas AllGather-GEMM overlap
    kernel instead (``h`` row-sharded P(axis) on the wire; needs
    rows % n == 0 and (rows/n) % cfg.block_m == 0): the throughput path
    for real weights, numerically ALLCLOSE but not bit-pinned — the f32
    accumulator tiling differs from the XLA dot, so it is excluded from
    the bit-exact trace contract (docs/serving.md).

    ``gemm_rs`` is deliberately NOT offered here: its reduce-scatter sums
    partial products across ranks in rank-varying order, which breaks the
    bitwise cross-mesh-size contract serving pins.
    """
    n = ctx.axis_size(axis)
    if n == 1:
        return h @ w
    assert w.shape[1] % n == 0, (
        f"out dim {w.shape[1]} not divisible by |{axis}|={n}")
    if impl == "xla":
        def body(h, w_l):
            return lax.all_gather(h @ w_l, axis, axis=1, tiled=True)
        return ctx.shard_map(body, in_specs=(P(), P(None, axis)),
                             out_specs=P())(h, w)
    if impl == "gemm_rs":
        raise ValueError(
            "tp_column_linear refuses impl='gemm_rs': its reduce-scatter "
            "sums partial products in rank-varying order, which breaks the "
            "bitwise cross-mesh-size trace contract serving pins "
            "(docs/serving.md). Use 'xla' (bitwise) or 'ag_gemm' "
            "(allclose-only overlap).")
    assert impl == "ag_gemm", f"unknown tp_column_linear impl {impl!r}"
    c = ag_gemm(ctx, h, w, axis=axis, cfg=cfg)     # [M, N] P(None, axis)
    return ctx.shard_map(
        lambda c_l: lax.all_gather(c_l, axis, axis=1, tiled=True),
        in_specs=P(None, axis), out_specs=P())(c)


__all__ = ["ag_gemm", "ag_gemm_ws", "create_ag_gemm_workspace",
           "create_ag_gemm_context", "AgGemmContext", "GemmConfig",
           "tp_column_linear"]
