"""Chaos harness (ISSUE 7): replay ONE 50-request disaggregated trace
under a sweep of seeded fault schedules and hold the engine to the
robustness contract:

- **survivable schedule** (degradation allowed): every request finishes
  and every token stream is BIT-IDENTICAL to the fault-free golden run —
  whatever mix of dropped/delayed/duplicated signals and dead peers the
  plan injected, the recovery ladder (deadline → retry/backoff → local
  re-prefill) must erase it without changing a single token.
- **unsurvivable schedule** (degradation off): the injected faults fail
  exactly the requests they touch, each with a TYPED reason carrying the
  ledger dump — never a hang, never an engine crash — and every
  un-faulted request still finishes bit-identical.
- after EVERY run, faulted or not: both page pools pass the
  ``KVPagePool.check`` full-invariant audit with zero pages in use.

Every test runs under a per-test SIGALRM watchdog (autouse fixture) on
top of the engine's own step-space stall watchdog — "no hang" is
enforced twice, once inside the contract and once outside it.
"""

import dataclasses
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.serving import (ControlJournal, DisaggServingEngine,
                                     EngineStallError,
                                     MigrationSignalTimeout,
                                     SignalProtocolError)
from triton_dist_tpu.serving.scheduler import RequestState
from triton_dist_tpu.shmem import FaultPlan
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.shmem.faults import InjectedCrash

pytestmark = [pytest.mark.disagg, pytest.mark.chaos]

WATCHDOG_S = 240          # per-test wall cap — generous, CPU CI is slow
N_REQUESTS = 50
MAX_STEPS = 6000          # step cap far above any legitimate run length


@pytest.fixture(autouse=True)
def chaos_watchdog():
    """Hard per-test wall-clock watchdog: a hang in ANY chaos schedule
    must kill the test loudly, not stall the suite. SIGALRM (not a
    thread) so even a wedged C call inside jax gets interrupted."""
    def boom(signum, frame):
        raise TimeoutError(
            f"chaos watchdog: test exceeded {WATCHDOG_S}s wall — "
            "the engine (or its harness) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def role_ctx():
    return initialize_distributed(axis_names=("role",), mesh_shape=(2,))


@pytest.fixture(scope="module")
def chaos_model():
    """Smaller than test_disagg's tiny model: the sweep runs the trace
    many times, so per-step cost dominates the budget."""
    cfg = dataclasses.replace(
        LlamaConfig(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
                    n_kv_heads=1, d_ff=64, max_seq_len=64),
        dtype=jnp.float32)
    params = init_params(jax.random.key(1), cfg)
    return cfg, params


def _trace():
    """The 50-request trace: staggered arrivals, prompt lengths spanning
    one to several pages, mixed decode budgets. Deterministic."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(N_REQUESTS):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        prompt = list(rng.randint(1, 128, size=plen))
        out.append((2 * i, prompt, mnt))       # arrival step, prompt, mnt
    return out


def _engine(chaos_model, ctx, **kw):
    cfg, params = chaos_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("num_prefill_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("pages_per_seq", 6)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("signal_deadline_steps", 3)
    kw.setdefault("max_retries", 3)
    return DisaggServingEngine(params, cfg, ctx=ctx, **kw)


def _audit(eng):
    """The end-of-run invariant wall (ISSUE 7 satellite): the pools'
    full self-audit, cross-checked against the live ledger, with zero
    residual ownership."""
    assert eng.alloc_p.used_pages == 0, "prefill pool leaked pages"
    assert eng.alloc_d.used_pages == 0, "decode pool leaked pages"
    eng.alloc_p.check(eng.channel.ledger)
    eng.alloc_d.check(eng.channel.ledger)


@pytest.fixture(scope="module")
def golden(chaos_model, role_ctx):
    """Fault-free run of the trace — the bit-identity reference."""
    eng = _engine(chaos_model, role_ctx)
    gold = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    assert len(gold) == N_REQUESTS
    _audit(eng)
    return gold


# the sweep: ≥8 seeded schedules covering the whole fault matrix. All of
# them are SURVIVABLE with degradation allowed (local re-prefill needs no
# peer), so each must reproduce the golden tokens bit for bit.
SCHEDULES = [
    ("clean", FaultPlan(seed=0)),
    ("drop_light", FaultPlan(seed=11, p_drop=0.25)),
    ("drop_heavy", FaultPlan(seed=12, p_drop=1.0)),
    ("delay", FaultPlan(seed=13, p_delay=0.9, max_delay_steps=12)),
    ("dup", FaultPlan(seed=14, p_dup=0.5)),
    ("drop_delay_mix", FaultPlan(seed=15, p_drop=0.2, p_delay=0.4,
                                 p_dup=0.1)),
    ("dead_peer_early", FaultPlan(seed=16, dead_peer_after=10)),
    ("dead_peer_late", FaultPlan(seed=17, dead_peer_after=60)),
    ("storm", FaultPlan(seed=18, p_drop=0.5, p_dup=0.3, p_delay=0.5,
                        max_delay_steps=10)),
    ("scoped_drop", FaultPlan(seed=19, p_drop=1.0, rids=(3, 7, 11))),
]


@pytest.mark.quick
@pytest.mark.parametrize("name,plan", SCHEDULES,
                         ids=[n for n, _ in SCHEDULES])
def test_survivable_schedule_bit_identical(chaos_model, role_ctx, golden,
                                           name, plan):
    """The headline sweep: under every seeded schedule, with the full
    ladder available, all 50 requests finish with golden-identical
    tokens, nothing fails, nothing hangs, and the pools audit clean."""
    eng = _engine(chaos_model, role_ctx, fault_plan=plan)
    res = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    assert eng.failed == [], (
        f"{name}: ladder should have saved every request; "
        f"failures: {[(r.rid, r.failure) for r in eng.failed]}")
    assert sorted(res) == sorted(golden), f"{name}: requests went missing"
    for rid in golden:
        assert res[rid] == golden[rid], (
            f"{name}: rid {rid} tokens diverged under faults")
    if plan.any_host_faults and name != "clean":
        assert eng.metrics.counters["faults_injected"] > 0, (
            f"{name}: schedule injected nothing — sweep lost its teeth")
    _audit(eng)


def test_replay_is_deterministic(chaos_model, role_ctx):
    """Same seed → byte-identical recovery trajectory: not just the same
    tokens, the same retry/degradation/fault counts. The property that
    makes a chaos failure reproducible from one integer."""
    plan = FaultPlan(seed=15, p_drop=0.2, p_delay=0.4, p_dup=0.1)
    trace = _trace()[:15]      # determinism needs two runs, not two LONG runs
    runs = []
    for _ in range(2):
        eng = _engine(chaos_model, role_ctx, fault_plan=plan)
        res = eng.run(max_steps=MAX_STEPS, arrivals=trace)
        c, d = eng.metrics.counters, eng.metrics_decode.counters
        runs.append((res, c["faults_injected"], d["retries"],
                     d["degradations"], d["failed_requests"]))
    assert runs[0] == runs[1]


def test_dropped_signal_recovers_via_retry(chaos_model, role_ctx, golden):
    """ISSUE 7 acceptance: a dropped-signal schedule that the RETRY rung
    alone absorbs — retries counted, zero degradations, tokens golden."""
    plan = FaultPlan(seed=21, p_drop=0.3)
    eng = _engine(chaos_model, role_ctx, fault_plan=plan, max_retries=6)
    res = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    assert eng.metrics_decode.counters["retries"] > 0
    assert eng.metrics_decode.counters["degradations"] == 0, (
        "this seed was chosen so retry alone recovers — degradation "
        "firing means the retry rung regressed")
    assert eng.failed == []
    for rid in golden:
        assert res[rid] == golden[rid]
    _audit(eng)


def test_dead_peer_degrades_via_local_reprefill(chaos_model, role_ctx,
                                                golden):
    """ISSUE 7 acceptance: a dead peer forces the DEGRADE rung — every
    request caught mid-migration re-prefills locally on the decode
    worker, survivors are bit-identical, the engine never stalls."""
    plan = FaultPlan(seed=22, dead_peer_after=10)
    eng = _engine(chaos_model, role_ctx, fault_plan=plan,
                  signal_deadline_steps=2, max_retries=1)
    res = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    assert eng.metrics_decode.counters["degradations"] > 0
    assert eng.metrics_decode.hist["degraded_prefill_tokens"].count > 0
    assert eng.metrics_decode.hist["degraded_ttft_s"].count > 0
    assert eng.failed == []
    for rid in golden:
        assert res[rid] == golden[rid]
    _audit(eng)


@pytest.mark.parametrize("name,plan,faulted_rids", [
    ("drop_heavy", FaultPlan(seed=12, p_drop=1.0), None),
    ("scoped_drop", FaultPlan(seed=19, p_drop=1.0, rids=(3, 7, 11)),
     {3, 7, 11}),
    ("dup_scoped", FaultPlan(seed=23, p_dup=1.0, rids=(5,)), {5}),
], ids=["drop_heavy", "scoped_drop", "dup_scoped"])
def test_unsurvivable_schedule_fails_typed(chaos_model, role_ctx, golden,
                                           name, plan, faulted_rids):
    """Degradation OFF: the same schedules must now fail exactly the
    requests they touch — typed reasons with the ledger dump, the engine
    still running, every untouched request bit-identical (the
    per-request failure domain, demonstrated on neighbors)."""
    eng = _engine(chaos_model, role_ctx, fault_plan=plan,
                  allow_degradation=False, signal_deadline_steps=2,
                  max_retries=1)
    res = eng.run(max_steps=MAX_STEPS, arrivals=_trace())   # never raises
    failed = {r.rid for r in eng.failed}
    assert failed, f"{name}: an unsurvivable schedule must fail someone"
    if faulted_rids is not None:
        assert failed == faulted_rids, (
            f"{name}: failure domain leaked — {failed} vs {faulted_rids}")
    for req in eng.failed:
        assert req.state is RequestState.FAILED
        assert isinstance(req.failure,
                          (MigrationSignalTimeout, SignalProtocolError))
        assert "chunk" in str(req.failure), "ledger dump missing"
        assert req.rid not in res
    # everyone the plan did NOT touch is golden
    for rid in golden:
        if rid not in failed:
            assert res[rid] == golden[rid], (
                f"{name}: un-faulted rid {rid} diverged")
    assert (eng.metrics_decode.counters["failed_requests"]
            == len(eng.failed))
    _audit(eng)


def test_over_signal_is_protocol_error_not_coverage(chaos_model, role_ctx):
    """The silent-poison fix (ISSUE 7 satellite): a duplicated increment
    must be DETECTED as over-signal, not widen coverage. With degradation
    off the poisoned request fails carrying SignalProtocolError."""
    plan = FaultPlan(seed=24, p_dup=1.0, rids=(0,))
    eng = _engine(chaos_model, role_ctx, fault_plan=plan,
                  allow_degradation=False)
    trace = _trace()[:4]
    res = eng.run(max_steps=MAX_STEPS, arrivals=trace)
    failed = {r.rid: r for r in eng.failed}
    assert set(failed) == {0}
    assert isinstance(failed[0].failure, SignalProtocolError)
    assert "over-signal" in str(failed[0].failure)
    assert sorted(res) == [1, 2, 3]
    _audit(eng)


@pytest.mark.recovery
def test_crash_under_signal_chaos_recovers_golden(chaos_model, role_ctx,
                                                  golden):
    """ISSUE 9 satellite: the crash rung composes with the ISSUE-7
    ladder. A schedule mixing dropped signals with a mid-trace CRASH must
    still land on the golden tokens — the restarted engine replays the
    journal, re-earns every dropped signal through retry, and the two
    fault tiers never observe each other."""
    plan = FaultPlan(seed=31, p_drop=0.25, crash_at=(40,))
    journal = ControlJournal()
    eng = _engine(chaos_model, role_ctx, fault_plan=plan, max_retries=6,
                  journal=journal, checkpoint_every=8)
    with pytest.raises(InjectedCrash):
        eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    done = sum(1 for e in journal.entries if e["kind"] == "submit")
    # the restarted incarnation keeps the SAME plan: signal drops stay
    # live after restore (only the crash is incarnation-gated)
    eng2 = _engine(chaos_model, role_ctx, fault_plan=plan, max_retries=6,
                   journal=journal, checkpoint_every=8)
    res = eng2.run(max_steps=MAX_STEPS, arrivals=_trace()[done:],
                   recover=True)
    assert eng2.metrics.counters["restores"] == 1
    assert eng2.failed == []
    assert sorted(res) == sorted(golden)
    for rid in golden:
        assert res[rid] == golden[rid], f"rid {rid} diverged"
    _audit(eng2)


def test_stall_watchdog_backstops_ladder_bugs(chaos_model, role_ctx,
                                              monkeypatch):
    """If the ladder itself were broken (here: its terminal verb is
    stubbed out so an expired request just waits forever), the global
    step-space watchdog must convert the livelock into EngineStallError
    with a state dump — the 'never a hang' guarantee does not depend on
    the ladder being correct."""
    plan = FaultPlan(seed=25, p_drop=1.0)
    eng = _engine(chaos_model, role_ctx, fault_plan=plan,
                  signal_deadline_steps=2, max_retries=0,
                  stall_deadline_steps=40)
    monkeypatch.setattr(eng, "_degrade_or_fail", lambda *a, **k: None)
    with pytest.raises(EngineStallError, match="no progress"):
        eng.run(max_steps=MAX_STEPS, arrivals=_trace()[:3])
