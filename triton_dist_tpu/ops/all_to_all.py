"""Low-latency All-to-All + MoE EP dispatch/combine (analog of reference
python/triton_dist/kernels/nvidia/low_latency_all_to_all.py — the README
showcase kernel, 137 µs vs DeepEP's 182 µs — and ep_a2a.py).

Reference protocol (low_latency_all_to_all.py:35-118): one CTA per peer does
``putmem_nbi_block`` of capacity-padded token data + splits into the peer's
symmetric buffer, ``fence``, ``signal_op``; then ``signal_wait_until`` on its
own flags; double-buffered by call-count parity (:125-164).

TPU-native redesign:

- The token-routing scatter the reference does with warp-level atomic slot
  allocation inside the kernel (ep_a2a.py:64-147) has no TPU analog (no
  per-warp atomics); it is a *static-shape scatter* here, computed on the VPU
  with one-hot cumsums (`route_tokens`) — compiler-friendly and fully
  vectorized.
- The wire collective is ``all_to_all_push``: every PE owns a
  ``[n, capacity, ...]`` payload, slot p goes to peer p; delivery is signaled
  by the receive DMA semaphore (no separate flag word needed). Payload sizes
  are static (capacity-padded) — the reference pads to MAX_M the same way
  (:141-147).
- Per-call output buffers + an entry barrier replace the call-count parity
  scheme: a peer cannot write into a buffer instance of call k+1 before
  every PE has entered call k+1.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


# ---------------------------------------------------------------------------
# wire collective
# ---------------------------------------------------------------------------

def _a2a_kernel(axis, mesh_axes, n_arrays, refs):
    """refs = [in_0..in_{A-1}, out_0..out_{A-1}, send_sems, recv_sems].
    Each array is [n, ...]: in slot p is the payload for peer p; out slot p
    is the payload received from peer p."""
    ins = refs[:n_arrays]
    outs = refs[n_arrays:2 * n_arrays]
    send_sems, recv_sems = refs[2 * n_arrays:]
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)

    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    local_copies = []
    for a in range(n_arrays):
        c = pltpu.make_async_copy(ins[a].at[me], outs[a].at[me],
                                  recv_sems.at[a, me])
        c.start()
        local_copies.append(c)
    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        for a in range(n_arrays):
            rdmas.append(shd.putmem_nbi(outs[a].at[me], ins[a].at[dst],
                                        send_sems.at[a, dst],
                                        recv_sems.at[a, me], pid))
    for c in local_copies:
        c.wait()
    for p in range(1, n):
        src = lax.rem(me + p, n)
        for a in range(n_arrays):
            shd.wait_recv(outs[a].at[src], recv_sems.at[a, src])
    shd.quiet(*rdmas)


def all_to_all_push(ctx: ShmemContext, *arrays: jax.Array,
                    axis: str | None = None) -> tuple[jax.Array, ...]:
    """Generic low-latency All-to-All: each input is globally
    ``[n*n, ...]`` sharded P(axis) — locally ``[n, ...]`` where slot p is the
    payload destined for peer p. Returns same-shaped arrays where local slot
    p holds the payload *received from* peer p. One kernel, one put per
    (peer, array), arrival = DMA semaphore."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    n_arrays = len(arrays)

    def f(*shards):
        kernel = lambda *refs: _a2a_kernel(axis, mesh_axes, n_arrays, refs)
        out = pl.pallas_call(
            kernel,
            out_shape=tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                            for s in shards),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_arrays,
            out_specs=tuple(pl.BlockSpec(memory_space=pl.ANY)
                            for _ in shards),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((n_arrays, n)),
                pltpu.SemaphoreType.DMA((n_arrays, n)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("all_to_all")),
            interpret=default_interpret(),
        )(*shards)
        return out if isinstance(out, tuple) else (out,)

    sm = ctx.shard_map(f, in_specs=tuple(P(axis) for _ in arrays),
                       out_specs=tuple(P(axis) for _ in arrays))
    return sm(*arrays)


# ---------------------------------------------------------------------------
# MoE EP dispatch / combine
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class EpAllToAllContext:
    """Analog of the reference's A2A context dataclass
    (low_latency_all_to_all.py:125-164): static shapes + mesh info.
    ``capacity`` is the per-(src,dst) token budget — tokens routed beyond it
    are dropped (standard expert-capacity semantics; the reference instead
    sizes buffers for the worst case, which equals
    ``capacity = max_tokens * topk``)."""
    ctx: ShmemContext
    axis: str
    max_tokens: int      # tokens per rank entering dispatch
    hidden: int
    topk: int
    num_experts: int     # global expert count
    capacity: int        # slots per (src,dst) rank pair
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def n_ranks(self) -> int:
        return self.ctx.axis_size(self.axis)

    @property
    def experts_per_rank(self) -> int:
        return self.num_experts // self.n_ranks


def create_all_to_all_context(ctx: ShmemContext, max_tokens: int, hidden: int,
                              topk: int, num_experts: int,
                              capacity: int | None = None,
                              axis: str | None = None,
                              dtype=jnp.bfloat16) -> EpAllToAllContext:
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    assert num_experts % n == 0, (num_experts, n)
    if capacity is None:
        capacity = max_tokens * topk  # worst case: everything to one rank
    # round up to the bf16 sublane count so [capacity, hidden] DMA slices
    # meet Mosaic's tiling alignment on real TPUs
    capacity = (capacity + 15) // 16 * 16
    assert hidden % 128 == 0, f"hidden={hidden} must be a lane multiple (128)"
    return EpAllToAllContext(ctx=ctx, axis=axis, max_tokens=max_tokens,
                             hidden=hidden, topk=topk,
                             num_experts=num_experts, capacity=capacity,
                             dtype=jnp.dtype(dtype))


def route_tokens(a2a: EpAllToAllContext, topk_ids: jax.Array):
    """Static-shape routing (replaces the reference's in-kernel atomic slot
    allocation, ep_a2a.py:64-147). ``topk_ids`` is the *local* [T, topk]
    expert assignment. Returns (dest [T,k], slot [T,k], valid [T,k]) where
    ``slot`` is the token's position in the capacity-padded lane to rank
    ``dest``. Pure jnp — runs under jit/shard_map per device."""
    T, k = topk_ids.shape
    n = a2a.n_ranks
    dest = topk_ids // a2a.experts_per_rank                      # [T,k]
    flat_dest = dest.reshape(-1)                                  # [T*k]
    one_hot = jax.nn.one_hot(flat_dest, n, dtype=jnp.int32)       # [T*k, n]
    slot_flat = jnp.cumsum(one_hot, axis=0) - one_hot             # exclusive
    slot = jnp.take_along_axis(slot_flat, flat_dest[:, None],
                               axis=1)[:, 0].reshape(T, k)
    valid = slot < a2a.capacity
    return dest, slot, valid


def dispatch(a2a: EpAllToAllContext, tokens: jax.Array, topk_ids: jax.Array):
    """EP dispatch (analog of ``fast_all_to_all``,
    low_latency_all_to_all.py:189-248). Global inputs sharded P(axis):
    ``tokens`` [n*T, H], ``topk_ids`` [n*T, topk]. Returns
    (recv_tokens [n, n, capacity, H] P(axis), recv_ids [n, n, capacity]
    P(axis), layout) — receiver slot (src, c) holds a token from rank src
    targeting local expert recv_ids[src, c] (or -1 padding). ``layout`` is
    kept for ``combine``."""
    ctx, axis = a2a.ctx, a2a.axis
    n, cap, H, k = a2a.n_ranks, a2a.capacity, a2a.hidden, a2a.topk
    assert tokens.shape == (n * a2a.max_tokens, H), (
        f"dispatch: tokens {tokens.shape} != "
        f"({n}*{a2a.max_tokens}, {H}) from the a2a context")
    assert topk_ids.shape == (n * a2a.max_tokens, k), (
        f"dispatch: topk_ids {topk_ids.shape} != ({n * a2a.max_tokens}, {k})")

    id_cols = max((cap + 127) // 128 * 128, 128)  # lane-aligned ids lane

    def build(tok_shard, ids_shard):
        dest, slot, valid = route_tokens(a2a, ids_shard)
        send_buf = jnp.zeros((n, cap, H), a2a.dtype)
        send_ids = jnp.full((n, id_cols), -1, jnp.int32)
        tok_rep = jnp.repeat(tok_shard[:, None, :], k, axis=1).reshape(-1, H)
        d_f, s_f, v_f = (x.reshape(-1) for x in (dest, slot, valid))
        # over-capacity tokens get an out-of-bounds slot -> dropped by the
        # scatter (never clobbering a valid slot)
        s_drop = jnp.where(v_f, s_f, cap)
        local_eid = (ids_shard % a2a.experts_per_rank).reshape(-1)
        send_buf = send_buf.at[d_f, s_drop].set(
            tok_rep.astype(a2a.dtype), mode="drop")
        send_ids = send_ids.at[d_f, s_drop].set(local_eid, mode="drop")
        # wire format: [n, rows, 128] so the per-peer DMA slice is
        # lane-aligned on real TPUs
        return send_buf, send_ids.reshape(n, id_cols // 128, 128), dest, slot, valid

    sm = ctx.shard_map(build, in_specs=(P(axis), P(axis)),
                       out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)))
    send_buf, send_ids, dest, slot, valid = sm(tokens, topk_ids)
    recv_tokens, recv_ids_wire = all_to_all_push(ctx, send_buf, send_ids,
                                                 axis=axis)
    unpack = ctx.shard_map(
        lambda w: w.reshape(n, id_cols)[:, :cap],
        in_specs=P(axis), out_specs=P(axis))
    recv_ids = unpack(recv_ids_wire)
    layout = (dest, slot, valid)
    return recv_tokens, recv_ids, layout


def combine(a2a: EpAllToAllContext, processed: jax.Array, layout,
            topk_weights: jax.Array) -> jax.Array:
    """EP combine (analog of ``kernel_combine_token`` ep_a2a.py:150-241 +
    post-process :251-270): send processed tokens back to their source ranks
    at the same slots, then weighted-sum each token's topk copies.
    ``processed`` is [n*n, capacity, H] sharded P(axis) — local [n, cap, H]
    where slot (src, c) is the processed token for rank src's slot c."""
    ctx, axis = a2a.ctx, a2a.axis
    n, cap, H, k = a2a.n_ranks, a2a.capacity, a2a.hidden, a2a.topk
    (back,) = all_to_all_push(ctx, processed, axis=axis)

    def gather_back(back_shard, dest, slot, valid, w):
        # back_shard: [n, cap, H] — slot (d, c) = my token processed by rank d
        d_f = dest.reshape(-1)
        s_f = jnp.where(valid, slot, 0).reshape(-1)
        tok = back_shard[d_f, s_f]                                # [T*k, H]
        tok = jnp.where(valid.reshape(-1)[:, None], tok, 0.0)
        T = dest.shape[0]
        tok = tok.reshape(T, k, H).astype(jnp.float32)
        return jnp.sum(tok * w[..., None].astype(jnp.float32),
                       axis=1).astype(a2a.dtype)

    dest, slot, valid = layout
    sm = ctx.shard_map(gather_back,
                       in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
                       out_specs=P(axis))
    return sm(back, dest, slot, valid, topk_weights)


__all__ = ["all_to_all_push", "EpAllToAllContext", "create_all_to_all_context",
           "route_tokens", "dispatch", "combine"]
