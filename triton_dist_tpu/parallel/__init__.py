from triton_dist_tpu.parallel.mesh import make_mesh, factorize_devices  # noqa: F401
from triton_dist_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
from triton_dist_tpu.parallel.train import (  # noqa: F401
    ParallelPlan, TrainState, make_train_step)
