"""Kernel tests at the driver's exact 8-way configuration.

The multichip dryrun covers the pure-XLA training path at 8 devices; this
module runs the hand-written Pallas collectives and overlap kernels on an
8-participant mesh (over 12 virtual devices — see conftest on why spare
device threads are required)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD_WIDE
from triton_dist_tpu.ops import all_gather, reduce_scatter
from triton_dist_tpu.ops.all_to_all import (combine,
                                            create_all_to_all_context,
                                            dispatch)
from triton_dist_tpu.ops.allgather_gemm import ag_gemm
from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx8():
    return initialize_distributed(axis_names=("x",),
                                  mesh_shape=(TEST_WORLD_WIDE,))


@pytest.mark.parametrize("method", ["push", "ring"])
def test_all_gather_8way(ctx8, method):
    n = ctx8.num_ranks
    x = jax.random.normal(jax.random.key(0), (n * 8, 128), jnp.float32)
    xs = ctx8.shard(x, P("x"))
    y = jax.jit(lambda v: all_gather(ctx8, v, axis="x", method=method))(xs)
    assert_allclose(np.asarray(y), np.asarray(x))


def test_reduce_scatter_8way(ctx8):
    n = ctx8.num_ranks
    x = jnp.round(jax.random.normal(jax.random.key(1), (n * 8, 128)) * 4)
    xs = ctx8.shard(x.astype(jnp.float32), P("x"))
    got = jax.jit(lambda v: reduce_scatter(ctx8, v, axis="x"))(xs)
    gold = jax.jit(ctx8.shard_map(
        lambda s: jax.lax.psum_scatter(s, "x", scatter_dimension=0,
                                       tiled=True),
        in_specs=P("x"), out_specs=P("x")))(xs)
    assert_allclose(np.asarray(got), np.asarray(gold))


def test_ag_gemm_8way(ctx8):
    n = ctx8.num_ranks
    M = K = 8 * n
    N = 128 * n
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    cfg = GemmConfig(M // n, 128)
    c = jax.jit(lambda u, v: ag_gemm(ctx8, u, v, axis="x", cfg=cfg))(
        ctx8.shard(a, P("x")), ctx8.shard(b, P(None, "x")))
    assert_allclose(np.asarray(c, np.float32), np.asarray(a @ b),
                    rtol=5e-2, atol=5e-1)


def test_a2a_roundtrip_8way(ctx8):
    n = ctx8.num_ranks
    T, H, topk = n * 4, 128, 2
    a2a = create_all_to_all_context(ctx8, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x")
    t = jax.random.normal(jax.random.key(2), (T, H), jnp.float32
                          ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(3), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk

    def roundtrip(tt, ii, ww):
        recv, _, layout = dispatch(a2a, tt, ii)
        return combine(a2a, recv, layout, ww)

    out = jax.jit(roundtrip)(ctx8.shard(t, P("x")), ctx8.shard(ids, P("x")),
                             ctx8.shard(w, P("x")))
    assert_allclose(np.asarray(out, np.float32), np.asarray(t, np.float32),
                    rtol=3e-2, atol=3e-2)


def test_sp_decode_fused_8way(ctx8):
    n = ctx8.num_ranks
    B, Hq, Hkv, D, s_local = 1, 4, 2, 128, 64
    S = n * s_local
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv = jnp.array([S], jnp.int32)
    out = jax.jit(lambda *a: sp_gqa_flash_decode(ctx8, *a,
                                                 ag_method="fused"))(
        q, ctx8.shard(k, P(None, None, "x")),
        ctx8.shard(v, P(None, None, "x")), kv)
    # golden via the generic push path (independently tested vs dense)
    gold = jax.jit(lambda *a: sp_gqa_flash_decode(ctx8, *a,
                                                  ag_method="push"))(
        q, ctx8.shard(k, P(None, None, "x")),
        ctx8.shard(v, P(None, None, "x")), kv)
    assert_allclose(np.asarray(out), np.asarray(gold), atol=1e-4, rtol=1e-4)


@pytest.fixture(scope="module")
def ctx24():
    """(2, 4) two-tier mesh over the driver's 8-way device count."""
    return initialize_distributed(axis_names=("o", "i"), mesh_shape=(2, 4))


def test_ag_gemm_2d_8way(ctx24):
    from triton_dist_tpu.ops.allgather_gemm import GemmConfig, ag_gemm
    n, axes = 8, ("o", "i")
    M, K, N = n * 8, 128, n * 16
    a = ctx24.shard(jax.random.normal(jax.random.key(0), (M, K)), P(axes))
    b = ctx24.shard(jax.random.normal(jax.random.key(1), (K, N)),
                    P(None, axes))
    c = jax.jit(lambda a, b: ag_gemm(ctx24, a, b, axis=axes,
                                     cfg=GemmConfig(8, 16),
                                     out_dtype=jnp.float32))(a, b)

    def g(a_s, b_s):
        af = jax.lax.all_gather(a_s, axes, axis=0, tiled=True)
        return jnp.dot(af, b_s, preferred_element_type=jnp.float32)
    gold = jax.jit(ctx24.shard_map(g, in_specs=(P(axes), P(None, axes)),
                                   out_specs=P(None, axes)))(a, b)
    assert_allclose(np.asarray(c), np.asarray(gold), atol=1e-4, rtol=1e-4)


def test_gemm_rs_2d_8way(ctx24):
    from triton_dist_tpu.ops.gemm_reduce_scatter import GemmConfig, gemm_rs
    n, axes = 8, ("o", "i")
    M, K, N = n * 8, n * 16, 32
    a = ctx24.shard(jax.random.normal(jax.random.key(0), (M, K)),
                    P(None, axes))
    b = ctx24.shard(jax.random.normal(jax.random.key(1), (K, N)),
                    P(axes, None))
    c = jax.jit(lambda a, b: gemm_rs(ctx24, a, b, axis=axes,
                                     cfg=GemmConfig(8, 32),
                                     out_dtype=jnp.float32))(a, b)

    def g(a_s, b_s):
        part = jnp.dot(a_s, b_s, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(part, axes, scatter_dimension=0,
                                    tiled=True)
    gold = jax.jit(ctx24.shard_map(g, in_specs=(P(None, axes),
                                                P(axes, None)),
                                   out_specs=P(axes)))(a, b)
    assert_allclose(np.asarray(c), np.asarray(gold), atol=1e-4, rtol=1e-4)
