"""Multi-process worker driven by tests/test_multiprocess.py (and runnable
by hand: see __main__). One python process per "host", CPU backend with 2
local virtual devices each — the single-controller-per-process model a real
TPU pod uses, minus the chips (reference analog: one torchrun rank per GPU,
launch.sh:33-44 + utils.py:91-111 bootstrap).

Covers the three multi-host paths nothing else tests with
``process_count() > 1``:
- ``initialize_distributed``'s env-gated ``jax.distributed.initialize``
  (shmem/context.py) incl. the JAX_NUM_PROCESSES/JAX_PROCESS_ID forwarding,
- a pure-XLA collective over a mesh spanning both processes,
- the autotuner's cross-process MAX consensus
  (``_consensus_times`` → ``multihost_utils.process_allgather``).
"""

import os
import sys


def main() -> None:
    # env must be pinned BEFORE jax import: 2 local CPU devices per process
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_dist_tpu.shmem.context import initialize_distributed
    from triton_dist_tpu.tools import contextual_autotune

    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(4,))
    assert jax.process_count() == 2, jax.process_count()
    me = jax.process_index()
    sharding = NamedSharding(ctx.mesh, P("x"))

    # Backend capability probe FIRST: on the jax 0.4.x line the jaxlib CPU
    # client refuses ANY computation spanning processes ("Multiprocess
    # computations aren't implemented on the CPU backend") — the bootstrap
    # above succeeds, the first spanning jit raises. Probe it with a tiny
    # array so that version's pinned outcome is one explicit token the
    # test keys on, not a traceback halfway through the real work.
    try:
        jax.block_until_ready(
            jax.jit(lambda: jnp.zeros((4, 1), jnp.float32),
                    out_shardings=sharding)())
    except Exception as e:  # noqa: BLE001 — the token carries the type
        print(f"MP_BACKEND_NO_MULTIPROC {type(e).__name__}: "
              f"{str(e)[:160]}", flush=True)
        os._exit(0)

    # pure-XLA collective across both processes' devices, traced into a
    # merged per-host-track profile when the harness asks for one
    from triton_dist_tpu.utils.perf import group_profile

    prof_dir = os.environ.get("TDT_PROF_DIR")
    with group_profile("mp", do_prof=prof_dir is not None,
                       out_dir=prof_dir or "prof"):
        ones = jax.jit(lambda: jnp.ones((8, 128), jnp.float32),
                       out_shardings=sharding)()
        total = jax.jit(
            ctx.shard_map(lambda s: jax.lax.psum(jnp.sum(s), "x"),
                          in_specs=P("x"), out_specs=P()))(ones)
        np.testing.assert_allclose(np.asarray(total), 8 * 128)
    if prof_dir and me == 0:
        merged = os.path.join(prof_dir, "mp", "merged.trace.json.gz")
        assert os.path.exists(merged), f"missing merged trace {merged}"
        print("MP_PROF_MERGED", flush=True)

    # autotuned op: both configs timed on every process, consensus = MAX
    calls = []

    @contextual_autotune(configs=[2, 3], iters=1, warmup=0)
    def op(x, cfg=None):
        calls.append(cfg)
        return x * cfg

    y = op(jnp.ones((4,), jnp.float32))
    assert sorted(set(calls)) == [2, 3], calls
    picked = float(np.asarray(y)[0])
    print(f"MP_OK process={me}/{jax.process_count()} picked={picked}",
          flush=True)

    # LAST: an OVERLAP KERNEL across the process boundary (VERDICT r4 #8
    # — no Pallas protocol crossed a process boundary before). The
    # interpret-mode runtime simulates DMA/semaphores with IN-PROCESS
    # state, so a kernel whose mesh spans two processes cannot see the
    # other process's signals: the attempt DEADLOCKS (measured round 5 —
    # not an error, a hang; each interpreter waits on semaphores only the
    # other process's interpreter would satisfy). A daemon watchdog pins
    # that outcome; if a future runtime routes the cross-process slices,
    # the same probe flips to MP_AG_OK and the golden is checked.
    # os._exit afterwards: a hung interpret thread would otherwise block
    # interpreter shutdown forever.
    import threading

    def attempt():
        try:
            from triton_dist_tpu.ops import all_gather
            x = jax.jit(lambda: jnp.arange(4 * 8 * 128, dtype=jnp.float32
                                           ).reshape(4 * 8, 128),
                        out_shardings=sharding)()
            y2 = jax.jit(lambda v: all_gather(ctx, v, axis="x",
                                              method="push"))(x)
            got = np.asarray(jax.device_get(y2))
        except Exception as e:
            print(f"MP_AG_UNSUPPORTED {type(e).__name__}: {str(e)[:160]}",
                  flush=True)
            return
        try:
            np.testing.assert_allclose(
                got, np.arange(4 * 8 * 128,
                               dtype=np.float32).reshape(4 * 8, 128))
        except AssertionError as e:
            # ran but produced WRONG data — a distinct (worst) outcome
            # that must fail the test, never read as "unsupported"
            print(f"MP_AG_WRONG_RESULT {str(e)[:160]}", flush=True)
            return
        print("MP_AG_OK", flush=True)

    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    t.join(timeout=45)
    if t.is_alive():
        print("MP_AG_UNSUPPORTED Deadlock: interpret-mode kernel "
              "semaphores are in-process state; a 2-process mesh never "
              "sees the peer's signals", flush=True)
    os._exit(0)


if __name__ == "__main__":
    # standalone: python tests/mp_worker.py <process_id> <num_processes> <addr>
    if len(sys.argv) == 4:
        os.environ["JAX_PROCESS_ID"] = sys.argv[1]
        os.environ["JAX_NUM_PROCESSES"] = sys.argv[2]
        os.environ["JAX_COORDINATOR_ADDRESS"] = sys.argv[3]
    main()
