"""Sequence-parallel GQA flash-decode attention module (analog of reference
layers/nvidia/sp_flash_decode_layer.py:43-184 ``SpGQAFlashDecodeAttention``).

The reference module owns a growable AG staging buffer that it resizes as
the serving batch changes (:111-132) and toggles between JIT and AOT kernel
paths (:96-105). The TPU analog of "growable buffer, no re-setup": a
``max_batch`` configured once — the KV cache is allocated at ``max_batch``
(as a serving loop does anyway), incoming sub-batches are padded to it
OUTSIDE the kernel, and ONE compiled kernel instance serves every batch
size ≤ ``max_batch`` (padding rows attend to one token and are sliced
away). Without ``max_batch`` each distinct batch size compiles once and is
then cached (jit shape-keying) — steps never recompile either way."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.flash_decode import (gqa_decode_paged,
                                              sp_gqa_flash_decode)
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class SpGQAFlashDecodeAttention:
    ctx: ShmemContext
    num_q_heads: int
    num_kv_heads: int
    head_dim: int
    axis: str | None = None
    block_s: int = 128
    ag_method: str = "fused"  # fused partial-AG + lse-merge latency path
    max_batch: int | None = None  # serve any B <= max_batch, one compile

    def __post_init__(self):
        # one jitted forward per layer object: shape-keyed cache means a
        # repeated (batch, seq) shape NEVER retraces; with ``max_batch``
        # padding there is exactly one kernel shape, period
        object.__setattr__(self, "_fwd", jax.jit(
            lambda q, k, v, lens: sp_gqa_flash_decode(
                self.ctx, q, k, v, lens, axis=self.axis,
                block_s=self.block_s, ag_method=self.ag_method)))

    def __call__(self, q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                 global_kv_lens: jax.Array) -> jax.Array:
        """q [B, Hq, D] replicated; k/v_cache [B', Hkv, S, D] sequence-sharded
        P(None, None, axis); global_kv_lens [B]. Returns [B, Hq, D] replicated
        (local split-KV decode → partial (out‖lse) allgather → lse-merge).

        With ``max_batch`` set, B' must be ``max_batch`` (the serving
        loop's cache allocation) and any B ≤ ``max_batch`` is served by
        the SAME compiled kernel: q/kv_lens are padded to ``max_batch``
        (pad rows attend to 1 token of the allocated cache — real rows,
        finite math) and the result is sliced back to B."""
        B, Hq, D = q.shape
        assert Hq == self.num_q_heads and D == self.head_dim
        assert k_cache.shape[1] == self.num_kv_heads, (
            f"cache has {k_cache.shape[1]} kv heads, "
            f"layer configured for {self.num_kv_heads}")
        if self.max_batch is None or B == k_cache.shape[0] == self.max_batch:
            return self._fwd(q, k_cache, v_cache, global_kv_lens)
        mb = self.max_batch
        assert B <= mb, f"batch {B} exceeds the layer's max_batch {mb}"
        assert k_cache.shape[0] == mb, (
            f"with max_batch={mb} the KV cache must be allocated at "
            f"max_batch (got batch dim {k_cache.shape[0]}) — that is the "
            "buffer the serving loop owns, reference "
            "sp_flash_decode_layer.py:111-132")
        q_pad = jnp.concatenate(
            [q, jnp.zeros((mb - B, Hq, D), q.dtype)])
        lens_pad = jnp.concatenate(
            [global_kv_lens,
             jnp.ones((mb - B,), global_kv_lens.dtype)])
        return self._fwd(q_pad, k_cache, v_cache, lens_pad)[:B]


@dataclasses.dataclass(frozen=True)
class PagedGQADecodeAttention:
    """Paged twin of :class:`SpGQAFlashDecodeAttention` — the serving-side
    module over ``ops.flash_decode.gqa_decode_paged`` and the page pool the
    serving runtime allocates (``serving.kv_pool.KVPagePool``).

    Where the SP layer owns a growable AG buffer, the paged layer owns
    nothing: the POOL is the growable buffer (pages, not rows), shared by
    every sequence, so batch membership changes without touching device
    memory — the block table is the only thing that moves. One jitted
    forward serves every step: q [B, Hq, D], block_table [B, pages_per_seq]
    and kv_len [B] are fixed shapes in a slot-based serving loop
    (``serving.engine.ServingEngine``), and inactive rows ride along masked
    (kv_len's mask means a parked row costs one page of compute).
    """
    num_q_heads: int
    num_kv_heads: int
    head_dim: int
    page_size: int = 16
    sm_scale: float | None = None

    def __post_init__(self):
        object.__setattr__(self, "_fwd", jax.jit(
            lambda q, kp, vp, bt, lens: gqa_decode_paged(
                q, kp, vp, bt, lens, sm_scale=self.sm_scale)))

    def __call__(self, q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                 block_table: jax.Array, kv_len: jax.Array
                 ) -> tuple[jax.Array, jax.Array]:
        """q [B, Hq, D]; k/v_pages [P, Hkv, page_size, D] pool;
        block_table [B, pages_per_seq] int32; kv_len [B] (0 allowed).
        Returns (out [B, Hq, D], lse [B, Hq, 128] f32) — the same
        (out, lse) contract the SP combine consumes, so a later SP-serving
        layer can allgather-merge paged partials exactly like
        ``sp_gqa_flash_decode`` merges contiguous ones."""
        B, Hq, D = q.shape
        assert Hq == self.num_q_heads and D == self.head_dim
        assert k_pages.shape[1] == self.num_kv_heads, (
            f"pool has {k_pages.shape[1]} kv heads, layer configured for "
            f"{self.num_kv_heads}")
        assert k_pages.shape[2] == self.page_size, (
            f"pool page_size {k_pages.shape[2]} != layer page_size "
            f"{self.page_size}")
        return self._fwd(q, k_pages, v_pages, block_table, kv_len)

    def update_and_attend(self, q: jax.Array, k_new: jax.Array,
                          v_new: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, block_table: jax.Array,
                          pos: jax.Array,
                          active: jax.Array | None = None
                          ) -> tuple[jax.Array, jax.Array, jax.Array,
                                     jax.Array]:
        """The decode-step composite: scatter this step's (k, v) row into
        the pool (``ops.flash_decode.paged_kv_write``), then attend over
        ``kv_len = pos + 1``. ``pos`` [B] int32 is each slot's write
        position; ``active`` [B] bool parks frozen rows' writes on the
        scratch page (the multi-token scanned decode's done-mask).
        Returns (out, lse, k_pages, v_pages) — callers thread the updated
        pool through their layer loop."""
        from triton_dist_tpu.ops.flash_decode import paged_kv_write

        k_pages, v_pages = paged_kv_write(k_pages, v_pages, k_new, v_new,
                                          block_table, pos, active=active)
        out, lse = self(q, k_pages, v_pages, block_table,
                        (pos + 1).astype(jnp.int32))
        return out, lse, k_pages, v_pages
