"""sigcheck — static signal-protocol verifier for the overlap kernels.

The dynamic validation ladder (interpret-mode race detector → serial-mode
bisection → noise fuzzing, docs/debugging.md) only checks the one schedule
it executed, at one mesh size. This package adds rung 0: a *static* pass
that replays each kernel's Python body per rank with symbolic bookkeeping —
no devices, no execution — and proves, over n ∈ {2, 3, 4}:

- **coverage**: signals reaching each ``signal_wait_until(sem, v)`` /
  ``wait_recv`` sum to exactly what it consumes (under-signal = static
  deadlock, over-signal = the PR-6 ledger-poison bug class);
- **deadlock-freedom**: the cross-rank wait graph has an execution order
  (found by simulating the recorded event streams);
- **ordering**: every read of a remote-put destination is dominated by a
  wait on the covering semaphore (static analog of the race detector,
  covering all grid positions at once);
- **trace determinism** (serving contract): the serving programs' jaxprs
  contain no rank-count-dependent reduction or host-callback op.

Entry points: :func:`sigcheck` (one op), :func:`check_registry` (the whole
public surface), :func:`lint.lint_serving_programs` (the jaxpr lint), and
``scripts/sigcheck.py`` (CLI, JSON findings).
"""

from .events import Event, Region, SemId
from .checker import Finding, check_events
from .capture import FakeContext, capture_op
from .lint import lint_determinism, lint_serving_programs
from .api import OpReport, sigcheck, check_registry, check_gallery

__all__ = [
    "Event", "Region", "SemId", "Finding", "check_events",
    "FakeContext", "capture_op", "lint_determinism",
    "lint_serving_programs", "OpReport", "sigcheck", "check_registry",
    "check_gallery",
]
