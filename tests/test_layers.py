"""Module-layer smoke tests (the layers are thin over already-golden-tested
ops; these check wiring and the dispatch→combine layout hand-off)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.layers import (AllGatherLayer, ColumnParallelLinear,
                                    EPAll2AllLayer, RowParallelLinear,
                                    SpGQAFlashDecodeAttention)
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def test_allgather_layer(ctx):
    n = ctx.num_ranks
    layer = AllGatherLayer(ctx, axis="x")
    x = jax.random.normal(jax.random.key(0), (n * 16, 128))
    xs = ctx.shard(x, P("x"))
    for fwd in (layer.forward_push, layer.forward_ring, layer):
        y = jax.jit(fwd)(xs)
        assert_allclose(np.asarray(y), np.asarray(x))


def test_tp_linears_compose(ctx):
    """Column-parallel then row-parallel = the classic 2-linear TP MLP
    data path; end result must equal the dense computation."""
    n = ctx.num_ranks
    M, K, F = n * 32, 128, n * 64
    x = jax.random.normal(jax.random.key(0), (M, K)) * 0.3
    w1 = jax.random.normal(jax.random.key(1), (K, F)) * 0.3
    w2 = jax.random.normal(jax.random.key(2), (F, K)) * 0.3
    cfg = GemmConfig(block_m=32, block_n=32)
    col = ColumnParallelLinear(ctx, axis="x", cfg=cfg)
    row = RowParallelLinear(ctx, axis="x", cfg=cfg)

    @jax.jit
    def f(xs, w1s, w2s):
        h = col(xs, w1s)          # [M, F] P(None, x)
        return row(h, w2s)        # [M, K] P(x)

    y = f(ctx.shard(x, P("x")), ctx.shard(w1, P(None, "x")),
          ctx.shard(w2, P("x", None)))
    golden = np.asarray(x) @ np.asarray(w1) @ np.asarray(w2)
    assert_allclose(np.asarray(y), golden, atol=1e-3, rtol=1e-3)


def test_ep_layer_roundtrip(ctx):
    n = ctx.num_ranks
    T, H, k, E = 8, 128, 2, n * 2
    layer = EPAll2AllLayer.create(ctx, max_tokens=T, hidden=H, topk=k,
                                  num_experts=E, dtype=jnp.float32)
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n * T, k), 0, E)
    w = jnp.full((n * T, k), 1.0 / k)
    ts, is_, ws = (ctx.shard(t, P("x")) for t in (tokens, ids, w))
    recv_tok, recv_ids, layout = layer.dispatch(ts, is_)
    out = layer.combine(recv_tok, layout, ws)  # identity experts
    # each token = mean of k identical copies of itself (weights 1/k)
    assert_allclose(np.asarray(out), np.asarray(tokens), atol=1e-4, rtol=1e-4)


def test_sp_decode_layer(ctx):
    n = ctx.num_ranks
    B, Hq, Hkv, D, s_local = 1, 4, 2, 128, 128
    S = n * s_local
    attn = SpGQAFlashDecodeAttention(ctx, num_q_heads=Hq, num_kv_heads=Hkv,
                                     head_dim=D, axis="x")
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    kc = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    vc = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    lens = jnp.array([S], jnp.int32)
    out = jax.jit(attn)(q, ctx.shard(kc, P(None, None, "x")),
                        ctx.shard(vc, P(None, None, "x")), lens)
    assert out.shape == (B, Hq, D)
    assert np.isfinite(np.asarray(out)).all()


def test_sp_decode_layer_dynamic_batch(ctx):
    """ONE layer object serves three serving batch sizes through ONE
    compiled kernel (max_batch mode — the reference's growable AG-buffer
    serving loop, sp_flash_decode_layer.py:111-132; VERDICT r4 #7). The
    padded path must also match the exact per-batch computation."""
    from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode
    n = ctx.num_ranks
    MB, Hq, Hkv, D, s_local = 4, 4, 2, 128, 128
    S = n * s_local
    attn = SpGQAFlashDecodeAttention(ctx, num_q_heads=Hq, num_kv_heads=Hkv,
                                     head_dim=D, axis="x", max_batch=MB)
    kc = jax.random.normal(jax.random.key(1), (MB, Hkv, S, D), jnp.float32)
    vc = jax.random.normal(jax.random.key(2), (MB, Hkv, S, D), jnp.float32)
    kcs = ctx.shard(kc, P(None, None, "x"))
    vcs = ctx.shard(vc, P(None, None, "x"))
    for B in (1, 2, 4):
        q = jax.random.normal(jax.random.key(10 + B), (B, Hq, D),
                              jnp.float32)
        lens = jnp.full((B,), S, jnp.int32)
        out = attn(q, kcs, vcs, lens)
        assert out.shape == (B, Hq, D)
        want = sp_gqa_flash_decode(
            ctx, jnp.concatenate([q, jnp.zeros((MB - B, Hq, D))]), kcs, vcs,
            jnp.concatenate([lens, jnp.ones((MB - B,), jnp.int32)]),
            axis="x")[:B]
        assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5,
                        rtol=1e-5)
    # the whole sweep compiled the kernel exactly once
    assert attn._fwd._cache_size() == 1


def test_ep_layer_2d_roundtrip():
    """EPAll2AllLayer over a (major, minor) axis tuple routes through the
    hierarchical dispatch_2d/combine_2d (reference layer's inter-node path,
    ep_a2a_layer.py:187-240)."""
    ctx2 = initialize_distributed(axis_names=("a", "b"), mesh_shape=(2, 3))
    n = 6
    T, H, k, E = 8, 128, 2, n * 2
    layer = EPAll2AllLayer.create(ctx2, max_tokens=T, hidden=H, topk=k,
                                  num_experts=E, axis=("a", "b"),
                                  dtype=jnp.float32)
    assert layer.is_2d
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n * T, k), 0, E)
    w = jnp.full((n * T, k), 1.0 / k)
    spec = P(("a", "b"))
    ts, is_, ws = (ctx2.shard(t, spec) for t in (tokens, ids, w))
    recv_tok, recv_ids, layouts = layer.dispatch(ts, is_)
    out = layer.combine(recv_tok, layouts, ws)  # identity experts
    assert_allclose(np.asarray(out), np.asarray(tokens), atol=1e-4,
                    rtol=1e-4)
    # preprocess exposes the tier-1 (major-hop) plan — it must agree with
    # what dispatch_2d actually used (layouts[0], flat [T*k] per shard)
    a_dst, slot1, ok1 = (np.asarray(v) for v in layer.preprocess(is_))
    la, ls, lo = (np.asarray(v) for v in layouts[0])
    np.testing.assert_array_equal(a_dst.reshape(la.shape), la)
    np.testing.assert_array_equal(slot1.reshape(ls.shape), ls)
    np.testing.assert_array_equal(ok1.reshape(lo.shape), lo)
