from triton_dist_tpu.tools.autotuner import contextual_autotune  # noqa: F401
from triton_dist_tpu.tools.aot import (  # noqa: F401
    aot_compile, aot_compile_spaces, export_serialized, load_serialized)
