"""Symbolic protocol-event model shared by capture and checker.

One :class:`Event` is appended per shmem-primitive call while
``capture`` replays a kernel's Python body for one rank. Identities are
strings built deterministically from the per-rank call sequence, so the
same program point gets the same buffer/semaphore id on every rank — the
checker exploits this symmetry to match producer and consumer sites
across ranks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SemId:
    """One semaphore *cell*: the allocation (scratch slot or barrier
    collective) plus the concrete element coordinates within it."""

    alloc: str                  # e.g. "call0:ag_push/scratch1", "barrier:123"
    cell: Tuple[int, ...]       # fully-resolved element coords, () for scalar
    kind: str = "regular"       # "regular" | "dma" | "barrier"

    def __str__(self) -> str:
        c = "" if not self.cell else "[" + ",".join(map(str, self.cell)) + "]"
        return f"{self.alloc}{c}"


@dataclasses.dataclass(frozen=True)
class Region:
    """A rectangular byte region: buffer id + per-dimension half-open
    element intervals over the *base* buffer shape (squeezed dims kept as
    size-1 intervals so overlap tests stay dimension-aligned)."""

    buffer: str
    intervals: Tuple[Tuple[int, int], ...]

    def overlaps(self, other: "Region") -> bool:
        if self.buffer != other.buffer:
            return False
        if len(self.intervals) != len(other.intervals):
            # different views of the same buffer should never disagree on
            # rank; treat conservatively as overlapping
            return True
        for (a0, a1), (b0, b1) in zip(self.intervals, other.intervals):
            if a1 <= b0 or b1 <= a0:
                return False
        return True

    def covers(self, other: "Region") -> bool:
        if self.buffer != other.buffer:
            return False
        if len(self.intervals) != len(other.intervals):
            return False
        return all(a0 <= b0 and b1 <= a1 for (a0, a1), (b0, b1)
                   in zip(self.intervals, other.intervals))

    def __str__(self) -> str:
        dims = ",".join(f"{a}:{b}" for a, b in self.intervals)
        return f"{self.buffer}[{dims}]"


# Event kinds:
#   put        one-sided copy; dst_rank may equal rank (local async copy).
#              Credits ``sem`` (the DMA recv semaphore at dst_rank) with
#              ``value`` = nbytes when delivered; ``send_sem`` at the source
#              tracks local completion (rdma_id joins it to wait_send).
#   wait_recv  consume ``value`` = nbytes from DMA ``sem``; ``dst`` is the
#              region whose delivery the protocol believes this covers.
#   signal     credit ``value`` = inc onto ``sem`` at ``dst_rank``
#              (None → own rank).
#   wait       consume ``value`` from REGULAR/barrier ``sem`` (decrements).
#   wait_send  local send-completion wait for put ``rdma_id``.
#   read       kernel reads ``src`` region (compute input).
#   write      kernel writes ``dst`` region (compute output).
#   sem_read   non-destructive semaphore poll.
#   fence      ordering no-op, kept for completeness.
@dataclasses.dataclass
class Event:
    rank: int
    seq: int
    kind: str
    sem: Optional[SemId] = None
    send_sem: Optional[SemId] = None
    dst_rank: Optional[int] = None
    value: int = 0
    src: Optional[Region] = None
    dst: Optional[Region] = None
    rdma_id: Optional[int] = None
    grid: Optional[Tuple[int, ...]] = None
    site: str = ""              # call-site label for findings

    def describe(self) -> str:
        bits = [f"r{self.rank}#{self.seq} {self.kind}"]
        if self.sem is not None:
            bits.append(f"sem={self.sem}")
        if self.dst_rank is not None:
            bits.append(f"to=r{self.dst_rank}")
        if self.value:
            bits.append(f"v={self.value}")
        if self.src is not None:
            bits.append(f"src={self.src}")
        if self.dst is not None:
            bits.append(f"dst={self.dst}")
        if self.grid:
            bits.append(f"grid={self.grid}")
        return " ".join(bits)
