"""Prefix cache (ISSUE 13): ref-counted copy-on-write KV pages, the
token-keyed radix index, LRU eviction, and the bit-identity contract.

THE contract: serving with ``prefix_cache=True`` is a pure OPTIMIZATION —
every request's tokens are bit-identical to the cache-off run of the same
trace, on the colocated engine and on the sharded engine at n∈{1,2,4},
including traces that force LRU eviction, growth-driven preemption, and
mid-prefill preemption of a request that adopted cached pages. Greedy
decode makes KV a pure function of the token prefix, so adopting a
cached page IS recomputing it; everything here checks that the ledger
mechanics (refcounts, COW, retention, eviction) never violate that.

Ledger invariants under test (kv_pool.py):
- a page's refcount never goes negative and a shared page is never freed
  or migrated while referenced;
- COW refuses sole-owned pages (in-place write is correct there) and
  never lets a writer touch a refcount>1 page;
- cached (refcount-0, index-retained) pages live on the LRU list, never
  the free list, and ``check()``/``digest()`` audit all of it.

Every test runs under the per-test SIGALRM watchdog (test_chaos.py
pattern).
"""

import dataclasses
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.serving import (KVPagePool, PageLedgerError,
                                     PrefixCache, ReplicaPrefixIndex,
                                     ServingEngine, ShardedServingEngine,
                                     serving_mesh)
from triton_dist_tpu.serving.scheduler import RequestState

pytestmark = [pytest.mark.prefix, pytest.mark.serving]

WATCHDOG_S = 240          # per-test wall cap — generous, CPU CI is slow
N_REQUESTS = 50
MAX_STEPS = 100_000       # engine's own stall watchdog trips far earlier
WIRE = jnp.float8_e4m3fn  # pinned (test_sharded_serving caveat)


@pytest.fixture(autouse=True)
def prefix_watchdog():
    def boom(signum, frame):
        raise TimeoutError(
            f"prefix watchdog: test exceeded {WATCHDOG_S}s wall — "
            "an engine (or a mesh collective) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# ------------------------------------------------------ pool refcount units
def test_pool_acquire_shared_page_never_freed_while_referenced():
    pool = KVPagePool(8, 8, reserved=1)
    pages = pool.alloc("a", 2)
    pool.acquire("b", pages)
    assert [pool.refcount(p) for p in pages] == [2, 2]
    pool.check()
    pool.free_seq("a")                    # b still reads these pages
    assert [pool.refcount(p) for p in pages] == [1, 1]
    assert all(p not in pool._free for p in pages)
    pool.check()
    pool.free_seq("b")                    # last reference → free list
    assert [pool.refcount(p) for p in pages] == [0, 0]
    assert pool.free_pages == 7
    pool.check()


def test_pool_acquire_refuses_free_and_duplicate_pages():
    pool = KVPagePool(8, 8, reserved=1)
    pages = pool.alloc("a", 1)
    with pytest.raises(PageLedgerError, match="no live KV"):
        pool.acquire("b", [pool._free[-1]])
    with pytest.raises(PageLedgerError, match="already holds"):
        pool.acquire("a", pages)
    # refused acquires mutated nothing
    assert pool.refcount(pages[0]) == 1
    pool.check()


def test_pool_release_underflow_is_loud():
    pool = KVPagePool(8, 8, reserved=1)
    (p,) = pool.alloc("a", 1)
    pool.free_seq("a")
    with pytest.raises(PageLedgerError, match="underflow"):
        pool._release_page("a", p)


def test_pool_cacheable_parks_on_lru_not_free_list():
    pool = KVPagePool(10, 8, reserved=1)
    pa = pool.alloc("a", 2)
    pb = pool.alloc("b", 1)
    for p in pa + pb:
        pool.mark_cacheable(p)
    pool.free_seq("a")
    pool.free_seq("b")
    # release order IS the LRU order (oldest first), free list untouched
    assert pool.lru_cached() == pa + pb
    assert pool.cached_pages == 3
    assert all(p not in pool._free for p in pa + pb)
    pool.check()
    # adoption revives a cached page off the LRU list
    pool.acquire("c", [pa[0]])
    assert pool.refcount(pa[0]) == 1 and pool.lru_cached() == pa[1:] + pb
    # uncache reclaims a cached page NOW, a referenced one only later
    assert pool.uncache(pa[1]) is True
    assert pool.uncache(pa[0]) is False   # still referenced by c
    pool.free_seq("c")
    assert pa[0] in pool._free            # retention mark was dropped
    pool.check()


def test_pool_mark_cacheable_refuses_free_pages():
    pool = KVPagePool(8, 8, reserved=1)
    with pytest.raises(PageLedgerError, match="free page"):
        pool.mark_cacheable(pool._free[-1])


def test_pool_cow_only_for_shared_pages():
    pool = KVPagePool(8, 8, reserved=1)
    pages = pool.alloc("a", 2)
    with pytest.raises(PageLedgerError, match="copy-on-write is only"):
        pool.cow_page("a", 0)             # sole-owned: write in place
    pool.acquire("b", pages)
    old, new = pool.cow_page("b", 1)
    assert old == pages[1] and new != old
    assert pool.refcount(old) == 1 and pool.refcount(new) == 1
    assert pool.pages_of("b") == [pages[0], new]
    assert pool.pages_of("a") == pages    # a's view untouched
    pool.check()


def test_pool_cow_dry_pool_returns_none():
    pool = KVPagePool(3, 8, reserved=1)   # 2 usable pages
    pages = pool.alloc("a", 2)
    pool.acquire("b", pages)
    assert pool.cow_page("b", 0) is None  # caller evicts/preempts
    assert pool.refcount(pages[0]) == 2   # nothing mutated
    pool.check()


def test_pool_migration_refuses_shared_pages():
    pool = KVPagePool(8, 8, reserved=1)
    pages = pool.alloc("a", 2)
    pool.check_migratable("a", pages)     # sole-owned: fine
    pool.acquire("b", pages)
    with pytest.raises(PageLedgerError, match="sole ownership"):
        pool.check_migratable("a", pages)


def test_pool_digest_and_snapshot_cover_cache_state():
    pool = KVPagePool(8, 8, reserved=1)
    pages = pool.alloc("a", 2)
    d0 = pool.digest()
    pool.mark_cacheable(pages[0])
    d1 = pool.digest()
    assert d1 != d0                       # retention mark folds in
    pool.free_seq("a")
    d2 = pool.digest()
    assert d2 != d1                       # cached LRU list folds in
    back = KVPagePool.from_snapshot(pool.snapshot(), 8, 8, 1)
    assert back.digest() == d2
    assert back.lru_cached() == pool.lru_cached()
    assert back._cacheable == pool._cacheable
    back.check()


# ---------------------------------------------------------- radix index units
def test_cache_match_insert_full_page_runs_only():
    pool = KVPagePool(10, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    prompt = list(range(1, 11))           # 10 tokens = 2 full runs + 2
    pages = pool.alloc("a", 3)
    assert cache.insert(prompt, pages[:2]) == 2
    assert cache.match(prompt) == pages[:2]
    assert cache.match(prompt[:7]) == pages[:1]   # 1 full run of 4
    assert cache.match(prompt[:3]) == []          # no full run
    assert cache.match([9] + prompt[1:]) == []    # first run differs
    assert cache.indexed_pages == 2


def test_cache_insert_first_writer_wins():
    pool = KVPagePool(10, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    prompt = list(range(1, 9))
    pa = pool.alloc("a", 2)
    pb = pool.alloc("b", 2)
    assert cache.insert(prompt, pa) == 2
    assert cache.insert(prompt, pb) == 0  # duplicate compute: not indexed
    assert cache.match(prompt) == pa
    # b's pages free normally at finish — never retained
    pool.free_seq("b")
    assert pool.cached_pages == 0 and pool.free_pages == 7


def test_cache_insert_refusals():
    pool = KVPagePool(10, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    pages = pool.alloc("a", 3)
    with pytest.raises(PageLedgerError, match="full-page runs"):
        cache.insert([1, 2, 3, 4, 5], pages[:2])  # 5 tokens = 1 run
    cache.insert([1, 2, 3, 4], pages[:1])
    with pytest.raises(PageLedgerError, match="already indexed"):
        cache.insert([9, 9, 9, 9], pages[:1])     # same page, other run


def test_cache_evict_lru_order_and_subtrees():
    pool = KVPagePool(12, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    # chain A: two runs deep; chain B: one run — released A-then-B, so
    # A's root is the LRU victim and its CHILD must leave with it
    pa = pool.alloc("a", 2)
    pb = pool.alloc("b", 1)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8], pa)
    cache.insert([9, 10, 11, 12], pb)
    pool.free_seq("a")
    pool.free_seq("b")
    assert cache.evictable == 3
    assert cache.evict(1) == 2            # victim + its child run
    assert cache.indexed_pages == 1
    assert cache.match([1, 2, 3, 4, 5, 6, 7, 8]) == []
    assert cache.match([9, 10, 11, 12]) == pb
    pool.check()
    # asking for more than exists reclaims what's there and reports it
    assert cache.evict(10) == 1
    assert cache.evictable == 0 and pool.free_pages == 11
    pool.check()


def test_cache_evict_referenced_subtree_page_frees_on_release():
    pool = KVPagePool(12, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    pa = pool.alloc("a", 2)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8], pa)
    pool.acquire("r", pa)                 # a reader adopted both pages
    pool.free_seq("a")
    assert cache.evictable == 0           # refcount 1: nothing cached
    assert cache.evict(1) == 0
    pool.free_seq("r")
    # retention marks survived the failed evict → pages park cached
    assert pool.cached_pages == 2
    assert cache.evict(1) == 2
    assert pool.free_pages == 11
    pool.check()


def test_cache_clear_reclaims_everything():
    pool = KVPagePool(12, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    pa = pool.alloc("a", 2)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8], pa)
    pool.free_seq("a")
    assert cache.clear() == 2
    assert cache.indexed_pages == 0 and pool.free_pages == 11
    pool.check()


def test_cache_snapshot_digest_tamper():
    from triton_dist_tpu.serving import checkpoint as ckpt_mod
    from triton_dist_tpu.serving.checkpoint import CheckpointIntegrityError

    pool = KVPagePool(12, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    pa = pool.alloc("a", 2)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8], pa)
    snap, dig = cache.snapshot(), cache.digest()
    ckpt_mod.audit_prefix_snapshot(snap, dig)     # clean
    snap[0][2] = 99                               # tamper one page id
    with pytest.raises(CheckpointIntegrityError):
        ckpt_mod.audit_prefix_snapshot(snap, dig)


def test_replica_prefix_index_deepest_hit():
    ix = ReplicaPrefixIndex(4)
    ix.insert([1, 2, 3, 4, 5, 6, 7, 8], 0)
    ix.insert([1, 2, 3, 4, 9, 9, 9, 9], 2)        # shares run 0 — first
    depth, owner = ix.match([1, 2, 3, 4, 5, 6, 7, 8, 11])
    assert (depth, owner) == (2, 0)
    depth, owner = ix.match([1, 2, 3, 4, 9, 9, 9, 9])
    assert (depth, owner) == (2, 2)               # deepest hit wins
    assert ix.match([1, 2, 3, 4, 0, 0])[0] == 1   # partial: run-0 owner
    assert ix.match([5, 5, 5, 5]) == (0, None)
    ix.insert([1, 2, 3, 4], 3)                    # first-writer-wins
    assert ix.match([1, 2, 3, 4]) == (1, 0)


# ----------------------------------------------------- colocated bit-identity
@pytest.fixture(scope="module")
def tiny_model():
    cfg = dataclasses.replace(LlamaConfig.tiny(n_layers=2),
                              dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _template_trace(vocab, n=N_REQUESTS, page_size=8, templates=3):
    """The acceptance trace: Zipf-ish template reuse so the cache actually
    fires — page-aligned shared prefixes + tiny unique tails, staggered
    arrivals, against a pool too small for the working set (forces both
    preemption and LRU eviction)."""
    rng = np.random.RandomState(77)
    tpls = [rng.randint(1, vocab, size=2 * page_size).tolist()
            for _ in range(templates)]
    out = []
    for i in range(n):
        t = int(rng.randint(0, templates))
        tail = rng.randint(1, vocab,
                           size=int(rng.randint(1, 5))).tolist()
        out.append((i // 2, tpls[t] + tail, int(rng.randint(4, 9))))
    return out


def _colocated(tiny_model, **kw):
    cfg, params = tiny_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)          # tight: forces preempt + evict
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    return ServingEngine(params, cfg, **kw)


@pytest.fixture(scope="module")
def colocated_golden(tiny_model):
    cfg, _ = tiny_model
    eng = _colocated(tiny_model)
    res = eng.run(max_steps=MAX_STEPS,
                  arrivals=_template_trace(cfg.vocab_size))
    assert eng.metrics.counters["preemptions"] >= 1
    return res, eng.compile_stats


@pytest.mark.quick
def test_colocated_trace_bit_identical_cache_on(tiny_model,
                                                colocated_golden):
    """The acceptance trace, cache ON: 50 template-sharing requests with
    forced preemption AND forced LRU eviction replay the cache-off run
    bit-for-bit, with zero extra compiled programs."""
    cfg, _ = tiny_model
    gold, gold_compiles = colocated_golden
    eng = _colocated(tiny_model, prefix_cache=True)
    res = eng.run(max_steps=MAX_STEPS,
                  arrivals=_template_trace(cfg.vocab_size))
    assert res == gold, "prefix cache changed tokens"
    c = eng.metrics.counters
    assert c["prefix_hits"] >= 1, "trace never hit the cache"
    assert c["prefix_evictions"] >= 1, "pool sizing no longer forces " \
                                       "eviction"
    assert c["preemptions"] >= 1
    assert eng.compile_stats == gold_compiles, \
        "the cache compiled extra programs"
    eng.alloc.check()
    # conservation: every indexed page is referenced or cached, never free
    for p in eng.prefix_cache._node_of:
        assert eng.alloc.refcount(p) > 0 or p in eng.alloc._cached


def test_colocated_whole_prompt_hit_cows_last_page(tiny_model):
    """An EXACT repeat prompt is a whole-prompt hit: the engine resumes at
    sp-1 (the final chunk recomputes only the on-device argmax), COWs the
    final adopted page when shared, and the tokens still match a cold
    engine's."""
    cfg, _ = tiny_model
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, cfg.vocab_size, size=16).tolist()  # 2 pages
    cold = _colocated(tiny_model, num_pages=16, pages_per_seq=4)
    cold.submit(prompt, 4)
    gold = cold.run(max_steps=MAX_STEPS)
    eng = _colocated(tiny_model, num_pages=16, pages_per_seq=4,
                     prefix_cache=True)
    r0 = eng.submit(prompt, 4)
    first = eng.run(max_steps=MAX_STEPS)
    r1 = eng.submit(prompt, 4)            # identical prompt → whole hit
    second = eng.run(max_steps=MAX_STEPS)
    assert first[r0] == second[r1] == gold[next(iter(gold))]
    c = eng.metrics.counters
    assert c["prefix_hits"] == 1 and c["prefix_misses"] == 1
    # prompt is 16 tokens: the whole-prompt hit resumes at sp-1 = 15
    assert c["prefix_hit_tokens"] == 15
    # the adopted final page was cached (refcount 0) at adoption, so the
    # sole-owner fast path wrote in place — no COW needed
    assert c["cow_copies"] == 0
    eng.alloc.check()


def test_colocated_concurrent_whole_prompt_hits_cow(tiny_model):
    """TWO simultaneous whole-prompt hits on the same cached prefix: the
    second adopter shares the final page at refcount 2, so its sp-1
    rewrite MUST copy-on-write — and both requests still match the cold
    tokens."""
    cfg, _ = tiny_model
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, cfg.vocab_size, size=16).tolist()
    cold = _colocated(tiny_model, num_pages=16, pages_per_seq=4)
    cold.submit(prompt, 4)
    gold = cold.run(max_steps=MAX_STEPS)
    gold_toks = gold[next(iter(gold))]
    eng = _colocated(tiny_model, num_pages=16, pages_per_seq=4,
                     prefix_cache=True)
    eng.submit(prompt, 4)
    eng.run(max_steps=MAX_STEPS)          # seeds the index
    ra, rb = eng.submit(prompt, 4), eng.submit(prompt, 4)
    res = eng.run(max_steps=MAX_STEPS)
    assert res[ra] == gold_toks and res[rb] == gold_toks
    assert eng.metrics.counters["cow_copies"] >= 1, \
        "second adopter should have COWed the shared final page"
    eng.alloc.check()


def test_colocated_mid_prefill_preemption_of_cache_hit(tiny_model):
    """A request that ADOPTED cached pages is preempted mid-prefill: the
    free_tail path must keep its filled prefix (including the adopted
    pages), requeue it at its chunk cursor, and the resumed request's
    tokens must still match a cold single-request run."""
    cfg, _ = tiny_model
    rng = np.random.RandomState(5)
    tpl = rng.randint(1, cfg.vocab_size, size=16).tolist()
    long_prompt = tpl + rng.randint(1, cfg.vocab_size, size=14).tolist()
    cold = _colocated(tiny_model, num_pages=16, pages_per_seq=8)
    cold.submit(long_prompt, 4)
    gold = cold.run(max_steps=MAX_STEPS)
    gold_toks = gold[next(iter(gold))]

    eng = _colocated(tiny_model, num_pages=16, pages_per_seq=8,
                     prefix_cache=True)
    eng.submit(tpl, 2)
    eng.run(max_steps=MAX_STEPS)          # seeds 2 pages of the template
    rid = eng.submit(long_prompt, 4)
    # one step: admission adopts the 2 template pages (cursor jumps to
    # 16) and dispatches one chunk → cursor 24 of 30
    eng.step()
    slot, req = next((i, r) for i, r in enumerate(eng.sched.slots)
                     if r is not None and r.rid == rid)
    assert req.state is RequestState.PREFILLING
    assert req.cache_hit_tokens == 16 and req.prefill_cursor == 24
    eng._preempt(slot)                    # forced mid-prefill preemption
    eng.alloc.check()
    # filled prefix (3 pages for cursor 24) survived the eviction
    assert len(eng.alloc.pages_of(rid)) == 3
    res = eng.run(max_steps=MAX_STEPS)
    assert res[rid] == gold_toks
    assert req.preemptions == 1
    eng.alloc.check()


def test_colocated_capture_restore_carries_prefix_audit(tiny_model):
    """Checkpoint state includes the prefix-index snapshot + digest; the
    restore contract starts with an EMPTY cache (KV is re-earned by
    re-prefill) and the audit rejects a tampered snapshot."""
    from triton_dist_tpu.serving import ControlJournal
    from triton_dist_tpu.serving.checkpoint import CheckpointIntegrityError

    cfg, _ = tiny_model
    journal = ControlJournal()
    eng = _colocated(tiny_model, prefix_cache=True, journal=journal,
                     checkpoint_every=8)
    eng.run(max_steps=MAX_STEPS,
            arrivals=_template_trace(cfg.vocab_size, n=12))
    state = eng._capture_state()
    assert state["prefix_digest"] == \
        PrefixCache.snapshot_digest(state["prefix_index"])
    eng._restore_state(state)
    assert eng.prefix_cache.indexed_pages == 0    # restored EMPTY
    assert eng.alloc.free_pages == eng.alloc.num_pages - 1
    state["prefix_index"][0][2] ^= 1
    with pytest.raises(CheckpointIntegrityError):
        eng._restore_state(state)


# ------------------------------------------------------- sharded bit-identity
@pytest.fixture(scope="module")
def moe_model():
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _sharded(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)          # tight: forces preempt + evict
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _sharded_serve(moe_model, tp, sp, ep, **kw):
    cfg, _ = moe_model
    eng = _sharded(moe_model, tp, sp, ep, **kw)
    tokens = eng.run(max_steps=MAX_STEPS,
                     arrivals=_template_trace(cfg.base.vocab_size))
    return tokens, dict(eng.metrics.counters), eng.compile_stats


@pytest.fixture(scope="module")
def sharded_golden(moe_model):
    """Cache-OFF n=1 golden for the sharded acceptance trace."""
    tokens, counters, compiles = _sharded_serve(moe_model, 1, 1, 1)
    assert counters["preemptions"] >= 1
    return tokens, compiles


def _assert_sharded_cache_run(moe_model, tp, sp, ep, golden, **kw):
    gold, gold_compiles = golden
    tokens, counters, compiles = _sharded_serve(
        moe_model, tp, sp, ep, prefix_cache=True, **kw)
    assert tokens == gold, \
        f"cache-on {tp}x{sp}x{ep} diverged from the cache-off golden"
    assert counters["prefix_hits"] >= 1
    assert counters["prefix_evictions"] >= 1
    assert compiles == gold_compiles


@pytest.mark.quick
def test_sharded_cache_bit_identical_n1(moe_model, sharded_golden):
    _assert_sharded_cache_run(moe_model, 1, 1, 1, sharded_golden)


def test_sharded_cache_bit_identical_n2(moe_model, sharded_golden):
    _assert_sharded_cache_run(moe_model, 1, 1, 2, sharded_golden)


def test_sharded_cache_bit_identical_n4(moe_model, sharded_golden):
    _assert_sharded_cache_run(moe_model, 1, 2, 2, sharded_golden,
                              decode_horizon=4)


# --------------------------------------------------------------- sigcheck
def test_sigcheck_lint_clean_with_cache_on(tiny_model, monkeypatch):
    """TDT_SIGCHECK=1 engine construction with the cache on: adoption and
    COW are host ledger ops plus eager device copies, so the linted
    program set is unchanged and the determinism lint stays clean."""
    monkeypatch.setenv("TDT_SIGCHECK", "1")
    eng = _colocated(tiny_model, prefix_cache=True)
    assert eng.prefix_cache is not None
