from triton_dist_tpu.shmem.context import (  # noqa: F401
    ShmemContext,
    initialize_distributed,
    get_default_context,
)
from triton_dist_tpu.shmem import device  # noqa: F401
from triton_dist_tpu.shmem.faults import (  # noqa: F401
    FaultPlan,
    InjectedCrash,
    active_plan,
    use_plan,
)
