"""Runnable per-op tutorials (analog of reference tutorials/01-10 and the
per-op test entry scripts, test/nvidia/test_ag_gemm_intra_node.py:44-73).

Each module runs standalone on a real TPU (any device count, including a
single chip) or on a simulated multi-device CPU mesh:

    python -m tutorials.t01_notify_wait --case correctness
    python -m tutorials.t05_ag_gemm --case perf
    python -m tutorials.t02_allgather --sim 4 --case correctness
    python -m tutorials.t03_reduce_scatter --list

``--sim N`` forces an N-device virtual CPU mesh (Pallas interpret mode) —
the single-process cluster simulator the reference lacks (its tutorials
need torchrun + real GPUs, tutorials/README.md:1-16).
"""
