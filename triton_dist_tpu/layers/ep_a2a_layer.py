"""EP All-to-All module layer (analog of reference
layers/nvidia/ep_a2a_layer.py:31-240 — preprocess/dispatch/combine
orchestration over the low-latency A2A kernels)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops import all_to_all as a2a_ops
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class EPAll2AllLayer:
    """Holds the static A2A context (buffer shapes/capacity) — the role the
    reference's layer plays with its preprocess()/dispatch()/combine()
    triple (ep_a2a_layer.py:110-240). The routing *layout* is returned by
    ``dispatch`` and passed to ``combine`` explicitly (it contains traced
    arrays; stashing it on the layer would leak tracers across jit
    boundaries)."""
    a2a: "a2a_ops.EpAllToAllContext | a2a_ops.Ep2dAllToAllContext"

    @classmethod
    def create(cls, ctx: ShmemContext, max_tokens: int, hidden: int,
               topk: int, num_experts: int, capacity: int | None = None,
               axis=None, dtype=jnp.bfloat16, wire_dtype=None,
               quant_edge: str = "fused", dequant_edge: str = "post",
               expert_major: bool = False):
        """``wire_dtype=jnp.float8_e4m3fn`` enables the quantized wire with
        the f32 scale side-channel (the reference's fp8 showcase protocol,
        low_latency_all_to_all.py:60-88).

        ``axis`` may be a 2-tuple ``(major, minor)`` — the layer then runs
        the hierarchical 2-tier dispatch/combine (slow-tier hop + fast-tier
        expert scatter; the reference layer's inter-node path,
        ep_a2a_layer.py:187-240 over ep_a2a.py:35-147), including the
        quantized wire: tokens are quantized once at the edge and the
        scale side-channel rides both tiers.

        ``expert_major=True`` (1d only) lays each (src, dst) capacity block
        out expert-major with a per-expert slot budget — receive blocks
        arrive expert-segmented, so the serving FFN skips its align
        gather/scatter entirely (see ``EpAllToAllContext.expert_major``)."""
        if axis is not None and not isinstance(axis, str):
            axes = tuple(axis)
            assert len(axes) == 2, (
                f"2-tier A2A takes exactly (major, minor) axes, got {axes}")
            assert not expert_major, (
                "expert_major is a 1d-context layout (the tier-2 re-slot "
                "would have to re-group arrivals per expert)")
            return cls(a2a_ops.create_all_to_all_context_2d(
                ctx, max_tokens, hidden, topk, num_experts, axes=axes,
                cap1=capacity, dtype=dtype, wire_dtype=wire_dtype,
                quant_edge=quant_edge, dequant_edge=dequant_edge))
        return cls(a2a_ops.create_all_to_all_context(
            ctx, max_tokens, hidden, topk, num_experts,
            capacity=capacity, axis=axis, dtype=dtype,
            wire_dtype=wire_dtype, quant_edge=quant_edge,
            dequant_edge=dequant_edge, expert_major=expert_major))

    @property
    def is_2d(self) -> bool:
        return isinstance(self.a2a, a2a_ops.Ep2dAllToAllContext)

    def preprocess(self, topk_ids: jax.Array):
        """Routing plan for globally sharded ``topk_ids`` — the same plan
        ``dispatch`` computes internally (≈ layer.preprocess token sort,
        ep_a2a_layer.py:110-130). Slot allocation is per source shard, so
        this must run under shard_map — calling ``route_tokens`` on the
        global array would count slots across ranks jointly and disagree
        with dispatch's capacity-drop decisions.

        On the 2-tier path this is the tier-1 (major-hop) plan; the tier-2
        plan re-slots actual arrivals on the intermediate device, so it is
        inherently dispatch-time data — ``dispatch`` returns it as
        ``layouts[1]``."""
        from jax.sharding import PartitionSpec as P
        ctx = self.a2a.ctx
        if self.is_2d:
            spec = P(self.a2a.axes)
            sm = ctx.shard_map(
                lambda ids: a2a_ops.route_tokens_2d(self.a2a, ids),
                in_specs=spec, out_specs=(spec,) * 3)
            return sm(topk_ids)
        axis = self.a2a.axis
        sm = ctx.shard_map(lambda ids: a2a_ops.route_tokens(self.a2a, ids),
                           in_specs=P(axis),
                           out_specs=(P(axis), P(axis), P(axis)))
        return sm(topk_ids)

    def dispatch(self, tokens: jax.Array, topk_ids: jax.Array):
        """Returns (recv_tokens, recv_ids, layout); thread ``layout`` into
        ``combine``."""
        if self.is_2d:
            return a2a_ops.dispatch_2d(self.a2a, tokens, topk_ids)
        return a2a_ops.dispatch(self.a2a, tokens, topk_ids)

    def combine(self, processed: jax.Array, layout,
                topk_weights: jax.Array) -> jax.Array:
        if self.is_2d:
            return a2a_ops.combine_2d(self.a2a, processed, layout,
                                      topk_weights)
        return a2a_ops.combine(self.a2a, processed, layout, topk_weights)
