"""Cluster serving (ISSUE 12): the composed disagg×sharded engine, the
replica wrapper, and the deterministic router.

THE contract, composed tier: ``DisaggShardedEngine`` — a disaggregated
prefill fleet feeding a ``ShardedServingEngine`` decode fleet on ONE
TP/SP/EP mesh over the unified pool contract — replays a preemption-
heavy trace BIT-IDENTICALLY to the plain sharded engine's 1x1x1 golden
at n∈{2,4}, with the compile guard pinned at one executable per program
(the prefill fleet REUSES the decode engine's chunk executable) and the
decode panel's ``step_prefill_tokens`` identically 0 (fault-free).

THE contract, cluster tier: routing is a pure function of (alive set,
prompt prefix, load) — two identical runs place identically; per-replica
journals are path-namespaced so N replicas in one directory never
cross-replay (the no-bleed test kills and restores BOTH); and a routed,
preempted, killed-and-restored SimEngine workload matches the closed-
form ``expected_tokens`` golden bitwise.

Every test runs under the per-test SIGALRM watchdog (test_chaos.py /
test_sharded_serving.py pattern).
"""

import json
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_dist_tpu.models.llama import LlamaConfig
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.serving import (Cluster, ControlJournal,
                                     DisaggShardedEngine, EngineReplica,
                                     ShardedServingEngine, SimEngine,
                                     expected_tokens, serving_mesh)
from triton_dist_tpu.shmem.faults import FaultPlan, InjectedCrash

pytestmark = [pytest.mark.cluster, pytest.mark.serving]

WATCHDOG_S = 240
N_REQUESTS = 16
MAX_STEPS = 100_000
WIRE = jnp.float8_e4m3fn  # pinned — "auto" resolves per rank count


@pytest.fixture(autouse=True)
def cluster_watchdog():
    def boom(signum, frame):
        raise TimeoutError(
            f"cluster watchdog: test exceeded {WATCHDOG_S}s wall — "
            "an engine (or a mesh collective) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def moe_model():
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _trace(n=N_REQUESTS):
    rng = np.random.RandomState(77)
    out = []
    for i in range(n):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        prompt = rng.randint(1, 128, size=plen).tolist()
        out.append((i // 2, prompt, mnt))
    return out


ENGINE_KW = dict(num_slots=4, page_size=8, num_pages=9, pages_per_seq=4,
                 prefill_chunk=8, wire_dtype=WIRE)


def _composed(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    merged = {**ENGINE_KW, **kw}
    return DisaggShardedEngine(params, cfg, serving_mesh(tp, sp, ep),
                               **merged)


@pytest.fixture(scope="module")
def golden(moe_model):
    """The n=1 golden: the plain SHARDED engine at mesh 1x1x1 — the
    composition must not change a single token of it."""
    cfg, params = moe_model
    eng = ShardedServingEngine(params, cfg, serving_mesh(1, 1, 1),
                               **ENGINE_KW)
    return eng.run(max_steps=MAX_STEPS, arrivals=_trace())


# ---------------------------------------------------------------------------
# the composed engine: disagg prefill × sharded decode, one mesh
# ---------------------------------------------------------------------------

@pytest.mark.mesh
@pytest.mark.parametrize("mesh", [(1, 2, 1), (1, 2, 2)],
                         ids=["1x2x1", "1x2x2"])
def test_composed_bit_identical_to_sharded_golden(moe_model, golden, mesh):
    """ISSUE 12 acceptance: the disagg demo with its decode role under
    shard_map on a TP/SP(/EP) mesh, per-request trace bit-identical to
    the n=1 golden at n∈{2,4} — plus the compile guard (ONE chunk
    executable SHARED by both fleets, one decode, one migration copy)
    and the decode-panel prefill-isolation invariant."""
    eng = _composed(moe_model, *mesh)
    out = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    assert set(out) == set(golden)
    for rid in golden:
        assert out[rid] == golden[rid], (
            f"rid {rid} diverged on composed mesh {eng.mesh_desc}: "
            f"{out[rid]} != {golden[rid]}")
    assert eng.compile_stats == {"prefill_chunk_compiles": 1,
                                 "decode_compiles": 1,
                                 "migrate_compiles": 1}
    # every request went through the full remote pipeline...
    c, d = eng.metrics.counters, eng.metrics_decode.counters
    assert c["handoffs"] == N_REQUESTS and d["handoffs"] == N_REQUESTS
    assert c["pages_migrated"] > 0
    # ...and the decode fleet never prefilled a token (fault-free run)
    assert eng.metrics_decode.hist["step_prefill_tokens"].max in (0, None)
    assert d["degradations"] == 0 and d["failed_requests"] == 0


@pytest.mark.mesh
def test_composed_retry_rung_recovers_bit_identical(moe_model, golden):
    """Light seeded signal drops: the deadline/retry ladder re-sends the
    lost chunks and every trace still matches the golden bitwise."""
    eng = _composed(moe_model, 1, 2, 1,
                    fault_plan=FaultPlan(seed=11, p_drop=0.25),
                    signal_deadline_steps=2, max_retries=4)
    out = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    d = eng.metrics_decode.counters
    assert d["retries"] > 0, "drop plan should have forced retries"
    assert d["failed_requests"] == 0
    assert out == {rid: golden[rid] for rid in out} and len(out) == len(golden)


@pytest.mark.mesh
def test_composed_degrade_rung_local_reprefill_bit_identical(moe_model,
                                                            golden):
    """Total signal loss on targeted rids: retries run dry, the degrade
    rung requeues the request into the DECODE fleet's own chunked
    admission (it keeps its page reservation), and the locally
    re-prefilled trace is still bit-identical — determinism makes the
    transport loss invisible in token space."""
    eng = _composed(moe_model, 1, 2, 1,
                    fault_plan=FaultPlan(seed=19, p_drop=1.0, rids=(1, 3)),
                    signal_deadline_steps=2, max_retries=1)
    out = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    d = eng.metrics_decode.counters
    assert d["degradations"] >= 1
    assert d["failed_requests"] == 0
    assert set(out) == set(golden)
    for rid in golden:
        assert out[rid] == golden[rid]
    # degraded requests DID re-prefill on the decode fleet
    assert eng.metrics_decode.counters["prefill_chunks"] > 0


@pytest.mark.mesh
@pytest.mark.recovery
def test_composed_crash_recover_bit_identical(moe_model, golden, tmp_path):
    """Engine-tier crash mid-run: a FRESH composed engine restores from
    the journal (full-journal replay — restart-from-prompt through the
    whole remote pipeline) and finishes the trace bit-identically."""
    cfg, params = moe_model
    jpath = str(tmp_path / "composed.jsonl")
    journal = ControlJournal(path=jpath)
    eng = _composed(moe_model, 1, 2, 1, journal=journal,
                    checkpoint_every=8,
                    fault_plan=FaultPlan(seed=0, crash_at=(12,)))
    arrivals = _trace()
    with pytest.raises(InjectedCrash):
        eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    done = sum(1 for e in journal.entries
               if e["kind"] in ("submit", "reject"))
    assert 0 < done
    j2 = ControlJournal.load(jpath)
    eng2 = _composed(moe_model, 1, 2, 1, journal=j2,
                     fault_plan=FaultPlan(seed=0, crash_at=(12,)))
    out = eng2.run(max_steps=MAX_STEPS, arrivals=arrivals[done:],
                   recover=True)
    assert eng2.metrics.counters["restores"] == 1
    assert set(out) == set(golden)
    for rid in golden:
        assert out[rid] == golden[rid]


# ---------------------------------------------------------------------------
# replica wrapper: path-namespaced journals, kill/restore
# ---------------------------------------------------------------------------

def test_replica_journals_do_not_bleed(tmp_path):
    """Two replicas, ONE directory: each journal is its own
    journal-r{i}.jsonl; killing and restoring BOTH replays each strictly
    from its own file — no request crosses over."""
    def factory(journal):
        return SimEngine(num_slots=2, page_size=8, num_pages=17,
                         pages_per_seq=4, journal=journal)

    reps = [EngineReplica(i, factory, str(tmp_path)) for i in range(2)]
    assert reps[0].journal_path != reps[1].journal_path
    prompts = {0: [], 1: []}
    for i in range(10):
        ri = i % 2
        prompt = [100 * (ri + 1) + i] * 4     # replica-tagged prompts
        reps[ri].submit(prompt, 3)
        prompts[ri].append(tuple(prompt))
    for _ in range(4):                         # some finish, some queued
        for r in reps:
            r.step()
    for r in reps:
        r.kill()
    assert reps[0].engine is None
    for r in reps:
        r.restore()
    # drain and check every request landed on the replica it was
    # submitted to — and ONLY there
    for _ in range(200):
        if not any(r.step() for r in reps):
            break
    for ri, r in enumerate(reps):
        got = {tuple(q.prompt) for q in r.engine._finished}
        assert got == set(prompts[ri]), (
            f"replica {ri} finished foreign requests: journal bleed")
        for q in r.engine._finished:
            assert q.generated == expected_tokens(q.prompt,
                                                  q.max_new_tokens)
    # the on-disk journals are disjoint too
    for ri, r in enumerate(reps):
        with open(r.journal_path) as fh:
            for line in fh:
                e = json.loads(line)
                if e.get("kind") == "submit":
                    assert tuple(e["prompt"]) in set(prompts[ri])


def test_replica_restore_without_checkpoint_replays_whole_journal(tmp_path):
    """checkpoint_every=None: kill/restore falls back to full-journal
    replay (the ISSUE 9 ckpt=None rung) and loses nothing."""
    def factory(journal):
        return SimEngine(num_slots=2, page_size=8, num_pages=17,
                         pages_per_seq=4, journal=journal)

    rep = EngineReplica(0, factory, str(tmp_path))
    for i in range(6):
        rep.submit([7 + i] * 5, 4)
    rep.step()
    rep.kill()
    stats = rep.restore()
    assert stats["checkpoint_step"] is None and stats["replayed"] >= 6
    for _ in range(200):
        if not rep.step():
            break
    assert len(rep.engine._finished) == 6
    for q in rep.engine._finished:
        assert q.generated == expected_tokens(q.prompt, q.max_new_tokens)


# ---------------------------------------------------------------------------
# the router: deterministic prefix affinity
# ---------------------------------------------------------------------------

def _mk_cluster(tmp_path=None, replicas=4):
    def factory(journal):
        return SimEngine(num_slots=4, page_size=8, num_pages=33,
                         pages_per_seq=8, journal=journal)

    return Cluster(factory, replicas=replicas,
                   journal_dir=None if tmp_path is None else str(tmp_path))


def test_router_prefix_affinity_and_determinism():
    """Same 8-token prefix => same replica (whatever the tail); the
    whole placement map is a pure function of the submission sequence —
    two identical runs place identically."""
    def run():
        cl = _mk_cluster()
        placements = []
        rng = np.random.RandomState(5)
        prefixes = [rng.randint(1, 1000, size=8).tolist()
                    for _ in range(6)]
        for i in range(60):
            pre = prefixes[i % 6]
            tail = rng.randint(1, 1000, size=3).tolist()
            cl.submit(pre + tail, 2)
            placements.append(cl._placement[i][0])
            cl.step()
        return placements, prefixes

    pl1, prefixes = run()
    pl2, _ = run()
    assert pl1 == pl2, "router must be deterministic"
    # affinity: every request sharing prefix k landed on ONE replica
    by_prefix = {}
    for i, ri in enumerate(pl1):
        by_prefix.setdefault(i % 6, set()).add(ri)
    assert all(len(v) == 1 for v in by_prefix.values()), by_prefix


def test_router_skips_dead_replicas_and_rendezvous_moves_only_their_keys():
    cl = _mk_cluster()
    rng = np.random.RandomState(6)
    prefixes = [rng.randint(1, 1000, size=8).tolist() for _ in range(12)]
    before = {k: cl.route(p).index for k, p in enumerate(prefixes)}
    dead = 2
    cl.replicas[dead].kill()
    after = {k: cl.route(p).index for k, p in enumerate(prefixes)}
    for k in before:
        if before[k] != dead:
            assert after[k] == before[k], (
                "rendezvous hashing must move ONLY the dead replica's "
                "keys")
        else:
            assert after[k] != dead


def test_router_radix_routing_deterministic_with_hits():
    """Cache-aware routing (ISSUE 13): a template workload routes by
    radix-index hit after the first submit of each template, the whole
    placement map is still a pure function of the submission sequence,
    and affinity holds — every request of a template lands on ONE
    replica."""
    def run():
        cl = _mk_cluster()
        rng = np.random.RandomState(12)
        tpls = [rng.randint(1, 1000, size=16).tolist() for _ in range(4)]
        placements = []
        for i in range(40):
            prompt = tpls[i % 4] + rng.randint(1, 1000, size=2).tolist()
            gid = cl.submit(prompt, 2)
            placements.append(cl._placement[gid][0])
            cl.step()
        cl.drain()
        return placements, dict(cl.metrics.counters)

    p1, c1 = run()
    p2, c2 = run()
    assert p1 == p2, "radix routing broke router determinism"
    assert c1["router_radix_hits"] == c2["router_radix_hits"]
    # first submit of each template misses (rendezvous), the rest hit
    assert c1["router_radix_misses"] == 4
    assert c1["router_radix_hits"] == 36
    for k in range(4):
        assert len({p1[i] for i in range(40) if i % 4 == k}) == 1


def test_router_radix_affinity_survives_kill_restore(tmp_path):
    """A routed prompt's prefix sticks to the replica that first served
    it; while that replica is dead the same prefix falls back to
    rendezvous (entries are never dropped), and the affinity returns the
    moment the replica is restored."""
    cl = _mk_cluster(tmp_path)
    rng = np.random.RandomState(11)
    pre = rng.randint(1, 1000, size=8).tolist()
    gid = cl.submit(pre + [7], 2)
    home = cl._placement[gid][0]
    assert cl.metrics.counters["router_radix_misses"] == 1
    for _ in range(3):
        g = cl.submit(pre + rng.randint(1, 1000, size=2).tolist(), 2)
        assert cl._placement[g][0] == home, "radix affinity broken"
    assert cl.metrics.counters["router_radix_hits"] == 3
    cl.drain()
    cl.kill(home)
    assert cl.route(pre + [9]).index != home
    cl.restore(home)
    assert cl.route(pre + [9]).index == home, "affinity did not return"


def test_cluster_kill_restore_traces_bit_identical(tmp_path):
    """The cluster_sim contract in miniature: a routed workload with a
    mid-run kill/restore; every trace matches the closed-form golden."""
    cl = _mk_cluster(tmp_path)
    reqs = {}
    rng = np.random.RandomState(9)
    for i in range(300):
        plen = int(rng.randint(3, 33))
        mnt = int(rng.randint(2, 9))
        prompt = rng.randint(1, 1000, size=plen).tolist()
        gid = cl.submit(prompt, mnt)
        reqs[gid] = (tuple(prompt), mnt)
        if i == 150:
            cl.kill(1)
        if i == 210:
            stats = cl.restore(1)
            assert stats["replayed"] > 0
        if i % 3 == 0:
            cl.step()
    res = cl.drain()
    assert len(res) == 300 and not cl.failed_gids
    for gid, toks in res.items():
        assert toks == expected_tokens(*reqs[gid]), gid
    assert cl.metrics.counters["restores"] == 1


def test_sim_engine_preemption_matches_closed_form():
    """Growth-driven preemption on a deliberately tight pool: evicted
    requests restart from the prompt and STILL match expected_tokens —
    the same restart-determinism contract the device engines pin."""
    eng = SimEngine(num_slots=4, page_size=4, num_pages=7,
                    pages_per_seq=6)
    rng = np.random.RandomState(3)
    arrivals = []
    for i in range(30):
        plen = int(rng.randint(3, 13))
        mnt = int(rng.randint(2, 8))
        arrivals.append((i // 3, rng.randint(1, 500, size=plen).tolist(),
                         mnt))
    out = eng.run(max_steps=100_000, arrivals=arrivals)
    assert len(out) == 30
    assert eng.metrics.counters["preemptions"] > 0, (
        "pool was sized to force eviction")
    for req in eng._finished:
        assert req.generated == expected_tokens(req.prompt,
                                                req.max_new_tokens)
