"""Persisted tuning + ahead-of-time compilation subsystem (ROADMAP item 5,
the reference's L9 tier: ``contextual_autotune`` winners that survive the
process + ``tools/compile_aot.py``-style serving artifacts).

- :mod:`~triton_dist_tpu.aot.registry` — sigcheck-gated, digest-audited
  tuned-config registry keyed on ``(op, mesh_shape, dtype, shape_bucket)``.
- :mod:`~triton_dist_tpu.aot.artifact` — the versioned AOT artifact
  directory holding every serving engine's compiled-program set, loaded
  at replica restart for a zero-fresh-trace cold start.
"""

from triton_dist_tpu.aot.artifact import (ArtifactIntegrityError,
                                          ArtifactMissError, ArtifactSpec,
                                          LoadedProgram, ServingArtifact,
                                          build_artifact,
                                          engine_artifact_key, load_artifact,
                                          make_engine)
from triton_dist_tpu.aot.registry import (GATE_RUNNERS,
                                          RegistryAdmissionError,
                                          RegistryIntegrityError,
                                          TunedConfigRegistry, TunedKey,
                                          get_default_registry,
                                          set_default_registry,
                                          shape_bucket_of)

__all__ = [
    "TunedKey", "TunedConfigRegistry", "RegistryIntegrityError",
    "RegistryAdmissionError", "shape_bucket_of", "GATE_RUNNERS",
    "set_default_registry", "get_default_registry",
    "ArtifactSpec", "ServingArtifact", "LoadedProgram", "ArtifactMissError",
    "ArtifactIntegrityError", "build_artifact", "load_artifact",
    "make_engine", "engine_artifact_key",
]
