#!/usr/bin/env python
"""sigcheck CLI: static signal-protocol verification + determinism lint.

Runs entirely at trace time on CPU — no TPU, no kernel execution. Exit
status is 0 unless ``--fail-on-findings`` is set and any finding (or any
gallery miss) is reported. Output is one JSON document on stdout so CI and
the dryrun gate can parse it.

  python scripts/sigcheck.py --all --fail-on-findings   # the CI gate
  python scripts/sigcheck.py --op gemm_rs               # one op
  python scripts/sigcheck.py --gallery                  # checker self-test
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402

# the migrate_pages determinism lint traces through shard_map on a 2-device
# mesh; everything else is device-count independent
force_virtual_cpu_devices(2)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--all", action="store_true",
                    help="check every registered op + the serving lint")
    ap.add_argument("--op", action="append", default=[],
                    help="check one registered op (repeatable)")
    ap.add_argument("--gallery", action="store_true",
                    help="run the broken-kernel gallery (checker self-test)")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the serving-program determinism lint")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 if any finding is reported")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human summary on stderr")
    args = ap.parse_args()
    if not (args.all or args.op or args.gallery):
        ap.error("pick --all, --op NAME, or --gallery")

    from triton_dist_tpu.analysis import (check_gallery, check_registry,
                                          lint_serving_programs)

    t0 = time.monotonic()
    doc = {"ops": {}, "serving_lint": [], "gallery": {}}
    n_findings = 0
    gallery_misses = []

    if args.all or args.op:
        reports = check_registry(args.op or None)
        if args.op:
            unknown = [o for o in args.op if o not in reports]
            if unknown:
                print(f"unknown op(s): {unknown}", file=sys.stderr)
                return 2
        for name, rep in sorted(reports.items()):
            doc["ops"][name] = rep.to_json()
            n_findings += len(rep.findings)
            if not args.quiet and rep.findings:
                for f in rep.findings:
                    print(f"  {f}", file=sys.stderr)

    if (args.all and not args.no_lint):
        lint = lint_serving_programs()
        doc["serving_lint"] = [f.to_json() for f in lint]
        n_findings += len(lint)
        if not args.quiet:
            for f in lint:
                print(f"  {f}", file=sys.stderr)

    if args.gallery:
        for name, (expected, rep) in check_gallery().items():
            caught = expected in rep.finding_kinds
            doc["gallery"][name] = {"expected": expected, "caught": caught,
                                    "report": rep.to_json()}
            if not caught:
                gallery_misses.append(name)

    doc["elapsed_s"] = round(time.monotonic() - t0, 3)
    doc["n_findings"] = n_findings
    doc["gallery_misses"] = gallery_misses
    json.dump(doc, sys.stdout, indent=1)
    print()

    if not args.quiet:
        checked = sum(1 for r in doc["ops"].values() if not r["skipped"])
        skipped = len(doc["ops"]) - checked
        misses = gallery_misses or "none"
        print(f"sigcheck: {checked} ops checked, {skipped} skipped, "
              f"{n_findings} findings, gallery misses: {misses} "
              f"[{doc['elapsed_s']}s]", file=sys.stderr)

    if args.fail_on_findings and (n_findings or gallery_misses):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
