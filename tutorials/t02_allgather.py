"""Tutorial 02 — AllGather: full-mesh push, 1-D ring, hierarchical 2-D ring.

Analog of reference tutorials/02 + kernels/nvidia/allgather.py. The push
method is one hop (latency-optimal for small messages); the ring moves one
segment per link per step (bandwidth-optimal); ring_2d runs ring-AG along
the fast (minor) axis then along the slow (major) axis for multi-tier
meshes.

Run:  python -m tutorials.t02_allgather [--sim 6] [--case correctness|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _data(ctx, rows_per_rank=32):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    n = ctx.num_ranks
    x = jax.random.normal(jax.random.key(0), (n * rows_per_rank, 256),
                          jnp.float32)
    return x, ctx.shard(x, P("x"))


@register_case("correctness")
def correctness():
    import jax
    import numpy as np

    from triton_dist_tpu.ops import all_gather
    ctx = world_context()
    x, xs = _data(ctx)
    for method in ("push", "ring"):
        y = jax.jit(lambda v, m=method: all_gather(ctx, v, axis="x",
                                                   method=m))(xs)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)
        print(f"all_gather[{method}] == golden")


@register_case("correctness_2d")
def correctness_2d():
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tutorials.common import world_size
    from triton_dist_tpu.ops import all_gather
    n_dev = world_size()
    if n_dev < 4 or n_dev % 2:
        raise SystemExit(f"need an even device count >= 4, have {n_dev} "
                         "(try --sim 6)")
    ctx = world_context(axis_names=("a", "b"), mesh_shape=(2, n_dev // 2))
    import jax.numpy as jnp
    x = jnp.arange(n_dev * 8 * 128, dtype=jnp.float32).reshape(n_dev * 8, 128)
    xs = ctx.shard(x, P(("a", "b")))
    for method in ("ring_2d", "push_2d"):
        y = jax.jit(lambda v, m=method: all_gather(ctx, v, method=m))(xs)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))
        print(f"hierarchical {method} over a (2, {n_dev // 2}) mesh "
              "== golden")


@register_case("correctness_ll")
def correctness_ll():
    """Barrier-free low-latency AG (reference low_latency_allgather.py
    family): phase-keyed double-buffered symmetric workspace, repeated
    calls through one context."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops import AgLLContext
    ctx = world_context()
    ag = AgLLContext(ctx, m_local=16, trailing=(256,), dtype=jnp.float32)
    n = ctx.num_ranks
    for it in range(4):
        x = jax.random.normal(jax.random.key(it), (n * 16, 256),
                              jnp.float32)
        y = ag(ctx.shard(x, P("x")))
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-6)
    print("all_gather_ll x4 calls (parity reuse) == golden")


@register_case("correctness_dcn")
def correctness_dcn():
    """DCN-tier routing: with TDT_DCN_AXES forcing the major axis onto the
    slice-crossing transport, the gather group runs on XLA collectives —
    same result, different transport (cf. the reference's inter-node
    IBRC tier, allgather.py:291-375)."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tutorials.common import world_size
    from triton_dist_tpu.ops import all_gather
    n_dev = world_size()
    if n_dev < 4 or n_dev % 2:
        raise SystemExit(f"need an even device count >= 4, have {n_dev}")
    ctx = world_context(axis_names=("a", "b"), mesh_shape=(2, n_dev // 2))
    os.environ["TDT_DCN_AXES"] = "a"
    try:
        assert ctx.is_dcn_axis("a") and not ctx.is_dcn_axis("b")
        x = jnp.arange(n_dev * 8 * 128, dtype=jnp.float32
                       ).reshape(n_dev * 8, 128)
        y = jax.jit(lambda v: all_gather(ctx, v))(
            ctx.shard(x, P(("a", "b"))))
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))
        print("DCN-routed all_gather (major axis on XLA collectives) "
              "== golden")
    finally:
        del os.environ["TDT_DCN_AXES"]


@register_case("correctness_broadcast")
def correctness_broadcast():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops import broadcast
    ctx = world_context()
    n = ctx.num_ranks
    x = jnp.stack([jnp.full((16, 128), float(i)) for i in range(n)])
    root = n - 1
    y = jax.jit(lambda v: broadcast(ctx, v, axis="x", root=root))(
        ctx.shard(x, P("x")))
    np.testing.assert_allclose(np.asarray(y), np.asarray(x[root]))
    print(f"broadcast(root={root}) over {n} PEs == golden")


@register_case("perf")
def perf():
    import jax

    from triton_dist_tpu.ops import all_gather
    ctx = world_context()
    _, xs = _data(ctx, rows_per_rank=256)
    for method in ("push", "ring"):
        f = jax.jit(lambda v, m=method: all_gather(ctx, v, axis="x",
                                                   method=m))
        s = time_op(lambda: f(xs))
        perf_report(f"all_gather[{method}]", s,
                    f"({xs.nbytes / 1e6:.1f} MB global)")


if __name__ == "__main__":
    tutorial_main(__doc__)
