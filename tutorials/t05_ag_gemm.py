"""Tutorial 05 — overlapping AllGather-GEMM (the first overlap op).

Analog of reference tutorials/07 + allgather_gemm.py. One kernel per
device: non-blocking puts of the local activation shard to every peer run
on the ICI DMA engines while the MXU computes segments in start-local
swizzled order, waiting each remote segment's arrival semaphore exactly
once. The persistent-workspace form (ag_gemm_ws) reuses a context-owned
symmetric buffer across calls.

Run:  python -m tutorials.t05_ag_gemm [--sim 4] [--case correctness|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _shapes(ctx, M=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    n = ctx.num_ranks
    M = M or 128 * n
    K, N = 256, 128 * n
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32
                          ).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32
                          ).astype(jnp.bfloat16)
    return a, b, ctx.shard(a, P("x")), ctx.shard(b, P(None, "x"))


@register_case("correctness")
def correctness():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_dist_tpu.ops import ag_gemm
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context()
    n = ctx.num_ranks
    a, b, a_s, b_s = _shapes(ctx)
    cfg = GemmConfig(128, 128)
    c = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis="x", cfg=cfg))(a_s, b_s)
    gold = (a.astype(jnp.float32) @ b.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(c, np.float32), gold, rtol=5e-2,
                               atol=5e-1)
    print(f"overlapped AG-GEMM over {n} PEs == all_gather+dot golden")


@register_case("correctness_persistent")
def correctness_persistent():
    """Context-owned symmetric workspace reused across 3 calls."""
    import jax.numpy as jnp
    import numpy as np

    from triton_dist_tpu.ops import create_ag_gemm_context
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context()
    n = ctx.num_ranks
    a, b, a_s, b_s = _shapes(ctx)
    agc = create_ag_gemm_context(ctx, a.shape[0] // n, a.shape[1],
                                 jnp.bfloat16, axis="x")
    gold = a.astype(jnp.float32) @ b.astype(jnp.float32)
    for _ in range(3):
        c = agc(a_s, b_s, cfg=GemmConfig(128, 128))
        np.testing.assert_allclose(np.asarray(c, np.float32), gold,
                                   rtol=5e-2, atol=5e-1)
    print("persistent-workspace AG-GEMM: 3 calls, zero per-call workspace "
          "allocation")


@register_case("perf")
def perf():
    import jax

    from triton_dist_tpu.ops import ag_gemm
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context()
    n = ctx.num_ranks
    _, _, a_s, b_s = _shapes(ctx, M=512 * n)
    cfg = GemmConfig(128, 128)
    f = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis="x", cfg=cfg))
    s = time_op(lambda: f(a_s, b_s))
    M, K = a_s.shape
    N = b_s.shape[1]
    perf_report("ag_gemm", s,
                f"~{2 * M * N * K / s / max(n, 1) / 1e12:.1f} TFLOP/s/chip "
                "(wall-clock; see bench.py for tunnel-corrected numbers)")


if __name__ == "__main__":
    tutorial_main(__doc__)
