"""Continuous-batching serving runtime over the paged decode +
EP/SP overlap ops (see docs/serving.md).

- kv_pool    — paged KV page allocator + cache<->pages converters
- scheduler  — FIFO admission / preemption policy over fixed batch slots
- engine     — the jitted one-step-per-token decode engine
- sharded    — the engine on a TP/SP/EP mesh (SP-sharded page pool, TP
               projections, EP MoE FFN through the overlap kernels, with
               the replicated-decision digest guard)
- disagg     — disaggregated prefill/decode over the shmem page-migration
               kernel (signal-gated admission + the ISSUE-7 recovery
               ladder: deadline → retry/backoff → local re-prefill →
               typed per-request failure)
- compose    — disagg × sharded (ISSUE 12): a disaggregated prefill fleet
               feeding a ShardedServingEngine decode fleet on ONE
               TP/SP/EP mesh, over the unified pool contract
- cluster    — N engine replicas behind a deterministic prefix-affinity
               router, each with a private path-namespaced journal and
               kill/restore through the ISSUE-9 ladder; SimEngine is the
               host-only scale vehicle (scripts/cluster_sim.py)
- prefix_cache — token-keyed radix index over KVPagePool pages (ISSUE
               13): refcounted adoption of cached prefixes, copy-on-
               write on divergence, LRU eviction of refcount-0 pages,
               and the cluster-authoritative ReplicaPrefixIndex twin
- lending    — cluster-wide prefix sharing (ISSUE 17): on a borrower-
               side cache miss with a remote index hit the owner LENDS
               its refcount-0 cached pages (ops.lend_pages on device
               meshes, export/adopt_prefix on host engines), wrapped in
               the Deadline/Backoff/degrade ladder; a restored replica
               re-warms its empty cache from peers the same way
- deadline   — Deadline/Backoff helpers + EngineStallError (the global
               progress watchdog both engines share)
- journal    — append-only WAL of control-plane events (ISSUE 9)
- checkpoint — periodic control-plane snapshot + journal-suffix replay
               restore (crash recovery with zero new compiles)
- metrics    — counters + histograms, JSON-lines wire format
- scheduler (ISSUE 14) — also the multi-tenant SLO policy surface:
               ClassSpec/SLOPolicy (priority classes, WFQ weights,
               per-tenant token-bucket quotas, per-class caps/TTLs)
- workload   — bursty two-class trace generation (ISSUE 14): Zipf prompt
               sharing × chat-vs-batch × diurnal bursts, plus the
               --workload / --slo CLI spec parsers
- autoscaler — elastic fleet control (ISSUE 18): a deterministic policy
               loop over windowed per-class TTFT/ITL SLO attainment that
               scales replicas up from the AOT artifact and down through
               the graceful drain ladder (requeue, lend-ahead, retire),
               journaling every decision so a controller restart resumes
               the fleet from the journal
- speculate  — model-free speculative decoding primitives (ISSUE 20):
               the bigram prompt-lookup drafter, the exact-match-greedy
               accept rule (EOS/limit composed), and the draft-length
               resolution ladder (explicit → tuned registry → default)
"""

from triton_dist_tpu.serving.autoscaler import Autoscaler, parse_budgets
from triton_dist_tpu.serving.checkpoint import (Checkpoint,
                                                CheckpointIntegrityError,
                                                capture, latest, restore)
from triton_dist_tpu.serving.cluster import (Cluster, EngineReplica,
                                             ReplicaState, SimEngine,
                                             expected_tokens, sim_token)
from triton_dist_tpu.serving.compose import DisaggShardedEngine
from triton_dist_tpu.serving.deadline import (Backoff, Deadline,
                                              EngineStallError)
from triton_dist_tpu.serving.disagg import (ChunkSignalLedger,
                                            DisaggServingEngine,
                                            MigrationSignalTimeout,
                                            PageMigrationChannel,
                                            SignalProtocolError)
from triton_dist_tpu.serving.engine import ServingEngine
from triton_dist_tpu.serving.journal import (EVENT_KINDS, SCHEMA_VERSION,
                                             ControlJournal)
from triton_dist_tpu.serving.kv_pool import (KVPagePool, PageLedgerError,
                                             cache_to_pages, page_pool_pspec,
                                             pages_to_cache,
                                             shard_pool_arrays)
from triton_dist_tpu.serving.lending import PageLendingTier
from triton_dist_tpu.serving.metrics import (AttainmentWindow, Histogram,
                                             ServingMetrics)
from triton_dist_tpu.serving.prefix_cache import (PrefixCache,
                                                  ReplicaPrefixIndex)
from triton_dist_tpu.serving.scheduler import (AdmissionRejected, ClassSpec,
                                               ContinuousBatchingScheduler,
                                               Request, RequestState,
                                               SLOPolicy, TtlExpired)
from triton_dist_tpu.serving.sharded import (MESH_AXES,
                                             ReplicatedDecisionError,
                                             ShardedServingEngine,
                                             serving_mesh)
from triton_dist_tpu.serving.speculate import (ngram_draft, resolve_spec_k,
                                               spec_accept)
from triton_dist_tpu.serving.workload import (WorkloadSpec,
                                              generate_arrivals, parse_slo,
                                              parse_workload, rate_at,
                                              spec_bucket_of)

__all__ = [
    "ServingEngine",
    "ShardedServingEngine",
    "ReplicatedDecisionError",
    "serving_mesh",
    "MESH_AXES",
    "DisaggServingEngine",
    "DisaggShardedEngine",
    "Cluster",
    "EngineReplica",
    "ReplicaState",
    "SimEngine",
    "Autoscaler",
    "parse_budgets",
    "PageLendingTier",
    "expected_tokens",
    "sim_token",
    "shard_pool_arrays",
    "PageMigrationChannel",
    "ChunkSignalLedger",
    "MigrationSignalTimeout",
    "SignalProtocolError",
    "Deadline",
    "Backoff",
    "EngineStallError",
    "ControlJournal",
    "EVENT_KINDS",
    "SCHEMA_VERSION",
    "Checkpoint",
    "CheckpointIntegrityError",
    "capture",
    "restore",
    "latest",
    "AdmissionRejected",
    "TtlExpired",
    "ClassSpec",
    "SLOPolicy",
    "WorkloadSpec",
    "parse_workload",
    "generate_arrivals",
    "parse_slo",
    "rate_at",
    "KVPagePool",
    "PageLedgerError",
    "PrefixCache",
    "ReplicaPrefixIndex",
    "page_pool_pspec",
    "cache_to_pages",
    "pages_to_cache",
    "ContinuousBatchingScheduler",
    "Request",
    "RequestState",
    "ServingMetrics",
    "Histogram",
    "AttainmentWindow",
    "ngram_draft",
    "spec_accept",
    "resolve_spec_k",
    "spec_bucket_of",
]
