"""Paged KV block allocator over the ``[P, Hkv, page_size, D]`` page pool
that ``ops.flash_decode.gqa_decode_paged`` consumes.

Two cleanly separated halves:

- **device memory**: ``models.llama.init_page_pool`` arrays — plain jax
  arrays the engine threads through its jitted step (donated, so the hot
  loop updates pages in place). Nothing here ever looks at their values.
- **host accounting** (this module): ``KVPagePool`` — a free-list over
  page ids with per-sequence ownership, allocate-on-decode growth and
  free-on-finish. Pure Python, deterministic (LIFO free list), microsecond
  scale next to a decode step.

Sharding: the pool shards exactly like the SP cache — the page-major pool
array is the paged twin of the ``[L, B, Hkv, S, D]`` cache whose S dim is
``P(..., axis, ...)``-sharded. ``page_pool_pspec(axis)`` shards the page
dim: each SP rank owns the pages of its sequence shard and runs an
identical (replicated-decision) allocator instance, so block tables stay
host-replicated control plane — same split as ``decode_step_sp``'s cache.

ONE pool contract (ISSUE 12): a single ``KVPagePool`` is simultaneously

- **shard_map-visible**: construct with ``sp_ranks=n`` and place the
  device arrays with ``shard_pool_arrays`` — the page dim is padded up to
  a multiple of ``n`` so ``page_pool_pspec`` splits it evenly. The
  allocator never hands out a padding id (``device_pages`` > ids ≥
  ``num_pages`` exist only on device), so allocation/preemption schedules
  are identical at every mesh size; and
- **a valid ``migrate_pages`` target**: ``check_migratable`` refuses
  scratch AND padding ids, and ``landed_row`` exposes only the signal-
  covered prefix of real owned pages — both independent of ``sp_ranks``.

``digest()`` deliberately EXCLUDES ``sp_ranks``/``device_pages``: the
ledger digest describes allocation DECISIONS, which the device layout
must never influence — pools driving meshes of different SP widths over
the same trace digest identically (test-pinned at n ∈ {1, 2, 4}).

``cache_to_pages`` / ``pages_to_cache`` convert between the head-major
contiguous ``init_kv_cache`` layout and the page pool — pure data
movement (gather/scatter by block table), bit-exact round trip — so
prefill can fill a contiguous cache (the layout the prefill kernels like)
and hand the pages off to the pool.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _fnv1a(h: int, *words: int) -> int:
    """Fold ints into a 32-bit FNV-1a state (4 bytes each, two's
    complement for the odd negative sentinel). Shared by the pool and
    scheduler digests so the two ledgers hash identically across ranks."""
    for w in words:
        w &= 0xFFFFFFFF
        for shift in (0, 8, 16, 24):
            h ^= (w >> shift) & 0xFF
            h = (h * 0x01000193) & 0xFFFFFFFF
    return h


class PageLedgerError(AssertionError):
    """Page-accounting corruption: double free, freeing a foreign page, or
    migrating a reserved/scratch page. Raised EXPLICITLY (not via bare
    ``assert``) so detection survives ``python -O`` — silent ledger
    corruption would let two sequences share a page and scribble over each
    other's KV. Subclasses ``AssertionError`` because the ledger checks
    started life as asserts and callers/tests catch them as such."""


def page_pool_pspec(axis: str | None) -> P:
    """PartitionSpec for the [L, P, Hkv, page_size, D] pool arrays: pages
    sharded over ``axis`` (the SP-cache analog — its S dim becomes the
    page dim here); everything else replicated."""
    return P(None, axis, None, None, None)


class KVPagePool:
    """Host-side free-list allocator over ``num_pages`` page ids.

    Invariants (asserted here, exercised in tests/test_serving.py):
    - a page id is owned by at most one sequence at a time;
    - ``reserved`` low ids are never handed out (the engine parks
      inactive batch slots on page 0 — its writes must never land on a
      live sequence's page);
    - alloc is all-or-nothing: a request for ``n`` pages either returns
      ``n`` ids or ``None`` and changes nothing (no partial grabs to
      unwind on preemption).
    The free list is LIFO so allocation order is deterministic — replay
    of the same trace allocates the same pages.

    ``sp_ranks`` (ISSUE 12, the unified pool contract) declares the SP
    width of the DEVICE arrays this ledger fronts: the device page dim is
    padded up to ``device_pages`` (a multiple of ``sp_ranks`` so
    ``page_pool_pspec`` splits evenly), but the allocator's id space stays
    ``[reserved, num_pages)`` — padding ids exist only on device, are
    never handed out, and are refused by ``check_migratable``. Every
    allocation DECISION (and hence ``digest()``) is independent of
    ``sp_ranks``; only ``page_shard`` / ``device_pages`` see the layout.

    ``layout`` (ISSUE 19) picks the ledger-id → device-row placement:

    - ``"blocked"`` (default): device row == page id — consecutive ids
      land on the same SP shard, the across-REQUESTS balance the pool-
      allgather attention path wants.
    - ``"interleaved"``: row ``(id % sp_ranks) * (device_pages /
      sp_ranks) + id // sp_ranks`` — consecutive ids round-robin across
      SP shards, so ONE long sequence's pages spread evenly over the
      mesh (the ``flash_decode_dist`` long-context mode, where per-rank
      attention compute is ∝ the LOCAL page count).

    Either way the map is a bijection over ``[0, device_pages)`` with
    row 0 fixed (the scratch page parks in shard 0's slice under both),
    and it is pure DEVICE layout: allocator ids, snapshots, and
    ``digest()`` never see it — the fixed-order page fold makes the
    attention result placement-invariant, so layout is a balance knob,
    never a decision input.
    """

    def __init__(self, num_pages: int, page_size: int, reserved: int = 0,
                 sp_ranks: int = 1, layout: str = "blocked"):
        assert num_pages > reserved >= 0
        assert sp_ranks >= 1
        assert layout in ("blocked", "interleaved"), (
            f"layout must be 'blocked' or 'interleaved', got {layout!r}")
        self.num_pages = num_pages
        self.page_size = page_size
        self.reserved = reserved
        self.sp_ranks = sp_ranks
        self.layout = layout
        # device page count: padded up so the page dim splits evenly over
        # the SP axis (the padding pages are invisible to the allocator)
        self.device_pages = num_pages + (-num_pages) % sp_ranks
        # LIFO: lowest ids on top, so fresh pools allocate reserved, 1, 2…
        self._free = list(range(num_pages - 1, reserved - 1, -1))
        self._owned: dict[object, list[int]] = {}
        # prefix caching (ISSUE 13): every referenced page carries a
        # refcount (1 for a plain allocation; >1 when the prefix cache
        # shares it across sequences). ``_cacheable`` marks pages the
        # prefix index holds; a cacheable page whose last reference drops
        # is RETAINED on the ``_cached`` LRU list (oldest first) instead
        # of returning to the free list — reclaimable, never a leak.
        self._refs: dict[int, int] = {}
        self._cached: list[int] = []
        self._cacheable: set[int] = set()

    # -- introspection ----------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - self.reserved) - len(self._free)

    def occupancy(self) -> float:
        cap = self.num_pages - self.reserved
        return self.used_pages / cap if cap else 0.0

    def pages_of(self, seq_id) -> list[int]:
        return list(self._owned.get(seq_id, ()))

    def holds(self, seq_id) -> bool:
        return seq_id in self._owned

    def refcount(self, page_id: int) -> int:
        """How many sequences hold ``page_id`` right now (0 = free or
        cached). The COW guard: a writer must never touch a page whose
        refcount exceeds 1."""
        return self._refs.get(page_id, 0)

    @property
    def cached_pages(self) -> int:
        """Refcount-0 pages retained for the prefix index — reclaimable
        on demand (LRU), counted as used by ``occupancy`` because they
        hold live KV bytes."""
        return len(self._cached)

    def lru_cached(self) -> list[int]:
        """Cached (refcount-0, index-retained) pages, oldest first — the
        eviction scan order. Copy; mutations go through ``uncache``."""
        return list(self._cached)

    def device_row(self, page_id: int) -> int:
        """Device-array row (page-dim index) holding ledger page
        ``page_id`` — identity under ``"blocked"``, the round-robin
        bijection under ``"interleaved"``. Every id that crosses to the
        device (block-table entries, host-side pool gathers/scatters)
        goes through here; everything that stays in the ledger (digest,
        snapshot, journal payloads) never does."""
        if not 0 <= page_id < self.device_pages:
            raise PageLedgerError(
                f"page {page_id} outside the device range "
                f"[0, {self.device_pages})")
        if self.layout == "blocked":
            return page_id
        return ((page_id % self.sp_ranks)
                * (self.device_pages // self.sp_ranks)
                + page_id // self.sp_ranks)

    def page_shard(self, page_id: int) -> int:
        """Which SP rank's device shard holds ``page_id`` under the
        ``page_pool_pspec`` even split of the padded page dim. Pure layout
        introspection — no allocation decision may depend on it (that
        would fork the replicated control plane across mesh sizes)."""
        return self.device_row(page_id) \
            // (self.device_pages // self.sp_ranks)

    def digest(self) -> int:
        """Cheap order-sensitive ledger digest (32-bit FNV-1a) over the
        ENTIRE allocator state: free-list order, ownership map in insertion
        order, and the static geometry. Two pools that ever made a
        different allocation decision — even ones that converged back to
        the same free-page COUNT — digest differently, because the LIFO
        free-list ORDER encodes the whole decision history. This is the
        replicated-decision guard the sharded serving engine cross-checks
        every step: every rank runs an identical allocator on identical
        inputs, so any digest divergence means a rank's control plane
        forked (and its block tables are about to scribble on the wrong
        pages). Pure Python ints, microseconds at serving pool sizes."""
        h = _fnv1a(0x811C9DC5, self.num_pages, self.page_size, self.reserved)
        h = _fnv1a(h, len(self._free), *self._free)
        for sid, pages in self._owned.items():
            h = _fnv1a(h, hash(sid) & 0xFFFFFFFF, len(pages), *pages)
        # prefix-cache state (ISSUE 13): refcounts by page id, the cached
        # LRU order, and the index-retention marks — all allocation
        # DECISIONS, all still independent of ``sp_ranks``
        for p in sorted(self._refs):
            h = _fnv1a(h, p, self._refs[p])
        h = _fnv1a(h, len(self._cached), *self._cached)
        h = _fnv1a(h, len(self._cacheable), *sorted(self._cacheable))
        return h

    # -- checkpointing (ISSUE 9) ------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able snapshot of the ledger: free-list order and ownership
        map in insertion order (both order-sensitive — they round-trip the
        digest exactly). Used by serving/checkpoint.py, which rebuilds a
        pool from the snapshot and audits ``digest()`` against the value
        recorded at capture time (a torn snapshot fails loudly instead of
        silently double-owning pages after a restore)."""
        return {"free": list(self._free),
                "owned": [[sid, list(pages)]
                          for sid, pages in self._owned.items()],
                "refs": [[p, self._refs[p]] for p in sorted(self._refs)],
                "cached": list(self._cached),
                "cacheable": sorted(self._cacheable)}

    @classmethod
    def from_snapshot(cls, snap: dict, num_pages: int, page_size: int,
                      reserved: int = 0, sp_ranks: int = 1,
                      layout: str = "blocked") -> "KVPagePool":
        """Rebuild a ledger from ``snapshot()`` output (geometry is not in
        the snapshot — it comes from the engine's own configuration, which
        a restore never changes; ``sp_ranks``/``layout`` are device layout
        only and do not affect the rebuilt digest)."""
        pool = cls(num_pages, page_size, reserved, sp_ranks=sp_ranks,
                   layout=layout)
        pool._free = [int(p) for p in snap["free"]]
        pool._owned = {sid: [int(p) for p in pages]
                       for sid, pages in snap["owned"]}
        # restored VERBATIM (not re-derived from ownership multiplicity):
        # the checkpoint integrity audit digests the rebuilt pool against
        # the capture-time value, so a tampered refcount/cache field must
        # surface as a digest mismatch, not be silently repaired
        if "refs" in snap:
            pool._refs = {int(p): int(c) for p, c in snap["refs"]}
        else:           # pre-cache snapshot: refcounts are the ownership
            for pages in pool._owned.values():
                for p in pages:
                    pool._refs[p] = pool._refs.get(p, 0) + 1
        pool._cached = [int(p) for p in snap.get("cached", ())]
        pool._cacheable = {int(p) for p in snap.get("cacheable", ())}
        return pool

    # -- allocation -------------------------------------------------------
    def alloc(self, seq_id, n_pages: int) -> list[int] | None:
        """Grow ``seq_id`` by ``n_pages``; all-or-nothing. Returns the new
        page ids or ``None`` when the pool is dry."""
        if n_pages > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n_pages)]
        for p in got:
            self._refs[p] = 1
        self._owned.setdefault(seq_id, []).extend(got)
        return got

    def acquire(self, seq_id, page_ids) -> None:
        """Adopt ``page_ids`` for ``seq_id`` — the prefix-cache hit path
        (ISSUE 13). Each page must already hold live KV: either cached
        (refcount 0, retained for the index — it leaves the LRU list) or
        referenced by other sequences (its refcount climbs). Appended to
        the sequence's page list IN ORDER (pages are positional). All
        checks run before any mutation, so a refused acquire changes
        nothing."""
        own = set(self._owned.get(seq_id, ()))
        seen: set[int] = set()
        for p in page_ids:
            if not (self.reserved <= p < self.num_pages):
                raise PageLedgerError(
                    f"cannot adopt out-of-range page {p} (seq {seq_id!r})")
            if p in own or p in seen:
                raise PageLedgerError(
                    f"seq {seq_id!r} already holds page {p}")
            seen.add(p)
            if self._refs.get(p, 0) == 0 and p not in self._cached:
                raise PageLedgerError(
                    f"page {p} holds no live KV (free?) — refusing to "
                    f"adopt it for seq {seq_id!r}")
        for p in page_ids:
            if self._refs.get(p, 0) == 0:
                self._cached.remove(p)
            self._refs[p] = self._refs.get(p, 0) + 1
            self._owned.setdefault(seq_id, []).append(p)

    def _release_page(self, seq_id, p: int) -> bool:
        """Drop one reference to ``p``. On the LAST reference the page
        returns to the free list — unless the prefix index retains it
        (``_cacheable``), in which case it parks on the cached LRU list.
        True iff the page actually left the referenced set."""
        r = self._refs.get(p, 0)
        if r <= 0:
            raise PageLedgerError(
                f"refcount underflow on page {p} (seq {seq_id!r})")
        if r > 1:
            self._refs[p] = r - 1
            return False
        del self._refs[p]
        if p in self._free:
            raise PageLedgerError(
                f"double free of page {p} (seq {seq_id!r})")
        if p in self._cacheable:
            self._cached.append(p)      # MRU position
        else:
            self._free.append(p)
        return True

    def ensure(self, seq_id, kv_len: int) -> bool:
        """Allocate-on-decode growth: make ``seq_id`` own enough pages to
        hold ``kv_len`` tokens. True on success (including no-op), False
        when the pool is dry (caller preempts and retries)."""
        have = len(self._owned.get(seq_id, ()))
        need = -(-kv_len // self.page_size) - have
        if need <= 0:
            return True
        return self.alloc(seq_id, need) is not None

    def free_tail(self, seq_id, keep: int) -> int:
        """Free every page of ``seq_id`` past the first ``keep`` — the
        mid-prefill preemption primitive: the pages already holding
        computed KV (up to the chunk cursor) stay owned across the
        eviction, only the unfilled tail returns to the pool. Freed in
        allocation order (same convention as ``free_seq``) so replay
        stays deterministic. Returns how many were freed."""
        pages = self._owned.get(seq_id, [])
        if not 0 <= keep <= len(pages):
            raise PageLedgerError(
                f"free_tail(keep={keep}) out of range for seq {seq_id!r} "
                f"owning {len(pages)} pages")
        tail = pages[keep:]
        for p in tail:
            self._release_page(seq_id, p)
        if keep:
            self._owned[seq_id] = pages[:keep]
        else:
            self._owned.pop(seq_id, None)
        return len(tail)

    def free_seq(self, seq_id) -> int:
        """Free-on-finish (and on preemption): return every page of
        ``seq_id`` to the free list. Returns how many were freed."""
        pages = self._owned.pop(seq_id, [])
        for p in pages:
            self._release_page(seq_id, p)
        return len(pages)

    # -- prefix-cache retention + copy-on-write (ISSUE 13) ----------------
    def mark_cacheable(self, page_id: int) -> None:
        """Flag ``page_id`` as held by the prefix index: when its last
        reference drops it parks on the cached LRU list instead of the
        free list. Only live pages can be marked — a free page holds no
        KV worth retaining."""
        if not (self.reserved <= page_id < self.num_pages):
            raise PageLedgerError(
                f"cannot index out-of-range page {page_id}")
        if page_id in self._free:
            raise PageLedgerError(
                f"cannot index free page {page_id} — it holds no KV")
        self._cacheable.add(page_id)

    def uncache(self, page_id: int) -> bool:
        """Drop the index retention mark (eviction / index invalidation).
        If the page is sitting on the cached LRU list it returns to the
        free list NOW; if it is still referenced it simply frees normally
        on its last release. True iff a cached page was reclaimed."""
        self._cacheable.discard(page_id)
        if page_id in self._cached:
            self._cached.remove(page_id)
            self._free.append(page_id)
            return True
        return False

    def cow_page(self, seq_id, index: int) -> tuple[int, int] | None:
        """Copy-on-write ledger half: ``seq_id`` is about to WRITE its
        ``index``-th page but shares it (refcount > 1), so swap a fresh
        page into its page list and drop one reference on the shared one
        (which stays alive for its other holders / the index). Returns
        ``(old_id, new_id)`` — the caller must copy the device page bytes
        old → new before any read — or ``None`` when the pool is dry
        (caller evicts or preempts, then retries). Refuses a COW of a
        sole-owned page: writing in place is correct there, and a silent
        pointless copy would hide an engine-side guard bug."""
        pages = self._owned.get(seq_id, [])
        if not 0 <= index < len(pages):
            raise PageLedgerError(
                f"cow_page(index={index}) out of range for seq "
                f"{seq_id!r} owning {len(pages)} pages")
        old = pages[index]
        if self._refs.get(old, 0) <= 1:
            raise PageLedgerError(
                f"COW of page {old} with refcount "
                f"{self._refs.get(old, 0)} — copy-on-write is only for "
                f"shared pages (seq {seq_id!r})")
        if not self._free:
            return None
        new = self._free.pop()
        self._refs[new] = 1
        pages[index] = new
        self._refs[old] -= 1
        return old, new

    # -- migration support (disaggregated serving, ISSUE 6) ---------------
    def check_migratable(self, seq_id, page_ids) -> None:
        """Migration precondition: every id in ``page_ids`` must be owned
        by ``seq_id``, non-reserved, and a REAL page (< ``num_pages``).
        The scratch page(s) are engine-local parking — inactive rows WRITE
        to them every dispatch, so shipping one to a peer pool would plant
        live-mutating garbage there. SP padding ids (``num_pages`` ≤ id <
        ``device_pages``) exist only to even the device shard split —
        migrating one would write KV into a slot no block table can ever
        expose (a silent data loss). Raises ``PageLedgerError`` (loud,
        not silent corruption)."""
        owned = set(self._owned.get(seq_id, ()))
        for p in page_ids:
            if p < self.reserved:
                raise PageLedgerError(
                    f"page {p} is a reserved scratch page — scratch pages "
                    f"are never migrated (seq {seq_id!r})")
            if p >= self.num_pages:
                raise PageLedgerError(
                    f"page {p} is an SP padding/out-of-range id (real "
                    f"pages end at {self.num_pages}, device shard pads to "
                    f"{self.device_pages}) — padding pages are never "
                    f"migrated (seq {seq_id!r})")
            if p not in owned:
                raise PageLedgerError(
                    f"page {p} is not owned by seq {seq_id!r} — refusing "
                    "to migrate a foreign page")
            if self._refs.get(p, 0) > 1:
                raise PageLedgerError(
                    f"page {p} is shared (refcount {self._refs[p]}) — "
                    f"migration requires sole ownership; a migrated page "
                    f"is rewritten at the destination while other "
                    f"sequences still read it here (seq {seq_id!r})")

    def check_lendable(self, page_ids) -> int:
        """Lending precondition (ISSUE 17): how many pages of the
        POSITIONAL PREFIX of ``page_ids`` may be lent to a peer replica.
        A page is lendable iff it is refcount-0 AND retained on the
        cached LRU list — nobody here reads or writes it, the prefix
        index alone keeps it alive, so copying its bytes out races with
        nothing and the COW contract is untouched (the sole-ownership
        twin of ``check_migratable``, one rung stricter: migration wants
        exactly one owner, lending wants zero). Pages are positional
        (page i holds tokens ``[i*page_size, (i+1)*page_size)``), so the
        lendable run stops at the FIRST non-lendable page — a borrower
        resumes chunked prefill right there. Out-of-range/reserved ids
        are loud errors, not a short count: the caller handed us ids
        straight from its prefix index, so a bad id is ledger
        corruption."""
        cached = set(self._cached)
        n = 0
        for p in page_ids:
            if not (self.reserved <= p < self.num_pages):
                raise PageLedgerError(
                    f"check_lendable: page {p} outside the real-page "
                    f"range [{self.reserved}, {self.num_pages})")
            if self._refs.get(p, 0) != 0 or p not in cached:
                break
            n += 1
        return n

    def landed_row(self, seq_id, covered, pages_per_seq: int,
                   fill: int = 0) -> list[int]:
        """Block-table row exposing only the LANDED PREFIX of ``seq_id``'s
        pages. Pages are positional (page i holds tokens
        ``[i*page_size, (i+1)*page_size)``), so a page is usable only when
        it AND every page before it are in ``covered`` — the set of ids
        whose delivery signals have fired (``ChunkSignalLedger.covered``).
        Entries past the prefix are ``fill`` (the scratch page): the
        decode worker can never dereference a page whose signal has not
        fired. This is the block-table-patching half of signal-gated
        admission (serving/disagg.py)."""
        row: list[int] = []
        for p in self._owned.get(seq_id, []):
            if p not in covered:
                break
            row.append(p)
        if len(row) > pages_per_seq:
            raise PageLedgerError(
                f"seq {seq_id!r} landed {len(row)} pages > pages_per_seq "
                f"{pages_per_seq}")
        return row + [fill] * (pages_per_seq - len(row))

    def check(self, ledger=None) -> None:
        """Full-invariant audit (ISSUE 7): verify the free-list and
        ownership map are mutually consistent, and — given the migration
        ``ChunkSignalLedger`` — that signal accounting agrees with page
        ownership. Cheap enough to run after every chaos schedule; raises
        ``PageLedgerError`` with the first violation found.

        Invariants:
        - every free id is in range ``[reserved, num_pages)`` and listed
          exactly once;
        - every owned id is in range, not simultaneously free, and held
          by exactly ``refcount`` sequences (a page in two sequences'
          lists without a matching refcount is corruption, with one it
          is prefix sharing);
        - every refcount is positive and matches the ownership
          multiplicity; every cached page has refcount 0, carries the
          index-retention mark, and is neither free nor owned;
        - free + referenced + cached together account for every
          non-reserved page (count conservation — cached pages are
          reclaimable, never audited as leaks);
        - (with ``ledger``) every page a chunk expects to land for a
          sequence is owned by that sequence here, landed never exceeds
          expected per chunk, and the covered set never exceeds the
          sequence's allocation (landed prefix <= allocated).
        """
        owner: dict[int, object] = {}
        mult: dict[int, int] = {}
        for sid, pages in self._owned.items():
            seen: set[int] = set()
            for p in pages:
                if not (self.reserved <= p < self.num_pages):
                    raise PageLedgerError(
                        f"seq {sid!r} owns out-of-range page {p}")
                if p in seen:
                    raise PageLedgerError(
                        f"seq {sid!r} lists page {p} twice")
                seen.add(p)
                mult[p] = mult.get(p, 0) + 1
                owner.setdefault(p, sid)
        for p, n in mult.items():
            if self._refs.get(p, 0) != n:
                raise PageLedgerError(
                    f"page {p} held by {n} sequence(s) but refcount is "
                    f"{self._refs.get(p, 0)}")
        for p, r in self._refs.items():
            if r <= 0:
                raise PageLedgerError(
                    f"page {p} carries non-positive refcount {r}")
            if p not in mult:
                raise PageLedgerError(
                    f"page {p} has refcount {r} but no owning sequence")
        free = set(self._free)
        if len(free) != len(self._free):
            raise PageLedgerError("duplicate ids on the free list")
        for p in free:
            if not (self.reserved <= p < self.num_pages):
                raise PageLedgerError(f"out-of-range page {p} on free list")
            if p in owner:
                raise PageLedgerError(
                    f"page {p} is both free and owned by seq {owner[p]!r}")
            if p in self._cacheable:
                raise PageLedgerError(
                    f"page {p} is free yet still index-retained")
        cached = set(self._cached)
        if len(cached) != len(self._cached):
            raise PageLedgerError("duplicate ids on the cached LRU list")
        for p in cached:
            if not (self.reserved <= p < self.num_pages):
                raise PageLedgerError(
                    f"out-of-range page {p} on the cached list")
            if p in owner:
                raise PageLedgerError(
                    f"page {p} is cached (refcount 0) yet owned by seq "
                    f"{owner[p]!r}")
            if p in free:
                raise PageLedgerError(f"page {p} is both cached and free")
            if p not in self._cacheable:
                raise PageLedgerError(
                    f"page {p} is cached without an index-retention mark")
        total = len(free) + len(owner) + len(cached)
        if total != self.num_pages - self.reserved:
            raise PageLedgerError(
                f"page conservation violated: {len(free)} free + "
                f"{len(owner)} referenced + {len(cached)} cached != "
                f"{self.num_pages - self.reserved} non-reserved pages")
        if ledger is None:
            return
        for sid in ledger.rids():
            owned = set(self._owned.get(sid, ()))
            covered = ledger.covered(sid)
            if not covered <= owned:
                raise PageLedgerError(
                    f"seq {sid!r}: ledger covers pages "
                    f"{sorted(covered - owned)} this pool never allocated "
                    "to it (landed prefix exceeds allocation)")
            for chunk_idx, dst_ids, landed in ledger.chunk_items(sid):
                if landed > len(dst_ids):
                    raise PageLedgerError(
                        f"seq {sid!r} chunk {chunk_idx}: landed {landed} > "
                        f"expected {len(dst_ids)}")
                if not set(dst_ids) <= owned:
                    raise PageLedgerError(
                        f"seq {sid!r} chunk {chunk_idx}: expects pages "
                        f"{sorted(set(dst_ids) - owned)} not owned here")

    def block_table_row(self, seq_id, pages_per_seq: int,
                        fill: int = 0) -> list[int]:
        """Fixed-width block-table row for the kernel: owned pages then
        ``fill`` (the engine's scratch page — entries past the valid count
        are never dereferenced by ``gqa_decode_paged``, but a valid id
        keeps the row honest)."""
        pages = self._owned.get(seq_id, [])
        assert len(pages) <= pages_per_seq, (
            f"seq {seq_id!r} owns {len(pages)} pages > pages_per_seq "
            f"{pages_per_seq}")
        return pages + [fill] * (pages_per_seq - len(pages))


# ---------------------------------------------------------------------------
# device-side pool layout (the shard_map half of the one pool contract)
# ---------------------------------------------------------------------------

def shard_pool_arrays(pool: dict, sp_ranks: int, sharding=None) -> dict:
    """Pad the ``{"k", "v"}`` pool arrays' page dim (axis 1) up to a
    multiple of ``sp_ranks`` and (optionally) commit them to ``sharding``
    — the one place the SP device layout is materialized, shared by the
    sharded engine and the composed disagg-on-mesh prefill fleet so both
    sides of a cross-mesh migration carry the SAME array shapes and
    placement (one pjit executable serves both pools).

    Zero-init padding matches the live pages' init; the allocator never
    hands a padding id out (``KVPagePool(sp_ranks=...)``), so every
    block-table fill entry stays the scratch page and the padding is
    unreachable from any compiled program's reads."""
    pad = (-pool["k"].shape[1]) % sp_ranks
    if pad:
        pool = {
            k: jnp.concatenate(
                [v, jnp.zeros(v.shape[:1] + (pad,) + v.shape[2:],
                              v.dtype)], axis=1)
            for k, v in pool.items()}
    if sharding is not None:
        pool = {k: jax.device_put(v, sharding) for k, v in pool.items()}
    return pool


# ---------------------------------------------------------------------------
# contiguous cache <-> page pool converters
# ---------------------------------------------------------------------------

def cache_to_pages(cache: jax.Array, pages: jax.Array,
                   block_table: jax.Array) -> jax.Array:
    """Scatter a head-major contiguous cache into the page pool.

    cache [L, B, Hkv, S, D] (``init_kv_cache`` layout, one of k/v);
    pages [L, P, Hkv, page_size, D] (``init_page_pool`` layout);
    block_table [B, n_pages] int32 with n_pages * page_size <= S.
    Writes cache[:, b, :, p*ps:(p+1)*ps] into pages[:, bt[b, p]] for every
    (b, p) — whole pages, pure data movement (prefill zero-pads the tail
    of its last page; decode overwrites those rows one token at a time).
    """
    L, B, Hkv, S, D = cache.shape
    ps = pages.shape[3]
    n_pages = block_table.shape[1]
    assert n_pages * ps <= S, (n_pages, ps, S)
    src = cache[:, :, :, :n_pages * ps].reshape(L, B, Hkv, n_pages, ps, D)
    src = src.transpose(0, 1, 3, 2, 4, 5).reshape(L, B * n_pages, Hkv, ps, D)
    return pages.at[:, block_table.reshape(-1)].set(src)


def pages_to_cache(pages: jax.Array, block_table: jax.Array) -> jax.Array:
    """Gather pool pages back into a contiguous head-major cache — the
    exact inverse of ``cache_to_pages`` (bit-compare round trip is a
    test). pages [L, P, Hkv, ps, D]; block_table [B, n_pages] →
    [L, B, Hkv, n_pages*ps, D]."""
    L = pages.shape[0]
    Hkv, ps, D = pages.shape[2:]
    B, n_pages = block_table.shape
    g = pages[:, block_table.reshape(-1)]          # [L, B*n_pages, Hkv, ps, D]
    g = g.reshape(L, B, n_pages, Hkv, ps, D).transpose(0, 1, 3, 2, 4, 5)
    return g.reshape(L, B, Hkv, n_pages * ps, D)


__all__ = ["KVPagePool", "PageLedgerError", "page_pool_pspec",
           "shard_pool_arrays", "cache_to_pages", "pages_to_cache",
           "_fnv1a"]
