"""GEMM-ReduceScatter overlap (analog of reference
python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py).

The reference runs a producer GEMM that writes tiles into a symmetric buffer
and sets per-tile scatter signals, with a reduce-scatter consumer draining
them on a second stream under an SM budget (gemm_reduce_scatter.py:77-87,
:104-234, :482-521). TPU-native single-kernel design:

1. Walk output segments in swizzled order ``me+1, me+2, …, me`` (own segment
   LAST — its result never travels, so remote partials spend the longest
   possible time in flight behind compute).
2. For each remote segment: pipelined MXU GEMM of that segment's rows into a
   double-buffered staging slot, then a non-blocking put of the partial into
   the owner's symmetric slot ``me``. Stage slots are reused every 2 steps,
   guarded by the send semaphore of the put issued 2 steps earlier.
3. Own segment: GEMM straight into our symmetric slot ``me`` (no copy).
4. Reduce phase: wait each peer's arrival once, then a pipelined VPU
   reduction over the ``n`` partial slots → output shard.

Row-parallel TP semantics: A is [M, K] K-sharded, B is [K, N] K-sharded
(row-parallel weight); each rank's partial is A_local @ B_local and ranks
receive the M/n rows they own, summed over all ranks.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for, norm_axis as _norm_axis
from triton_dist_tpu.ops.gemm import (GemmConfig, best_gemm_config,
                                       emit_gemm)
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def rs_overlap_protocol(axis, mesh_axes, ws_ref, stage_ref,
                        send_sems, recv_sems, emit):
    """The shared GEMM-ReduceScatter producer protocol (one copy — GEMM-RS
    and the fused MoE GroupGEMM-RS both run it):

    1. Entry barrier (slots + semaphores are reused across calls).
    2. Own-segment-last swizzle: for each remote segment,
       ``emit(seg, dst_ref)`` computes that segment's partial into a
       double-buffered stage slot (reused every 2 steps, guarded by the
       send semaphore of the put issued 2 steps earlier), then a
       non-blocking put ships it to the owner's symm slot ``me``.
    3. Own segment: emitted straight into our own slot (never travels).
    4. Drain the last sends, wait each peer's arrival once.

    The caller runs its reduction over ``ws_ref``'s n slots afterwards.

    ``axis`` may be a tuple of mesh axes — the PE group is their flattened
    product (used by the hierarchical GEMM-RS for its fast-tier stage).
    """
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    group = (axis,) if isinstance(axis, str) else tuple(axis)
    shd.barrier_all(group, mesh_axes=mesh_axes)

    rdmas = [None] * max(n - 1, 0)
    for s in range(n - 1):
        seg = lax.rem(me + 1 + s, n)
        slot = s % 2
        if s >= 2:
            rdmas[s - 2].wait_send()  # stage slot free again
        emit(seg, stage_ref.at[slot])
        pid = shd.pe_at_group(mesh_axes, axis, seg)
        rdmas[s] = shd.putmem_nbi(ws_ref.at[me], stage_ref.at[slot],
                                  send_sems.at[slot], recv_sems.at[me], pid)

    emit(me, ws_ref.at[me])

    for s in range(max(n - 3, 0), n - 1):
        rdmas[s].wait_send()
    for p in range(1, n):
        src = lax.rem(me + p, n)
        shd.wait_recv(ws_ref.at[src], recv_sems.at[src])


def emit_slot_reduction(ws_ref, out_ref, bm: int, bn: int):
    """Pipelined VPU sum over ``ws_ref``'s [n, M, N] partial slots into
    ``out_ref`` [M, N]. Tile sizes fall back to divisors of the actual
    shape so ragged dims never silently drop rows/columns."""
    import math

    n = ws_ref.shape[0]
    m_seg, N = out_ref.shape
    bm = math.gcd(min(bm, m_seg), m_seg)
    bn = math.gcd(min(bn, N), N)

    def body(ws_blk, o_blk):
        o_blk[...] = jnp.sum(
            ws_blk[...].astype(jnp.float32), axis=0
        ).astype(out_ref.dtype)

    pltpu.emit_pipeline(
        body,
        grid=(m_seg // bm, N // bn),
        in_specs=[pl.BlockSpec((n, bm, bn), lambda i, j: (0, i, j))],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )(ws_ref, out_ref)


def _gemm_rs_kernel(axis, mesh_axes, cfg, acc_dtype,
                    a_ref, b_ref, out_ref, ws_ref, stage_ref,
                    send_sems, recv_sems):
    m_seg = out_ref.shape[0]

    def emit(seg, dst_ref):
        emit_gemm(a_ref.at[pl.ds(seg * m_seg, m_seg)], b_ref, dst_ref,
                  cfg, acc_dtype)

    rs_overlap_protocol(axis, mesh_axes, ws_ref, stage_ref,
                        send_sems, recv_sems, emit)
    emit_slot_reduction(ws_ref, out_ref, cfg.block_m, cfg.block_n)


def _gemm_rs_2d_stage_kernel(axes, mesh_axes, cfg, acc_dtype,
                             a_ref, b_ref, red_ref, ws_ref, stage_ref,
                             send_sems, recv_sems):
    """Fast-tier stage of the hierarchical GEMM-RS: fused producer GEMM +
    inner-group RS. The "segment" owned by inner peer ``j`` is the *strided*
    row set {(r, j) : r < no} in outer-major block order, so the surviving
    chunk is laid out ready for the outer-axis ring — no re-permute (the
    role of the reference's scatter layout, reduce_scatter.py:527-561)."""
    outer, inner = axes[0], tuple(axes[1:])
    no = shd.n_pes(outer)
    ni = shd.n_pes(inner)
    m_seg = red_ref.shape[0] // no

    def emit(j, dst_ref):
        for r in range(no):
            emit_gemm(a_ref.at[pl.ds((r * ni + j) * m_seg, m_seg)], b_ref,
                      dst_ref.at[pl.ds(r * m_seg, m_seg)], cfg, acc_dtype)

    rs_overlap_protocol(inner, mesh_axes, ws_ref, stage_ref,
                        send_sems, recv_sems, emit)
    emit_slot_reduction(ws_ref, red_ref, cfg.block_m, cfg.block_n)


def _gemm_rs_xla(ctx, a, b, axis, out_dtype):
    """XLA-collective GEMM-RS for a scatter axis that crosses slice
    boundaries (``is_dcn_axis``): remote DMA cannot cross DCN, so the
    partial GEMM runs as a plain sharded dot and ``psum_scatter`` routes
    the reduction over the right transport — the same per-op DCN routing
    ``reduce_scatter``/``all_gather`` apply (reduce_scatter.py), and the
    RS twin of ``allgather_gemm._ag_gemm_dcn``. Segment order matches the
    ring path (the golden the Pallas kernel is tested against)."""
    out_dtype = out_dtype or a.dtype
    acc_dtype = jnp.float32 if out_dtype == jnp.bfloat16 else out_dtype

    def f(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard, preferred_element_type=acc_dtype)
        return lax.psum_scatter(part, axis, scatter_dimension=0,
                                tiled=True).astype(out_dtype)

    sm = ctx.shard_map(f, in_specs=(P(None, axis), P(axis, None)),
                       out_specs=P(axis))
    return sm(a, b)


def _gemm_rs_2d(ctx, a, b, axes, cfg, out_dtype, ws=None, stage=None):
    """Hierarchical 2-tier GEMM-RS over ``axes = (outer, *inner)`` — the
    inter-node analog of ``gemm_rs`` (reference 2-D RS pipeline,
    reduce_scatter.py:430-785: intra-node scatter + per-node reduce +
    inter-node tier). Stage 1 fuses the producer GEMM into a fast-tier
    (inner-group) RS; stage 2 ring-reduces the surviving chunk along the
    slow outer axis — each row crosses the slow tier exactly once, already
    reduced over the fast tier. With ``ws``/``stage`` the fast-tier
    buffers are persistent aliased operands (returned for re-threading)."""
    from triton_dist_tpu.ops.reduce_scatter import _rs_call

    cfg = cfg or _default_cfg(ctx, a, b, axes)
    out_dtype = out_dtype or a.dtype
    acc_dtype = jnp.float32 if out_dtype == jnp.bfloat16 else out_dtype
    mesh_axes = ctx.axis_names
    outer, inner = axes[0], tuple(axes[1:])
    inner_dcn = tuple(ax for ax in inner if ctx.is_dcn_axis(ax))
    if inner_dcn:
        raise ValueError(
            f"DCN (slice-crossing) axes {inner_dcn} must come first in the "
            f"hierarchical axis tuple {axes} — put the slow tier outermost "
            "(the fast-tier stage is remote DMA, which cannot cross DCN; "
            "cf. gemm_rs docstring)")
    # DCN outer tier: the fast-tier fused GEMM+RS stays Pallas, the slow
    # outer ring becomes an XLA psum_scatter (same surviving-chunk layout,
    # same segment order — only the transport changes)
    dcn_outer = ctx.is_dcn_axis(outer)
    no, ni = ctx.axis_size(outer), ctx.axis_size(inner)
    n, M, _K, N, m_seg, cfg = _validate(ctx, a, b, axes, cfg)
    chunk = no * m_seg
    persistent = ws is not None
    if persistent:
        assert ws.shape == (n, ni, chunk, N) and ws.dtype == acc_dtype, (
            f"ws {ws.shape}/{ws.dtype} != ({n}, {ni}, {chunk}, {N})/"
            f"{acc_dtype} — create it with create_gemm_rs_workspace("
            f"ctx, m_seg={m_seg}, n_cols={N}, axis={axes})")
        assert stage.shape == (n, 2, chunk, N) and stage.dtype == acc_dtype

    def f(a_shard, b_shard, *persist):
        common = dict(
            out_shape=(jax.ShapeDtypeStruct((chunk, N), acc_dtype),
                       jax.ShapeDtypeStruct((ni, chunk, N), acc_dtype),
                       jax.ShapeDtypeStruct((2, chunk, N), acc_dtype)),
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 3,
            scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((ni,))],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"gemm_rs_{axes}")),
            cost_estimate=pl.CostEstimate(
                flops=2 * M * N * a_shard.shape[1],
                bytes_accessed=(a_shard.size + b_shard.size)
                * jnp.dtype(a_shard.dtype).itemsize
                # red + ws[ni] + stage[2] outputs, all [chunk, N] acc-dtype
                + (ni + 3) * chunk * N * jnp.dtype(acc_dtype).itemsize,
                transcendentals=0),
            interpret=default_interpret(),
        )
        if persistent:
            kernel = lambda a_r, b_r, ws_in, st_in, red_r, ws_r, st_r, \
                *sems: _gemm_rs_2d_stage_kernel(
                    axes, mesh_axes, cfg, acc_dtype, a_r, b_r, red_r,
                    ws_r, st_r, *sems)
            ws_s = persist[0].reshape(ni, chunk, N)
            st_s = persist[1].reshape(2, chunk, N)
            red, ws_o, st_o = pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
                input_output_aliases={2: 1, 3: 2},
                **common,
            )(a_shard, b_shard, ws_s, st_s)
        else:
            kernel = lambda a_r, b_r, red_r, ws_r, st_r, *sems: \
                _gemm_rs_2d_stage_kernel(axes, mesh_axes, cfg, acc_dtype,
                                         a_r, b_r, red_r, ws_r, st_r, *sems)
            red, ws_o, st_o = pl.pallas_call(
                kernel,
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
                **common,
            )(a_shard, b_shard)
        if dcn_outer:
            out = lax.psum_scatter(red, outer, scatter_dimension=0,
                                   tiled=True).astype(out_dtype)
        else:
            out = _rs_call(outer, mesh_axes, no, red).astype(out_dtype)
        if persistent:
            return (out, ws_o.reshape(persist[0].shape),
                    st_o.reshape(persist[1].shape))
        return out

    if persistent:
        sm = ctx.shard_map(
            f, in_specs=(P(None, axes), P(axes, None), P(axes), P(axes)),
            out_specs=(P(axes), P(axes), P(axes)))
        return sm(a, b, ws, stage)
    sm = ctx.shard_map(f, in_specs=(P(None, axes), P(axes, None)),
                       out_specs=P(axes))
    return sm(a, b)


def _default_cfg(ctx, a, b, axis) -> GemmConfig:
    """Shape-keyed default tiles (measured-best table, docs/benchmarks.md):
    the per-segment GEMM here is [M/n, K/n] x [K/n, N]."""
    n = ctx.axis_size(axis)
    M, K = a.shape
    return best_gemm_config(max(M // n, 1), b.shape[1], max(K // n, 1),
                            jnp.dtype(a.dtype).itemsize)


def _validate(ctx, a, b, axis, cfg):
    n = ctx.axis_size(axis)
    M, K = a.shape
    Kb, N = b.shape
    assert K == Kb, f"A/B inner dims {K} vs {Kb}"
    if not default_interpret() and (K // n) % 128:
        raise ValueError(
            f"gemm_rs on compiled TPU needs a lane-multiple K shard: K={K} "
            f"over {n} ranks gives K_local={K // n} (Mosaic tiles lanes by "
            "128; the interpret-mode simulator does not enforce this)")
    assert M % n == 0, f"M={M} not divisible by ranks {n}"
    m_seg = M // n
    # clamp tiles to the segment, then require exact divisibility
    cfg = GemmConfig(block_m=min(cfg.block_m, m_seg),
                     block_n=min(cfg.block_n, N), block_k=cfg.block_k)
    assert m_seg % cfg.block_m == 0, (
        f"segment rows {m_seg} not divisible by block_m {cfg.block_m}")
    assert N % cfg.block_n == 0, (
        f"N={N} not divisible by block_n {cfg.block_n}")
    k_local_g = K // n
    assert cfg.vmem_ok(k_local_g, jnp.dtype(a.dtype).itemsize), (
        f"tile config exceeds VMEM budget for K_local={k_local_g}")
    return n, M, K, N, m_seg, cfg


def _pallas_gemm_rs(axis, mesh_axes, cfg, acc_dtype, out_dtype, n, M, N,
                    m_seg, a_shard, b_shard, ws_shard=None, stage_shard=None):
    """Shared pallas_call builder: fresh workspace outputs (legacy), or
    persistent aliased workspace+stage buffers when provided."""
    k_local = a_shard.shape[1]
    common = dict(
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=collective_id_for(f"gemm_rs_{axis}")),
        cost_estimate=pl.CostEstimate(
            flops=2 * M * N * k_local,
            bytes_accessed=(a_shard.size + b_shard.size + m_seg * N)
            * jnp.dtype(a_shard.dtype).itemsize,
            transcendentals=0),
        interpret=default_interpret(),
    )
    out_shapes = (
        jax.ShapeDtypeStruct((m_seg, N), out_dtype),
        jax.ShapeDtypeStruct((n, m_seg, N), acc_dtype),   # symm slots
        jax.ShapeDtypeStruct((2, m_seg, N), acc_dtype),   # send stage
    )
    if ws_shard is None:
        kernel = lambda a_r, b_r, o_r, ws_r, st_r, *sems: _gemm_rs_kernel(
            axis, mesh_axes, cfg, acc_dtype, a_r, b_r, o_r, ws_r, st_r, *sems)
        out, _ws, _stage = pl.pallas_call(
            kernel,
            out_shape=out_shapes,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 3,
            **common,
        )(a_shard, b_shard)
        return out, None, None
    kernel = lambda a_r, b_r, ws_in, st_in, o_r, ws_r, st_r, *sems: \
        _gemm_rs_kernel(axis, mesh_axes, cfg, acc_dtype,
                        a_r, b_r, o_r, ws_r, st_r, *sems)
    out, ws_out, stage_out = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 3,
        input_output_aliases={2: 1, 3: 2},
        **common,
    )(a_shard, b_shard, ws_shard, stage_shard)
    return out, ws_out, stage_out


def gemm_rs(ctx: ShmemContext, a: jax.Array, b: jax.Array,
            axis=None, cfg: GemmConfig | None = None,
            out_dtype=None) -> jax.Array:
    """Row-parallel GEMM + ReduceScatter: ``a`` [M, K] sharded P(None, axis),
    ``b`` [K, N] sharded P(axis, None). Returns sum_r(a_r @ b_r) scattered
    over M — global [M, N] sharded P(axis). Entry analog: ``gemm_rs``
    (gemm_reduce_scatter.py:524-538); golden: dot + psum_scatter.

    ``axis`` may be a tuple ``(outer, inner…)`` spanning a multi-axis mesh —
    the hierarchical 2-tier path (fused GEMM + fast-tier RS, then a
    slow-tier ring — see ``_gemm_rs_2d``), the TPU analog of the
    reference's inter-node GEMM-RS (tutorial 08 + reduce_scatter.py:430-785).
    Put the slow tier (DCN/inter-slice) first.

    Allocates fresh workspace/stage buffers per call; for repeated calls use
    ``gemm_rs_ws`` / ``GemmRsContext`` (reference parity:
    create_gemm_rs_context, gemm_reduce_scatter.py:77-87)."""
    axis = _norm_axis(ctx, axis)
    if isinstance(axis, tuple):
        return _gemm_rs_2d(ctx, a, b, axis, cfg, out_dtype)
    if ctx.is_dcn_axis(axis):
        # slice-crossing scatter axis: XLA collectives end to end (remote
        # DMA cannot cross DCN) — mirrors reduce_scatter/all_gather routing
        return _gemm_rs_xla(ctx, a, b, axis, out_dtype)
    cfg = cfg or _default_cfg(ctx, a, b, axis)
    out_dtype = out_dtype or a.dtype
    acc_dtype = jnp.float32 if out_dtype == jnp.bfloat16 else out_dtype
    mesh_axes = ctx.axis_names
    n, M, K, N, m_seg, cfg = _validate(ctx, a, b, axis, cfg)

    def f(a_shard, b_shard):
        out, _, _ = _pallas_gemm_rs(axis, mesh_axes, cfg, acc_dtype,
                                    out_dtype, n, M, N, m_seg,
                                    a_shard, b_shard)
        return out

    sm = ctx.shard_map(f, in_specs=(P(None, axis), P(axis, None)),
                       out_specs=P(axis))
    return sm(a, b)


def gemm_rs_ws(ctx: ShmemContext, a: jax.Array, b: jax.Array,
               ws: jax.Array, stage: jax.Array,
               axis: str | None = None, cfg: GemmConfig | None = None,
               out_dtype=None) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Workspace-threading GEMM-RS: symmetric slots + send stage are explicit
    aliased operands, returned for re-threading. Jit with ``donate_argnums``
    on both (or carry through ``lax.scan``) for zero per-call allocation.
    Create them with ``create_gemm_rs_workspace``. ``axis`` may be a tuple
    (hierarchical 2-tier path: the fast-tier chunk buffers persist; the
    slow-tier ring uses VMEM relay slots, nothing to persist)."""
    axis = _norm_axis(ctx, axis)
    if isinstance(axis, tuple):
        return _gemm_rs_2d(ctx, a, b, axis, cfg, out_dtype,
                           ws=ws, stage=stage)
    if ctx.is_dcn_axis(axis):
        # XLA path needs no symmetric workspace; thread the buffers back
        # untouched so callers' donate/scan plumbing is shape-stable
        return _gemm_rs_xla(ctx, a, b, axis, out_dtype), ws, stage
    cfg = cfg or _default_cfg(ctx, a, b, axis)
    out_dtype = out_dtype or a.dtype
    acc_dtype = jnp.float32 if out_dtype == jnp.bfloat16 else out_dtype
    mesh_axes = ctx.axis_names
    n, M, K, N, m_seg, cfg = _validate(ctx, a, b, axis, cfg)
    assert ws.shape == (n, n, m_seg, N) and ws.dtype == acc_dtype, (
        f"ws {ws.shape}/{ws.dtype} != ({n}, {n}, {m_seg}, {N})/{acc_dtype}")
    assert stage.shape == (n, 2, m_seg, N) and stage.dtype == acc_dtype, (
        f"stage {stage.shape}/{stage.dtype} != ({n}, 2, {m_seg}, {N})/"
        f"{acc_dtype}")

    def f(a_shard, b_shard, ws_shard, stage_shard):
        out, ws_out, stage_out = _pallas_gemm_rs(
            axis, mesh_axes, cfg, acc_dtype, out_dtype, n, M, N, m_seg,
            a_shard, b_shard, ws_shard.reshape(n, m_seg, N),
            stage_shard.reshape(2, m_seg, N))
        return (out, ws_out.reshape(ws_shard.shape),
                stage_out.reshape(stage_shard.shape))

    sm = ctx.shard_map(
        f, in_specs=(P(None, axis), P(axis, None), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis)))
    return sm(a, b, ws, stage)


def create_gemm_rs_workspace(ctx: ShmemContext, m_seg: int, n_cols: int,
                             out_dtype=jnp.bfloat16, axis=None
                             ) -> tuple[jax.Array, jax.Array]:
    """(symm partial slots, send stage) for ``gemm_rs_ws``; dtypes follow the
    accumulator rule (f32 for bf16 outputs). With a tuple ``axis`` the
    slots are the fast-tier chunk buffers ([ni, no*m_seg, n_cols])."""
    axis = _norm_axis(ctx, axis)
    acc_dtype = jnp.float32 if out_dtype == jnp.bfloat16 else out_dtype
    if isinstance(axis, tuple):
        no, ni = ctx.axis_size(axis[0]), ctx.axis_size(tuple(axis[1:]))
        chunk = no * m_seg
        ws = ctx.create_symm_tensor((ni, chunk, n_cols), acc_dtype,
                                    axis=axis)
        stage = ctx.create_symm_tensor((2, chunk, n_cols), acc_dtype,
                                       axis=axis)
        return ws, stage
    n = ctx.axis_size(axis)
    ws = ctx.create_symm_tensor((n, m_seg, n_cols), acc_dtype, axis=axis)
    stage = ctx.create_symm_tensor((2, m_seg, n_cols), acc_dtype, axis=axis)
    return ws, stage


@dataclasses.dataclass
class GemmRsContext:
    """Stateful sugar over ``gemm_rs_ws`` — see ``AgGemmContext``."""
    ctx: ShmemContext
    axis: str
    ws: jax.Array
    stage: jax.Array
    _steps: dict = dataclasses.field(default_factory=dict)

    def __call__(self, a: jax.Array, b: jax.Array,
                 cfg: GemmConfig | None = None, out_dtype=None) -> jax.Array:
        from triton_dist_tpu.ops.common import lru_step, require_eager
        require_eager("GemmRsContext", "gemm_rs_ws")
        key = (a.shape, b.shape, str(a.dtype), cfg, out_dtype)
        step = lru_step(self._steps, key, lambda: jax.jit(
            lambda ws, stage, a, b: gemm_rs_ws(
                self.ctx, a, b, ws, stage, axis=self.axis, cfg=cfg,
                out_dtype=out_dtype),
            donate_argnums=(0, 1)))
        c, self.ws, self.stage = step(self.ws, self.stage, a, b)
        return c


def create_gemm_rs_context(ctx: ShmemContext, m_seg: int, n_cols: int,
                           out_dtype=jnp.bfloat16,
                           axis: str | None = None) -> GemmRsContext:
    axis = axis or ctx.axis_names[0]
    ws, stage = create_gemm_rs_workspace(ctx, m_seg, n_cols, out_dtype, axis)
    return GemmRsContext(ctx=ctx, axis=axis, ws=ws, stage=stage)


__all__ = ["gemm_rs", "gemm_rs_ws", "create_gemm_rs_workspace",
           "create_gemm_rs_context", "GemmRsContext"]
