"""Autotuned entry points for the overlap ops — the reference wraps its
AG-GEMM/GEMM-RS thunks in ``contextual_autotune`` the same way
(docs/autotuner.md; autotuner.py:247-256).

Candidate tile configs are pruned by shape divisibility and VMEM budget
before timing, and every process agrees on the winner (consensus in
tools.autotuner)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_dist_tpu.ops.allgather_gemm import ag_gemm
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.tools.autotuner import contextual_autotune

_CANDIDATES = [
    GemmConfig(128, 128), GemmConfig(128, 256), GemmConfig(256, 128),
    GemmConfig(256, 256), GemmConfig(512, 256), GemmConfig(256, 512),
    GemmConfig(64, 128), GemmConfig(32, 64),
    # tall K-split tiles: fit large K under the scoped-VMEM budget and
    # amortize B-strip reloads at large N (measured ~2x at 70B/405B shapes)
    GemmConfig(256, 256, 4096), GemmConfig(512, 256, 2048),
    GemmConfig(1024, 256, 1024), GemmConfig(1024, 384, 1024),
    # square half-MB output tiles: best measured at the 4096^3 headline
    # shape on v5e (179 vs 158 TFLOP/s, docs/benchmarks.md)
    GemmConfig(512, 512, 2048), GemmConfig(512, 1024, 1024),
]


def _axis_of(ctx, args, kw):
    if len(args) > 3 and args[3] is not None:
        return args[3]
    return kw.get("axis") or ctx.axis_names[0]


def _prune_ag(cfg: GemmConfig, args, kw) -> bool:
    ctx, a, b = args[:3]
    n = ctx.axis_size(_axis_of(ctx, args, kw))
    M, K = a.shape
    n_local = b.shape[1] // n
    return ((M // n) % cfg.block_m == 0 and n_local % cfg.block_n == 0
            and cfg.vmem_ok(K, jnp.dtype(a.dtype).itemsize))


def _prune_rs(cfg: GemmConfig, args, kw) -> bool:
    ctx, a, b = args[:3]
    n = ctx.axis_size(_axis_of(ctx, args, kw))
    M, K = a.shape
    N = b.shape[1]
    return ((M // n) % cfg.block_m == 0 and N % cfg.block_n == 0
            and cfg.vmem_ok(K // n, jnp.dtype(a.dtype).itemsize))


_ag_jit = jax.jit(ag_gemm, static_argnums=(0,),
                  static_argnames=("axis", "cfg", "out_dtype"))
_rs_jit = jax.jit(gemm_rs, static_argnums=(0,),
                  static_argnames=("axis", "cfg", "out_dtype"))


@contextual_autotune(configs=_CANDIDATES, prune=_prune_ag, op="ag_gemm")
def ag_gemm_autotuned(ctx: ShmemContext, a: jax.Array, b: jax.Array,
                      axis: str | None = None, cfg: GemmConfig | None = None,
                      out_dtype=None) -> jax.Array:
    return _ag_jit(ctx, a, b, axis=axis, cfg=cfg, out_dtype=out_dtype)


@contextual_autotune(configs=_CANDIDATES, prune=_prune_rs, op="gemm_rs")
def gemm_rs_autotuned(ctx: ShmemContext, a: jax.Array, b: jax.Array,
                      axis: str | None = None, cfg: GemmConfig | None = None,
                      out_dtype=None) -> jax.Array:
    return _rs_jit(ctx, a, b, axis=axis, cfg=cfg, out_dtype=out_dtype)


_MOE_BLOCK_CANDIDATES = [32, 64, 128, 256]


def _moe_vmem_ok(bm: int, k_local: int, itemsize: int) -> bool:
    # the grouped pipeline streams (bm, k_local) token strips against
    # (k_local, 128) expert tiles — same budget rule as the dense GEMM
    return GemmConfig(bm, 128).vmem_ok(k_local, itemsize)


def _prune_moe_ag(bm: int, args, kw) -> bool:
    tokens = args[1]   # [T, H] sharded on T — each device holds full H rows
    return _moe_vmem_ok(bm, tokens.shape[-1],
                        jnp.dtype(tokens.dtype).itemsize)


def _prune_moe_rs(bm: int, args, kw) -> bool:
    ctx, tokens = args[0], args[1]   # [T*topk, K] sharded P(None, axis) on K
    axis = (args[5] if len(args) > 5 and args[5] is not None
            else kw.get("axis")) or ctx.axis_names[0]
    k_local = tokens.shape[-1] // ctx.axis_size(axis)
    return _moe_vmem_ok(bm, k_local, jnp.dtype(tokens.dtype).itemsize)


from triton_dist_tpu.ops.moe import (ag_moe_group_gemm,  # noqa: E402
                                     moe_reduce_rs)

_moe_ag_jit = jax.jit(ag_moe_group_gemm, static_argnums=(0,),
                      static_argnames=("axis", "block_m"))
_moe_rs_jit = jax.jit(moe_reduce_rs, static_argnums=(0,),
                      static_argnames=("axis", "block_m"))


@contextual_autotune(configs=_MOE_BLOCK_CANDIDATES, prune=_prune_moe_ag,
                     op="ag_moe_group_gemm")
def ag_moe_group_gemm_autotuned(ctx: ShmemContext, tokens, ids, weights,
                                axis: str | None = None, cfg=None):
    """``ag_moe_group_gemm`` with the grouped-GEMM block size tuned per
    shape (cfg = block_m), reference-style (docs/autotuner.md)."""
    return _moe_ag_jit(ctx, tokens, ids, weights, axis=axis, block_m=cfg)


@contextual_autotune(configs=_MOE_BLOCK_CANDIDATES, prune=_prune_moe_rs,
                     op="moe_reduce_rs")
def moe_reduce_rs_autotuned(ctx: ShmemContext, tokens, ids, topk_weights,
                            weights, axis: str | None = None, cfg=None):
    return _moe_rs_jit(ctx, tokens, ids, topk_weights, weights, axis=axis,
                       block_m=cfg)


# grouped GEMM: tune the (block_m, block_n) tile pair (VERDICT r4 Missing
# #5 — the reference tunes its grouped kernels through the same
# contextual_autotune machinery, docs/autotuner.md). Alignment tables
# depend on block_m ([P // bm] block_expert), so the tunable surface takes
# raw (tokens, ids) and builds the alignment per candidate — exactly what
# a caller does. block_m trades padding compute (small bm = tighter
# packing) against per-expert weight re-streaming (each used block streams
# its expert's full weight tiles once): at few-tokens-per-expert shapes
# the sweep is the only honest way to pick.
_GG_CANDIDATES = [(64, 128), (64, 256), (128, 128), (128, 256), (128, 512),
                  (256, 128), (256, 256), (512, 256)]


def _prune_gg(cfg, args, kw) -> bool:
    tokens, weights = args[0], args[2]
    bm, bn = cfg
    H = tokens.shape[-1]
    bn = min(bn, weights.shape[-1])
    itemsize = jnp.dtype(tokens.dtype).itemsize
    # x strip + (possibly two) weight tiles double-buffered + f32 acc
    n_w = 2 if len(args) > 3 and hasattr(args[3], "shape") else 1
    vmem = 2 * itemsize * (bm * H + n_w * H * bn) + 4 * bm * bn * (n_w + 1)
    return vmem <= 14 * 2**20


import functools  # noqa: E402

from triton_dist_tpu.ops.group_gemm import (apply_grouped,  # noqa: E402
                                            grouped_gemm, grouped_gemm_gated)


@functools.partial(jax.jit, static_argnames=("num_experts", "bm", "bn"))
def _gg_run(tokens, ids, weights, num_experts, bm, bn):
    def f(x, be, nb):
        return grouped_gemm(x, weights, be, block_m=bm, block_n=bn,
                            n_blocks_used=nb, masked=False)

    return apply_grouped(tokens, ids, num_experts, f, block_m=bm)


@functools.partial(jax.jit, static_argnames=("bm", "bn"))
def _ffn_run(tokens, ids, w_gate, w_up, w_down, bm, bn):
    def f(x, be, nb):
        h = grouped_gemm_gated(x, w_gate, w_up, be, block_m=bm, block_n=bn,
                               n_blocks_used=nb, masked=False)
        # down gemm at the SAME bn the winner deploys with: 512,
        # moe_mlp_ep_overlap's down_block_n default (measured best — see
        # docs/benchmarks.md tile sweep). The autotuner must measure the
        # configuration it selects, so the candidate's bn applies only to
        # the gated kernel, exactly as deployment does.
        return grouped_gemm(h, w_down, be, block_m=bm, block_n=512,
                            n_blocks_used=nb, masked=False)

    return apply_grouped(tokens, ids, w_gate.shape[0], f, block_m=bm)


@contextual_autotune(configs=_GG_CANDIDATES, prune=_prune_gg,
                     op="grouped_gemm")
def grouped_gemm_autotuned(tokens, ids, weights,
                           num_experts: int | None = None, cfg=None):
    """Single grouped GEMM over (tokens [T,H], ids [T], weights [E,H,N])
    with the alignment built in and (block_m, block_n) tuned per shape."""
    bm, bn = cfg if cfg is not None else (128, 128)
    return _gg_run(tokens, ids, weights, num_experts or weights.shape[0],
                   bm, bn)


@contextual_autotune(configs=_GG_CANDIDATES, prune=_prune_gg,
                     op="moe_ffn_gated")
def moe_ffn_gated_autotuned(tokens, ids, w_gate, w_up, w_down, cfg=None):
    """The EP serving block's expert-FFN stage (fused gate+up+act grouped
    GEMM, then the down grouped GEMM) with (block_m, block_n) tuned per
    shape — the winner feeds ``moe_mlp_ep_overlap(block_m=..., block_n=...)``."""
    bm, bn = cfg if cfg is not None else (128, 128)
    return _ffn_run(tokens, ids, w_gate, w_up, w_down, bm, bn)


# ring attention: tune the (block_q, block_k) tile pair — measured range
# on v5e at S=4096: 52.9 (512^2) -> 83.1 (1024^2) TFLOP/s with the old
# f32-operand kernel. 2048-tall/square tiles can NEVER fit: the f32
# score+p intermediates alone are >= 16 MB at D=128. What bf16 operands
# DO enable is the wide-bk asymmetric tile (512, 2048) — its q/k/v
# pipeline blocks halve, bringing it under budget (the prune below is
# dtype-aware so it stays excluded for f32 inputs). `bench.py
# --attn-sweep` sweeps this list plus over-budget probes of the cliff.
_ATTN_CANDIDATES = [(512, 512), (512, 1024), (1024, 512), (1024, 1024),
                    (512, 2048), (1024, 2048), (2048, 512), (256, 512),
                    (256, 256)]


def _prune_attn(bqbk, args, kw) -> bool:
    q = args[1]
    D = q.shape[-1]
    bq, bk = bqbk
    itemsize = jnp.dtype(q.dtype).itemsize
    # q + k + v pipeline blocks (input dtype, double-buffered) + packed
    # [acc||m||l] f32 state (carry blocks double-buffered + the VMEM
    # scratch accumulator) + one f32 s_ij/p intermediate. Calibrated
    # against Mosaic's 16 MB scoped-VMEM limit by the round-4 on-chip
    # sweep: (2048,512) and (1024,2048) compile, (2048,1024) and
    # (4096,512) are rejected — this formula reproduces exactly that
    # boundary. A margin below 16 MiB would wrongly prune (1024,2048),
    # which measures competitively — so the formula stays exact, and a
    # candidate this formula admits on some other head dim/toolchain that
    # the real Mosaic boundary rejects degrades gracefully: the autotuner
    # catches per-candidate compile failures and skips them
    # (tools/autotuner.py, the FAILED log path).
    vmem = (2 * itemsize * (bq + 2 * bk) * D
            + 3 * 4 * bq * (D + 256)
            + 4 * bq * bk)
    return vmem <= 16 * 2**20


from triton_dist_tpu.ops.ring_attention import ring_attention  # noqa: E402

_attn_jit = jax.jit(
    ring_attention, static_argnums=(0,),
    static_argnames=("axis", "causal", "sm_scale", "block_q", "block_k",
                     "batch_axis", "head_axis", "layout"))


@contextual_autotune(configs=_ATTN_CANDIDATES, prune=_prune_attn,
                     op="ring_attention")
def ring_attention_autotuned(ctx: ShmemContext, q, k, v,
                             axis: str | None = None, causal: bool = True,
                             layout: str = "contiguous", cfg=None):
    bq, bk = cfg if cfg is not None else (1024, 1024)
    return _attn_jit(ctx, q, k, v, axis=axis, causal=causal,
                     layout=layout, block_q=bq, block_k=bk)


__all__ = ["ag_gemm_autotuned", "gemm_rs_autotuned",
           "ag_moe_group_gemm_autotuned", "moe_reduce_rs_autotuned",
           "grouped_gemm_autotuned", "moe_ffn_gated_autotuned",
           "ring_attention_autotuned"]
