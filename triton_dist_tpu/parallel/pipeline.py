"""Pipeline parallelism: GPipe-style microbatch wavefront over a ``pp`` mesh
axis via ``shard_map`` + ``lax.ppermute``.

Out of scope for the reference (a kernel-level TP/EP/SP library — SURVEY.md
§2.4 notes DP/PP are absent), but jax composition makes it nearly free, and
the driver's multi-chip dryrun exercises it. Design: the stacked per-layer
params are sharded over ``pp`` on the layer dim; all stages run the same
``T = n_micro + P - 1``-step scan; stage 0 injects microbatches, activations
hop stage→stage+1 through ``ppermute`` (differentiable, so ``jax.grad``
through the whole pipeline yields the standard GPipe backward schedule).
Partial-manual ``shard_map`` (manual over ``pp`` only) leaves dp/tp sharding
inside each stage to GSPMD.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x_micro: jax.Array,
                   axis: str = "pp", with_aux: bool = False):
    """Run inside ``shard_map`` (manual over ``axis``).

    stage_fn(stage_params, h) -> h : this stage's chunk of the network
    (with ``with_aux``: ``-> (h, aux_scalar)`` — e.g. MoE balance loss).
    stage_params: params for the local layer chunk (leading layer dim already
    sliced by shard_map).
    x_micro: [n_micro, mb, ...] microbatched input (same on every stage;
    only stage 0 reads it).
    Returns [n_micro, mb, ...] outputs, valid on the LAST stage and zeros
    elsewhere — callers ``psum`` over ``axis`` to broadcast. With
    ``with_aux``: ``(outs, aux_total)`` where aux is summed over real work
    steps only (pipeline bubbles run stage_fn on garbage activations; their
    aux must not pollute the loss) and psum-reduced over stages.
    """
    n_stages = lax.axis_size(axis)
    stage = lax.axis_index(axis)
    n_micro = x_micro.shape[0]
    steps = n_micro + n_stages - 1
    state0 = jnp.zeros_like(x_micro[0])
    outs0 = jnp.zeros_like(x_micro)

    def step(carry, t):
        state, outs, aux_acc = carry
        # stage 0 injects microbatch t; later stages consume last hop's recv
        inject = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), keepdims=False)
        h_in = jnp.where(stage == 0, inject, state)
        if with_aux:
            h_out, aux = stage_fn(stage_params, h_in)
            # real work ⇔ this step's activation is microbatch (t - stage)
            working = ((t - stage >= 0)
                       & (t - stage < n_micro)).astype(jnp.float32)
            aux_acc = aux_acc + aux.astype(jnp.float32) * working
        else:
            h_out = stage_fn(stage_params, h_in)
        # last stage stores microbatch (t - (P-1)) when it's valid
        out_idx = t - (n_stages - 1)
        valid = (stage == n_stages - 1) & (out_idx >= 0)
        idx = jnp.clip(out_idx, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(outs, idx, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(valid, h_out, cur), idx, 0)
        # hop to the next stage (wrap-around to 0 is ignored — stage 0
        # overwrites with its injection)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        state = lax.ppermute(h_out, axis, perm)
        return (state, outs, aux_acc), None

    (_, outs, aux_acc), _ = lax.scan(
        step, (state0, outs0, jnp.float32(0)),
        jnp.arange(steps, dtype=jnp.int32))
    # broadcast the last stage's outputs to every stage (f32 psum: XLA CPU's
    # AllReducePromotion pass check-fails cloning a bf16 all-reduce here)
    is_last = (stage == n_stages - 1).astype(jnp.float32)
    outs = lax.psum(outs.astype(jnp.float32) * is_last,
                    axis).astype(outs.dtype)
    if with_aux:
        return outs, lax.psum(aux_acc, axis) / n_micro
    return outs


__all__ = ["pipeline_apply"]
