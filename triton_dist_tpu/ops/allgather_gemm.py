"""AllGather-GEMM overlap (analog of reference
python/triton_dist/kernels/nvidia/allgather_gemm.py).

The reference overlaps a copy-engine allgather producer with a persistent
consumer GEMM on separate CUDA streams, synchronized by per-rank flags that
GEMM tiles spin-wait on, with a rank-swizzle so each rank computes its local
segment first (allgather_gemm.py:203-217, :222-225, :405-527).

TPU-native design — ONE kernel per device, no streams:

1. On entry, a light barrier (cf. ``local_copy_and_barrier_all``,
   allgather_gemm.py:99-116) protects the symmetric workspace across calls.
2. Issue *all* remote puts of the local A-shard into every peer's workspace
   slot ``me`` as non-blocking DMAs. The ICI DMA engines are the
   "copy-engine producer" running in the background of compute.
3. Walk segments in swizzled order ``me, me+1, …`` (start-local trick).
   The FIRST segment is always our own shard, so its GEMM reads ``a_ref``
   directly — no workspace copy, no wait: compute starts immediately while
   every transfer is in flight (one better than the reference, which
   local-copies into the symm buffer first, allgather_gemm.py:99-116).
   Each remote segment waits its receive semaphore once (TPU grids are
   sequential per core — no per-tile spin flags needed), then runs the
   pipelined MXU GEMM via ``emit_gemm``.

Steady state overlaps segment s's GEMM with segment s+1's arrival — same
overlap structure, no CUDA-stream machinery. The n=1 degenerate case leaves
barrier + MXU pipeline only, preserving full single-chip GEMM efficiency.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.ops.gemm import GemmConfig, emit_gemm
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def _ag_gemm_kernel(axis, mesh_axes, cfg, out_dtype,
                    a_ref, b_ref, out_ref, ws_ref,
                    send_sems, recv_sems):
    # ws_ref is an HBM *output* used as the symmetric workspace (interpret
    # mode does not allocate ANY-space scratch; an output works on both
    # paths and is discarded by the host wrapper).
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m_local = a_ref.shape[0]

    # entry barrier: nobody puts into a peer's workspace before that peer
    # has entered this call (workspace slots are reused across calls)
    shd.barrier_all(axis if isinstance(axis, tuple) else (axis,),
                    mesh_axes=mesh_axes)

    # producer phase: puts to every peer (non-blocking); our own segment
    # never touches the workspace (consumed straight from a_ref below)
    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(ws_ref.at[me], a_ref,
                                    send_sems.at[dst], recv_sems.at[me], pid))

    # consumer phase: swizzled segment loop — s=0 is statically the local
    # segment (seg == me), fed by a_ref with zero wait
    emit_gemm(a_ref, b_ref, out_ref.at[pl.ds(me * m_local, m_local)], cfg,
              out_dtype)
    for s in range(1, n):
        seg = lax.rem(me + s, n)
        shd.wait_recv(ws_ref.at[seg], recv_sems.at[seg])
        emit_gemm(ws_ref.at[seg], b_ref,
                  out_ref.at[pl.ds(seg * m_local, m_local)], cfg,
                  out_dtype)

    shd.quiet(*rdmas)


def ag_gemm(ctx: ShmemContext, a: jax.Array, b: jax.Array,
            axis: str | None = None, cfg: GemmConfig | None = None,
            out_dtype=None) -> jax.Array:
    """Tensor-parallel AllGather-GEMM: ``a`` is [M, K] sharded P(axis) on M
    (each rank holds [M/n, K]); ``b`` is [K, N] sharded P(None, axis) on N
    (column-parallel weight). Returns C = all_gather(a) @ b — [M, N] sharded
    P(None, axis). Entry analog: ``ag_gemm_intra_node``
    (allgather_gemm.py:835-880); golden: all_gather + dot."""
    axis = axis or ctx.axis_names[0]
    cfg = cfg or GemmConfig()
    out_dtype = out_dtype or a.dtype
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    M, K = a.shape
    assert M % n == 0, f"M={M} not divisible by ranks {n}"
    m_local = M // n
    assert m_local % cfg.block_m == 0, (
        f"local M {m_local} not divisible by block_m {cfg.block_m}")
    assert cfg.vmem_ok(K, jnp.dtype(a.dtype).itemsize), (
        f"tile config exceeds VMEM budget for K={K}")

    def f(a_shard, b_shard):
        kernel = lambda *refs: _ag_gemm_kernel(axis, mesh_axes, cfg,
                                               out_dtype, *refs)
        n_local = b_shard.shape[1]
        flops = 2 * M * n_local * K
        c, _ws = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((M, n_local), out_dtype),
                jax.ShapeDtypeStruct((n, m_local, K), a_shard.dtype),  # symm ws
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((n,)),
                pltpu.SemaphoreType.DMA((n,)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("ag_gemm")),
            cost_estimate=pl.CostEstimate(
                flops=flops,
                bytes_accessed=(a_shard.size + b_shard.size + M * n_local)
                * jnp.dtype(a_shard.dtype).itemsize,
                transcendentals=0),
            interpret=default_interpret(),
        )(a_shard, b_shard)
        return c

    sm = ctx.shard_map(f, in_specs=(P(axis), P(None, axis)),
                       out_specs=P(None, axis))
    return sm(a, b)


__all__ = ["ag_gemm", "GemmConfig"]
