"""Tutorial 11 — differentiable ring attention (context parallelism).

Beyond the reference's scope (its sequence story is decode-only, SURVEY
§5.7): blockwise attention over a sequence-sharded KV cache where KV
blocks travel a ring (2-slot relay + ack credits — the reduce_scatter
transport) behind the per-step flash inner loop, with a backward ring in
which each block's (dk ‖ dv) accumulator arrives home after a full circle.

Run:  python -m tutorials.t11_ring_attention [--sim 4]
      [--case correctness|grad|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _inputs(ctx, s_loc=256, B=1, Hq=8, Hkv=2, D=128):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    n = ctx.num_ranks
    S = n * s_loc
    ks = jax.random.split(jax.random.key(0), 3)
    mk = lambda k, h: (jax.random.normal(k, (B, h, S, D), jnp.float32)
                       * 0.5).astype(jnp.bfloat16)
    q, k, v = mk(ks[0], Hq), mk(ks[1], Hkv), mk(ks[2], Hkv)
    spec = P(None, None, "x")
    return q, k, v, (ctx.shard(q, spec), ctx.shard(k, spec),
                     ctx.shard(v, spec))


def _dense(q, k, v):
    import jax
    import jax.numpy as jnp
    import numpy as np
    Hq, Hkv, S, D = q.shape[1], k.shape[1], q.shape[2], q.shape[3]
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    kf = jnp.repeat(kf, Hq // Hkv, axis=1)
    vf = jnp.repeat(vf, Hq // Hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) / np.sqrt(D)
    s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -jnp.inf)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vf)


@register_case("correctness")
def correctness():
    import jax
    import numpy as np

    from triton_dist_tpu.ops import ring_attention
    ctx = world_context()
    q, k, v, (qs, ks, vs) = _inputs(ctx)
    out = jax.jit(lambda a, b, c: ring_attention(ctx, a, b, c, axis="x",
                                                 causal=True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(_dense(q, k, v)), rtol=4e-2,
                               atol=4e-2)
    print(f"ring attention over {ctx.num_ranks} PEs == dense causal golden")


@register_case("grad")
def grad():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_dist_tpu.ops import ring_attention
    ctx = world_context()
    q, k, v, (qs, ks, vs) = _inputs(ctx, s_loc=128)
    tgt = jax.random.normal(jax.random.key(7), q.shape, jnp.float32)

    def loss_ring(a, b, c):
        o = ring_attention(ctx, a, b, c, axis="x", causal=True)
        return jnp.sum((o.astype(jnp.float32) - tgt) ** 2)

    def loss_dense(a, b, c):
        return jnp.sum((_dense(a, b, c) - tgt) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qs, ks, vs)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for got, want, nm in zip(gr, gd, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=6e-2, atol=6e-1)
    print("backward ring == jax.grad of dense golden (dq, dk, dv)")


@register_case("perf")
def perf():
    import jax

    from triton_dist_tpu.ops import ring_attention
    ctx = world_context()
    n = ctx.num_ranks
    q, k, v, (qs, ks, vs) = _inputs(ctx, s_loc=1024, Hq=16, Hkv=4)
    f = jax.jit(lambda a, b, c: ring_attention(ctx, a, b, c, axis="x",
                                               causal=True))
    s = time_op(lambda: f(qs, ks, vs))
    B, Hq, S, D = q.shape
    flops = 2 * 2 * B * Hq * S * S * D / 2  # causal halves the work
    perf_report("ring_attention", s,
                f"~{flops / s / max(n, 1) / 1e12:.1f} TFLOP/s/chip "
                "(wall-clock; see bench.py for tunnel-corrected numbers)")


if __name__ == "__main__":
    tutorial_main(__doc__)
